"""Thread-pool discipline rule: ``threadpool-discipline``.

All host-side parallelism goes through ``delta_tpu/utils/threads.py``
(the analogue of the reference's managed ``DeltaThreadPool`` family): a
shared, bounded, named daemon pool plus ``parallel_map``. A
``ThreadPoolExecutor(...)`` constructed anywhere else is a discipline
leak three ways:

- **unbounded fan-out** — every ad-hoc pool adds its own worker set on
  top of the shared one, so aggregate concurrency is no longer the one
  number ``default_io_threads()`` was sized to;
- **churn** — a throwaway pool pays thread spawn/join on every call in
  paths that are hot enough to have wanted a pool in the first place;
- **deadlock surface** — the shared pool's no-nesting rule (pool tasks
  are leaf work only) is only auditable while every submission site
  goes through the one module.

``delta_tpu/utils/threads.py`` itself is exempt by path — it is the one
place allowed to own an executor. Audited exceptions elsewhere carry a
``# delta-lint: disable=threadpool-discipline`` pragma.
"""

from __future__ import annotations

import ast
from typing import List, Set

from delta_tpu.tools.analyzer.core import Finding, ModuleInfo, Rule, register
from delta_tpu.tools.analyzer.passes._astutil import call_name


def _executor_call_names(tree: ast.Module) -> Set[str]:
    """Dotted call names that resolve to
    ``concurrent.futures.ThreadPoolExecutor`` in this module:
    ``from concurrent.futures import ThreadPoolExecutor [as x]`` binds
    ``x``; ``from concurrent import futures [as f]`` binds
    ``f.ThreadPoolExecutor``; ``import concurrent.futures [as cf]``
    binds ``cf.ThreadPoolExecutor`` (or the full dotted path)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "concurrent.futures":
                for a in node.names:
                    if a.name == "ThreadPoolExecutor":
                        names.add(a.asname or a.name)
            elif node.module == "concurrent":
                for a in node.names:
                    if a.name == "futures":
                        names.add(
                            f"{a.asname or a.name}.ThreadPoolExecutor")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "concurrent.futures":
                    names.add(
                        f"{a.asname}.ThreadPoolExecutor" if a.asname
                        else "concurrent.futures.ThreadPoolExecutor")
                elif a.name == "concurrent" and not a.asname:
                    names.add("concurrent.futures.ThreadPoolExecutor")
    return names


@register
class ThreadPoolDisciplineRule(Rule):
    id = "threadpool-discipline"
    description = ("direct ThreadPoolExecutor(...) construction outside "
                   "delta_tpu/utils/threads.py — use the shared pool "
                   "(shared_pool() / parallel_map) so worker counts stay "
                   "bounded and nesting stays auditable")

    def check_module(self, mod: ModuleInfo) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        # the one module allowed to own executors
        rel = mod.rel.replace("\\", "/")
        if rel.endswith("utils/threads.py"):
            return []
        names = _executor_call_names(tree)
        if not names:
            return []
        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in names:
                out.append(Finding(
                    self.id, mod.rel, node.lineno, node.col_offset,
                    f"{name}(...) constructed outside utils/threads.py: "
                    f"route the work through shared_pool()/parallel_map "
                    f"(or audit + suppress)"))
        return out

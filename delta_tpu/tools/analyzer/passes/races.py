"""Shared-state race detector: ``shared-state-race``.

Flags functions that (a) are reachable from **two or more thread-root
sites** — or from one *multi* root: a worker pool, executor
``submit``/``map``, ``obs.wrap`` hand-off, or socketserver handler,
any of which alone implies concurrent execution — and (b) mutate
instance attributes or module globals **without the owning lock held on
every path** from a thread entry point.

Lock context is interprocedural: the lexically-held locks at each call
site (from the lock model in ``passes/locks.py``) become edge gains in
a meet-over-paths dataflow over the shared
:class:`~delta_tpu.tools.analyzer.core.ProjectGraph` — a lock counts
only if it is held on EVERY path from a thread root to the mutation
(intersection merge), so a single unlocked path surfaces.

What counts as a mutation (the taxonomy is collected by the lock
model): read-modify-write (``self.n += 1``, ``self.x = f(self.x)``),
subscript stores (``self.cache[k] = v``), container mutator calls
(``self.xs.append(...)``), and ``del``. Plain attribute rebinding
(``self.snapshot = snap``) is exempt — a single store is atomic
publication under the GIL and is the idiomatic lock-free hand-off.

Exemptions (each one is a claim the mutation is safe by construction):

- mutations inside ``__init__`` / ``__new__`` / ``__post_init__`` — the
  object is not yet shared;
- attributes whose inferred type is itself thread-safe
  (``queue.Queue``, ``threading.Event``, ``ContextVar``, locks, the
  obs metric instruments — their methods take their own lock);
- attributes that ARE locks (``self._lock``-style);
- the owning lock held: any held lock whose owner is the mutating
  class (or a base class), or a module-level lock of the defining
  module for globals.

Everything else is a finding; audited false positives carry
``# delta-lint: disable=shared-state-race`` with a rationale.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Set

from delta_tpu.tools.analyzer.core import (
    Finding,
    ModuleInfo,
    Rule,
    module_stem,
    project_graph,
    register,
)
from delta_tpu.tools.analyzer.passes.locks import _analysis

# attribute types whose mutators are internally synchronized (or
# per-context by construction); bare class names as the graph infers
# them from constructor calls and annotations
_THREADSAFE_TYPES = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "Event", "Semaphore", "BoundedSemaphore", "Barrier",
    "Lock", "RLock", "Condition", "ContextVar", "local",
    # obs metric instruments: inc()/dec()/observe() lock internally
    "Counter", "Gauge", "Histogram",
})

# methods in which mutations are pre-publication by construction
_CONSTRUCTION_METHODS = frozenset({
    "__init__", "__new__", "__post_init__", "__init_subclass__",
})


@register
class SharedStateRaceRule(Rule):
    id = "shared-state-race"
    description = (
        "instance attr or module global mutated by code reachable from "
        "multiple thread roots without the owning lock held on every "
        "path (interprocedural held-locks meet-over-paths)")

    def check_project(self, mods: List[ModuleInfo]) -> List[Finding]:
        graph = project_graph(mods)
        la = _analysis(mods)
        root_sites = graph.root_reach()
        shared = {k for k, s in root_sites.items() if len(s) >= 2}
        if not shared:
            return []

        # meet-over-paths held locks: a thread entry starts with
        # nothing held; each edge adds the locks lexically held around
        # that call site in the caller; merging is intersection
        entries: Dict[str, FrozenSet[str]] = {
            r.target: frozenset() for r in graph.thread_roots}
        domain = graph.reachable_from(entries)
        held_in = graph.propagate_meet(
            entries,
            edge_gain=lambda e: frozenset(
                la.held_at_call.get(e.node_id, ())),
            domain=domain,
        )

        out: List[Finding] = []
        for key in sorted(shared):
            ff = la.facts.get(key)
            if ff is None or not ff.mutations:
                continue
            method = ff.qualname.rpartition(".")[2]
            if method in _CONSTRUCTION_METHODS:
                continue
            stem = module_stem(ff.mod_rel)
            entry_held = held_in.get(key, frozenset())
            n_roots = len(root_sites[key])
            for mut in ff.mutations:
                if mut.kind == "store":
                    continue  # GIL-atomic publication
                if self._attr_exempt(graph, la, ff, mut):
                    continue
                held = entry_held | set(mut.held)
                if self._owned_lock_held(graph, la, ff, mut, stem, held):
                    continue
                owner = (f"{mut.owner_cls}.{mut.attr}"
                         if mut.owner_cls else f"global {mut.attr!r}")
                via = f".{mut.detail}()" if mut.detail else ""
                held_note = (f"held here: {', '.join(sorted(held))}"
                             if held else "no lock held")
                out.append(Finding(
                    self.id, ff.mod_rel, mut.line, mut.col,
                    f"{mut.kind} of {owner}{via} in {ff.qualname}(), "
                    f"reachable from {n_roots} thread-root sites, "
                    f"without the owning lock on every path "
                    f"({held_note})"))
        return out

    @staticmethod
    def _attr_exempt(graph, la, ff, mut) -> bool:
        """Thread-safe attr types, and attrs that are locks."""
        if mut.owner_cls is None:
            return False
        ml = la.per_mod.get(ff.mod_rel)
        if ml is not None and (mut.owner_cls, mut.attr) in ml.by_attr:
            return True  # the attr IS a lock
        v = graph.views.get(ff.mod_rel)
        if v is None:
            return False
        ci = graph._class_info(v, mut.owner_cls)
        if ci is None:
            return False
        tname = ci.attr_types.get(mut.attr, "")
        return tname.rpartition(".")[2] in _THREADSAFE_TYPES

    @staticmethod
    def _owned_lock_held(graph, la, ff, mut, stem: str,
                         held: Set[str]) -> bool:
        if not held:
            return False
        if mut.owner_cls is not None:
            # the class and its same-project bases all count as owners
            names = {mut.owner_cls}
            v = graph.views.get(ff.mod_rel)
            queue = [mut.owner_cls]
            while queue and v is not None:
                ci = graph._class_info(v, queue.pop())
                if ci is None:
                    continue
                for b in ci.bases:
                    b = b.rpartition(".")[2]
                    if b not in names:
                        names.add(b)
                        queue.append(b)
            for lid in held:
                o = la.lock_owners.get(lid)
                if o is not None and o[1] in names:
                    return True
        # a module-level lock of the defining module also counts
        # (module-singleton classes guarded by a global lock)
        for lid in held:
            o = la.lock_owners.get(lid)
            if o is not None and o[0] == stem and o[1] is None:
                return True
        return False

"""Findings baseline: ratchet CI on NEW findings only.

``delta-lint --baseline write`` snapshots the current unsuppressed
findings into a committed JSON file; ``--baseline check`` re-runs the
scan and fails only on findings not in that snapshot, reporting the
rest as known debt. This is how a new rule lands on a big tree without
a flag day: commit the baseline with the rule, burn the debt down in
follow-ups, and the ratchet stops regressions in the meantime.

Fingerprints must survive unrelated edits, so they deliberately exclude
line numbers: a finding is identified by its rule id, file path, the
*text* of the source line it points at (stripped), and the message.
Inserting code above a finding moves its line number but not its
fingerprint. Identical findings are disambiguated by multiplicity: the
baseline stores a count per fingerprint, and a check consumes matches
up to that count — adding a second identical defect on a new line is
still NEW.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

from delta_tpu.tools.analyzer.core import Finding, Report

BASELINE_ENV = "DELTA_LINT_BASELINE"
DEFAULT_BASELINE_NAME = "delta-lint-baseline.json"
_SCHEMA = 1


def default_baseline_path() -> str:
    return os.environ.get(BASELINE_ENV) or DEFAULT_BASELINE_NAME


def _line_text(f: Finding, root: Optional[str],
               _cache: Dict[str, List[str]]) -> str:
    path = os.path.join(root, f.path) if root else f.path
    lines = _cache.get(path)
    if lines is None:
        try:
            with open(path, encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            lines = []
        _cache[path] = lines
    if 1 <= f.line <= len(lines):
        return lines[f.line - 1].strip()
    return ""


def fingerprint(f: Finding, line_text: str) -> str:
    return hashlib.sha1(
        f"{f.rule}|{f.path}|{line_text}|{f.message}".encode()
    ).hexdigest()


def _fingerprints(findings: List[Finding],
                  root: Optional[str]) -> List[Tuple[Finding, str]]:
    cache: Dict[str, List[str]] = {}
    return [(f, fingerprint(f, _line_text(f, root, cache)))
            for f in findings]


def write_baseline(path: str, report: Report,
                   root: Optional[str] = None) -> int:
    """Snapshot `report`'s unsuppressed findings; returns the count."""
    counts: Dict[str, int] = {}
    for _, fp in _fingerprints(report.findings, root):
        counts[fp] = counts.get(fp, 0) + 1
    doc = {"schema": _SCHEMA, "findings": len(report.findings),
           "fingerprints": dict(sorted(counts.items()))}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return len(report.findings)


def load_baseline(path: str) -> Optional[Dict[str, int]]:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != _SCHEMA:
        return None
    fps = doc.get("fingerprints")
    return {str(k): int(v) for k, v in fps.items()} \
        if isinstance(fps, dict) else None


def apply_baseline(report: Report, baseline: Dict[str, int],
                   root: Optional[str] = None) -> Report:
    """Partition `report.findings` against `baseline`: matched
    fingerprints (up to their stored multiplicity) move to
    ``report.baselined``; the remainder stay failing."""
    budget = dict(baseline)
    new: List[Finding] = []
    known: List[Finding] = []
    for f, fp in _fingerprints(report.findings, root):
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            known.append(f)
        else:
            new.append(f)
    return Report(findings=new, suppressed=report.suppressed,
                  files_scanned=report.files_scanned,
                  rules_run=report.rules_run, baselined=known,
                  baseline_checked=True)

"""``python -m delta_tpu.tools.analyzer`` entry point."""

import sys

from delta_tpu.tools.analyzer.cli import main

if __name__ == "__main__":
    sys.exit(main())

"""delta-lint core: module model, rule plugin registry, analysis engine,
and the shared whole-program layer.

The engine runs in three passes, so project-wide rules (lock-order
cycles, catalog conformance) see every module before they report:

1. **load** — read + parse every target file once into a
   :class:`ModuleInfo` (AST, source lines, suppression pragmas);
2. **module pass** — each rule's :meth:`Rule.check_module` runs per
   file (purely local rules live entirely here);
3. **project pass** — each rule's :meth:`Rule.check_project` runs once
   over all modules (rules typically accumulate facts during the module
   pass and cross-reference them here).

Interprocedural rules (lock discipline, the shared-state race detector,
the device-transfer budget) additionally share ONE :class:`ProjectGraph`
per module set — a project-wide call graph with def/attr/method
resolution (imports and re-exports, ``functools.partial`` aliases,
dict-dispatch tables, constructor/annotation-based receiver typing),
thread-root discovery (``threading.Thread`` targets and spawn wrappers,
executor ``submit``/``map``, ``obs.wrap``), and a small dataflow driver
(:meth:`ProjectGraph.reachable_from`,
:meth:`ProjectGraph.propagate_meet`). Get it via :func:`project_graph`;
it is cached on module-set identity exactly like the lock model.

Adding a rule: subclass :class:`Rule`, set ``id``/``description``,
implement either hook, decorate with :func:`register`, and import the
module from ``passes/__init__.py``. Fixture-test it in
``tests/test_analyzer.py`` (every rule must both fire on its positive
fixture and stay silent on its negative one).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import (
    Callable,
    Dict,
    FrozenSet,
    Iterable,
    List,
    Optional,
    Set,
    Tuple,
    Type,
)

from delta_tpu.tools.analyzer.suppress import is_suppressed, parse_suppressions


@dataclass(frozen=True)
class Finding:
    """One diagnostic. `line`/`col` are 1-based / 0-based like CPython
    AST nodes. `severity` is "error" or "warning" (both fail the run;
    the split only drives reporting)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str = "error"


class ModuleInfo:
    """One parsed target file plus its suppression pragmas."""

    def __init__(self, path: str, source: str, rel: Optional[str] = None):
        self.path = path
        self.rel = rel if rel is not None else path
        self.source = source
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(source, path)
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
        self.suppress_lines, self.suppress_file = parse_suppressions(source)

    def suppressed(self, rule_id: str, line: int) -> bool:
        return is_suppressed(rule_id, line, self.suppress_lines,
                             self.suppress_file)


class Rule:
    """Plugin base. Stateless across runs: the engine instantiates a
    fresh rule object per analysis, so instance attributes are safe
    scratch space for module-pass fact accumulation."""

    id: str = "?"
    description: str = ""
    # anchor into docs/static_analysis.md; the SARIF reporter turns it
    # into the rule's helpUri so CI annotations are clickable. Rules
    # documented under a shared section override this.
    help_anchor: str = ""

    @classmethod
    def help_uri(cls) -> str:
        anchor = cls.help_anchor or cls.id
        return f"docs/static_analysis.md#{anchor}"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def check_project(self, mods: List[ModuleInfo]) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a rule to the global registry."""
    if cls.id in _REGISTRY and _REGISTRY[cls.id] is not cls:
        raise ValueError(f"duplicate delta-lint rule id: {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    """id -> rule class for every registered rule (imports the bundled
    passes on first use so the registry is populated)."""
    import delta_tpu.tools.analyzer.passes  # noqa: F401  (registers)

    return dict(_REGISTRY)


@dataclass
class Report:
    findings: List[Finding]          # unsuppressed — these fail the run
    suppressed: List[Finding]        # matched a disable pragma
    files_scanned: int
    rules_run: List[str]
    # findings matched against a committed baseline (``delta-lint
    # --baseline check``): known debt, reported but not failing
    baselined: List[Finding] = field(default_factory=list)
    baseline_checked: bool = False

    @property
    def ok(self) -> bool:
        return not self.findings

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


# --------------------------------------------------------------- collection

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def _iter_py_files(path: str) -> Iterable[str]:
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def load_modules(paths: Iterable[str],
                 root: Optional[str] = None) -> List[ModuleInfo]:
    mods: List[ModuleInfo] = []
    for p in paths:
        for fp in _iter_py_files(p):
            with open(fp, encoding="utf-8") as f:
                source = f.read()
            rel = os.path.relpath(fp, root) if root else fp
            mods.append(ModuleInfo(fp, source, rel=rel))
    return mods


# ------------------------------------------------------------------ engine


def resolve_rules(
        rule_ids: Optional[Iterable[str]] = None,
) -> Tuple[List[str], List[Rule]]:
    """Validate + instantiate: (sorted/ordered ids, fresh instances)."""
    registry = all_rules()
    ids = list(rule_ids) if rule_ids is not None else sorted(registry)
    unknown = [i for i in ids if i not in registry]
    if unknown:
        raise ValueError(f"unknown delta-lint rule(s): {unknown}; "
                         f"known: {sorted(registry)}")
    return ids, [registry[i]() for i in ids]


def module_pass(mod: ModuleInfo, rules: List[Rule]) -> List[Finding]:
    """Per-file findings only — the cacheable half of the engine."""
    if mod.syntax_error is not None:
        e = mod.syntax_error
        return [Finding("parse-error", mod.rel, e.lineno or 1, 0,
                        f"syntax error: {e.msg}")]
    out: List[Finding] = []
    for rule in rules:
        out.extend(rule.check_module(mod))
    return out


def project_pass(mods: List[ModuleInfo],
                 rules: List[Rule]) -> List[Finding]:
    """Whole-program findings; sees every parsed module at once."""
    parsed = [m for m in mods if m.tree is not None]
    out: List[Finding] = []
    for rule in rules:
        out.extend(rule.check_project(parsed))
    return out


def partition_findings(
        mods: List[ModuleInfo],
        raw: Iterable[Finding]) -> Tuple[List[Finding], List[Finding]]:
    """Split raw findings into (unsuppressed, suppressed), both sorted."""
    by_rel = {m.rel: m for m in mods}
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for f in raw:
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed(f.rule, f.line):
            suppressed.append(f)
        else:
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, suppressed


def _run(mods: List[ModuleInfo],
         rule_ids: Optional[Iterable[str]] = None) -> Report:
    ids, rules = resolve_rules(rule_ids)
    raw: List[Finding] = []
    for mod in mods:
        raw.extend(module_pass(mod, rules))
    raw.extend(project_pass(mods, rules))
    findings, suppressed = partition_findings(mods, raw)
    return Report(findings=findings, suppressed=suppressed,
                  files_scanned=len(mods), rules_run=ids)


def analyze_paths(paths: Iterable[str], root: Optional[str] = None,
                  rules: Optional[Iterable[str]] = None) -> Report:
    """Analyze every ``.py`` file under `paths` (files or directories)."""
    return _run(load_modules(paths, root=root), rules)


def analyze_sources(sources: Dict[str, str],
                    rules: Optional[Iterable[str]] = None) -> Report:
    """Analyze in-memory sources (virtual path -> source text) — the
    fixture-test entry point."""
    mods = [ModuleInfo(path, src) for path, src in sources.items()]
    return _run(mods, rules)


# ===================================================================
# Whole-program layer: project call graph, thread roots, dataflow.
# ===================================================================

MODULE_BODY = "<module>"


def module_stem(rel: str) -> str:
    """``a/b/c.py`` -> ``a.b.c``; packages drop ``__init__``."""
    stem = rel[:-3] if rel.endswith(".py") else rel
    stem = stem.replace(os.sep, ".").replace("/", ".")
    if stem.endswith(".__init__"):
        stem = stem[:-len(".__init__")]
    return stem


@dataclass(frozen=True)
class CallEdge:
    """One resolved call: `caller` invokes `callee` at `line` of the
    caller's file. Keys are ``<rel-path>::<qualname>``. `node_id` is
    ``id()`` of the ``ast.Call`` for direct calls (0 for synthesized
    edges: higher-order escapes, deferred attr calls) — passes that
    walk the same shared ASTs use it to join their own per-site facts
    (e.g. lexically-held locks) onto graph edges."""

    caller: str
    callee: str
    line: int
    node_id: int = 0


@dataclass(frozen=True)
class ThreadRoot:
    """A function that runs on a thread other than its spawner's.

    `multi` marks roots that can run on MORE than one concurrent thread
    from this single syntactic site (worker pools: the spawn sits in a
    loop, or goes through an executor ``submit``/``map``) — a
    multi-root alone makes everything it reaches shared state."""

    target: str       # function key the new thread enters
    site_path: str
    site_line: int
    kind: str         # thread | spawn-wrapper | submit | pool-map |
    #                   obs-wrap | thread-subclass
    multi: bool

    @property
    def site(self) -> str:
        return f"{self.site_path}:{self.site_line}"


@dataclass
class FunctionNode:
    key: str                      # "<rel>::<qualname>"
    mod_rel: str
    qualname: str
    cls: Optional[str]            # enclosing class name, if a method
    node: ast.AST                 # FunctionDef / AsyncFunctionDef


# attribute-method names too generic for the unique-definition fallback:
# resolving `xs.append(...)` to the one project class defining `append`
# would wire list mutations into the call graph
_COMMON_METHODS = frozenset({
    "append", "add", "get", "set", "put", "pop", "update", "items",
    "keys", "values", "join", "start", "close", "read", "write", "wait",
    "clear", "sort", "remove", "insert", "extend", "copy", "format",
    "split", "strip", "encode", "decode", "count", "index", "setdefault",
    "popitem", "discard", "send", "recv", "acquire", "release", "open",
    "flush", "seek", "tell", "next", "run", "name", "result", "submit",
    "map", "group", "match", "search", "startswith", "endswith",
})

_PARTIAL_NAMES = {"functools.partial", "partial"}
_WRAP_NAMES = {"obs.wrap", "wrap"}
_THREAD_NAMES = {"threading.Thread", "Thread"}


class _ClassInfo:
    __slots__ = ("name", "mod_rel", "bases", "methods", "attr_types")

    def __init__(self, name: str, mod_rel: str):
        self.name = name
        self.mod_rel = mod_rel
        self.bases: List[str] = []        # dotted base names, unresolved
        self.methods: Dict[str, str] = {}  # method name -> function key
        # attr -> dotted type name, from `self.x = Cls()` stores, class
        # body annotations, and `self.x = fn()` with `-> Cls` annotation
        self.attr_types: Dict[str, str] = {}


def _ann_class_name(ann: ast.AST) -> Optional[str]:
    """`Cls`, `Optional[Cls]`, `"Cls"` -> "Cls" (dotted ok)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        base = _dotted(ann.value)
        if base and base.rpartition(".")[2] in ("Optional", "Union"):
            inner = ann.slice
            if isinstance(inner, ast.Tuple):
                elts = [e for e in inner.elts
                        if not (isinstance(e, ast.Constant)
                                and e.value is None)]
                inner = elts[0] if len(elts) == 1 else None
            return _ann_class_name(inner) if inner is not None else None
        return None
    return _dotted(ann)


def _dotted(node) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _ModuleView:
    """Per-module symbol tables feeding project-wide resolution."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.stem = module_stem(mod.rel)
        self.functions: Dict[str, ast.AST] = {}   # qualname -> def node
        self.fn_class: Dict[str, Optional[str]] = {}
        self.imports: Dict[str, str] = {}         # alias -> dotted module
        self.from_names: Dict[str, Tuple[str, str]] = {}  # alias -> (mod, orig)
        self.classes: Dict[str, _ClassInfo] = {}
        self.aliases: Dict[str, str] = {}   # module-level fn alias -> dotted
        self.dispatch: Dict[str, List[str]] = {}  # dict name -> dotted fns
        self.instances: Dict[str, str] = {}  # module-level var -> class name
        self.returns: Dict[str, str] = {}    # qualname -> annotated class

        tree = mod.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.partition(".")[0]] = (
                        a.name if a.asname else a.name.partition(".")[0])
                    if not a.asname and "." in a.name:
                        # `import a.b.c` also binds the full dotted path
                        self.imports[a.name] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        self.from_names[a.asname or a.name] = (
                            node.module, a.name)

        self._collect_defs(tree.body, prefix="", cls=None)

        for st in tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                name, v = st.targets[0].id, st.value
                if isinstance(v, ast.Call):
                    cn = _dotted(v.func)
                    if cn in _PARTIAL_NAMES and v.args:
                        t = _dotted(v.args[0])
                        if t:
                            self.aliases[name] = t
                    elif cn:
                        self.instances[name] = cn
                elif isinstance(v, ast.Name) or isinstance(v, ast.Attribute):
                    t = _dotted(v)
                    if t:
                        self.aliases[name] = t
                elif isinstance(v, ast.Dict):
                    fns = []
                    for val in v.values:
                        t = _dotted(val)
                        if t:
                            fns.append(t)
                    if fns:
                        self.dispatch[name] = fns

    def _collect_defs(self, body, prefix: str, cls: Optional[str]):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{node.name}"
                self.functions[qn] = node
                self.fn_class[qn] = cls
                rcls = node.returns and _ann_class_name(node.returns)
                if rcls:
                    self.returns[qn] = rcls
                if cls is not None and prefix.count(".") == 1:
                    self.classes[cls].methods[node.name] = qn
                # nested defs: attributed their own node, one level of
                # dotting per nesting level
                self._collect_defs(node.body, prefix=f"{qn}.", cls=cls)
            elif isinstance(node, ast.ClassDef) and not prefix:
                ci = self.classes.setdefault(
                    node.name, _ClassInfo(node.name, self.mod.rel))
                for b in node.bases:
                    bn = _dotted(b)
                    if bn:
                        ci.bases.append(bn)
                for st in node.body:
                    if isinstance(st, ast.AnnAssign) \
                            and isinstance(st.target, ast.Name):
                        tn = _ann_class_name(st.annotation)
                        if tn:
                            ci.attr_types[st.target.id] = tn
                self._collect_defs(node.body, prefix=f"{node.name}.",
                                   cls=node.name)


class ProjectGraph:
    """Project-wide call graph + thread roots + dataflow driver.

    Resolution is deliberately an over-approximation where precision is
    unavailable (dict dispatch resolves to every value; an attribute
    method with no receiver type resolves through the project-unique
    definition fallback) — for the race detector and budget lint a
    missed edge hides a real bug, while a spurious edge costs one
    triaged suppression.
    """

    def __init__(self, mods: List[ModuleInfo]):
        self.mods = mods
        self.views: Dict[str, _ModuleView] = {
            m.rel: _ModuleView(m) for m in mods}
        self.by_stem: Dict[str, _ModuleView] = {
            v.stem: v for v in self.views.values()}
        self.functions: Dict[str, FunctionNode] = {}
        for v in self.views.values():
            for qn, fn in v.functions.items():
                key = f"{v.mod.rel}::{qn}"
                self.functions[key] = FunctionNode(
                    key, v.mod.rel, qn, v.fn_class[qn], fn)
        # method-name index for the unique-definition fallback
        self._method_defs: Dict[str, List[Tuple[str, str]]] = {}
        for v in self.views.values():
            for ci in v.classes.values():
                for mname, qn in ci.methods.items():
                    self._method_defs.setdefault(mname, []).append(
                        (ci.name, f"{v.mod.rel}::{qn}"))
        self.edges: List[CallEdge] = []
        self.edges_out: Dict[str, List[CallEdge]] = {}
        self.edges_in: Dict[str, List[CallEdge]] = {}
        self.thread_roots: List[ThreadRoot] = []
        self._spawn_wrappers: Dict[str, int] = {}  # fn key -> param index
        self._attr_class_fallback: Dict[str, Set[str]] = {}
        self._find_spawn_wrappers()
        self._infer_attr_types()
        # socketserver protocol: a *RequestHandler subclass's handle()
        # runs on a per-connection thread the stdlib spawns
        for v in self.views.values():
            for ci in v.classes.values():
                if "handle" in ci.methods and any(
                        b.rpartition(".")[2].endswith("RequestHandler")
                        for b in ci.bases):
                    node = v.functions[ci.methods["handle"]]
                    self.thread_roots.append(ThreadRoot(
                        f"{ci.mod_rel}::{ci.methods['handle']}",
                        ci.mod_rel, node.lineno, "request-handler", True))
        # callables that escape into a class's constructor (stored on
        # the instance, invoked later through an attribute: `req.fn()`)
        self._escaped_into: Dict[str, Set[str]] = {}
        self._pending_attr_calls: List[Tuple[str, str, str, int]] = []
        # id(ast.Call) -> resolved callee keys, for passes that walk
        # the same shared ASTs (locks, races)
        self.call_sites: Dict[int, List[str]] = {}
        for v in self.views.values():
            self._scan_module(v)
        # second pass: `x.attr()` on a typed receiver whose class has no
        # such method resolves to everything that escaped into the class
        for caller, cls_name, attr, line in self._pending_attr_calls:
            for key in self._escaped_into.get(cls_name, ()):
                self.edges.append(CallEdge(caller, key, line))
        for e in self.edges:
            self.edges_out.setdefault(e.caller, []).append(e)
            self.edges_in.setdefault(e.callee, []).append(e)

    def _infer_attr_types(self):
        """Fill each class's attr -> type table from ``self.attr = X()``
        stores in its methods, where X is a constructor or a function
        with a ``-> Cls`` return annotation. Runs before edge building
        so ``self.attr.method()`` calls resolve. Also builds the
        project-wide attr-name fallback: ``anything.attr = X()`` records
        attr -> class, consulted (only when unique) to type locals
        seeded from attribute loads — covers fields deliberately
        annotated ``object`` to break import cycles
        (``SnapshotState.resident``)."""
        for v in self.views.values():
            for qn, fn in v.functions.items():
                cls = v.fn_class[qn]
                ci = v.classes.get(cls) if cls else None
                param_types: Dict[str, str] = {}
                for a in list(fn.args.args) + list(fn.args.kwonlyargs):
                    if a.annotation is not None:
                        tn = _ann_class_name(a.annotation)
                        if tn:
                            param_types[a.arg] = tn.rpartition(".")[2]
                for st in ast.walk(fn):
                    if not isinstance(st, (ast.Assign, ast.AnnAssign)):
                        continue
                    targets = (st.targets if isinstance(st, ast.Assign)
                               else [st.target])
                    if len(targets) != 1:
                        continue
                    t = targets[0]
                    if not isinstance(t, ast.Attribute):
                        continue
                    rcls = None
                    if isinstance(st, ast.AnnAssign):
                        # `self._cached_snapshot: Optional[Snapshot] = None`
                        tn = _ann_class_name(st.annotation)
                        if tn:
                            rcls = tn.rpartition(".")[2]
                    elif isinstance(st.value, ast.Call):
                        cn = _dotted(st.value.func)
                        if cn is not None:
                            rcls = self._class_of_callable(v, cls, cn)
                    elif isinstance(st.value, ast.Name):
                        # `self.table = table` with `table: Table` param
                        rcls = param_types.get(st.value.id)
                    if not rcls or rcls in ("object", "Any"):
                        continue
                    self._attr_class_fallback.setdefault(
                        t.attr, set()).add(rcls)
                    if ci is not None and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        ci.attr_types.setdefault(t.attr, rcls)

    # ---------------------------------------------------- spawn wrappers

    def _find_spawn_wrappers(self):
        """A function that passes one of its own parameters as
        ``threading.Thread(target=...)`` is a spawn wrapper: each of its
        call sites is a thread-root site for the argument it forwards
        (serve/pool.spawn is the canonical instance)."""
        for key, fn in self.functions.items():
            node = fn.node
            params = [a.arg for a in node.args.args]
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and _dotted(sub.func) in _THREAD_NAMES:
                    for kw in sub.keywords:
                        if kw.arg == "target" \
                                and isinstance(kw.value, ast.Name) \
                                and kw.value.id in params:
                            self._spawn_wrappers[key] = params.index(
                                kw.value.id)

    # ------------------------------------------------------- module scan

    def _scan_module(self, v: _ModuleView):
        for qn, fn in v.functions.items():
            caller = f"{v.mod.rel}::{qn}"
            self._scan_body(v, caller, v.fn_class[qn], fn,
                            skip_nested=True)
        # module body (import-time calls, thread spawns at module level)
        self._scan_body(v, f"{v.mod.rel}::{MODULE_BODY}", None,
                        v.mod.tree, skip_nested=True)

    def _scan_body(self, v: _ModuleView, caller: str, cls: Optional[str],
                   fn: ast.AST, skip_nested: bool):
        env_types: Dict[str, str] = {}       # local var -> class name
        env_fns: Dict[str, List[str]] = {}   # local var -> function keys
        submit_aliases: Set[str] = set()
        own_prefix = caller.split("::", 1)[1]
        if own_prefix == MODULE_BODY:
            own_prefix = ""
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for a in list(fn.args.args) + list(fn.args.kwonlyargs):
                if a.annotation is not None:
                    tn = _ann_class_name(a.annotation)
                    if tn:
                        env_types[a.arg] = tn.rpartition(".")[2]

        # own-subtree preorder walk (nested defs/classes are their own
        # graph nodes), tagging each node with whether it executes
        # repeatedly (loop body or comprehension)
        nodes: List[Tuple[ast.AST, bool]] = []

        def collect(node: ast.AST, in_loop: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                child_loop = in_loop or isinstance(
                    child, (ast.For, ast.AsyncFor, ast.While,
                            ast.ListComp, ast.SetComp, ast.DictComp,
                            ast.GeneratorExp))
                nodes.append((child, child_loop))
                collect(child, child_loop)

        for st in _body_of(fn):
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            st_loop = isinstance(st, (ast.For, ast.AsyncFor, ast.While))
            nodes.append((st, st_loop))
            collect(st, st_loop)

        # seed locals first (flow-insensitive: a later assignment types
        # earlier calls too — an over-approximation, by design)
        for node, _ in nodes:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._seed_locals(v, cls, node, env_types, env_fns,
                                  submit_aliases, own_prefix)
        for node, in_loop in nodes:
            if isinstance(node, ast.Call):
                self._handle_call(v, caller, cls, node, env_types,
                                  env_fns, submit_aliases, in_loop)

    def _seed_locals(self, v, cls, st, env_types, env_fns,
                     submit_aliases, own_prefix):
        if not isinstance(st, (ast.Assign, ast.AnnAssign)):
            return
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        if len(targets) != 1 or not isinstance(targets[0], ast.Name):
            return
        name = targets[0].id
        val = st.value
        if isinstance(st, ast.AnnAssign):
            tn = st.annotation and _ann_class_name(st.annotation)
            if tn:
                env_types[name] = tn.rpartition(".")[2]
        if val is None:
            return
        if isinstance(val, ast.Attribute) and val.attr == "submit":
            submit_aliases.add(name)
            return
        if isinstance(val, ast.Call):
            cn = _dotted(val.func)
            if cn in (_PARTIAL_NAMES | _WRAP_NAMES) and val.args:
                keys = self._resolve_target_expr(v, cls, val.args[0],
                                                 env_fns, own_prefix)
                if keys:
                    env_fns[name] = keys
                return
            if cn:
                # constructor: `x = ClassName(...)`
                rcls = self._class_of_callable(v, cls, cn)
                if rcls:
                    env_types[name] = rcls
        else:
            t = _dotted(val)
            if t:
                keys = self._resolve_name(v, cls, t, env_fns, own_prefix)
                if keys:
                    env_fns[name] = keys
                elif isinstance(val, ast.Attribute):
                    # `x = self.attr` / `x = y.attr`: receiver's class
                    # attr table, then the project-unique attr fallback
                    recv_cls = None
                    if isinstance(val.value, ast.Name):
                        if val.value.id == "self" and cls is not None:
                            recv_cls = cls
                        else:
                            recv_cls = env_types.get(val.value.id)
                    acls = None
                    if recv_cls is not None:
                        ci = self._class_info(v, recv_cls)
                        if ci is not None:
                            acls = ci.attr_types.get(val.attr)
                    if acls is None \
                            or acls.rpartition(".")[2] in ("object", "Any"):
                        cands = self._attr_class_fallback.get(val.attr, ())
                        acls = (next(iter(cands)) if len(cands) == 1
                                else None)
                    if acls and acls.rpartition(".")[2] not in (
                            "object", "Any"):
                        env_types.setdefault(
                            name, acls.rpartition(".")[2])

    # -------------------------------------------------------- resolution

    def _resolve_module(self, dotted_mod: str) -> Optional[_ModuleView]:
        return self.by_stem.get(dotted_mod)

    def _lookup_in_module(self, view: _ModuleView, name: str,
                          depth: int = 0) -> List[str]:
        """Resolve `name` inside `view`'s namespace, following
        re-export chains (``from x import name``) up to 3 hops."""
        if name in view.functions:
            return [f"{view.mod.rel}::{name}"]
        if name in view.classes:
            ci = view.classes[name]
            if "__init__" in ci.methods:
                return [f"{view.mod.rel}::{ci.methods['__init__']}"]
            return []
        if name in view.aliases and depth < 3:
            return self._resolve_name(view, None, view.aliases[name],
                                      {}, "", depth + 1)
        if name in view.from_names and depth < 3:
            src_mod, orig = view.from_names[name]
            src = self._resolve_module(src_mod)
            if src is not None:
                return self._lookup_in_module(src, orig, depth + 1)
        return []

    def _class_info(self, view: _ModuleView,
                    cls_name: str) -> Optional[_ClassInfo]:
        cls_name = cls_name.rpartition(".")[2]
        if cls_name in view.classes:
            return view.classes[cls_name]
        if cls_name in view.from_names:
            src_mod, orig = view.from_names[cls_name]
            src = self._resolve_module(src_mod)
            if src is not None and orig in src.classes:
                return src.classes[orig]
        # unique class name project-wide
        hits = [ci for v2 in self.views.values()
                for n, ci in v2.classes.items() if n == cls_name]
        return hits[0] if len(hits) == 1 else None

    def _class_of_callable(self, view, cls, dotted_name) -> Optional[str]:
        """`ClassName(...)` or `fn(...)` with `-> ClassName`: the class
        name the result is an instance of."""
        tail = dotted_name.rpartition(".")[2]
        if tail[:1].isupper():
            ci = self._class_info(view, tail)
            if ci is not None:
                return ci.name
            # external constructor (queue.Queue(), threading.Event()):
            # still the instance's class name — method resolution on it
            # fails harmlessly, but type-based exemptions (the race
            # rule's thread-safe table) need it
            return tail
        for key in self._resolve_name(view, cls, dotted_name, {}, ""):
            fnode = self.functions.get(key)
            if fnode is None:
                continue
            v2 = self.views[fnode.mod_rel]
            rcls = v2.returns.get(fnode.qualname)
            if rcls:
                return rcls.rpartition(".")[2]
        return None

    def _method_on(self, view: _ModuleView, cls_name: str,
                   method: str) -> List[str]:
        """Resolve `method` on class `cls_name`, walking same-project
        base classes."""
        seen: Set[str] = set()
        queue = [cls_name]
        while queue:
            cn = queue.pop()
            if cn in seen:
                continue
            seen.add(cn)
            ci = self._class_info(view, cn)
            if ci is None:
                continue
            if method in ci.methods:
                return [f"{ci.mod_rel}::{ci.methods[method]}"]
            owner = self.views.get(ci.mod_rel, view)
            for b in ci.bases:
                queue.append(b.rpartition(".")[2])
            view = owner
        return []

    def _resolve_name(self, v: _ModuleView, cls: Optional[str],
                      name: str, env_fns: Dict[str, List[str]],
                      own_prefix: str = "", depth: int = 0) -> List[str]:
        """Resolve a dotted callable name to function keys."""
        if depth > 4:
            return []
        head, _, rest = name.partition(".")
        if not rest:
            if name in env_fns:
                return env_fns[name]
            # sibling nested def in the same enclosing function
            if own_prefix:
                parts = own_prefix.split(".")
                for i in range(len(parts), 0, -1):
                    qn = ".".join(parts[:i]) + f".{name}"
                    if qn in v.functions:
                        return [f"{v.mod.rel}::{qn}"]
            if name in v.functions:
                return [f"{v.mod.rel}::{name}"]
            if cls is not None and f"{cls}.{name}" in v.functions:
                # unqualified call to a sibling method only resolves as
                # a bare module function; don't invent `self.`
                pass
            if name in v.aliases:
                return self._resolve_name(v, cls, v.aliases[name],
                                          env_fns, "", depth + 1)
            if name in v.from_names:
                src_mod, orig = v.from_names[name]
                src = self._resolve_module(src_mod)
                if src is not None:
                    return self._lookup_in_module(src, orig, depth + 1)
            if name in v.classes:
                ci = v.classes[name]
                if "__init__" in ci.methods:
                    return [f"{v.mod.rel}::{ci.methods['__init__']}"]
            return []

        # dotted: try module-path resolution on the longest alias prefix
        # (`import a.b as x` binds x; `from a import b` binds b as a
        # module alias too when a.b is a scanned module)
        parts = name.split(".")
        for i in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:i])
            mod_dotted = v.imports.get(prefix)
            if mod_dotted is None and i == 1 and prefix in v.from_names:
                src_mod, orig = v.from_names[prefix]
                if self._resolve_module(f"{src_mod}.{orig}") is not None:
                    mod_dotted = f"{src_mod}.{orig}"
            if mod_dotted is None:
                continue
            sub = ".".join(parts[i:-1])
            src = self._resolve_module(
                f"{mod_dotted}.{sub}" if sub else mod_dotted)
            if src is not None:
                return self._lookup_in_module(src, parts[-1])
            break
        method = parts[-1]
        recv = ".".join(parts[:-1])
        if recv in ("self", "cls") and cls is not None:
            return self._method_on(v, cls, method)
        if len(parts) == 2:
            head = parts[0]
            if head in v.classes or (head[:1].isupper()
                                     and head in v.from_names):
                return self._method_on(v, head, method)
            if head in v.instances:
                recv_cls = self._class_of_callable(
                    v, cls, v.instances[head])
                if recv_cls is not None:
                    return self._method_on(v, recv_cls, method)
        # `self.attr.method()`: receiver type from the class attr table
        if len(parts) == 3 and parts[0] in ("self", "cls") \
                and cls is not None:
            ci = self._class_info(v, cls)
            if ci is not None and parts[1] in ci.attr_types:
                tcls = ci.attr_types[parts[1]].rpartition(".")[2]
                got = self._method_on(v, tcls, method)
                if got:
                    return got
        return []

    def _resolve_call(self, v: _ModuleView, cls: Optional[str],
                      node: ast.Call, env_types: Dict[str, str],
                      env_fns: Dict[str, List[str]],
                      own_prefix: str) -> List[str]:
        # dict dispatch: DISPATCH[op](...) / DISPATCH.get(op, d)(...)
        f = node.func
        if isinstance(f, ast.Subscript) and isinstance(f.value, ast.Name) \
                and f.value.id in v.dispatch:
            out: List[str] = []
            for t in v.dispatch[f.value.id]:
                out.extend(self._resolve_name(v, cls, t, env_fns,
                                              own_prefix))
            return out
        if isinstance(f, ast.Call):
            inner = _dotted(f.func)
            if inner and inner.rpartition(".")[2] == "get" \
                    and isinstance(f.func, ast.Attribute) \
                    and isinstance(f.func.value, ast.Name) \
                    and f.func.value.id in v.dispatch:
                out = []
                for t in v.dispatch[f.func.value.id]:
                    out.extend(self._resolve_name(v, cls, t, env_fns,
                                                  own_prefix))
                return out
        name = _dotted(f)
        if name is None:
            return []
        got = self._resolve_name(v, cls, name, env_fns, own_prefix)
        if got:
            return got
        # typed local receiver: `x = ClassName(...); x.method()`
        head, _, rest = name.partition(".")
        if rest and "." not in rest and head in env_types:
            got = self._method_on(v, env_types[head], rest)
            if got:
                return got
        # typed local attr chain: `e.table.update()` via attr_types
        parts = name.split(".")
        if len(parts) == 3 and parts[0] in env_types:
            ci = self._class_info(v, env_types[parts[0]])
            if ci is not None and parts[1] in ci.attr_types:
                owner = self.views.get(ci.mod_rel, v)
                tcls = ci.attr_types[parts[1]].rpartition(".")[2]
                got = self._method_on(owner, tcls, parts[2])
                if got:
                    return got
        # unique-definition fallback for attribute calls
        if rest:
            method = name.rpartition(".")[2]
            if method not in _COMMON_METHODS \
                    and not (method.startswith("__")
                             and method.endswith("__")):
                defs = self._method_defs.get(method, ())
                if len(defs) == 1:
                    return [defs[0][1]]
        return []

    # ------------------------------------------------------ call handler

    def _handle_call(self, v: _ModuleView, caller: str,
                     cls: Optional[str], node: ast.Call,
                     env_types, env_fns, submit_aliases,
                     in_loop: bool):
        own_prefix = caller.split("::", 1)[1]
        if own_prefix == MODULE_BODY:
            own_prefix = ""
        callees = self._resolve_call(v, cls, node, env_types, env_fns,
                                     own_prefix)
        if callees:
            self.call_sites[id(node)] = callees
        for key in callees:
            self.edges.append(CallEdge(caller, key, node.lineno,
                                       id(node)))
        # higher-order escape: a function value passed as an argument is
        # assumed invoked by the receiver (CFA-0). `Request(lambda: ...)`
        # gets an edge Request.__init__ -> lambda-callees, so a worker
        # pool draining Request objects still reaches the closure's code
        # through the constructor. With no resolved receiver the edge
        # falls back to the caller (the callable doesn't vanish).
        hosts = callees or [caller]
        name = _dotted(node.func)
        if name not in _PARTIAL_NAMES | _WRAP_NAMES | _THREAD_NAMES:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if not isinstance(arg, (ast.Lambda, ast.Name,
                                        ast.Attribute, ast.Call)):
                    continue
                for key in self._resolve_target_expr(v, cls, arg,
                                                     env_fns, own_prefix):
                    for h in hosts:
                        self.edges.append(
                            CallEdge(h, key, node.lineno))
                    for ck in callees:
                        fnode = self.functions.get(ck)
                        if fnode is not None and fnode.cls is not None \
                                and fnode.qualname.endswith("__init__"):
                            self._escaped_into.setdefault(
                                fnode.cls, set()).add(key)
        # `x.attr()` where x is typed but attr is not a method of the
        # class: deferred — resolves against callables that escaped into
        # the class's constructor (`self.fn = fn; ...; req.fn()`)
        if not callees and name and "." in name:
            head, _, rest = name.partition(".")
            if rest and "." not in rest:
                recv_cls = env_types.get(head)
                if recv_cls is None and head == "self" and cls is not None:
                    recv_cls = cls
                if recv_cls is not None:
                    self._pending_attr_calls.append(
                        (caller, recv_cls, rest, node.lineno))
        self._maybe_root(v, caller, cls, node, env_types, env_fns,
                         submit_aliases, in_loop, callees, own_prefix)

    def _maybe_root(self, v, caller, cls, node, env_types, env_fns,
                    submit_aliases, in_loop, callees, own_prefix):
        name = _dotted(node.func)
        rel, line = v.mod.rel, node.lineno

        def add_roots(expr, kind, multi):
            for key in self._resolve_target_expr(v, cls, expr, env_fns,
                                                 own_prefix):
                self.thread_roots.append(
                    ThreadRoot(key, rel, line, kind, multi))

        # threading.Thread(target=X) and Thread subclasses
        if name in _THREAD_NAMES:
            for kw in node.keywords:
                if kw.arg == "target":
                    add_roots(kw.value, "thread", in_loop)
        elif name is not None:
            tail = name.rpartition(".")[2]
            # instantiation of a threading.Thread subclass
            ci = None
            if tail[:1].isupper():
                ci = self._class_info(v, tail)
            if ci is not None and any(
                    b in _THREAD_NAMES or b.rpartition(".")[2] == "Thread"
                    for b in ci.bases) and "run" in ci.methods:
                self.thread_roots.append(ThreadRoot(
                    f"{ci.mod_rel}::{ci.methods['run']}", rel, line,
                    "thread-subclass", in_loop))
            # spawn wrappers (pool.spawn and friends)
            for key in callees:
                idx = self._spawn_wrappers.get(key)
                if idx is not None and idx < len(node.args):
                    add_roots(node.args[idx], "spawn-wrapper", in_loop)
                else:
                    fnode = self.functions.get(key)
                    if idx is not None and fnode is not None:
                        pname = fnode.node.args.args[idx].arg
                        for kw in node.keywords:
                            if kw.arg == pname:
                                add_roots(kw.value, "spawn-wrapper",
                                          in_loop)
            # obs.wrap(X): X is about to cross a thread boundary
            if name in _WRAP_NAMES and node.args:
                resolved_wrap = any(
                    self.functions.get(k) is not None
                    and "obs" in self.functions[k].mod_rel
                    for k in callees)
                if name != "wrap" or resolved_wrap:
                    add_roots(node.args[0], "obs-wrap", True)
        # executor submit / pool map
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "submit" and node.args:
                add_roots(node.args[0], "submit", True)
            elif node.func.attr == "map" and len(node.args) >= 2:
                add_roots(node.args[0], "pool-map", True)
        elif isinstance(node.func, ast.Name) \
                and node.func.id in submit_aliases and node.args:
            add_roots(node.args[0], "submit", True)

    def _resolve_target_expr(self, v, cls, expr, env_fns,
                             own_prefix) -> List[str]:
        """Resolve a thread-target expression to function keys,
        unwrapping obs.wrap(f) / functools.partial(f, ...) and lambdas
        (a lambda roots every function it calls)."""
        if isinstance(expr, ast.Call):
            cn = _dotted(expr.func)
            if cn in _WRAP_NAMES | _PARTIAL_NAMES and expr.args:
                return self._resolve_target_expr(v, cls, expr.args[0],
                                                 env_fns, own_prefix)
            return []
        if isinstance(expr, ast.Lambda):
            out: List[str] = []
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    n = _dotted(sub.func)
                    if n:
                        out.extend(self._resolve_name(
                            v, cls, n, env_fns, own_prefix))
            return out
        name = _dotted(expr)
        if name is None:
            return []
        return self._resolve_name(v, cls, name, env_fns, own_prefix)

    # ------------------------------------------------------ dataflow API

    def reachable_from(self, keys: Iterable[str]) -> Set[str]:
        """Transitive closure over call edges."""
        seen: Set[str] = set()
        queue = [k for k in keys]
        while queue:
            k = queue.pop()
            if k in seen:
                continue
            seen.add(k)
            for e in self.edges_out.get(k, ()):
                if e.callee not in seen:
                    queue.append(e.callee)
        return seen

    def root_reach(self) -> Dict[str, Set[str]]:
        """function key -> set of thread-root site ids that reach it.
        Multi roots contribute two pseudo-sites (they alone imply
        concurrent execution of everything they reach)."""
        out: Dict[str, Set[str]] = {}
        for r in self.thread_roots:
            sites = [r.site] if not r.multi else [r.site, r.site + "*"]
            for k in self.reachable_from([r.target]):
                out.setdefault(k, set()).update(sites)
        return out

    def propagate_meet(
        self, entries: Dict[str, FrozenSet[str]],
        edge_gain: Callable[[CallEdge], FrozenSet[str]],
        domain: Optional[Set[str]] = None,
    ) -> Dict[str, FrozenSet[str]]:
        """Meet-over-paths dataflow: fact(F) = ∩ over incoming edges of
        (fact(caller) ∪ edge_gain(edge)), seeded by `entries` (thread
        entry points start with their given fact — usually ∅).

        Used for interprocedural held-locks: a lock protects a mutation
        only if it is held on EVERY path from a thread entry, so the
        merge is intersection and unanalyzed callers contribute top
        (ignored). Monotone on a finite lattice -> terminates."""
        fact: Dict[str, FrozenSet[str]] = dict(entries)
        keys = domain if domain is not None else set(self.functions)
        changed = True
        while changed:
            changed = False
            for k in keys:
                if k in entries:
                    continue
                met: Optional[FrozenSet[str]] = None
                for e in self.edges_in.get(k, ()):
                    src = fact.get(e.caller)
                    if src is None:
                        continue  # caller not on any analyzed path: top
                    val = src | edge_gain(e)
                    met = val if met is None else (met & val)
                if met is not None and fact.get(k) != met:
                    fact[k] = met
                    changed = True
        return fact


# cached like the lock model: keyed on module-list identity, holding the
# module objects so addresses can't be reused by a later scan
_GRAPH_CACHE: List[Tuple[List[ModuleInfo], ProjectGraph]] = []


def project_graph(mods: List[ModuleInfo]) -> ProjectGraph:
    if _GRAPH_CACHE:
        cached_mods, cached = _GRAPH_CACHE[0]
        if len(cached_mods) == len(mods) \
                and all(a is b for a, b in zip(cached_mods, mods)):
            return cached
    g = ProjectGraph([m for m in mods if m.tree is not None])
    _GRAPH_CACHE[:] = [(list(mods), g)]
    return g


def _body_of(fn: ast.AST) -> list:
    return getattr(fn, "body", [])

"""delta-lint core: module model, rule plugin registry, analysis engine.

The engine runs in three passes, so project-wide rules (lock-order
cycles, catalog conformance) see every module before they report:

1. **load** — read + parse every target file once into a
   :class:`ModuleInfo` (AST, source lines, suppression pragmas);
2. **module pass** — each rule's :meth:`Rule.check_module` runs per
   file (purely local rules live entirely here);
3. **project pass** — each rule's :meth:`Rule.check_project` runs once
   over all modules (rules typically accumulate facts during the module
   pass and cross-reference them here).

Adding a rule: subclass :class:`Rule`, set ``id``/``description``,
implement either hook, decorate with :func:`register`, and import the
module from ``passes/__init__.py``. Fixture-test it in
``tests/test_analyzer.py`` (every rule must both fire on its positive
fixture and stay silent on its negative one).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Type

from delta_tpu.tools.analyzer.suppress import is_suppressed, parse_suppressions


@dataclass(frozen=True)
class Finding:
    """One diagnostic. `line`/`col` are 1-based / 0-based like CPython
    AST nodes. `severity` is "error" or "warning" (both fail the run;
    the split only drives reporting)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str = "error"


class ModuleInfo:
    """One parsed target file plus its suppression pragmas."""

    def __init__(self, path: str, source: str, rel: Optional[str] = None):
        self.path = path
        self.rel = rel if rel is not None else path
        self.source = source
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(source, path)
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
        self.suppress_lines, self.suppress_file = parse_suppressions(source)

    def suppressed(self, rule_id: str, line: int) -> bool:
        return is_suppressed(rule_id, line, self.suppress_lines,
                             self.suppress_file)


class Rule:
    """Plugin base. Stateless across runs: the engine instantiates a
    fresh rule object per analysis, so instance attributes are safe
    scratch space for module-pass fact accumulation."""

    id: str = "?"
    description: str = ""

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def check_project(self, mods: List[ModuleInfo]) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a rule to the global registry."""
    if cls.id in _REGISTRY and _REGISTRY[cls.id] is not cls:
        raise ValueError(f"duplicate delta-lint rule id: {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    """id -> rule class for every registered rule (imports the bundled
    passes on first use so the registry is populated)."""
    import delta_tpu.tools.analyzer.passes  # noqa: F401  (registers)

    return dict(_REGISTRY)


@dataclass
class Report:
    findings: List[Finding]          # unsuppressed — these fail the run
    suppressed: List[Finding]        # matched a disable pragma
    files_scanned: int
    rules_run: List[str]

    @property
    def ok(self) -> bool:
        return not self.findings

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


# --------------------------------------------------------------- collection

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def _iter_py_files(path: str) -> Iterable[str]:
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def load_modules(paths: Iterable[str],
                 root: Optional[str] = None) -> List[ModuleInfo]:
    mods: List[ModuleInfo] = []
    for p in paths:
        for fp in _iter_py_files(p):
            with open(fp, encoding="utf-8") as f:
                source = f.read()
            rel = os.path.relpath(fp, root) if root else fp
            mods.append(ModuleInfo(fp, source, rel=rel))
    return mods


# ------------------------------------------------------------------ engine


def _run(mods: List[ModuleInfo],
         rule_ids: Optional[Iterable[str]] = None) -> Report:
    registry = all_rules()
    ids = list(rule_ids) if rule_ids is not None else sorted(registry)
    unknown = [i for i in ids if i not in registry]
    if unknown:
        raise ValueError(f"unknown delta-lint rule(s): {unknown}; "
                         f"known: {sorted(registry)}")
    rules = [registry[i]() for i in ids]

    raw: List[Finding] = []
    for mod in mods:
        if mod.syntax_error is not None:
            e = mod.syntax_error
            raw.append(Finding("parse-error", mod.rel, e.lineno or 1, 0,
                               f"syntax error: {e.msg}"))
            continue
        for rule in rules:
            raw.extend(rule.check_module(mod))
    parsed = [m for m in mods if m.tree is not None]
    for rule in rules:
        raw.extend(rule.check_project(parsed))

    by_rel = {m.rel: m for m in mods}
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for f in raw:
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed(f.rule, f.line):
            suppressed.append(f)
        else:
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return Report(findings=findings, suppressed=suppressed,
                  files_scanned=len(mods), rules_run=ids)


def analyze_paths(paths: Iterable[str], root: Optional[str] = None,
                  rules: Optional[Iterable[str]] = None) -> Report:
    """Analyze every ``.py`` file under `paths` (files or directories)."""
    return _run(load_modules(paths, root=root), rules)


def analyze_sources(sources: Dict[str, str],
                    rules: Optional[Iterable[str]] = None) -> Report:
    """Analyze in-memory sources (virtual path -> source text) — the
    fixture-test entry point."""
    mods = [ModuleInfo(path, src) for path, src in sources.items()]
    return _run(mods, rules)

"""Command-line front end: ``python -m delta_tpu.tools.analyzer`` /
the ``delta-lint`` console script.

Exit status: 0 when the unsuppressed-findings list is empty, 1 when
any rule fired, 2 on usage errors — so CI can gate on it directly.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from delta_tpu.tools.analyzer.core import all_rules, analyze_paths
from delta_tpu.tools.analyzer.report import render_json, render_text


def _default_target() -> str:
    """The installed delta_tpu package itself."""
    import delta_tpu

    return os.path.dirname(os.path.abspath(delta_tpu.__file__))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="delta-lint",
        description="delta-tpu project-native static analysis "
                    "(lock discipline, JAX purity, error-catalog "
                    "conformance, exception hygiene, undefined names)")
    p.add_argument("paths", nargs="*",
                   help="files or directories to scan "
                        "(default: the delta_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (json is SARIF-lite)")
    p.add_argument("--rules",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by "
                        "`# delta-lint: disable=...` pragmas")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule_id, cls in sorted(all_rules().items()):
            print(f"{rule_id}: {cls.description or cls.__doc__ or ''}"
                  .strip())
        return 0

    paths = args.paths or [_default_target()]
    for p in paths:
        if not os.path.exists(p):
            print(f"delta-lint: no such path: {p}", file=sys.stderr)
            return 2
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        report = analyze_paths(paths, rules=rules)
    except ValueError as e:  # unknown rule id
        print(f"delta-lint: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(render_json(report))
    else:
        print(render_text(report, verbose=args.show_suppressed))
    return 0 if report.ok else 1


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())

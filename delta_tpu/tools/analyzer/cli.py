"""Command-line front end: ``python -m delta_tpu.tools.analyzer`` /
the ``delta-lint`` console script.

Exit status: 0 when the unsuppressed-findings list is empty, 1 when
any rule fired, 2 on usage errors — so CI can gate on it directly.
With ``--baseline check``, findings matched against the committed
baseline are known debt and do not fail the run; only NEW findings do.
``--changed`` consults the scan cache and skips the scan entirely when
no scanned file changed since the cached run.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from delta_tpu.tools.analyzer.baseline import (
    apply_baseline,
    default_baseline_path,
    load_baseline,
    write_baseline,
)
from delta_tpu.tools.analyzer.cache import (
    analyze_paths_cached,
    default_cache_path,
)
from delta_tpu.tools.analyzer.core import all_rules, analyze_paths
from delta_tpu.tools.analyzer.report import render_json, render_text


def _default_target() -> str:
    """The installed delta_tpu package itself."""
    import delta_tpu

    return os.path.dirname(os.path.abspath(delta_tpu.__file__))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="delta-lint",
        description="delta-tpu project-native static analysis "
                    "(lock discipline, shared-state races, transfer "
                    "budgets, JAX purity, error-catalog conformance, "
                    "exception hygiene, undefined names)")
    p.add_argument("paths", nargs="*",
                   help="files or directories to scan "
                        "(default: the delta_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (json is SARIF-lite)")
    p.add_argument("--rules",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by "
                        "`# delta-lint: disable=...` pragmas "
                        "(and baselined findings under "
                        "--baseline check)")
    p.add_argument("--changed", action="store_true",
                   help="use the scan cache: skip the scan when no "
                        "target file changed since the last cached run")
    p.add_argument("--cache-file", default=None,
                   help="scan cache location (default: "
                        "$DELTA_LINT_CACHE or .delta-lint-cache.json)")
    p.add_argument("--baseline", choices=("write", "check"),
                   help="'write': snapshot current findings as the "
                        "accepted baseline; 'check': fail only on "
                        "findings not in the baseline")
    p.add_argument("--baseline-file", default=None,
                   help="baseline location (default: "
                        "$DELTA_LINT_BASELINE or "
                        "delta-lint-baseline.json)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule_id, cls in sorted(all_rules().items()):
            desc = (cls.description or cls.__doc__ or "").strip()
            print(f"{rule_id}: {desc}  [{cls.help_uri()}]")
        return 0

    # Anchor the default target at the package parent so module rels
    # come out as "delta_tpu/..." — the form the module-scoped rules
    # (dispatch coverage, transfer budget, recompile risk) and the
    # manifest site keys are written in. Explicit paths scan as given.
    root = None
    if not args.paths:
        root = os.path.dirname(_default_target())
    paths = args.paths or [_default_target()]
    for p in paths:
        if not os.path.exists(p):
            print(f"delta-lint: no such path: {p}", file=sys.stderr)
            return 2
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        if args.changed:
            report, stats = analyze_paths_cached(
                paths, root=root, rules=rules,
                cache_path=args.cache_file or default_cache_path())
            print(f"delta-lint: cache {stats['cache']} "
                  f"({stats['changed_files']} changed of "
                  f"{stats['files']} files)", file=sys.stderr)
        else:
            report = analyze_paths(paths, root=root, rules=rules)
    except ValueError as e:  # unknown rule id
        print(f"delta-lint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline_file or default_baseline_path()
    if args.baseline == "write":
        n = write_baseline(baseline_path, report)
        print(f"delta-lint: baseline written to {baseline_path} "
              f"({n} finding(s))", file=sys.stderr)
        return 0
    if args.baseline == "check":
        baseline = load_baseline(baseline_path)
        if baseline is None:
            print(f"delta-lint: no readable baseline at "
                  f"{baseline_path} (run --baseline write first)",
                  file=sys.stderr)
            return 2
        report = apply_baseline(report, baseline)

    if args.format == "json":
        print(render_json(report))
    else:
        print(render_text(report, verbose=args.show_suppressed))
    return 0 if report.ok else 1


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())

"""delta-lint: project-native static analysis for delta-tpu.

An AST-based multi-pass analyzer that understands *this* codebase's
invariants, in the role scalastyle + compile-time checks play for the
JVM reference implementation:

- ``lock-order`` / ``lock-io`` / ``global-mutation`` — lock-discipline
  race detector over the optimistic-concurrency path
  (:mod:`delta_tpu.tools.analyzer.passes.locks`);
- ``jit-impure`` / ``jit-sync`` — purity lint for every function
  reachable from a ``jax.jit`` / ``pallas_call`` decoration site
  (:mod:`delta_tpu.tools.analyzer.passes.purity`);
- ``error-uncataloged`` / ``error-dead-entry`` / ``error-untyped-raise``
  — two-way conformance between raise sites and
  ``resources/error_classes.json``
  (:mod:`delta_tpu.tools.analyzer.passes.errors_catalog`);
- ``except-swallow`` / ``mutable-default`` — exception hygiene
  (:mod:`delta_tpu.tools.analyzer.passes.hygiene`);
- ``undefined-name`` — module-level name resolution
  (:mod:`delta_tpu.tools.analyzer.passes.imports`).

Run it as ``python -m delta_tpu.tools.analyzer delta_tpu/`` (or the
``delta-lint`` console script), suppress audited false positives with
``# delta-lint: disable=RULE`` comments, and see
``docs/static_analysis.md`` for the rule catalog and plugin API.
"""

from delta_tpu.tools.analyzer.core import (  # noqa: F401
    Finding,
    ModuleInfo,
    Report,
    Rule,
    all_rules,
    analyze_paths,
    analyze_sources,
    register,
)

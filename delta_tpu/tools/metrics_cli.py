"""delta-metrics: scrape or render Prometheus-text metrics.

Usage::

    delta-metrics --connect HOST:PORT        # scrape a running server
    delta-metrics --local                    # this process's registry
    delta-metrics --connect HOST:PORT --json # parsed series as JSON
    delta-metrics --local --grep server.     # filter series by substring
    python -m delta_tpu.tools.metrics_cli    # same, without the script

``--connect`` issues the ``metrics`` op over the framed connect
protocol (served inline by `delta-serve` even when the admission queue
is full, and by the plain connect server's op table), so any running
server is scrapeable with no extra listener or HTTP stack. ``--local``
renders this process's registry — mostly useful under
``DELTA_LINT_METRIC_CATALOG`` experiments or in scripts that import
delta_tpu and want a one-shot exposition dump.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from delta_tpu.obs.expose import parse_prometheus, render_prometheus


def _scrape_remote(target: str, timeout: float) -> str:
    host, _, port = target.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"--connect wants HOST:PORT, got {target!r}")
    from delta_tpu.connect.client import DeltaConnectClient

    with DeltaConnectClient(host, int(port), timeout=timeout,
                            reconnect=False) as client:
        return client.metrics_text()


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="delta-metrics",
        description="Scrape or render delta-tpu metrics "
                    "(Prometheus text exposition).")
    source = parser.add_mutually_exclusive_group(required=True)
    source.add_argument("--connect", metavar="HOST:PORT",
                        help="scrape a running delta-serve/connect server")
    source.add_argument("--local", action="store_true",
                        help="render this process's registry")
    parser.add_argument("--json", action="store_true",
                        help="print parsed series as JSON instead of text")
    parser.add_argument("--grep", metavar="SUBSTR",
                        help="only series whose name contains SUBSTR")
    parser.add_argument("--timeout", type=float, default=10.0,
                        help="scrape timeout in seconds (default 10)")
    args = parser.parse_args(argv)

    try:
        if args.connect:
            text = _scrape_remote(args.connect, args.timeout)
        else:
            text = render_prometheus()
    except Exception as e:
        print(f"delta-metrics: {e}", file=sys.stderr)
        return 2

    if args.grep:
        kept = [line for line in text.splitlines()
                if args.grep in line]
        text = "\n".join(kept) + ("\n" if kept else "")
    if args.json:
        print(json.dumps(parse_prometheus(text), indent=2,
                         sort_keys=True))
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())

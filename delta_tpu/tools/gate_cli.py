"""delta-gate: gate-calibration report over a device-obs gate log.

The dispatch profiler (`obs.device`) journals two record types when
``DELTA_TPU_DEVICE_OBS=on``: ``gate_decision`` (route chosen, inputs,
per-route predicted cost, joined observed cost, signed calibration
error) and ``device_dispatch`` (per-kernel wall time, compile flag,
audited transfer bytes). `obs.dump_gate_log(path)` — called by the
bench harness — serializes both as JSONL; this tool turns that artifact
into the answer the link-model economics actually need: *how wrong are
the DEVICE_MERIT predictions on this hardware, per gate, per route?*

Usage::

    delta-gate gate_log.jsonl                 # calibration table
    delta-gate gate_log.jsonl --dispatches    # per-kernel dispatch rollup
    delta-gate gate_log.jsonl --json          # summary as JSON
    delta-gate gate_log.jsonl --merit out.json  # fresh DEVICE_MERIT capture
    python -m delta_tpu.tools.gate_cli ...    # same, without the script

``--merit`` distills the log into a DEVICE_MERIT.json-shaped capture
(observed link bandwidth, replay workload rates, capture conditions) —
running the bench on real hardware with device obs on and exporting
here IS the ROADMAP's deferred merit recapture.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from delta_tpu.obs.device import export_device_merit, summarize_gates


def load_gate_log(path: str) -> Tuple[List[dict], List[dict]]:
    """Split a dump_gate_log JSONL artifact into (gates, dispatches);
    unparseable lines are skipped (the log may be tail-truncated)."""
    gates: List[dict] = []
    dispatches: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("type") == "gate_decision":
                gates.append(rec)
            elif rec.get("type") == "device_dispatch":
                dispatches.append(rec)
    return gates, dispatches


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v * 1e3:.3f}ms" if v < 1 else f"{v:.3f}s"


def render_calibration(summary: Dict[str, dict]) -> str:
    lines = []
    for gate in sorted(summary):
        g = summary[gate]
        lines.append(f"gate {gate}: {g['decisions']} decisions, "
                     f"{g['fallbacks']} fallbacks")
        for route in sorted(g["routes"]):
            r = g["routes"][route]
            err = (f"{r['median_abs_err_pct']:.1f}%"
                   if r["median_abs_err_pct"] is not None else "-")
            lines.append(
                f"  {route:<8} n={r['n']:<4} joined={r['joined']:<4} "
                f"predicted~{_fmt_s(r['median_predicted_s']):<10} "
                f"observed~{_fmt_s(r['median_observed_s']):<10} "
                f"|err|~{err}")
    return "\n".join(lines) if lines else "no gate decisions in log"


def dispatch_rollup(dispatches: List[dict]) -> Dict[str, dict]:
    """Per-kernel aggregate: dispatch/compile counts, median steady-state
    wall, transferred bytes, budget violations."""
    out: Dict[str, dict] = {}
    for d in dispatches:
        k = out.setdefault(d.get("kernel", "?"),
                           {"dispatches": 0, "compiles": 0, "h2d_bytes": 0,
                            "d2h_bytes": 0, "violations": 0, "_walls": []})
        k["dispatches"] += 1
        k["compiles"] += bool(d.get("compile"))
        k["h2d_bytes"] += int(d.get("h2d_bytes", 0))
        k["d2h_bytes"] += int(d.get("d2h_bytes", 0))
        k["violations"] += len(d.get("violations") or [])
        if not d.get("compile"):
            k["_walls"].append(int(d.get("wall_ns", 0)))
    for k in out.values():
        walls = sorted(k.pop("_walls"))
        k["median_steady_wall_ns"] = walls[len(walls) // 2] if walls else None
    return out


def render_dispatches(rollup: Dict[str, dict]) -> str:
    lines = []
    for kernel in sorted(rollup):
        k = rollup[kernel]
        wall = k["median_steady_wall_ns"]
        wall_s = f"{wall / 1e6:.3f}ms" if wall is not None else "-"
        viol = f"  VIOLATIONS={k['violations']}" if k["violations"] else ""
        lines.append(
            f"{kernel:<28} n={k['dispatches']:<5} "
            f"compiles={k['compiles']:<3} steady~{wall_s:<10} "
            f"h2d={k['h2d_bytes']:<12} d2h={k['d2h_bytes']}{viol}")
    return "\n".join(lines) if lines else "no dispatch records in log"


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="delta-gate",
        description="Predicted-vs-observed gate calibration from a "
                    "device-obs gate log (obs.dump_gate_log JSONL).")
    parser.add_argument("log", help="gate log path (JSONL)")
    parser.add_argument("--json", action="store_true",
                        help="print the summary as JSON")
    parser.add_argument("--dispatches", action="store_true",
                        help="per-kernel dispatch rollup instead of the "
                             "calibration table")
    parser.add_argument("--merit", metavar="OUT",
                        help="also write a DEVICE_MERIT-shaped capture "
                             "distilled from the log")
    args = parser.parse_args(argv)

    try:
        gates, dispatches = load_gate_log(args.log)
    except OSError as e:
        print(f"delta-gate: {e}", file=sys.stderr)
        return 2

    payload: Dict[str, Any]
    if args.dispatches:
        payload = dispatch_rollup(dispatches)
        print(json.dumps(payload, indent=2) if args.json
              else render_dispatches(payload))
    else:
        payload = summarize_gates(gates)
        print(json.dumps(payload, indent=2) if args.json
              else render_calibration(payload))

    if args.merit:
        capture = export_device_merit(gates, dispatches)
        with open(args.merit, "w", encoding="utf-8") as f:
            json.dump(capture, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"merit capture -> {args.merit}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""delta-hbm: resident-artifact report over an HBM ledger dump.

The resident ledger (`obs.hbm`) tracks every device-resident artifact —
replay key lanes, scan-planning stats indexes, checkpoint handoff
codes — with ``(table_path, kind, version, nbytes, rebuild_cost_class,
created_at, last_access)``. `hbm.dump_ledger(path)` serializes the live
residents plus the leak ring as JSONL; this tool turns that artifact
into the fleet-budget answers ROADMAP item 6 needs: *which tables hold
how much HBM, in what kinds, and did anything leak?*

Usage::

    delta-hbm ledger.jsonl                  # rollup by table (default)
    delta-hbm ledger.jsonl --by kind        # rollup by kind
    delta-hbm ledger.jsonl --top 10         # N largest residents
    delta-hbm ledger.jsonl --leaks          # leak report
    delta-hbm ledger.jsonl --json           # any of the above as JSON
    python -m delta_tpu.tools.hbm_cli ...   # same, without the script

Rollups computed here from a JSONL dump match `hbm.rollup()` over the
live ledger record-for-record — the round-trip is covered by
tests/test_hbm_ledger.py.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple


def load_ledger_dump(path: str) -> Tuple[List[dict], List[dict]]:
    """Split a dump_ledger JSONL artifact into (residents, leaks);
    unparseable lines are skipped (the dump may be tail-truncated)."""
    residents: List[dict] = []
    leaks: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("type") == "hbm_resident":
                residents.append(rec)
            elif rec.get("type") == "hbm_leak":
                leaks.append(rec)
    return residents, leaks


def rollup_records(residents: List[dict], by: str = "table") -> Dict[str, dict]:
    """Per-table (or per-kind) byte/artifact totals from dump records —
    the same shape `hbm.rollup()` produces from the live ledger."""
    if by not in ("table", "kind"):
        raise ValueError(f"rollup by {by!r}; expected 'table' or 'kind'")
    sub_key = "by_kind" if by == "table" else "by_table"
    out: Dict[str, dict] = {}
    for r in residents:
        key = r.get("table_path") if by == "table" else r.get("kind")
        sub = r.get("kind") if by == "table" else r.get("table_path")
        nbytes = int(r.get("nbytes", 0))
        ent = out.setdefault(key, {"nbytes": 0, "artifacts": 0, sub_key: {}})
        ent["nbytes"] += nbytes
        ent["artifacts"] += 1
        ent[sub_key][sub] = ent[sub_key].get(sub, 0) + nbytes
    return out


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def render_rollup(rollup: Dict[str, dict], by: str) -> str:
    sub_key = "by_kind" if by == "table" else "by_table"
    lines = []
    for key in sorted(rollup, key=lambda k: -rollup[k]["nbytes"]):
        ent = rollup[key]
        lines.append(f"{by} {key}: {_fmt_bytes(ent['nbytes'])} "
                     f"in {ent['artifacts']} artifacts")
        for sub in sorted(ent[sub_key], key=lambda s: -ent[sub_key][s]):
            lines.append(f"  {sub:<16} {_fmt_bytes(ent[sub_key][sub])}")
    return "\n".join(lines) if lines else "no resident artifacts in dump"


def render_top(residents: List[dict], top: int) -> str:
    ranked = sorted(residents,
                    key=lambda r: (-int(r.get("nbytes", 0)),
                                   r.get("seq", 0)))[:top]
    lines = []
    for r in ranked:
        ver = r.get("version")
        lines.append(
            f"{_fmt_bytes(int(r.get('nbytes', 0))):>10}  "
            f"{r.get('kind', '?'):<14} {r.get('table_path', '?')}"
            f"{'' if ver is None else f' @v{ver}'}  "
            f"[{r.get('rebuild_cost_class', '?')}]")
    return "\n".join(lines) if lines else "no resident artifacts in dump"


def render_leaks(leaks: List[dict]) -> str:
    lines = []
    for r in leaks:
        lines.append(
            f"LEAK {r.get('kind', '?')} artifact of "
            f"{r.get('table_path', '?')} "
            f"({_fmt_bytes(int(r.get('nbytes', 0)))}) — owner GC'd "
            f"without release()")
    return "\n".join(lines) if lines else "no leaks recorded"


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="delta-hbm",
        description="Resident-artifact rollups, top-N, and leak report "
                    "from an HBM ledger dump (hbm.dump_ledger JSONL).")
    parser.add_argument("dump", help="ledger dump path (JSONL)")
    parser.add_argument("--by", choices=("table", "kind"), default="table",
                        help="rollup dimension (default: table)")
    parser.add_argument("--top", type=int, metavar="N",
                        help="N largest residents instead of the rollup")
    parser.add_argument("--leaks", action="store_true",
                        help="leak report instead of the rollup")
    parser.add_argument("--json", action="store_true",
                        help="print the selected view as JSON")
    args = parser.parse_args(argv)

    try:
        residents, leaks = load_ledger_dump(args.dump)
    except OSError as e:
        print(f"delta-hbm: {e}", file=sys.stderr)
        return 2

    payload: Any
    if args.leaks:
        payload = leaks
        print(json.dumps(payload, indent=2) if args.json
              else render_leaks(leaks))
        # a nonzero leak count is the signal CI greps for
        return 1 if leaks else 0
    if args.top:
        payload = sorted(residents,
                         key=lambda r: (-int(r.get("nbytes", 0)),
                                        r.get("seq", 0)))[:args.top]
        print(json.dumps(payload, indent=2) if args.json
              else render_top(residents, args.top))
    else:
        payload = rollup_records(residents, by=args.by)
        print(json.dumps(payload, indent=2) if args.json
              else render_rollup(payload, args.by))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Bulk import into Delta tables: the `connectors/sql-delta-import`
role (reference `connectors/sql-delta-import/src/main/scala/.../
ImportRunner.scala`) rebuilt for file sources.

The reference splits a JDBC source into numeric-range chunks and writes
each chunk through the Delta writer; here the source is CSV / Parquet /
NDJSON files (plus any Arrow-readable iterable), chunked by row count,
with each chunk appended in its own transaction so imports of arbitrary
size never materialize fully in memory. A SQLite source covers the
"database table → Delta" path without a JDBC driver.

CLI:
    python -m delta_tpu.tools.importer --source data.csv \
        --destination /path/to/table [--format csv|parquet|ndjson|sqlite]
        [--partition-by col,col] [--chunk-rows N] [--mode append|overwrite]
        [--query 'SELECT ...'] (sqlite only)
"""

from __future__ import annotations

import argparse
import glob
import os
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

import pyarrow as pa

from delta_tpu.errors import DeltaError, ImportError_

DEFAULT_CHUNK_ROWS = 1_000_000


@dataclass
class ImportResult:
    num_rows: int = 0
    num_chunks: int = 0
    num_source_files: int = 0
    first_version: Optional[int] = None
    last_version: Optional[int] = None

    def to_dict(self):
        return dict(self.__dict__)


def _detect_format(path: str) -> str:
    ext = os.path.splitext(path)[1].lower().lstrip(".")
    if ext in ("csv", "tsv"):
        return "csv"
    if ext in ("parquet", "pq"):
        return "parquet"
    if ext in ("json", "jsonl", "ndjson"):
        return "ndjson"
    if ext in ("db", "sqlite", "sqlite3"):
        return "sqlite"
    raise ImportError_(
        f"cannot infer import format from {path!r}; pass --format",
        error_class="DELTA_IMPORT_FORMAT_UNKNOWN")


def _expand_sources(source: str) -> List[str]:
    if os.path.isdir(source):
        files = sorted(
            p for p in glob.glob(os.path.join(source, "**", "*"), recursive=True)
            if os.path.isfile(p) and not os.path.basename(p).startswith((".", "_"))
        )
    else:
        files = sorted(glob.glob(source)) or [source]
    missing = [f for f in files if not os.path.exists(f)]
    if missing:
        raise ImportError_(f"source file(s) not found: {missing}",
                           error_class="DELTA_IMPORT_SOURCE_NOT_FOUND")
    return files


def _iter_batches(path: str, fmt: str, chunk_rows: int,
                  query: Optional[str] = None) -> Iterator[pa.Table]:
    if fmt == "csv":
        import pyarrow.csv as pacsv

        delim = "\t" if path.endswith(".tsv") else ","
        with pacsv.open_csv(
            path,
            read_options=pacsv.ReadOptions(block_size=16 << 20),
            parse_options=pacsv.ParseOptions(delimiter=delim),
        ) as reader:
            for batch in reader:
                yield pa.Table.from_batches([batch])
    elif fmt == "parquet":
        import pyarrow.parquet as pq

        f = pq.ParquetFile(path)
        for batch in f.iter_batches(batch_size=chunk_rows):
            yield pa.Table.from_batches([batch])
    elif fmt == "ndjson":
        import pyarrow.json as pajson

        # pyarrow.json reads whole-file; chunk by slicing
        tbl = pajson.read_json(path)
        for start in range(0, max(tbl.num_rows, 1), chunk_rows):
            sl = tbl.slice(start, chunk_rows)
            if sl.num_rows or tbl.num_rows == 0:
                yield sl
    elif fmt == "sqlite":
        yield from _iter_sqlite(path, query, chunk_rows)
    else:
        raise ImportError_(f"unsupported import format {fmt!r}")


def _iter_sqlite(path: str, query: Optional[str],
                 chunk_rows: int) -> Iterator[pa.Table]:
    import sqlite3

    conn = sqlite3.connect(path)
    try:
        if query is None:
            tables = [r[0] for r in conn.execute(
                "SELECT name FROM sqlite_master WHERE type='table'")]
            if len(tables) != 1:
                raise ImportError_(
                    error_class="DELTA_IMPORT_AMBIGUOUS_QUERY",
                    message=f"sqlite source has tables {tables}; pass --query "
                    "'SELECT ... FROM <table>'")
            query = f"SELECT * FROM {tables[0]}"
        cur = conn.execute(query)
        names = [d[0] for d in cur.description]
        schema: Optional[pa.Schema] = None
        while True:
            rows = cur.fetchmany(chunk_rows)
            if not rows:
                break
            cols = list(zip(*rows))
            tbl = pa.table({n: pa.array(list(c)) for n, c in zip(names, cols)})
            # all-NULL columns infer arrow's null type and chunk-local
            # inference can drift; pin the first chunk's schema (nulls →
            # string) and cast every later chunk to it
            if schema is None:
                fields = [
                    pa.field(f.name, pa.string() if pa.types.is_null(f.type)
                             else f.type)
                    for f in tbl.schema
                ]
                schema = pa.schema(fields)
            yield tbl.cast(schema)
    finally:
        conn.close()


def _accumulate(batches: Iterator[pa.Table], chunk_rows: int) -> Iterator[pa.Table]:
    """Regroup arbitrary-size batches into ≤chunk_rows transactions
    (oversized source batches are sliced, small ones coalesced)."""
    pending: List[pa.Table] = []
    n = 0
    for b in batches:
        for start in range(0, max(b.num_rows, 1), chunk_rows):
            sl = b.slice(start, chunk_rows)
            # flush before appending whenever the slice would push the
            # transaction past chunk_rows, so a yielded chunk never
            # exceeds the bound (only the slice that exactly fills it
            # rides in the same transaction)
            if pending and n + sl.num_rows > chunk_rows:
                yield pa.concat_tables(pending, promote_options="permissive")
                pending, n = [], 0
            pending.append(sl)
            n += sl.num_rows
            if n >= chunk_rows:
                yield pa.concat_tables(pending, promote_options="permissive")
                pending, n = [], 0
    if pending:
        yield pa.concat_tables(pending, promote_options="permissive")


def import_into_delta(
    source: str,
    destination: str,
    fmt: Optional[str] = None,
    partition_by: Optional[Sequence[str]] = None,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
    mode: str = "append",
    query: Optional[str] = None,
    engine=None,
) -> ImportResult:
    """Stream `source` into the Delta table at `destination` in
    chunk-sized transactions. `mode='overwrite'` replaces the table with
    the first chunk, then appends."""
    import delta_tpu.api as dta

    files = _expand_sources(source)
    result = ImportResult(num_source_files=len(files))
    write_mode = mode
    for path in files:
        f_fmt = fmt or _detect_format(path)
        for chunk in _accumulate(
                _iter_batches(path, f_fmt, chunk_rows, query), chunk_rows):
            if chunk.num_rows == 0 and result.num_chunks:
                continue
            v = dta.write_table(
                destination, chunk, mode=write_mode,
                partition_by=partition_by, engine=engine)
            write_mode = "append"  # only the first chunk may overwrite
            result.num_rows += chunk.num_rows
            result.num_chunks += 1
            if result.first_version is None:
                result.first_version = v
            result.last_version = v
    if result.num_chunks == 0:
        raise ImportError_(f"source {source!r} produced no rows",
                           error_class="DELTA_IMPORT_EMPTY_SOURCE")
    return result


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="delta-tpu-import",
        description="Bulk-import CSV/Parquet/NDJSON/SQLite into a Delta table")
    ap.add_argument("--source", required=True,
                    help="file, glob, or directory to import")
    ap.add_argument("--destination", required=True, help="Delta table path")
    ap.add_argument("--format", dest="fmt",
                    choices=["csv", "parquet", "ndjson", "sqlite"])
    ap.add_argument("--partition-by", default=None,
                    help="comma-separated partition columns")
    ap.add_argument("--chunk-rows", type=int, default=DEFAULT_CHUNK_ROWS)
    ap.add_argument("--mode", choices=["append", "overwrite"], default="append")
    ap.add_argument("--query", default=None,
                    help="SELECT statement (sqlite sources)")
    args = ap.parse_args(argv)
    result = import_into_delta(
        source=args.source,
        destination=args.destination,
        fmt=args.fmt,
        partition_by=(args.partition_by.split(",") if args.partition_by else None),
        chunk_rows=args.chunk_rows,
        mode=args.mode,
        query=args.query,
    )
    print(result.to_dict())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Operational tools: bulk import (the `connectors/sql-delta-import`
equivalent) and the remote-protocol server/client live under
`delta_tpu.connect`."""

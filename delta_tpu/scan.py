"""Scan: pruned file listing (and data read) over a snapshot.

Mirrors kernel `ScanBuilder`/`Scan`/`ScanImpl.java:438`: a scan applies,
in order,
1. partition pruning — the filter conjuncts that touch only partition
   columns, evaluated against each file's `partitionValues`;
2. data skipping — remaining conjuncts translated into min/max-stats
   predicates over the stats index (delta_tpu.stats.skipping), evaluated
   on device for the TpuEngine;
3. (on read) deletion-vector row filtering and column mapping.

`add_files_table()` returns the surviving files columnar; `to_arrow()`
reads the actual data rows.
"""
# delta-lint: file-disable=shared-state-race — audited:
# ScanBuilder is a per-operation builder: created and consumed by the
# thread running the scan; instances are never shared across threads
# (matching the reference's ScanBuilder contract).

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu import obs
from delta_tpu.expressions.tree import Expression, split_conjuncts
from delta_tpu.models.actions import AddFile


class ScanBuilder:
    def __init__(self, snapshot):
        self._snapshot = snapshot
        self._filter: Optional[Expression] = None
        self._columns: Optional[List[str]] = None

    def with_filter(self, expr: Expression) -> "ScanBuilder":
        self._filter = expr if self._filter is None else (self._filter & expr)
        return self

    def with_columns(self, columns: Sequence[str]) -> "ScanBuilder":
        self._columns = list(columns)
        return self

    def build(self) -> "Scan":
        return Scan(self._snapshot, self._filter, self._columns)


class Scan:
    def __init__(self, snapshot, filter: Optional[Expression], columns: Optional[List[str]]):
        self._snapshot = snapshot
        self.filter = filter
        self.columns = columns
        self._result_cache: Optional[pa.Table] = None
        self.partition_pruned = 0
        self.skipped_by_stats = 0

    @property
    def snapshot(self):
        return self._snapshot

    def _partition_batch(self, files: pa.Table) -> pa.Table:
        """Reconstruct typed partition-column values from the
        partitionValues string map (protocol Partition Value Serialization)."""
        from delta_tpu.stats.partition import partition_values_to_columns

        return partition_values_to_columns(
            files.column("partition_values"),
            self._snapshot.metadata,
        )

    def add_files_table(self) -> pa.Table:
        """Surviving AddFiles (canonical columnar schema) after pruning."""
        if self._result_cache is not None:
            return self._result_cache
        with obs.span("scan.plan", table=self._snapshot.table_path,
                      version=self._snapshot.version) as sp:
            result = self._plan(sp)
            sp.set_attrs(surviving=result.num_rows,
                         partition_pruned=self.partition_pruned,
                         skipped_by_stats=self.skipped_by_stats)
            return result

    def _plan(self, sp) -> pa.Table:
        files = self._snapshot.state.add_files_table
        sp.set_attr("total_files", files.num_rows)
        if self.filter is None or files.num_rows == 0:
            self._result_cache = files
            return files

        partition_cols = set(self._snapshot.partition_columns)
        conjuncts = split_conjuncts(self.filter)
        part_conjuncts = [
            c for c in conjuncts
            if c.references() and all(r[0] in partition_cols for r in c.references())
        ]
        # identity, not `in`: Expression.__eq__ BUILDS a (truthy)
        # Comparison node, so `c not in part_conjuncts` was False for
        # every conjunct whenever any partition conjunct existed —
        # silently disabling stats skipping on partition-filtered scans
        part_ids = {id(c) for c in part_conjuncts}
        data_conjuncts = [c for c in conjuncts if id(c) not in part_ids]

        keep = np.ones(files.num_rows, dtype=bool)
        if part_conjuncts:
            batch = self._partition_batch(files)
            from delta_tpu.expressions.eval import evaluate_predicate_host

            for c in part_conjuncts:
                keep &= evaluate_predicate_host(c, batch)
            self.partition_pruned = int((~keep).sum())

        if data_conjuncts:
            from delta_tpu.stats.skipping import skipping_mask

            stats_keep = skipping_mask(
                files,
                data_conjuncts,
                self._snapshot.metadata,
                engine=self._snapshot._engine,
                state=self._snapshot.state,
            )
            self.skipped_by_stats = int((keep & ~stats_keep).sum())
            keep &= stats_keep

        result = files.filter(pa.array(keep))
        self._result_cache = result
        self._report_metrics(files.num_rows, result.num_rows)
        return result

    def _report_metrics(self, total: int, surviving: int) -> None:
        eng = self._snapshot._engine
        if getattr(eng, "metrics_reporters", None):
            eng.report_metrics(
                {
                    "type": "ScanReport",
                    "tablePath": self._snapshot.table_path,
                    "tableVersion": self._snapshot.version,
                    "totalFiles": total,
                    "survivingFiles": surviving,
                    "partitionPruned": self.partition_pruned,
                    "skippedByStats": self.skipped_by_stats,
                    "filter": repr(self.filter) if self.filter else None,
                }
            )

    def files(self) -> List[AddFile]:
        from delta_tpu.replay.state import _row_to_add

        return [_row_to_add(r) for r in self.add_files_table().to_pylist()]

    def file_paths(self) -> List[str]:
        return self.add_files_table().column("path").to_pylist()

    def to_arrow(self) -> pa.Table:
        """Read the scanned data into one Arrow table (applies DV row
        filtering, partition-column injection, and residual filters)."""
        from delta_tpu.read.reader import read_scan

        return read_scan(self)

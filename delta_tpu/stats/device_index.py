"""Resident device stats index for scan planning.

Once per snapshot version, the parsed file-stats table
(`stats/skipping.py::StatsIndex`) is columnarized into a dense int64
lane matrix covering every *skipping-eligible* column — numeric,
timestamp, date, and bool leaves whose min/max stats parsed to a
comparable type — and cached on `SnapshotState` next to the
PR 7 resident replay state (`parallel/resident.py`):

  row 3c   : minValues  of eligible column c
  row 3c+1 : maxValues  of eligible column c
  row 3c+2 : nullCount  of eligible column c
  row -1   : numRecords

plus a validity bitplane (missing/unparseable stat -> invalid ->
"unknown" -> keep, preserving the host path's Kleene semantics). All
lanes are int64 in an order-preserving encoding (see `_enc_f64` for
the float total order; timestamps/dates become epoch microseconds), so
`ops/skipping.py` can evaluate a whole conjunct list against every
file in one type-agnostic dispatch on either backend, bit-identically.

Lifecycle mirrors `parallel/resident.py` discipline: built at most
once per `SnapshotState` under the state's dedicated
`_stats_index_lock` (NOT `_splice_lock` — building reads
`add_files_table`, which takes the splice lock itself), advanced by
`replay/state.py::advance_state` (carried over verbatim on empty
deltas, released and lazily rebuilt otherwise), and released on
serve-cache eviction through `release_snapshot_resident`. The device
upload is lazy (first device-routed scan) and budgeted in
`resources/transfer_budget.json` (`stats-index-lanes`): the lanes ship
ONCE per version and stay HBM-resident across scans, so the per-scan
device cost is one RTT plus the compiled atom arrays.
"""

from __future__ import annotations

import datetime
import threading
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu import obs
from delta_tpu.obs import hbm
from delta_tpu.expressions.tree import (
    Column,
    Comparison,
    Expression,
    In,
    IsNotNull,
    IsNull,
    Literal,
    Not,
    Or,
)
from delta_tpu.ops.skipping import AtomBlock

_BUILDS = obs.counter("scan.stats_index_builds")
_REUSES = obs.counter("scan.stats_index_reuses")
# device bytes are accounted in the resident ledger (obs/hbm.py),
# which derives the `scan.stats_index_hbm_bytes` gauge this module
# used to maintain by hand

_OP_CODES = {"<": 0, "<=": 1, ">": 2, ">=": 3, "=": 4, "!=": 5}
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}
_NEG = {"=": "!=", "!=": "=", "<": ">=", "<=": ">", ">": "<=", ">=": "<"}
_OP_ISNULL = 6
_OP_ISNOTNULL = 7

# an int cast to float64 is exact only within +/-2^53; literals outside
# that window fall back to the Arrow route rather than compare inexactly
_F64_EXACT_INT = 1 << 53

# In-lists longer than this compile to a pure range prefilter (two
# atoms) instead of one '=' atom per value
IN_LIST_ATOM_LIMIT = 64

_ARROW_ERRS = (pa.ArrowInvalid, pa.ArrowNotImplementedError,
               pa.ArrowTypeError)


def _enc_f64(a: np.ndarray) -> np.ndarray:
    """Order-preserving float64 -> int64 total-order encoding (sign-
    magnitude IEEE bits flipped into two's complement); -0.0 is
    canonicalized to +0.0 first so both compare equal to 0."""
    a = np.asarray(a, np.float64) + 0.0
    u = a.view(np.int64)
    return np.where(u >= 0, u, np.int64(np.iinfo(np.int64).min) ^ ~u)


def _lane_kind(t: pa.DataType) -> Optional[str]:
    """Encoding kind for a parsed stat leaf type; None = ineligible."""
    if pa.types.is_boolean(t):
        return "bool"
    if pa.types.is_integer(t):
        return "int"
    if pa.types.is_floating(t):
        return "float"
    if pa.types.is_timestamp(t):
        return None if t.tz is not None else "ts"
    if pa.types.is_date(t):
        return "ts"
    return None


def _resolve_kind(k_min: Optional[str], k_max: Optional[str]) -> Optional[str]:
    """Unify the min/max leaf kinds (pa_json infers each JSON field
    independently, so `min=1, max=1.5` parses as int64/double)."""
    if k_min is None or k_max is None:
        return None
    if k_min == k_max:
        return k_min
    if {k_min, k_max} == {"int", "float"}:
        return "float"
    return None


def _leaf_paths(t: pa.DataType, prefix: Tuple[str, ...] = ()) -> List[tuple]:
    out = []
    for f in t:
        p = prefix + (f.name,)
        if pa.types.is_struct(f.type):
            out.extend(_leaf_paths(f.type, p))
        else:
            out.append(p)
    return out


def _encode_lane(arr: pa.Array, kind: str):
    """(int64 values, validity) for one stat leaf under `kind`; invalid
    slots hold 0. None when the whole leaf can't be encoded."""
    try:
        valid = np.asarray(pc.is_valid(arr), dtype=bool)
        if kind == "bool":
            enc = np.asarray(pc.fill_null(arr.cast(pa.int64()), 0), np.int64)
        elif kind == "int":
            enc = np.asarray(pc.fill_null(arr.cast(pa.int64()), 0), np.int64)
        elif kind == "float":
            f = np.asarray(pc.fill_null(arr.cast(pa.float64()), 0.0),
                           np.float64)
            if pa.types.is_integer(arr.type):
                # int64 -> float64 is lossy past 2^53: such stats stay
                # "unknown" rather than compare inexactly
                raw = np.asarray(pc.fill_null(arr.cast(pa.int64()), 0),
                                 np.int64)
                valid &= np.abs(raw) <= _F64_EXACT_INT
            valid &= ~np.isnan(f)
            enc = _enc_f64(f)
        elif kind == "ts":
            ts = arr.cast(pa.timestamp("us"))
            enc = np.asarray(pc.fill_null(ts.cast(pa.int64()), 0), np.int64)
        else:
            return None
        return enc, valid
    except _ARROW_ERRS:
        return None


def encode_literal(value, kind: str) -> Optional[int]:
    """Encode a predicate literal into the lane's int64 order; None =
    not exactly representable -> the conjunct falls back to Arrow."""
    if value is None:
        return None
    if kind == "bool":
        return int(value) if isinstance(value, bool) else None
    if isinstance(value, bool):
        return None
    if kind == "int":
        if isinstance(value, (int, np.integer)):
            v = int(value)
            return v if -(1 << 63) <= v < (1 << 63) else None
        return None
    if kind == "float":
        if isinstance(value, (int, np.integer)):
            if abs(int(value)) > _F64_EXACT_INT:
                return None
            value = float(value)
        if isinstance(value, (float, np.floating)):
            f = np.float64(value)
            if np.isnan(f):
                return None
            return int(_enc_f64(np.asarray([f]))[0])
        return None
    if kind == "ts":
        if isinstance(value, datetime.datetime) and value.tzinfo is not None:
            return None
        if isinstance(value, (str, datetime.date, datetime.datetime)):
            try:
                s = pa.scalar(value).cast(pa.timestamp("us"))
            except _ARROW_ERRS:
                return None
            return s.value if s.is_valid else None
        return None
    return None


class ResidentStatsIndex:
    """Per-snapshot-version stats index: the parsed Arrow table (shared
    with the host fallback ladder) plus the encoded int64 lanes, with a
    lazily uploaded device copy."""

    def __init__(self, arrow_index, vals: Optional[np.ndarray],
                 valid: Optional[np.ndarray],
                 cols: Dict[tuple, Tuple[int, str]], n: int,
                 table_path: Optional[str] = None,
                 version: Optional[int] = None):
        self._lock = threading.Lock()
        self.arrow_index = arrow_index
        self.vals = vals          # int64 [R, n_pad] or None
        self.valid = valid        # bool  [R, n_pad] or None
        self.cols = cols          # {physical name_path: (min row, kind)}
        self.n = n
        self.table_path = table_path
        self.version = version
        self.released = False
        self._dev = None
        self._hbm = hbm.noop_handle()

    @property
    def has_lanes(self) -> bool:
        return self.vals is not None and not self.released

    def device_lanes(self):
        """(values, validity) device arrays, uploading on first use."""
        with self._lock:
            dev = self._upload_locked()
            if dev is not None:
                self._hbm.touch()
            return dev

    def _upload_locked(self):
        if self._dev is not None or self.vals is None or self.released:
            return self._dev
        import jax
        import jax.numpy as jnp

        from delta_tpu.ops.stats import _x64

        n_pad = self.vals.shape[1]
        lane_vals = np.asarray(self.vals, np.int64)
        valid_words = np.packbits(np.asarray(self.valid, bool), axis=1,
                                  bitorder="little")
        cells = lane_vals.shape[0] * n_pad
        with obs.device_dispatch("stats.index_upload",
                                 key=(lane_vals.shape[0], n_pad),
                                 budget="stats-index-lanes",
                                 units=cells) as dd, _x64():
            dd.h2d("lane_vals", lane_vals)
            dd.h2d("valid_words", valid_words)
            dv = jax.device_put(lane_vals)
            dw = jax.device_put(valid_words)
            dvalid = jnp.unpackbits(dw, axis=1, count=n_pad,
                                    bitorder="little").astype(bool)
        self._dev = (dv, dvalid)
        self._hbm = hbm.register(
            self, kind=hbm.KIND_STATS_INDEX, table_path=self.table_path,
            version=self.version, arrays=(dv, dvalid),
            rebuild_cost_class="cheap",  # lazy re-upload from host lanes
            evictor=self.evict_device,
        )
        return self._dev

    def evict_device(self) -> None:
        """Drop only the device copy (ledger shed under HBM pressure).
        The host lanes stay, so the next `device_lanes()` call lazily
        re-uploads — this is what makes the artifact cheap-to-rebuild
        rather than lost."""
        with self._lock:
            if self._dev is not None:
                self._dev = None
                self._hbm.release()
                self._hbm = hbm.noop_handle()

    def release(self) -> None:
        """Drop host lanes and the device copy (serve-cache eviction or
        version advancement). jax arrays are refcounted, so a scan
        concurrently holding the lanes finishes safely; the next scan
        of a still-live snapshot simply rebuilds."""
        with self._lock:
            if self._dev is not None:
                self._dev = None
                self._hbm.release()
                self._hbm = hbm.noop_handle()
            self.vals = None
            self.valid = None
            self.released = True


def build_index(files: pa.Table, table_path: Optional[str] = None,
                version: Optional[int] = None) -> ResidentStatsIndex:
    """Columnarize one snapshot version's parsed stats into lanes."""
    from delta_tpu.ops.replay import pad_bucket
    from delta_tpu.stats.skipping import StatsIndex

    arrow_index = StatsIndex.from_stats_column(files.column("stats"))
    n = arrow_index.n
    table = arrow_index._table
    if table is None:
        return ResidentStatsIndex(arrow_index, None, None, {}, n,
                                  table_path=table_path, version=version)

    names = table.column_names
    mins = table.column("minValues").combine_chunks() \
        if "minValues" in names else None
    maxs = table.column("maxValues").combine_chunks() \
        if "maxValues" in names else None
    if (mins is None or maxs is None
            or not pa.types.is_struct(mins.type)
            or not pa.types.is_struct(maxs.type)):
        return ResidentStatsIndex(arrow_index, None, None, {}, n,
                                  table_path=table_path, version=version)

    lanes: List[Tuple[np.ndarray, np.ndarray]] = []
    cols: Dict[tuple, Tuple[int, str]] = {}
    nr = arrow_index.num_records()
    for path in _leaf_paths(mins.type):
        mn = arrow_index.min_values(path)
        mx = arrow_index.max_values(path)
        if mn is None or mx is None:
            continue
        kind = _resolve_kind(_lane_kind(mn.type), _lane_kind(mx.type))
        if kind is None:
            continue
        enc_mn = _encode_lane(mn, kind)
        enc_mx = _encode_lane(mx, kind)
        if enc_mn is None or enc_mx is None:
            continue
        nc = arrow_index.null_count(path)
        enc_nc = _encode_lane(nc, "int") if nc is not None else None
        if enc_nc is None:
            enc_nc = (np.zeros(n, np.int64), np.zeros(n, bool))
        cols[path] = (len(lanes), kind)
        lanes.extend((enc_mn, enc_mx, enc_nc))
    if not cols:
        return ResidentStatsIndex(arrow_index, None, None, {}, n,
                                  table_path=table_path, version=version)

    enc_nr = _encode_lane(nr, "int") if nr is not None else None
    if enc_nr is None:
        enc_nr = (np.zeros(n, np.int64), np.zeros(n, bool))
    lanes.append(enc_nr)

    n_pad = pad_bucket(max(n, 1), min_bucket=128)
    vals = np.zeros((len(lanes), n_pad), np.int64)
    valid = np.zeros((len(lanes), n_pad), bool)
    for r, (ev, eva) in enumerate(lanes):
        vals[r, :n] = ev
        valid[r, :n] = eva
    return ResidentStatsIndex(arrow_index, vals, valid, cols, n,
                              table_path=table_path, version=version)


def _compile_conj(conj: Expression,
                  cols: Dict[tuple, Tuple[int, str]]):
    """Compile one conjunct to a list of OR-groups of atom triples
    (min_row, op_code, encoded literal); None = not compilable (the
    conjunct joins the Arrow fallback ladder)."""
    if isinstance(conj, Comparison):
        sides = (conj.left, conj.right)
        if isinstance(sides[0], Column) and isinstance(sides[1], Literal):
            colref, lit, op = sides[0], sides[1], conj.op
        elif isinstance(sides[1], Column) and isinstance(sides[0], Literal):
            colref, lit, op = sides[1], sides[0], _FLIP[conj.op]
        else:
            return None
        ent = cols.get(colref.name_path)
        if ent is None or op not in _OP_CODES:
            return None
        enc = encode_literal(lit.value, ent[1])
        if enc is None:
            return None
        return [[(ent[0], _OP_CODES[op], enc)]]
    if isinstance(conj, Or):
        left = _compile_conj(conj.left, cols)
        right = _compile_conj(conj.right, cols)
        if left is None or right is None or len(left) != 1 or len(right) != 1:
            # an AND nested under OR doesn't flatten into atom groups;
            # the host ladder keeps it (it returns None there too)
            return None
        return [left[0] + right[0]]
    if isinstance(conj, (IsNull, IsNotNull)):
        child = conj.child
        ent = cols.get(child.name_path) if isinstance(child, Column) else None
        if ent is None:
            return None
        code = _OP_ISNULL if isinstance(conj, IsNull) else _OP_ISNOTNULL
        return [[(ent[0], code, 0)]]
    if isinstance(conj, In):
        if not isinstance(conj.child, Column) or not conj.values:
            return None
        ent = cols.get(conj.child.name_path)
        if ent is None:
            return None
        encs = []
        for v in conj.values:
            e = encode_literal(v, ent[1])
            if e is None:
                return None
            encs.append(e)
        if len(encs) > IN_LIST_ATOM_LIMIT:
            # range prefilter only: col >= min(values) AND col <= max
            # (the encoding is order-preserving, so min/max over the
            # encoded ints bound the raw values)
            return [[(ent[0], _OP_CODES[">="], min(encs))],
                    [(ent[0], _OP_CODES["<="], max(encs))]]
        return [[(ent[0], _OP_CODES["="], e) for e in encs]]
    if isinstance(conj, Not):
        inner = conj.child
        if isinstance(inner, Comparison):
            return _compile_conj(
                Comparison(_NEG[inner.op], inner.left, inner.right), cols)
        if isinstance(inner, IsNull):
            return _compile_conj(IsNotNull(inner.child), cols)
        if isinstance(inner, IsNotNull):
            return _compile_conj(IsNull(inner.child), cols)
        return None
    return None


def compile_conjuncts(conjuncts: List[Expression],
                      index: ResidentStatsIndex):
    """Split a conjunct list into (AtomBlock, fallback conjuncts). The
    block covers every compilable conjunct in ONE dispatch; the rest
    go through the per-conjunct Arrow ladder on both routes, so the
    final mask is route-independent by construction."""
    if not index.has_lanes:
        return None, list(conjuncts)
    rows_mn: List[int] = []
    ops: List[int] = []
    lits: List[int] = []
    grp: List[int] = []
    fallback: List[Expression] = []
    n_groups = 0
    for conj in conjuncts:
        groups = _compile_conj(conj, index.cols)
        if groups is None:
            fallback.append(conj)
            continue
        for g in groups:
            for (row0, code, enc) in g:
                rows_mn.append(row0)
                ops.append(code)
                lits.append(enc)
                grp.append(n_groups)
            n_groups += 1
    if not rows_mn:
        return None, fallback
    rmn = np.asarray(rows_mn, np.int32)
    block = AtomBlock(
        rows_mn=rmn,
        rows_mx=rmn + 1,
        rows_nc=rmn + 2,
        ops=np.asarray(ops, np.int32),
        lits=np.asarray(lits, np.int64),
        grp=np.asarray(grp, np.int32),
        n_atoms=len(rows_mn),
        n_groups=n_groups,
    )
    return block, fallback


def snapshot_stats_index(state, files: pa.Table):
    """The state's resident index, building it on first use. Returns
    None when `state` can't host one or `files` isn't the state's own
    live-file table (e.g. the conflict checker's stats subsets)."""
    lock = getattr(state, "_stats_index_lock", None)
    if lock is None:
        return None
    try:
        if state.add_files_table is not files:
            return None
    except AttributeError:
        return None
    with lock:
        idx = state.stats_index
        if idx is not None and not idx.released:
            _REUSES.inc()
            return idx
        idx = build_index(files,
                          table_path=getattr(state, "table_path", None),
                          version=getattr(state, "version", None))
        state.stats_index = idx
        # built implicitly by ordinary filtered scans, so a state
        # dropped outside the explicit-release paths (one-shot reads,
        # version advance, serve eviction) must not read as a ledger
        # leak: the state's own GC releases the lanes (idempotent with
        # the explicit paths — same contract as the operand cache in
        # sqlengine/operands.py)
        weakref.finalize(state, ResidentStatsIndex.release, idx)
        _BUILDS.inc()
        return idx


def release_state_stats_index(state) -> None:
    """Release a state's resident index, if any (duck-typed like
    `parallel/resident.py::release_snapshot_resident`)."""
    idx = getattr(state, "stats_index", None)
    if idx is not None:
        idx.release()
        state.stats_index = None

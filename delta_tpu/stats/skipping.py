"""Data skipping: prune files whose min/max/nullCount stats prove a
predicate can't match (reference `stats/DataSkippingReader.scala:287`
constructDataFilters).

The stats index is columnar: the `stats` JSON strings of all surviving
AddFiles are parsed in ONE `pyarrow.json.read_json` call into struct
columns (`numRecords`, `minValues.*`, `maxValues.*`, `nullCount.*`).
When the caller supplies the snapshot's `SnapshotState`, the parsed
stats are further columnarized once per version into the resident
device lanes of `stats/device_index.py`, and every compilable conjunct
is evaluated in one batched dispatch (`ops/skipping.py`, jit kernel or
bit-identical numpy twin per `parallel/gate.py::skip_route`); anything
the compiler can't express — string and complex columns, inexact
literals — falls back to the per-conjunct Arrow ladder in this module.

Semantics: a file is SKIPPED only when stats *prove* no row can match.
Missing stats (null stats string, missing column, or unparseable value)
always keep the file. NULL handling: `col op lit` can only match non-null
rows, so files where nullCount == numRecords are skippable for such
conjuncts — but only when both counts are present.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.json as pa_json

from delta_tpu import obs
from delta_tpu.expressions.tree import (
    Column,
    Comparison,
    Expression,
    In,
    IsNotNull,
    IsNull,
    Literal,
    Not,
    Or,
)

_DEVICE_PLANS = obs.counter("scan.device_plans")
_DEVICE_FALLBACKS = obs.counter("scan.device_fallbacks")


class StatsIndex:
    """Parsed stats for a batch of files."""

    def __init__(self, table: Optional[pa.Table], n: int):
        self._table = table
        self.n = n

    @staticmethod
    def from_stats_column(stats_col: pa.ChunkedArray) -> "StatsIndex":
        n = len(stats_col)
        arr = stats_col.combine_chunks() if isinstance(stats_col, pa.ChunkedArray) else stats_col
        if n == 0 or arr.null_count == n:
            return StatsIndex(None, n)
        # one-shot parse: substitute "{}" for null rows to keep row alignment
        filled = pc.fill_null(arr, "{}")
        # pretty-printed stats embed raw newlines, which would desync the
        # one-row-per-line framing below (parsed.num_rows != n -> ALL
        # skipping silently disabled). Raw newlines are illegal inside a
        # JSON string value (they must be escaped as \n), so every literal
        # newline in a stats row is structural whitespace — flatten it.
        filled = pc.replace_substring(filled, pattern="\r", replacement=" ")
        filled = pc.replace_substring(filled, pattern="\n", replacement=" ")
        joined = ("\n".join(filled.to_pylist()) + "\n").encode()
        try:
            parsed = pa_json.read_json(pa.BufferReader(joined))
        except pa.ArrowInvalid:
            # A non-finite float stat serializes as the string "NaN" /
            # "Infinity" / "-Infinity" (see collection.py); ONE such
            # file makes Arrow's JSON inference see a string/number mix
            # and refuse the column — which used to disable skipping
            # for the whole table. Nulling those tokens loses only
            # precision (a null stat means unknown -> keep), never
            # correctness: a raw `:"NaN"` byte sequence cannot occur
            # inside a JSON string value (its quote would be escaped),
            # so only whole stat values can match.
            for tok in ('"NaN"', '"Infinity"', '"-Infinity"'):
                filled = pc.replace_substring_regex(
                    filled, pattern=r":\s*" + tok, replacement=":null")
            joined = ("\n".join(filled.to_pylist()) + "\n").encode()
            try:
                parsed = pa_json.read_json(pa.BufferReader(joined))
            except pa.ArrowInvalid:
                return StatsIndex(None, n)
        if parsed.num_rows != n:
            return StatsIndex(None, n)
        return StatsIndex(parsed, n)

    def _leaf(self, group: str, name_path: tuple) -> Optional[np.ndarray]:
        """Return (values, valid) for e.g. group='minValues', col path.
        None when the column isn't in the index."""
        if self._table is None or group not in self._table.column_names:
            return None
        arr = self._table.column(group).combine_chunks()
        if not pa.types.is_struct(arr.type):
            return None
        for part in name_path:
            if not pa.types.is_struct(arr.type) or arr.type.get_field_index(part) < 0:
                return None
            arr = pc.struct_field(arr, part)
        return arr

    def num_records(self):
        if self._table is None or "numRecords" not in self._table.column_names:
            return None
        return self._table.column("numRecords").combine_chunks()

    def min_values(self, name_path):
        return self._leaf("minValues", name_path)

    def max_values(self, name_path):
        return self._leaf("maxValues", name_path)

    def null_count(self, name_path):
        return self._leaf("nullCount", name_path)


def _max_truncated(maxv) -> Optional[pa.Array]:
    """Per-file "this string max MAY be truncated" mask. The collector
    caps string maxValues at MAX_STRING_PREFIX_LENGTH with an upward
    tie-break (stats/collection.py), and foreign writers do the same,
    so any stored max AT the cap may differ from the true column max —
    comparisons that rely on the max being exact must keep such files."""
    if maxv is None or not (pa.types.is_string(maxv.type)
                            or pa.types.is_large_string(maxv.type)):
        return None
    from delta_tpu.stats.collection import MAX_STRING_PREFIX_LENGTH

    return pc.greater_equal(pc.utf8_length(maxv),
                            pa.scalar(MAX_STRING_PREFIX_LENGTH))


def _cmp_keep(op: str, minv, maxv, lit_arr) -> Optional[pa.Array]:
    """Keep-condition (nullable bool Arrow array) for `col op lit` given
    min/max arrays; None = cannot decide (keep).

    String maxValues get prefix-aware semantics: a truncated max is only
    a lower bound on the true max (tie-broken upward), so `maxv >= lit`
    may be false while rows above `lit` exist — every max-dependent
    verdict is widened to keep possibly-truncated files. minValues need
    no guard: a truncated min prefix sorts <= the true min, so min-side
    comparisons are already conservative."""
    try:
        trunc = _max_truncated(maxv)
        if op == "=":
            if minv is None or maxv is None:
                return None
            hi = pc.greater_equal(maxv, lit_arr)
            if trunc is not None:
                hi = pc.or_kleene(hi, trunc)
            return pc.and_kleene(pc.less_equal(minv, lit_arr), hi)
        if op == "<":
            return None if minv is None else pc.less(minv, lit_arr)
        if op == "<=":
            return None if minv is None else pc.less_equal(minv, lit_arr)
        if op == ">":
            if maxv is None:
                return None
            keep = pc.greater(maxv, lit_arr)
            return keep if trunc is None else pc.or_kleene(keep, trunc)
        if op == ">=":
            if maxv is None:
                return None
            keep = pc.greater_equal(maxv, lit_arr)
            return keep if trunc is None else pc.or_kleene(keep, trunc)
        if op == "!=":
            if minv is None or maxv is None:
                return None
            # skip only when min == max == lit (every row equals lit) —
            # and the max is exact, not a truncation-bumped prefix
            all_eq = pc.and_kleene(pc.equal(minv, lit_arr),
                                   pc.equal(maxv, lit_arr))
            if trunc is not None:
                all_eq = pc.and_kleene(all_eq, pc.invert(trunc))
            return pc.invert(all_eq)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError):
        return None
    return None


def _conjunct_keep(conj: Expression, index: StatsIndex) -> Optional[pa.Array]:
    """Nullable keep-mask for one conjunct; None/null = keep."""
    if isinstance(conj, Or):
        left = _conjunct_keep(conj.left, index)
        right = _conjunct_keep(conj.right, index)
        if left is None or right is None:
            return None
        return pc.or_kleene(left, right)
    if isinstance(conj, Comparison):
        sides = (conj.left, conj.right)
        if isinstance(sides[0], Column) and isinstance(sides[1], Literal):
            colref, lit, op = sides[0], sides[1], conj.op
        elif isinstance(sides[1], Column) and isinstance(sides[0], Literal):
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}
            colref, lit, op = sides[1], sides[0], flip[conj.op]
        else:
            return None
        if lit.value is None:
            return None
        minv = index.min_values(colref.name_path)
        maxv = index.max_values(colref.name_path)
        try:
            lit_arr = pa.scalar(lit.value)
        except pa.ArrowInvalid:
            return None
        keep = _cmp_keep(op, minv, maxv, lit_arr)
        # additionally: an all-null column can't match col op lit
        nc = index.null_count(colref.name_path)
        nr = index.num_records()
        if nc is not None and nr is not None:
            try:
                not_all_null = pc.less(nc, nr)
                keep = not_all_null if keep is None else pc.and_kleene(keep, not_all_null)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError):
                pass
        return keep
    if isinstance(conj, IsNull):
        child = conj.child
        if isinstance(child, Column):
            nc = index.null_count(child.name_path)
            if nc is None:
                return None
            try:
                return pc.greater(nc, pa.scalar(0))
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                return None
        return None
    if isinstance(conj, IsNotNull):
        child = conj.child
        if isinstance(child, Column):
            nc = index.null_count(child.name_path)
            nr = index.num_records()
            if nc is None or nr is None:
                return None
            try:
                return pc.less(nc, nr)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                return None
        return None
    if isinstance(conj, In):
        if isinstance(conj.child, Column) and conj.values:
            if any(v is None for v in conj.values):
                return None
            # range prefilter: one pass with min(values)/max(values)
            # bounds instead of len(values) passes — any file outside
            # [min, max] can't contain any listed value
            pre = None
            try:
                lo, hi = min(conj.values), max(conj.values)
            except TypeError:  # mixed uncomparable values
                lo = hi = None
            if lo is not None:
                k_lo = _conjunct_keep(
                    Comparison(">=", conj.child, Literal(lo)), index)
                k_hi = _conjunct_keep(
                    Comparison("<=", conj.child, Literal(hi)), index)
                if k_lo is not None and k_hi is not None:
                    pre = pc.and_kleene(k_lo, k_hi)
                elif k_lo is not None or k_hi is not None:
                    pre = k_lo if k_lo is not None else k_hi
            if pre is not None:
                # large lists: the range bound IS the verdict (still
                # conservative — a superset of the exact per-value OR)
                if len(conj.values) > 64:
                    return pre
                if not pc.any(pc.fill_null(pre, True)).as_py():
                    return pre  # nothing survives the range — done
            keeps = None
            for v in conj.values:
                k = _conjunct_keep(Comparison("=", conj.child, Literal(v)), index)
                if k is None:
                    return pre
                keeps = k if keeps is None else pc.or_kleene(keeps, k)
            if keeps is not None and pre is not None:
                keeps = pc.and_kleene(keeps, pre)
            return keeps
        return None
    if isinstance(conj, Not):
        inner = conj.child
        if isinstance(inner, Comparison):
            neg = {"=": "!=", "!=": "=", "<": ">=", "<=": ">", ">": "<=", ">=": "<"}
            return _conjunct_keep(
                Comparison(neg[inner.op], inner.left, inner.right), index
            )
        if isinstance(inner, IsNull):
            return _conjunct_keep(IsNotNull(inner.child), index)
        if isinstance(inner, IsNotNull):
            return _conjunct_keep(IsNull(inner.child), index)
        return None
    return None


def _to_physical(expr: Expression, schema) -> Optional[Expression]:
    """Rewrite logical column paths to physical names (stats JSON keys use
    physical names under column mapping). None = untranslatable -> keep."""
    from delta_tpu.columnmapping import physical_name_path

    if isinstance(expr, Column):
        phys = physical_name_path(schema, expr.name_path)
        return Column(phys) if phys is not None else None
    children = expr.children()
    if not children:
        return expr
    import dataclasses

    new_children = []
    for c in children:
        nc = _to_physical(c, schema)
        if nc is None:
            return None
        new_children.append(nc)
    field_names = [
        f.name for f in dataclasses.fields(expr)
        if isinstance(getattr(expr, f.name), Expression)
    ]
    replacements = dict(zip(field_names, new_children))
    return dataclasses.replace(expr, **replacements)


def skipping_mask(
    files: pa.Table,
    conjuncts: List[Expression],
    metadata,
    engine=None,
    state=None,
) -> np.ndarray:
    """Boolean keep-mask over `files` rows. True = must read the file.

    With `state` (the snapshot's `SnapshotState`), skipping plans
    through the resident stats index (`stats/device_index.py`): every
    compilable conjunct is evaluated in ONE batched dispatch over the
    encoded int64 lanes — jit kernel or its bit-identical numpy twin,
    chosen by `parallel/gate.py::skip_route` — and only the remainder
    (string/complex/missing-stats columns, inexact literals) walks the
    per-conjunct Arrow ladder below. Both routes AND into the same
    mask, so the result is route-independent by construction."""
    n = files.num_rows
    keep = np.ones(n, dtype=bool)
    if n == 0 or not conjuncts:
        return keep
    rs = None
    if state is not None:
        from delta_tpu.stats.device_index import snapshot_stats_index

        rs = snapshot_stats_index(state, files)
    index = rs.arrow_index if rs is not None \
        else StatsIndex.from_stats_column(files.column("stats"))
    if index._table is None:
        return keep
    if (
        metadata is not None
        and metadata.configuration.get("delta.columnMapping.mode", "none") != "none"
    ):
        schema = metadata.schema
        translated = []
        for conj in conjuncts:
            t = _to_physical(conj, schema)
            if t is not None:
                translated.append(t)
        conjuncts = translated
    fallback = conjuncts
    if rs is not None and rs.has_lanes:
        from delta_tpu.ops import skipping as ops_skipping
        from delta_tpu.parallel.gate import skip_route
        from delta_tpu.stats.device_index import compile_conjuncts

        block, fallback = compile_conjuncts(conjuncts, rs)
        if block is not None:
            route = skip_route(
                n, block.n_atoms,
                engine_enabled=bool(getattr(engine, "use_device_skip", False)),
            )
            if route == "device":
                from delta_tpu.parallel import gate as gate_mod
                from delta_tpu.resilience import device_faults
                try:
                    lanes = device_faults.shed_retry(
                        "skip", rs.device_lanes)
                    if lanes is None:
                        obs.gate_fell_back("skip", "host",
                                           reason="no-resident-lanes")
                        route = "host"
                    else:
                        keep &= device_faults.shed_retry(
                            "skip",
                            lambda: ops_skipping.skip_mask_block(
                                lanes[0], lanes[1], block, n))
                        gate_mod.route_ok("skip")
                        _DEVICE_PLANS.inc()
                        if fallback:
                            _DEVICE_FALLBACKS.inc(len(fallback))
                except Exception as e:
                    # disciplined fallback: classify (feeds the route
                    # breaker), bump the cataloged counter, host twin
                    if not device_faults.absorb_route_failure("skip", e):
                        raise
                    _DEVICE_FALLBACKS.inc()
                    obs.gate_fell_back(
                        "skip", "host",
                        reason=f"device-error:{type(e).__name__}")
                    route = "host"
            if route == "host":
                with obs.gate_observation("skip", "host"):
                    keep &= ops_skipping.host_skip_mask(
                        rs.vals, rs.valid, block, n)
            obs.set_attrs(skip_route=route, skip_atoms=block.n_atoms,
                          skip_fallback_conjuncts=len(fallback))
    for conj in fallback:
        mask = _conjunct_keep(conj, index)
        if mask is None:
            continue
        # null (missing stats for that file) -> keep
        filled = pc.fill_null(mask, True)
        keep &= np.asarray(filled, dtype=bool)
    return keep

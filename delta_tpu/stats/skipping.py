"""Data skipping: prune files whose min/max/nullCount stats prove a
predicate can't match (reference `stats/DataSkippingReader.scala:287`
constructDataFilters).

The stats index is columnar: the `stats` JSON strings of all surviving
AddFiles are parsed in ONE `pyarrow.json.read_json` call into struct
columns (`numRecords`, `minValues.*`, `maxValues.*`, `nullCount.*`), then
per-conjunct keep-masks are evaluated vectorized — numpy on the host
engine, jit'd on device for the TpuEngine (delta_tpu.ops.stats).

Semantics: a file is SKIPPED only when stats *prove* no row can match.
Missing stats (null stats string, missing column, or unparseable value)
always keep the file. NULL handling: `col op lit` can only match non-null
rows, so files where nullCount == numRecords are skippable for such
conjuncts — but only when both counts are present.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.json as pa_json

from delta_tpu.expressions.tree import (
    Column,
    Comparison,
    Expression,
    In,
    IsNotNull,
    IsNull,
    Literal,
    Not,
    Or,
)


class StatsIndex:
    """Parsed stats for a batch of files."""

    def __init__(self, table: Optional[pa.Table], n: int):
        self._table = table
        self.n = n

    @staticmethod
    def from_stats_column(stats_col: pa.ChunkedArray) -> "StatsIndex":
        n = len(stats_col)
        arr = stats_col.combine_chunks() if isinstance(stats_col, pa.ChunkedArray) else stats_col
        if n == 0 or arr.null_count == n:
            return StatsIndex(None, n)
        # one-shot parse: substitute "{}" for null rows to keep row alignment
        filled = pc.fill_null(arr, "{}")
        joined = ("\n".join(filled.to_pylist()) + "\n").encode()
        try:
            parsed = pa_json.read_json(pa.BufferReader(joined))
        except pa.ArrowInvalid:
            return StatsIndex(None, n)
        if parsed.num_rows != n:
            return StatsIndex(None, n)
        return StatsIndex(parsed, n)

    def _leaf(self, group: str, name_path: tuple) -> Optional[np.ndarray]:
        """Return (values, valid) for e.g. group='minValues', col path.
        None when the column isn't in the index."""
        if self._table is None or group not in self._table.column_names:
            return None
        arr = self._table.column(group).combine_chunks()
        if not pa.types.is_struct(arr.type):
            return None
        for part in name_path:
            if not pa.types.is_struct(arr.type) or arr.type.get_field_index(part) < 0:
                return None
            arr = pc.struct_field(arr, part)
        return arr

    def num_records(self):
        if self._table is None or "numRecords" not in self._table.column_names:
            return None
        return self._table.column("numRecords").combine_chunks()

    def min_values(self, name_path):
        return self._leaf("minValues", name_path)

    def max_values(self, name_path):
        return self._leaf("maxValues", name_path)

    def null_count(self, name_path):
        return self._leaf("nullCount", name_path)


def _cmp_keep(op: str, minv, maxv, lit_arr) -> Optional[pa.Array]:
    """Keep-condition (nullable bool Arrow array) for `col op lit` given
    min/max arrays; None = cannot decide (keep)."""
    try:
        if op == "=":
            if minv is None or maxv is None:
                return None
            return pc.and_kleene(pc.less_equal(minv, lit_arr), pc.greater_equal(maxv, lit_arr))
        if op == "<":
            return None if minv is None else pc.less(minv, lit_arr)
        if op == "<=":
            return None if minv is None else pc.less_equal(minv, lit_arr)
        if op == ">":
            return None if maxv is None else pc.greater(maxv, lit_arr)
        if op == ">=":
            return None if maxv is None else pc.greater_equal(maxv, lit_arr)
        if op == "!=":
            if minv is None or maxv is None:
                return None
            # skip only when min == max == lit (every row equals lit)
            return pc.invert(
                pc.and_kleene(pc.equal(minv, lit_arr), pc.equal(maxv, lit_arr))
            )
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError):
        return None
    return None


def _conjunct_keep(conj: Expression, index: StatsIndex) -> Optional[pa.Array]:
    """Nullable keep-mask for one conjunct; None/null = keep."""
    if isinstance(conj, Or):
        left = _conjunct_keep(conj.left, index)
        right = _conjunct_keep(conj.right, index)
        if left is None or right is None:
            return None
        return pc.or_kleene(left, right)
    if isinstance(conj, Comparison):
        sides = (conj.left, conj.right)
        if isinstance(sides[0], Column) and isinstance(sides[1], Literal):
            colref, lit, op = sides[0], sides[1], conj.op
        elif isinstance(sides[1], Column) and isinstance(sides[0], Literal):
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}
            colref, lit, op = sides[1], sides[0], flip[conj.op]
        else:
            return None
        if lit.value is None:
            return None
        minv = index.min_values(colref.name_path)
        maxv = index.max_values(colref.name_path)
        try:
            lit_arr = pa.scalar(lit.value)
        except pa.ArrowInvalid:
            return None
        keep = _cmp_keep(op, minv, maxv, lit_arr)
        # additionally: an all-null column can't match col op lit
        nc = index.null_count(colref.name_path)
        nr = index.num_records()
        if nc is not None and nr is not None:
            try:
                not_all_null = pc.less(nc, nr)
                keep = not_all_null if keep is None else pc.and_kleene(keep, not_all_null)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError):
                pass
        return keep
    if isinstance(conj, IsNull):
        child = conj.child
        if isinstance(child, Column):
            nc = index.null_count(child.name_path)
            if nc is None:
                return None
            try:
                return pc.greater(nc, pa.scalar(0))
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                return None
        return None
    if isinstance(conj, IsNotNull):
        child = conj.child
        if isinstance(child, Column):
            nc = index.null_count(child.name_path)
            nr = index.num_records()
            if nc is None or nr is None:
                return None
            try:
                return pc.less(nc, nr)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                return None
        return None
    if isinstance(conj, In):
        if isinstance(conj.child, Column) and conj.values:
            keeps = None
            for v in conj.values:
                k = _conjunct_keep(Comparison("=", conj.child, Literal(v)), index)
                if k is None:
                    return None
                keeps = k if keeps is None else pc.or_kleene(keeps, k)
            return keeps
        return None
    if isinstance(conj, Not):
        inner = conj.child
        if isinstance(inner, Comparison):
            neg = {"=": "!=", "!=": "=", "<": ">=", "<=": ">", ">": "<=", ">=": "<"}
            return _conjunct_keep(
                Comparison(neg[inner.op], inner.left, inner.right), index
            )
        if isinstance(inner, IsNull):
            return _conjunct_keep(IsNotNull(inner.child), index)
        if isinstance(inner, IsNotNull):
            return _conjunct_keep(IsNull(inner.child), index)
        return None
    return None


def _to_physical(expr: Expression, schema) -> Optional[Expression]:
    """Rewrite logical column paths to physical names (stats JSON keys use
    physical names under column mapping). None = untranslatable -> keep."""
    from delta_tpu.columnmapping import physical_name_path

    if isinstance(expr, Column):
        phys = physical_name_path(schema, expr.name_path)
        return Column(phys) if phys is not None else None
    children = expr.children()
    if not children:
        return expr
    import dataclasses

    new_children = []
    for c in children:
        nc = _to_physical(c, schema)
        if nc is None:
            return None
        new_children.append(nc)
    field_names = [
        f.name for f in dataclasses.fields(expr)
        if isinstance(getattr(expr, f.name), Expression)
    ]
    replacements = dict(zip(field_names, new_children))
    return dataclasses.replace(expr, **replacements)


def skipping_mask(
    files: pa.Table,
    conjuncts: List[Expression],
    metadata,
    engine=None,
) -> np.ndarray:
    """Boolean keep-mask over `files` rows. True = must read the file."""
    n = files.num_rows
    keep = np.ones(n, dtype=bool)
    if n == 0 or not conjuncts:
        return keep
    index = StatsIndex.from_stats_column(files.column("stats"))
    if index._table is None:
        return keep
    if (
        metadata is not None
        and metadata.configuration.get("delta.columnMapping.mode", "none") != "none"
    ):
        schema = metadata.schema
        translated = []
        for conj in conjuncts:
            t = _to_physical(conj, schema)
            if t is not None:
                translated.append(t)
        conjuncts = translated
    for conj in conjuncts:
        mask = _conjunct_keep(conj, index)
        if mask is None:
            continue
        # null (missing stats for that file) -> keep
        filled = pc.fill_null(mask, True)
        keep &= np.asarray(filled, dtype=bool)
    return keep

"""Per-file statistics collection on write.

Reference `stats/StatisticsCollection.scala:257-356`: each written file's
AddFile carries a JSON `stats` document — `numRecords`, and
`minValues` / `maxValues` / `nullCount` per indexed leaf column (first
`delta.dataSkippingNumIndexedCols` = 32 leaves by default, or the explicit
`delta.dataSkippingStatsColumns` list).

Min/max are computed columnar (pyarrow C++ on host; numeric columns can
be reduced on-device in batch via delta_tpu.ops.stats when writing many
files in one call). String min/max are truncated to
`MAX_STRING_PREFIX_LENGTH` with the max tie-broken upward (appending
U+10FFFF would not round-trip JSON cleanly, so like the reference we
bump the last character — `StatisticsCollection.truncateMaxStringAgg`).
"""

from __future__ import annotations

import datetime as dt
import json
import math
from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.config import (
    DATA_SKIPPING_NUM_INDEXED_COLS,
    DATA_SKIPPING_STATS_COLUMNS,
    get_table_config,
)

MAX_STRING_PREFIX_LENGTH = 32


def _truncate_min(s: str) -> str:
    return s[:MAX_STRING_PREFIX_LENGTH]


def bump_string(s: str) -> Optional[str]:
    """Smallest convenient string > every string with prefix `s`:
    increment the last bumpable character. None when all characters are
    already U+10FFFF (unbumpable -> caller drops the max stat)."""
    for i in range(len(s) - 1, -1, -1):
        if ord(s[i]) < 0x10FFFF:
            return s[:i] + chr(ord(s[i]) + 1)
    return None


def _truncate_max(s: str) -> Optional[str]:
    if len(s) <= MAX_STRING_PREFIX_LENGTH:
        return s
    # bump the truncated prefix so it >= every string it covers
    return bump_string(s[:MAX_STRING_PREFIX_LENGTH])


def _json_value(v: Any) -> Any:
    if v is None:
        return None
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        return v
    if isinstance(v, dt.datetime):
        return v.strftime("%Y-%m-%dT%H:%M:%S.%f%z") or v.isoformat()
    if isinstance(v, dt.date):
        return v.isoformat()
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    try:
        import decimal

        if isinstance(v, decimal.Decimal):
            return float(v)
    except ImportError:
        pass
    return v


def _set_nested(d: dict, path: List[str], value) -> None:
    for p in path[:-1]:
        d = d.setdefault(p, {})
    d[path[-1]] = value


def stats_columns(schema, configuration: Dict[str, str], partition_columns: List[str]) -> List[List[str]]:
    """Leaf column name-paths to index, honoring the explicit list / first-N
    rule; partition columns are excluded (their values are in
    partitionValues)."""
    explicit = get_table_config(configuration, DATA_SKIPPING_STATS_COLUMNS)
    if explicit:
        return [c.split(".") for c in explicit]
    n = get_table_config(configuration, DATA_SKIPPING_NUM_INDEXED_COLS)
    leaves = [list(p) for p, _ in schema.leaves()]
    leaves = [p for p in leaves if p[0] not in set(partition_columns)]
    if n < 0:
        return leaves
    return leaves[:n]


def _leaf_array(table: pa.Table, path: List[str]) -> Optional[pa.ChunkedArray]:
    if path[0] not in table.column_names:
        return None
    arr = table.column(path[0])
    for p in path[1:]:
        try:
            arr = pc.struct_field(arr, p)
        except (pa.ArrowInvalid, KeyError):
            return None
    return arr


_MINMAX_TYPES = (
    pa.types.is_integer,
    pa.types.is_floating,
    pa.types.is_string,
    pa.types.is_date,
    pa.types.is_timestamp,
    pa.types.is_decimal,
)


def _supports_minmax(t: pa.DataType) -> bool:
    return any(check(t) for check in _MINMAX_TYPES)


def collect_stats(
    table: pa.Table,
    schema,
    configuration: Dict[str, str],
    partition_columns: List[str],
) -> str:
    """Stats JSON for one written file."""
    cols = stats_columns(schema, configuration, partition_columns)
    stats: dict = {"numRecords": table.num_rows}
    min_d: dict = {}
    max_d: dict = {}
    null_d: dict = {}
    for path in cols:
        arr = _leaf_array(table, path)
        if arr is None:
            continue
        null_count = arr.null_count
        _set_nested(null_d, path, int(null_count))
        if not _supports_minmax(arr.type) or arr.length() == null_count:
            continue
        is_float = pa.types.is_floating(arr.type)
        if is_float:
            # NaN must not poison min/max; delta treats NaN > any value
            no_nan = pc.drop_null(arr)
            nan_mask = pc.is_nan(no_nan)
            has_nan = pc.any(nan_mask).as_py()
            clean = no_nan.filter(pc.invert(nan_mask))
            if clean.length() == 0:
                _set_nested(min_d, path, "NaN")
                _set_nested(max_d, path, "NaN")
                continue
            mn = pc.min(clean).as_py()
            mx = pc.max(clean).as_py() if not has_nan else float("nan")
        else:
            mm = pc.min_max(arr)
            mn, mx = mm["min"].as_py(), mm["max"].as_py()
        if isinstance(mn, str):
            mn = _truncate_min(mn)
            mx_t = _truncate_max(mx)
            if mx_t is None:
                _set_nested(min_d, path, _json_value(mn))
                continue
            mx = mx_t
        _set_nested(min_d, path, _json_value(mn))
        _set_nested(max_d, path, _json_value(mx))
    if min_d:
        stats["minValues"] = min_d
        stats["maxValues"] = max_d
    stats["nullCount"] = null_d
    return json.dumps(stats, separators=(",", ":"))

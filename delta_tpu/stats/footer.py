"""Per-file stats from Parquet footers (no data scan).

CONVERT TO DELTA needs an AddFile stats document per existing file so
the converted table data-skips immediately (reference:
`commands/convert/ConvertUtils.scala` + ConvertToDeltaCommand's stats
collection). Re-reading every file's data would make conversion O(table
bytes); row-group footer statistics give min/max/nullCount in O(files).

Conservative by construction — a column's min/max is emitted only when
every row group carries trustworthy stats for it:
- floating columns are skipped entirely (Parquet min/max ordering around
  NaN is writer-dependent, and Delta's contract is NaN > everything);
- string stats honor Parquet's `is_max_value_exact` flag (a truncated
  footer max is NOT an upper bound of the column) and re-truncate to the
  Delta 32-char prefix rule;
- any conversion oddity (decimal/physical-type mismatch) drops that
  column's min/max, never the whole document.
Absent stats only cost skipping opportunities; they can never cause a
wrong prune.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from delta_tpu.stats.collection import (
    _json_value,
    _set_nested,
    _truncate_max,
    _truncate_min,
    bump_string,
    stats_columns,
)


def footer_stats(
    parquet_path: str,
    schema,
    configuration: Dict[str, str],
    partition_columns: List[str],
) -> Optional[str]:
    """Stats JSON for one existing Parquet file, from its footer only.
    Returns None when the footer is unreadable (caller converts the file
    without stats)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    try:
        md = pq.ParquetFile(parquet_path).metadata
    except (OSError, pa.ArrowException, ValueError):
        return None

    stats: dict = {"numRecords": md.num_rows}
    min_d: dict = {}
    max_d: dict = {}
    null_d: dict = {}

    # map dotted parquet leaf path -> column-chunk index
    col_index: Dict[str, int] = {}
    if md.num_row_groups:
        rg0 = md.row_group(0)
        for j in range(rg0.num_columns):
            col_index[rg0.column(j).path_in_schema] = j

    for path in stats_columns(schema, configuration, partition_columns):
        j = col_index.get(".".join(path))
        if j is None:
            continue
        nulls = 0
        mins: list = []
        maxs: list = []
        max_dropped = False
        usable = md.num_row_groups > 0
        for g in range(md.num_row_groups):
            col = md.row_group(g).column(j)
            st = col.statistics
            if st is None or st.null_count is None:
                usable = False
                break
            nulls += st.null_count
            if col.num_values - st.null_count == 0:
                continue  # all-null group contributes no min/max
            if not st.has_min_max:
                mins = maxs = None  # type: ignore[assignment]
                continue
            if mins is None:
                continue
            gmin, gmax = st.min, st.max
            if isinstance(gmin, bytes) or isinstance(gmax, bytes):
                # UTF-8 byte order == code-point order, so decoding before
                # aggregation preserves min/max
                try:
                    gmin = gmin.decode("utf-8") if isinstance(gmin, bytes) else gmin
                    gmax = gmax.decode("utf-8") if isinstance(gmax, bytes) else gmax
                except UnicodeDecodeError:
                    mins = maxs = None  # type: ignore[assignment]
                    continue
            mins.append(gmin)
            if getattr(st, "is_max_value_exact", True) is False:
                # this group's footer max is a truncated prefix of its real
                # max — a LOWER bound, not an upper bound. Bump it above
                # everything sharing the prefix BEFORE aggregating, so every
                # element of maxs is a true upper bound of its group (an
                # aggregated-then-bumped max can undershoot another group's
                # exact max that extends the same prefix).
                bumped = bump_string(gmax) if isinstance(gmax, str) else None
                if bumped is None:
                    max_dropped = True
                else:
                    maxs.append(bumped)
            else:
                maxs.append(gmax)
        if not usable:
            continue
        _set_nested(null_d, path, int(nulls))
        if not mins or mins is None:
            continue
        try:
            mn = min(mins)
            mx = max(maxs) if maxs and not max_dropped else None
        except TypeError:
            continue  # incomparable physical values — skip min/max
        if isinstance(mn, float) or isinstance(mx, float):
            continue  # NaN ordering is writer-dependent; never trust
        if isinstance(mn, str):
            mn = _truncate_min(mn)
            mx = _truncate_max(mx) if mx is not None else None
        _set_nested(min_d, path, _json_value(mn))
        if mx is not None:
            _set_nested(max_d, path, _json_value(mx))

    if min_d:
        stats["minValues"] = min_d
        stats["maxValues"] = max_d
    stats["nullCount"] = null_d
    return json.dumps(stats, separators=(",", ":"))

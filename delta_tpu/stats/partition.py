"""Partition-value handling.

Partition values are serialized as strings in `add.partitionValues`
(PROTOCOL.md Partition Value Serialization): `null` for NULL, ISO dates,
plain decimal numbers, etc. This module reconstructs typed columns from
the string map for partition pruning, and serializes values on write.
"""

from __future__ import annotations

import datetime as dt
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.models.schema import (
    PrimitiveType,
    StructType,
    to_arrow_type,
)


def serialize_partition_value(value) -> Optional[str]:
    """Python value → partition-value string (None stays None = null)."""
    if value is None:
        return None
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (dt.datetime,)):
        return value.strftime("%Y-%m-%d %H:%M:%S.%f")
    if isinstance(value, dt.date):
        return value.isoformat()
    if isinstance(value, bytes):
        return value.decode("utf-8", errors="replace")
    return str(value)


def deserialize_partition_value(s: Optional[str], dtype: PrimitiveType):
    if s is None:
        return None
    name = dtype.name
    if name == "string":
        return s
    if name in ("long", "integer", "short", "byte"):
        return int(s)
    if name in ("double", "float"):
        return float(s)
    if name == "boolean":
        return s.lower() == "true"
    if name == "date":
        return dt.date.fromisoformat(s)
    if name in ("timestamp", "timestamp_ntz"):
        try:
            return dt.datetime.fromisoformat(s)
        except ValueError:
            return dt.datetime.strptime(s, "%Y-%m-%d %H:%M:%S.%f")
    if dtype.is_decimal:
        import decimal

        return decimal.Decimal(s)
    return s


def _partition_field_types(metadata) -> Dict[str, tuple]:
    """logical name -> (map key in partitionValues, type). Under column
    mapping the map is keyed by physical names."""
    out: Dict[str, tuple] = {}
    schema = metadata.schema if metadata is not None else None
    mapped = (
        metadata is not None
        and metadata.configuration.get("delta.columnMapping.mode", "none") != "none"
    )
    for c in (metadata.partitionColumns if metadata else []):
        dtype = PrimitiveType("string")
        key = c
        if schema is not None and c in schema:
            f = schema[c]
            if isinstance(f.dataType, PrimitiveType):
                dtype = f.dataType
            if mapped:
                key = f.physical_name
        out[c] = (key, dtype)
    return out


def partition_values_to_columns(pv_column: pa.ChunkedArray, metadata) -> pa.Table:
    """Explode the partitionValues map column into typed columns named
    after the partition columns. Vectorized: map keys/items flattened once."""
    types = _partition_field_types(metadata)
    if not types:
        return pa.table({})
    arr = (
        pv_column.combine_chunks()
        if isinstance(pv_column, pa.ChunkedArray)
        else pv_column
    )
    n = len(arr)
    # Flatten map → per-row dict lookup via numpy. Maps are small (few
    # partition columns), so flatten + searchsorted-style grouping:
    offsets = np.asarray(arr.offsets)
    keys = np.asarray(arr.keys, dtype=object)
    items = np.asarray(arr.items, dtype=object)
    row_of_entry = np.repeat(np.arange(n), np.diff(offsets))

    cols = {}
    for name, (map_key, dtype) in types.items():
        values = np.full(n, None, dtype=object)
        sel = keys == map_key
        values[row_of_entry[sel]] = items[sel]
        py = [deserialize_partition_value(v, dtype) for v in values]
        try:
            cols[name] = pa.array(py, to_arrow_type(dtype))
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            cols[name] = pa.array([None if v is None else str(v) for v in values])
    return pa.table(cols)


def partition_values_to_batch(
    pv_dicts: Sequence[Dict[str, Optional[str]]], partition_columns: List[str]
) -> pa.Table:
    """Small-scale helper (conflict checking): list of string maps → typed-ish
    batch (strings; callers' literals compare as strings)."""
    cols = {}
    for c in partition_columns:
        cols[c] = pa.array([d.get(c) for d in pv_dicts], pa.string())
    return pa.table(cols) if cols else pa.table({})


def partition_path(partition_values: Dict[str, Optional[str]], partition_columns: List[str]) -> str:
    """Hive-style directory fragment `col1=v1/col2=v2/` (empty for
    unpartitioned). `__HIVE_DEFAULT_PARTITION__` encodes null."""
    from urllib.parse import quote

    parts = []
    for c in partition_columns:
        v = partition_values.get(c)
        ev = "__HIVE_DEFAULT_PARTITION__" if v is None else quote(v, safe="")
        parts.append(f"{c}={ev}")
    return "/".join(parts) + ("/" if parts else "")

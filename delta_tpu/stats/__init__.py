"""Stats: collection on write, columnar stats index, data skipping."""

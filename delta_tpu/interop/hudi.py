"""UniForm Hudi export.

Reference `hudi/HudiConversionTransaction.scala` (1.6k LoC): each Delta
commit converts into a timeline-correct Hudi COPY_ON_WRITE commit — the
instant moves through its real lifecycle (`<ts>.commit.requested` ->
`<ts>.inflight` -> `<ts>.commit`), the commit document carries
HoodieCommitMetadata (partitionToWriteStats incl. written/updated
records, previous commit linkage) and WRITE-level stats, and old
instants are archived into `.hoodie/archived/` past the active-timeline
cap — a real Hudi reader walks the same three-state timeline it would
find under a Hudi writer.

Incremental: each conversion covers the Delta commits since the last
converted version (tracked in extraMetadata), emitting per-partition
write stats for the files those commits added and marking replaced file
groups. A full snapshot conversion seeds the timeline.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

UNIFORM_FORMATS_KEY = "delta.universalFormat.enabledFormats"

ACTIVE_TIMELINE_CAP = 10   # archive completed instants beyond this many
# both commit actions, all three lifecycle states
_STATE_SUFFIXES = (".commit", ".commit.requested", ".commit.inflight",
                   ".replacecommit", ".replacecommit.requested",
                   ".replacecommit.inflight", ".inflight")


def _timeline_instants(hoodie: str) -> List[tuple]:
    """Completed instants as (instant_ts, action), ascending. Removals
    complete as `replacecommit` (the only action whose replaced file
    groups Hudi readers honor); pure appends as `commit`."""
    try:
        names = os.listdir(hoodie)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        for action in ("commit", "replacecommit"):
            if n.endswith(f".{action}"):
                out.append((n[:-(len(action) + 1)], action))
                break
    return sorted(out)


def _last_converted_delta_version(hoodie: str) -> Optional[int]:
    for instant, action in reversed(_timeline_instants(hoodie)):
        try:
            with open(os.path.join(hoodie, f"{instant}.{action}")) as f:
                doc = json.load(f)
            v = doc.get("extraMetadata", {}).get("delta.version")
            if v is not None:
                return int(v)
        except (ValueError, OSError):
            continue
    return None


def _write_properties(hoodie: str, meta, table_path: str) -> None:
    props_path = os.path.join(hoodie, "hoodie.properties")
    if os.path.exists(props_path):
        return
    props = {
        "hoodie.table.name": meta.name or os.path.basename(table_path),
        "hoodie.table.type": "COPY_ON_WRITE",
        "hoodie.table.version": "6",
        "hoodie.timeline.layout.version": "1",
        "hoodie.table.base.file.format": "PARQUET",
        "hoodie.table.partition.fields": ",".join(meta.partitionColumns),
        "hoodie.datasource.write.hive_style_partitioning": "true",
        "hoodie.table.checksum": "0",
        "hoodie.populate.meta.fields": "false",
    }
    with open(props_path, "w") as f:
        f.write("#Updated at " + time.strftime("%c") + "\n")
        for k, v in props.items():
            f.write(f"{k}={v}\n")


def _partition_of(pv) -> str:
    pv_dict = {k: v for k, v in pv} if isinstance(pv, list) else (pv or {})
    return "/".join(f"{k}={v}" for k, v in sorted(pv_dict.items())) or ""


def _write_stat(path: str, size, stats, prev_commit: str) -> Dict:
    nrec = 0
    if stats:
        try:
            nrec = int(json.loads(stats).get("numRecords") or 0)
        except ValueError:
            pass
    return {
        "fileId": os.path.basename(path).rsplit(".", 1)[0],
        "path": path,
        "prevCommit": prev_commit,
        "numWrites": nrec,
        "numInserts": nrec,
        "numUpdateWrites": 0,
        "numDeletes": 0,
        "totalWriteBytes": int(size or 0),
        "fileSizeInBytes": int(size or 0),
    }


def _archive_old_instants(hoodie: str) -> None:
    """Move completed instants beyond the active cap into
    `.hoodie/archived/` (the reference's timeline archival)."""
    instants = _timeline_instants(hoodie)
    if len(instants) <= ACTIVE_TIMELINE_CAP:
        return
    archived_dir = os.path.join(hoodie, "archived")
    os.makedirs(archived_dir, exist_ok=True)
    for instant, _action in instants[:-ACTIVE_TIMELINE_CAP]:
        for suffix in _STATE_SUFFIXES:
            src = os.path.join(hoodie, f"{instant}{suffix}")
            if os.path.exists(src):
                os.replace(src, os.path.join(archived_dir,
                                             f"{instant}{suffix}"))


def convert_snapshot(snapshot, table_path: Optional[str] = None) -> str:
    """Convert `snapshot` into the next Hudi timeline instant; returns
    the completed `.commit` path."""
    table_path = table_path or snapshot.table_path
    hoodie = os.path.join(table_path, ".hoodie")
    os.makedirs(hoodie, exist_ok=True)
    meta = snapshot.metadata
    _write_properties(hoodie, meta, table_path)

    prev_instants = _timeline_instants(hoodie)
    prev_commit = prev_instants[-1][0] if prev_instants else "null"
    prev_delta_v = _last_converted_delta_version(hoodie)
    if prev_delta_v is not None and prev_delta_v >= snapshot.version:
        last_ts, last_action = prev_instants[-1]
        return os.path.join(hoodie, f"{last_ts}.{last_action}")

    # instants must be strictly increasing even within one wall-second
    instant = time.strftime("%Y%m%d%H%M%S") + f"{snapshot.version % 1000:03d}"
    if prev_instants and instant <= prev_instants[-1][0]:
        instant = f"{int(prev_instants[-1][0]) + 1:017d}"

    # --- gather write stats (incremental when the range is available) ---
    incremental = None
    if prev_delta_v is not None and prev_delta_v < snapshot.version:
        from delta_tpu.interop.commitrange import delta_range_actions

        rng = delta_range_actions(
            table_path, prev_delta_v + 1, snapshot.version)
        if rng is not None:
            incremental = (rng[0], rng[3])

    partition_stats: Dict[str, List[Dict]] = {}
    replaced: Dict[str, List[str]] = {}
    if incremental is not None:
        adds, removed = incremental
        for p, a in adds.items():
            partition = _partition_of(a.get("partitionValues"))
            partition_stats.setdefault(partition, []).append(
                _write_stat(p, a.get("size"), a.get("stats"), prev_commit))
        for p in sorted(removed):
            # replaced file groups are looked up PER PARTITION by Hudi
            # readers — key by the remove action's partition values
            partition = _partition_of(removed[p].get("partitionValues"))
            replaced.setdefault(partition, []).append(
                os.path.basename(p).rsplit(".", 1)[0])
        op = "UPSERT" if removed else "INSERT"
        action = "replacecommit" if removed else "commit"
    else:
        files = snapshot.state.add_files_table
        for p, size, pv, st in zip(
                files.column("path").to_pylist(),
                files.column("size").to_pylist(),
                files.column("partition_values").to_pylist(),
                files.column("stats").to_pylist()):
            partition = _partition_of(pv)
            partition_stats.setdefault(partition, []).append(
                _write_stat(p, size, st, prev_commit))
        op = "BULK_INSERT"
        action = "commit"

    # --- lifecycle: REQUESTED -> INFLIGHT (with the real planned op)
    # -> COMPLETED. Removals use the `replacecommit` action: Hudi readers
    # only honor replaced file groups declared by replacecommits.
    with open(os.path.join(hoodie, f"{instant}.{action}.requested"),
              "w") as f:
        f.write("")
    with open(os.path.join(hoodie, f"{instant}.{action}.inflight"),
              "w") as f:
        json.dump({"operationType": op}, f)

    commit_doc = {
        "partitionToWriteStats": partition_stats,
        "partitionToReplaceFileIds": replaced,
        "compacted": False,
        "extraMetadata": {
            "delta.version": str(snapshot.version),
            "schema": meta.schemaString,
        },
        "operationType": op,
    }

    commit_path = os.path.join(hoodie, f"{instant}.{action}")
    with open(commit_path, "w") as f:
        json.dump(commit_doc, f, indent=2)

    _archive_old_instants(hoodie)
    return commit_path


def hudi_converter_hook(table, txn, version: int, metadata) -> None:
    if "hudi" not in metadata.configuration.get(UNIFORM_FORMATS_KEY, ""):
        return
    convert_snapshot(table.snapshot_at(version))

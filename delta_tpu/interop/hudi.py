"""UniForm Hudi export (reference `hudi/` module + HudiConverterHook).

Writes the Hudi copy-on-write table skeleton: `.hoodie/hoodie.properties`
and a commit timeline where each converted Delta snapshot becomes a
`<ts>.commit` JSON document listing the live files (Hudi's
HoodieCommitMetadata shape: partitionToWriteStats)."""

from __future__ import annotations

import json
import os
import time
from typing import Optional

UNIFORM_FORMATS_KEY = "delta.universalFormat.enabledFormats"


def convert_snapshot(snapshot, table_path: Optional[str] = None) -> str:
    table_path = table_path or snapshot.table_path
    hoodie = os.path.join(table_path, ".hoodie")
    os.makedirs(hoodie, exist_ok=True)
    props_path = os.path.join(hoodie, "hoodie.properties")
    meta = snapshot.metadata
    if not os.path.exists(props_path):
        props = {
            "hoodie.table.name": meta.name or os.path.basename(table_path),
            "hoodie.table.type": "COPY_ON_WRITE",
            "hoodie.table.version": "6",
            "hoodie.timeline.layout.version": "1",
            "hoodie.table.base.file.format": "PARQUET",
            "hoodie.table.partition.fields": ",".join(meta.partitionColumns),
            "hoodie.table.checksum": "0",
        }
        with open(props_path, "w") as f:
            f.write("#Updated at " + time.strftime("%c") + "\n")
            for k, v in props.items():
                f.write(f"{k}={v}\n")

    instant = time.strftime("%Y%m%d%H%M%S") + f"{snapshot.version:03d}"
    files = snapshot.state.add_files_table
    partition_stats: dict = {}
    for p, size, pv in zip(
        files.column("path").to_pylist(),
        files.column("size").to_pylist(),
        files.column("partition_values").to_pylist(),
    ):
        pv_dict = {k: v for k, v in pv} if isinstance(pv, list) else (pv or {})
        partition = "/".join(
            f"{k}={v}" for k, v in sorted(pv_dict.items())
        ) or ""
        partition_stats.setdefault(partition, []).append(
            {"path": p, "fileSizeInBytes": int(size or 0)}
        )
    commit_doc = {
        "partitionToWriteStats": partition_stats,
        "compacted": False,
        "extraMetadata": {"delta.version": str(snapshot.version)},
        "operationType": "UPSERT",
    }
    commit_path = os.path.join(hoodie, f"{instant}.commit")
    with open(commit_path, "w") as f:
        json.dump(commit_doc, f, indent=2)
    return commit_path


def hudi_converter_hook(table, txn, version: int, metadata) -> None:
    if "hudi" not in metadata.configuration.get(UNIFORM_FORMATS_KEY, ""):
        return
    convert_snapshot(table.snapshot_at(version))

"""Delta Sharing client.

Reference `sharing/` module: the Spark client materializes a synthetic
in-memory `_delta_log` from the sharing server's protocol responses and
then reads it with the normal Delta machinery
(`DeltaSharingLogFileSystem.scala`, `DeltaSharingDataSource.scala:52`).

The same design here: `SharingClient` speaks the Delta Sharing REST
protocol (delta-io/delta-sharing PROTOCOL.md) through a pluggable
`transport` callable (so tests — and offline use — can inject responses;
an HTTP transport is a 5-line wrapper where egress exists), and
`materialize_shared_table` converts a query response's newline-JSON
(protocol/metaData/file lines) into a local synthetic `_delta_log` whose
AddFiles point at the presigned URLs / local paths, readable by the
normal `Table` stack.
"""

from __future__ import annotations

import http.client
import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from delta_tpu.errors import DeltaError, SharingError

Transport = Callable[[str, Optional[dict]], dict]
"""(endpoint_path, json_body_or_None_for_GET) -> parsed response.

For list endpoints the response is a JSON dict; for query endpoints it is
{"lines": [<ndjson line>, ...]}.
"""


@dataclass
class ShareProfile:
    endpoint: str
    bearer_token: str = ""
    share_credentials_version: int = 1

    @staticmethod
    def from_file(path: str) -> "ShareProfile":
        with open(path) as f:
            d = json.load(f)
        return ShareProfile(
            endpoint=d["endpoint"].rstrip("/"),
            bearer_token=d.get("bearerToken", ""),
            share_credentials_version=int(d.get("shareCredentialsVersion", 1)),
        )


class HttpTransport:
    """Real REST transport (urllib, stdlib-only) for the Delta Sharing
    protocol — the piece the reference implements in
    `sharing/.../DeltaSharingRestClient` (via the delta-sharing client
    lib). GET for list/version endpoints, POST for `/query` and
    `/changes` (newline-delimited JSON responses). Bearer auth from the
    profile; 429/5xx retried with exponential backoff honouring
    `Retry-After`."""

    def __init__(self, profile: ShareProfile, timeout: float = 60.0,
                 max_retries: int = 4):
        self.profile = profile
        self.timeout = timeout
        self.max_retries = max_retries

    def _request(self, url: str, body: Optional[dict]):
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            url, data=data, method="GET" if body is None else "POST")
        if self.profile.bearer_token:
            req.add_header(
                "Authorization", f"Bearer {self.profile.bearer_token}")
        if data is not None:
            req.add_header("Content-Type", "application/json; charset=utf-8")
        delay = 0.5
        # delta-lint: disable=retry-discipline (audited: the sharing
        # protocol's backoff is server-directed — the Retry-After header
        # overrides any client-side schedule, which RetryPolicy's
        # decorrelated jitter cannot express)
        for attempt in range(self.max_retries + 1):
            try:
                return urllib.request.urlopen(req, timeout=self.timeout)
            except urllib.error.HTTPError as e:
                retryable = e.code == 429 or e.code >= 500
                if not retryable or attempt == self.max_retries:
                    detail = ""
                    try:
                        detail = e.read().decode(errors="replace")[:500]
                    except (OSError, http.client.HTTPException):
                        pass  # body unreadable: raise without detail
                    raise SharingError(
                        error_class="DELTA_SHARING_SERVER_ERROR",
                        message=f"sharing server returned HTTP {e.code} for "
                        f"{url}: {detail}") from e
                retry_after = e.headers.get("Retry-After")
                try:
                    # HTTP-date form (RFC 7231) isn't numeric; fall back
                    wait = float(retry_after) if retry_after else delay
                except ValueError:
                    wait = delay
                time.sleep(min(wait, 8.0))
                delay = min(delay * 2, 8.0)
            except urllib.error.URLError as e:
                if attempt == self.max_retries:
                    raise SharingError(
                        error_class="DELTA_SHARING_SERVER_UNREACHABLE",
                        message=f"sharing server unreachable at {url}: {e.reason}"
                    ) from e
                time.sleep(delay)
                delay = min(delay * 2, 8.0)

    def __call__(self, path: str, body: Optional[dict]) -> dict:
        resp = self._request(self.profile.endpoint + path, body)
        with resp:
            raw = resp.read()
            headers = resp.headers
        version = headers.get("Delta-Table-Version")
        base = path.split("?", 1)[0]
        if base.endswith("/version"):
            # version is carried by the response header, not the body
            return {"deltaTableVersion":
                    int(version) if version is not None else None}
        ctype = headers.get("Content-Type", "")
        if base.endswith(("/query", "/changes")) or "ndjson" in ctype:
            out: dict = {"lines": [ln for ln in raw.decode().splitlines()
                                   if ln.strip()]}
        else:
            out = json.loads(raw) if raw.strip() else {}
        if version is not None:
            out.setdefault("deltaTableVersion", int(version))
        return out


class SharingClient:
    def __init__(self, profile: ShareProfile,
                 transport: Optional[Transport] = None):
        self.profile = profile
        self.transport = (transport if transport is not None
                          else HttpTransport(profile))

    def _paged_items(self, path: str) -> List[dict]:
        """Drain a paginated list endpoint (nextPageToken protocol)."""
        items: List[dict] = []
        token: Optional[str] = None
        while True:
            page_path = path
            if token is not None:
                sep = "&" if "?" in path else "?"
                page_path = (f"{path}{sep}pageToken="
                             f"{urllib.parse.quote(token, safe='')}")
            resp = self.transport(page_path, None)
            items.extend(resp.get("items", []))
            token = resp.get("nextPageToken")
            if not token:
                return items

    def list_shares(self) -> List[str]:
        return [s["name"] for s in self._paged_items("/shares")]

    def list_schemas(self, share: str) -> List[str]:
        return [s["name"] for s in self._paged_items(f"/shares/{share}/schemas")]

    def list_tables(self, share: str, schema: str) -> List[str]:
        return [t["name"] for t in
                self._paged_items(f"/shares/{share}/schemas/{schema}/tables")]

    def table_version(self, share: str, schema: str, table: str,
                      starting_timestamp: Optional[str] = None) -> Optional[int]:
        """GET .../version — the server reports the current table version
        in the `Delta-Table-Version` response header."""
        path = f"/shares/{share}/schemas/{schema}/tables/{table}/version"
        if starting_timestamp is not None:
            path += ("?startingTimestamp="
                     + urllib.parse.quote(starting_timestamp, safe=""))
        resp = self.transport(path, None)
        return resp.get("deltaTableVersion")

    def query_table(
        self,
        share: str,
        schema: str,
        table: str,
        predicate_hints: Optional[List[str]] = None,
        limit_hint: Optional[int] = None,
        version: Optional[int] = None,
    ) -> List[dict]:
        """Returns the parsed ndjson response lines (protocol, metaData,
        file entries)."""
        body: dict = {}
        if predicate_hints:
            body["predicateHints"] = predicate_hints
        if limit_hint is not None:
            body["limitHint"] = limit_hint
        if version is not None:
            body["version"] = version
        resp = self.transport(
            f"/shares/{share}/schemas/{schema}/tables/{table}/query", body
        )
        return [json.loads(ln) if isinstance(ln, str) else ln for ln in resp["lines"]]


def materialize_shared_table(lines: List[dict], dest_path: str) -> str:
    """Sharing-protocol response → local synthetic `_delta_log`.

    The sharing wire format wraps delta-like actions: `protocol`
    {minReaderVersion}, `metaData` {id, format, schemaString,
    partitionColumns, configuration}, `file` {url, id, partitionValues,
    size, stats?}. Files become absolute-path AddFiles pointing at `url`.
    """
    protocol_line = next((l["protocol"] for l in lines if "protocol" in l), None)
    meta_line = next((l["metaData"] for l in lines if "metaData" in l), None)
    if meta_line is None:
        raise SharingError("sharing response has no metaData line",
                           error_class="DELTA_SHARING_NO_METADATA")
    files = [l["file"] for l in lines if "file" in l]

    log = os.path.join(dest_path, "_delta_log")
    os.makedirs(log, exist_ok=True)
    out_lines = []
    out_lines.append(
        json.dumps(
            {
                "protocol": {
                    "minReaderVersion": (protocol_line or {}).get("minReaderVersion", 1),
                    "minWriterVersion": 2,
                }
            }
        )
    )
    out_lines.append(
        json.dumps(
            {
                "metaData": {
                    "id": meta_line.get("id", "shared"),
                    "format": meta_line.get("format", {"provider": "parquet", "options": {}}),
                    "schemaString": meta_line["schemaString"],
                    "partitionColumns": meta_line.get("partitionColumns", []),
                    "configuration": meta_line.get("configuration", {}),
                }
            }
        )
    )
    for f in files:
        out_lines.append(
            json.dumps(
                {
                    "add": {
                        "path": f["url"],
                        "partitionValues": f.get("partitionValues", {}),
                        "size": int(f.get("size", 0)),
                        "modificationTime": int(f.get("timestamp", 0)),
                        "dataChange": True,
                        "stats": f.get("stats"),
                    }
                }
            )
        )
    with open(os.path.join(log, "00000000000000000000.json"), "w") as fh:
        fh.write("\n".join(out_lines) + "\n")
    return dest_path


def load_shared_table(
    client: SharingClient,
    share: str,
    schema: str,
    table: str,
    workdir: str,
    engine=None,
    **query_kwargs,
):
    """One-call read: query the server, materialize the synthetic log,
    return a `Table` handle."""
    from delta_tpu.table import Table

    lines = client.query_table(share, schema, table, **query_kwargs)
    dest = os.path.join(workdir, f"{share}.{schema}.{table}")
    materialize_shared_table(lines, dest)
    return Table.for_path(dest, engine)


class SharingStreamSource:
    """Streaming reads of a shared table (the reference's
    `sharing/.../DeltaFormatSharingSource.scala` role): each poll
    re-queries the server, re-materializes the synthetic log, and emits
    only files not seen before (keyed by the server-side file id, falling
    back to the url). The offset is the count of consumed file keys plus
    the last materialized snapshot — a restartable position for a
    protocol that exposes snapshots rather than a commit log."""

    def __init__(self, client: SharingClient, share: str, schema: str,
                 table: str, workdir: str, engine=None,
                 ignore_changes: bool = False):
        self.client = client
        self.share = share
        self.schema = schema
        self.table = table
        self.workdir = workdir
        self.engine = engine
        self.ignore_changes = ignore_changes
        self._seen: set = set()
        self._poll = 0

    @staticmethod
    def _file_key(f: dict) -> str:
        return f.get("id") or f["url"]

    def poll(self):
        """One micro-batch: (new_rows_arrow_table | None, num_new_files).
        None means no new data since the last poll."""
        import shutil

        from delta_tpu.table import Table

        lines = self.client.query_table(self.share, self.schema, self.table)
        files = [l["file"] for l in lines if "file" in l]
        keys_now = {self._file_key(f) for f in files}
        vanished = self._seen - keys_now
        if vanished and not self.ignore_changes:
            # a previously-emitted file left the share: the table was
            # updated/deleted/compacted server-side, and re-emitting the
            # rewritten files would duplicate rows downstream — same
            # contract as DeltaSource's data-changing-remove error
            raise SharingError(
                error_class="DELTA_SHARING_FILES_REWRITTEN",
                message=f"{len(vanished)} previously-streamed file(s) were "
                "rewritten or removed on the sharing server; restart the "
                "stream, or pass ignore_changes=True to re-emit "
                "rewritten files (downstream must tolerate duplicates)")
        fresh = [f for f in files if self._file_key(f) not in self._seen]
        if not fresh:
            return None, 0
        dest = os.path.join(
            self.workdir,
            f"{self.share}.{self.schema}.{self.table}.poll{self._poll}")
        self._poll += 1
        fresh_lines = [l for l in lines if "file" not in l] + [
            {"file": f} for f in fresh]
        materialize_shared_table(fresh_lines, dest)
        try:
            rows = (Table.for_path(dest, self.engine)
                    .latest_snapshot().scan().to_arrow())
        finally:
            # the materialized dir is only a synthetic log (data lives at
            # the server urls); rows are in memory now, so a long-running
            # stream must not accrete one dir per poll
            shutil.rmtree(dest, ignore_errors=True)
        for f in fresh:
            self._seen.add(self._file_key(f))
        return rows, len(fresh)

    def micro_batches(self):
        """Drain currently-available new data."""
        while True:
            rows, n = self.poll()
            if rows is None:
                return
            yield rows, n

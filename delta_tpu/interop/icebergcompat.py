"""IcebergCompat v1/v2 commit-time validation.

Reference `IcebergCompat.scala:42-70`: when
`delta.enableIcebergCompatV1` / `V2` is set, every commit must satisfy
the compat invariants so the UniForm Iceberg conversion can mirror the
table: single compat version, column mapping on, stats on every added
file, no deletion vectors, and (V2) field types restricted to Iceberg's
allow-list.
"""

from __future__ import annotations

from typing import Optional, Sequence

from delta_tpu.errors import DeltaError, IcebergCompatViolationError
from delta_tpu.models.schema import ArrayType, MapType, PrimitiveType, StructType

from delta_tpu.config import ICEBERG_COMPAT_V1, ICEBERG_COMPAT_V2

ICEBERG_COMPAT_V1_KEY = ICEBERG_COMPAT_V1.key
ICEBERG_COMPAT_V2_KEY = ICEBERG_COMPAT_V2.key

# Iceberg's primitive type space (CheckTypeInV2AllowList)
_V2_ALLOWED_PRIMITIVES = {
    "byte", "short", "integer", "long", "float", "double", "boolean",
    "string", "binary", "date", "timestamp", "timestamp_ntz",
}


def _is_true(configuration, key) -> bool:
    from delta_tpu.config import _parse_bool

    return _parse_bool((configuration or {}).get(key, ""))


def enabled_version(configuration) -> Optional[int]:
    from delta_tpu.config import get_table_config

    conf = configuration or {}
    v1 = get_table_config(conf, ICEBERG_COMPAT_V1)
    v2 = get_table_config(conf, ICEBERG_COMPAT_V2)
    if v1 and v2:
        raise IcebergCompatViolationError(
            error_class="DELTA_ICEBERG_COMPAT_VIOLATION.VERSION_MUTUAL_EXCLUSIVE",
            message="icebergCompatV1 and icebergCompatV2 are mutually exclusive "
            "(CheckOnlySingleVersionEnabled)")
    return 1 if v1 else 2 if v2 else None


def _walk_types(dt, path, problems, version: int):
    if isinstance(dt, StructType):
        for f in dt.fields:
            _walk_types(f.dataType, path + [f.name], problems, version)
        return
    if isinstance(dt, ArrayType):
        _walk_types(dt.elementType, path + ["element"], problems, version)
        return
    if isinstance(dt, MapType):
        _walk_types(dt.keyType, path + ["key"], problems, version)
        _walk_types(dt.valueType, path + ["value"], problems, version)
        return
    if isinstance(dt, PrimitiveType):
        if version == 2 and not dt.is_decimal and \
                dt.name not in _V2_ALLOWED_PRIMITIVES:
            problems.append(f"{'.'.join(path)}: type {dt.name!r} outside "
                            "the Iceberg V2 allow-list")


def validate_enablement(snapshot, new_configuration) -> None:
    """Called when a property change newly enables a compat version:
    beyond the metadata checks, no LIVE file may still carry a deletion
    vector — stale DVs would resurrect deleted rows in the Iceberg
    mirror. (The reference routes enablement through REORG UPGRADE
    UNIFORM, which purges first.)"""
    old_v = enabled_version(snapshot.metadata.configuration)
    new_v = enabled_version(new_configuration)
    if new_v is None or new_v == old_v:
        return
    dvs = [d for d in snapshot.state.add_files_table
           .column("deletion_vector").to_pylist() if d]
    if dvs:
        raise IcebergCompatViolationError(
            error_class="DELTA_ICEBERG_COMPAT_VIOLATION.DELETION_VECTORS_NOT_PURGED",
            message=f"cannot enable icebergCompatV{new_v}: {len(dvs)} live "
            "file(s) still carry deletion vectors; run REORG TABLE ... "
            "APPLY (UPGRADE UNIFORM (...)) or PURGE first")


def validate_iceberg_compat(metadata, protocol,
                            adds: Sequence = ()) -> None:
    """Raise when the staged commit violates the enabled compat version;
    no-op when neither flag is set."""
    conf = metadata.configuration or {}
    version = enabled_version(conf)
    if version is None:
        return
    feature = f"icebergCompatV{version}"
    if feature not in (protocol.writerFeatures or []):
        raise IcebergCompatViolationError(
            error_class="DELTA_ICEBERG_COMPAT_VIOLATION.MISSING_REQUIRED_TABLE_FEATURE",
            message=f"delta.enableIcebergCompatV{version} requires the "
            f"{feature} writer table feature")
    mode = conf.get("delta.columnMapping.mode", "none")
    if mode not in ("name", "id"):
        raise IcebergCompatViolationError(
            error_class="DELTA_ICEBERG_COMPAT_VIOLATION.WRONG_REQUIRED_TABLE_PROPERTY",
            message=f"icebergCompatV{version} requires column mapping "
            f"(delta.columnMapping.mode=name), found {mode!r} "
            "(RequireColumnMapping)")
    if _is_true(conf, "delta.enableDeletionVectors"):
        # config-level check, as the reference's
        # CheckDeletionVectorDisabled; live files are additionally
        # checked at ENABLEMENT time (validate_enablement) and staged
        # adds on every commit below — REORG ... APPLY (UPGRADE UNIFORM)
        # is the purge path for tables that already wrote DVs
        raise IcebergCompatViolationError(
            error_class="DELTA_ICEBERG_COMPAT_VIOLATION.DELETION_VECTORS_SHOULD_BE_DISABLED",
            message=f"icebergCompatV{version} is incompatible with deletion "
            "vectors (CheckDeletionVectorDisabled)")
    dv_adds = [a.path for a in adds
               if getattr(a, "deletionVector", None) is not None]
    if dv_adds:
        raise IcebergCompatViolationError(
            error_class="DELTA_ICEBERG_COMPAT_VIOLATION.ADDING_DELETION_VECTORS",
            message=f"icebergCompatV{version}: staged add(s) carry deletion "
            f"vectors ({dv_adds[:3]})")
    problems: list = []
    if metadata.schema is not None:
        _walk_types(metadata.schema, [], problems, version)
    if problems:
        raise IcebergCompatViolationError(
            error_class="DELTA_ICEBERG_COMPAT_VIOLATION.INCOMPATIBLE_SCHEMA",
            message=f"icebergCompatV{version} schema violations: "
            + "; ".join(problems))
    # every AddFile, including dataChange=false rewrites: the Iceberg
    # mirror needs numRecords for each data file (CheckAddFileHasStats)
    missing_stats = [a.path for a in adds if not a.stats]
    if missing_stats:
        raise IcebergCompatViolationError(
            error_class="DELTA_ICEBERG_COMPAT_VIOLATION.FILES_MISSING_STATS",
            message=f"icebergCompatV{version} requires stats on every added "
            f"file (CheckAddFileHasStats); missing on "
            f"{missing_stats[:3]}")

"""IcebergCompat v1/v2 commit-time validation.

Reference `IcebergCompat.scala:42-70`: when
`delta.enableIcebergCompatV1` / `V2` is set, every commit must satisfy
the compat invariants so the UniForm Iceberg conversion can mirror the
table: single compat version, column mapping on, stats on every added
file, no deletion vectors, and (V2) field types restricted to Iceberg's
allow-list.
"""

from __future__ import annotations

from typing import Optional, Sequence

from delta_tpu.errors import DeltaError
from delta_tpu.models.schema import ArrayType, MapType, PrimitiveType, StructType

ICEBERG_COMPAT_V1_KEY = "delta.enableIcebergCompatV1"
ICEBERG_COMPAT_V2_KEY = "delta.enableIcebergCompatV2"

# Iceberg's primitive type space (CheckTypeInV2AllowList)
_V2_ALLOWED_PRIMITIVES = {
    "byte", "short", "integer", "long", "float", "double", "boolean",
    "string", "binary", "date", "timestamp", "timestamp_ntz",
}


def _is_true(configuration, key) -> bool:
    from delta_tpu.config import _parse_bool

    return _parse_bool((configuration or {}).get(key, ""))


def enabled_version(configuration) -> Optional[int]:
    v1 = _is_true(configuration, ICEBERG_COMPAT_V1_KEY)
    v2 = _is_true(configuration, ICEBERG_COMPAT_V2_KEY)
    if v1 and v2:
        raise DeltaError(
            "icebergCompatV1 and icebergCompatV2 are mutually exclusive "
            "(CheckOnlySingleVersionEnabled)")
    return 1 if v1 else 2 if v2 else None


def _walk_types(dt, path, problems, version: int):
    if isinstance(dt, StructType):
        for f in dt.fields:
            _walk_types(f.dataType, path + [f.name], problems, version)
        return
    if isinstance(dt, ArrayType):
        _walk_types(dt.elementType, path + ["element"], problems, version)
        return
    if isinstance(dt, MapType):
        _walk_types(dt.keyType, path + ["key"], problems, version)
        _walk_types(dt.valueType, path + ["value"], problems, version)
        return
    if isinstance(dt, PrimitiveType):
        name = dt.name
        if name == "null":
            problems.append(f"{'.'.join(path)}: null type")
        elif version == 2 and not dt.is_decimal and \
                name not in _V2_ALLOWED_PRIMITIVES:
            problems.append(f"{'.'.join(path)}: type {name!r} outside the "
                            "Iceberg V2 allow-list")


def validate_iceberg_compat(metadata, protocol,
                            adds: Sequence = ()) -> None:
    """Raise when the staged commit violates the enabled compat version;
    no-op when neither flag is set."""
    conf = metadata.configuration or {}
    version = enabled_version(conf)
    if version is None:
        return
    feature = f"icebergCompatV{version}"
    if feature not in (protocol.writerFeatures or []):
        raise DeltaError(
            f"delta.enableIcebergCompatV{version} requires the "
            f"{feature} writer table feature")
    mode = conf.get("delta.columnMapping.mode", "none")
    if mode not in ("name", "id"):
        raise DeltaError(
            f"icebergCompatV{version} requires column mapping "
            f"(delta.columnMapping.mode=name), found {mode!r} "
            "(RequireColumnMapping)")
    if _is_true(conf, "delta.enableDeletionVectors"):
        raise DeltaError(
            f"icebergCompatV{version} is incompatible with deletion "
            "vectors (CheckDeletionVectorDisabled)")
    problems: list = []
    if metadata.schema is not None:
        _walk_types(metadata.schema, [], problems, version)
    if problems:
        raise DeltaError(
            f"icebergCompatV{version} schema violations: "
            + "; ".join(problems))
    missing_stats = [a.path for a in adds
                     if getattr(a, "dataChange", True) and not a.stats]
    if missing_stats:
        raise DeltaError(
            f"icebergCompatV{version} requires stats on every added "
            f"file (CheckAddFileHasStats); missing on "
            f"{missing_stats[:3]}")

"""UniForm: Iceberg metadata generated alongside the Delta log.

Reference `iceberg/` module + `UniversalFormat.scala` +
`IcebergConverterHook.scala:31`: when
`delta.universalFormat.enabledFormats` contains `iceberg`, every commit
triggers (asynchronously in the reference; synchronously here) a
conversion that writes Iceberg v2 metadata — manifest files (Avro),
a manifest list (Avro), vN.metadata.json, and version-hint.text — under
`<table>/metadata/`, all pointing at the same Parquet data files.

The converter snapshots from the Delta state table; each conversion is a
full rewrite of one manifest (correct, if not incremental — the
reference's IcebergConversionTransaction also rewrites on snapshot
boundaries)."""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

from delta_tpu.interop import avro as avro_io
from delta_tpu.models.schema import (
    ArrayType,
    DataType,
    MapType,
    PrimitiveType,
    StructType,
)

UNIFORM_FORMATS_KEY = "delta.universalFormat.enabledFormats"

_DELTA_TO_ICEBERG = {
    "boolean": "boolean",
    "integer": "int",
    "short": "int",
    "byte": "int",
    "long": "long",
    "float": "float",
    "double": "double",
    "string": "string",
    "binary": "binary",
    "date": "date",
    "timestamp": "timestamptz",
    "timestamp_ntz": "timestamp",
}


class _IdGen:
    def __init__(self):
        self.next_id = 0

    def __call__(self):
        self.next_id += 1
        return self.next_id


def _iceberg_type(dt: DataType, ids: _IdGen):
    if isinstance(dt, PrimitiveType):
        if dt.is_decimal:
            p, s = dt.decimal_precision_scale()
            return f"decimal({p}, {s})"
        t = _DELTA_TO_ICEBERG.get(dt.name)
        if t is None:
            from delta_tpu.errors import UniFormConversionError

            raise UniFormConversionError(
                f"no iceberg mapping for {dt.name}",
                error_class="DELTA_UNIVERSAL_FORMAT_CONVERSION_FAILED")
        return t
    if isinstance(dt, StructType):
        return {
            "type": "struct",
            "fields": [
                {
                    "id": ids(),
                    "name": f.name,
                    "required": not f.nullable,
                    "type": _iceberg_type(f.dataType, ids),
                }
                for f in dt.fields
            ],
        }
    if isinstance(dt, ArrayType):
        return {
            "type": "list",
            "element-id": ids(),
            "element": _iceberg_type(dt.elementType, ids),
            "element-required": not dt.containsNull,
        }
    if isinstance(dt, MapType):
        return {
            "type": "map",
            "key-id": ids(),
            "key": _iceberg_type(dt.keyType, ids),
            "value-id": ids(),
            "value": _iceberg_type(dt.valueType, ids),
            "value-required": not dt.valueContainsNull,
        }
    from delta_tpu.errors import UniFormConversionError

    raise UniFormConversionError(
        f"cannot convert {dt!r}",
        error_class="DELTA_UNIVERSAL_FORMAT_CONVERSION_FAILED")


def iceberg_schema(schema: StructType) -> Dict:
    ids = _IdGen()
    top = _iceberg_type(schema, ids)
    return {"schema-id": 0, **top}, ids.next_id


def _field_id_of(ice_schema: Dict, name: str) -> int:
    for f in ice_schema["fields"]:
        if f["name"] == name:
            return f["id"]
    raise KeyError(name)


# Avro schemas for manifests (field-ids per the Iceberg spec appendix).


def _manifest_entry_schema(partition_fields: List[Dict]) -> Dict:
    partition_record = {
        "type": "record",
        "name": "r102",
        "fields": partition_fields,
    }
    data_file = {
        "type": "record",
        "name": "r2",
        "fields": [
            {"name": "content", "type": "int", "field-id": 134},
            {"name": "file_path", "type": "string", "field-id": 100},
            {"name": "file_format", "type": "string", "field-id": 101},
            {"name": "partition", "type": partition_record, "field-id": 102},
            {"name": "record_count", "type": "long", "field-id": 103},
            {"name": "file_size_in_bytes", "type": "long", "field-id": 104},
        ],
    }
    return {
        "type": "record",
        "name": "manifest_entry",
        "fields": [
            {"name": "status", "type": "int", "field-id": 0},
            {"name": "snapshot_id", "type": ["null", "long"], "field-id": 1},
            {"name": "sequence_number", "type": ["null", "long"], "field-id": 3},
            {"name": "file_sequence_number", "type": ["null", "long"], "field-id": 4},
            {"name": "data_file", "type": data_file, "field-id": 2},
        ],
    }


_MANIFEST_FILE_SCHEMA = {
    "type": "record",
    "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "content", "type": "int", "field-id": 517},
        {"name": "sequence_number", "type": "long", "field-id": 515},
        {"name": "min_sequence_number", "type": "long", "field-id": 516},
        {"name": "added_snapshot_id", "type": "long", "field-id": 503},
        {"name": "added_files_count", "type": "int", "field-id": 504},
        {"name": "existing_files_count", "type": "int", "field-id": 505},
        {"name": "deleted_files_count", "type": "int", "field-id": 506},
        {"name": "added_rows_count", "type": "long", "field-id": 512},
        {"name": "existing_rows_count", "type": "long", "field-id": 513},
        {"name": "deleted_rows_count", "type": "long", "field-id": 514},
    ],
}

_ICEBERG_PRIM_TO_AVRO = {
    "boolean": "boolean",
    "int": "int",
    "long": "long",
    "float": "float",
    "double": "double",
    "string": "string",
    "binary": "bytes",
    "date": {"type": "int", "logicalType": "date"},
    "timestamp": {"type": "long", "logicalType": "timestamp-micros"},
    "timestamptz": {"type": "long", "logicalType": "timestamp-micros"},
}


SNAPSHOT_RETENTION = 20  # expire-snapshots: keep at most this many


def iceberg_schema_stable(schema: StructType, configuration) -> tuple:
    """Iceberg schema with STABLE field ids: when Delta column mapping is
    active (IcebergCompat requires it), field ids come from
    `delta.columnMapping.id` so renames keep their identity across
    conversions (reference `IcebergConversionTransaction`'s schema
    mapping). Collection element ids are allocated past maxColumnId.
    Without mapping, falls back to first-fit sequential ids."""
    mode = (configuration or {}).get("delta.columnMapping.mode", "none")
    if mode == "none":
        return iceberg_schema(schema)
    max_id = int((configuration or {}).get(
        "delta.columnMapping.maxColumnId", "0"))
    gen = _IdGen()
    gen.next_id = max_id  # element/key/value ids go beyond mapped ids

    def conv(dt: DataType):
        if isinstance(dt, StructType):
            out = []
            for f in dt.fields:
                fid = f.metadata.get("delta.columnMapping.id")
                out.append({
                    "id": int(fid) if fid is not None else gen(),
                    "name": f.name,
                    "required": not f.nullable,
                    "type": conv(f.dataType),
                })
            return {"type": "struct", "fields": out}
        if isinstance(dt, ArrayType):
            return {"type": "list", "element-id": gen(),
                    "element": conv(dt.elementType),
                    "element-required": not dt.containsNull}
        if isinstance(dt, MapType):
            return {"type": "map", "key-id": gen(), "key": conv(dt.keyType),
                    "value-id": gen(), "value": conv(dt.valueType),
                    "value-required": not dt.valueContainsNull}
        return _iceberg_type(dt, gen)

    top = conv(schema)
    return {"schema-id": 0, **top}, gen.next_id


def _partition_spec(ice_schema, schema, partition_cols):
    spec_fields = []
    partition_avro_fields = []
    for i, c in enumerate(partition_cols):
        source_id = _field_id_of(ice_schema, c)
        field_id = 1000 + i
        spec_fields.append(
            {"name": c, "transform": "identity", "source-id": source_id,
             "field-id": field_id})
        f = schema[c]
        ice_t = (_DELTA_TO_ICEBERG.get(f.dataType.name, "string")
                 if isinstance(f.dataType, PrimitiveType) else "string")
        avro_t = _ICEBERG_PRIM_TO_AVRO.get(ice_t, "string")
        partition_avro_fields.append(
            {"name": c, "type": ["null", avro_t], "field-id": field_id})
    return spec_fields, partition_avro_fields


def _partition_value(schema, partition_cols, pv, c):
    from delta_tpu.stats.partition import deserialize_partition_value
    import datetime as dt

    f = schema[c]
    dtype = (f.dataType if isinstance(f.dataType, PrimitiveType)
             else PrimitiveType("string"))
    v = deserialize_partition_value(pv.get(c), dtype)
    if isinstance(v, dt.date) and not isinstance(v, dt.datetime):
        v = (v - dt.date(1970, 1, 1)).days
    elif isinstance(v, dt.datetime):
        v = int(v.timestamp() * 1_000_000)
    return v


def _data_file_entry(table_path, schema, partition_cols, path, size, pv,
                     stats, status, snapshot_id):
    abs_path = (path if ("://" in path or path.startswith("/"))
                else f"{table_path}/{path}")
    nrec = 0
    if stats:
        try:
            nrec = int(json.loads(stats).get("numRecords") or 0)
        except ValueError:
            pass
    pv_dict = {k: v for k, v in pv} if isinstance(pv, list) else (pv or {})
    partition = {c: _partition_value(schema, partition_cols, pv_dict, c)
                 for c in partition_cols}
    return {
        "status": status,  # 1 ADDED / 0 EXISTING / 2 DELETED
        "snapshot_id": snapshot_id,
        "sequence_number": None,       # inherited
        "file_sequence_number": None,
        "data_file": {
            "content": 0,
            "file_path": abs_path,
            "file_format": "PARQUET",
            "partition": partition,
            "record_count": nrec,
            "file_size_in_bytes": int(size or 0),
        },
    }, nrec


def _write_manifest(meta_dir, entries, entry_schema, ice_schema,
                    spec_fields):
    name = f"manifest-{uuid.uuid4()}.avro"
    path = os.path.join(meta_dir, name)
    data = avro_io.write_ocf(
        entry_schema, entries,
        metadata={
            "schema": json.dumps(ice_schema),
            "partition-spec": json.dumps(spec_fields),
            "partition-spec-id": "0",
            "format-version": "2",
            "content": "data",
        })
    with open(path, "wb") as f:
        f.write(data)
    return path, len(data)


def _manifest_list_entry(path, length, seq, snapshot_id, added, existing,
                         deleted, added_rows, existing_rows, deleted_rows):
    return {
        "manifest_path": path,
        "manifest_length": length,
        "partition_spec_id": 0,
        "content": 0,
        "sequence_number": seq,
        "min_sequence_number": seq,
        "added_snapshot_id": snapshot_id,
        "added_files_count": added,
        "existing_files_count": existing,
        "deleted_files_count": deleted,
        "added_rows_count": added_rows,
        "existing_rows_count": existing_rows,
        "deleted_rows_count": deleted_rows,
    }


def _load_prev_metadata(meta_dir):
    v = _read_version_hint(meta_dir)
    if v is None:
        return None, None
    path = os.path.join(meta_dir, f"v{v}.metadata.json")
    try:
        with open(path) as f:
            return json.load(f), v
    except (FileNotFoundError, ValueError):
        return None, None


def convert_snapshot(snapshot, table_path: Optional[str] = None) -> str:
    """Write Iceberg metadata for `snapshot`; returns the metadata.json
    path.

    Incremental per-commit-type conversion (reference
    `IcebergConverter.scala:74` + `IcebergConversionTransaction`):
    appends become a new ADDED manifest while previous manifests are
    REUSED untouched; deletes/rewrites rewrite only the manifests that
    contain removed files (entries marked DELETED); the snapshot list
    grows with parent ids + snapshot-log/metadata-log entries; snapshots
    beyond SNAPSHOT_RETENTION are expired (their manifest lists removed,
    manifests kept while any retained snapshot references them). Falls
    back to a full rewrite when there is no previous conversion, the
    schema changed, or the needed commit range was vacuumed."""
    table_path = table_path or snapshot.table_path
    meta_dir = os.path.join(table_path, "metadata")
    os.makedirs(meta_dir, exist_ok=True)

    delta_meta = snapshot.metadata
    schema = delta_meta.schema
    configuration = delta_meta.configuration
    ice_schema, last_column_id = iceberg_schema_stable(schema, configuration)
    partition_cols = list(delta_meta.partitionColumns)
    spec_fields, partition_avro_fields = _partition_spec(
        ice_schema, schema, partition_cols)
    entry_schema = _manifest_entry_schema(partition_avro_fields)
    snapshot_id = snapshot.version + 1  # stable, monotonic
    now_ms = int(time.time() * 1000)

    prev_doc, prev_md_version = _load_prev_metadata(meta_dir)
    incremental = None
    schema_changed = False
    if prev_doc is not None:
        try:
            prev_delta_v = int(prev_doc["properties"]["delta.version"])
        except (KeyError, ValueError):
            prev_delta_v = None
        prev_schema = next(
            (s for s in prev_doc.get("schemas", [])
             if s.get("schema-id") == prev_doc.get("current-schema-id")),
            None)
        schema_changed = (prev_schema is not None and
                          prev_schema.get("fields") != ice_schema["fields"])
        if (prev_delta_v is not None and prev_delta_v < snapshot.version
                and not schema_changed):
            from delta_tpu.interop.commitrange import delta_range_actions

            rng = delta_range_actions(
                table_path, prev_delta_v + 1, snapshot.version)
            # a metadata change may alter the partition spec that reused
            # manifests were written under: force the full rewrite
            if rng is not None and not rng[2]:
                # remove-then-re-add (rng[4]) must drop the old entry
                # from reused manifests — the re-add lands in the new
                # ADDED manifest, so the stale live entry would be a
                # duplicate
                incremental = (rng[0], rng[1] | rng[4])
        if prev_delta_v is not None and prev_delta_v >= snapshot.version:
            return os.path.join(
                meta_dir, f"v{prev_md_version}.metadata.json")

    sequence_number = (prev_doc["last-sequence-number"] + 1
                       if prev_doc is not None else 1)

    mlist_entries: List[dict] = []
    summary_op = "overwrite"
    added_count = deleted_count = 0
    added_rows = 0
    deleted_rows_total = 0

    if incremental is not None and prev_doc is not None:
        adds, removed_paths = incremental
        removed_abs = {
            p if ("://" in p or p.startswith("/")) else f"{table_path}/{p}"
            for p in removed_paths}
        # previous snapshot's manifest list
        prev_snap = next(
            s for s in prev_doc["snapshots"]
            if s["snapshot-id"] == prev_doc["current-snapshot-id"])
        with open(prev_snap["manifest-list"], "rb") as f:
            _, prev_manifests, _ = avro_io.read_ocf(f.read())
        for m in prev_manifests:
            with open(m["manifest_path"], "rb") as f:
                _, entries, _ = avro_io.read_ocf(f.read())
            live = [e for e in entries if e["status"] != 2]
            hit = [e for e in live
                   if e["data_file"]["file_path"] in removed_abs]
            if not hit:
                mlist_entries.append(m)  # reuse untouched
                continue
            # rewrite: removed entries marked DELETED, the rest EXISTING
            new_entries = []
            kept_rows = del_rows = 0
            for e in live:
                dead = e["data_file"]["file_path"] in removed_abs
                new_entries.append({
                    **e,
                    "status": 2 if dead else 0,
                    "snapshot_id": snapshot_id if dead
                    else e["snapshot_id"],
                    # EXISTING/DELETED entries may not inherit a null
                    # sequence number from a manifest they didn't enter
                    # with (Iceberg v2 inheritance rule): make the data
                    # sequence explicit
                    "sequence_number": (e["sequence_number"]
                                        if e["sequence_number"] is not None
                                        else m["sequence_number"]),
                })
                if dead:
                    del_rows += e["data_file"]["record_count"]
                    deleted_rows_total += e["data_file"]["record_count"]
                    deleted_count += 1
                else:
                    kept_rows += e["data_file"]["record_count"]
            path, length = _write_manifest(
                meta_dir, new_entries, entry_schema, ice_schema, spec_fields)
            mlist_entries.append(_manifest_list_entry(
                path, length, m["sequence_number"], snapshot_id,
                0, len(new_entries) - len(hit), len(hit),
                0, kept_rows, del_rows))
        new_adds = []
        for p, a in adds.items():
            entry, nrec = _data_file_entry(
                table_path, schema, partition_cols, p, a.get("size"),
                a.get("partitionValues"), a.get("stats"), 1, snapshot_id)
            new_adds.append(entry)
            added_rows += nrec
        added_count = len(new_adds)
        if new_adds:
            path, length = _write_manifest(
                meta_dir, new_adds, entry_schema, ice_schema, spec_fields)
            mlist_entries.append(_manifest_list_entry(
                path, length, sequence_number, snapshot_id,
                len(new_adds), 0, 0, added_rows, 0, 0))
        summary_op = ("append" if not removed_paths
                      else ("delete" if not adds else "overwrite"))
    else:
        # full conversion from the snapshot's live set
        files = snapshot.state.add_files_table
        entries = []
        for p, size, pv, st in zip(
                files.column("path").to_pylist(),
                files.column("size").to_pylist(),
                files.column("partition_values").to_pylist(),
                files.column("stats").to_pylist()):
            entry, nrec = _data_file_entry(
                table_path, schema, partition_cols, p, size, pv, st, 1,
                snapshot_id)
            entries.append(entry)
            added_rows += nrec
        added_count = len(entries)
        path, length = _write_manifest(
            meta_dir, entries, entry_schema, ice_schema, spec_fields)
        mlist_entries.append(_manifest_list_entry(
            path, length, sequence_number, snapshot_id,
            len(entries), 0, 0, added_rows, 0, 0))

    # --- manifest list ---
    mlist_name = f"snap-{snapshot_id}-{uuid.uuid4()}.avro"
    mlist_path = os.path.join(meta_dir, mlist_name)
    mlist_bytes = avro_io.write_ocf(
        _MANIFEST_FILE_SCHEMA, mlist_entries,
        metadata={"format-version": "2"})
    with open(mlist_path, "wb") as f:
        f.write(mlist_bytes)

    # --- table metadata: lineage, schema evolution, expiry ---
    # running table total: previous snapshot's total +/- this commit's
    # net rows (full conversions re-derive it from the live set)
    if prev_doc is not None and incremental is not None:
        prev_snap_for_total = next(
            (s for s in prev_doc.get("snapshots", [])
             if s["snapshot-id"] == prev_doc.get("current-snapshot-id")),
            None)
        try:
            prev_total = int(
                prev_snap_for_total["summary"]["total-records"])
        except (TypeError, KeyError, ValueError):
            prev_total = 0
        total_records = prev_total + added_rows - deleted_rows_total
    else:
        total_records = added_rows
    new_snap = {
        "snapshot-id": snapshot_id,
        "sequence-number": sequence_number,
        "timestamp-ms": now_ms,
        "manifest-list": mlist_path,
        "summary": {
            "operation": summary_op,
            "added-data-files": str(added_count),
            "deleted-data-files": str(deleted_count),
            "added-records": str(added_rows),
            "total-records": str(total_records),
        },
        "schema-id": 0,
    }
    snapshots: List[dict] = []
    snapshot_log: List[dict] = []
    metadata_log: List[dict] = []
    schemas = [ice_schema]
    current_schema_id = 0
    if prev_doc is not None:
        snapshots = list(prev_doc.get("snapshots", []))
        snapshot_log = list(prev_doc.get("snapshot-log", []))
        metadata_log = list(prev_doc.get("metadata-log", []))
        new_snap["parent-snapshot-id"] = prev_doc.get("current-snapshot-id")
        # schema evolution: keep history, bump schema-id on change
        schemas = list(prev_doc.get("schemas", [])) or [ice_schema]
        if schema_changed:
            current_schema_id = max(
                s.get("schema-id", 0) for s in schemas) + 1
            schemas.append({**ice_schema, "schema-id": current_schema_id})
        else:
            current_schema_id = prev_doc.get("current-schema-id", 0)
        new_snap["schema-id"] = current_schema_id
        metadata_log.append({
            "metadata-file": os.path.join(
                meta_dir, f"v{prev_md_version}.metadata.json"),
            "timestamp-ms": prev_doc.get("last-updated-ms", now_ms),
        })
    snapshots.append(new_snap)
    snapshot_log.append({"snapshot-id": snapshot_id, "timestamp-ms": now_ms})

    # expire-snapshots: retain the newest SNAPSHOT_RETENTION
    if len(snapshots) > SNAPSHOT_RETENTION:
        expired = snapshots[:-SNAPSHOT_RETENTION]
        snapshots = snapshots[-SNAPSHOT_RETENTION:]
        keep_ids = {s["snapshot-id"] for s in snapshots}
        snapshot_log = [e for e in snapshot_log
                        if e["snapshot-id"] in keep_ids]
        # referenced manifests survive; orphaned manifest lists go
        referenced = set()
        for s in snapshots:
            try:
                with open(s["manifest-list"], "rb") as f:
                    _, ms, _ = avro_io.read_ocf(f.read())
                referenced |= {m["manifest_path"] for m in ms}
            except (FileNotFoundError, ValueError):
                pass
        for s in expired:
            try:
                with open(s["manifest-list"], "rb") as f:
                    _, ms, _ = avro_io.read_ocf(f.read())
                for m in ms:
                    mp = m["manifest_path"]
                    if mp not in referenced and os.path.exists(mp):
                        os.unlink(mp)
                os.unlink(s["manifest-list"])
            except (FileNotFoundError, ValueError):
                pass

    schemas_out = []
    for s in schemas:
        sid = s.get("schema-id", 0)
        if sid == current_schema_id:
            schemas_out.append({**ice_schema, "schema-id": sid})
        else:
            schemas_out.append(s)

    metadata_version = (prev_md_version or 0) + 1
    metadata_doc = {
        "format-version": 2,
        "table-uuid": delta_meta.id,
        "location": table_path,
        "last-sequence-number": sequence_number,
        "last-updated-ms": now_ms,
        "last-column-id": last_column_id,
        "current-schema-id": current_schema_id,
        "schemas": schemas_out,
        "default-spec-id": 0,
        "partition-specs": [{"spec-id": 0, "fields": spec_fields}],
        "last-partition-id": 1000 + max(0, len(spec_fields)) - 1 if spec_fields else 999,
        "default-sort-order-id": 0,
        "sort-orders": [{"order-id": 0, "fields": []}],
        "properties": {
            "delta.universalFormat": "iceberg",
            "delta.version": str(snapshot.version),
        },
        "current-snapshot-id": snapshot_id,
        "snapshots": snapshots,
        "snapshot-log": snapshot_log,
        "metadata-log": metadata_log,
    }
    md_path = os.path.join(meta_dir, f"v{metadata_version}.metadata.json")
    with open(md_path, "w") as f:
        json.dump(metadata_doc, f, indent=2)
    with open(os.path.join(meta_dir, "version-hint.text"), "w") as f:
        f.write(str(metadata_version))
    return md_path


def _read_version_hint(meta_dir: str) -> Optional[int]:
    try:
        with open(os.path.join(meta_dir, "version-hint.text")) as f:
            return int(f.read().strip())
    except (FileNotFoundError, ValueError):
        return None


def iceberg_converter_hook(table, txn, version: int, metadata) -> None:
    """Post-commit UniForm hook (register via
    delta_tpu.hooks.register_post_commit_hook)."""
    formats = metadata.configuration.get(UNIFORM_FORMATS_KEY, "")
    if "iceberg" not in formats:
        return
    snap = table.snapshot_at(version)
    convert_snapshot(snap)

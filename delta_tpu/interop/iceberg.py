"""UniForm: Iceberg metadata generated alongside the Delta log.

Reference `iceberg/` module + `UniversalFormat.scala` +
`IcebergConverterHook.scala:31`: when
`delta.universalFormat.enabledFormats` contains `iceberg`, every commit
triggers (asynchronously in the reference; synchronously here) a
conversion that writes Iceberg v2 metadata — manifest files (Avro),
a manifest list (Avro), vN.metadata.json, and version-hint.text — under
`<table>/metadata/`, all pointing at the same Parquet data files.

The converter snapshots from the Delta state table; each conversion is a
full rewrite of one manifest (correct, if not incremental — the
reference's IcebergConversionTransaction also rewrites on snapshot
boundaries)."""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

from delta_tpu.interop import avro as avro_io
from delta_tpu.models.schema import (
    ArrayType,
    DataType,
    MapType,
    PrimitiveType,
    StructType,
)

UNIFORM_FORMATS_KEY = "delta.universalFormat.enabledFormats"

_DELTA_TO_ICEBERG = {
    "boolean": "boolean",
    "integer": "int",
    "short": "int",
    "byte": "int",
    "long": "long",
    "float": "float",
    "double": "double",
    "string": "string",
    "binary": "binary",
    "date": "date",
    "timestamp": "timestamptz",
    "timestamp_ntz": "timestamp",
}


class _IdGen:
    def __init__(self):
        self.next_id = 0

    def __call__(self):
        self.next_id += 1
        return self.next_id


def _iceberg_type(dt: DataType, ids: _IdGen):
    if isinstance(dt, PrimitiveType):
        if dt.is_decimal:
            p, s = dt.decimal_precision_scale()
            return f"decimal({p}, {s})"
        t = _DELTA_TO_ICEBERG.get(dt.name)
        if t is None:
            raise ValueError(f"no iceberg mapping for {dt.name}")
        return t
    if isinstance(dt, StructType):
        return {
            "type": "struct",
            "fields": [
                {
                    "id": ids(),
                    "name": f.name,
                    "required": not f.nullable,
                    "type": _iceberg_type(f.dataType, ids),
                }
                for f in dt.fields
            ],
        }
    if isinstance(dt, ArrayType):
        return {
            "type": "list",
            "element-id": ids(),
            "element": _iceberg_type(dt.elementType, ids),
            "element-required": not dt.containsNull,
        }
    if isinstance(dt, MapType):
        return {
            "type": "map",
            "key-id": ids(),
            "key": _iceberg_type(dt.keyType, ids),
            "value-id": ids(),
            "value": _iceberg_type(dt.valueType, ids),
            "value-required": not dt.valueContainsNull,
        }
    raise ValueError(f"cannot convert {dt!r}")


def iceberg_schema(schema: StructType) -> Dict:
    ids = _IdGen()
    top = _iceberg_type(schema, ids)
    return {"schema-id": 0, **top}, ids.next_id


def _field_id_of(ice_schema: Dict, name: str) -> int:
    for f in ice_schema["fields"]:
        if f["name"] == name:
            return f["id"]
    raise KeyError(name)


# Avro schemas for manifests (field-ids per the Iceberg spec appendix).


def _manifest_entry_schema(partition_fields: List[Dict]) -> Dict:
    partition_record = {
        "type": "record",
        "name": "r102",
        "fields": partition_fields,
    }
    data_file = {
        "type": "record",
        "name": "r2",
        "fields": [
            {"name": "content", "type": "int", "field-id": 134},
            {"name": "file_path", "type": "string", "field-id": 100},
            {"name": "file_format", "type": "string", "field-id": 101},
            {"name": "partition", "type": partition_record, "field-id": 102},
            {"name": "record_count", "type": "long", "field-id": 103},
            {"name": "file_size_in_bytes", "type": "long", "field-id": 104},
        ],
    }
    return {
        "type": "record",
        "name": "manifest_entry",
        "fields": [
            {"name": "status", "type": "int", "field-id": 0},
            {"name": "snapshot_id", "type": ["null", "long"], "field-id": 1},
            {"name": "sequence_number", "type": ["null", "long"], "field-id": 3},
            {"name": "file_sequence_number", "type": ["null", "long"], "field-id": 4},
            {"name": "data_file", "type": data_file, "field-id": 2},
        ],
    }


_MANIFEST_FILE_SCHEMA = {
    "type": "record",
    "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "content", "type": "int", "field-id": 517},
        {"name": "sequence_number", "type": "long", "field-id": 515},
        {"name": "min_sequence_number", "type": "long", "field-id": 516},
        {"name": "added_snapshot_id", "type": "long", "field-id": 503},
        {"name": "added_files_count", "type": "int", "field-id": 504},
        {"name": "existing_files_count", "type": "int", "field-id": 505},
        {"name": "deleted_files_count", "type": "int", "field-id": 506},
        {"name": "added_rows_count", "type": "long", "field-id": 512},
        {"name": "existing_rows_count", "type": "long", "field-id": 513},
        {"name": "deleted_rows_count", "type": "long", "field-id": 514},
    ],
}

_ICEBERG_PRIM_TO_AVRO = {
    "boolean": "boolean",
    "int": "int",
    "long": "long",
    "float": "float",
    "double": "double",
    "string": "string",
    "binary": "bytes",
    "date": {"type": "int", "logicalType": "date"},
    "timestamp": {"type": "long", "logicalType": "timestamp-micros"},
    "timestamptz": {"type": "long", "logicalType": "timestamp-micros"},
}


def convert_snapshot(snapshot, table_path: Optional[str] = None) -> str:
    """Write Iceberg metadata for `snapshot`; returns the metadata.json
    path."""
    table_path = table_path or snapshot.table_path
    meta_dir = os.path.join(table_path, "metadata")
    os.makedirs(meta_dir, exist_ok=True)

    delta_meta = snapshot.metadata
    schema = delta_meta.schema
    ice_schema, last_column_id = iceberg_schema(schema)
    partition_cols = list(delta_meta.partitionColumns)
    snapshot_id = snapshot.version + 1  # stable, monotonic
    sequence_number = snapshot.version + 1
    now_ms = int(time.time() * 1000)

    # partition spec
    spec_fields = []
    partition_avro_fields = []
    for i, c in enumerate(partition_cols):
        source_id = _field_id_of(ice_schema, c)
        field_id = 1000 + i
        spec_fields.append(
            {"name": c, "transform": "identity", "source-id": source_id,
             "field-id": field_id}
        )
        f = schema[c]
        ice_t = (
            _DELTA_TO_ICEBERG.get(f.dataType.name, "string")
            if isinstance(f.dataType, PrimitiveType)
            else "string"
        )
        avro_t = _ICEBERG_PRIM_TO_AVRO.get(ice_t, "string")
        partition_avro_fields.append(
            {"name": c, "type": ["null", avro_t], "field-id": field_id}
        )

    # --- manifest ---
    from delta_tpu.stats.partition import deserialize_partition_value

    entries = []
    files = snapshot.state.add_files_table
    paths = files.column("path").to_pylist()
    sizes = files.column("size").to_pylist()
    pvs = files.column("partition_values").to_pylist()
    stats_col = files.column("stats").to_pylist()
    total_rows = 0
    for p, size, pv, st in zip(paths, sizes, pvs, stats_col):
        abs_path = p if ("://" in p or p.startswith("/")) else f"{table_path}/{p}"
        nrec = 0
        if st:
            try:
                nrec = int(json.loads(st).get("numRecords") or 0)
            except ValueError:
                pass
        total_rows += nrec
        pv_dict = {k: v for k, v in pv} if isinstance(pv, list) else (pv or {})
        partition = {}
        for c in partition_cols:
            f = schema[c]
            dtype = f.dataType if isinstance(f.dataType, PrimitiveType) else PrimitiveType("string")
            v = deserialize_partition_value(pv_dict.get(c), dtype)
            import datetime as dt

            if isinstance(v, dt.date) and not isinstance(v, dt.datetime):
                v = (v - dt.date(1970, 1, 1)).days
            elif isinstance(v, dt.datetime):
                v = int(v.timestamp() * 1_000_000)
            partition[c] = v
        entries.append(
            {
                "status": 1,  # ADDED (full rewrite each conversion)
                "snapshot_id": snapshot_id,
                "sequence_number": None,     # inherited
                "file_sequence_number": None,
                "data_file": {
                    "content": 0,
                    "file_path": abs_path,
                    "file_format": "PARQUET",
                    "partition": partition,
                    "record_count": nrec,
                    "file_size_in_bytes": int(size or 0),
                },
            }
        )

    entry_schema = _manifest_entry_schema(partition_avro_fields)
    manifest_name = f"manifest-{uuid.uuid4()}.avro"
    manifest_path = os.path.join(meta_dir, manifest_name)
    manifest_bytes = avro_io.write_ocf(
        entry_schema, entries,
        metadata={
            "schema": json.dumps(ice_schema),
            "partition-spec": json.dumps(spec_fields),
            "partition-spec-id": "0",
            "format-version": "2",
            "content": "data",
        },
    )
    with open(manifest_path, "wb") as f:
        f.write(manifest_bytes)

    # --- manifest list ---
    mlist_name = f"snap-{snapshot_id}-{uuid.uuid4()}.avro"
    mlist_path = os.path.join(meta_dir, mlist_name)
    mlist_bytes = avro_io.write_ocf(
        _MANIFEST_FILE_SCHEMA,
        [
            {
                "manifest_path": manifest_path,
                "manifest_length": len(manifest_bytes),
                "partition_spec_id": 0,
                "content": 0,
                "sequence_number": sequence_number,
                "min_sequence_number": sequence_number,
                "added_snapshot_id": snapshot_id,
                "added_files_count": len(entries),
                "existing_files_count": 0,
                "deleted_files_count": 0,
                "added_rows_count": total_rows,
                "existing_rows_count": 0,
                "deleted_rows_count": 0,
            }
        ],
        metadata={"format-version": "2"},
    )
    with open(mlist_path, "wb") as f:
        f.write(mlist_bytes)

    # --- table metadata ---
    prev_meta = _read_version_hint(meta_dir)
    metadata_version = (prev_meta or 0) + 1
    metadata_doc = {
        "format-version": 2,
        "table-uuid": delta_meta.id,
        "location": table_path,
        "last-sequence-number": sequence_number,
        "last-updated-ms": now_ms,
        "last-column-id": last_column_id,
        "current-schema-id": 0,
        "schemas": [ice_schema],
        "default-spec-id": 0,
        "partition-specs": [{"spec-id": 0, "fields": spec_fields}],
        "last-partition-id": 1000 + max(0, len(spec_fields)) - 1 if spec_fields else 999,
        "default-sort-order-id": 0,
        "sort-orders": [{"order-id": 0, "fields": []}],
        "properties": {
            "delta.universalFormat": "iceberg",
            "delta.version": str(snapshot.version),
        },
        "current-snapshot-id": snapshot_id,
        "snapshots": [
            {
                "snapshot-id": snapshot_id,
                "sequence-number": sequence_number,
                "timestamp-ms": now_ms,
                "manifest-list": mlist_path,
                "summary": {
                    "operation": "overwrite",
                    "added-data-files": str(len(entries)),
                    "total-records": str(total_rows),
                },
                "schema-id": 0,
            }
        ],
        "snapshot-log": [
            {"snapshot-id": snapshot_id, "timestamp-ms": now_ms}
        ],
        "metadata-log": [],
    }
    md_path = os.path.join(meta_dir, f"v{metadata_version}.metadata.json")
    with open(md_path, "w") as f:
        json.dump(metadata_doc, f, indent=2)
    with open(os.path.join(meta_dir, "version-hint.text"), "w") as f:
        f.write(str(metadata_version))
    return md_path


def _read_version_hint(meta_dir: str) -> Optional[int]:
    try:
        with open(os.path.join(meta_dir, "version-hint.text")) as f:
            return int(f.read().strip())
    except (FileNotFoundError, ValueError):
        return None


def iceberg_converter_hook(table, txn, version: int, metadata) -> None:
    """Post-commit UniForm hook (register via
    delta_tpu.hooks.register_post_commit_hook)."""
    formats = metadata.configuration.get(UNIFORM_FORMATS_KEY, "")
    if "iceberg" not in formats:
        return
    snap = table.snapshot_at(version)
    convert_snapshot(snap)

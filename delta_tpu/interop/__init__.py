"""Interop: UniForm metadata converters (Iceberg, Hudi) and the sharing
client. The reference ships these as `iceberg/`, `hudi/`, `sharing/`
modules driven by post-commit hooks (`IcebergConverterHook.scala`,
`HudiConverterHook.scala`)."""

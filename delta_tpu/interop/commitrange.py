"""Shared Delta commit-range walker for the UniForm converters.

Both the Iceberg and Hudi incremental conversions consume the same
input: the net added files and removed paths across a contiguous range
of Delta commits (reference `IcebergConverter`'s commit-range walk).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple


def delta_range_actions(
    table_path: str, lo: int, hi: int,
) -> Optional[Tuple[Dict[str, dict], set, bool, Dict[str, dict]]]:
    """Walk commits [lo, hi] of `table_path`'s log. Returns (net added
    AddFile dicts by path, net removed path set, metadata_changed,
    removed RemoveFile dicts by path) — or None when any commit file in
    the range is gone (cleaned/checkpointed), signalling the caller to
    fall back to a full conversion."""
    log = os.path.join(table_path, "_delta_log")
    adds: Dict[str, dict] = {}
    removes: Dict[str, dict] = {}
    meta_changed = False
    for v in range(lo, hi + 1):
        try:
            fh = open(os.path.join(log, f"{v:020d}.json"))
        except FileNotFoundError:
            return None
        with fh:
            for ln in fh:
                if not ln.strip():
                    continue
                act = json.loads(ln)
                if "add" in act:
                    a = act["add"]
                    adds[a["path"]] = a
                    removes.pop(a["path"], None)
                elif "remove" in act:
                    r = act["remove"]
                    removes[r["path"]] = r
                    adds.pop(r["path"], None)
                elif "metaData" in act:
                    meta_changed = True
    return adds, set(removes), meta_changed, removes

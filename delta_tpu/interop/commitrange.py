"""Shared Delta commit-range walker for the UniForm converters.

Both the Iceberg and Hudi incremental conversions consume the same
input: the net added files and removed paths across a contiguous range
of Delta commits (reference `IcebergConverter`'s commit-range walk).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple


def delta_range_actions(
    table_path: str, lo: int, hi: int,
) -> Optional[Tuple[Dict[str, dict], set, bool, Dict[str, dict], set]]:
    """Walk commits [lo, hi] of `table_path`'s log. Returns (net added
    AddFile dicts by path, net removed path set, metadata_changed,
    removed RemoveFile dicts by path, rewritten path set) — or None when
    any commit file in the range is gone (cleaned/checkpointed),
    signalling the caller to fall back to a full conversion.

    `rewritten` is the set of paths removed at some point in the range
    but net-ADDED by its end (remove-then-re-add, e.g. RESTORE).  The
    netting alone would hide these from converters that REUSE prior
    metadata: the path lands in `adds`, so an incremental Iceberg
    conversion would emit it ADDED in a new manifest while the reused
    old manifest still carries it live — a duplicate entry.  Manifest-
    reusing converters must treat `rewritten` paths as removed from
    prior state (then re-added by the new commit).  Hudi ignores it by
    design: same path = same fileId, and Hudi readers take the latest
    write stat per file group, so a re-emitted stat supersedes cleanly."""
    log = os.path.join(table_path, "_delta_log")
    adds: Dict[str, dict] = {}
    removes: Dict[str, dict] = {}
    ever_removed: set = set()
    meta_changed = False
    for v in range(lo, hi + 1):
        try:
            fh = open(os.path.join(log, f"{v:020d}.json"))
        except FileNotFoundError:
            return None
        with fh:
            for ln in fh:
                if not ln.strip():
                    continue
                act = json.loads(ln)
                if "add" in act:
                    a = act["add"]
                    adds[a["path"]] = a
                    removes.pop(a["path"], None)
                elif "remove" in act:
                    r = act["remove"]
                    removes[r["path"]] = r
                    ever_removed.add(r["path"])
                    adds.pop(r["path"], None)
                elif "metaData" in act:
                    meta_changed = True
    rewritten = ever_removed & set(adds)
    return adds, set(removes), meta_changed, removes, rewritten

"""Minimal clean-room Avro Object Container File writer/reader.

Implements exactly the subset the Iceberg manifest format needs (the
Avro 1.11 spec's binary encoding): null/boolean/int/long/float/double/
bytes/string primitives, records, unions, arrays, maps, and the OCF
framing (magic, metadata map, sync-marked blocks, null codec).

Schemas are plain dicts in Avro JSON form; extra keys (like Iceberg's
`field-id`) pass through into the embedded schema JSON, which is how
Iceberg attaches its ids.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, Dict, Iterable, List

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# binary encoding
# ---------------------------------------------------------------------------


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: io.BytesIO, n: int) -> None:
    z = _zigzag(n) & 0xFFFFFFFFFFFFFFFF
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            break


def read_long(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return _unzigzag(acc)


def write_bytes(buf: io.BytesIO, data: bytes) -> None:
    write_long(buf, len(data))
    buf.write(data)


def read_bytes(buf: io.BytesIO) -> bytes:
    n = read_long(buf)
    return buf.read(n)


def _resolve(schema):
    if isinstance(schema, str):
        return {"type": schema}
    return schema


def encode(buf: io.BytesIO, schema, value) -> None:
    if isinstance(schema, list):  # union
        for i, branch in enumerate(schema):
            bt = _resolve(branch)["type"] if not isinstance(branch, list) else None
            if value is None and bt == "null":
                write_long(buf, i)
                return
            if value is not None and bt != "null":
                write_long(buf, i)
                encode(buf, branch, value)
                return
        raise ValueError(f"value {value!r} matches no union branch {schema}")
    s = _resolve(schema)
    t = s["type"]
    if t == "null":
        return
    if t == "boolean":
        buf.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        write_long(buf, int(value))
    elif t == "float":
        buf.write(struct.pack("<f", float(value)))
    elif t == "double":
        buf.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        write_bytes(buf, bytes(value))
    elif t == "string":
        write_bytes(buf, value.encode("utf-8"))
    elif t == "record":
        for f in s["fields"]:
            fv = value.get(f["name"]) if isinstance(value, dict) else getattr(value, f["name"])
            encode(buf, f["type"], fv)
    elif t == "array":
        items = list(value or [])
        if items:
            write_long(buf, len(items))
            for it in items:
                encode(buf, s["items"], it)
        write_long(buf, 0)
    elif t == "map":
        entries = dict(value or {})
        if entries:
            write_long(buf, len(entries))
            for k, v in entries.items():
                write_bytes(buf, k.encode("utf-8"))
                encode(buf, s["values"], v)
        write_long(buf, 0)
    elif t == "fixed":
        assert len(value) == s["size"]
        buf.write(bytes(value))
    else:
        raise ValueError(f"unsupported avro type {t}")


def decode(buf: io.BytesIO, schema):
    if isinstance(schema, list):
        idx = read_long(buf)
        return decode(buf, schema[idx])
    s = _resolve(schema)
    t = s["type"]
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return read_bytes(buf)
    if t == "string":
        return read_bytes(buf).decode("utf-8")
    if t == "record":
        return {f["name"]: decode(buf, f["type"]) for f in s["fields"]}
    if t == "array":
        out = []
        while True:
            n = read_long(buf)
            if n == 0:
                break
            if n < 0:
                read_long(buf)  # block byte size
                n = -n
            for _ in range(n):
                out.append(decode(buf, s["items"]))
        return out
    if t == "map":
        out = {}
        while True:
            n = read_long(buf)
            if n == 0:
                break
            if n < 0:
                read_long(buf)
                n = -n
            for _ in range(n):
                k = read_bytes(buf).decode("utf-8")
                out[k] = decode(buf, s["values"])
        return out
    if t == "fixed":
        return buf.read(s["size"])
    raise ValueError(f"unsupported avro type {t}")


# ---------------------------------------------------------------------------
# object container files
# ---------------------------------------------------------------------------


def write_ocf(
    schema: Dict,
    records: Iterable[Dict],
    metadata: Dict[str, str] | None = None,
) -> bytes:
    buf = io.BytesIO()
    buf.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema), "avro.codec": "null"}
    meta.update(metadata or {})
    write_long(buf, len(meta))
    for k, v in meta.items():
        write_bytes(buf, k.encode())
        write_bytes(buf, v.encode() if isinstance(v, str) else v)
    write_long(buf, 0)
    sync = os.urandom(16)
    buf.write(sync)

    records = list(records)
    if records:
        block = io.BytesIO()
        for r in records:
            encode(block, schema, r)
        data = block.getvalue()
        write_long(buf, len(records))
        write_long(buf, len(data))
        buf.write(data)
        buf.write(sync)
    return buf.getvalue()


def read_ocf(data: bytes) -> tuple[Dict, List[Dict], Dict[str, bytes]]:
    buf = io.BytesIO(data)
    assert buf.read(4) == MAGIC, "not an avro object container file"
    meta: Dict[str, bytes] = {}
    while True:
        n = read_long(buf)
        if n == 0:
            break
        if n < 0:
            read_long(buf)
            n = -n
        for _ in range(n):
            k = read_bytes(buf).decode()
            meta[k] = read_bytes(buf)
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null")
    assert codec in (b"null", "null"), f"unsupported codec {codec}"
    sync = buf.read(16)
    records = []
    while True:
        try:
            count = read_long(buf)
        except EOFError:
            break
        size = read_long(buf)
        block = io.BytesIO(buf.read(size))
        for _ in range(count):
            records.append(decode(block, schema))
        assert buf.read(16) == sync, "sync marker mismatch"
    return schema, records, meta

"""Host (numpy-over-Arrow) expression evaluation with SQL 3-valued logic.

Null semantics: comparisons involving NULL yield NULL; AND/OR use Kleene
logic; IsNull/IsNotNull produce definite booleans. Boolean results are
returned as a pair encoded in a masked float — we use numpy object-free
representation: (value: np.ndarray, valid: np.ndarray[bool]).

Public entry `evaluate_host` returns, for predicates, a numpy bool array
where NULL results are False (SQL WHERE semantics).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.expressions.tree import (
    And,
    Column,
    Comparison,
    Expression,
    In,
    IsNotNull,
    IsNull,
    Literal,
    Not,
    Or,
    StartsWith,
)


def _resolve_column(batch: pa.Table, name_path: Tuple[str, ...]) -> pa.ChunkedArray:
    if name_path[0] not in batch.column_names:
        raise KeyError(f"column {'.'.join(name_path)} not in batch")
    arr = batch.column(name_path[0])
    for part in name_path[1:]:
        arr = pc.struct_field(arr, part)
    return arr


def _eval(expr: Expression, batch: pa.Table):
    """Returns a pyarrow Array/ChunkedArray (nullable) for any expression."""
    n = batch.num_rows
    if isinstance(expr, Column):
        return _resolve_column(batch, expr.name_path)
    if isinstance(expr, Literal):
        return pa.chunked_array([pa.array([expr.value] * n)])
    if isinstance(expr, Comparison):
        left = _eval(expr.left, batch)
        right = _eval(expr.right, batch)
        op = {
            "=": pc.equal,
            "!=": pc.not_equal,
            "<": pc.less,
            "<=": pc.less_equal,
            ">": pc.greater,
            ">=": pc.greater_equal,
        }[expr.op]
        return op(left, right)
    if isinstance(expr, And):
        return pc.and_kleene(_eval(expr.left, batch), _eval(expr.right, batch))
    if isinstance(expr, Or):
        return pc.or_kleene(_eval(expr.left, batch), _eval(expr.right, batch))
    if isinstance(expr, Not):
        return pc.invert(_eval(expr.child, batch))
    if isinstance(expr, IsNull):
        return pc.is_null(_eval(expr.child, batch))
    if isinstance(expr, IsNotNull):
        return pc.is_valid(_eval(expr.child, batch))
    if isinstance(expr, In):
        child = _eval(expr.child, batch)
        return pc.is_in(child, value_set=pa.array(list(expr.values)))
    if isinstance(expr, StartsWith):
        return pc.starts_with(_eval(expr.child, batch), pattern=expr.prefix)
    from delta_tpu.errors import InvalidArgumentError

    raise InvalidArgumentError(
        f"cannot evaluate {expr!r}",
        error_class="DELTA_CANNOT_EVALUATE_EXPRESSION")


def evaluate_host(expr: Expression, batch: pa.Table):
    return _eval(expr, batch)


def evaluate_predicate_host(expr: Expression, batch: pa.Table) -> np.ndarray:
    """Boolean selection with NULL -> False (WHERE semantics)."""
    result = _eval(expr, batch)
    if isinstance(result, pa.ChunkedArray):
        result = result.combine_chunks()
    filled = pc.fill_null(result, False)
    return np.asarray(filled, dtype=np.bool_)

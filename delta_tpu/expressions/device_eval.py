"""Device (JAX) expression evaluation over columnar batches.

Numeric comparisons/boolean algebra run as jitted elementwise kernels —
XLA fuses an entire predicate tree into one pass over the columns (this is
what the TpuEngine uses for data-skipping over the stats index and for
partition pruning on dictionary-encoded partition columns). Anything
non-numeric (strings, decimals, maps) falls back to the host evaluator —
strings reach the device only as dictionary codes, never as bytes.

Null handling: each column is carried as (values, valid) pair; Kleene
logic propagates validity exactly like the host evaluator.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.engine.spi import ExpressionHandler
from delta_tpu.expressions.tree import (
    And,
    Column,
    Comparison,
    Expression,
    In,
    IsNotNull,
    IsNull,
    Literal,
    Not,
    Or,
)

_NUMERIC_KINDS = ("i", "u", "f", "b")


def _batch_to_device_columns(batch: pa.Table) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    cols = {}
    for name in batch.column_names:
        arr = batch.column(name).combine_chunks()
        if pa.types.is_integer(arr.type) or pa.types.is_floating(arr.type) or pa.types.is_boolean(arr.type):
            valid = np.asarray(pc.is_valid(arr), dtype=bool)
            values = np.asarray(pc.fill_null(arr, 0))
            if values.dtype == np.int64:
                # avoid x64 traps on TPU: split not needed for comparisons
                # that fit int32; keep float64->float32 would lose precision,
                # so keep i64/f64 on host numpy and only ship when safe
                if np.all(np.abs(values) < 2**31):
                    values = values.astype(np.int32)
            if values.dtype == np.float64:
                values = values.astype(np.float32)
            cols[name] = (values, valid)
        elif pa.types.is_date32(arr.type):
            valid = np.asarray(pc.is_valid(arr), dtype=bool)
            values = np.asarray(arr.cast(pa.int32()).fill_null(0))
            cols[name] = (values, valid)
    return cols


class _HostFallback(Exception):
    pass


def _eval_device(expr: Expression, cols) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (bool values, valid) arrays."""
    if isinstance(expr, Column):
        name = ".".join(expr.name_path)
        if name not in cols:
            raise _HostFallback(name)
        return cols[name]
    if isinstance(expr, Literal):
        if not isinstance(expr.value, (int, float, bool, np.number)) or expr.value is None:
            raise _HostFallback(repr(expr))
        return (jnp.asarray(expr.value), jnp.asarray(True))
    if isinstance(expr, Comparison):
        lv, lval = _eval_device(expr.left, cols)
        rv, rval = _eval_device(expr.right, cols)
        op = {
            "=": jnp.equal,
            "!=": jnp.not_equal,
            "<": jnp.less,
            "<=": jnp.less_equal,
            ">": jnp.greater,
            ">=": jnp.greater_equal,
        }[expr.op]
        return op(lv, rv), jnp.logical_and(lval, rval)
    if isinstance(expr, And):
        lv, lval = _eval_device(expr.left, cols)
        rv, rval = _eval_device(expr.right, cols)
        # Kleene: false wins over null
        value = jnp.logical_and(lv, rv)
        valid = (lval & rval) | (lval & ~lv) | (rval & ~rv)
        return value, valid
    if isinstance(expr, Or):
        lv, lval = _eval_device(expr.left, cols)
        rv, rval = _eval_device(expr.right, cols)
        value = jnp.logical_or(lv, rv)
        valid = (lval & rval) | (lval & lv) | (rval & rv)
        return value, valid
    if isinstance(expr, Not):
        v, val = _eval_device(expr.child, cols)
        return jnp.logical_not(v), val
    if isinstance(expr, IsNull):
        _, val = _eval_device(expr.child, cols)
        return jnp.logical_not(val), jnp.ones_like(val, dtype=bool)
    if isinstance(expr, IsNotNull):
        _, val = _eval_device(expr.child, cols)
        return val, jnp.ones_like(val, dtype=bool)
    if isinstance(expr, In):
        cv, cval = _eval_device(expr.child, cols)
        acc = jnp.zeros_like(cv, dtype=bool)
        for v in expr.values:
            if not isinstance(v, (int, float, bool, np.number)):
                raise _HostFallback(repr(expr))
            acc = acc | (cv == v)
        return acc, cval
    raise _HostFallback(repr(expr))


class DeviceExpressionHandler(ExpressionHandler):
    def evaluate(self, expr, batch: pa.Table):
        from delta_tpu.expressions.eval import evaluate_host

        return evaluate_host(expr, batch)

    def evaluate_predicate(self, expr, batch: pa.Table) -> np.ndarray:
        cols = _batch_to_device_columns(batch)
        try:
            value, valid = jax.jit(
                functools.partial(_eval_device, expr)
            )(cols)
            # WHERE semantics: NULL -> False
            return np.asarray(value & valid)
        except _HostFallback:
            from delta_tpu.expressions.eval import evaluate_predicate_host

            return evaluate_predicate_host(expr, batch)

"""Minimal SQL-ish predicate/expression parser.

Persisted expressions (CHECK constraints in `delta.constraints.*`,
generated-column expressions in field metadata) need a stable textual
form. This parser covers the subset the reference's constraint/
generated-column machinery uses in practice:

    a.b = 5, x > 'abc', flag, NOT deleted, id IS NOT NULL,
    c IN (1, 2, 3), (a = 1 AND b = 2) OR c < 3.0

Grammar (precedence low→high): OR, AND, NOT, comparison / IS NULL / IN,
atom (literal, column, parenthesized). Strings use single quotes with
'' escaping. TRUE/FALSE/NULL literals. Arithmetic is intentionally not
supported (neither host nor device eval implements it yet) — fail loud
at parse time rather than mis-evaluate.
"""

from __future__ import annotations

import re
from typing import List, Optional

from delta_tpu.expressions.tree import (
    Column,
    Comparison,
    Expression,
    In,
    IsNotNull,
    IsNull,
    Literal,
    Not,
    And,
    Or,
)

_TOKEN_RE = re.compile(
    r"""
    \s*(
        (?P<string>'(?:[^']|'')*') |
        (?P<number>-?\d+\.\d+([eE][+-]?\d+)?|-?\d+) |
        (?P<op><=|>=|!=|<>|=|<|>) |
        (?P<lparen>\() |
        (?P<rparen>\)) |
        (?P<comma>,) |
        (?P<ident>[A-Za-z_][A-Za-z0-9_]*(\.[A-Za-z_][A-Za-z0-9_]*)*) |
        (?P<backtick>`[^`]+`(\.`[^`]+`)*)
    )
    """,
    re.VERBOSE,
)

_KEYWORDS = {"AND", "OR", "NOT", "IS", "NULL", "IN", "TRUE", "FALSE"}


class ParseError(ValueError):
    error_class = "DELTA_FAILED_RECOGNIZE_PREDICATE"


def _tokenize(s: str) -> List[tuple]:
    out = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if m is None or m.end() == pos:
            if s[pos:].strip() == "":
                break
            raise ParseError(f"cannot tokenize {s[pos:]!r}")
        pos = m.end()
        if m.group("string") is not None:
            out.append(("str", m.group("string")[1:-1].replace("''", "'")))
        elif m.group("number") is not None:
            text = m.group("number")
            out.append(("num", float(text) if ("." in text or "e" in text.lower()) else int(text)))
        elif m.group("op") is not None:
            op = m.group("op")
            out.append(("op", "!=" if op == "<>" else op))
        elif m.group("lparen"):
            out.append(("(", "("))
        elif m.group("rparen"):
            out.append((")", ")"))
        elif m.group("comma"):
            out.append((",", ","))
        elif m.group("backtick") is not None:
            parts = [p.strip("`") for p in m.group("backtick").split("`.`")]
            out.append(("col", tuple(parts)))
        else:
            ident = m.group("ident")
            if ident.upper() in _KEYWORDS and "." not in ident:
                out.append(("kw", ident.upper()))
            else:
                out.append(("col", tuple(ident.split("."))))
    return out


class _Parser:
    def __init__(self, tokens: List[tuple]):
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[tuple]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> tuple:
        t = self.peek()
        if t is None:
            raise ParseError("unexpected end of expression")
        self.pos += 1
        return t

    def expect(self, kind: str, value=None) -> tuple:
        t = self.next()
        if t[0] != kind or (value is not None and t[1] != value):
            raise ParseError(f"expected {value or kind}, got {t}")
        return t

    def parse(self) -> Expression:
        e = self.parse_or()
        if self.peek() is not None:
            raise ParseError(f"trailing tokens: {self.tokens[self.pos:]}")
        return e

    def parse_or(self) -> Expression:
        left = self.parse_and()
        while (t := self.peek()) and t == ("kw", "OR"):
            self.next()
            left = Or(left, self.parse_and())
        return left

    def parse_and(self) -> Expression:
        left = self.parse_not()
        while (t := self.peek()) and t == ("kw", "AND"):
            self.next()
            left = And(left, self.parse_not())
        return left

    def parse_not(self) -> Expression:
        if (t := self.peek()) and t == ("kw", "NOT"):
            self.next()
            return Not(self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> Expression:
        left = self.parse_atom()
        t = self.peek()
        if t is None:
            return left
        if t[0] == "op":
            op = self.next()[1]
            right = self.parse_atom()
            return Comparison(op, left, right)
        if t == ("kw", "IS"):
            self.next()
            if self.peek() == ("kw", "NOT"):
                self.next()
                self.expect("kw", "NULL")
                return IsNotNull(left)
            self.expect("kw", "NULL")
            return IsNull(left)
        if t == ("kw", "IN"):
            self.next()
            self.expect("(")
            values = []
            while True:
                v = self.parse_atom()
                if not isinstance(v, Literal):
                    raise ParseError("IN list must contain literals")
                values.append(v.value)
                nxt = self.next()
                if nxt[0] == ")":
                    break
                if nxt[0] != ",":
                    raise ParseError(f"expected , or ) in IN list, got {nxt}")
            return In(left, tuple(values))
        return left

    def parse_atom(self) -> Expression:
        t = self.next()
        if t[0] == "(":
            e = self.parse_or()
            self.expect(")")
            return e
        if t[0] == "str":
            return Literal(t[1])
        if t[0] == "num":
            return Literal(t[1])
        if t[0] == "kw":
            if t[1] == "TRUE":
                return Literal(True)
            if t[1] == "FALSE":
                return Literal(False)
            if t[1] == "NULL":
                return Literal(None)
            raise ParseError(f"unexpected keyword {t[1]}")
        if t[0] == "col":
            return Column(t[1])
        raise ParseError(f"unexpected token {t}")


def parse_expression(s: str) -> Expression:
    return _Parser(_tokenize(s)).parse()


def to_sql(expr: Expression) -> str:
    """Serialize an expression back to the parseable textual form."""
    if isinstance(expr, Column):
        return ".".join(
            f"`{p}`" if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", p) else p
            for p in expr.name_path
        )
    if isinstance(expr, Literal):
        v = expr.value
        if v is None:
            return "NULL"
        if isinstance(v, bool):
            return "TRUE" if v else "FALSE"
        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        return repr(v)
    if isinstance(expr, Comparison):
        return f"{to_sql(expr.left)} {expr.op} {to_sql(expr.right)}"
    if isinstance(expr, And):
        return f"({to_sql(expr.left)} AND {to_sql(expr.right)})"
    if isinstance(expr, Or):
        return f"({to_sql(expr.left)} OR {to_sql(expr.right)})"
    if isinstance(expr, Not):
        return f"NOT ({to_sql(expr.child)})"
    if isinstance(expr, IsNull):
        return f"{to_sql(expr.child)} IS NULL"
    if isinstance(expr, IsNotNull):
        return f"{to_sql(expr.child)} IS NOT NULL"
    if isinstance(expr, In):
        vals = ", ".join(to_sql(Literal(v)) for v in expr.values)
        return f"{to_sql(expr.child)} IN ({vals})"
    raise ValueError(f"cannot serialize {expr!r}")

"""Expression tree for scan filters, partition pruning, and data skipping.

A deliberately small language — the same scope as the kernel's
`expressions/` package (Column/Literal/And/Or/Predicate/ScalarExpression):
enough to express partition predicates and min/max skipping, not a general
SQL engine. Evaluation backends: `eval.py` (host, numpy over Arrow) and
`device_eval.py` (jitted, over the columnar stats index).

Expressions are built with `col()` / `lit()` and operators:

    (col("date") >= lit("2024-01-01")) & col("country").is_in("US", "CA")
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple


class Expression:
    def __and__(self, other: "Expression") -> "Expression":
        return And(self, _as_expr(other))

    def __or__(self, other: "Expression") -> "Expression":
        return Or(self, _as_expr(other))

    def __invert__(self) -> "Expression":
        return Not(self)

    def _cmp(self, op: str, other) -> "Expression":
        return Comparison(op, self, _as_expr(other))

    def __eq__(self, other):  # type: ignore[override]
        return self._cmp("=", other)

    def __ne__(self, other):  # type: ignore[override]
        return self._cmp("!=", other)

    def __lt__(self, other):
        return self._cmp("<", other)

    def __le__(self, other):
        return self._cmp("<=", other)

    def __gt__(self, other):
        return self._cmp(">", other)

    def __ge__(self, other):
        return self._cmp(">=", other)

    def __hash__(self):
        return hash(repr(self))

    def is_null(self) -> "Expression":
        return IsNull(self)

    def is_not_null(self) -> "Expression":
        return IsNotNull(self)

    def is_in(self, *values) -> "Expression":
        return In(self, tuple(values))

    def starts_with(self, prefix: str) -> "Expression":
        return StartsWith(self, prefix)

    def references(self) -> set:
        """Set of column name-paths (tuples) referenced."""
        out = set()
        for child in self.children():
            out |= child.references()
        return out

    def children(self) -> Tuple["Expression", ...]:
        return ()


def _as_expr(v) -> Expression:
    return v if isinstance(v, Expression) else Literal(v)


@dataclass(frozen=True, eq=False)
class Column(Expression):
    """A (possibly nested) column reference; `name_path` is a tuple of
    field names, e.g. ("user", "id")."""

    name_path: Tuple[str, ...]

    def references(self) -> set:
        return {self.name_path}

    def __repr__(self):
        return f"col({'.'.join(self.name_path)})"


@dataclass(frozen=True, eq=False)
class Literal(Expression):
    value: Any

    def __repr__(self):
        return f"lit({self.value!r})"


@dataclass(frozen=True, eq=False)
class Comparison(Expression):
    op: str  # one of = != < <= > >=
    left: Expression
    right: Expression

    VALID_OPS = ("=", "!=", "<", "<=", ">", ">=")

    def __post_init__(self):
        assert self.op in self.VALID_OPS, self.op

    def children(self):
        return (self.left, self.right)

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


@dataclass(frozen=True, eq=False)
class And(Expression):
    left: Expression
    right: Expression

    def children(self):
        return (self.left, self.right)

    def __repr__(self):
        return f"({self.left!r} AND {self.right!r})"


@dataclass(frozen=True, eq=False)
class Or(Expression):
    left: Expression
    right: Expression

    def children(self):
        return (self.left, self.right)

    def __repr__(self):
        return f"({self.left!r} OR {self.right!r})"


@dataclass(frozen=True, eq=False)
class Not(Expression):
    child: Expression

    def children(self):
        return (self.child,)

    def __repr__(self):
        return f"(NOT {self.child!r})"


@dataclass(frozen=True, eq=False)
class IsNull(Expression):
    child: Expression

    def children(self):
        return (self.child,)


@dataclass(frozen=True, eq=False)
class IsNotNull(Expression):
    child: Expression

    def children(self):
        return (self.child,)


@dataclass(frozen=True, eq=False)
class In(Expression):
    child: Expression
    values: Tuple[Any, ...]

    def children(self):
        return (self.child,)


@dataclass(frozen=True, eq=False)
class StartsWith(Expression):
    child: Expression
    prefix: str

    def children(self):
        return (self.child,)


def col(name: str) -> Column:
    """`col("a.b")` references nested field b of struct a."""
    return Column(tuple(name.split(".")))


def lit(value) -> Literal:
    return Literal(value)


def split_conjuncts(expr: Expression) -> list:
    """Flatten nested ANDs into a conjunct list (used by pruning to apply
    each conjunct independently)."""
    if isinstance(expr, And):
        return split_conjuncts(expr.left) + split_conjuncts(expr.right)
    return [expr]

"""Generated / identity / default column value generation on write.

Reference `GeneratedColumn.scala` / `IdentityColumn.scala` /
`GenerateIdentityValues.scala` / `ColumnWithDefaultExprUtils.scala`:

- generated columns: field metadata `delta.generationExpression`
  (parseable predicate/expression text). Missing on write → computed;
  present → validated against the expression.
- identity columns: field metadata `delta.identity.start` / `.step` /
  `.highWaterMark` / `.allowExplicitInsert`. Missing on write → values
  allocated from the high watermark (which advances in the SAME commit
  via a schema-metadata update); present → rejected unless
  allowExplicitInsert.
- default columns (`allowColumnDefaults` writer feature): field metadata
  `CURRENT_DEFAULT` holds an expression; a column missing from the
  written data is filled with its evaluated default instead of null.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from delta_tpu.errors import DeltaError, IdentityColumnError, InvariantViolationError
from delta_tpu.models.schema import StructField, StructType, to_arrow_type

GENERATION_EXPRESSION_KEY = "delta.generationExpression"
IDENTITY_START_KEY = "delta.identity.start"
IDENTITY_STEP_KEY = "delta.identity.step"
IDENTITY_HIGH_WATERMARK_KEY = "delta.identity.highWaterMark"
IDENTITY_ALLOW_EXPLICIT_KEY = "delta.identity.allowExplicitInsert"
CURRENT_DEFAULT_KEY = "CURRENT_DEFAULT"

GENERATION_KEYS = (GENERATION_EXPRESSION_KEY, IDENTITY_START_KEY,
                   IDENTITY_STEP_KEY, CURRENT_DEFAULT_KEY)


def needs_column_generation(schema: StructType) -> bool:
    return any(
        any(k in f.metadata for k in GENERATION_KEYS) for f in schema.fields
    )


def identity_field(
    name: str, start: int = 1, step: int = 1, allow_explicit_insert: bool = False
) -> StructField:
    """Helper to declare an identity column in a new table's schema."""
    from delta_tpu.models.schema import LONG

    if step == 0:
        raise IdentityColumnError("identity step must not be 0",
                                  error_class="DELTA_IDENTITY_COLUMNS_ILLEGAL_STEP")
    return StructField(
        name,
        LONG,
        nullable=True,
        metadata={
            IDENTITY_START_KEY: start,
            IDENTITY_STEP_KEY: step,
            IDENTITY_ALLOW_EXPLICIT_KEY: allow_explicit_insert,
        },
    )


def generated_field(name: str, dtype, expression: str) -> StructField:
    from delta_tpu.expressions.parser import parse_expression

    parse_expression(expression)  # validate early
    return StructField(name, dtype, metadata={GENERATION_EXPRESSION_KEY: expression})


def default_field(name: str, dtype, default: str,
                  nullable: bool = True) -> StructField:
    """Declare a column with a DEFAULT expression (requires the
    `allowColumnDefaults` writer feature; enforced at commit)."""
    from delta_tpu.expressions.parser import parse_expression

    parse_expression(default)  # validate early
    return StructField(name, dtype, nullable=nullable,
                       metadata={CURRENT_DEFAULT_KEY: default})


def apply_column_generation(
    data: pa.Table, schema: StructType
) -> Tuple[pa.Table, Optional[StructType]]:
    """Fill generated/identity columns. Returns (new data, updated schema
    or None when no watermark moved)."""
    from delta_tpu.expressions.eval import evaluate_host
    from delta_tpu.expressions.parser import parse_expression

    new_schema_fields = list(schema.fields)
    schema_changed = False
    n = data.num_rows

    for i, f in enumerate(schema.fields):
        gen_expr = f.metadata.get(GENERATION_EXPRESSION_KEY)
        is_identity = IDENTITY_START_KEY in f.metadata or IDENTITY_STEP_KEY in f.metadata
        default_expr = f.metadata.get(CURRENT_DEFAULT_KEY)

        if (default_expr is not None and gen_expr is None and not is_identity
                and f.name not in data.column_names):
            expr = parse_expression(default_expr)
            computed = evaluate_host(expr, data)
            if isinstance(computed, pa.ChunkedArray):
                computed = computed.combine_chunks()
            if isinstance(computed, pa.Scalar) or not isinstance(
                    computed, (pa.Array, pa.ChunkedArray)):
                computed = pa.array(
                    [computed.as_py() if isinstance(computed, pa.Scalar)
                     else computed] * n)
            computed = computed.cast(to_arrow_type(f.dataType), safe=False)
            data = data.append_column(f.name, computed)
            continue

        if gen_expr is not None:
            expr = parse_expression(gen_expr)
            computed = evaluate_host(expr, data)
            if isinstance(computed, pa.ChunkedArray):
                computed = computed.combine_chunks()
            computed = computed.cast(to_arrow_type(f.dataType), safe=False)
            if f.name in data.column_names:
                actual = data.column(f.name).combine_chunks()
                import pyarrow.compute as pc

                mismatch = pc.sum(
                    pc.cast(
                        pc.fill_null(pc.not_equal(actual, computed), True),
                        pa.int64(),
                    )
                ).as_py()
                if mismatch:
                    raise InvariantViolationError(
                        error_class="DELTA_GENERATED_COLUMNS_EXPR_TYPE_MISMATCH",
                        message=f"{mismatch} row(s) violate generation expression of "
                        f"column {f.name}: {gen_expr}"
                    )
            else:
                data = data.append_column(f.name, computed)
            continue

        if is_identity:
            step = int(f.metadata.get(IDENTITY_STEP_KEY, 1))
            start = int(f.metadata.get(IDENTITY_START_KEY, 1))
            allow_explicit = bool(f.metadata.get(IDENTITY_ALLOW_EXPLICIT_KEY, False))
            if f.name in data.column_names:
                if not allow_explicit:
                    raise IdentityColumnError(
                        error_class="DELTA_IDENTITY_COLUMNS_EXPLICIT_INSERT_NOT_SUPPORTED",
                        message=f"explicit values for identity column {f.name} are "
                        "not allowed (allowExplicitInsert=false)"
                    )
                continue
            if n == 0:
                continue
            watermark = f.metadata.get(IDENTITY_HIGH_WATERMARK_KEY)
            first = start if watermark is None else int(watermark) + step
            values = first + step * np.arange(n, dtype=np.int64)
            data = data.append_column(f.name, pa.array(values, pa.int64()))
            md = dict(f.metadata)
            md[IDENTITY_HIGH_WATERMARK_KEY] = int(values[-1]) if step > 0 else int(values.min())
            new_schema_fields[i] = StructField(f.name, f.dataType, f.nullable, md)
            schema_changed = True

    return data, (StructType(new_schema_fields) if schema_changed else None)


def validate_generated_schema(schema: StructType,
                              partition_columns=()) -> None:
    """Schema-level generation/identity invariants, checked when table
    metadata is (re)committed (`IdentityColumn.scala` /
    `GeneratedColumn.scala` declaration-time validations)."""
    from delta_tpu.models.schema import INTEGER, LONG

    names = {f.name for f in schema.fields}
    pcols = set(partition_columns or ())
    for f in schema.fields:
        is_identity = IDENTITY_START_KEY in f.metadata \
            or IDENTITY_STEP_KEY in f.metadata
        gen_expr = f.metadata.get(GENERATION_EXPRESSION_KEY)
        if is_identity and gen_expr is not None:
            raise IdentityColumnError(
                f"identity column {f.name} cannot also have a "
                "generation expression",
                error_class=(
                    "DELTA_IDENTITY_COLUMNS_WITH_GENERATED_EXPRESSION"))
        if is_identity and f.name in pcols:
            raise IdentityColumnError(
                f"identity column {f.name} cannot be a partition "
                "column (PARTITIONED BY is not supported for identity "
                "columns)",
                error_class="DELTA_IDENTITY_COLUMNS_PARTITION_NOT_SUPPORTED")
        if is_identity and f.dataType not in (LONG, INTEGER):
            raise IdentityColumnError(
                f"identity column {f.name} must be BIGINT or INT, got "
                f"{f.dataType.to_json_value()}",
                error_class="DELTA_IDENTITY_COLUMNS_UNSUPPORTED_DATA_TYPE")
        if gen_expr is not None:
            from delta_tpu.expressions.parser import parse_expression

            try:
                refs = {r[0] for r in
                        parse_expression(gen_expr).references()}
            except Exception as e:
                # an unparseable expression must fail at DECLARATION,
                # not on the first write
                # (`DeltaErrors.unsupportedExpression` for generated
                # columns)
                raise InvariantViolationError(
                    f"generation expression of {f.name} cannot be "
                    f"parsed: {gen_expr!r} ({e})",
                    error_class=(
                        "DELTA_UNSUPPORTED_EXPRESSION_GENERATED_COLUMN"))
            generated = {
                g.name for g in schema.fields
                if GENERATION_EXPRESSION_KEY in g.metadata
                or IDENTITY_START_KEY in g.metadata
                or IDENTITY_STEP_KEY in g.metadata}
            bad = sorted((refs - names) | (refs & generated))
            if bad:
                # missing columns AND other generated/identity columns
                # are both invalid references (computation order over
                # generated inputs is undefined)
                raise InvariantViolationError(
                    f"generation expression of {f.name} references "
                    f"non-existent or generated column(s) {bad}",
                    error_class="DELTA_INVALID_GENERATED_COLUMN_REFERENCES")


def _ref_overlaps(ref: str, column: str) -> bool:
    """A dotted reference depends on `column` when either is a prefix
    path of the other: referencing `s.x` depends on both `s.x` and
    `s`; referencing `s` depends on every field under `s`."""
    return (ref == column or ref.startswith(column + ".")
            or column.startswith(ref + "."))


def generated_dependents(schema: StructType, column: str):
    """Names of generated columns whose expression references
    `column` — possibly a dotted nested path — (dependency guard for
    DROP/RENAME COLUMN)."""
    from delta_tpu.expressions.parser import ParseError, parse_expression

    out = []
    for f in schema.fields:
        expr = f.metadata.get(GENERATION_EXPRESSION_KEY)
        if expr is None:
            continue
        try:
            refs = {".".join(r) for r in
                    parse_expression(expr).references()}
        except ParseError:
            continue
        if any(_ref_overlaps(r, column) for r in refs):
            out.append(f.name)
    return out

"""Schema evolution: merge-on-write, type widening.

Reference `schema/SchemaMergingUtils.scala` + `TypeWidening.scala`:
- `merge_schemas(current, incoming)`: incoming may ADD nullable columns
  (appended in order) and, when widening is allowed, widen primitive
  types along safe chains; anything else is a SchemaMismatch.
- widening chains (`TypeWideningMode`): byte→short→int→long,
  float→double, int→long→double(+decimal), date→timestamp_ntz.
"""

from __future__ import annotations

from typing import Optional

from delta_tpu.errors import SchemaMismatchError
from delta_tpu.models.schema import (
    ArrayType,
    DataType,
    MapType,
    PrimitiveType,
    StructField,
    StructType,
)

_WIDEN = {
    ("byte", "short"), ("byte", "integer"), ("byte", "long"),
    ("short", "integer"), ("short", "long"),
    ("integer", "long"),
    ("float", "double"),
    ("byte", "double"), ("short", "double"), ("integer", "double"),
    ("date", "timestamp_ntz"),
}


def can_widen(from_t: DataType, to_t: DataType) -> bool:
    if not isinstance(from_t, PrimitiveType) or not isinstance(to_t, PrimitiveType):
        return False
    if from_t.is_decimal or to_t.is_decimal:
        if from_t.is_decimal and to_t.is_decimal:
            p1, s1 = from_t.decimal_precision_scale()
            p2, s2 = to_t.decimal_precision_scale()
            return s2 >= s1 and (p2 - s2) >= (p1 - s1) and (p1, s1) != (p2, s2)
        return False
    return (from_t.name, to_t.name) in _WIDEN


def merge_types(
    current: DataType, incoming: DataType, allow_widening: bool, path: str
) -> DataType:
    if current == incoming:
        return current
    if isinstance(current, StructType) and isinstance(incoming, StructType):
        return merge_schemas(current, incoming, allow_widening, prefix=path + ".")
    if isinstance(current, ArrayType) and isinstance(incoming, ArrayType):
        return ArrayType(
            merge_types(current.elementType, incoming.elementType, allow_widening,
                        path + ".element"),
            current.containsNull or incoming.containsNull,
        )
    if isinstance(current, MapType) and isinstance(incoming, MapType):
        return MapType(
            merge_types(current.keyType, incoming.keyType, allow_widening, path + ".key"),
            merge_types(current.valueType, incoming.valueType, allow_widening,
                        path + ".value"),
            current.valueContainsNull or incoming.valueContainsNull,
        )
    if allow_widening and can_widen(current, incoming):
        return incoming
    if can_widen(incoming, current):
        return current  # incoming is narrower: fits without change
    raise SchemaMismatchError(
        error_class="DELTA_FAILED_TO_MERGE_FIELDS",
        message=f"cannot merge types at {path or '<root>'}: "
        f"{current.to_json_value()} vs {incoming.to_json_value()}"
    )


def merge_schemas(
    current: StructType,
    incoming: StructType,
    allow_widening: bool = False,
    prefix: str = "",
) -> StructType:
    """Evolved schema accepting `incoming` data. New incoming fields are
    appended as nullable."""
    by_name = {f.name.lower(): f for f in incoming.fields}
    out = []
    for f in current.fields:
        inc = by_name.pop(f.name.lower(), None)
        if inc is None:
            out.append(f)
            continue
        merged_type = merge_types(
            f.dataType, inc.dataType, allow_widening, prefix + f.name
        )
        out.append(StructField(f.name, merged_type, f.nullable, dict(f.metadata)))
    for f in incoming.fields:
        if f.name.lower() in by_name:  # genuinely new
            out.append(StructField(f.name, f.dataType, True, dict(f.metadata)))
    return StructType(out)


def is_read_compatible(table_schema: StructType, read_schema: StructType) -> bool:
    """Can data written with table_schema be read as read_schema (missing
    columns become nulls)?"""
    try:
        merge_schemas(read_schema, table_schema)
        return True
    except SchemaMismatchError:
        return False

"""Per-version `.crc` checksum files.

Reference `Checksum.scala`: after each commit, a `%020d.crc` JSON document
records the post-commit table state summary (tableSizeBytes, numFiles,
protocol, metadata, ...). Readers use it to (a) get P&M + counts without
replay, (b) validate a reconstructed snapshot (`ValidateChecksum`).

Derivation here is incremental (`incrementallyDeriveChecksum:155`): new
checksum = previous checksum + this commit's actions — no replay. When
the previous `.crc` is missing or the commit lacks the information to
derive sizes exactly (e.g. removes without size), we fall back to writing
nothing; the next checkpointed snapshot can seed a fresh chain via
`write_checksum_from_state`.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

from delta_tpu.errors import ChecksumMismatchError
from delta_tpu.models.actions import Metadata, Protocol
from delta_tpu.utils import filenames


@dataclass
class VersionChecksum:
    tableSizeBytes: int
    numFiles: int
    numMetadata: int
    numProtocol: int
    metadata: Metadata
    protocol: Protocol
    txnId: Optional[str] = None
    inCommitTimestamp: Optional[int] = None
    numDeletedRecordsOpt: Optional[int] = None
    numDeletionVectorsOpt: Optional[int] = None

    def to_json(self) -> str:
        d = {
            "tableSizeBytes": self.tableSizeBytes,
            "numFiles": self.numFiles,
            "numMetadata": self.numMetadata,
            "numProtocol": self.numProtocol,
            "metadata": self.metadata.to_dict(),
            "protocol": self.protocol.to_dict(),
        }
        if self.txnId is not None:
            d["txnId"] = self.txnId
        if self.inCommitTimestamp is not None:
            d["inCommitTimestampOpt"] = self.inCommitTimestamp
        if self.numDeletedRecordsOpt is not None:
            d["numDeletedRecordsOpt"] = self.numDeletedRecordsOpt
        if self.numDeletionVectorsOpt is not None:
            d["numDeletionVectorsOpt"] = self.numDeletionVectorsOpt
        return json.dumps(d, separators=(",", ":"))

    @staticmethod
    def from_json(data) -> "VersionChecksum":
        d = json.loads(data)
        return VersionChecksum(
            tableSizeBytes=int(d["tableSizeBytes"]),
            numFiles=int(d["numFiles"]),
            numMetadata=int(d.get("numMetadata", 1)),
            numProtocol=int(d.get("numProtocol", 1)),
            metadata=Metadata.from_dict(d["metadata"]),
            protocol=Protocol.from_dict(d["protocol"]),
            txnId=d.get("txnId"),
            inCommitTimestamp=d.get("inCommitTimestampOpt"),
            numDeletedRecordsOpt=d.get("numDeletedRecordsOpt"),
            numDeletionVectorsOpt=d.get("numDeletionVectorsOpt"),
        )


def read_checksum(fs, log_path: str, version: int) -> Optional[VersionChecksum]:
    try:
        return VersionChecksum.from_json(
            fs.read_file(filenames.checksum_file(log_path, version))
        )
    except (FileNotFoundError, ValueError, KeyError):
        return None


def write_checksum_from_state(engine, log_path: str, state) -> None:
    ci = state.commit_infos.get(state.version)
    crc = VersionChecksum(
        tableSizeBytes=state.size_in_bytes,
        numFiles=state.num_files,
        numMetadata=1,
        numProtocol=1,
        metadata=state.metadata,
        protocol=state.protocol,
        inCommitTimestamp=(ci.inCommitTimestamp if ci is not None else None),
    )
    engine.json.write_json_file_atomically(
        filenames.checksum_file(log_path, state.version),
        crc.to_json().encode(),
        overwrite=True,
    )


def write_checksum_for_commit(table, txn, version: int) -> None:
    """Incremental derivation from the previous version's checksum and the
    transaction's staged actions. No-op when the chain is broken."""
    engine = table.engine
    log_path = table.log_path
    if version == 0:
        prev_size, prev_files = 0, 0
    else:
        prev = read_checksum(engine.fs, log_path, version - 1)
        if prev is None:
            return
        prev_size, prev_files = prev.tableSizeBytes, prev.numFiles

    adds = txn._adds
    removes = txn._removes
    if any(r.size is None for r in removes):
        return  # can't derive exactly
    # NOTE: exact derivation also requires that adds don't replace existing
    # live files with the same (path, dv) key. DML commands re-add with the
    # same path only after removing it in the same commit, which cancels
    # out below; blind double-adds break the chain, which validation will
    # catch and drop.
    new_size = prev_size + sum(a.size for a in adds) - sum(r.size for r in removes)
    new_files = prev_files + len(adds) - len(removes)
    if new_files < 0 or new_size < 0:
        return

    meta = txn.metadata()
    proto = txn.protocol()
    crc = VersionChecksum(
        tableSizeBytes=new_size,
        numFiles=new_files,
        numMetadata=1,
        numProtocol=1,
        metadata=meta,
        protocol=proto,
        txnId=txn.txn_id,
        inCommitTimestamp=getattr(txn, "_committed_ict", None),
    )
    engine.json.write_json_file_atomically(
        filenames.checksum_file(log_path, version), crc.to_json().encode(), overwrite=True
    )


def validate_state_against_checksum(state, crc: VersionChecksum) -> None:
    """`ValidateChecksum` semantics: replayed state must match the stored
    summary exactly."""
    problems = []
    if state.num_files != crc.numFiles:
        problems.append(f"numFiles {state.num_files} != crc {crc.numFiles}")
    if state.size_in_bytes != crc.tableSizeBytes:
        problems.append(
            f"tableSizeBytes {state.size_in_bytes} != crc {crc.tableSizeBytes}"
        )
    if state.protocol.to_dict() != crc.protocol.to_dict():
        problems.append("protocol mismatch")
    if state.metadata.id != crc.metadata.id:
        problems.append("metadata id mismatch")
    if problems:
        raise ChecksumMismatchError("; ".join(problems),
                                    error_class="DELTA_TXN_LOG_FAILED_INTEGRITY")

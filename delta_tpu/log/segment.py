"""LogSegment: the minimal set of log files that reproduces a version.

Construction semantics follow the reference (spark
`SnapshotManagement.scala:329,461`; kernel
`internal/snapshot/SnapshotManager.java:311`):

1. LIST `_delta_log` from the last-known checkpoint version (hint) —
   lexicographic listing == version order thanks to zero padding.
2. Partition the listing into commit files, checkpoint files, compacted
   deltas; drop everything after the target version.
3. Pick the newest *complete* checkpoint at or below the target version.
4. Keep commit files with `checkpoint_version < v <= target`; verify they
   are contiguous and reach the target (a gap means a corrupt/raced
   listing).
5. Prefer compacted delta files covering whole sub-ranges when allowed
   (fewer files to parse; PROTOCOL.md:270).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from delta_tpu import obs
from delta_tpu.errors import DeltaError, TableNotFoundError, VersionNotFoundError
from delta_tpu.storage.logstore import FileStatus
from delta_tpu.utils import filenames
from delta_tpu.utils.filenames import CheckpointInstance, group_complete_checkpoints

_HINT_DISCARDED = obs.counter("log.hint_discarded")


@dataclass
class LogSegment:
    log_path: str
    version: int
    deltas: List[FileStatus] = field(default_factory=list)       # ascending version
    checkpoints: List[FileStatus] = field(default_factory=list)  # parts of ONE checkpoint
    compacted_deltas: List[FileStatus] = field(default_factory=list)  # chosen replacements
    checkpoint_version: Optional[int] = None
    last_commit_timestamp: int = 0

    @property
    def delta_versions(self) -> List[int]:
        return [filenames.delta_version(f.path) for f in self.deltas]

    def commit_files_descending(self) -> List[FileStatus]:
        return list(reversed(self.deltas))


class CorruptLogError(DeltaError):
    error_class = "DELTA_CORRUPT_LOG"


class _IncrementalUnavailable(Exception):
    """The log can't be advanced incrementally from the given segment —
    a checkpoint/compaction landed past it, or the listing has a gap
    (concurrent log cleanup). The caller falls back to a full load;
    this is a control-flow signal, never a user-facing error."""


def extend_log_segment(fs, prev: LogSegment):
    """LIST only log files with version > `prev.version` and extend the
    segment with the new commits — the incremental half of snapshot
    maintenance (`SnapshotManagement.getUpdatedLogSegment`).

    Returns None when there is nothing new (the common poll outcome —
    one directory listing, zero reads/parses), or
    `(new_segment, new_deltas)` where `new_deltas` are just the appended
    commit FileStatus entries.

    Raises _IncrementalUnavailable when a checkpoint or compacted delta
    newer than `prev.version` appeared (the canonical segment for the
    new version starts from that checkpoint — rebuilding keeps segments
    identical to what a cold load would produce), or when the new
    commit versions aren't contiguous with `prev` (log cleanup raced
    the listing).
    """
    with obs.span("log.list_incremental", log_path=prev.log_path,
                  from_version=prev.version) as sp:
        ext = _extend_log_segment(fs, prev)
        if ext is not None:
            sp.set_attrs(to_version=ext[0].version, new_commits=len(ext[1]))
        return ext


def _extend_log_segment(fs, prev: LogSegment):
    start = prev.version + 1
    prefix = filenames.listing_prefix(prev.log_path, start)
    # same stat-skipping policy as build_log_segment: commit entries
    # keep (size=-1, mtime=0), so the parsed-commit cache keys of an
    # incremental load match a later full listing's keys exactly
    fast = getattr(fs, "list_from_fast", None)
    try:
        if fast is not None:
            listing = list(fast(
                prefix, lambda n: filenames.DELTA_FILE_RE.match(n)
                is not None))
        else:
            listing = list(fs.list_from(prefix))
    except FileNotFoundError:
        raise TableNotFoundError(f"no _delta_log at {prev.log_path}",
                                 error_class="DELTA_EMPTY_DIRECTORY")

    new_deltas: List[tuple] = []
    delta_match = filenames.DELTA_FILE_RE.match
    for fstat in listing:
        name = filenames.file_name(fstat.path)
        if delta_match(name):
            v = int(name.split(".", 1)[0])
            if v >= start:
                new_deltas.append((v, fstat))
        elif filenames.CHECKPOINT_FILE_RE.match(name) and fstat.size > 0:
            ci = CheckpointInstance.parse(fstat.path)
            if ci is not None and ci.version > prev.version:
                raise _IncrementalUnavailable(
                    f"checkpoint appeared at version {ci.version}")
        elif filenames.COMPACTED_DELTA_FILE_RE.match(name):
            _, hi = filenames.compacted_delta_versions(fstat.path)
            if hi > prev.version:
                raise _IncrementalUnavailable(
                    f"compacted delta appeared covering up to {hi}")
    if not new_deltas:
        return None
    new_deltas.sort(key=lambda t: t[0])
    versions = [v for v, _ in new_deltas]
    if versions != list(range(start, versions[-1] + 1)):
        raise _IncrementalUnavailable(
            f"non-contiguous new commits {versions[:5]}..., expected "
            f"[{start}, {versions[-1]}]")

    files = [f for _, f in new_deltas]
    last_ts = max(prev.last_commit_timestamp,
                  max(f.modification_time for f in files))
    if files[-1].modification_time == 0:
        # stat-deferred listing: the newest commit's mtime is the
        # snapshot timestamp — fetch just that one
        try:
            last_ts = max(last_ts,
                          fs.file_status(files[-1].path).modification_time)
        except FileNotFoundError:
            pass

    import dataclasses

    seg = dataclasses.replace(
        prev,
        version=versions[-1],
        deltas=list(prev.deltas) + files,
        last_commit_timestamp=last_ts,
    )
    return seg, files


def _verify_deltas_contiguous(versions: List[int], expected_start: int, target: int) -> None:
    if versions != list(range(expected_start, target + 1)):
        raise CorruptLogError(
            error_class="DELTA_TRUNCATED_TRANSACTION_LOG",
            message=f"Log is missing commit files: have versions {versions[:5]}..., "
            f"expected contiguous [{expected_start}, {target}]"
        )


def _apply_compaction(
    deltas: List[FileStatus], compacted: List[FileStatus], start: int, target: int
) -> tuple[List[FileStatus], List[FileStatus]]:
    """Greedily substitute compacted-delta files for runs of single-commit
    files inside [start, target]. Returns (kept singles, chosen compacted).
    Mirrors the listing-time substitution in `SnapshotManagement.scala:329`.
    """
    if not compacted:
        return deltas, []
    by_version = {filenames.delta_version(f.path): f for f in deltas}
    chosen: List[FileStatus] = []
    covered: set[int] = set()
    # Prefer widest ranges first.
    ranges = sorted(
        ((filenames.compacted_delta_versions(f.path), f) for f in compacted),
        key=lambda t: (t[0][0], -(t[0][1] - t[0][0])),
    )
    for (lo, hi), f in ranges:
        if lo < start or hi > target:
            continue
        rng = set(range(lo, hi + 1))
        if rng & covered:
            continue
        if not rng <= set(by_version):
            # compaction may cover commits we no longer list; only usable
            # when every covered single exists in-window or is irrelevant
            if not rng <= (set(by_version) | covered):
                continue
        chosen.append(f)
        covered |= rng
    singles = [f for v, f in sorted(by_version.items()) if v not in covered]
    return singles, chosen


def build_log_segment(
    fs,
    log_path: str,
    target_version: Optional[int] = None,
    checkpoint_hint: Optional[int] = None,
    use_compacted_deltas: bool = True,
    max_checkpoint_version: Optional[int] = None,
) -> LogSegment:
    """LIST the log and assemble the segment for `target_version` (or the
    latest version when None).

    `max_checkpoint_version` caps which checkpoints may anchor the
    segment (corruption fallback: a reader that failed to consume the
    checkpoint at version V rebuilds with `max_checkpoint_version=V - 1`
    to replay from the previous complete checkpoint, or from the JSON
    commits alone when none remains)."""
    with obs.span("log.list_segment", log_path=log_path) as sp:
        try:
            seg = _build_log_segment(fs, log_path, target_version,
                                     checkpoint_hint, use_compacted_deltas,
                                     max_checkpoint_version)
        except CorruptLogError:
            if checkpoint_hint is None:
                raise
            # the hint is only an accelerator: a window that can't be
            # assembled from it (e.g. the hinted checkpoint lost a part)
            # may still assemble from a full listing anchored earlier
            _HINT_DISCARDED.inc()
            sp.set_attr("hint_discarded", True)
            seg = _build_log_segment(fs, log_path, target_version,
                                     None, use_compacted_deltas,
                                     max_checkpoint_version)
        sp.set_attrs(version=seg.version, num_deltas=len(seg.deltas),
                     num_checkpoint_parts=len(seg.checkpoints),
                     num_compacted=len(seg.compacted_deltas))
        return seg


def _build_log_segment(
    fs,
    log_path: str,
    target_version: Optional[int],
    checkpoint_hint: Optional[int],
    use_compacted_deltas: bool,
    max_checkpoint_version: Optional[int] = None,
) -> LogSegment:
    start = checkpoint_hint if checkpoint_hint is not None else 0
    prefix = filenames.listing_prefix(log_path, start)
    # commit files skip the per-entry stat (their sizes come from the
    # reader; only the segment's LAST commit needs an mtime, stat'd
    # below) — checkpoint/compacted files still stat (size>0 checks)
    fast = getattr(fs, "list_from_fast", None)
    try:
        if fast is not None:
            listing = list(fast(
                prefix, lambda n: filenames.DELTA_FILE_RE.match(n)
                is not None))
        else:
            listing = list(fs.list_from(prefix))
    except FileNotFoundError:
        raise TableNotFoundError(f"no _delta_log at {log_path}",
                                 error_class="DELTA_EMPTY_DIRECTORY")

    # (version, fstat) pairs: each name is parsed exactly once — at 100k
    # commits the repeated delta_version() calls below were measurable
    deltas: List[tuple] = []
    checkpoint_files: List[CheckpointInstance] = []
    compacted: List[FileStatus] = []
    delta_match = filenames.DELTA_FILE_RE.match
    for fstat in listing:
        name = filenames.file_name(fstat.path)
        if delta_match(name):
            v = int(name.split(".", 1)[0])
            if target_version is None or v <= target_version:
                deltas.append((v, fstat))
        elif filenames.CHECKPOINT_FILE_RE.match(name) and fstat.size > 0:
            ci = CheckpointInstance.parse(fstat.path)
            if (ci is not None
                    and (target_version is None
                         or ci.version <= target_version)
                    and (max_checkpoint_version is None
                         or ci.version <= max_checkpoint_version)):
                checkpoint_files.append(ci)
        elif filenames.COMPACTED_DELTA_FILE_RE.match(name):
            lo, hi = filenames.compacted_delta_versions(fstat.path)
            if target_version is None or hi <= target_version:
                compacted.append(fstat)

    if not deltas and not checkpoint_files:
        if checkpoint_hint is not None and checkpoint_hint > 0:
            # stale hint (log may have been cleaned differently) — retry full
            return build_log_segment(
                fs, log_path, target_version, checkpoint_hint=None,
                use_compacted_deltas=use_compacted_deltas,
                max_checkpoint_version=max_checkpoint_version,
            )
        raise TableNotFoundError(f"no commits found in {log_path}",
                                 error_class="DELTA_NO_COMMITS_FOUND")

    complete = group_complete_checkpoints(checkpoint_files)
    chosen_checkpoint: List[CheckpointInstance] = complete[-1] if complete else []
    cp_version = chosen_checkpoint[0].version if chosen_checkpoint else None

    window_start = (cp_version + 1) if cp_version is not None else 0
    deltas_in_window = [(v, f) for v, f in deltas if v >= window_start]
    versions = [v for v, _ in deltas_in_window]

    if target_version is None:
        if versions:
            version = versions[-1]
        elif cp_version is not None:
            version = cp_version
        else:
            raise TableNotFoundError(f"no commits found in {log_path}")
    else:
        version = target_version
        have_max = versions[-1] if versions else cp_version
        if have_max is None or have_max < target_version:
            raise VersionNotFoundError(
                version=target_version,
                earliest=versions[0] if versions else cp_version,
                latest=have_max,
            )

    deltas_needed = [f for v, f in deltas_in_window if v <= version]
    needed_versions = [v for v, _ in deltas_in_window if v <= version]
    if needed_versions:
        _verify_deltas_contiguous(needed_versions, window_start, version)
    elif cp_version is None:
        raise VersionNotFoundError(version=version, earliest=None, latest=None)
    elif cp_version != version:
        raise CorruptLogError(
            f"checkpoint at {cp_version} but no commits up to requested {version}"
        )

    chosen_compacted: List[FileStatus] = []
    if use_compacted_deltas and compacted:
        deltas_needed, chosen_compacted = _apply_compaction(
            deltas_needed, compacted, window_start, version
        )

    checkpoint_statuses = []
    for ci in chosen_checkpoint:
        try:
            checkpoint_statuses.append(
                next(
                    fstat
                    for fstat in listing
                    if fstat.path == ci.path
                )
            )
        except StopIteration:  # pragma: no cover - listing produced it
            pass

    last_ts = 0
    if deltas_needed:
        for f in deltas_needed:
            last_ts = max(last_ts, f.modification_time)
        if deltas_needed[-1].modification_time == 0:
            # fast listing deferred the stat; the last commit's mtime is
            # the snapshot timestamp, so fetch just that one (through the
            # fs abstraction — a non-local store may defer too)
            try:
                last_ts = max(
                    last_ts,
                    fs.file_status(deltas_needed[-1].path)
                    .modification_time)
            except FileNotFoundError:
                pass
    else:
        # checkpoint-at-head: the snapshot's timestamp is the LAST
        # COMMIT's (the checkpoint parquet is written after it and its
        # mtime would overshoot — history/time-travel use commit mtimes)
        cp_commit = next(
            (f for v, f in deltas if v == version), None)
        if cp_commit is not None:
            ts = cp_commit.modification_time
            if ts == 0:
                try:
                    ts = fs.file_status(cp_commit.path).modification_time
                except FileNotFoundError:
                    ts = 0
            last_ts = ts
        if last_ts == 0:
            for f in checkpoint_statuses:
                last_ts = max(last_ts, f.modification_time)

    return LogSegment(
        log_path=log_path,
        version=version,
        deltas=deltas_needed,
        checkpoints=checkpoint_statuses,
        compacted_deltas=chosen_compacted,
        checkpoint_version=cp_version,
        last_commit_timestamp=last_ts,
    )

"""Checkpoint writing (classic single-file, multi-part, V2+sidecars).

Reference: spark `Checkpoints.scala:616` writeCheckpoint, kernel
`CreateCheckpointIterator` → `ParquetHandler.writeParquetFileAtomically`.

A checkpoint materializes the reconciled state at a version as Parquet in
the SingleAction layout: struct columns `protocol`, `metaData`, `txn`,
`domainMetadata`, `add`, `remove` — one non-null per row. Contents:
- 1 protocol + 1 metaData row,
- one `txn` row per appId, one `domainMetadata` row per domain
  (including removal tombstones),
- every live `add` (dataChange=false),
- every `remove` tombstone younger than the retention window
  (`delta.deletedFileRetentionDuration`), dataChange=false.

The add/remove struct columns are assembled directly from the snapshot's
canonical columnar state — no per-row object hop. Finishes by pointing
`_last_checkpoint` at the new checkpoint.

Multi-artifact checkpoints (multipart parts, V2 sidecars) go through
`delta_tpu.write.ckpt_pipeline`: per-artifact serialize and upload are
split so encode(part i+1) overlaps upload(part i) on remote stores,
and any failure settles the in-flight tail, deletes every artifact
this attempt created, bumps `checkpoint.aborted_writes`, and re-raises
WITHOUT advancing `_last_checkpoint` — a torn multipart write can
never become the active checkpoint.

Incremental checkpoints: each file-action part is content-fingerprinted
(`_part_fp`) and the fingerprints ride the `_last_checkpoint` hint as
`partManifest`. The next write reuses fingerprint-matched parts —
byte-copied under the new filename for multipart (old parts are
cleanup-eligible once shadowed), re-referenced in place for V2
sidecars (log cleanup never deletes `_sidecars/`). Append-only
workloads rewrite only the tail part.
"""

from __future__ import annotations

import hashlib
import json
import time
import uuid
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.config import (
    CHECKPOINT_POLICY,
    TOMBSTONE_RETENTION,
    get_table_config,
    settings,
)
from delta_tpu import obs
from delta_tpu.errors import ChecksumMismatchError, InvalidArgumentError
from delta_tpu.log.last_checkpoint import LastCheckpointInfo, write_last_checkpoint
from delta_tpu.models.actions import Sidecar
from delta_tpu.replay.columnar import DV_STRUCT_TYPE
from delta_tpu.utils import filenames
from delta_tpu.write import ckpt_pipeline

_BYTES_WRITTEN = obs.counter("checkpoint.bytes_written")
_PARTS_WRITTEN = obs.counter("checkpoint.parts_written")
_PARTS_REUSED = obs.counter("checkpoint.parts_reused")
_ABORTED_WRITES = obs.counter("checkpoint.aborted_writes")

PV_MAP = pa.map_(pa.string(), pa.string())

ADD_STRUCT = pa.struct(
    [
        pa.field("path", pa.string()),
        pa.field("partitionValues", PV_MAP),
        pa.field("size", pa.int64()),
        pa.field("modificationTime", pa.int64()),
        pa.field("dataChange", pa.bool_()),
        pa.field("stats", pa.string()),
        pa.field("deletionVector", DV_STRUCT_TYPE),
        pa.field("baseRowId", pa.int64()),
        pa.field("defaultRowCommitVersion", pa.int64()),
        pa.field("clusteringProvider", pa.string()),
    ]
)

REMOVE_STRUCT = pa.struct(
    [
        pa.field("path", pa.string()),
        pa.field("deletionTimestamp", pa.int64()),
        pa.field("dataChange", pa.bool_()),
        pa.field("extendedFileMetadata", pa.bool_()),
        pa.field("partitionValues", PV_MAP),
        pa.field("size", pa.int64()),
        pa.field("deletionVector", DV_STRUCT_TYPE),
        pa.field("baseRowId", pa.int64()),
        pa.field("defaultRowCommitVersion", pa.int64()),
    ]
)

PROTOCOL_STRUCT = pa.struct(
    [
        pa.field("minReaderVersion", pa.int32()),
        pa.field("minWriterVersion", pa.int32()),
        pa.field("readerFeatures", pa.list_(pa.string())),
        pa.field("writerFeatures", pa.list_(pa.string())),
    ]
)

METADATA_STRUCT = pa.struct(
    [
        pa.field("id", pa.string()),
        pa.field("name", pa.string()),
        pa.field("description", pa.string()),
        pa.field(
            "format",
            pa.struct(
                [pa.field("provider", pa.string()), pa.field("options", PV_MAP)]
            ),
        ),
        pa.field("schemaString", pa.string()),
        pa.field("partitionColumns", pa.list_(pa.string())),
        pa.field("configuration", PV_MAP),
        pa.field("createdTime", pa.int64()),
    ]
)

TXN_STRUCT = pa.struct(
    [
        pa.field("appId", pa.string()),
        pa.field("version", pa.int64()),
        pa.field("lastUpdated", pa.int64()),
    ]
)

DOMAIN_STRUCT = pa.struct(
    [
        pa.field("domain", pa.string()),
        pa.field("configuration", pa.string()),
        pa.field("removed", pa.bool_()),
    ]
)


def _stats_parsed_schema(schema, configuration,
                         partition_columns) -> Optional[pa.Schema]:
    """Explicit arrow schema for stats_parsed, typed per the TABLE
    schema (external struct-form readers expect e.g. timestamp mins as
    timestamps, not inferred strings): numRecords int64, minValues /
    maxValues as nested structs of the indexed leaves' arrow types,
    nullCount as int64 per leaf."""
    from delta_tpu.models.schema import PrimitiveType, StructType, to_arrow_type
    from delta_tpu.stats.collection import stats_columns

    if schema is None:
        return None

    def resolve(path):
        node = schema
        for name in path[:-1]:
            if not isinstance(node, StructType) or name not in node:
                return None
            node = node[name].dataType
        if not isinstance(node, StructType) or path[-1] not in node:
            return None
        return node[path[-1]].dataType

    minmax_tree: dict = {}
    null_tree: dict = {}

    def insert(tree, path, typ):
        for p in path[:-1]:
            tree = tree.setdefault(p, {})
        tree[path[-1]] = typ

    for path in stats_columns(schema, configuration, partition_columns):
        dt = resolve(path)
        if not isinstance(dt, PrimitiveType):
            continue
        try:
            arrow_t = to_arrow_type(dt)
        except (ValueError, InvalidArgumentError):
            continue  # unmappable type: no stats column for it
        insert(null_tree, path, pa.int64())
        if dt.name != "binary":
            insert(minmax_tree, path, arrow_t)

    def to_struct(tree) -> pa.DataType:
        return pa.struct([
            pa.field(k, to_struct(v) if isinstance(v, dict) else v)
            for k, v in tree.items()
        ])

    fields = [pa.field("numRecords", pa.int64())]
    if minmax_tree:
        fields.append(pa.field("minValues", to_struct(minmax_tree)))
        fields.append(pa.field("maxValues", to_struct(minmax_tree)))
    if null_tree:
        fields.append(pa.field("nullCount", to_struct(null_tree)))
    # DV-capable writers mark whether min/max reflect the post-delete
    # rows; without this field in the explicit schema a struct-only
    # checkpoint round-trip would silently drop it
    fields.append(pa.field("tightBounds", pa.bool_()))
    return pa.schema(fields)


def _stats_ndjson_buffer(stats_col: pa.Array) -> Optional[pa.Buffer]:
    """The stats strings as one newline-delimited buffer, built with
    Arrow kernels (no per-row Python objects — this runs at
    checkpoint-write scale)."""
    import pyarrow.compute as _pc

    filled = _pc.fill_null(stats_col, "{}")
    # append "\n" per row: the LAST argument is the separator, so join
    # (value, "") with separator "\n" — value + "\n" + ""
    with_nl = _pc.binary_join_element_wise(filled, pa.scalar(""),
                                           pa.scalar("\n"))
    arr = (with_nl.combine_chunks()
           if isinstance(with_nl, pa.ChunkedArray) else with_nl)
    if arr.offset != 0:
        arr = pa.concat_arrays([arr])  # re-materialize at offset 0
    offsets_buf = arr.buffers()[1]
    width = 8 if pa.types.is_large_string(arr.type) else 4
    dtype = np.int64 if width == 8 else np.int32
    offsets = np.frombuffer(offsets_buf, dtype=dtype, count=len(arr) + 1)
    total = int(offsets[-1])
    return arr.buffers()[2].slice(0, total)


def _parse_stats_structs(
    stats_col: pa.Array, explicit_schema: Optional[pa.Schema] = None
) -> Optional[pa.Array]:
    """Parse per-file stats JSON strings into a struct array, typed by
    `explicit_schema` when given (falling back to inference if the
    explicit parse fails — e.g. 'NaN' strings in double stats). Null
    stats become empty objects (all-null fields). None when nothing
    parses."""
    import pyarrow.json as pa_json

    if stats_col.null_count == len(stats_col):
        return None
    buf = _stats_ndjson_buffer(stats_col)
    if buf is None:
        return None
    parsed = None
    if explicit_schema is not None:
        try:
            parsed = pa_json.read_json(
                pa.BufferReader(buf),
                parse_options=pa_json.ParseOptions(
                    explicit_schema=explicit_schema,
                    unexpected_field_behavior="ignore"))
        except (pa.ArrowException, ValueError, OSError):
            parsed = None  # schema mismatch: retry with inference below
    if parsed is None:
        try:
            parsed = pa_json.read_json(pa.BufferReader(buf))
        except (pa.ArrowException, ValueError, OSError):
            return None  # malformed stats: skip the struct form entirely
    if parsed.num_rows != len(stats_col):
        return None
    return parsed.to_struct_array().combine_chunks()


def _file_struct_from_canonical(
    tbl: pa.Table,
    is_add: bool,
    stats_as_json: bool = True,
    stats_as_struct: bool = False,
    stats_schema: Optional[pa.Schema] = None,
) -> pa.Array:
    """Canonical columnar rows → add/remove StructArray. Stats shaping
    per `delta.checkpoint.writeStatsAsJson` / `writeStatsAsStruct`
    (`Checkpoints.scala` buildCheckpoint)."""
    n = tbl.num_rows
    false_col = pa.array(np.zeros(n, dtype=bool))

    def col(name):
        return tbl.column(name).combine_chunks()

    if is_add:
        stats = col("stats")
        fields = list(ADD_STRUCT)
        children = [
            col("path"),
            col("partition_values"),
            col("size"),
            col("modification_time"),
            false_col,  # dataChange normalized to false in checkpoints
            stats if stats_as_json else pa.nulls(n, pa.string()),
            col("deletion_vector"),
            col("base_row_id"),
            col("default_row_commit_version"),
            col("clustering_provider"),
        ]
        if stats_as_struct:
            parsed = _parse_stats_structs(stats, stats_schema)
            if parsed is not None:
                children.append(parsed)
                fields = fields + [pa.field("stats_parsed", parsed.type)]
        return pa.StructArray.from_arrays(children, fields=fields)
    children = [
        col("path"),
        col("deletion_timestamp"),
        false_col,
        col("extended_file_metadata"),
        col("partition_values"),
        col("size"),
        col("deletion_vector"),
        col("base_row_id"),
        col("default_row_commit_version"),
    ]
    return pa.StructArray.from_arrays(children, fields=list(REMOVE_STRUCT))


def _single_action_table(
    n: int,
    protocol_rows: Optional[pa.Array] = None,
    metadata_rows: Optional[pa.Array] = None,
    txn_rows: Optional[pa.Array] = None,
    domain_rows: Optional[pa.Array] = None,
    add_rows: Optional[pa.Array] = None,
    remove_rows: Optional[pa.Array] = None,
) -> pa.Table:
    """Assemble a SingleAction table: each input occupies its own row
    range; all other columns null there."""
    blocks = [
        ("protocol", PROTOCOL_STRUCT, protocol_rows),
        ("metaData", METADATA_STRUCT, metadata_rows),
        ("txn", TXN_STRUCT, txn_rows),
        ("domainMetadata", DOMAIN_STRUCT, domain_rows),
        ("add", ADD_STRUCT, add_rows),
        ("remove", REMOVE_STRUCT, remove_rows),
    ]
    sizes = [len(b[2]) if b[2] is not None else 0 for b in blocks]
    total = sum(sizes)
    assert total == n, (total, n)
    # chunked columns, not concat_arrays: the null spans and the payload
    # arrays become chunks as-is, so a million-file checkpoint table is
    # assembled without copying a single struct row
    cols = {}
    offset = 0
    offsets = []
    for (name, typ, arr), sz in zip(blocks, sizes):
        offsets.append(offset)
        offset += sz
    for i, (name, typ, arr) in enumerate(blocks):
        sz = sizes[i]
        # honor the payload's actual type when present — the add struct
        # may carry an extra stats_parsed field beyond the static schema
        if arr is not None and sz:
            typ = arr.type
        before, after = offsets[i], n - offsets[i] - sz
        chunks = []
        if before:
            chunks.append(pa.nulls(before, typ))
        if arr is not None and sz:
            chunks.append(arr)
        if after:
            chunks.append(pa.nulls(after, typ))
        cols[name] = (pa.chunked_array(chunks, type=typ) if chunks
                      else pa.chunked_array([], type=typ))
    return pa.table(cols)


def _small_action_arrays(state, txn_min_last_updated: Optional[int] = None) -> tuple:
    proto = state.protocol
    protocol_rows = pa.array(
        [
            {
                "minReaderVersion": proto.minReaderVersion,
                "minWriterVersion": proto.minWriterVersion,
                "readerFeatures": (
                    sorted(proto.readerFeatures) if proto.readerFeatures is not None else None
                ),
                "writerFeatures": (
                    sorted(proto.writerFeatures) if proto.writerFeatures is not None else None
                ),
            }
        ],
        PROTOCOL_STRUCT,
    )
    meta = state.metadata
    metadata_rows = pa.array(
        [
            {
                "id": meta.id,
                "name": meta.name,
                "description": meta.description,
                "format": {"provider": meta.format.provider, "options": list(meta.format.options.items())},
                "schemaString": meta.schemaString,
                "partitionColumns": list(meta.partitionColumns),
                "configuration": list(meta.configuration.items()),
                "createdTime": meta.createdTime,
            }
        ],
        METADATA_STRUCT,
    )
    txns = list(state.set_transactions.values())
    if txn_min_last_updated is not None:
        # expire idle SetTransaction entries from the checkpoint
        # (`InMemoryLogReplay.scala:84-91`: lastUpdated.exists(_ > min) —
        # entries without a timestamp are dropped once retention is on)
        txns = [t for t in txns
                if t.lastUpdated is not None
                and t.lastUpdated >= txn_min_last_updated]
    txn_rows = (
        pa.array(
            [
                {"appId": t.appId, "version": t.version, "lastUpdated": t.lastUpdated}
                for t in txns
            ],
            TXN_STRUCT,
        )
        if txns
        else None
    )
    domain_rows = (
        pa.array(
            [
                {"domain": d.domain, "configuration": d.configuration, "removed": d.removed}
                for d in state.domain_metadata.values()
            ],
            DOMAIN_STRUCT,
        )
        if state.domain_metadata
        else None
    )
    return protocol_rows, metadata_rows, txn_rows, domain_rows


def _retained_tombstones(state, now_ms: int, retention_ms: int) -> pa.Table:
    tombs = state.tombstones_table
    if tombs.num_rows == 0:
        return tombs
    min_retain = now_ms - retention_ms
    del_ts = pc.fill_null(tombs.column("deletion_timestamp"), 0)
    keep = pc.greater_equal(del_ts, pa.scalar(min_retain, pa.int64()))
    return tombs.filter(keep)


def _partition_codes(state, adds: pa.Table) -> tuple:
    """Dictionary-code each add row's partition-value tuple.
    Unpartitioned tables (the common case) take the zero-work
    single-code path; partitioned tables code the tuples on host — the
    expensive per-part distinct-count then reduces with the other
    lanes in the one batched dispatch."""
    n = adds.num_rows
    if not list(state.metadata.partitionColumns or []):
        return np.zeros(n, np.int64), 1
    codebook: dict = {}
    codes = np.empty(n, np.int64)
    for i, kv in enumerate(adds.column("partition_values").to_pylist()):
        key = tuple(kv) if kv is not None else ()
        codes[i] = codebook.setdefault(key, len(codebook))
    return codes, max(len(codebook), 1)


def _checkpoint_aggregates(engine, state, adds: pa.Table, plan) -> None:
    """Stats summary for the checkpoint being written: per-part
    min/max/sum/null-count over the add lanes (file size, modification
    time, DV cardinality) plus distinct partition values. On an engine
    with an accelerator backend (`device_stats_enabled`) the whole
    stage is ONE batched device dispatch returning one dense D2H block
    (`ops/stats.py`, budgeted in transfer_budget.json), colocated with
    the resident replay state's device when one exists; otherwise the
    bit-identical host twin runs. The block feeds the
    `checkpoint.aggregate` span — it is deliberately NOT part of the
    reuse fingerprint, so stat-mode flips can never change checkpoint
    bytes."""
    from delta_tpu.ops import stats as ckstats

    n = adds.num_rows
    n_parts = len(plan)
    with obs.span("checkpoint.aggregate", rows=n, parts=n_parts) as sp:

        def lane(col) -> tuple:
            arr = (col.combine_chunks()
                   if isinstance(col, pa.ChunkedArray) else col)
            vals = pc.fill_null(arr, 0).to_numpy(
                zero_copy_only=False).astype(np.int64, copy=False)
            ok = pc.is_valid(arr).to_numpy(zero_copy_only=False)
            return vals, ok

        size_v, size_ok = lane(adds.column("size"))
        mt_v, mt_ok = lane(adds.column("modification_time"))
        dv_v, dv_ok = lane(pc.struct_field(
            adds.column("deletion_vector").combine_chunks(), "cardinality"))
        codes, n_codes = _partition_codes(state, adds)
        lanes = [size_v, mt_v, dv_v, codes]
        valids = [size_ok, mt_ok, dv_ok, np.ones(n, bool)]
        part_of = np.zeros(n, np.int32)
        for i, (a0, a1, _r0, _r1) in enumerate(plan):
            part_of[a0:a1] = i
        mode = "host"
        if ckstats.device_stats_enabled(engine):
            resident = getattr(state, "resident", None)
            hint = resident.device_hint() if resident is not None else None
            try:
                block = ckstats.checkpoint_stats_block(
                    lanes, valids, part_of, n_parts, n_codes, device=hint)
                mode = "device"
            # delta-lint: disable=except-swallow (audited: the aggregate
            # block is telemetry riding the checkpoint write — a device
            # dispatch failure must degrade to the bit-identical host
            # twin, never abort the checkpoint)
            except Exception:
                block = ckstats.host_stats_block(
                    lanes, valids, part_of, n_parts, n_codes)
        else:
            block = ckstats.host_stats_block(
                lanes, valids, part_of, n_parts, n_codes)
        n_l = len(lanes)
        sp.set_attrs(
            stats_mode=mode,
            logical_bytes=int(block[2 * n_l].sum()),
            dv_cardinality=int(block[2 * n_l + 2].sum()),
            distinct_partition_values=int(block[4 * n_l].max(initial=0)),
        )


def write_checkpoint(engine, snapshot, policy: Optional[str] = None,
                     prev_info: Optional[LastCheckpointInfo] = None,
                     ) -> LastCheckpointInfo:
    """Write a checkpoint for `snapshot` and update `_last_checkpoint`.

    `prev_info` is the previous `_last_checkpoint` hint; when it carries
    a `partManifest` from an identically-configured writer, unchanged
    parts/sidecars are reused instead of re-serialized."""
    with obs.span("checkpoint.write", log_path=snapshot._table.log_path,
                  version=snapshot.version) as sp:
        info = _write_checkpoint(engine, snapshot, policy, prev_info)
        sp.set_attrs(actions=info.size, num_add_files=info.numOfAddFiles,
                     size_bytes=info.sizeInBytes)
        return info


def _write_checkpoint(engine, snapshot, policy: Optional[str],
                      prev_info: Optional[LastCheckpointInfo] = None,
                      ) -> LastCheckpointInfo:
    state = snapshot.state
    meta_conf = state.metadata.configuration
    if policy is None:
        policy = get_table_config(meta_conf, CHECKPOINT_POLICY)
    now_ms = int(time.time() * 1000)
    retention = get_table_config(meta_conf, TOMBSTONE_RETENTION)
    from delta_tpu.config import (
        CHECKPOINT_WRITE_STATS_AS_JSON,
        CHECKPOINT_WRITE_STATS_AS_STRUCT,
        SET_TXN_RETENTION,
    )

    stats_as_json = get_table_config(meta_conf, CHECKPOINT_WRITE_STATS_AS_JSON)
    stats_as_struct = get_table_config(meta_conf, CHECKPOINT_WRITE_STATS_AS_STRUCT)
    txn_retention = get_table_config(meta_conf, SET_TXN_RETENTION)
    txn_min = (now_ms - txn_retention) if txn_retention is not None else None

    adds = state.add_files_table
    tombs = _retained_tombstones(state, now_ms, retention)
    stats_schema = (_stats_parsed_schema(
        state.metadata.schema, meta_conf,
        list(state.metadata.partitionColumns or []))
        if stats_as_struct else None)
    add_struct = _file_struct_from_canonical(
        adds, is_add=True,
        stats_as_json=stats_as_json, stats_as_struct=stats_as_struct,
        stats_schema=stats_schema)
    remove_struct = _file_struct_from_canonical(tombs, is_add=False)
    protocol_rows, metadata_rows, txn_rows, domain_rows = _small_action_arrays(
        state, txn_min_last_updated=txn_min)

    if settings.verify_checkpoint_row_count and len(add_struct) != state.num_files:
        raise ChecksumMismatchError(
            error_class="DELTA_CHECKPOINT_SNAPSHOT_MISMATCH",
            message=f"checkpoint add rows {len(add_struct)} != snapshot numFiles "
            f"{state.num_files}"
        )

    log_path = snapshot._table.log_path
    version = snapshot.version
    part_size = settings.checkpoint_part_size
    n_files = len(add_struct) + len(remove_struct)

    if policy == "v2":
        route = "v2"
        plan = _chunk_plan(len(add_struct), len(remove_struct),
                           part_size or max(n_files, 1))
    elif part_size is not None and n_files > part_size:
        route = "multipart"
        plan = _chunk_plan(len(add_struct), len(remove_struct), part_size)
    else:
        route = "classic"
        plan = [(0, len(add_struct), 0, len(remove_struct))]

    _checkpoint_aggregates(engine, state, adds, plan)
    writer_fp = _writer_fp(policy, part_size, stats_as_json,
                           stats_as_struct, state.metadata.schemaString)
    prev_parts = (_prev_part_index(prev_info, writer_fp)
                  if route != "classic" else {})

    try:
        if route == "v2":
            info = _write_v2_checkpoint(
                engine, log_path, version, add_struct, remove_struct,
                protocol_rows, metadata_rows, txn_rows, domain_rows,
                plan, writer_fp, prev_parts,
            )
        elif route == "multipart":
            info = _write_multipart_checkpoint(
                engine, log_path, version, add_struct, remove_struct,
                protocol_rows, metadata_rows, txn_rows, domain_rows,
                plan, writer_fp, prev_parts,
            )
        else:
            n = (
                len(protocol_rows) + len(metadata_rows)
                + (len(txn_rows) if txn_rows is not None else 0)
                + (len(domain_rows) if domain_rows is not None else 0)
                + len(add_struct) + len(remove_struct)
            )
            table = _single_action_table(
                n, protocol_rows, metadata_rows, txn_rows, domain_rows,
                add_struct, remove_struct,
            )
            path = filenames.checkpoint_file_singular(log_path, version)
            # same funnel as multipart/V2: put-if-absent with the
            # torn-collision wholeness check, CheckpointWriteError on
            # failure, and bytes/parts accounting
            results = ckpt_pipeline.run_write_tasks(
                engine,
                [ckpt_pipeline.WriteTask(
                    path, lambda: _encode_parquet(table),
                    overwrite=False, label="classic")],
                pipelined=False)
            _count_written(results)
            info = LastCheckpointInfo(
                version=version,
                size=n,
                sizeInBytes=_file_size(engine, path),
                numOfAddFiles=len(add_struct),
            )
    except ckpt_pipeline.CheckpointWriteError as e:
        # torn checkpoint: delete everything this attempt materialized
        # and leave `_last_checkpoint` pointing at the previous (still
        # complete) checkpoint — readers never see a partial part set
        _ABORTED_WRITES.inc()
        _cleanup_orphans(engine, e.touched_paths)
        raise
    write_last_checkpoint(engine.json, log_path, info)
    return info


def _file_size(engine, path: str) -> Optional[int]:
    try:
        return engine.fs.file_status(path).size
    except OSError:
        return None


def _chunk_plan(n_add: int, n_rem: int, part_size: int) -> List[tuple]:
    """FIXED `part_size`-row chunks over the concatenated [adds;
    removes] file-action row space → [(a0, a1, r0, r1)] per part.

    Fixed chunks (not an even split) are what makes incremental reuse
    work: append-only commits add rows at the END of the canonical
    state, so every full earlier chunk covers the same rows as last
    time and its fingerprint — and therefore its bytes — are unchanged.
    An even split would shift every boundary on each append and
    invalidate all parts."""
    total = n_add + n_rem
    out = []
    lo = 0
    while lo < total:
        hi = min(lo + part_size, total)
        out.append((min(lo, n_add), min(hi, n_add),
                    max(lo, n_add) - n_add, max(hi, n_add) - n_add))
        lo = hi
    return out or [(0, 0, 0, 0)]


def _writer_fp(policy, part_size, stats_as_json, stats_as_struct,
               schema_string) -> str:
    """Fingerprint of everything that shapes part bytes besides the rows
    themselves. A part is only reusable when the writer that produced it
    had an identical config — chunk boundaries (part_size), stats
    shaping, the table schema (drives stats_parsed typing), and the
    layout revision of this module."""
    blob = json.dumps(
        {
            "layout": 1,
            "policy": policy,
            "partSize": part_size,
            "statsAsJson": bool(stats_as_json),
            "statsAsStruct": bool(stats_as_struct),
            "schema": hashlib.sha1(
                (schema_string or "").encode()).hexdigest(),
        },
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def _part_fp(writer_fp: str, adds_i: pa.Array, rems_i: pa.Array) -> str:
    """Content fingerprint of one part's file-action rows: sha1 over the
    Arrow IPC bytes of the slices, re-materialized at offset 0 first
    (`pa.concat_arrays`) — a plain slice's IPC stream leaks its parent's
    buffer truncation and absolute offset, so only the rebased form is
    byte-stable across snapshots. Equal fingerprints ⇒ identical rows ⇒
    the previous checkpoint's part bytes are valid for this part."""
    h = hashlib.sha1(writer_fp.encode())
    for name, arr in (("add", adds_i), ("remove", rems_i)):
        batch = pa.record_batch({name: pa.concat_arrays([arr])})
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, batch.schema) as w:
            w.write_batch(batch)
        h.update(sink.getvalue())
    return h.hexdigest()[:20]


def _prev_part_index(prev_info: Optional[LastCheckpointInfo],
                     writer_fp: str) -> Dict[str, dict]:
    """fp → manifest entry for the previous checkpoint's file-action
    parts; empty unless the manifest was written by an identically
    configured writer (unknown/absent manifests degrade to a full
    write, never to wrong reuse)."""
    if prev_info is None:
        return {}
    pm = getattr(prev_info, "partManifest", None)
    if not isinstance(pm, dict) or pm.get("writerFp") != writer_fp:
        return {}
    out: Dict[str, dict] = {}
    for e in pm.get("parts") or []:
        if isinstance(e, dict) and e.get("fp") and e.get("name"):
            out[e["fp"]] = e
    return out


def _encode_parquet(table: pa.Table) -> bytes:
    import pyarrow.parquet as pq

    sink = pa.BufferOutputStream()
    pq.write_table(table, sink, compression="snappy")
    return sink.getvalue().to_pybytes()


def _file_part_build(engine, log_path: str, prev_entry: Optional[dict],
                     adds_i: pa.Array, rems_i: pa.Array,
                     ) -> Callable[[], bytes]:
    """Build closure for one file-action part. With a fingerprint-matched
    previous part the bytes are COPIED from the old object: multipart
    part names embed version and part count, and log cleanup may delete
    old parts once shadowed, so reuse must re-materialize under the new
    checkpoint's filename rather than re-reference. A vacuumed or
    unreadable old part degrades to a fresh encode."""

    def fresh() -> bytes:
        return _encode_parquet(_single_action_table(
            len(adds_i) + len(rems_i), None, None, None, None,
            adds_i, rems_i))

    if prev_entry is None:
        return fresh
    prev_path = f"{log_path}/{prev_entry['name']}"

    def build() -> bytes:
        try:
            data = engine.fs.read_file(prev_path)
        except OSError:
            return fresh()
        _PARTS_REUSED.inc()
        return data

    return build


def _count_written(results) -> None:
    for r in results:
        if r.created:
            _PARTS_WRITTEN.inc()
            _BYTES_WRITTEN.inc(r.nbytes)


def _cleanup_orphans(engine, paths) -> None:
    """Best-effort delete of an aborted checkpoint attempt's artifacts.
    The write failure is re-raised by the caller either way; a path
    that refuses to delete merely leaves an orphan part behind, which
    readers ignore (an incomplete part set is never selected)."""
    for p in paths:
        try:
            engine.fs.delete(p)
        # delta-lint: disable=except-swallow (audited: cleanup after an
        # aborted checkpoint is best-effort — the original failure
        # propagates regardless, and a surviving orphan is inert)
        except Exception:
            pass


def _write_multipart_checkpoint(
    engine, log_path, version, add_struct, remove_struct,
    protocol_rows, metadata_rows, txn_rows, domain_rows,
    plan, writer_fp, prev_parts,
):
    """Legacy multi-part. Part 1 holds the small actions ONLY (they
    churn every checkpoint — protocol/metaData/txn/domainMetadata must
    never dirty a reusable file-action chunk); parts 2..N are fixed
    `part_size`-row file-action chunks per `plan`. Layout mirrors
    `Checkpoints.scala:669-699` (hash split by row — contiguous ranges
    are equally valid: parts are unordered). Parts flow through the
    serialize→upload pipeline (`write/ckpt_pipeline.py`) when its gate
    engages."""
    num_parts = 1 + len(plan)
    paths = filenames.checkpoint_file_with_parts(log_path, version, num_parts)
    n_small = (
        len(protocol_rows) + len(metadata_rows)
        + (len(txn_rows) if txn_rows is not None else 0)
        + (len(domain_rows) if domain_rows is not None else 0)
    )

    def small_build() -> bytes:
        return _encode_parquet(_single_action_table(
            n_small, protocol_rows, metadata_rows, txn_rows, domain_rows,
            None, None))

    tasks = [ckpt_pipeline.WriteTask(paths[0], small_build,
                                     overwrite=False, label="small-actions")]
    part_rows = [n_small]
    part_fps: List[Optional[str]] = [None]
    prev_parts = dict(prev_parts)
    for i, (a0, a1, r0, r1) in enumerate(plan):
        adds_i = add_struct.slice(a0, a1 - a0)
        rems_i = remove_struct.slice(r0, r1 - r0)
        fp = _part_fp(writer_fp, adds_i, rems_i)
        # pop, not get: one old part must not satisfy two new chunks
        prev = prev_parts.pop(fp, None)
        tasks.append(ckpt_pipeline.WriteTask(
            paths[i + 1],
            _file_part_build(engine, log_path, prev, adds_i, rems_i),
            overwrite=False,
            label=f"part-{i + 2}" + (":reuse" if prev else "")))
        part_rows.append(len(adds_i) + len(rems_i))
        part_fps.append(fp)

    pipelined = ckpt_pipeline.profitable(engine, log_path, len(tasks))
    results = ckpt_pipeline.run_write_tasks(engine, tasks, pipelined)
    _count_written(results)

    manifest: Optional[dict] = {"writerFp": writer_fp, "parts": []}
    total_bytes = 0
    for path, fp, n, r in zip(paths, part_fps, part_rows, results):
        if r.status is None:
            # another writer materialized this part: its bytes may not
            # match our fingerprints or sizes — publish no manifest
            manifest = None
            break
        total_bytes += r.status.size or 0
        if fp is not None and manifest is not None:
            manifest["parts"].append({
                "name": filenames.file_name(path), "fp": fp, "rows": n,
                "bytes": r.status.size,
                "mtime": r.status.modification_time,
            })
    return LastCheckpointInfo(
        version=version, size=sum(part_rows), parts=num_parts,
        sizeInBytes=total_bytes if manifest is not None else None,
        numOfAddFiles=len(add_struct),
        partManifest=manifest,
    )


def _sidecar_usable(engine, log_path: str, prev_entry: dict) -> bool:
    """Plan-time existence check before re-referencing a previous
    checkpoint's sidecar, so one lost to manual deletion degrades to a
    rewrite instead of a dangling pointer in the new checkpoint."""
    path = f"{filenames.sidecar_dir(log_path)}/{prev_entry['name']}"
    try:
        return bool(engine.fs.exists(path))
    except OSError:
        return False


def _write_v2_checkpoint(
    engine, log_path, version, add_struct, remove_struct,
    protocol_rows, metadata_rows, txn_rows, domain_rows,
    plan, writer_fp, prev_parts,
):
    """V2 (PROTOCOL.md:196-269): file actions go to `_sidecars/<uuid>.parquet`;
    the top-level UUID checkpoint holds checkpointMetadata + sidecar
    pointers + the small actions. File actions split across fixed
    `checkpoint_part_size`-row sidecars per `plan` (the reference
    writes one sidecar per state partition), run through the
    serialize→upload pipeline when its gate engages.

    Reuse here is a RE-REFERENCE, not a copy: sidecars are uuid-named,
    so log cleanup never deletes them (their names parse to no
    version) and a fingerprint-matched previous sidecar can simply be
    pointed at again — zero serialize, zero upload."""
    n_files = len(add_struct) + len(remove_struct)
    num_parts = len(plan)
    prev_parts = dict(prev_parts)
    tasks: List[ckpt_pipeline.WriteTask] = []
    # per part: ("reuse", Sidecar) | ("task", task index, sidecar name)
    slots: List[tuple] = []
    part_fps: List[str] = []
    part_rows: List[int] = []
    for i, (a0, a1, r0, r1) in enumerate(plan):
        adds_i = add_struct.slice(a0, a1 - a0)
        rems_i = remove_struct.slice(r0, r1 - r0)
        fp = _part_fp(writer_fp, adds_i, rems_i)
        part_fps.append(fp)
        part_rows.append(len(adds_i) + len(rems_i))
        prev = prev_parts.pop(fp, None)
        if prev is not None and _sidecar_usable(engine, log_path, prev):
            _PARTS_REUSED.inc()
            slots.append(("reuse", Sidecar(
                path=prev["name"], sizeInBytes=prev.get("bytes"),
                modificationTime=prev.get("mtime"))))
            continue

        def fresh(adds_i=adds_i, rems_i=rems_i) -> bytes:
            return _encode_parquet(_single_action_table(
                len(adds_i) + len(rems_i), None, None, None, None,
                adds_i, rems_i))

        name = f"{uuid.uuid4()}.parquet"
        tasks.append(ckpt_pipeline.WriteTask(
            f"{filenames.sidecar_dir(log_path)}/{name}", fresh,
            overwrite=True,  # uuid-named: never contended
            label=f"sidecar-{i + 1}"))
        slots.append(("task", len(tasks) - 1, name))

    pipelined = ckpt_pipeline.profitable(engine, log_path, len(tasks))
    results = ckpt_pipeline.run_write_tasks(engine, tasks, pipelined)
    _count_written(results)

    sidecars: List[Sidecar] = []
    manifest_parts: List[dict] = []
    for slot, fp, n in zip(slots, part_fps, part_rows):
        if slot[0] == "reuse":
            sc = slot[1]
        else:
            status = results[slot[1]].status
            sc = Sidecar(path=slot[2], sizeInBytes=status.size,
                         modificationTime=status.modification_time)
        sidecars.append(sc)
        manifest_parts.append({
            "name": sc.path, "fp": fp, "rows": n,
            "bytes": sc.sizeInBytes, "mtime": sc.modificationTime,
        })

    top_schema_cols = {}
    n_top = (
        1 + num_parts  # checkpointMetadata + sidecar pointers
        + len(protocol_rows) + len(metadata_rows)
        + (len(txn_rows) if txn_rows is not None else 0)
        + (len(domain_rows) if domain_rows is not None else 0)
    )
    CP_META_STRUCT = pa.struct([pa.field("version", pa.int64())])
    SIDECAR_STRUCT = pa.struct(
        [
            pa.field("path", pa.string()),
            pa.field("sizeInBytes", pa.int64()),
            pa.field("modificationTime", pa.int64()),
        ]
    )

    def block(arr, typ, start, sz):
        parts = []
        if start:
            parts.append(pa.nulls(start, typ))
        if arr is not None and sz:
            parts.append(arr)
        rest = n_top - start - sz
        if rest:
            parts.append(pa.nulls(rest, typ))
        return pa.concat_arrays(parts)

    offset = 0
    cp_arr = pa.array([{"version": version}], CP_META_STRUCT)
    top_schema_cols["checkpointMetadata"] = block(cp_arr, CP_META_STRUCT, offset, 1)
    offset += 1
    sc_arr = pa.array(
        [{
            "path": sc.path,
            "sizeInBytes": sc.sizeInBytes,
            "modificationTime": sc.modificationTime,
        } for sc in sidecars],
        SIDECAR_STRUCT,
    )
    top_schema_cols["sidecar"] = block(sc_arr, SIDECAR_STRUCT, offset, num_parts)
    offset += num_parts
    top_schema_cols["protocol"] = block(protocol_rows, PROTOCOL_STRUCT, offset, len(protocol_rows))
    offset += len(protocol_rows)
    top_schema_cols["metaData"] = block(metadata_rows, METADATA_STRUCT, offset, len(metadata_rows))
    offset += len(metadata_rows)
    if txn_rows is not None:
        top_schema_cols["txn"] = block(txn_rows, TXN_STRUCT, offset, len(txn_rows))
        offset += len(txn_rows)
    if domain_rows is not None:
        top_schema_cols["domainMetadata"] = block(domain_rows, DOMAIN_STRUCT, offset, len(domain_rows))
        offset += len(domain_rows)

    top_table = pa.table(top_schema_cols)
    top_path = filenames.top_level_v2_checkpoint_file(log_path, version, "parquet")
    try:
        engine.parquet.write_parquet_file_atomically(top_path, top_table)
    except BaseException as e:
        # only OUR fresh sidecars are orphans — re-referenced ones
        # belong to the previous (still active) checkpoint
        touched = [r.task.path for r in results if r.created] + [top_path]
        raise ckpt_pipeline.CheckpointWriteError(e, touched) from e
    total_bytes = sum(sc.sizeInBytes or 0 for sc in sidecars)
    total_bytes += _file_size(engine, top_path) or 0
    return LastCheckpointInfo(
        version=version,
        size=n_top + n_files,
        sizeInBytes=total_bytes or None,
        numOfAddFiles=len(add_struct),
        tag=filenames.file_name(top_path),
        partManifest={"writerFp": writer_fp, "parts": manifest_parts},
    )

"""Checkpoint writing (classic single-file, multi-part, V2+sidecars).

Reference: spark `Checkpoints.scala:616` writeCheckpoint, kernel
`CreateCheckpointIterator` → `ParquetHandler.writeParquetFileAtomically`.

A checkpoint materializes the reconciled state at a version as Parquet in
the SingleAction layout: struct columns `protocol`, `metaData`, `txn`,
`domainMetadata`, `add`, `remove` — one non-null per row. Contents:
- 1 protocol + 1 metaData row,
- one `txn` row per appId, one `domainMetadata` row per domain
  (including removal tombstones),
- every live `add` (dataChange=false),
- every `remove` tombstone younger than the retention window
  (`delta.deletedFileRetentionDuration`), dataChange=false.

The add/remove struct columns are assembled directly from the snapshot's
canonical columnar state — no per-row object hop. Finishes by pointing
`_last_checkpoint` at the new checkpoint.
"""

from __future__ import annotations

import time
import uuid
from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.config import (
    CHECKPOINT_POLICY,
    TOMBSTONE_RETENTION,
    get_table_config,
    settings,
)
from delta_tpu import obs
from delta_tpu.errors import ChecksumMismatchError, InvalidArgumentError
from delta_tpu.log.last_checkpoint import LastCheckpointInfo, write_last_checkpoint
from delta_tpu.models.actions import Sidecar
from delta_tpu.replay.columnar import DV_STRUCT_TYPE
from delta_tpu.utils import filenames

PV_MAP = pa.map_(pa.string(), pa.string())

ADD_STRUCT = pa.struct(
    [
        pa.field("path", pa.string()),
        pa.field("partitionValues", PV_MAP),
        pa.field("size", pa.int64()),
        pa.field("modificationTime", pa.int64()),
        pa.field("dataChange", pa.bool_()),
        pa.field("stats", pa.string()),
        pa.field("deletionVector", DV_STRUCT_TYPE),
        pa.field("baseRowId", pa.int64()),
        pa.field("defaultRowCommitVersion", pa.int64()),
        pa.field("clusteringProvider", pa.string()),
    ]
)

REMOVE_STRUCT = pa.struct(
    [
        pa.field("path", pa.string()),
        pa.field("deletionTimestamp", pa.int64()),
        pa.field("dataChange", pa.bool_()),
        pa.field("extendedFileMetadata", pa.bool_()),
        pa.field("partitionValues", PV_MAP),
        pa.field("size", pa.int64()),
        pa.field("deletionVector", DV_STRUCT_TYPE),
        pa.field("baseRowId", pa.int64()),
        pa.field("defaultRowCommitVersion", pa.int64()),
    ]
)

PROTOCOL_STRUCT = pa.struct(
    [
        pa.field("minReaderVersion", pa.int32()),
        pa.field("minWriterVersion", pa.int32()),
        pa.field("readerFeatures", pa.list_(pa.string())),
        pa.field("writerFeatures", pa.list_(pa.string())),
    ]
)

METADATA_STRUCT = pa.struct(
    [
        pa.field("id", pa.string()),
        pa.field("name", pa.string()),
        pa.field("description", pa.string()),
        pa.field(
            "format",
            pa.struct(
                [pa.field("provider", pa.string()), pa.field("options", PV_MAP)]
            ),
        ),
        pa.field("schemaString", pa.string()),
        pa.field("partitionColumns", pa.list_(pa.string())),
        pa.field("configuration", PV_MAP),
        pa.field("createdTime", pa.int64()),
    ]
)

TXN_STRUCT = pa.struct(
    [
        pa.field("appId", pa.string()),
        pa.field("version", pa.int64()),
        pa.field("lastUpdated", pa.int64()),
    ]
)

DOMAIN_STRUCT = pa.struct(
    [
        pa.field("domain", pa.string()),
        pa.field("configuration", pa.string()),
        pa.field("removed", pa.bool_()),
    ]
)


def _stats_parsed_schema(schema, configuration,
                         partition_columns) -> Optional[pa.Schema]:
    """Explicit arrow schema for stats_parsed, typed per the TABLE
    schema (external struct-form readers expect e.g. timestamp mins as
    timestamps, not inferred strings): numRecords int64, minValues /
    maxValues as nested structs of the indexed leaves' arrow types,
    nullCount as int64 per leaf."""
    from delta_tpu.models.schema import PrimitiveType, StructType, to_arrow_type
    from delta_tpu.stats.collection import stats_columns

    if schema is None:
        return None

    def resolve(path):
        node = schema
        for name in path[:-1]:
            if not isinstance(node, StructType) or name not in node:
                return None
            node = node[name].dataType
        if not isinstance(node, StructType) or path[-1] not in node:
            return None
        return node[path[-1]].dataType

    minmax_tree: dict = {}
    null_tree: dict = {}

    def insert(tree, path, typ):
        for p in path[:-1]:
            tree = tree.setdefault(p, {})
        tree[path[-1]] = typ

    for path in stats_columns(schema, configuration, partition_columns):
        dt = resolve(path)
        if not isinstance(dt, PrimitiveType):
            continue
        try:
            arrow_t = to_arrow_type(dt)
        except (ValueError, InvalidArgumentError):
            continue  # unmappable type: no stats column for it
        insert(null_tree, path, pa.int64())
        if dt.name != "binary":
            insert(minmax_tree, path, arrow_t)

    def to_struct(tree) -> pa.DataType:
        return pa.struct([
            pa.field(k, to_struct(v) if isinstance(v, dict) else v)
            for k, v in tree.items()
        ])

    fields = [pa.field("numRecords", pa.int64())]
    if minmax_tree:
        fields.append(pa.field("minValues", to_struct(minmax_tree)))
        fields.append(pa.field("maxValues", to_struct(minmax_tree)))
    if null_tree:
        fields.append(pa.field("nullCount", to_struct(null_tree)))
    # DV-capable writers mark whether min/max reflect the post-delete
    # rows; without this field in the explicit schema a struct-only
    # checkpoint round-trip would silently drop it
    fields.append(pa.field("tightBounds", pa.bool_()))
    return pa.schema(fields)


def _stats_ndjson_buffer(stats_col: pa.Array) -> Optional[pa.Buffer]:
    """The stats strings as one newline-delimited buffer, built with
    Arrow kernels (no per-row Python objects — this runs at
    checkpoint-write scale)."""
    import pyarrow.compute as _pc

    filled = _pc.fill_null(stats_col, "{}")
    # append "\n" per row: the LAST argument is the separator, so join
    # (value, "") with separator "\n" — value + "\n" + ""
    with_nl = _pc.binary_join_element_wise(filled, pa.scalar(""),
                                           pa.scalar("\n"))
    arr = (with_nl.combine_chunks()
           if isinstance(with_nl, pa.ChunkedArray) else with_nl)
    if arr.offset != 0:
        arr = pa.concat_arrays([arr])  # re-materialize at offset 0
    offsets_buf = arr.buffers()[1]
    width = 8 if pa.types.is_large_string(arr.type) else 4
    dtype = np.int64 if width == 8 else np.int32
    offsets = np.frombuffer(offsets_buf, dtype=dtype, count=len(arr) + 1)
    total = int(offsets[-1])
    return arr.buffers()[2].slice(0, total)


def _parse_stats_structs(
    stats_col: pa.Array, explicit_schema: Optional[pa.Schema] = None
) -> Optional[pa.Array]:
    """Parse per-file stats JSON strings into a struct array, typed by
    `explicit_schema` when given (falling back to inference if the
    explicit parse fails — e.g. 'NaN' strings in double stats). Null
    stats become empty objects (all-null fields). None when nothing
    parses."""
    import pyarrow.json as pa_json

    if stats_col.null_count == len(stats_col):
        return None
    buf = _stats_ndjson_buffer(stats_col)
    if buf is None:
        return None
    parsed = None
    if explicit_schema is not None:
        try:
            parsed = pa_json.read_json(
                pa.BufferReader(buf),
                parse_options=pa_json.ParseOptions(
                    explicit_schema=explicit_schema,
                    unexpected_field_behavior="ignore"))
        except (pa.ArrowException, ValueError, OSError):
            parsed = None  # schema mismatch: retry with inference below
    if parsed is None:
        try:
            parsed = pa_json.read_json(pa.BufferReader(buf))
        except (pa.ArrowException, ValueError, OSError):
            return None  # malformed stats: skip the struct form entirely
    if parsed.num_rows != len(stats_col):
        return None
    return parsed.to_struct_array().combine_chunks()


def _file_struct_from_canonical(
    tbl: pa.Table,
    is_add: bool,
    stats_as_json: bool = True,
    stats_as_struct: bool = False,
    stats_schema: Optional[pa.Schema] = None,
) -> pa.Array:
    """Canonical columnar rows → add/remove StructArray. Stats shaping
    per `delta.checkpoint.writeStatsAsJson` / `writeStatsAsStruct`
    (`Checkpoints.scala` buildCheckpoint)."""
    n = tbl.num_rows
    false_col = pa.array(np.zeros(n, dtype=bool))

    def col(name):
        return tbl.column(name).combine_chunks()

    if is_add:
        stats = col("stats")
        fields = list(ADD_STRUCT)
        children = [
            col("path"),
            col("partition_values"),
            col("size"),
            col("modification_time"),
            false_col,  # dataChange normalized to false in checkpoints
            stats if stats_as_json else pa.nulls(n, pa.string()),
            col("deletion_vector"),
            col("base_row_id"),
            col("default_row_commit_version"),
            col("clustering_provider"),
        ]
        if stats_as_struct:
            parsed = _parse_stats_structs(stats, stats_schema)
            if parsed is not None:
                children.append(parsed)
                fields = fields + [pa.field("stats_parsed", parsed.type)]
        return pa.StructArray.from_arrays(children, fields=fields)
    children = [
        col("path"),
        col("deletion_timestamp"),
        false_col,
        col("extended_file_metadata"),
        col("partition_values"),
        col("size"),
        col("deletion_vector"),
        col("base_row_id"),
        col("default_row_commit_version"),
    ]
    return pa.StructArray.from_arrays(children, fields=list(REMOVE_STRUCT))


def _single_action_table(
    n: int,
    protocol_rows: Optional[pa.Array] = None,
    metadata_rows: Optional[pa.Array] = None,
    txn_rows: Optional[pa.Array] = None,
    domain_rows: Optional[pa.Array] = None,
    add_rows: Optional[pa.Array] = None,
    remove_rows: Optional[pa.Array] = None,
) -> pa.Table:
    """Assemble a SingleAction table: each input occupies its own row
    range; all other columns null there."""
    blocks = [
        ("protocol", PROTOCOL_STRUCT, protocol_rows),
        ("metaData", METADATA_STRUCT, metadata_rows),
        ("txn", TXN_STRUCT, txn_rows),
        ("domainMetadata", DOMAIN_STRUCT, domain_rows),
        ("add", ADD_STRUCT, add_rows),
        ("remove", REMOVE_STRUCT, remove_rows),
    ]
    sizes = [len(b[2]) if b[2] is not None else 0 for b in blocks]
    total = sum(sizes)
    assert total == n, (total, n)
    # chunked columns, not concat_arrays: the null spans and the payload
    # arrays become chunks as-is, so a million-file checkpoint table is
    # assembled without copying a single struct row
    cols = {}
    offset = 0
    offsets = []
    for (name, typ, arr), sz in zip(blocks, sizes):
        offsets.append(offset)
        offset += sz
    for i, (name, typ, arr) in enumerate(blocks):
        sz = sizes[i]
        # honor the payload's actual type when present — the add struct
        # may carry an extra stats_parsed field beyond the static schema
        if arr is not None and sz:
            typ = arr.type
        before, after = offsets[i], n - offsets[i] - sz
        chunks = []
        if before:
            chunks.append(pa.nulls(before, typ))
        if arr is not None and sz:
            chunks.append(arr)
        if after:
            chunks.append(pa.nulls(after, typ))
        cols[name] = (pa.chunked_array(chunks, type=typ) if chunks
                      else pa.chunked_array([], type=typ))
    return pa.table(cols)


def _small_action_arrays(state, txn_min_last_updated: Optional[int] = None) -> tuple:
    proto = state.protocol
    protocol_rows = pa.array(
        [
            {
                "minReaderVersion": proto.minReaderVersion,
                "minWriterVersion": proto.minWriterVersion,
                "readerFeatures": (
                    sorted(proto.readerFeatures) if proto.readerFeatures is not None else None
                ),
                "writerFeatures": (
                    sorted(proto.writerFeatures) if proto.writerFeatures is not None else None
                ),
            }
        ],
        PROTOCOL_STRUCT,
    )
    meta = state.metadata
    metadata_rows = pa.array(
        [
            {
                "id": meta.id,
                "name": meta.name,
                "description": meta.description,
                "format": {"provider": meta.format.provider, "options": list(meta.format.options.items())},
                "schemaString": meta.schemaString,
                "partitionColumns": list(meta.partitionColumns),
                "configuration": list(meta.configuration.items()),
                "createdTime": meta.createdTime,
            }
        ],
        METADATA_STRUCT,
    )
    txns = list(state.set_transactions.values())
    if txn_min_last_updated is not None:
        # expire idle SetTransaction entries from the checkpoint
        # (`InMemoryLogReplay.scala:84-91`: lastUpdated.exists(_ > min) —
        # entries without a timestamp are dropped once retention is on)
        txns = [t for t in txns
                if t.lastUpdated is not None
                and t.lastUpdated >= txn_min_last_updated]
    txn_rows = (
        pa.array(
            [
                {"appId": t.appId, "version": t.version, "lastUpdated": t.lastUpdated}
                for t in txns
            ],
            TXN_STRUCT,
        )
        if txns
        else None
    )
    domain_rows = (
        pa.array(
            [
                {"domain": d.domain, "configuration": d.configuration, "removed": d.removed}
                for d in state.domain_metadata.values()
            ],
            DOMAIN_STRUCT,
        )
        if state.domain_metadata
        else None
    )
    return protocol_rows, metadata_rows, txn_rows, domain_rows


def _retained_tombstones(state, now_ms: int, retention_ms: int) -> pa.Table:
    tombs = state.tombstones_table
    if tombs.num_rows == 0:
        return tombs
    min_retain = now_ms - retention_ms
    del_ts = pc.fill_null(tombs.column("deletion_timestamp"), 0)
    keep = pc.greater_equal(del_ts, pa.scalar(min_retain, pa.int64()))
    return tombs.filter(keep)


def write_checkpoint(engine, snapshot, policy: Optional[str] = None) -> LastCheckpointInfo:
    """Write a checkpoint for `snapshot` and update `_last_checkpoint`."""
    with obs.span("checkpoint.write", log_path=snapshot._table.log_path,
                  version=snapshot.version) as sp:
        info = _write_checkpoint(engine, snapshot, policy)
        sp.set_attrs(actions=info.size, num_add_files=info.numOfAddFiles,
                     size_bytes=info.sizeInBytes)
        return info


def _write_checkpoint(engine, snapshot, policy: Optional[str]) -> LastCheckpointInfo:
    state = snapshot.state
    meta_conf = state.metadata.configuration
    if policy is None:
        policy = get_table_config(meta_conf, CHECKPOINT_POLICY)
    now_ms = int(time.time() * 1000)
    retention = get_table_config(meta_conf, TOMBSTONE_RETENTION)
    from delta_tpu.config import (
        CHECKPOINT_WRITE_STATS_AS_JSON,
        CHECKPOINT_WRITE_STATS_AS_STRUCT,
        SET_TXN_RETENTION,
    )

    stats_as_json = get_table_config(meta_conf, CHECKPOINT_WRITE_STATS_AS_JSON)
    stats_as_struct = get_table_config(meta_conf, CHECKPOINT_WRITE_STATS_AS_STRUCT)
    txn_retention = get_table_config(meta_conf, SET_TXN_RETENTION)
    txn_min = (now_ms - txn_retention) if txn_retention is not None else None

    adds = state.add_files_table
    tombs = _retained_tombstones(state, now_ms, retention)
    stats_schema = (_stats_parsed_schema(
        state.metadata.schema, meta_conf,
        list(state.metadata.partitionColumns or []))
        if stats_as_struct else None)
    add_struct = _file_struct_from_canonical(
        adds, is_add=True,
        stats_as_json=stats_as_json, stats_as_struct=stats_as_struct,
        stats_schema=stats_schema)
    remove_struct = _file_struct_from_canonical(tombs, is_add=False)
    protocol_rows, metadata_rows, txn_rows, domain_rows = _small_action_arrays(
        state, txn_min_last_updated=txn_min)

    if settings.verify_checkpoint_row_count and len(add_struct) != state.num_files:
        raise ChecksumMismatchError(
            error_class="DELTA_CHECKPOINT_SNAPSHOT_MISMATCH",
            message=f"checkpoint add rows {len(add_struct)} != snapshot numFiles "
            f"{state.num_files}"
        )

    log_path = snapshot._table.log_path
    version = snapshot.version

    if policy == "v2":
        info = _write_v2_checkpoint(
            engine, log_path, version, add_struct, remove_struct,
            protocol_rows, metadata_rows, txn_rows, domain_rows,
        )
    else:
        part_size = settings.checkpoint_part_size
        n_files = len(add_struct) + len(remove_struct)
        if part_size is not None and n_files > part_size:
            info = _write_multipart_checkpoint(
                engine, log_path, version, part_size, add_struct, remove_struct,
                protocol_rows, metadata_rows, txn_rows, domain_rows,
            )
        else:
            n = (
                len(protocol_rows) + len(metadata_rows)
                + (len(txn_rows) if txn_rows is not None else 0)
                + (len(domain_rows) if domain_rows is not None else 0)
                + len(add_struct) + len(remove_struct)
            )
            table = _single_action_table(
                n, protocol_rows, metadata_rows, txn_rows, domain_rows,
                add_struct, remove_struct,
            )
            path = filenames.checkpoint_file_singular(log_path, version)
            try:
                engine.parquet.write_parquet_file_atomically(path, table)
            except FileExistsError:
                pass  # another writer already checkpointed this version
            info = LastCheckpointInfo(
                version=version,
                size=n,
                sizeInBytes=_file_size(engine, path),
                numOfAddFiles=len(add_struct),
            )
    write_last_checkpoint(engine.json, log_path, info)
    return info


def _file_size(engine, path: str) -> Optional[int]:
    try:
        return engine.fs.file_status(path).size
    except OSError:
        return None


def _write_multipart_checkpoint(
    engine, log_path, version, part_size, add_struct, remove_struct,
    protocol_rows, metadata_rows, txn_rows, domain_rows,
):
    """Legacy multi-part: file actions split across parts; small actions in
    part 1. Part layout mirrors `Checkpoints.scala:669-699` (hash split by
    row — here contiguous ranges, equally valid: parts are unordered)."""
    file_rows: List[tuple] = [(True, add_struct), (False, remove_struct)]
    total_files = len(add_struct) + len(remove_struct)
    num_parts = max(1, -(-total_files // part_size))
    paths = filenames.checkpoint_file_with_parts(log_path, version, num_parts)

    add_splits = _split_ranges(len(add_struct), num_parts)
    rem_splits = _split_ranges(len(remove_struct), num_parts)

    def _write_part(i: int) -> int:
        """One part; returns its action count. Parts are independent
        files, so they write concurrently — the reference's task-per-part
        distributed write (`Checkpoints.scala:717-782`) mapped onto the
        shared I/O pool."""
        a0, a1 = add_splits[i]
        r0, r1 = rem_splits[i]
        adds_i = add_struct.slice(a0, a1 - a0)
        rems_i = remove_struct.slice(r0, r1 - r0)
        p_rows = protocol_rows if i == 0 else None
        m_rows = metadata_rows if i == 0 else None
        t_rows = txn_rows if i == 0 else None
        d_rows = domain_rows if i == 0 else None
        n = (
            (len(p_rows) if p_rows is not None else 0)
            + (len(m_rows) if m_rows is not None else 0)
            + (len(t_rows) if t_rows is not None else 0)
            + (len(d_rows) if d_rows is not None else 0)
            + len(adds_i) + len(rems_i)
        )
        table = _single_action_table(n, p_rows, m_rows, t_rows, d_rows,
                                     adds_i, rems_i)
        try:
            engine.parquet.write_parquet_file_atomically(paths[i], table)
        except FileExistsError:
            pass
        return n

    from delta_tpu.utils.threads import parallel_map

    total_actions = sum(parallel_map(_write_part, range(num_parts)))
    return LastCheckpointInfo(
        version=version, size=total_actions, parts=num_parts,
        numOfAddFiles=len(add_struct),
    )


def _split_ranges(n: int, parts: int) -> List[tuple]:
    bounds = [round(i * n / parts) for i in range(parts + 1)]
    return [(bounds[i], bounds[i + 1]) for i in range(parts)]


def _write_v2_checkpoint(
    engine, log_path, version, add_struct, remove_struct,
    protocol_rows, metadata_rows, txn_rows, domain_rows,
):
    """V2 (PROTOCOL.md:196-269): file actions go to `_sidecars/<uuid>.parquet`;
    the top-level UUID checkpoint holds checkpointMetadata + sidecar
    pointers + the small actions. File actions split across
    `checkpoint_part_size`-row sidecars written concurrently (the
    reference writes one sidecar per state partition)."""
    n_files = len(add_struct) + len(remove_struct)
    part_size = settings.checkpoint_part_size
    num_parts = (max(1, -(-n_files // part_size)) if part_size else 1)
    add_splits = _split_ranges(len(add_struct), num_parts)
    rem_splits = _split_ranges(len(remove_struct), num_parts)

    def _write_sidecar(i: int) -> Sidecar:
        a0, a1 = add_splits[i]
        r0, r1 = rem_splits[i]
        adds_i = add_struct.slice(a0, a1 - a0)
        rems_i = remove_struct.slice(r0, r1 - r0)
        sidecar_uuid = str(uuid.uuid4())
        sidecar_path = filenames.sidecar_file(log_path, sidecar_uuid)
        sidecar_table = _single_action_table(
            len(adds_i) + len(rems_i), None, None, None, None, adds_i, rems_i
        )
        status = engine.parquet.write_parquet_file(sidecar_path, sidecar_table)
        return Sidecar(
            path=f"{sidecar_uuid}.parquet",
            sizeInBytes=status.size,
            modificationTime=status.modification_time,
        )

    from delta_tpu.utils.threads import parallel_map

    sidecars = parallel_map(_write_sidecar, range(num_parts))

    top_schema_cols = {}
    n_top = (
        1 + num_parts  # checkpointMetadata + sidecar pointers
        + len(protocol_rows) + len(metadata_rows)
        + (len(txn_rows) if txn_rows is not None else 0)
        + (len(domain_rows) if domain_rows is not None else 0)
    )
    CP_META_STRUCT = pa.struct([pa.field("version", pa.int64())])
    SIDECAR_STRUCT = pa.struct(
        [
            pa.field("path", pa.string()),
            pa.field("sizeInBytes", pa.int64()),
            pa.field("modificationTime", pa.int64()),
        ]
    )

    def block(arr, typ, start, sz):
        parts = []
        if start:
            parts.append(pa.nulls(start, typ))
        if arr is not None and sz:
            parts.append(arr)
        rest = n_top - start - sz
        if rest:
            parts.append(pa.nulls(rest, typ))
        return pa.concat_arrays(parts)

    offset = 0
    cp_arr = pa.array([{"version": version}], CP_META_STRUCT)
    top_schema_cols["checkpointMetadata"] = block(cp_arr, CP_META_STRUCT, offset, 1)
    offset += 1
    sc_arr = pa.array(
        [{
            "path": sc.path,
            "sizeInBytes": sc.sizeInBytes,
            "modificationTime": sc.modificationTime,
        } for sc in sidecars],
        SIDECAR_STRUCT,
    )
    top_schema_cols["sidecar"] = block(sc_arr, SIDECAR_STRUCT, offset, num_parts)
    offset += num_parts
    top_schema_cols["protocol"] = block(protocol_rows, PROTOCOL_STRUCT, offset, len(protocol_rows))
    offset += len(protocol_rows)
    top_schema_cols["metaData"] = block(metadata_rows, METADATA_STRUCT, offset, len(metadata_rows))
    offset += len(metadata_rows)
    if txn_rows is not None:
        top_schema_cols["txn"] = block(txn_rows, TXN_STRUCT, offset, len(txn_rows))
        offset += len(txn_rows)
    if domain_rows is not None:
        top_schema_cols["domainMetadata"] = block(domain_rows, DOMAIN_STRUCT, offset, len(domain_rows))
        offset += len(domain_rows)

    top_table = pa.table(top_schema_cols)
    top_path = filenames.top_level_v2_checkpoint_file(log_path, version, "parquet")
    engine.parquet.write_parquet_file_atomically(top_path, top_table)
    total_bytes = sum(sc.sizeInBytes or 0 for sc in sidecars)
    total_bytes += _file_size(engine, top_path) or 0
    return LastCheckpointInfo(
        version=version,
        size=n_top + n_files,
        sizeInBytes=total_bytes or None,
        numOfAddFiles=len(add_struct),
        tag=filenames.file_name(top_path),
    )

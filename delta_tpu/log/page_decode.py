"""Device checkpoint-page decoder (SURVEY §7 hard part (d)).

The reference hand-rolls its own Parquet reader precisely because page
decode sits on its replay hot path
(`kernel/kernel-defaults/src/main/java/io/delta/kernel/defaults/internal/parquet/ParquetFileReader.java`).
This module is the TPU-native counterpart for the checkpoint's numeric
columns (add.size, add.modificationTime, add.dataChange, version...):

- host: thrift compact-protocol PageHeader parse (hand-rolled from the
  parquet-format spec), page decompression, and the tiny varint run
  headers of the RLE/bit-packed hybrid;
- device: the O(bytes) work — bit-unpacking of the packed index runs
  through the Pallas kernel (`ops/pallas_kernels.py::unpack_bitpacked`)
  and the dictionary gather.

Scope (DecodeUnsupported → caller falls back to the Arrow reader):
data page v1, SNAPPY or uncompressed, non-repeated columns (struct
nesting adds definition levels and is handled; lists/maps are not),
PLAIN / RLE_DICTIONARY values, physical INT32/INT64/DOUBLE/BOOLEAN.
"""
# delta-lint: file-disable=shared-state-race — audited:
# _Thrift is a function-local decode cursor: constructed inside the
# decode call, never stored or returned, so no two threads ever see
# the same instance.

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np


class DecodeUnsupported(Exception):
    """Shape/encoding outside the decoder's scope — use the fallback."""


# ------------------------------------------------ thrift compact read --

_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12


class _Thrift:
    """Minimal thrift compact-protocol reader: varints, zigzag ints,
    struct field iteration, and recursive skipping of what we don't
    model (statistics, crc...)."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_struct(self) -> dict:
        """field id -> python value (structs become dicts, unmodeled
        types are skipped with a None placeholder)."""
        out = {}
        fid = 0
        while True:
            head = self.buf[self.pos]
            self.pos += 1
            if head == _CT_STOP:
                return out
            delta = head >> 4
            ctype = head & 0x0F
            fid = fid + delta if delta else self.zigzag()
            out[fid] = self._read_value(ctype)

    def _read_value(self, ctype: int):
        if ctype == _CT_TRUE:
            return True
        if ctype == _CT_FALSE:
            return False
        if ctype == _CT_BYTE:
            v = self.buf[self.pos]
            self.pos += 1
            return v
        if ctype in (_CT_I16, _CT_I32, _CT_I64):
            return self.zigzag()
        if ctype == _CT_DOUBLE:
            v = struct.unpack_from("<d", self.buf, self.pos)[0]
            self.pos += 8
            return v
        if ctype == _CT_BINARY:
            n = self.varint()
            v = self.buf[self.pos:self.pos + n]
            self.pos += n
            return v
        if ctype == _CT_STRUCT:
            return self.read_struct()
        if ctype in (_CT_LIST, _CT_SET):
            head = self.buf[self.pos]
            self.pos += 1
            size = head >> 4
            elem = head & 0x0F
            if size == 15:
                size = self.varint()
            return [self._read_value(elem) for _ in range(size)]
        if ctype == _CT_MAP:
            size = self.varint()
            if size == 0:
                return {}
            kv = self.buf[self.pos]
            self.pos += 1
            kt, vt = kv >> 4, kv & 0x0F
            return {self._read_value(kt): self._read_value(vt)
                    for _ in range(size)}
        raise DecodeUnsupported(f"thrift type {ctype}")


# page types (parquet-format PageType)
_PAGE_DATA = 0
_PAGE_DICT = 2
_PAGE_DATA_V2 = 3

# encodings
_ENC_PLAIN = 0
_ENC_PLAIN_DICT = 2
_ENC_RLE = 3
_ENC_RLE_DICT = 8


@dataclass
class PageInfo:
    type: int
    uncompressed_size: int
    compressed_size: int
    num_values: int
    encoding: int
    payload_start: int  # offset of the (compressed) payload in the chunk


def split_pages(chunk: bytes) -> List[PageInfo]:
    """Host page splitting: walk the chunk's PageHeaders."""
    pages = []
    pos = 0
    while pos < len(chunk):
        t = _Thrift(chunk, pos)
        hdr = t.read_struct()
        ptype = hdr.get(1)
        if ptype is None:
            break
        if ptype == _PAGE_DATA:
            dph = hdr.get(5) or {}
            nv, enc = dph.get(1, 0), dph.get(2, _ENC_PLAIN)
        elif ptype == _PAGE_DICT:
            dph = hdr.get(7) or {}
            nv, enc = dph.get(1, 0), dph.get(2, _ENC_PLAIN)
        elif ptype == _PAGE_DATA_V2:
            raise DecodeUnsupported("data page v2")
        else:
            nv, enc = 0, _ENC_PLAIN
        pages.append(PageInfo(ptype, hdr.get(2, 0), hdr.get(3, 0),
                              nv, enc, t.pos))
        pos = t.pos + hdr.get(3, 0)
    return pages


def _decompress(chunk: bytes, page: PageInfo, codec: str) -> bytes:
    raw = chunk[page.payload_start:page.payload_start
                + page.compressed_size]
    if codec in ("UNCOMPRESSED", "NONE"):
        return raw
    if codec == "SNAPPY":
        import pyarrow as pa

        return pa.Codec("snappy").decompress(
            raw, decompressed_size=page.uncompressed_size).to_pybytes()
    raise DecodeUnsupported(f"codec {codec}")


# ------------------------------------------- RLE/bit-packed hybrid ----

@dataclass
class HybridRuns:
    """Parsed hybrid stream: RLE runs resolved host-side (they're a
    value + count — nothing to compute), bit-packed runs forwarded to
    the device kernel as (out_start, n_values, word blocks)."""

    n: int
    w: int = 0  # bit width (set by parse_hybrid)
    rle: List[Tuple[int, int, int]] = field(default_factory=list)
    # per bit-packed run: (out_start, n_values, words[G, ...] flat)
    packed: List[Tuple[int, int, np.ndarray]] = field(
        default_factory=list)


def parse_hybrid(data: bytes, pos: int, w: int, n: int,
                 end: Optional[int] = None) -> Tuple[HybridRuns, int]:
    """Parse the RLE/bit-packed hybrid stream for `n` values at bit
    width `w` starting at `pos`. Returns (runs, next_pos)."""
    if not isinstance(w, int) or not 0 <= w <= 32:
        raise DecodeUnsupported(f"hybrid bit width {w!r} outside [0, 32]")
    runs = HybridRuns(n, w)
    out = 0
    byte_w = (w + 7) // 8
    limit = len(data) if end is None else end
    t = _Thrift(data, pos)
    while out < n and t.pos < limit:
        header = t.varint()
        if header & 1:  # bit-packed: (header >> 1) groups of 8
            groups8 = header >> 1
            nvals = groups8 * 8
            nbytes = groups8 * w
            seg = data[t.pos:t.pos + nbytes]
            t.pos += nbytes
            padded = seg + b"\x00" * (-len(seg) % 4)
            words = np.frombuffer(padded, np.uint32)
            runs.packed.append((out, min(nvals, n - out), words))
            out += nvals
        else:  # RLE: value repeated (header >> 1) times
            count = header >> 1
            vbytes = data[t.pos:t.pos + byte_w]
            t.pos += byte_w
            value = int.from_bytes(vbytes, "little")
            runs.rle.append((out, min(count, n - out), value))
            out += count
    if out < n:
        raise DecodeUnsupported(f"hybrid stream ended early ({out}/{n})")
    return runs, t.pos


def materialize_runs(runs: HybridRuns, device=None) -> np.ndarray:
    """Expand a hybrid stream to uint32[n]: RLE fills host-side, all
    bit-packed runs decode in ONE device kernel launch (runs are
    concatenated group-aligned into a single [w-major] word stream)."""
    out = np.zeros(runs.n, np.uint32)
    for start, count, value in runs.rle:
        out[start:start + count] = value
    if runs.packed:
        from delta_tpu.ops.pallas_kernels import unpack_bitpacked

        w = runs.w
        if not isinstance(w, int) or not 0 <= w <= 32:
            # guards callers that build HybridRuns directly; w outside the
            # kernel's domain means a corrupt page, not a kernel bug
            raise DecodeUnsupported(f"bit-packed width {w!r} outside [0, 32]")
        group_counts = [-(-max(nv, 1) // 32) for _s, nv, _w in
                        runs.packed]
        total_groups = sum(group_counts)
        words = np.zeros(total_groups * w, np.uint32)
        woff = 0
        for (_s, _nv, rw), g in zip(runs.packed, group_counts):
            need = g * w
            words[woff:woff + min(len(rw), need)] = rw[:need]
            woff += need
        decoded = np.asarray(unpack_bitpacked(words, w, total_groups,
                                               device=device))
        goff = 0
        for (start, nv, _rw), g in zip(runs.packed, group_counts):
            out[start:start + nv] = decoded[goff * 32:goff * 32 + nv]
            goff += g
    return out


# ------------------------------------------------- column decoding ----

_PHYS_NP = {"INT32": np.int32, "INT64": np.int64, "DOUBLE": np.float64}


def decode_dictionary(payload: bytes, num_values: int,
                      physical_type: str) -> np.ndarray:
    if physical_type not in _PHYS_NP:
        raise DecodeUnsupported(f"dict physical {physical_type}")
    dt = np.dtype(_PHYS_NP[physical_type]).newbyteorder("<")
    return np.frombuffer(payload, dt, count=num_values)


def decode_data_page(payload: bytes, page: PageInfo, physical_type: str,
                     max_def: int, dictionary: Optional[np.ndarray],
                     device=None):
    """One v1 data page → (values np.ndarray, valid bool ndarray)."""
    pos = 0
    n = page.num_values
    defined = np.ones(n, bool)
    if max_def > 0:
        # def levels: 4-byte LE length + hybrid at
        # bit_length(max_def); a value is present only at the FULL
        # definition level (nested struct ancestors add levels)
        dw = max(1, int(max_def).bit_length())
        (dl_len,) = struct.unpack_from("<i", payload, pos)
        pos += 4
        druns, _ = parse_hybrid(payload, pos, dw, n, end=pos + dl_len)
        levels = materialize_runs(druns, device)
        defined = levels == max_def
        pos += dl_len
    n_present = int(defined.sum())
    if page.encoding in (_ENC_RLE_DICT, _ENC_PLAIN_DICT):
        if dictionary is None:
            raise DecodeUnsupported("dict-encoded page without dict")
        w = payload[pos]
        pos += 1
        if w > 32:
            raise DecodeUnsupported(f"index width {w}")
        iruns, _ = parse_hybrid(payload, pos, w, n_present)
        idx = materialize_runs(iruns, device)
        present = dictionary[idx]
    elif page.encoding == _ENC_PLAIN:
        if physical_type == "BOOLEAN":
            # PLAIN booleans ARE the bit-packed stream at width 1
            if n_present == 0:  # e.g. the column is all-null in a page
                present = np.zeros(0, bool)
            else:
                nbytes = -(-n_present // 8)
                seg = payload[pos:pos + nbytes]
                padded = seg + b"\x00" * (-len(seg) % 4)
                words = np.frombuffer(padded, np.uint32)
                from delta_tpu.ops.pallas_kernels import unpack_bitpacked

                groups = -(-n_present // 32)
                bits = np.asarray(unpack_bitpacked(words, 1, groups,
                                                   device=device))
                present = bits[:n_present].astype(bool)
        elif physical_type in _PHYS_NP:
            dt = np.dtype(_PHYS_NP[physical_type]).newbyteorder("<")
            present = np.frombuffer(payload, dt, count=n_present,
                                    offset=pos)
        else:
            raise DecodeUnsupported(f"plain physical {physical_type}")
    else:
        raise DecodeUnsupported(f"encoding {page.encoding}")
    if max_def == 0 or defined.all():
        return np.asarray(present), defined
    out = np.zeros(n, np.asarray(present).dtype)
    out[defined] = present
    return out, defined


def decode_column_chunk(chunk: bytes, physical_type: str, codec: str,
                        max_def: int, device=None):
    """Decode one column chunk (dictionary page + v1 data pages) into
    (values, valid). Raises DecodeUnsupported outside scope."""
    pages = split_pages(chunk)
    dictionary = None
    vals: List[np.ndarray] = []
    valids: List[np.ndarray] = []
    for page in pages:
        if page.type == _PAGE_DICT:
            payload = _decompress(chunk, page, codec)
            dictionary = decode_dictionary(payload, page.num_values,
                                           physical_type)
        elif page.type == _PAGE_DATA:
            payload = _decompress(chunk, page, codec)
            v, ok = decode_data_page(payload, page, physical_type,
                                     max_def, dictionary, device)
            vals.append(v)
            valids.append(ok)
    if not vals:
        raise DecodeUnsupported("no data pages")
    return np.concatenate(vals), np.concatenate(valids)


def _decode_file_column(pf, f, column: str, device=None):
    """Decode one column given an already-parsed ParquetFile and open
    handle (the footer is parsed ONCE per file, not per column)."""
    md = pf.metadata
    schema = md.schema
    col_idx = None
    for i in range(len(schema)):
        if schema.column(i).path == column:
            col_idx = i
            break
    if col_idx is None:
        raise DecodeUnsupported(f"column {column} not found")
    sc = schema.column(col_idx)
    max_def = sc.max_definition_level
    if sc.max_repetition_level != 0:
        raise DecodeUnsupported("repeated column")
    out_vals: List[np.ndarray] = []
    out_valid: List[np.ndarray] = []
    for rg in range(md.num_row_groups):
        col = md.row_group(rg).column(col_idx)
        start = col.data_page_offset
        if col.dictionary_page_offset is not None:
            start = min(start, col.dictionary_page_offset)
        f.seek(start)
        chunk = f.read(col.total_compressed_size)
        v, ok = decode_column_chunk(
            chunk, col.physical_type, col.compression, max_def,
            device)
        out_vals.append(v)
        out_valid.append(ok)
    return np.concatenate(out_vals), np.concatenate(out_valid)


def read_checkpoint_column(path: str, column: str, device=None):
    """Decode one flat column of a checkpoint Parquet file through the
    device page decoder. Returns (values, valid). The file footer is
    read via pyarrow METADATA only (offsets/types); all page bytes
    decode through this module + the Pallas kernel."""
    import pyarrow.parquet as pq

    pf = pq.ParquetFile(path)
    with open(path, "rb") as f:
        return _decode_file_column(pf, f, column, device)


DEVICE_COLUMNS = ("add.size", "add.modificationTime", "add.dataChange")


def read_checkpoint_part_hybrid(path: str, device=None):
    """Read a checkpoint part with the device page decoder handling the
    hot numeric add columns and Arrow handling the rest, grafted into
    one table identical to a plain Arrow read. None -> caller falls
    back to the Arrow reader (shape outside the decoder's scope)."""
    import pyarrow as pa
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    try:
        pf = pq.ParquetFile(path)
        schema = pf.metadata.schema
        leaves = [schema.column(i).path for i in range(len(schema))]
        targets = [c for c in DEVICE_COLUMNS if c in leaves]
        if not targets:
            return None
        decoded = {}
        with open(path, "rb") as f:
            for col in targets:
                decoded[col] = _decode_file_column(pf, f, col, device)
        rest = [c for c in leaves if c not in targets]
        tbl = pf.read(columns=rest)
        add_idx = tbl.column_names.index("add")
        add = tbl.column("add").combine_chunks()
        names = [f.name for f in add.type]
        children = {n: add.field(i) for i, n in enumerate(names)}
        for col in targets:
            vals, valid = decoded[col]
            leaf = col.split(".", 1)[1]
            children[leaf] = pa.array(vals, mask=~valid)
        # restore the file's field order from the Arrow schema (the
        # leaf-path list loses the order of nested children)
        arrow_add = pf.schema_arrow.field("add").type
        order = [f.name for f in arrow_add]
        order += [n for n in children if n not in order]
        arrays = [children[n] for n in order if n in children]
        new_add = pa.StructArray.from_arrays(
            arrays, [n for n in order if n in children],
            mask=pc.is_null(add))
        return tbl.set_column(add_idx, "add", new_add)
    except DecodeUnsupported:
        return None
    # delta-lint: disable=except-swallow (audited: the native decoder is
    # an accelerator with a byte-identical Arrow fallback — any surprise
    # must select the fallback, never fail the read)
    except Exception:
        return None  # any surprise -> Arrow fallback, never a failure

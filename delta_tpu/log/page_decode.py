"""Host side of the device checkpoint-page decoder (SURVEY §7 hard
part (d)).

The reference hand-rolls its own Parquet reader precisely because page
decode sits on its replay hot path
(`kernel/kernel-defaults/src/main/java/io/delta/kernel/defaults/internal/parquet/ParquetFileReader.java`).
This module is the TPU-native counterpart for the checkpoint's
projected columns (add.size, add.modificationTime, add.dataChange,
add.path / remove.path as replay keys, ...):

- host: thrift compact-protocol PageHeader parse (hand-rolled from the
  parquet-format spec), page decompression, and the tiny varint run
  headers of the RLE/bit-packed hybrid — everything O(pages), nothing
  O(values);
- device: the O(bytes) work, batched into ONE dispatch per part — all
  page payloads pack into a single padded uint8 byte lane with int32
  run/page plans, and `ops/page_decode.py::decode_part` extracts every
  hybrid position, expands def-levels, and gathers dictionary / PLAIN
  values in one launch.

Scope (DecodeUnsupported → caller falls back to the Arrow reader):
data page v1, SNAPPY / ZSTD / uncompressed, non-repeated columns
(struct nesting adds definition levels and is handled; lists/maps are
not), PLAIN / RLE_DICTIONARY values, physical INT32/INT64/DOUBLE/
BOOLEAN — plus dictionary-coded BYTE_ARRAY for the two replay-key path
columns, whose part-local codes stay device-resident for the replay
handoff (`ops/page_decode.py::launch_checkpoint_handoff`).
"""
# delta-lint: file-disable=shared-state-race — audited:
# _Thrift and _PlanState are function-local decode cursors: constructed
# inside the decode call, never stored or returned, so no two threads
# ever see the same instance.

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


class DecodeUnsupported(Exception):
    """Shape/encoding outside the decoder's scope — use the fallback."""


# ------------------------------------------------ thrift compact read --

_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12


class _Thrift:
    """Minimal thrift compact-protocol reader: varints, zigzag ints,
    struct field iteration, and recursive skipping of what we don't
    model (statistics, crc...)."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_struct(self) -> dict:
        """field id -> python value (structs become dicts, unmodeled
        types are skipped with a None placeholder)."""
        out = {}
        fid = 0
        while True:
            head = self.buf[self.pos]
            self.pos += 1
            if head == _CT_STOP:
                return out
            delta = head >> 4
            ctype = head & 0x0F
            fid = fid + delta if delta else self.zigzag()
            out[fid] = self._read_value(ctype)

    def _read_value(self, ctype: int):
        if ctype == _CT_TRUE:
            return True
        if ctype == _CT_FALSE:
            return False
        if ctype == _CT_BYTE:
            v = self.buf[self.pos]
            self.pos += 1
            return v
        if ctype in (_CT_I16, _CT_I32, _CT_I64):
            return self.zigzag()
        if ctype == _CT_DOUBLE:
            v = struct.unpack_from("<d", self.buf, self.pos)[0]
            self.pos += 8
            return v
        if ctype == _CT_BINARY:
            n = self.varint()
            v = self.buf[self.pos:self.pos + n]
            self.pos += n
            return v
        if ctype == _CT_STRUCT:
            return self.read_struct()
        if ctype in (_CT_LIST, _CT_SET):
            head = self.buf[self.pos]
            self.pos += 1
            size = head >> 4
            elem = head & 0x0F
            if size == 15:
                size = self.varint()
            return [self._read_value(elem) for _ in range(size)]
        if ctype == _CT_MAP:
            size = self.varint()
            if size == 0:
                return {}
            kv = self.buf[self.pos]
            self.pos += 1
            kt, vt = kv >> 4, kv & 0x0F
            return {self._read_value(kt): self._read_value(vt)
                    for _ in range(size)}
        raise DecodeUnsupported(f"thrift type {ctype}")


# page types (parquet-format PageType)
_PAGE_DATA = 0
_PAGE_DICT = 2
_PAGE_DATA_V2 = 3

# encodings
_ENC_PLAIN = 0
_ENC_PLAIN_DICT = 2
_ENC_RLE = 3
_ENC_RLE_DICT = 8


@dataclass
class PageInfo:
    type: int
    uncompressed_size: int
    compressed_size: int
    num_values: int
    encoding: int
    payload_start: int  # offset of the (compressed) payload in the chunk


def split_pages(chunk: bytes) -> List[PageInfo]:
    """Host page splitting: walk the chunk's PageHeaders."""
    pages = []
    pos = 0
    while pos < len(chunk):
        t = _Thrift(chunk, pos)
        hdr = t.read_struct()
        ptype = hdr.get(1)
        if ptype is None:
            break
        if ptype == _PAGE_DATA:
            dph = hdr.get(5) or {}
            nv, enc = dph.get(1, 0), dph.get(2, _ENC_PLAIN)
        elif ptype == _PAGE_DICT:
            dph = hdr.get(7) or {}
            nv, enc = dph.get(1, 0), dph.get(2, _ENC_PLAIN)
        elif ptype == _PAGE_DATA_V2:
            raise DecodeUnsupported("data page v2")
        else:
            nv, enc = 0, _ENC_PLAIN
        pages.append(PageInfo(ptype, hdr.get(2, 0), hdr.get(3, 0),
                              nv, enc, t.pos))
        pos = t.pos + hdr.get(3, 0)
    return pages


_CODECS = {"SNAPPY": "snappy", "ZSTD": "zstd"}


def _decompress(chunk: bytes, page: PageInfo, codec: str) -> bytes:
    """Page payload bytes. EVERY codec outside the supported set raises
    DecodeUnsupported so the caller takes the whole-part Arrow fallback
    — including a supported name whose codec wasn't built into this
    pyarrow."""
    raw = chunk[page.payload_start:page.payload_start
                + page.compressed_size]
    if codec in ("UNCOMPRESSED", "NONE"):
        return raw
    name = _CODECS.get(codec)
    if name is None:
        raise DecodeUnsupported(f"codec {codec}")
    import pyarrow as pa

    if not pa.Codec.is_available(name):
        raise DecodeUnsupported(f"codec {codec} not available")
    return pa.Codec(name).decompress(
        raw, decompressed_size=page.uncompressed_size).to_pybytes()


# ------------------------------------------- RLE/bit-packed hybrid ----

@dataclass
class HybridRuns:
    """Parsed hybrid stream: RLE runs as (value, count), bit-packed runs
    as (out_start, n_values, word blocks). Host-side reference form —
    the hot path plans runs into the device byte lane instead
    (`_plan_hybrid`)."""

    n: int
    w: int = 0  # bit width (set by parse_hybrid)
    rle: List[Tuple[int, int, int]] = field(default_factory=list)
    # per bit-packed run: (out_start, n_values, words[G, ...] flat)
    packed: List[Tuple[int, int, np.ndarray]] = field(
        default_factory=list)


def parse_hybrid(data: bytes, pos: int, w: int, n: int,
                 end: Optional[int] = None) -> Tuple[HybridRuns, int]:
    """Parse the RLE/bit-packed hybrid stream for `n` values at bit
    width `w` starting at `pos`. Returns (runs, next_pos)."""
    if not isinstance(w, int) or not 0 <= w <= 32:
        raise DecodeUnsupported(f"hybrid bit width {w!r} outside [0, 32]")
    runs = HybridRuns(n, w)
    out = 0
    byte_w = (w + 7) // 8
    limit = len(data) if end is None else end
    t = _Thrift(data, pos)
    while out < n and t.pos < limit:
        header = t.varint()
        if header & 1:  # bit-packed: (header >> 1) groups of 8
            groups8 = header >> 1
            nvals = groups8 * 8
            nbytes = groups8 * w
            seg = data[t.pos:t.pos + nbytes]
            t.pos += nbytes
            padded = seg + b"\x00" * (-len(seg) % 4)
            words = np.frombuffer(padded, np.uint32)
            runs.packed.append((out, min(nvals, n - out), words))
            out += nvals
        else:  # RLE: value repeated (header >> 1) times
            count = header >> 1
            vbytes = data[t.pos:t.pos + byte_w]
            t.pos += byte_w
            value = int.from_bytes(vbytes, "little")
            runs.rle.append((out, min(count, n - out), value))
            out += count
    if out < n:
        raise DecodeUnsupported(f"hybrid stream ended early ({out}/{n})")
    return runs, t.pos


def materialize_runs(runs: HybridRuns, device=None) -> np.ndarray:
    """Expand a hybrid stream to uint32[n] host-side: the numpy
    reference twin of the device extract (validation and cold paths).
    The hot path never expands on host — it ships run PLANS in the
    one-lane batch instead (`build_part_plan` + `ops/page_decode.py`).
    `device` is accepted for API compatibility and ignored."""
    del device
    out = np.zeros(runs.n, np.uint32)
    for start, count, value in runs.rle:
        out[start:start + count] = value
    w = runs.w
    if runs.packed and not (isinstance(w, int) and 0 <= w <= 32):
        # guards callers that build HybridRuns directly; w outside the
        # extract's domain means a corrupt page, not a decoder bug
        raise DecodeUnsupported(f"bit-packed width {w!r} outside [0, 32]")
    for start, nv, words in runs.packed:
        nv = min(nv, runs.n - start)
        if nv <= 0 or w == 0:
            continue
        bits = np.unpackbits(words.view(np.uint8), bitorder="little")
        need = nv * w
        if bits.size < need:
            bits = np.concatenate(
                [bits, np.zeros(need - bits.size, np.uint8)])
        weights = np.uint32(1) << np.arange(w, dtype=np.uint32)
        out[start:start + nv] = (
            bits[:need].reshape(nv, w).astype(np.uint64) * weights
        ).sum(axis=1).astype(np.uint32)
    return out


# ------------------------------------------------- the one-lane plan --

_PHYS_NP = {"INT32": np.int32, "INT64": np.int64, "DOUBLE": np.float64}
_PHYS_ITEM = {"INT32": 4, "INT64": 8, "DOUBLE": 8, "BOOLEAN": 1}


@dataclass
class _PlanState:
    """Mutable accumulator while planning one part: byte-lane segments
    plus the run/page plan rows (layout documented in
    `ops/page_decode.py`), with running hybrid/row counters."""

    segs: List[bytes] = field(default_factory=list)
    lane_len: int = 0
    runs: List[Tuple[int, ...]] = field(default_factory=list)
    pages: List[Tuple[int, ...]] = field(default_factory=list)
    h: int = 0
    rows: int = 0

    def append(self, b: bytes) -> int:
        """Append a byte segment to the lane, returning its offset."""
        from delta_tpu.ops.page_decode import MAX_LANE_BYTES

        off = self.lane_len
        self.segs.append(b)
        self.lane_len += len(b)
        if self.lane_len > MAX_LANE_BYTES:
            # bit offsets must fit int32 on device
            raise DecodeUnsupported("part byte lane over cap")
        return off

    def snapshot(self):
        return (len(self.segs), self.lane_len, len(self.runs),
                len(self.pages), self.h, self.rows)

    def restore(self, snap) -> None:
        n_segs, lane_len, n_runs, n_pages, h, rows = snap
        del self.segs[n_segs:]
        self.lane_len = lane_len
        del self.runs[n_runs:]
        del self.pages[n_pages:]
        self.h = h
        self.rows = rows


def _plan_hybrid(st: _PlanState, base_off: int, data: bytes, pos: int,
                 w: int, n: int, end: Optional[int] = None,
                 strict: bool = True) -> int:
    """Walk one hybrid stream's run headers WITHOUT expanding: each run
    becomes a plan row carrying its absolute lane bit offset. Reserves
    exactly `n` hybrid positions (the device addresses values as
    stream-start + logical index). `strict=False` tolerates a stream
    that ends before `n` values — dictionary-index and boolean streams
    are sized by the page's num_values upper bound, but only carry the
    page's PRESENT values, a count the host never computes."""
    if not isinstance(w, int) or not 0 <= w <= 32:
        raise DecodeUnsupported(f"hybrid bit width {w!r} outside [0, 32]")
    h0 = st.h
    out = 0
    byte_w = (w + 7) // 8
    limit = len(data) if end is None else end
    t = _Thrift(data, pos)
    while out < n and t.pos < limit:
        header = t.varint()
        if header & 1:  # bit-packed: (header >> 1) groups of 8
            groups8 = header >> 1
            nvals = groups8 * 8
            st.runs.append((h0 + out, nvals, 8 * (base_off + t.pos),
                            w, 0, 0))
            t.pos += groups8 * w
            out += nvals
        else:  # RLE: value repeated (header >> 1) times
            count = header >> 1
            value = int.from_bytes(data[t.pos:t.pos + byte_w], "little")
            t.pos += byte_w
            v32 = value & 0xFFFFFFFF
            if v32 >= 1 << 31:
                v32 -= 1 << 32  # int32 bit pattern for the plan lane
            st.runs.append((h0 + out, count, 0, w, 1, v32))
            out += count
    if strict and out < n:
        raise DecodeUnsupported(f"hybrid stream ended early ({out}/{n})")
    st.h = h0 + n
    return t.pos


def _parse_byte_array_dict(payload: bytes, num_values: int
                           ) -> List[bytes]:
    """PLAIN dictionary page of a BYTE_ARRAY column:
    [4-byte LE length][bytes] per entry."""
    out = []
    pos = 0
    for _ in range(num_values):
        (ln,) = struct.unpack_from("<i", payload, pos)
        pos += 4
        if ln < 0 or pos + ln > len(payload):
            raise DecodeUnsupported("corrupt byte-array dictionary")
        out.append(payload[pos:pos + ln])
        pos += ln
    return out


def _plan_column_chunk(st: _PlanState, chunk: bytes, phys: str,
                       codec: str, max_def: int, key: int,
                       part_dict: Dict[bytes, int],
                       uniq: List[bytes]) -> None:
    """Plan one column chunk's pages into the global lane. `key` is the
    KEY_* flag: for key columns the dictionary page is parsed host-side
    into the part-local path dictionary (shared across add/remove) and
    only the tiny int32 remap table enters the lane."""
    from delta_tpu.ops.page_decode import KIND_BOOL, KIND_DICT, KIND_PLAIN

    dict_b = dict_n = 0
    have_dict = False
    item = 4 if key else _PHYS_ITEM[phys]
    for page in split_pages(chunk):
        if page.type == _PAGE_DICT:
            payload = _decompress(chunk, page, codec)
            if key:
                local = _parse_byte_array_dict(payload, page.num_values)
                remap = np.empty(max(len(local), 1), np.int32)
                for j, b in enumerate(local):
                    code = part_dict.setdefault(b, len(part_dict))
                    if code == len(uniq):
                        uniq.append(b)
                    remap[j] = code
                dict_b = st.append(remap.tobytes())
                dict_n = len(local)
            else:
                if phys not in _PHYS_NP:
                    raise DecodeUnsupported(f"dict physical {phys}")
                dict_b = st.append(payload)
                dict_n = page.num_values
            have_dict = True
        elif page.type == _PAGE_DATA:
            payload = _decompress(chunk, page, codec)
            off = st.append(payload)
            n = page.num_values
            pos = 0
            def_h = 0
            if max_def > 0:
                # def levels: 4-byte LE length + hybrid at
                # bit_length(max_def); a value is present only at the
                # FULL definition level
                dw = max(1, int(max_def).bit_length())
                (dl_len,) = struct.unpack_from("<i", payload, pos)
                pos += 4
                def_h = st.h
                _plan_hybrid(st, off, payload, pos, dw, n,
                             end=pos + dl_len, strict=True)
                pos += dl_len
            if page.encoding in (_ENC_RLE_DICT, _ENC_PLAIN_DICT):
                if not have_dict:
                    raise DecodeUnsupported(
                        "dict-encoded page without dict")
                w = payload[pos]
                if w > 32:
                    raise DecodeUnsupported(f"index width {w}")
                aux_h = st.h
                _plan_hybrid(st, off, payload, pos + 1, w, n,
                             strict=False)
                kind, val_b = KIND_DICT, 0
            elif page.encoding == _ENC_PLAIN:
                if key:
                    # PLAIN BYTE_ARRAY is variable-width — no device
                    # plan; the caller drops just this key column
                    raise DecodeUnsupported("plain-encoded key column")
                if phys == "BOOLEAN":
                    # PLAIN booleans ARE a width-1 bit-packed stream
                    aux_h = st.h
                    st.runs.append((st.h, n, 8 * (off + pos), 1, 0, 0))
                    st.h += n
                    kind, val_b = KIND_BOOL, 0
                elif phys in _PHYS_NP:
                    kind, val_b, aux_h = KIND_PLAIN, off + pos, 0
                else:
                    raise DecodeUnsupported(f"plain physical {phys}")
            else:
                raise DecodeUnsupported(f"encoding {page.encoding}")
            st.pages.append((st.rows, n, max_def, def_h, kind, val_b,
                             item, aux_h, dict_b, dict_n, key))
            st.rows += n


# ------------------------------------------------- part plan + read ----

DEVICE_COLUMNS = ("add.size", "add.modificationTime", "add.dataChange")

# planned when present; the add columns above are the gate — a part
# without them falls back wholesale
_VALUE_COLUMNS = DEVICE_COLUMNS + ("remove.deletionTimestamp",
                                   "remove.dataChange")
_KEY_COLUMNS = ("add.path", "remove.path")


@dataclass
class _ColSpan:
    """One planned column's slice of the global output row space."""

    name: str
    phys: str
    row_start: int
    n_rows: int
    key: int


def _leaf_index(schema, column: str) -> Optional[int]:
    for i in range(len(schema)):
        if schema.column(i).path == column:
            return i
    return None


def _plan_column(st: _PlanState, pf, data: bytes, col_idx: int,
                 key: int, part_dict: Dict[bytes, int],
                 uniq: List[bytes]) -> _ColSpan:
    """Plan every row group's chunk of one leaf column. Row groups are
    the inner loop, so a column's rows are CONTIGUOUS in the global row
    space regardless of row-group count."""
    md = pf.metadata
    sc = md.schema.column(col_idx)
    if sc.max_repetition_level != 0:
        raise DecodeUnsupported("repeated column")
    phys = sc.physical_type
    if key:
        if phys != "BYTE_ARRAY" or sc.max_definition_level != 2:
            raise DecodeUnsupported("key column shape")
    elif phys not in _PHYS_ITEM:
        raise DecodeUnsupported(f"physical {phys}")
    row_start = st.rows
    try:
        for rg in range(md.num_row_groups):
            col = md.row_group(rg).column(col_idx)
            start = col.data_page_offset
            if col.dictionary_page_offset is not None:
                start = min(start, col.dictionary_page_offset)
            chunk = data[start:start + col.total_compressed_size]
            _plan_column_chunk(st, chunk, phys, col.compression,
                               sc.max_definition_level, key,
                               part_dict, uniq)
    except (IndexError, struct.error) as e:
        raise DecodeUnsupported(f"corrupt page stream: {e}") from e
    return _ColSpan(sc.path, phys, row_start, st.rows - row_start, key)


def build_part_plan(pf, data: bytes, value_cols: List[str],
                    key_cols: List[str]):
    """Build the one-lane decode plan for a checkpoint part: all pages
    of the projected columns packed into one padded uint8 lane plus
    int32 run/page plans (`ops/page_decode.py.PartPlan`).

    Value-column failures propagate (whole-part Arrow fallback, digest
    parity by construction); a KEY column that can't be planned (PLAIN
    pages from a dictionary overflow, odd nesting...) is rolled back via
    snapshot/restore and simply dropped — the part still decodes its
    numeric columns on device, only the replay handoff is off.

    Returns (plan, spans, uniq, dropped_keys)."""
    from delta_tpu.ops.page_decode import (
        KEY_ADD, KEY_REMOVE, PAGE_F, RUN_F, PartPlan, _FAR)
    from delta_tpu.ops.replay import pad_bucket

    st = _PlanState()
    part_dict: Dict[bytes, int] = {}
    uniq: List[bytes] = []
    spans: List[_ColSpan] = []
    dropped_keys: List[str] = []
    schema = pf.metadata.schema
    for name in value_cols:
        idx = _leaf_index(schema, name)
        if idx is None:
            raise DecodeUnsupported(f"column {name} not found")
        spans.append(_plan_column(st, pf, data, idx, 0, part_dict, uniq))
    for name in key_cols:
        idx = _leaf_index(schema, name)
        if idx is None:
            continue
        key = KEY_ADD if name.startswith("add.") else KEY_REMOVE
        snap = st.snapshot()
        n_uniq = len(uniq)
        try:
            spans.append(_plan_column(st, pf, data, idx, key,
                                      part_dict, uniq))
        except DecodeUnsupported:
            st.restore(snap)
            for b in uniq[n_uniq:]:
                del part_dict[b]
            del uniq[n_uniq:]
            dropped_keys.append(name)
    if not st.pages:
        raise DecodeUnsupported("no data pages")
    plan = _pack_plan(st, has_keys=any(s.key for s in spans))
    return plan, spans, uniq, dropped_keys


def _pack_plan(st: _PlanState, has_keys: bool):
    """Pad the accumulated plan state into a PartPlan: lane to the byte
    bucket, run/page plans to small buckets with searchsorted-safe
    sentinel starts on the pad rows."""
    from delta_tpu.ops.page_decode import PAGE_F, RUN_F, PartPlan, _FAR
    from delta_tpu.ops.replay import pad_bucket

    lane = np.zeros(pad_bucket(max(st.lane_len, 1)), np.uint8)
    if st.lane_len:
        lane[:st.lane_len] = np.frombuffer(b"".join(st.segs), np.uint8)
    runs = np.zeros((pad_bucket(len(st.runs), min_bucket=128), RUN_F),
                    np.int32)
    runs[len(st.runs):, 0] = _FAR  # pad runs sort after every real h
    if st.runs:
        runs[:len(st.runs)] = np.asarray(st.runs, np.int32)
    pages = np.zeros((pad_bucket(len(st.pages), min_bucket=128), PAGE_F),
                     np.int32)
    pages[len(st.pages):, 0] = _FAR
    pages[:len(st.pages)] = np.asarray(st.pages, np.int32)
    return PartPlan(lane=lane, runs=runs, pages=pages, h_total=st.h,
                    n_rows=st.rows, has_keys=has_keys)


def _combine_values(phys: str, lo: np.ndarray, hi: np.ndarray
                    ) -> np.ndarray:
    """Two u32 device lanes -> the column's numpy values (the decode jit
    stays x32-clean for Mosaic; widening happens here)."""
    if phys == "BOOLEAN":
        return lo.astype(bool)
    if phys == "INT32":
        return lo.view(np.int32)
    u = lo.astype(np.uint64) | (hi.astype(np.uint64) << np.uint64(32))
    return u.view(np.int64) if phys == "INT64" else u.view(np.float64)


def read_checkpoint_column(path: str, column: str, device=None):
    """Decode one flat column of a checkpoint Parquet file through the
    device page decoder (one plan, one dispatch). Returns
    (values, valid). The file footer is read via pyarrow METADATA only
    (offsets/types); all page bytes decode through the one-lane plan."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from delta_tpu.ops.page_decode import decode_part

    with open(path, "rb") as f:
        data = f.read()
    pf = pq.ParquetFile(pa.BufferReader(data))
    idx = _leaf_index(pf.metadata.schema, column)
    if idx is None:
        raise DecodeUnsupported(f"column {column} not found")
    st = _PlanState()
    span = _plan_column(st, pf, data, idx, 0, {}, [])
    if not st.pages:
        raise DecodeUnsupported("no data pages")
    plan = _pack_plan(st, has_keys=False)
    lo, hi, defined, _keys = decode_part(plan, device)
    sl = slice(span.row_start, span.row_start + span.n_rows)
    return _combine_values(span.phys, lo[sl], hi[sl]), defined[sl]


def _graft_struct(tbl, pf, root: str, decoded):
    """Replace `root`'s decoded children inside the Arrow-read table,
    restoring the file's field order from the Arrow schema (the
    leaf-path list loses the order of nested children)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    idx = tbl.column_names.index(root)
    col = tbl.column(root).combine_chunks()
    names = [f.name for f in col.type]
    children = {n: col.field(i) for i, n in enumerate(names)}
    children.update(decoded)
    arrow_root = pf.schema_arrow.field(root).type
    order = [f.name for f in arrow_root]
    order += [n for n in children if n not in order]
    present = [n for n in order if n in children]
    new_col = pa.StructArray.from_arrays(
        [children[n] for n in present], present, mask=pc.is_null(col))
    return tbl.set_column(idx, root, new_col)


def read_checkpoint_part_device(source, device=None, want_keys=True):
    """Read a checkpoint part with the device page decoder handling the
    projected hot columns in ONE dispatch and Arrow handling the rest,
    grafted into a table identical to a plain Arrow read. `source` is a
    path or the part's raw bytes (the pipeline prefetches bytes).

    Returns (table, PartKeys-or-None); PartKeys carries the part's
    device-resident replay-key code lane when both path columns planned
    cleanly. None -> caller falls back to the Arrow reader (shape
    outside the decoder's scope)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from delta_tpu.ops.page_decode import PartKeys, decode_part

    if isinstance(source, (bytes, bytearray, memoryview)):
        data = bytes(source)
    else:
        with open(source, "rb") as f:
            data = f.read()
    # the native-decoder probe: plan + dispatch. Anything outside scope
    # raises DecodeUnsupported; a genuine surprise must also select the
    # byte-identical Arrow fallback rather than fail the read — but the
    # suppression stops HERE: graft/assembly errors below raise.
    try:
        pf = pq.ParquetFile(pa.BufferReader(data))
        schema = pf.metadata.schema
        leaves = [schema.column(i).path for i in range(len(schema))]
        if not any(c in leaves for c in DEVICE_COLUMNS):
            return None
        if pf.metadata.num_rows == 0:
            # nothing to decode and nothing to replay: zero dispatches
            return pf.read(), PartKeys(None, 0, 0, 0, [], 0)
        value_cols = [c for c in _VALUE_COLUMNS if c in leaves]
        key_cols = [c for c in _KEY_COLUMNS
                    if want_keys and c in leaves
                    and _key_arrow_ok(pf, c)]
        plan, spans, uniq, _dropped = build_part_plan(
            pf, data, value_cols, key_cols)
        rest = [c for c in leaves
                if c not in {s.name for s in spans}]
        for root in {s.name.split(".", 1)[0] for s in spans}:
            if not any(c.startswith(root + ".") for c in rest):
                # the graft needs the Arrow-read root for struct
                # validity; a fully-planned root has no carrier
                raise DecodeUnsupported(f"no arrow leaf under {root}")
        lo, hi, defined, keys = decode_part(plan, device)
    except DecodeUnsupported:
        return None
    # delta-lint: disable=except-swallow (audited: the native decoder is
    # an accelerator with a byte-identical Arrow fallback — any surprise
    # in the probe must select the fallback, never fail the read)
    except Exception:
        return None

    tbl = pf.read(columns=rest)
    by_root: Dict[str, Dict[str, object]] = {}
    for s in spans:
        root, leaf = s.name.split(".", 1)
        sl = slice(s.row_start, s.row_start + s.n_rows)
        valid = defined[sl]
        if s.key:
            codes = pa.array(lo[sl].view(np.int32), mask=~valid)
            pool = pa.array([b.decode("utf-8") for b in uniq],
                            pa.string())
            arr = pa.DictionaryArray.from_arrays(codes, pool).cast(
                pa.string())
        else:
            arr = pa.array(_combine_values(s.phys, lo[sl], hi[sl]),
                           mask=~valid)
        by_root.setdefault(root, {})[leaf] = arr
    for root, decoded in by_root.items():
        tbl = _graft_struct(tbl, pf, root, decoded)
    if keys is not None:
        keys.uniq = uniq
        keys.n_rows = pf.metadata.num_rows
    return tbl, keys


def _key_arrow_ok(pf, column: str) -> bool:
    """The replay-key rebuild requires the path leaf be a plain utf8
    string directly under its root struct in the Arrow schema."""
    import pyarrow as pa

    root, leaf = column.split(".", 1)
    try:
        rt = pf.schema_arrow.field(root).type
        ft = rt.field(leaf).type
    except KeyError:
        return False
    return pa.types.is_struct(rt) and pa.types.is_string(ft)


def read_checkpoint_part_hybrid(path: str, device=None):
    """Compatibility wrapper: the grafted table only (no replay keys).
    None -> caller falls back to the Arrow reader."""
    out = read_checkpoint_part_device(path, device, want_keys=False)
    return None if out is None else out[0]

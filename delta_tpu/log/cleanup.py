"""Metadata cleanup (expire old commits) and log compaction.

- `cleanup_expired_logs`: delete commit/checkpoint files older than
  `delta.logRetentionDuration` that are shadowed by a newer checkpoint
  (reference `MetadataCleanup.scala:64,155`; never deletes past the most
  recent complete checkpoint — reconstructability invariant).
- `write_compacted_delta`: write `<lo>.<hi>.compacted.json` containing
  the reconciled actions of the commit range (PROTOCOL.md:270); listing
  substitutes it for the singles (delta_tpu.log.segment._apply_compaction).
"""

from __future__ import annotations

import time
from typing import List, Optional

from delta_tpu.errors import DeltaError, InvalidArgumentError
from delta_tpu.models.actions import (
    Action,
    AddFile,
    CommitInfo,
    DomainMetadata,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
    actions_from_commit_bytes,
    actions_to_commit_bytes,
)
from delta_tpu.utils import filenames
from delta_tpu.utils.filenames import CheckpointInstance, group_complete_checkpoints


def cleanup_expired_logs(
    table,
    retention_ms: Optional[int] = None,
    now_ms: Optional[int] = None,
) -> List[str]:
    """Delete expired, checkpoint-shadowed log files. Returns deleted paths."""
    from delta_tpu.config import (
        CHECKPOINT_RETENTION,
        LOG_RETENTION,
        get_table_config,
    )

    engine = table.engine
    snap = table.latest_snapshot()
    explicit_retention = retention_ms is not None
    if retention_ms is None:
        retention_ms = get_table_config(snap.metadata.configuration, LOG_RETENTION)
    now = now_ms if now_ms is not None else int(time.time() * 1000)
    cutoff = now - retention_ms
    # shadowed checkpoints expire on their own (usually shorter) clock:
    # delta.checkpointRetentionDuration (2 days default) vs the 30-day
    # commit retention. An explicitly passed retention overrides both
    # directions — a caller guaranteeing a week of time travel must not
    # lose 3-day-old checkpoints to the table default.
    if explicit_retention:
        cp_cutoff = cutoff
    else:
        cp_retention = get_table_config(
            snap.metadata.configuration, CHECKPOINT_RETENTION)
        cp_cutoff = max(cutoff, now - cp_retention)

    listing = list(engine.fs.list_from(filenames.listing_prefix(table.log_path, 0)))
    checkpoints = [
        ci for f in listing
        if (ci := CheckpointInstance.parse(f.path)) is not None
    ]
    complete = group_complete_checkpoints(checkpoints)
    if not complete:
        return []  # nothing shadowed; keep everything
    newest_cp_version = complete[-1][0].version

    deleted = []
    for f in listing:
        name = filenames.file_name(f.path)
        version = None
        if filenames.DELTA_FILE_RE.match(name):
            version = filenames.delta_version(f.path)
        elif filenames.CHECKSUM_FILE_RE.match(name):
            version = filenames.checksum_version(f.path)
        elif filenames.COMPACTED_DELTA_FILE_RE.match(name):
            _, version = filenames.compacted_delta_versions(f.path)
        file_cutoff = cutoff
        if filenames.CHECKPOINT_FILE_RE.match(name):
            version = filenames.checkpoint_version(f.path)
            if version >= newest_cp_version:
                continue  # never delete the active checkpoint
            file_cutoff = cp_cutoff
        if version is None:
            continue
        if version < newest_cp_version and f.modification_time < file_cutoff:
            try:
                engine.fs.delete(f.path)
                deleted.append(f.path)
            except FileNotFoundError:
                pass
    return deleted


def write_compacted_delta(table, from_version: int, to_version: int) -> str:
    """Reconcile commits [from, to] into one compacted file."""
    if to_version <= from_version:
        raise InvalidArgumentError(
            "compaction range must span at least two commits",
            error_class="DELTA_COMPACTION_RANGE_TOO_SMALL")
    engine = table.engine
    # Sequential reconciliation of the range (small: it's a commit range,
    # not a full table state).
    protocol = None
    metadata = None
    txns = {}
    domains = {}
    adds = {}
    removes = {}
    for v in range(from_version, to_version + 1):
        data = engine.fs.read_file(filenames.delta_file(table.log_path, v))
        for a in actions_from_commit_bytes(data):
            if isinstance(a, Protocol):
                protocol = a
            elif isinstance(a, Metadata):
                metadata = a
            elif isinstance(a, SetTransaction):
                txns[a.appId] = a
            elif isinstance(a, DomainMetadata):
                domains[a.domain] = a
            elif isinstance(a, AddFile):
                key = (a.path, a.dv_unique_id)
                removes.pop(key, None)
                adds[key] = a
            elif isinstance(a, RemoveFile):
                key = (a.path, a.dv_unique_id)
                adds.pop(key, None)
                removes[key] = a
    out: List[Action] = []
    if protocol is not None:
        out.append(protocol)
    if metadata is not None:
        out.append(metadata)
    out.extend(txns.values())
    out.extend(domains.values())
    out.extend(removes.values())
    out.extend(adds.values())
    path = filenames.compacted_delta_file(table.log_path, from_version, to_version)
    engine.json.write_json_file_atomically(
        path, actions_to_commit_bytes(out), overwrite=False
    )
    return path

from delta_tpu.log.segment import LogSegment, build_log_segment
from delta_tpu.log.last_checkpoint import LastCheckpointInfo

__all__ = ["LogSegment", "build_log_segment", "LastCheckpointInfo"]

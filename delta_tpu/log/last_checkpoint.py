"""`_last_checkpoint` pointer file.

A small JSON document naming the most recent checkpoint so readers can
start their LIST there instead of at version 0 (PROTOCOL.md:318; reference
`spark/.../delta/Checkpoints.scala:601` LastCheckpointInfo schema, kernel
`internal/checkpoints/CheckpointMetaData.java`). Always written with
overwrite=True — it is a hint, and a stale or corrupt pointer must degrade
to a full listing, never to an error.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, Optional

from delta_tpu.utils import filenames


@dataclass
class LastCheckpointInfo:
    version: int
    size: int                       # number of actions in the checkpoint
    parts: Optional[int] = None     # multi-part only
    sizeInBytes: Optional[int] = None
    numOfAddFiles: Optional[int] = None
    checkpointSchema: Optional[Dict[str, Any]] = None
    checksum: Optional[str] = None
    tag: Optional[str] = None       # V2: the UUID-named top-level file name

    def to_json(self) -> str:
        d = {"version": self.version, "size": self.size}
        for k in ("parts", "sizeInBytes", "numOfAddFiles", "checkpointSchema", "checksum", "tag"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return json.dumps(d, separators=(",", ":"))

    @staticmethod
    def from_json(data: bytes | str) -> "LastCheckpointInfo":
        d = json.loads(data)
        return LastCheckpointInfo(
            version=int(d["version"]),
            size=int(d.get("size", -1)),
            parts=(int(d["parts"]) if d.get("parts") is not None else None),
            sizeInBytes=d.get("sizeInBytes"),
            numOfAddFiles=d.get("numOfAddFiles"),
            checkpointSchema=d.get("checkpointSchema"),
            checksum=d.get("checksum"),
            tag=d.get("tag"),
        )


def read_last_checkpoint(fs, log_path: str) -> Optional[LastCheckpointInfo]:
    """Best-effort read; any failure returns None (degrade to listing)."""
    path = filenames.last_checkpoint_file(log_path)
    try:
        return LastCheckpointInfo.from_json(fs.read_file(path))
    except (FileNotFoundError, ValueError, KeyError):
        return None


def write_last_checkpoint(json_handler, log_path: str, info: LastCheckpointInfo) -> None:
    path = filenames.last_checkpoint_file(log_path)
    json_handler.write_json_file_atomically(
        path, info.to_json().encode("utf-8"), overwrite=True
    )

"""`_last_checkpoint` pointer file.

A small JSON document naming the most recent checkpoint so readers can
start their LIST there instead of at version 0 (PROTOCOL.md:318; reference
`spark/.../delta/Checkpoints.scala:601` LastCheckpointInfo schema, kernel
`internal/checkpoints/CheckpointMetaData.java`). Always written with
overwrite=True — it is a hint, and a stale or corrupt pointer must degrade
to a full listing, never to an error.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass
from typing import Any, Dict, Optional

from delta_tpu import obs
from delta_tpu.utils import filenames

_log = logging.getLogger(__name__)

_HINT_WRITE_FAILURES = obs.counter("log.hint_write_failures")


@dataclass
class LastCheckpointInfo:
    version: int
    size: int                       # number of actions in the checkpoint
    parts: Optional[int] = None     # multi-part only
    sizeInBytes: Optional[int] = None
    numOfAddFiles: Optional[int] = None
    checkpointSchema: Optional[Dict[str, Any]] = None
    checksum: Optional[str] = None
    tag: Optional[str] = None       # V2: the UUID-named top-level file name
    # Incremental-writer part manifest: {"writerFp": config fingerprint,
    # "parts": [{"name", "fp", "rows", "bytes", "mtime"}, ...]} — lets
    # the NEXT checkpoint reuse byte-identical parts/sidecars instead of
    # rewriting them (log/checkpointer.py). Purely an accelerator rider
    # on the hint: readers ignore it, a missing/stale manifest degrades
    # to a full rewrite, and from_json's unknown-key tolerance keeps old
    # readers compatible.
    partManifest: Optional[Dict[str, Any]] = None

    def to_json(self) -> str:
        d = {"version": self.version, "size": self.size}
        for k in ("parts", "sizeInBytes", "numOfAddFiles", "checkpointSchema", "checksum", "tag", "partManifest"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return json.dumps(d, separators=(",", ":"))

    @staticmethod
    def from_json(data: bytes | str) -> "LastCheckpointInfo":
        d = json.loads(data)
        return LastCheckpointInfo(
            version=int(d["version"]),
            size=int(d.get("size", -1)),
            parts=(int(d["parts"]) if d.get("parts") is not None else None),
            sizeInBytes=d.get("sizeInBytes"),
            numOfAddFiles=d.get("numOfAddFiles"),
            checkpointSchema=d.get("checkpointSchema"),
            checksum=d.get("checksum"),
            tag=d.get("tag"),
            partManifest=d.get("partManifest"),
        )


def read_last_checkpoint(fs, log_path: str) -> Optional[LastCheckpointInfo]:
    """Best-effort read; any failure returns None (degrade to listing)."""
    path = filenames.last_checkpoint_file(log_path)
    try:
        return LastCheckpointInfo.from_json(fs.read_file(path))
    except (FileNotFoundError, ValueError, KeyError):
        return None


def write_last_checkpoint(json_handler, log_path: str, info: LastCheckpointInfo) -> None:
    """Best-effort write, mirroring the reference (`Checkpoints.scala`
    logs and swallows hint-write failures): the checkpoint itself is
    durable at this point, and a missing/stale hint only costs readers a
    longer listing — failing the checkpoint over it would be strictly
    worse."""
    path = filenames.last_checkpoint_file(log_path)
    # delta-lint: disable=except-swallow (audited: the hint is an
    # accelerator — its write failure is counted and logged, never
    # allowed to fail the durable checkpoint that precedes it)
    try:
        json_handler.write_json_file_atomically(
            path, info.to_json().encode("utf-8"), overwrite=True
        )
    except Exception as e:
        _HINT_WRITE_FAILURES.inc()
        _log.warning("_last_checkpoint hint write failed for %s (%s); "
                     "readers will list from an older hint or version 0",
                     log_path, e)

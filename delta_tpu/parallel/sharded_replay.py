"""Sharded snapshot state reconstruction over a device mesh.

This is the TPU-native counterpart of the reference's distributed replay
(`Snapshot.scala:481-511`): shuffle by path hash, per-partition
reconcile. Here:

1. HOST ROUTE — rows are binned by `key % n_shards` (the "shuffle"; a
   stable numpy argsort by shard id, so each shard's rows stay in
   chronological order and the in-shard row index is the chronological
   rank). Because the replay key determines its shard, per-shard
   reconciliation is globally correct with zero cross-device key
   exchange.
2. DEVICE — a [n_shards, bucket] batch is laid out with
   `NamedSharding(mesh, P('shard', None))`; under `shard_map` each device
   runs the same (key, chrono) sort + run-boundary last-wins reduce as
   the single-chip kernel on its local rows, then contributes to global
   aggregates (live-file count, total bytes) with `psum` over the ICI.
3. HOST GATHER — per-shard masks come back and are scattered to the
   original row order. Padding rows never reach the output (their
   scatter index is -1) and contribute zero to the aggregates (is_add
   False, size 0), so no validity lane ships at all.

Multi-host scale-out: the mesh spans hosts; each host routes only the
rows it parsed (`jax.make_array_from_process_local_data`), the psum
rides ICI within a pod and DCN across pods — no NCCL/MPI analogue
needed, XLA owns the collectives.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from delta_tpu.ops.replay import _PAD_KEY, chrono_ok, combine_key_lanes, pad_bucket
from delta_tpu.parallel.mesh import REPLAY_AXIS, make_mesh


class ShardedReplayOut(NamedTuple):
    live: jax.Array        # [S, M] bool
    tombstone: jax.Array   # [S, M] bool
    num_live: jax.Array    # [] int32, global (psum over shards)
    live_bytes: jax.Array  # [] float32, global


def _shard_kernel(key, is_add, size):
    """Per-device replay over its local [1, M] shard block. Rows arrive
    in chronological order (stable routing), so the local iota is the
    chronological tiebreaker."""
    key, is_add, size = key[0], is_add[0], size[0]
    m = key.shape[0]
    idx = jnp.arange(m, dtype=jnp.int32)
    s_key, s_idx, s_add, s_size = lax.sort(
        (key, idx, is_add, size), num_keys=2, is_stable=False
    )
    is_last = jnp.concatenate([s_key[:-1] != s_key[1:], jnp.ones((1,), bool)])
    live_s = is_last & s_add
    tomb_s = is_last & ~s_add
    live = jnp.zeros((m,), bool).at[s_idx].set(live_s)
    tomb = jnp.zeros((m,), bool).at[s_idx].set(tomb_s)
    # global aggregates over the ICI (padding rows: add=False, size=0)
    local_live = jnp.sum(live_s.astype(jnp.int32))
    local_bytes = jnp.sum(jnp.where(live_s, s_size, 0.0))
    num_live = lax.psum(local_live, REPLAY_AXIS)
    live_bytes = lax.psum(local_bytes, REPLAY_AXIS)
    return live[None], tomb[None], num_live, live_bytes


def build_sharded_replay_fn(mesh: Mesh):
    """jit'd [S, M]-batch replay over `mesh` (S = mesh size)."""
    spec = P(REPLAY_AXIS, None)
    fn = shard_map(
        _shard_kernel,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec, P(), P()),
    )
    return jax.jit(fn)


def route_to_shards(
    path_key: np.ndarray,
    dv_key: np.ndarray,
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    size: Optional[np.ndarray],
    n_shards: int,
):
    """Host-side shuffle: returns ([S, M] operand arrays (key, is_add,
    size), scatter indexes) where scatter_index[s, j] = original row (or
    -1 for padding)."""
    n = len(path_key)
    # perm=None in the common chronological case avoids three O(n) copies
    perm = None
    if not chrono_ok(np.asarray(version), np.asarray(order)):
        perm = np.lexsort((order, version)).astype(np.int64)
    key = combine_key_lanes([path_key, dv_key])
    if key is None:
        # lanes too wide to combine: re-encode to dense uint32 codes via a
        # 64-bit fold + np.unique (exact; a single routing batch never
        # holds 2^32 distinct logical files). Dense codes also keep every
        # real key below the 0xFFFFFFFF pad sentinel — the kernel relies
        # on pads owning that key exclusively for aggregate correctness.
        wide = path_key.astype(np.uint64) << np.uint64(32) | dv_key.astype(np.uint64)
        _, key = np.unique(wide, return_inverse=True)
        key = key.astype(np.uint32)
    is_add = np.asarray(is_add, bool)
    size_p = None if size is None else np.asarray(size)
    if perm is not None:
        key = key[perm]
        is_add = is_add[perm]
        size_p = None if size_p is None else size_p[perm]

    shard_of = (key % np.uint32(n_shards)).astype(np.int64)
    sort_idx = np.argsort(shard_of, kind="stable")
    counts = np.bincount(shard_of, minlength=n_shards)
    m = pad_bucket(int(counts.max(initial=1)))

    k = np.full((n_shards, m), _PAD_KEY, dtype=np.uint32)
    add = np.zeros((n_shards, m), dtype=np.bool_)
    sz = np.zeros((n_shards, m), dtype=np.float32)
    scatter = np.full((n_shards, m), -1, dtype=np.int32)

    starts = np.zeros(n_shards + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    rows = shard_of[sort_idx]
    cols = np.arange(n) - starts[rows]
    k[rows, cols] = key[sort_idx]
    add[rows, cols] = is_add[sort_idx]
    if size_p is not None:
        sz[rows, cols] = size_p[sort_idx].astype(np.float32)
    orig = sort_idx if perm is None else perm[sort_idx]
    scatter[rows, cols] = orig.astype(np.int32)
    return (k, add, sz), scatter


def sharded_replay_select(
    path_key: np.ndarray,
    dv_key: np.ndarray,
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    size: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
) -> tuple[np.ndarray, np.ndarray, int, int]:
    """Full pipeline; returns (live_mask, tomb_mask, num_live, live_bytes)
    in original row order."""
    if mesh is None:
        mesh = make_mesh()
    n = len(path_key)
    if n == 0:
        z = np.zeros(0, bool)
        return z, z, 0, 0
    n_shards = mesh.devices.size
    operands, scatter = route_to_shards(
        path_key, dv_key, version, order, is_add, size, n_shards
    )
    spec = NamedSharding(mesh, P(REPLAY_AXIS, None))
    device_ops = tuple(jax.device_put(o, spec) for o in operands)
    fn = _cached_fn(mesh)
    live_sh, tomb_sh, num_live, live_bytes = fn(*device_ops)
    live_sh = np.asarray(live_sh)
    tomb_sh = np.asarray(tomb_sh)
    live = np.zeros(n, dtype=bool)
    tomb = np.zeros(n, dtype=bool)
    flat_scatter = scatter.ravel()
    sel = flat_scatter >= 0
    live[flat_scatter[sel]] = live_sh.ravel()[sel]
    tomb[flat_scatter[sel]] = tomb_sh.ravel()[sel]
    return live, tomb, int(num_live), int(live_bytes)


@functools.lru_cache(maxsize=8)
def _sharded_fn_for(mesh_key):
    return build_sharded_replay_fn(mesh_key[0])


def _cached_fn(mesh: Mesh):
    return _sharded_fn_for((mesh,))


def sharded_replay_step(mesh: Mesh):
    """The framework's "training step" equivalent for dry-run compilation:
    one jitted function that takes the routed [S, M] batch and returns
    masks + global aggregates, sharded over `mesh`."""
    return build_sharded_replay_fn(mesh)

"""Sharded snapshot state reconstruction over a device mesh.

This is the TPU-native counterpart of the reference's distributed replay
(`Snapshot.scala:481-511`): shuffle by path hash, per-partition
reconcile. Here:

1. HOST ROUTE — rows are binned by `path_key % n_shards` (the
   "shuffle"; a stable numpy argsort by shard id, so each shard's rows
   stay in chronological order and the in-shard row index is the
   chronological rank). The key fully determines its shard, so
   per-shard reconciliation is globally correct with zero cross-device
   key exchange. Rows sharing a path (any DV id) land together.
2. TRANSFER — the same first-appearance delta coding as the
   single-chip kernel (`ops/replay.py`), per shard. The trick that
   makes it free: global path codes are dense first-appearance codes,
   so shard s's local code for path c ≡ s (mod S) is exactly c // S —
   itself a dense first-appearance coding of the shard's stream. The
   global `is_new` flags route through unchanged; explicit refs ship as
   byte planes; the DV lane ships sparse; is_add ships bit-packed.
   ~1-2 bits/row crosses the link instead of 9 bytes/row.
3. DEVICE — under `shard_map` each device rebuilds its local code
   lane with a cumsum + gather, runs the same (key, chrono) sort +
   run-boundary last-wins reduce as the single-chip kernel, and
   contributes to global aggregates (live-file count, live bytes) with
   `psum` over the ICI. Winner masks come home bit-packed (32x smaller
   D2H).
4. HOST GATHER — per-shard winner words are unpacked, split into
   live/tombstone with the host-resident add bits, and scattered back
   to the original row order.

Streams that aren't first-appearance-coded (host-hashed keys, permuted
histories) fall back to shipping raw u32 key lanes — same kernel tail,
fatter transfer.

Multi-host scale-out: the mesh spans hosts; each host routes only the
rows it parsed (`jax.make_array_from_process_local_data`), the psum
rides ICI within a pod and DCN across pods — no NCCL/MPI analogue
needed, XLA owns the collectives. See tests/test_multiprocess.py for
the 2-process jax.distributed harness.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from delta_tpu import obs
from delta_tpu.ops.replay import (
    _PAD_KEY,
    _decode_planes,
    _sort_winner_pack,
    _unpack_bits,
    _unpack_bits_device,
    chrono_ok,
    derive_fa_flags,
    key_byte_width,
    pad_bucket,
)
from delta_tpu.parallel.mesh import REPLAY_AXIS, make_mesh

# Same counter as the single-chip launch path (ops/replay.py): total
# replay operand bytes shipped host->device, read by the residency
# tests and the bench transfer accounting.
_H2D_BYTES = obs.counter("replay.h2d_bytes")


# --------------------------------------------------------------- raw path


def _shard_kernel(key, is_add, size):
    """Per-device replay over its local [1, M] shard block. Rows arrive
    in chronological order (stable routing), so the local iota is the
    chronological tiebreaker."""
    key, is_add, size = key[0], is_add[0], size[0]
    m = key.shape[0]
    idx = jnp.arange(m, dtype=jnp.int32)
    s_key, s_idx, s_add, s_size = lax.sort(
        (key, idx, is_add, size), num_keys=2, is_stable=False
    )
    is_last = jnp.concatenate([s_key[:-1] != s_key[1:], jnp.ones((1,), bool)])
    live_s = is_last & s_add
    tomb_s = is_last & ~s_add
    live = jnp.zeros((m,), bool).at[s_idx].set(live_s)
    tomb = jnp.zeros((m,), bool).at[s_idx].set(tomb_s)
    # global aggregates over the ICI (padding rows: add=False, size=0)
    local_live = jnp.sum(live_s.astype(jnp.int32))
    local_bytes = jnp.sum(jnp.where(live_s, s_size, 0.0))
    num_live = lax.psum(local_live, REPLAY_AXIS)
    live_bytes = lax.psum(local_bytes, REPLAY_AXIS)
    return live[None], tomb[None], num_live, live_bytes


def build_sharded_replay_fn(mesh: Mesh):
    """jit'd [S, M]-batch replay over `mesh` (S = mesh size) — raw-key
    operands (uint32 key, bool add, f32 size)."""
    spec = P(REPLAY_AXIS, None)
    fn = shard_map(
        _shard_kernel,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec, P(), P()),
    )
    return jax.jit(fn)


def route_to_shards(
    path_key: np.ndarray,
    dv_key: np.ndarray,
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    size: Optional[np.ndarray],
    n_shards: int,
):
    """Host-side shuffle for the raw path: returns ([S, M] operand
    arrays (key, is_add, size), scatter indexes) where
    scatter_index[s, j] = original row (or -1 for padding)."""
    n = len(path_key)
    # perm=None in the common chronological case avoids three O(n) copies
    perm = None
    if not chrono_ok(np.asarray(version), np.asarray(order)):
        perm = np.lexsort((order, version)).astype(np.int64)
    key = _combined_u32(path_key, dv_key)
    is_add = np.asarray(is_add, bool)
    size_p = None if size is None else np.asarray(size)
    if perm is not None:
        key = key[perm]
        is_add = is_add[perm]
        size_p = None if size_p is None else size_p[perm]

    shard_of = (key % np.uint32(n_shards)).astype(np.int64)
    sort_idx, rows, cols, counts, m = _shard_coords(shard_of, n_shards)

    k = np.full((n_shards, m), _PAD_KEY, dtype=np.uint32)
    add = np.zeros((n_shards, m), dtype=np.bool_)
    sz = np.zeros((n_shards, m), dtype=np.float32)
    scatter = np.full((n_shards, m), -1, dtype=np.int32)

    k[rows, cols] = key[sort_idx]
    add[rows, cols] = is_add[sort_idx]
    if size_p is not None:
        sz[rows, cols] = size_p[sort_idx].astype(np.float32)
    orig = sort_idx if perm is None else perm[sort_idx]
    scatter[rows, cols] = orig.astype(np.int32)
    return (k, add, sz), scatter


def _combined_u32(path_key: np.ndarray, dv_key: np.ndarray) -> np.ndarray:
    """Combined (path, dv) -> one dense uint32 lane below the pad
    sentinel (re-encoding through np.unique when the radix product
    overflows)."""
    from delta_tpu.ops.replay import combine_key_lanes

    key = combine_key_lanes([path_key, dv_key])
    if key is None:
        wide = path_key.astype(np.uint64) << np.uint64(32) | dv_key.astype(
            np.uint64)
        _, key = np.unique(wide, return_inverse=True)
        key = key.astype(np.uint32)
    return key


def _shard_coords(shard_of: np.ndarray, n_shards: int):
    """(sort_idx, rows, cols, counts, padded bucket M) of the stable
    shard sort."""
    n = len(shard_of)
    sort_idx = np.argsort(shard_of, kind="stable")
    counts = np.bincount(shard_of, minlength=n_shards)
    m = pad_bucket(int(counts.max(initial=1)))
    starts = np.zeros(n_shards + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    rows = shard_of[sort_idx]
    cols = np.arange(n) - starts[rows]
    return sort_idx, rows, cols, counts, m


# ---------------------------------------------------------------- FA path


class ShardedFAOperands(NamedTuple):
    """Routed, delta-coded device operands + host bookkeeping."""
    flag_words: np.ndarray        # [S, M/32] u32 is_new bits
    ref_planes: tuple             # each [S, R] u8 (little-endian planes)
    sub_radix: int                # DV lane radix (1 = no DV anywhere)
    sub_idx: np.ndarray           # [S, D] u32 in-shard rows (pad 0xFFFFFFFF)
    sub_val: np.ndarray           # [S, D] u32
    n_real: np.ndarray            # [S, 1] i32 rows per shard
    add_words: np.ndarray         # [S, M/32] u32 is_add bits
    scatter: np.ndarray           # [S, M] i32 original row (-1 = pad)
    m: int
    nbytes: int                   # H2D payload bytes (transfer accounting)


def route_to_shards_fa(
    path_key: np.ndarray,
    dv_key: np.ndarray,
    is_new: np.ndarray,
    is_add: np.ndarray,
    n_shards: int,
) -> Optional[ShardedFAOperands]:
    """FA-coded routing (chronological input required — caller permutes
    first). Returns None when ranges don't fit (caller falls back to the
    raw route)."""
    n = len(path_key)
    path_key = np.asarray(path_key, np.uint32)
    dv_key = np.asarray(dv_key, np.uint32)
    n_uniq = (int(path_key.max()) + 1) if n else 0
    local_max = (n_uniq - 1) // n_shards if n_uniq else 0
    sub_radix = int(dv_key.max(initial=0)) + 1
    # the device key is local_code * sub_radix + dv; keep the pad
    # sentinel exclusive
    if (local_max + 1) * sub_radix >= 0xFFFFFFFF:
        return None

    shard_of = (path_key % np.uint32(n_shards)).astype(np.int64)
    sort_idx, rows, cols, counts, m = _shard_coords(shard_of, n_shards)

    # is_new flags route through unchanged (a globally-new path is new
    # in its shard; refs always target a path first seen in the SAME
    # shard because routing is by path)
    sorted_new = np.asarray(is_new, bool)[sort_idx]
    flags = np.zeros((n_shards, m), dtype=np.bool_)
    flags[rows, cols] = sorted_new
    flag_words = np.packbits(flags, axis=1, bitorder="little").view(np.uint32)

    add = np.zeros((n_shards, m), dtype=np.bool_)
    add[rows, cols] = np.asarray(is_add, bool)[sort_idx]
    add_words = np.packbits(add, axis=1, bitorder="little").view(np.uint32)

    # explicit refs: non-new rows, local code = global code // S, in
    # shard-stream order (the stable sort preserves it)
    ref_rows = rows[~sorted_new]
    ref_vals = (path_key[sort_idx][~sorted_new] //
                np.uint32(n_shards)).astype(np.uint32)
    ref_counts = np.bincount(ref_rows, minlength=n_shards)
    r_pad = pad_bucket(int(ref_counts.max(initial=1)), min_bucket=128)
    ref_width = key_byte_width(local_max)
    refs2d = np.zeros((n_shards, r_pad), dtype=np.uint32)
    ref_starts = np.zeros(n_shards + 1, dtype=np.int64)
    np.cumsum(ref_counts, out=ref_starts[1:])
    ref_cols = np.arange(len(ref_vals)) - ref_starts[ref_rows]
    refs2d[ref_rows, ref_cols] = ref_vals
    rbytes = refs2d.view(np.uint8).reshape(n_shards, r_pad, 4)
    ref_planes = tuple(
        np.ascontiguousarray(rbytes[:, :, j]) for j in range(ref_width))

    # DV lane: sparse (in-shard row, value); pad rows scatter-drop
    if sub_radix > 1:
        dv_sorted = dv_key[sort_idx]
        nz = dv_sorted != 0
        nz_rows = rows[nz]
        nz_counts = np.bincount(nz_rows, minlength=n_shards)
        d_pad = pad_bucket(int(nz_counts.max(initial=1)), min_bucket=128)
        sub_idx = np.full((n_shards, d_pad), 0xFFFFFFFF, dtype=np.uint32)
        sub_val = np.zeros((n_shards, d_pad), dtype=np.uint32)
        nz_starts = np.zeros(n_shards + 1, dtype=np.int64)
        np.cumsum(nz_counts, out=nz_starts[1:])
        nz_cols = np.arange(int(nz.sum())) - nz_starts[nz_rows]
        sub_idx[nz_rows, nz_cols] = cols[nz].astype(np.uint32)
        sub_val[nz_rows, nz_cols] = dv_sorted[nz]
    else:
        sub_idx = np.empty((n_shards, 0), dtype=np.uint32)
        sub_val = np.empty((n_shards, 0), dtype=np.uint32)

    scatter = np.full((n_shards, m), -1, dtype=np.int32)
    scatter[rows, cols] = sort_idx.astype(np.int32)

    n_real = counts.astype(np.int32).reshape(n_shards, 1)
    nbytes = (flag_words.nbytes + sum(p.nbytes for p in ref_planes)
              + sub_idx.nbytes + sub_val.nbytes + n_real.nbytes
              + add_words.nbytes)
    return ShardedFAOperands(flag_words, ref_planes, sub_radix, sub_idx,
                             sub_val, n_real, add_words, scatter,
                             m, nbytes)


def _shard_kernel_fa(ref_width: int, has_sub: bool, want_key: bool = False):
    """Kernel body factory for the FA-coded sharded replay. With
    `want_key` the rebuilt per-shard key lane is returned as a third
    output so the caller can keep it device-resident across
    `Snapshot.update()` calls (parallel/resident.py) — the lane already
    exists on device, so residency costs zero extra transfer."""

    def kernel(*ops):
        flag_words = ops[0][0]
        ref_planes = tuple(o[0] for o in ops[1:1 + ref_width])
        rest = ops[1 + ref_width:]
        if has_sub:
            sub_radix, sub_idx, sub_val = (rest[0], rest[1][0], rest[2][0])
            rest = rest[3:]
        n_real = rest[0][0][0]
        add_words = rest[1][0]

        m = flag_words.shape[0] * 32
        is_new = _unpack_bits_device(flag_words)
        new_rank = jnp.cumsum(is_new.astype(jnp.int32))
        ref_rank = jnp.arange(1, m + 1, dtype=jnp.int32) - new_rank
        refs = _decode_planes(ref_planes)
        ref_gather = refs[jnp.clip(ref_rank - 1, 0, refs.shape[0] - 1)]
        key = jnp.where(is_new == 1, (new_rank - 1).astype(jnp.uint32),
                        ref_gather)
        if has_sub:
            sub = jnp.zeros((m,), jnp.uint32).at[sub_idx].set(
                sub_val, mode="drop")
            key = key * sub_radix + sub
        iota = jnp.arange(m, dtype=jnp.int32)
        key = jnp.where(iota < n_real, key, jnp.uint32(0xFFFFFFFF))

        winner_words = _sort_winner_pack((key,), n_real)
        live_words = winner_words & add_words
        live_bits = _unpack_bits_device(live_words)
        local_live = jnp.sum(live_bits.astype(jnp.int32))
        # the only cross-device exchange in the whole replay: one scalar
        # psum over the ICI (int32 — exact)
        num_live = lax.psum(local_live, REPLAY_AXIS)
        if want_key:
            return winner_words[None], num_live, key[None]
        return winner_words[None], num_live

    return kernel


@functools.lru_cache(maxsize=32)
def _fa_fn_cached(mesh: Mesh, ref_width: int, has_sub: bool,
                  want_key: bool = False):
    spec = P(REPLAY_AXIS, None)
    in_specs = [spec]                       # flag_words
    in_specs += [spec] * ref_width          # ref planes
    if has_sub:
        in_specs += [P(), spec, spec]       # sub_radix (replicated), idx, val
    in_specs += [spec, spec]                # n_real, add_words
    out_specs = (spec, P(), spec) if want_key else (spec, P())
    fn = shard_map(
        _shard_kernel_fa(ref_width, has_sub, want_key),
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=out_specs,
    )
    return jax.jit(fn)


def build_sharded_replay_fa_fn(mesh: Mesh, ref_width: int, has_sub: bool,
                               want_key: bool = False):
    return _fa_fn_cached(mesh, ref_width, has_sub, want_key)


# ------------------------------------------------------------ public API


class ResidentPayload(NamedTuple):
    """Everything `parallel/resident.py` needs to keep a sharded replay
    device-resident after `sharded_replay_select` returns: the rebuilt
    per-shard key lane (already on device — zero extra transfer) plus
    the host-side routing bookkeeping."""
    key_sh: object                # jax [S, M] u32, NamedSharding over mesh
    mesh: Mesh
    m: int
    n_real: np.ndarray            # [S] i32 rows per shard
    add_words: np.ndarray         # [S, M/32] u32
    scatter: np.ndarray           # [S, M] i32 original row (-1 = pad)
    n: int                        # total real rows
    n_uniq: int                   # dense path-code count (sub_radix == 1)


def sharded_replay_select(
    path_key: np.ndarray,
    dv_key: np.ndarray,
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    size: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
    fa_hint: Optional[tuple] = None,
    resident_sink: Optional[list] = None,
) -> tuple[np.ndarray, np.ndarray, int, int]:
    """Full pipeline; returns (live_mask, tomb_mask, num_live, live_bytes)
    in original row order. `fa_hint` = (is_new flags, refs, n_uniq) from
    the native scanner's in-scan dictionary (refs unused here — the
    sharded route re-derives per-shard refs from the codes).

    `resident_sink`: when the FA route runs with chronological input and
    no DV lane, a `ResidentPayload` is appended so the caller can keep
    the per-shard state device-resident (see parallel/resident.py);
    otherwise the list is left untouched."""
    if mesh is None:
        mesh = make_mesh()
    n = len(path_key)
    if n == 0:
        z = np.zeros(0, bool)
        return z, z, 0, 0
    n_shards = mesh.devices.size

    size_orig = size  # original row order, for the exact host aggregate
    with obs.span("replay.shard_route", rows=n, shards=n_shards):
        perm = None
        if not chrono_ok(np.asarray(version), np.asarray(order)):
            perm = np.lexsort((order, version)).astype(np.int64)
            path_key = np.asarray(path_key)[perm]
            dv_key = np.asarray(dv_key)[perm]
            is_add = np.asarray(is_add)[perm]
            size = None if size is None else np.asarray(size)[perm]
            fa_hint = None  # hint flags were in original row order

        is_new = fa_hint[0] if fa_hint is not None else None
        if is_new is None or len(is_new) != n:
            is_new = derive_fa_flags(np.asarray(path_key))

        fa = None
        if is_new is not None:
            fa = route_to_shards_fa(path_key, dv_key, is_new, is_add,
                                    n_shards)
        if fa is None:
            operands, scatter = route_to_shards(
                path_key, dv_key,
                np.arange(n, dtype=np.int64), np.zeros(n, np.int64),
                is_add, size, n_shards)
    spec = NamedSharding(mesh, P(REPLAY_AXIS, None))
    live_bytes = None
    if fa is not None:
        has_sub = fa.sub_radix > 1
        want_key = (resident_sink is not None and perm is None
                    and not has_sub)
        ops = [fa.flag_words, *fa.ref_planes]
        if has_sub:
            ops += [np.uint32(fa.sub_radix), fa.sub_idx, fa.sub_val]
        ops += [fa.n_real, fa.add_words]
        # the budget entry is non-exhaustive: ref planes and the DV lane
        # are data-dependent and accounted through replay.h2d_bytes; the
        # two committed bitplanes are priced per padded shard row
        fa_rows = n_shards * fa.m
        with obs.device_dispatch("replay.sharded_fa",
                                 key=(n_shards, fa.m, len(fa.ref_planes),
                                      has_sub, want_key),
                                 budget="sharded-replay-fa-plane",
                                 units=fa_rows, gate="replay",
                                 route="sharded") as dd:
            dd.h2d("flag_words", fa.flag_words)
            dd.h2d("add_words", fa.add_words)
            for i, rp in enumerate(fa.ref_planes):
                dd.h2d(f"ref_plane_{i}", rp)
            with obs.span("replay.shard_transfer", nbytes=fa.nbytes,
                          route="fa"):
                _H2D_BYTES.inc(fa.nbytes)
                device_ops = tuple(
                    o if np.isscalar(o) or o.ndim == 0
                    else jax.device_put(o, spec)
                    for o in ops)
            # scalar sub_radix is replicated, not sharded
            fn = build_sharded_replay_fa_fn(mesh, len(fa.ref_planes),
                                            has_sub, want_key)
            with obs.span("replay.shard_reconcile", shards=n_shards,
                          route="fa"):
                if want_key:
                    winner_sh, num_live, key_sh = fn(*device_ops)
                else:
                    winner_sh, num_live = fn(*device_ops)
                winner_words = dd.d2h("winner_words", np.asarray(winner_sh))
        if want_key:
            resident_sink.append(ResidentPayload(
                key_sh=key_sh, mesh=mesh, m=fa.m,
                n_real=fa.n_real.reshape(-1).astype(np.int64),
                add_words=fa.add_words, scatter=fa.scatter, n=n,
                n_uniq=(int(np.asarray(path_key).max()) + 1) if n else 0))
        add_words = fa.add_words
        live_words = winner_words & add_words
        tomb_words = winner_words & ~add_words
        flat_live = _unpack_bits(live_words.ravel(), n_shards * fa.m)
        flat_tomb = _unpack_bits(tomb_words.ravel(), n_shards * fa.m)
        scatter = fa.scatter
        m = fa.m
    else:
        nbytes = sum(int(o.nbytes) for o in operands)
        with obs.device_dispatch("replay.sharded_raw",
                                 key=(n_shards, operands[0].shape[1]),
                                 gate="replay", route="sharded") as dd:
            dd.h2d("operands", nbytes)
            with obs.span("replay.shard_transfer", nbytes=nbytes,
                          route="raw"):
                _H2D_BYTES.inc(nbytes)
                device_ops = tuple(jax.device_put(o, spec)
                                   for o in operands)
            fn = _cached_fn(mesh)
            with obs.span("replay.shard_reconcile", shards=n_shards,
                          route="raw"):
                live_sh, tomb_sh, num_live, live_bytes = fn(*device_ops)
                flat_live = np.asarray(live_sh).ravel()
                flat_tomb = np.asarray(tomb_sh).ravel()
        m = operands[0].shape[1]

    live = np.zeros(n, dtype=bool)
    tomb = np.zeros(n, dtype=bool)
    flat_scatter = scatter.ravel()
    sel = flat_scatter >= 0
    live[flat_scatter[sel]] = flat_live[sel]
    tomb[flat_scatter[sel]] = flat_tomb[sel]
    if perm is not None:
        inv_live = np.zeros(n, dtype=bool)
        inv_tomb = np.zeros(n, dtype=bool)
        inv_live[perm] = live
        inv_tomb[perm] = tomb
        live, tomb = inv_live, inv_tomb

    n_live = int(num_live)
    if size_orig is not None:
        if live_bytes is None:
            # FA route ships no size lane: exact int64 host aggregate
            # (`live` is already back in original row order here)
            bytes_out = int(np.asarray(size_orig)[live].sum())
        else:
            bytes_out = int(live_bytes)  # raw route's f32 device psum
    else:
        bytes_out = 0
    return live, tomb, n_live, bytes_out


@functools.lru_cache(maxsize=8)
def _sharded_fn_for(mesh_key):
    return build_sharded_replay_fn(mesh_key[0])


def _cached_fn(mesh: Mesh):
    return _sharded_fn_for((mesh,))


def sharded_replay_step(mesh: Mesh):
    """The framework's "training step" equivalent for dry-run compilation:
    one jitted function that takes the routed [S, M] batch and returns
    masks + global aggregates, sharded over `mesh`."""
    return build_sharded_replay_fn(mesh)

"""Sharded snapshot state reconstruction over a device mesh.

This is the TPU-native counterpart of the reference's distributed replay
(`Snapshot.scala:481-511`): shuffle by path hash, per-partition
reconcile. Here:

1. HOST ROUTE — rows are binned by `path_key % n_shards` (the "shuffle";
   a numpy argsort by shard id). Because the replay key determines its
   shard, per-shard reconciliation is globally correct with zero
   cross-device key exchange.
2. DEVICE — a [n_shards, bucket] batch is laid out with
   `NamedSharding(mesh, P('shard', None))`; under `shard_map` each device
   runs the same sort + segmented last-wins reduce as the single-chip
   kernel on its local rows, then contributes to global aggregates
   (live-file count, total bytes) with `psum` over the ICI.
3. HOST GATHER — per-shard masks come back and are scattered to the
   original row order.

Multi-host scale-out: the mesh spans hosts; each host routes only the
rows it parsed (`jax.make_array_from_process_local_data`), the psum
rides ICI within a pod and DCN across pods — no NCCL/MPI analogue
needed, XLA owns the collectives.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from delta_tpu.ops.replay import _PAD_KEY, pad_bucket
from delta_tpu.parallel.mesh import REPLAY_AXIS, make_mesh


class ShardedReplayOut(NamedTuple):
    live: jax.Array        # [S, M] bool
    tombstone: jax.Array   # [S, M] bool
    num_live: jax.Array    # [] int32, global (psum over shards)
    live_bytes: jax.Array  # [] float32, global


def _shard_kernel(k0, k1, version, order, is_add, valid, size):
    """Per-device replay over its local [1, M] shard block."""
    k0, k1 = k0[0], k1[0]
    version, order = version[0], order[0]
    is_add, valid, size = is_add[0], valid[0], size[0]
    m = k0.shape[0]
    idx = jnp.arange(m, dtype=jnp.int32)
    s_k0, s_k1, s_ver, s_ord, s_add, s_valid, s_idx = lax.sort(
        (k0, k1, version, order, is_add, valid, idx), num_keys=4
    )
    same_next = (s_k0[:-1] == s_k0[1:]) & (s_k1[:-1] == s_k1[1:])
    is_last = jnp.concatenate([~same_next, jnp.ones((1,), bool)])
    winner = is_last & s_valid
    live_s = winner & s_add
    tomb_s = winner & ~s_add
    live = jnp.zeros((m,), bool).at[s_idx].set(live_s)
    tomb = jnp.zeros((m,), bool).at[s_idx].set(tomb_s)
    # global aggregates over the ICI
    local_live = jnp.sum(live_s.astype(jnp.int32))
    local_bytes = jnp.sum(jnp.where(live, size, 0.0))
    num_live = lax.psum(local_live, REPLAY_AXIS)
    live_bytes = lax.psum(local_bytes, REPLAY_AXIS)
    return live[None], tomb[None], num_live, live_bytes


def build_sharded_replay_fn(mesh: Mesh):
    """jit'd [S, M]-batch replay over `mesh` (S = mesh size)."""
    spec = P(REPLAY_AXIS, None)
    fn = shard_map(
        _shard_kernel,
        mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=(spec, spec, P(), P()),
    )
    return jax.jit(fn)


def route_to_shards(
    path_key: np.ndarray,
    dv_key: np.ndarray,
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    size: Optional[np.ndarray],
    n_shards: int,
):
    """Host-side shuffle: returns ([S, M] operand arrays, scatter indexes)
    where scatter_index[s, j] = original row (or -1 for padding)."""
    n = len(path_key)
    shard_of = (path_key % np.uint32(n_shards)).astype(np.int64)
    sort_idx = np.argsort(shard_of, kind="stable")
    counts = np.bincount(shard_of, minlength=n_shards)
    m = pad_bucket(int(counts.max(initial=1)))

    def mk(dtype, fill):
        return np.full((n_shards, m), fill, dtype=dtype)

    k0 = mk(np.uint32, _PAD_KEY)
    k1 = mk(np.uint32, _PAD_KEY)
    ver = mk(np.int32, -1)
    ordr = mk(np.int32, -1)
    add = mk(np.bool_, False)
    valid = mk(np.bool_, False)
    sz = mk(np.float32, 0.0)
    scatter = mk(np.int32, -1)

    starts = np.zeros(n_shards + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    pos_in_shard = np.arange(n) - starts[shard_of[sort_idx]]
    rows = shard_of[sort_idx]
    cols = pos_in_shard
    k0[rows, cols] = path_key[sort_idx]
    k1[rows, cols] = dv_key[sort_idx]
    ver[rows, cols] = version[sort_idx]
    ordr[rows, cols] = order[sort_idx]
    add[rows, cols] = is_add[sort_idx]
    valid[rows, cols] = True
    if size is not None:
        sz[rows, cols] = size[sort_idx].astype(np.float32)
    scatter[rows, cols] = sort_idx.astype(np.int32)
    return (k0, k1, ver, ordr, add, valid, sz), scatter


def sharded_replay_select(
    path_key: np.ndarray,
    dv_key: np.ndarray,
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    size: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
) -> tuple[np.ndarray, np.ndarray, int, int]:
    """Full pipeline; returns (live_mask, tomb_mask, num_live, live_bytes)
    in original row order."""
    if mesh is None:
        mesh = make_mesh()
    n = len(path_key)
    if n == 0:
        z = np.zeros(0, bool)
        return z, z, 0, 0
    n_shards = mesh.devices.size
    operands, scatter = route_to_shards(
        path_key, dv_key, version, order, is_add, size, n_shards
    )
    spec = NamedSharding(mesh, P(REPLAY_AXIS, None))
    device_ops = tuple(jax.device_put(o, spec) for o in operands)
    fn = _cached_fn(mesh)
    live_sh, tomb_sh, num_live, live_bytes = fn(device_ops)
    live_sh = np.asarray(live_sh)
    tomb_sh = np.asarray(tomb_sh)
    live = np.zeros(n, dtype=bool)
    tomb = np.zeros(n, dtype=bool)
    flat_scatter = scatter.ravel()
    sel = flat_scatter >= 0
    live[flat_scatter[sel]] = live_sh.ravel()[sel]
    tomb[flat_scatter[sel]] = tomb_sh.ravel()[sel]
    return live, tomb, int(num_live), int(live_bytes)


@functools.lru_cache(maxsize=8)
def _sharded_fn_for(mesh_key):
    mesh = mesh_key[0]
    base = build_sharded_replay_fn(mesh)

    def call(ops):
        return base(*ops)

    return call


def _cached_fn(mesh: Mesh):
    return _sharded_fn_for((mesh,))


def sharded_replay_step(mesh: Mesh):
    """The framework's "training step" equivalent for dry-run compilation:
    one jitted function that takes the routed [S, M] batch and returns
    masks + global aggregates, sharded over `mesh`."""
    return build_sharded_replay_fn(mesh)

from delta_tpu.parallel.mesh import make_mesh, replay_mesh_axis
from delta_tpu.parallel.sharded_replay import sharded_replay_select, sharded_replay_step

__all__ = [
    "make_mesh",
    "replay_mesh_axis",
    "sharded_replay_select",
    "sharded_replay_step",
]

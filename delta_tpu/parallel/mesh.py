"""Device meshes for sharded state reconstruction.

The reference distributes replay by `repartition(N, hash(path))` across
Spark executors (`Snapshot.scala:481`). Here the same idea is a
`jax.sharding.Mesh`: rows are routed to shards by path-key, each device
sorts/reduces its shard locally (no cross-device dedup is ever needed —
the key fully determines the shard), and only scalar aggregates cross the
ICI via psum. Multi-host: the same mesh spans processes; shard routing is
identical because the key hash is global.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

REPLAY_AXIS = "shard"


def replay_mesh_axis() -> str:
    return REPLAY_AXIS


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the fastest interconnect ordering of the available
    devices. `n_devices` trims (useful for tests)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (REPLAY_AXIS,))

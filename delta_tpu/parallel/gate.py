"""DEVICE_MERIT-derived profitability gate for the replay product path.

The replay driver has three routes — host-vectorized, single-chip
kernel, and mesh-sharded (`parallel/sharded_replay.py`) — and the right
one depends on the *link*, not the compute: DEVICE_MERIT.json measured
the bench host's host<->device path at ~1.05 GB/s for <=8 MB transfers
but only ~29 MB/s beyond, with a 78 ms round trip. This module turns
those measurements into the routing decision instead of hardcoded row
counts:

- tiny segments are RTT-dominated -> host replay beats any device
  dispatch;
- mid-size segments -> single-chip kernel, with H2D transfers chunked
  to the fast-bucket size (`LinkModel.chunk_bytes`);
- large segments on a >1-device mesh -> sharded replay, where per-shard
  state residency (parallel/resident.py) amortizes the link cost across
  `Snapshot.update()` calls.

The model is loaded from DEVICE_MERIT.json at the repo root when the
default JAX backend is an accelerator; on CPU backends (tests, dev
boxes) transfers are memcpys and the model collapses to "device always
profitable" so behavior is deterministic. Env overrides:

  DELTA_TPU_REPLAY_ROUTE       force "host" | "single" | "sharded"
  DELTA_TPU_SHARDED_MIN_ROWS   row floor for the sharded route
  DELTA_TPU_LINK_MODEL         path to an alternative DEVICE_MERIT json
  DELTA_TPU_LINK_H2D_BPS       flat H2D bandwidth override (bytes/s)
  DELTA_TPU_LINK_RTT_S         round-trip override (seconds)
  DELTA_TPU_H2D_CHUNK          transfer chunk size override (bytes)
  DELTA_TPU_DEVICE_PARSE       force|1|on -> device JSON parse,
                               0|off -> host (parse_route)
  DELTA_TPU_DEVICE_SKIP        force|1|on -> device data skipping,
                               0|off -> host numpy twin (skip_route)
  DELTA_TPU_DEVICE_DECODE      force|1|on -> device checkpoint page
                               decode, 0|off -> Arrow (decode_route)
  DELTA_TPU_DEVICE_SQL         force|1|on -> device SQL operators,
                               0|off -> host pandas (sql_route)
"""

from __future__ import annotations

import functools
import json
import os
from pathlib import Path
from typing import Dict, NamedTuple, Optional

from delta_tpu.obs.device import record_gate_decision
from delta_tpu.obs.registry import counter

# Fallbacks when no DEVICE_MERIT.json is available (same shape as the
# bench host's measurements so the gate degrades to sane behavior).
_FALLBACK_H2D = {8 << 20: 1_050_000_000.0, 64 << 20: 29_000_000.0}
_FALLBACK_RTT_S = 0.078
# replay_fa workload calibration fallbacks: host-vectorized replay rate
# and device compute rate (rows/s) when the json carries no workloads.
_FALLBACK_HOST_ROWS_S = 17e6
_FALLBACK_DEVICE_ROWS_S = 170e6

# Sharding below this many rows never pays on a single host: the host
# routing pass (stable shard argsort) costs more than the per-shard sort
# saving. Overridable; the sharded tests force it down to exercise the
# mesh on tiny logs, bench artifacts record where the real crossover is.
DEFAULT_SHARDED_MIN_ROWS = 4_000_000

# FA delta coding ships ~2 bits/row of flags plus byte-packed refs for
# the non-new minority — ~4 rows/byte is the planning estimate.
_FA_BYTES_PER_ROW = 0.25

# JSON-parse routing estimates: the host C++ field-extraction scan
# measured ~270 MB/s on one vCPU (BASELINE.md r05); the device
# structural scan is planned at ~2 GB/s — both deliberately coarse,
# the gate only needs the crossover's order of magnitude.
_HOST_SCAN_BPS = 270e6
_DEVICE_PARSE_BPS = 2e9

# Checkpoint page-decode routing estimates: the Arrow C++ reader
# decodes checkpoint parts at roughly 900 MB/s of raw page bytes on one
# vCPU; the one-lane device decode is planned at ~3 GB/s (a single
# dispatch whose extract/gather stages are memory-bound). As with the
# parse gate, only the crossover's order of magnitude matters.
_HOST_ARROW_BPS = 900e6
_DEVICE_DECODE_BPS = 3e9

# Data-skipping routing estimates in atom x file cells/s: the host
# numpy twin streams a few int64 compares per cell, the device kernel
# is one fused dispatch over lanes already resident in HBM (the index
# ships once per snapshot version — see stats/device_index.py — so the
# per-scan device cost is one RTT plus the compute).
_HOST_SKIP_CELLS_PS = 50e6
_DEVICE_SKIP_CELLS_PS = 5e9

# SQL operator routing estimates in rows/s, per operator class. The
# host numbers are pandas on one vCPU (merge is hash-probe bound,
# groupby is hash-agg bound, sort_values is comparison bound); the
# device numbers are the `ops/sqlops.py` kernels, whose sorts and
# segment reductions are memory-bound. As with the other gates only
# the crossover's order of magnitude matters — the dominant real-world
# term is the link (`h2d_seconds` over the operand bytes), which is
# what keeps SQL on host across a slow tunnel and on device locally.
_HOST_SQL_ROWS_PS = {"join": 8e6, "group-agg": 20e6, "sort": 15e6}
_DEVICE_SQL_ROWS_PS = {"join": 120e6, "group-agg": 300e6, "sort": 150e6}


class LinkModel(NamedTuple):
    """Host<->device link + replay-rate model used for routing."""

    h2d_bps: dict          # {transfer_size_bytes: bytes_per_s}
    rtt_s: float
    host_rows_per_s: float
    device_rows_per_s: float

    def chunk_bytes(self) -> int:
        """Largest transfer size that still rides the fastest measured
        bandwidth bucket — the H2D chunking quantum."""
        override = os.environ.get("DELTA_TPU_H2D_CHUNK")
        if override:
            return int(override)
        if not self.h2d_bps:
            return 0
        return int(max(self.h2d_bps, key=lambda sz: self.h2d_bps[sz]))

    def h2d_seconds(self, nbytes: int) -> float:
        """Predicted H2D time for `nbytes` shipped in fast-bucket
        chunks (one RTT per dispatch, amortized bandwidth after)."""
        if nbytes <= 0 or not self.h2d_bps:
            return 0.0
        chunk = self.chunk_bytes()
        bps = self.h2d_bps.get(chunk, max(self.h2d_bps.values()))
        return self.rtt_s + nbytes / max(bps, 1.0)


_CPU_MODEL = LinkModel({}, 0.0, _FALLBACK_HOST_ROWS_S, float("inf"))


def _device_platform() -> str:
    try:
        import jax

        return jax.default_backend()
    # delta-lint: disable=except-swallow (audited: backend discovery can
    # fail on hosts with no configured platform; the gate must degrade
    # to the CPU model, never fail routing)
    except Exception:
        return "cpu"


def _model_path() -> Optional[Path]:
    override = os.environ.get("DELTA_TPU_LINK_MODEL")
    if override:
        return Path(override)
    p = Path(__file__).resolve().parents[2] / "DEVICE_MERIT.json"
    return p if p.exists() else None


@functools.lru_cache(maxsize=1)
def link_model() -> LinkModel:
    """The active link model: measured numbers on accelerator backends,
    the trivial (free-transfer) model on CPU backends."""
    if (_device_platform() == "cpu"
            and not os.environ.get("DELTA_TPU_LINK_MODEL")):
        return _CPU_MODEL

    h2d = dict(_FALLBACK_H2D)
    rtt = _FALLBACK_RTT_S
    host_rate = _FALLBACK_HOST_ROWS_S
    dev_rate = _FALLBACK_DEVICE_ROWS_S
    path = _model_path()
    if path is not None:
        try:
            merit = json.loads(path.read_text())
            link = merit.get("link", {})
            raw = link.get("h2d_bytes_per_s") or {}
            if raw:
                h2d = {int(k): float(v) for k, v in raw.items()}
            rtt = float(link.get("rtt_s", rtt))
            fa = merit.get("workloads", {}).get("replay_fa", {})
            n = float(fa.get("n", 0))
            if n and fa.get("t_host_s"):
                host_rate = n / float(fa["t_host_s"])
            if n and fa.get("t_device_compute_s"):
                dev_rate = n / float(fa["t_device_compute_s"])
        except (OSError, ValueError):
            pass  # fall back to the baked-in shape
    bps_env = os.environ.get("DELTA_TPU_LINK_H2D_BPS")
    if bps_env:
        h2d = {self_sz: float(bps_env) for self_sz in (h2d or {8 << 20: 0})}
    rtt_env = os.environ.get("DELTA_TPU_LINK_RTT_S")
    if rtt_env:
        rtt = float(rtt_env)
    return LinkModel(h2d, rtt, host_rate, dev_rate)


def reset_model_cache() -> None:
    """Drop the cached model (tests flip env knobs)."""
    link_model.cache_clear()


def sharded_min_rows() -> int:
    env = os.environ.get("DELTA_TPU_SHARDED_MIN_ROWS")
    if env:
        return int(env)
    return DEFAULT_SHARDED_MIN_ROWS


class RouteSpec(NamedTuple):
    """Declared contract surface of one gated device route."""

    env: str               # override knob the route function reads
    fallback_counter: str  # cataloged counter the fallback path bumps
    doc_anchor: str        # docs/architecture.md heading slug (prefix)
    breaker: str           # registry key of the route's circuit breaker


# The route registry: one entry per gate name passed to `_decide`.
# This is the declarative half of the 7-point route contract (host
# twin, fallback + counter, dispatch funnel, budget entry, calibration
# join, env override, capture-conditions stamp); the delta-lint
# `route-contract` pass parses it statically and cross-checks every
# claim against the code, so a new `*_route` function must register
# here — and actually honor the contract — before lint passes. Keep
# values literal: the checker reads the AST, it never imports us.
ROUTES: Dict[str, RouteSpec] = {
    "replay": RouteSpec(
        env="DELTA_TPU_REPLAY_ROUTE",
        fallback_counter="replay.resident_fallbacks",
        doc_anchor="the-profitability-gate",
        breaker="route:replay"),
    "parse": RouteSpec(
        env="DELTA_TPU_DEVICE_PARSE",
        fallback_counter="parse.device_fallbacks",
        doc_anchor="device-json-action-parse",
        breaker="route:parse"),
    "decode": RouteSpec(
        env="DELTA_TPU_DEVICE_DECODE",
        fallback_counter="decode.device_fallbacks",
        doc_anchor="device-checkpoint-page-decode",
        breaker="route:decode"),
    "skip": RouteSpec(
        env="DELTA_TPU_DEVICE_SKIP",
        fallback_counter="scan.device_fallbacks",
        doc_anchor="device-scan-planning",
        breaker="route:skip"),
    "sql": RouteSpec(
        env="DELTA_TPU_DEVICE_SQL",
        fallback_counter="sql.device_fallbacks",
        doc_anchor="device-sql-execution",
        breaker="route:sql"),
}


_ROUTE_FAILURES = counter("gate.route_failures")
_BREAKER_DEGRADES = counter("gate.route_breaker_degrades")


def _route_breaker(gate: str):
    """The circuit breaker guarding one gate's device route (lazy
    import: gate.py must stay importable without the resilience
    package loaded)."""
    from delta_tpu.resilience.breaker import route_breaker_for
    return route_breaker_for(gate)


def _breaker_admit(gate: str, chosen: str, reason: str):
    """Consult the route breaker before committing a device choice.

    Open breaker -> degrade to the host twin ("breaker-open");
    half-open -> admit the decision as the probe ("breaker-probe") —
    the executing site reports the outcome via :func:`route_ok` /
    :func:`route_failed`, and a probe whose caller never reports is
    reclaimed by the breaker after its reset window."""
    from delta_tpu.errors import CircuitOpenError
    from delta_tpu.resilience.breaker import HALF_OPEN
    b = _route_breaker(gate)
    try:
        b.before_call()
    except CircuitOpenError:
        _BREAKER_DEGRADES.inc()
        return "host", "breaker-open"
    if b.state == HALF_OPEN:
        return chosen, "breaker-probe"
    return chosen, reason


def route_ok(gate: str) -> None:
    """Report one successful device-route execution to the gate's
    breaker (closes a half-open probe, clears failure streaks)."""
    _route_breaker(gate).on_success()


def route_failed(gate: str, exc: BaseException) -> str:
    """Report one failed device-route execution; returns the
    classification verdict.

    The exception is routed through `resilience/classify.py`: transient
    verdicts count toward the breaker's trip threshold, permanent ones
    report as success (the device answered; the error is an answer —
    same contract as storage breakers)."""
    from delta_tpu.resilience.classify import TRANSIENT, classify
    verdict = classify(exc)
    _ROUTE_FAILURES.inc()
    b = _route_breaker(gate)
    if verdict == TRANSIENT:
        b.on_failure()
    else:
        b.on_success()
    return verdict


def _decide(gate: str, chosen: str, inputs: Dict[str, object],
            predicted: Optional[Dict[str, float]] = None,
            reason: str = "economics") -> str:
    """Record the decision (obs/device.py joins it with the observed
    execution cost for calibration) and return the chosen route."""
    if chosen != "host" and reason not in ("env", "forced") \
            and inputs.get("op") != "query":
        # env/forced outrank the breaker (explicit operator intent);
        # every economic device choice pays the breaker toll so a
        # poisoned route degrades to its host twin within K failures.
        # The sql "query" spine resolution is exempt: it binds no
        # execution (no route_ok/route_failed ever answers it), so
        # letting it take the half-open probe would wedge the probe
        # slot for a full reset window — the per-operator decisions
        # that follow are the ones that pay the toll.
        chosen, reason = _breaker_admit(gate, chosen, reason)
    record_gate_decision(gate, chosen, inputs, predicted or {}, reason)
    return chosen


def replay_route(
    n_rows: int,
    n_shards: int = 1,
    nbytes_est: Optional[int] = None,
    forced: Optional[str] = None,
) -> str:
    """Pick the replay route: "host", "single", or "sharded".

    `forced` carries caller intent that bypasses the economics (an
    explicitly constructed mesh keeps its sharded semantics); the
    DELTA_TPU_REPLAY_ROUTE env var outranks everything (tests, bench
    lanes). Every decision emits a gate record — inputs, per-route
    predicted seconds, chosen route, reason — for calibration against
    the observed dispatch cost (see obs/device.py)."""
    inputs = {"n_rows": n_rows, "n_shards": n_shards,
              "nbytes_est": nbytes_est}
    env_route = os.environ.get("DELTA_TPU_REPLAY_ROUTE")
    if env_route in ("host", "single", "sharded"):
        if env_route == "sharded" and n_shards <= 1:
            return _decide("replay", "single", inputs, reason="env")
        return _decide("replay", env_route, inputs, reason="env")
    if forced == "sharded" and n_shards > 1:
        return _decide("replay", "sharded", inputs, reason="forced")
    if n_rows <= 0:
        return _decide("replay", "single", inputs, reason="empty")

    model = link_model()
    if nbytes_est is None:
        nbytes_est = int(n_rows * _FA_BYTES_PER_ROW)
        inputs["nbytes_est"] = nbytes_est
    t_host = n_rows / max(model.host_rows_per_s, 1.0)
    t_device = (model.h2d_seconds(nbytes_est)
                + n_rows / model.device_rows_per_s)
    # the sharded route shares the single-chip transfer economics; its
    # per-chip compute advantage is recorded under the same prediction
    predicted = {"host": t_host, "single": t_device, "sharded": t_device}
    if t_host < t_device:
        return _decide("replay", "host", inputs, predicted)
    if n_shards > 1 and n_rows >= sharded_min_rows():
        return _decide("replay", "sharded", inputs, predicted)
    return _decide("replay", "single", inputs, predicted)


def parse_route(
    nbytes: int,
    engine_enabled: bool = False,
    forced: Optional[str] = None,
) -> str:
    """Pick the commit-JSON parse route: "host" (C++ scanner / generic
    Arrow) or "device" (ops/json_parse.py batched field extraction).

    Unlike `replay_route`, the CPU free-transfer model does NOT flip
    this to device-always: the host C++ scanner IS the calibrated
    fast path on CPU backends, so the device route needs the engine's
    construction-time opt-in (`use_device_parse`, true on accelerator
    backends) before the link economics are even consulted.
    DELTA_TPU_DEVICE_PARSE outranks everything (tests, bench lanes)."""
    inputs = {"nbytes": nbytes, "engine_enabled": engine_enabled}
    env = os.environ.get("DELTA_TPU_DEVICE_PARSE")
    if env is not None:
        if env.lower() in ("force", "1", "on", "device"):
            return _decide("parse", "device", inputs, reason="env")
        if env.lower() in ("0", "off", "host"):
            return _decide("parse", "host", inputs, reason="env")
    if forced in ("host", "device"):
        return _decide("parse", forced, inputs, reason="forced")
    if not engine_enabled or nbytes <= 0:
        return _decide("parse", "host", inputs, reason="engine-disabled")
    model = link_model()
    t_host = nbytes / _HOST_SCAN_BPS
    t_device = model.h2d_seconds(nbytes) + nbytes / _DEVICE_PARSE_BPS
    predicted = {"host": t_host, "device": t_device}
    return _decide("parse", "device" if t_device < t_host else "host",
                   inputs, predicted)


def decode_route(
    nbytes: int,
    engine_enabled: bool = False,
    forced: Optional[str] = None,
) -> str:
    """Pick the checkpoint page-decode route: "host" (the Arrow reader)
    or "device" (log/page_decode.py one-lane plan +
    ops/page_decode.py batched decode, one dispatch per part).

    Decided ONCE per checkpoint read over the parts' total byte size —
    the dispatch funnel then accumulates every part's observed cost
    onto the single decision. Like `parse_route`, the CPU free-transfer
    model does NOT flip this to device-always: Arrow IS the calibrated
    fast path on CPU backends, so the device route needs the engine's
    construction-time opt-in (`use_device_decode`, true on accelerator
    backends) before the link economics are consulted. Unsupported
    shapes fall back whole-part mid-flight (`obs.gate_fell_back`).
    DELTA_TPU_DEVICE_DECODE outranks everything (tests, bench lanes)."""
    inputs = {"nbytes": nbytes, "engine_enabled": engine_enabled}
    env = os.environ.get("DELTA_TPU_DEVICE_DECODE")
    if env is not None:
        if env.lower() in ("force", "1", "on", "device"):
            return _decide("decode", "device", inputs, reason="env")
        if env.lower() in ("0", "off", "host"):
            return _decide("decode", "host", inputs, reason="env")
    if forced in ("host", "device"):
        return _decide("decode", forced, inputs, reason="forced")
    if not engine_enabled or nbytes <= 0:
        return _decide("decode", "host", inputs,
                       reason="engine-disabled")
    model = link_model()
    t_host = nbytes / _HOST_ARROW_BPS
    t_device = model.h2d_seconds(nbytes) + nbytes / _DEVICE_DECODE_BPS
    predicted = {"host": t_host, "device": t_device}
    return _decide("decode", "device" if t_device < t_host else "host",
                   inputs, predicted)


def sql_route(
    op: str,
    n_rows: int,
    nbytes: int = 0,
    engine_enabled: bool = False,
    forced: Optional[str] = None,
    probe_failed: bool = False,
) -> str:
    """Pick the route for one SQL operator: "host" (the pandas
    executor, the bit-exact parity oracle) or "device" (the
    `ops/sqlops.py` kernels behind `sqlengine/device.py::DeviceSpine`).

    `op` is the operator class ("join" | "group-agg" | "sort"; the
    per-query spine resolution uses "query" with the join economics).
    `nbytes` is the operand bytes that must cross the link for this
    operator — rows already HBM-resident via the operand cache
    (`sqlengine/operands.py`) are excluded by the caller, which is how
    a warm cache shifts the crossover toward the device. Like
    `parse_route`, the device route needs the engine's opt-in
    (`use_device_sql`, true on TpuEngine) before the economics run;
    `probe_failed` marks a broken link probe (the decision record says
    so instead of a spine silently resolving to None).
    DELTA_TPU_DEVICE_SQL outranks everything (tests, bench lanes)."""
    inputs = {"op": op, "n_rows": n_rows, "nbytes": nbytes,
              "engine_enabled": engine_enabled}
    env = os.environ.get("DELTA_TPU_DEVICE_SQL")
    if env is not None and env != "":
        if env.lower() in ("force", "1", "on", "device"):
            return _decide("sql", "device", inputs, reason="env")
        if env.lower() in ("0", "off", "host"):
            return _decide("sql", "host", inputs, reason="env")
    if probe_failed:
        return _decide("sql", "host", inputs, reason="probe-failed")
    if forced in ("host", "device"):
        return _decide("sql", forced, inputs, reason="forced")
    if not engine_enabled or n_rows <= 0:
        return _decide("sql", "host", inputs, reason="engine-disabled")
    model = link_model()
    rate_h = _HOST_SQL_ROWS_PS.get(op, _HOST_SQL_ROWS_PS["join"])
    rate_d = _DEVICE_SQL_ROWS_PS.get(op, _DEVICE_SQL_ROWS_PS["join"])
    t_host = n_rows / rate_h
    t_device = model.h2d_seconds(nbytes) + n_rows / rate_d
    predicted = {"host": t_host, "device": t_device}
    return _decide("sql", "device" if t_device < t_host else "host",
                   inputs, predicted)


def skip_route(
    n_files: int,
    n_atoms: int,
    engine_enabled: bool = False,
    forced: Optional[str] = None,
) -> str:
    """Pick the data-skipping route for one scan plan: "host" (numpy
    twin over the encoded lanes) or "device" (ops/skipping.py batched
    kernel over the resident index).

    Like `parse_route`, the CPU free-transfer model does not flip this
    to device-always — the numpy twin is fast and allocation-free on
    CPU backends, so the device route needs the engine's
    construction-time opt-in (`use_device_skip`) before the economics
    run. The economics differ from `parse_route` in one way: the lane
    matrix is already HBM-resident (shipped once per snapshot version),
    so the device side pays one dispatch RTT, never a bulk H2D.
    DELTA_TPU_DEVICE_SKIP outranks everything (tests, bench lanes)."""
    inputs = {"n_files": n_files, "n_atoms": n_atoms,
              "engine_enabled": engine_enabled}
    env = os.environ.get("DELTA_TPU_DEVICE_SKIP")
    if env is not None:
        if env.lower() in ("force", "1", "on", "device"):
            return _decide("skip", "device", inputs, reason="env")
        if env.lower() in ("0", "off", "host"):
            return _decide("skip", "host", inputs, reason="env")
    if forced in ("host", "device"):
        return _decide("skip", forced, inputs, reason="forced")
    if not engine_enabled or n_files <= 0 or n_atoms <= 0:
        return _decide("skip", "host", inputs, reason="engine-disabled")
    model = link_model()
    cells = float(n_files) * float(n_atoms)
    t_host = cells / _HOST_SKIP_CELLS_PS
    t_device = model.rtt_s + cells / _DEVICE_SKIP_CELLS_PS
    predicted = {"host": t_host, "device": t_device}
    return _decide("skip", "device" if t_device < t_host else "host",
                   inputs, predicted)

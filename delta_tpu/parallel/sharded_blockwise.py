"""Sharded × blockwise replay: the multi-host >HBM configuration.

The reference's production shape for 10M+-file tables is BOTH
distributed and bounded-memory at once: state reconstruction shuffles
by path hash across executors AND each partition streams through a
sequential reconciler without materializing the whole partition
(`Snapshot.scala:481-511` — `repartition(hash(path))` then
`mapPartitions { InMemoryLogReplay }` over an iterator).

This module composes the repo's two halves the same way:

- `parallel/sharded_replay.py`'s host shuffle: rows bin to shard
  `key % S`, so per-shard reconciliation is globally correct with no
  cross-device key exchange;
- `ops/replay_blockwise.py`'s reverse-chronological streaming: each
  shard walks its substream newest→oldest in fixed-size blocks with a
  persistent *seen* bitset (first occurrence wins — the
  kernel-descending formulation of `ActiveAddFilesIterator.java:146`),
  reusing the exact single-device block kernel under `shard_map`.

All S shards advance one block per step — operands are [S, m] slabs,
the seen bitsets an [S, W] donated array XLA updates in place. Device
residency per step is one block per shard plus the bitsets,
independent of total rows. Shard skew (a hot path-hash shard) costs
padded lanes on the cold shards, never correctness: each shard's
bitset only ever sees its own key space.

Local key space: shard s holds exactly the keys ≡ s (mod S), so
`key // S` is a dense code over the shard's keys and the bitset is
`ceil(n_uniq / S / 32)` u32 words per shard — 10M files over 8 shards
≈ 4.9KB per shard.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from delta_tpu import obs
from delta_tpu.ops.replay import (
    _PAD_KEY,
    _unpack_bits,
    chrono_ok,
    combine_key_lanes,
    pad_bucket,
)
from delta_tpu.ops.replay_blockwise import _block_kernel_impl
from delta_tpu.parallel.sharded_replay import REPLAY_AXIS

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

DEFAULT_BLOCK_ROWS = 1 << 20  # 1M rows/shard/block


def _shard_block_step(seen, keys, n_real, m: int):
    """[1, ...]-sliced wrapper running the single-device block kernel
    on this shard's slab."""
    winner_words, seen_out = _block_kernel_impl(
        seen[0], keys[0], n_real[0], m)
    return seen_out[None], winner_words[None]


@functools.lru_cache(maxsize=8)
def _step_fn(mesh: Mesh, m: int):
    spec = P(REPLAY_AXIS, None)
    fn = shard_map(
        functools.partial(_shard_block_step, m=m),
        mesh=mesh,
        in_specs=(spec, spec, P(REPLAY_AXIS)),
        out_specs=(spec, spec),
    )
    return jax.jit(fn, donate_argnums=(0,))


def replay_select_sharded_blockwise(
    key_lanes,
    version: np.ndarray,
    order: np.ndarray,
    is_add: np.ndarray,
    mesh: Mesh,
    block_rows: int = DEFAULT_BLOCK_ROWS,
):
    """Mesh-sharded, bounded-memory replay. Returns
    (live_mask, tombstone_mask, per_shard_block_counts); the masks are
    identical to `replay_select` / `replay_select_blockwise` on the
    same stream (original row order)."""
    version = np.asarray(version)
    n = int(version.shape[0])
    S = int(mesh.devices.size)
    if n == 0:
        z = np.zeros((0,), dtype=bool)
        return z, z, np.zeros(S, np.int64)

    is_add_orig = np.asarray(is_add, bool)
    perm = None
    if not chrono_ok(version, np.asarray(order)):
        perm = np.lexsort((order, version))
        key_lanes = [np.asarray(k)[perm] for k in key_lanes]

    # shard by the PATH lane (lane 0), exactly like
    # parallel/sharded_replay: all DV variants of a path land on one
    # shard, and — crucially — a sparse secondary lane (dv mostly 0)
    # can't bias the shard distribution the way `combined % S` would
    lanes = [np.asarray(k) for k in key_lanes]
    pk = lanes[0]
    shard_of = (pk % np.uint32(S)).astype(np.int64)
    local_key = combine_key_lanes(
        [(pk // np.uint32(S)).astype(np.uint32)] + lanes[1:])
    if local_key is None:
        # radix overflow: densify over ALL lanes (shard-local codes
        # stay dense because every (path, dv, ...) tuple maps to a
        # unique structured row)
        cols_ = [(pk // np.uint32(S)).astype(np.uint32)]
        cols_ += [l.astype(np.uint32) for l in lanes[1:]]
        stacked = np.ascontiguousarray(np.stack(cols_, axis=1))
        view = stacked.view(
            [("", np.uint32)] * stacked.shape[1]).reshape(-1)
        _, local_key = np.unique(view, return_inverse=True)
        local_key = local_key.astype(np.uint32)
    n_uniq_local = int(local_key.max()) + 1

    # stable per-shard chronological substreams (the "shuffle")
    sort_idx = np.argsort(shard_of, kind="stable")
    counts = np.bincount(shard_of, minlength=S)
    max_count = int(counts.max())
    m = pad_bucket(min(block_rows, max(max_count, 1)))
    n_blocks = -(-max_count // m)
    L = n_blocks * m

    rows = shard_of[sort_idx]
    cols = np.arange(n) - np.repeat(np.cumsum(counts) - counts, counts)
    keys_slab = np.full((S, L), _PAD_KEY, dtype=np.uint32)
    keys_slab[rows, cols] = local_key[sort_idx]
    # slab position -> ORIGINAL row id (pre-perm)
    scatter = np.full((S, L), -1, dtype=np.int64)
    scatter[rows, cols] = sort_idx if perm is None else perm[sort_idx]

    n_words = pad_bucket(-(-max(n_uniq_local, 1) // 32),
                         min_bucket=256)
    # one-time seed upload of the per-shard bitsets (donated and updated
    # in place by every block step after)
    with obs.device_dispatch("replay.sharded_seed",
                             key=(S, n_words)) as dd:
        seen = dd.h2d("seen", jax.device_put(
            jnp.zeros((S, n_words), jnp.uint32),
            NamedSharding(mesh, P(REPLAY_AXIS, None))))
    step = _step_fn(mesh, m)

    winner = np.zeros(n, dtype=bool)  # original row space
    for b in reversed(range(n_blocks)):
        blk = keys_slab[:, b * m:(b + 1) * m]
        n_real = np.clip(counts - b * m, 0, m).astype(np.int32)
        # block operands ride as jit arguments (no device_put lane); the
        # per-block costs accumulate onto the same pending replay
        # decision, so calibration prices the whole block loop
        with obs.device_dispatch("replay.sharded_blockwise",
                                 key=(S, m, n_words), gate="replay",
                                 route="sharded") as dd:
            dd.h2d("block", int(blk.nbytes) + int(n_real.nbytes))
            seen, packed = step(seen, jnp.asarray(blk),
                                jnp.asarray(n_real))
            words = dd.d2h("packed", np.asarray(packed))
        tgt = scatter[:, b * m:(b + 1) * m]
        for s in range(S):
            w = _unpack_bits(words[s], m)
            sel = tgt[s] >= 0
            winner[tgt[s][sel]] = w[sel]

    live = winner & is_add_orig
    tomb = winner & ~is_add_orig
    blocks_used = np.maximum(-(-counts // m), 0).astype(np.int64)
    return live, tomb, blocks_used

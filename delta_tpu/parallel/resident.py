"""Device-resident sharded replay state across `Snapshot.update()`.

The sharded replay (`sharded_replay.py`) already rebuilds each shard's
key lane on device; DEVICE_MERIT.json says the expensive thing is the
host->device link, not the sort. So after a sharded full replay the
rebuilt per-shard key lane is simply KEPT on device (zero extra
transfer — `want_key` in the FA kernel), and every incremental
`Snapshot.update()` ships only its delta rows to their owning shards:
~8 bytes/delta row (slot index + key) instead of re-routing and
re-shipping the multi-million-row base state. The device then re-runs
the per-shard last-wins sort over base+delta and returns bit-packed
winner words (~1 bit/row D2H); the host — which keeps the add bits,
slot->row scatter, and path dictionary — rebuilds the full live and
tombstone masks without probing the base table at all.

Lifecycle: established by `compute_masks_device` (replay/state.py) when
the sharded route runs on chronological, DV-free input; ownership moves
`ColumnarActions` -> `SnapshotState` -> the advanced state (the append
kernel donates the key buffer, so exactly one state may own it);
released when a snapshot falls back to a full load (`table.py`) or is
evicted from the serve cache (`serve/cache.py`). Any append the state
cannot express (DV rows, batches older than the resident tail, capacity
overflow) returns None and the caller falls back to the host delta
path, dropping residency; in-batch disorder is sorted away, not
rejected — real commits columnarize removes after adds. Disable with DELTA_TPU_RESIDENT=0.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Optional

import numpy as np

from delta_tpu import obs
from delta_tpu.obs import hbm

_H2D_BYTES = obs.counter("replay.h2d_bytes")
_APPENDS = obs.counter("replay.resident_appends")
_FALLBACKS = obs.counter("replay.resident_fallbacks")
# device bytes pinned by resident key lanes are accounted in the
# process-wide resident ledger (obs/hbm.py), which also derives the
# `replay.resident_hbm_bytes` gauge this module used to maintain


def enabled() -> bool:
    return os.environ.get("DELTA_TPU_RESIDENT") != "0"


@functools.lru_cache(maxsize=32)
def _append_fn_cached(mesh, d_pad: int):
    """jit'd per-mesh append+replay: scatter the delta keys into each
    shard's resident lane (slot indexes past the shard's capacity are
    the drop sentinel) and re-run the last-wins sort. The resident lane
    is donated — the update happens in place on device."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from delta_tpu.ops.replay import _sort_winner_pack
    from delta_tpu.parallel.mesh import REPLAY_AXIS
    from delta_tpu.parallel.sharded_replay import shard_map

    def kernel(key, idx, val, n_real):
        key, idx, val = key[0], idx[0], val[0]
        key = key.at[idx].set(val, mode="drop")
        winner = _sort_winner_pack((key,), n_real[0][0])
        return key[None], winner[None]

    spec = P(REPLAY_AXIS, None)
    fn = shard_map(kernel, mesh=mesh, in_specs=(spec,) * 4,
                   out_specs=(spec, spec))
    # donate the resident lane so the update is in place on device; CPU
    # backends don't implement donation and would warn on every call
    donate = (0,) if jax.default_backend() != "cpu" else ()
    return jax.jit(fn, donate_argnums=donate)


class ResidentShardState:
    """Host bookkeeping + device key lane for one resident snapshot."""

    def __init__(self, payload, paths, path_codes: np.ndarray):
        # payload: sharded_replay.ResidentPayload
        # Guards every post-publication mutation: append() rewrites the
        # slot bookkeeping and swaps the donated device lane, and
        # release() tears the lane down — the serve cache can evict (and
        # release) a snapshot while another thread's refresh is still
        # inside append(), so the two must serialize here, not rely on
        # callers holding the right entry lock.
        self._lock = threading.Lock()
        self.mesh = payload.mesh
        self.m = payload.m
        self.n_shards = int(payload.mesh.devices.size)
        self.key_sh = payload.key_sh
        self._hbm = hbm.register(
            self, kind=hbm.KIND_REPLAY_KEYS,
            arrays=(payload.key_sh,),
            rebuild_cost_class="expensive",  # full sharded replay
        )
        self.n_real = np.asarray(payload.n_real, np.int64).copy()
        self.add = np.unpackbits(
            payload.add_words.view(np.uint8).reshape(self.n_shards, -1),
            axis=1, bitorder="little")[:, :self.m].astype(bool)
        self.scatter = payload.scatter.astype(np.int64)
        self.n = int(payload.n)
        self.n_uniq = int(payload.n_uniq)
        # path -> dense code, built lazily on first append (pd.Index
        # hashtable build is O(base), each append lookup O(delta))
        self._paths = paths            # arrow ChunkedArray, zero-copy ref
        self._base_codes = np.asarray(path_codes, np.uint32)
        self._index = None
        self._overlay: dict = {}       # paths first seen after establish
        self._max_version: Optional[int] = None  # newest appended version

    # ------------------------------------------------------------ codes

    def _ensure_index(self) -> None:
        if self._index is not None:
            return
        import pandas as pd

        codes = self._base_codes
        n_base_uniq = int(codes.max()) + 1 if len(codes) else 0
        _, first_idx = np.unique(codes, return_index=True)
        paths_np = np.asarray(self._paths.to_pandas(), dtype=object)
        uniq_paths = paths_np[first_idx]
        assert len(uniq_paths) == n_base_uniq
        self._index = pd.Index(uniq_paths)
        self._paths = None             # dictionary built; drop the ref
        self._base_codes = None

    def _code_paths(self, delta_paths: list) -> np.ndarray:
        """Dense codes for the delta rows, extending the dictionary in
        first-appearance order (matching what a cold full replay's
        factorize would assign over concat(base, delta))."""
        self._ensure_index()
        codes = self._index.get_indexer(delta_paths)
        out = np.empty(len(delta_paths), np.uint32)
        for i, (p, c) in enumerate(zip(delta_paths, codes)):
            if c >= 0:
                out[i] = c
            else:
                c2 = self._overlay.get(p)
                if c2 is None:
                    c2 = self.n_uniq
                    self._overlay[p] = c2
                    self.n_uniq += 1
                out[i] = c2
        return out

    # ----------------------------------------------------------- append

    def append(self, delta_fa, n_prev: int):
        """Ship the delta rows to their shards, re-reconcile on device,
        and return (live_mask, tombstone_mask) over the concatenated
        n_prev + delta rows — or None when this state can't express the
        batch (caller falls back to the host delta path and drops
        residency)."""
        with self._lock:
            return self._append_locked(delta_fa, n_prev)

    def _append_locked(self, delta_fa, n_prev: int):
        from delta_tpu.ops.replay import chrono_ok

        d = delta_fa.num_rows
        if n_prev != self.n or self.key_sh is None:
            _FALLBACKS.inc()
            return None
        dv = delta_fa.column("dv_id")
        if dv.null_count != d:
            _FALLBACKS.inc()  # DV rows need the (path, dv) key: not resident
            return None
        version = np.asarray(delta_fa.column("version"), np.int64)
        order = np.asarray(delta_fa.column("order"), np.int32)
        # In-batch disorder is routine (a commit's removes serialize
        # before its adds but columnarize after), so sort here: the
        # device kernel breaks key ties by slot index, and slots are
        # assigned in processing order. Only a batch older than what's
        # already resident is inexpressible — appended slots always sort
        # after the base, so a stale version would win ties it lost.
        if chrono_ok(version, order):
            chrono = np.arange(d, dtype=np.int64)
        else:
            chrono = np.lexsort((order, version))
        if d:
            lo = int(version[chrono[0]])
            if self._max_version is not None and lo < self._max_version:
                _FALLBACKS.inc()
                return None

        with obs.span("replay.resident_append", rows=d, base=self.n):
            codes = self._code_paths(delta_fa.column("path").to_pylist())
            is_add = np.asarray(delta_fa.column("is_add"), bool)
            codes_c = codes[chrono]
            is_add_c = is_add[chrono]
            s = self.n_shards
            shard_of = (codes_c % np.uint32(s)).astype(np.int64)
            counts = np.bincount(shard_of, minlength=s)
            new_n_real = self.n_real + counts
            if int(new_n_real.max(initial=0)) > self.m:
                _FALLBACKS.inc()  # shard full: re-establish on next load
                return None

            # slot of row i = shard fill level + rank among its shard's
            # delta rows (stable shard sort keeps chronological order)
            sort_idx = np.argsort(shard_of, kind="stable")
            starts = np.zeros(s + 1, np.int64)
            np.cumsum(counts, out=starts[1:])
            rows = shard_of[sort_idx]
            slots = (np.arange(d) - starts[rows]) + self.n_real[rows]

            d_pad = max(128, 1 << int(d - 1).bit_length()) if d else 128
            idx2d = np.full((s, d_pad), self.m, np.int32)  # m = drop
            val2d = np.zeros((s, d_pad), np.uint32)
            cols = np.arange(d) - starts[rows]
            idx2d[rows, cols] = slots.astype(np.int32)
            val2d[rows, cols] = (codes_c[sort_idx] //
                                 np.uint32(s)).astype(np.uint32)

            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from delta_tpu.parallel.mesh import REPLAY_AXIS

            spec = NamedSharding(self.mesh, P(REPLAY_AXIS, None))
            nbytes = idx2d.nbytes + val2d.nbytes
            _H2D_BYTES.inc(nbytes)
            obs.set_attrs(h2d_bytes=nbytes)
            n_real_op = new_n_real.astype(np.int32).reshape(s, 1)
            fn = _append_fn_cached(self.mesh, d_pad)
            with obs.device_dispatch("replay.resident_append",
                                     key=(s, d_pad),
                                     budget="resident-append",
                                     units=s * d_pad) as dd:
                dd.h2d("idx2d", idx2d)
                dd.h2d("val2d", val2d)
                dd.h2d("n_real_op", n_real_op)
                new_key, winner_sh = fn(
                    self.key_sh,
                    jax.device_put(idx2d, spec),
                    jax.device_put(val2d, spec),
                    jax.device_put(n_real_op, spec))
            self.key_sh = new_key
            # the donated append produced a NEW device array for the
            # same logical artifact: re-point the ledger's audit refs
            self._hbm.grow(arrays=(new_key,))

            # host bookkeeping for the appended slots (scatter maps each
            # slot back to its original arrow row, so the returned masks
            # stay in the caller's row order even for sorted batches)
            self.add[rows, slots] = is_add_c[sort_idx]
            self.scatter[rows, slots] = (n_prev +
                                         chrono[sort_idx].astype(np.int64))
            self.n_real = new_n_real
            self.n = n_prev + d
            if d:
                self._max_version = int(version[chrono[-1]])

            winner_np = np.asarray(winner_sh)  # [S, M/32] packed D2H
            winner = np.unpackbits(
                winner_np.view(np.uint8).reshape(s, -1),
                axis=1, bitorder="little")[:, :self.m].astype(bool)
            live_slots = winner & self.add
            tomb_slots = winner & ~self.add
            valid = self.scatter >= 0
            live = np.zeros(self.n, bool)
            tomb = np.zeros(self.n, bool)
            live[self.scatter[valid]] = live_slots[valid]
            tomb[self.scatter[valid]] = tomb_slots[valid]
            _APPENDS.inc()
            return live, tomb

    def device_hint(self):
        """First device of the owning mesh, or None once released — the
        checkpoint writer colocates its aggregation upload with the
        resident replay lanes so the stats dispatch lands on a device
        that already holds this snapshot's columnar state."""
        with self._lock:
            if self.key_sh is None or self.mesh is None:
                return None
            try:
                return self.mesh.devices.flat[0]
            # delta-lint: disable=except-swallow (audited: the hint is
            # a placement optimization — any mesh-shape drift must fall
            # back to default placement, never fail a checkpoint)
            except Exception:
                return None

    def release(self) -> None:
        """Drop the device buffer (the host bookkeeping is garbage with
        it, so the whole state is dead after this). Serializes against
        append(): an in-flight append finishes against the lane it
        started with before the release lands."""
        with self._lock:
            if self.key_sh is not None:
                self.key_sh = None
                self._hbm.release()


def establish_resident(payload, file_actions,
                       path_codes: np.ndarray) -> Optional[ResidentShardState]:
    """Wrap a `ResidentPayload` from `sharded_replay_select` with the
    snapshot's path column so future appends can code new paths
    consistently. `file_actions` is the canonical arrow table the
    payload's rows came from (same row order)."""
    try:
        with obs.span("replay.resident_establish", rows=payload.n):
            return ResidentShardState(
                payload, file_actions.column("path").combine_chunks(),
                path_codes)
    # delta-lint: disable=except-swallow (audited: residency is an
    # optimization; any establishment failure must degrade to the
    # non-resident path, never fail the load)
    except Exception:
        _FALLBACKS.inc()
        return None


def touch_snapshot_resident(snapshot) -> None:
    """Record access recency on a snapshot's resident artifacts (serve
    cache hits/refreshes route here). Duck-typed like
    `release_snapshot_resident`; missing pieces are no-ops."""
    state = getattr(snapshot, "_state", None) or snapshot
    resident = getattr(state, "resident", None)
    if resident is not None:
        resident._hbm.touch()
    stats_index = getattr(state, "stats_index", None)
    if stats_index is not None:
        stats_index._hbm.touch()
    operand_cache = getattr(state, "operand_cache", None)
    if operand_cache is not None:
        operand_cache._hbm.touch()


def release_snapshot_resident(snapshot) -> None:
    """Free a snapshot's resident device state, if any. Accepts
    `Snapshot`, `SnapshotState`, or anything in between (duck-typed so
    the serve cache and table fallback paths don't need type checks)."""
    state = getattr(snapshot, "_state", None) or snapshot
    resident = getattr(state, "resident", None)
    if resident is not None:
        resident.release()
        state.resident = None
    # the scan-planning stats index (stats/device_index.py) shares the
    # residency lifecycle: evicting the snapshot frees its lanes too
    stats_index = getattr(state, "stats_index", None)
    if stats_index is not None:
        stats_index.release()
        state.stats_index = None
    # the SQL operand cache (sqlengine/operands.py) shares the same
    # lifecycle: evicting the snapshot frees its column lanes too
    operand_cache = getattr(state, "operand_cache", None)
    if operand_cache is not None:
        operand_cache.release()
        state.operand_cache = None

"""Row tracking: stable row ids + row commit versions.

Reference `RowId.scala` / `RowTracking.scala`: when the `rowTracking`
writer feature is supported, every committed AddFile gets a fresh
`baseRowId` range (row i of the file has row id baseRowId + i) and a
`defaultRowCommitVersion`. The allocator state is the
`delta.rowTracking` metadata domain: `{"rowIdHighWaterMark": N}`.

Concurrent writers both bump the watermark; that domain write is
auto-resolved at conflict time (winner's watermark is folded in and ids
reassigned on rebase) instead of failing the transaction — mirroring
`RowTracking.resolveRowIdConflicts` semantics.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Tuple

from delta_tpu.errors import DeltaError, RowTrackingError
from delta_tpu.models.actions import AddFile, DomainMetadata, Protocol

ROW_TRACKING_DOMAIN = "delta.rowTracking"
ROW_TRACKING_FEATURE = "rowTracking"


def is_row_tracking_supported(protocol: Optional[Protocol]) -> bool:
    return protocol is not None and ROW_TRACKING_FEATURE in protocol.writer_feature_set()


def watermark_from_domain(dm: Optional[DomainMetadata]) -> int:
    if dm is None or not dm.configuration:
        return -1
    try:
        return int(json.loads(dm.configuration).get("rowIdHighWaterMark", -1))
    except (ValueError, TypeError):
        return -1


def current_high_watermark(snapshot) -> int:
    if snapshot is None:
        return -1
    dm = snapshot.state.domain_metadata.get(ROW_TRACKING_DOMAIN)
    return watermark_from_domain(dm)


def assign_fresh_row_ids(
    adds: List[AddFile],
    high_watermark: int,
    commit_version: int,
) -> Tuple[List[AddFile], Optional[DomainMetadata]]:
    """Assign baseRowId/defaultRowCommitVersion to adds lacking them.
    Returns (new adds, watermark domain action or None if nothing moved)."""
    next_id = high_watermark + 1
    out = []
    assigned = False
    for a in adds:
        num = a.num_records()
        base = a.baseRowId
        if base is None:
            if num is None:
                raise RowTrackingError(
                    error_class="DELTA_ROW_ID_ASSIGNMENT_WITHOUT_STATS",
                    message=f"row tracking requires numRecords stats on {a.path}"
                )
            base = next_id
            next_id += num
            assigned = True
            a = dataclasses.replace(
                a, baseRowId=base, defaultRowCommitVersion=commit_version
            )
        elif a.defaultRowCommitVersion is None:
            a = dataclasses.replace(a, defaultRowCommitVersion=commit_version)
            next_id = max(next_id, base + (num or 0))
            assigned = True
        else:
            next_id = max(next_id, base + (num or 0))
        out.append(a)
    if not assigned and next_id == high_watermark + 1:
        return out, None
    dm = DomainMetadata(
        ROW_TRACKING_DOMAIN,
        json.dumps({"rowIdHighWaterMark": next_id - 1}),
        removed=False,
    )
    return out, dm

"""Allocator tuning for fault-expensive hosts.

On virtualized hosts whose memory is lazily faulted through a hypervisor
(common for TPU-attached VMs and microVM sandboxes), a minor page fault
costs tens of microseconds instead of ~1us. glibc's default malloc
returns large (>128KB) allocations to the OS on free, so every snapshot
load re-faults gigabytes of arena/buffer memory at that price — measured
2.4x end-to-end on 2.3GB log scans. Raising the mmap/trim thresholds
keeps freed memory in the process heap for reuse.

Called once from the engines; set DELTA_TPU_NO_MALLOC_TUNING=1 to skip.
"""

from __future__ import annotations

import ctypes
import os

_done = False

_M_TRIM_THRESHOLD = -1
_M_MMAP_THRESHOLD = -3


def tune_allocator() -> bool:
    """Idempotently raise glibc malloc's mmap/trim thresholds so freed
    GB-scale buffers are reused instead of re-faulted. Returns True when
    tuning was applied (glibc present, not opted out)."""
    global _done
    if _done:
        return True
    if os.environ.get("DELTA_TPU_NO_MALLOC_TUNING"):
        return False
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        mallopt = libc.mallopt
    except (OSError, AttributeError):
        return False
    mallopt.argtypes = [ctypes.c_int, ctypes.c_int]
    mallopt.restype = ctypes.c_int
    gb = 1 << 30
    ok = bool(mallopt(_M_MMAP_THRESHOLD, gb))
    ok = bool(mallopt(_M_TRIM_THRESHOLD, gb)) and ok
    _done = ok
    return ok

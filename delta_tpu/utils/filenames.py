"""`_delta_log` file-naming scheme.

The log directory contains, side by side (semantics per reference
`spark/.../delta/util/FileNames.scala` and `PROTOCOL.md:1495-1519`):

- commit ("delta") files              ``%020d.json``
- unbackfilled commits                ``_commits/%020d.<uuid>.json``
- per-version checksums               ``%020d.crc``
- compacted commit ranges             ``%020d.%020d.compacted.json``
- classic single-file checkpoints     ``%020d.checkpoint.parquet``
- legacy multi-part checkpoints       ``%020d.checkpoint.%010d.%010d.parquet``
- V2 / UUID checkpoints               ``%020d.checkpoint.<uuid>.{json,parquet}``
- V2 sidecars                         ``_sidecars/<uuid>.parquet``
- the last-checkpoint pointer         ``_last_checkpoint``

Zero padding exists so a lexicographic LIST from a prefix returns files in
version order — the listing contract everything above depends on.
"""

from __future__ import annotations

import re
import uuid as _uuid
from dataclasses import dataclass
from enum import Enum
from typing import Optional, Sequence

LOG_DIR_NAME = "_delta_log"
COMMIT_SUBDIR = "_commits"
SIDECAR_SUBDIR = "_sidecars"
LAST_CHECKPOINT = "_last_checkpoint"
CHANGE_DATA_DIR = "_change_data"

DELTA_FILE_RE = re.compile(r"^(\d+)\.json$")
UUID_DELTA_FILE_RE = re.compile(r"^(\d+)\.([^.]+)\.json$")
COMPACTED_DELTA_FILE_RE = re.compile(r"^(\d+)\.(\d+)\.compacted\.json$")
CHECKSUM_FILE_RE = re.compile(r"^(\d+)\.crc$")
CHECKPOINT_FILE_RE = re.compile(
    r"^(\d+)\.checkpoint((\.\d+\.\d+)?\.parquet|\.[^.]+\.(json|parquet))$"
)


def delta_file(log_path: str, version: int) -> str:
    """Backfilled commit file path for `version`."""
    return f"{log_path}/{version:020d}.json"


def unbackfilled_delta_file(log_path: str, version: int, uuid: Optional[str] = None) -> str:
    u = uuid if uuid is not None else str(_uuid.uuid4())
    return f"{log_path}/{COMMIT_SUBDIR}/{version:020d}.{u}.json"


def commit_dir(log_path: str) -> str:
    return f"{log_path}/{COMMIT_SUBDIR}"


def sidecar_dir(log_path: str) -> str:
    return f"{log_path}/{SIDECAR_SUBDIR}"


def sidecar_file(log_path: str, uuid: Optional[str] = None) -> str:
    u = uuid if uuid is not None else str(_uuid.uuid4())
    return f"{log_path}/{SIDECAR_SUBDIR}/{u}.parquet"


def checksum_file(log_path: str, version: int) -> str:
    return f"{log_path}/{version:020d}.crc"


def compacted_delta_file(log_path: str, from_version: int, to_version: int) -> str:
    return f"{log_path}/{from_version:020d}.{to_version:020d}.compacted.json"


def checkpoint_file_singular(log_path: str, version: int) -> str:
    return f"{log_path}/{version:020d}.checkpoint.parquet"


def checkpoint_file_with_parts(log_path: str, version: int, num_parts: int) -> list[str]:
    """Part paths are 1-based: part `i` of `n` is `...checkpoint.%010i.%010n.parquet`."""
    return [
        f"{log_path}/{version:020d}.checkpoint.{i:010d}.{num_parts:010d}.parquet"
        for i in range(1, num_parts + 1)
    ]


def top_level_v2_checkpoint_file(
    log_path: str, version: int, fmt: str = "parquet", uuid: Optional[str] = None
) -> str:
    assert fmt in ("json", "parquet"), fmt
    u = uuid if uuid is not None else str(_uuid.uuid4())
    return f"{log_path}/{version:020d}.checkpoint.{u}.{fmt}"


def last_checkpoint_file(log_path: str) -> str:
    return f"{log_path}/{LAST_CHECKPOINT}"


def listing_prefix(log_path: str, version: int) -> str:
    """Prefix such that a lexicographic listFrom returns all log files with
    version >= `version` (plus `_`-prefixed dirs, which sort after digits
    — callers filter)."""
    return f"{log_path}/{version:020d}."


def file_name(path: str) -> str:
    return path.rstrip("/").rsplit("/", 1)[-1]


def is_delta_file(path: str) -> bool:
    return DELTA_FILE_RE.match(file_name(path)) is not None


def is_unbackfilled_delta_file(path: str) -> bool:
    p = path.rstrip("/")
    return (
        UUID_DELTA_FILE_RE.match(file_name(p)) is not None
        and f"/{COMMIT_SUBDIR}/" in p
    )


def is_checksum_file(path: str) -> bool:
    return CHECKSUM_FILE_RE.match(file_name(path)) is not None


def is_checkpoint_file(path: str) -> bool:
    return CHECKPOINT_FILE_RE.match(file_name(path)) is not None


def is_compacted_delta_file(path: str) -> bool:
    return COMPACTED_DELTA_FILE_RE.match(file_name(path)) is not None


def delta_version(path: str) -> int:
    """Version encoded in a commit/unbackfilled-commit file name."""
    return int(file_name(path).split(".")[0])


def checksum_version(path: str) -> int:
    return int(file_name(path).removesuffix(".crc"))


def checkpoint_version(path: str) -> int:
    return int(file_name(path).split(".")[0])


def compacted_delta_versions(path: str) -> tuple[int, int]:
    parts = file_name(path).split(".")
    return int(parts[0]), int(parts[1])


class CheckpointFormat(Enum):
    CLASSIC = "classic"            # %020d.checkpoint.parquet
    MULTIPART = "multipart"        # %020d.checkpoint.%010d.%010d.parquet
    V2_JSON = "v2-json"            # %020d.checkpoint.<uuid>.json
    V2_PARQUET = "v2-parquet"      # %020d.checkpoint.<uuid>.parquet


@dataclass(frozen=True, order=False)
class CheckpointInstance:
    """Parsed identity of a checkpoint file (reference
    `kernel/.../internal/checkpoints/CheckpointInstance.java`,
    spark `Checkpoints.scala` CheckpointInstance).

    Ordering: by version, then format preference (V2 > multipart > classic —
    newer formats carry more information), used to pick the best complete
    checkpoint at or below a version.
    """

    version: int
    fmt: CheckpointFormat
    num_parts: int = 1
    part: int = 1          # 1-based part index for MULTIPART
    uuid: Optional[str] = None
    path: Optional[str] = None

    _FORMAT_RANK = {
        CheckpointFormat.CLASSIC: 0,
        CheckpointFormat.MULTIPART: 1,
        CheckpointFormat.V2_JSON: 2,
        CheckpointFormat.V2_PARQUET: 2,
    }

    @property
    def sort_key(self):
        return (self.version, self._FORMAT_RANK[self.fmt], self.num_parts)

    @staticmethod
    def parse(path: str) -> Optional["CheckpointInstance"]:
        name = file_name(path)
        m = CHECKPOINT_FILE_RE.match(name)
        if m is None:
            return None
        version = int(m.group(1))
        parts = name.split(".")
        # name.checkpoint.parquet -> 3 segments
        if len(parts) == 3:
            return CheckpointInstance(version, CheckpointFormat.CLASSIC, path=path)
        # name.checkpoint.<part>.<num>.parquet -> 5 segments, digits
        if len(parts) == 5 and parts[2].isdigit() and parts[3].isdigit():
            return CheckpointInstance(
                version,
                CheckpointFormat.MULTIPART,
                num_parts=int(parts[3]),
                part=int(parts[2]),
                path=path,
            )
        # name.checkpoint.<uuid>.{json,parquet} -> 4 segments
        if len(parts) == 4:
            fmt = (
                CheckpointFormat.V2_JSON if parts[3] == "json" else CheckpointFormat.V2_PARQUET
            )
            return CheckpointInstance(version, fmt, uuid=parts[2], path=path)
        return None


def group_complete_checkpoints(
    instances: Sequence[CheckpointInstance],
) -> list[list[CheckpointInstance]]:
    """Group parsed checkpoint files into *complete* checkpoints.

    A classic or V2 file is complete by itself; a multipart checkpoint is
    complete only when all `num_parts` parts for the same (version,
    num_parts) are present (reference `Checkpoints.scala` getLatestComplete
    semantics). Returns groups sorted ascending by (version, format rank).
    """
    singles: list[list[CheckpointInstance]] = []
    multi: dict[tuple[int, int], dict[int, CheckpointInstance]] = {}
    for ci in instances:
        if ci.fmt == CheckpointFormat.MULTIPART:
            multi.setdefault((ci.version, ci.num_parts), {})[ci.part] = ci
        else:
            singles.append([ci])
    for (version, num_parts), parts in multi.items():
        if len(parts) == num_parts and set(parts) == set(range(1, num_parts + 1)):
            singles.append([parts[i] for i in range(1, num_parts + 1)])
    singles.sort(key=lambda group: group[0].sort_key)
    return singles

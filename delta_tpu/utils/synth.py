"""Synthetic replay histories shared by tests, benchmarks, and the
driver dry-run — one generator so every harness exercises the same
scanner-shaped stream.

`fa_history` mimics what the native scanner emits for a real Delta log:
dense first-appearance path codes (~`new_rate` of rows introduce a fresh
file), a mostly-zero DV lane, sorted versions with within-commit order,
and re-adds."""

from __future__ import annotations

import numpy as np


def fa_history(n: int, seed: int = 0, new_rate: float = 0.85,
               dv_frac: float = 0.0, n_versions: int | None = None,
               readd_rate: float = 0.3):
    """Returns (path_codes u32, dv_codes u32, version i32, order i32,
    is_add bool, size i64)."""
    rng = np.random.default_rng(seed)
    is_new = rng.random(n) < new_rate
    if n:
        is_new[0] = True
    new_count = np.cumsum(is_new)
    back = (rng.random(n) * (new_count - 1)).astype(np.int64)
    pk = np.where(is_new, new_count - 1, back).astype(np.uint32)
    dk = np.zeros(n, np.uint32)
    if dv_frac:
        dv_rows = rng.random(n) < dv_frac
        dk[dv_rows] = rng.integers(1, 4, int(dv_rows.sum())).astype(np.uint32)
    if n_versions is None:
        n_versions = max(2, n // 100)
    ver = np.sort(rng.integers(0, n_versions, n)).astype(np.int32)
    # rank within each version run (ver is sorted, so the run start of
    # row i is searchsorted(ver, ver[i]))
    order = (np.arange(n) - np.searchsorted(ver, ver)).astype(np.int32)
    add = is_new | (rng.random(n) < readd_rate)
    size = rng.integers(100, 10_000, n).astype(np.int64)
    return pk, dk, ver, order, add, size

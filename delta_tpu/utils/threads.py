"""Host-side thread parallelism for I/O-bound table operations.

The reference keeps a family of named daemon thread pools
(`spark/src/main/scala/org/apache/spark/sql/delta/util/threads/` —
`DeltaThreadPool.scala`, `SparkThreadLocalForwardingThreadPoolExecutor`)
for parallel LIST/DELETE in VACUUM (`commands/VacuumCommand.scala:224`),
parallel manifest reads in CONVERT, and async post-commit work. The JAX
engine is single-process, so the equivalent here is a plain shared
`ThreadPoolExecutor` wrapper: ordered `map`, `submit`, and a bounded
default size. Note CPython joins executor workers at interpreter exit —
in-flight I/O (e.g. an unlink against a dead mount) delays shutdown
until it returns; `shutdown(wait=False)` only stops new work.
"""

from __future__ import annotations

import os
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Iterable, List, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def default_io_threads() -> int:
    """Worker count for I/O-bound and GIL-releasing native work.

    Deliberately floored at 16 rather than trusting `os.cpu_count()`:
    containerized/cgroup environments (including this one) routinely
    advertise 1 CPU while the host schedules many more, and measured
    native-scan throughput here scales ~4x from 1 to 16 threads on a
    "1-CPU" box. Oversubscription on a genuinely single-core machine
    costs a few percent; undersubscription costs multiples. Override
    with DELTA_TPU_THREADS."""
    env = os.environ.get("DELTA_TPU_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return min(32, max(16, (os.cpu_count() or 1) * 4))


def default_scan_threads() -> int:
    """Worker count for CPU-bound native parsing. Unlike I/O threads,
    oversubscribing a genuinely single-core host HURTS here (measured
    ~2x slower at 16 threads: context switches plus the multi-builder
    merge path replace the single-builder move path), so this trusts
    the schedulable-CPU set. Override with DELTA_TPU_SCAN_THREADS."""
    env = os.environ.get("DELTA_TPU_SCAN_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    try:
        return min(32, len(os.sched_getaffinity(0)))
    except AttributeError:  # non-Linux
        return min(32, os.cpu_count() or 1)


_DEFAULT_WORKERS = default_io_threads()


class DeltaThreadPool:
    """Named daemon pool with ordered map semantics."""

    def __init__(self, name: str, max_workers: Optional[int] = None):
        self.name = name
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers or _DEFAULT_WORKERS,
            thread_name_prefix=f"delta-tpu-{name}")

    def submit(self, fn: Callable[..., R], *args, **kwargs) -> "Future[R]":
        return self._pool.submit(fn, *args, **kwargs)

    def map(self, fn: Callable[[T], R], items: Iterable[T]) -> List[R]:
        """Apply `fn` to every item concurrently; results in input order.
        The first exception propagates (after all tasks were submitted)."""
        futures = [self._pool.submit(fn, it) for it in items]
        return [f.result() for f in futures]

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


_SHARED: Optional[DeltaThreadPool] = None


def shared_pool() -> DeltaThreadPool:
    """The process-wide pool used by VACUUM/CONVERT/listing."""
    global _SHARED
    if _SHARED is None:
        _SHARED = DeltaThreadPool("io")
    return _SHARED


def parallel_map(fn: Callable[[T], R], items: Sequence[T],
                 min_parallel: int = 8) -> List[R]:
    """Ordered parallel map over an I/O-bound function; falls back to a
    sequential loop for tiny inputs where pool dispatch costs more than
    it saves."""
    if len(items) < min_parallel:
        return [fn(it) for it in items]
    return shared_pool().map(fn, items)

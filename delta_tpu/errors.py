"""Error hierarchy for delta-tpu.

Mirrors the reference's error taxonomy: the concurrent-modification family
raised by conflict checking (spark `DeltaErrors.scala` /
`ConflictChecker.scala:175`), commit failures discriminated as
retryable-vs-conflict (`CommitFailedException`, OptimisticTransaction
retry loop), and the kernel's Table/Snapshot resolution errors.

Each error carries a stable ``error_class`` string (the reference keeps a
JSON catalog of these in ``delta-error-classes.json``) so callers can match
on class rather than message text.
"""

from __future__ import annotations


class DeltaError(Exception):
    """Base class for all delta-tpu errors.

    `error_class` identifies the stable catalog entry
    (resources/error_classes.json — the reference's
    delta-error-classes.json role). A raise site may narrow its
    exception type's default class by passing `error_class=` — the
    reference does the same thing with one `DeltaErrors.scala` factory
    per condition over a handful of exception types."""

    error_class: str = "DELTA_ERROR"

    def __init__(self, message: str = "", error_class: str = None,
                 **context):
        super().__init__(message)
        if error_class is not None:
            self.error_class = error_class
        self.context = context


class TableNotFoundError(DeltaError):
    error_class = "DELTA_TABLE_NOT_FOUND"


class VersionNotFoundError(DeltaError):
    """Requested version is outside the reconstructable range."""

    error_class = "DELTA_VERSION_NOT_FOUND"

    def __init__(self, version=None, earliest=None, latest=None):
        super().__init__(
            f"Cannot time travel Delta table to version {version}. "
            f"Available versions: [{earliest}, {latest}].",
            version=version,
            earliest=earliest,
            latest=latest,
        )


class TimestampEarlierThanCommitRetentionError(DeltaError):
    error_class = "DELTA_TIMESTAMP_EARLIER_THAN_COMMIT_RETENTION"


class TimestampLaterThanLatestCommitError(DeltaError):
    error_class = "DELTA_TIMESTAMP_GREATER_THAN_COMMIT"


class CommitFailedError(DeltaError):
    """A commit attempt failed.

    ``retryable`` discriminates transient failures (retry at same version)
    from losses of the put-if-absent race (rebase + retry at version+1);
    ``conflict`` marks the latter. Mirrors the semantics of
    storage `CommitFailedException` consumed by
    `OptimisticTransaction.scala:2229-2254`.
    """

    error_class = "DELTA_COMMIT_FAILED"

    def __init__(self, message: str, retryable: bool = False, conflict: bool = False):
        super().__init__(message)
        self.retryable = retryable
        self.conflict = conflict


class ConcurrentModificationError(DeltaError):
    """Base for logical conflicts detected against winning commits."""

    error_class = "DELTA_CONCURRENT_MODIFICATION"


class ProtocolChangedError(ConcurrentModificationError):
    error_class = "DELTA_PROTOCOL_CHANGED"


class MetadataChangedError(ConcurrentModificationError):
    error_class = "DELTA_METADATA_CHANGED"


class ConcurrentAppendError(ConcurrentModificationError):
    """A winning commit added files that this transaction's read predicate
    might have matched."""

    error_class = "DELTA_CONCURRENT_APPEND"


class ConcurrentDeleteReadError(ConcurrentModificationError):
    """A winning commit removed a file this transaction read."""

    error_class = "DELTA_CONCURRENT_DELETE_READ"


class ConcurrentDeleteDeleteError(ConcurrentModificationError):
    """A winning commit removed a file this transaction also removes."""

    error_class = "DELTA_CONCURRENT_DELETE_DELETE"


class ConcurrentTransactionError(ConcurrentModificationError):
    """A winning commit advanced an idempotent-txn appId this transaction read."""

    error_class = "DELTA_CONCURRENT_TRANSACTION"


class ConcurrentWriteError(ConcurrentModificationError):
    error_class = "DELTA_CONCURRENT_WRITE"


class MaxCommitRetriesExceededError(DeltaError):
    error_class = "DELTA_MAX_COMMIT_RETRIES_EXCEEDED"


class InvariantViolationError(DeltaError):
    """NOT NULL / CHECK constraint violated by written data."""

    error_class = "DELTA_VIOLATE_CONSTRAINT"


class UnsupportedTableFeatureError(DeltaError):
    """Protocol requires a reader/writer feature this client does not implement."""

    error_class = "DELTA_UNSUPPORTED_FEATURES_FOR_READ"

    def __init__(self, features, read: bool = True):
        kind = "read" if read else "write"
        super().__init__(
            f"Unsupported Delta table features for {kind}: {sorted(features)}",
            features=sorted(features),
            error_class=("DELTA_UNSUPPORTED_FEATURES_FOR_READ" if read
                         else "DELTA_UNSUPPORTED_FEATURES_FOR_WRITE"),
        )
        self.features = frozenset(features)


class InvalidProtocolVersionError(DeltaError):
    error_class = "DELTA_INVALID_PROTOCOL_VERSION"


class ChecksumMismatchError(DeltaError):
    """Post-replay state disagrees with the `.crc` version checksum."""

    error_class = "DELTA_CHECKSUM_MISMATCH"


class CorruptStatsError(DeltaError):
    """Stats content failed to decode (invalid JSON escapes)."""

    error_class = "DELTA_CORRUPT_STATS"


class SchemaMismatchError(DeltaError):
    error_class = "DELTA_SCHEMA_MISMATCH"



class SqlParseError(DeltaError):
    """SQL text failed to tokenize/parse (reference
    `DELTA_PARSE_SYNTAX_ERROR` family, `DeltaSqlParser.scala`)."""

    error_class = "DELTA_PARSE_SYNTAX_ERROR"


class UnresolvedColumnError(DeltaError):
    error_class = "DELTA_UNRESOLVED_COLUMN"


class AmbiguousColumnError(DeltaError):
    error_class = "DELTA_AMBIGUOUS_COLUMN"


class UnsupportedSqlError(DeltaError):
    """Valid-looking SQL using surface this engine does not implement."""

    error_class = "DELTA_UNSUPPORTED_SQL"


class SubqueryShapeError(DeltaError):
    """Scalar/IN subquery returned the wrong shape."""

    error_class = "DELTA_INVALID_SUBQUERY"


class InvalidTablePropertyError(DeltaError):
    error_class = "DELTA_INVALID_TABLE_PROPERTY"



class InvalidArgumentError(DeltaError):
    """Bad argument to a command/API builder (reference
    `DeltaErrors.illegalDeltaOptionException` family)."""

    error_class = "DELTA_ILLEGAL_ARGUMENT"


class PathExistsError(DeltaError):
    error_class = "DELTA_PATH_EXISTS"


class MissingTransactionLogError(DeltaError):
    error_class = "DELTA_MISSING_TRANSACTION_LOG"


class FileNotFoundInLogError(DeltaError):
    error_class = "DELTA_FILE_NOT_FOUND_DETAILED"


class AppendOnlyTableError(DeltaError):
    """DELETE/UPDATE/MERGE-delete on a delta.appendOnly table."""

    error_class = "DELTA_CANNOT_MODIFY_APPEND_ONLY"



class ColumnMappingError(DeltaError):
    error_class = "DELTA_UNSUPPORTED_COLUMN_MAPPING_OPERATION"


class ColumnMappingModeChangeError(ColumnMappingError):
    error_class = "DELTA_UNSUPPORTED_COLUMN_MAPPING_MODE_CHANGE"



class NonExistentColumnError(DeltaError):
    error_class = "DELTA_COLUMN_NOT_FOUND"


class DuplicateColumnError(DeltaError):
    error_class = "DELTA_DUPLICATE_COLUMNS_FOUND"



class IdentityColumnError(DeltaError):
    error_class = "DELTA_IDENTITY_COLUMNS_ILLEGAL_OPERATION"


class ConstraintAlreadyExistsError(DeltaError):
    error_class = "DELTA_CONSTRAINT_ALREADY_EXISTS"


class ConstraintNotFoundError(DeltaError):
    error_class = "DELTA_CONSTRAINT_DOES_NOT_EXIST"


class FeatureDropError(DeltaError):
    """DROP FEATURE preconditions not met (reference
    `DELTA_FEATURE_DROP_*` family)."""

    error_class = "DELTA_FEATURE_DROP_UNSUPPORTED_CLIENT_FEATURE"


class FeatureDropHistoricalVersionsExistError(FeatureDropError):
    error_class = "DELTA_FEATURE_DROP_HISTORICAL_VERSIONS_EXIST"



class RestoreTargetError(DeltaError):
    error_class = "DELTA_CANNOT_RESTORE_TABLE_VERSION"


class CloneTargetExistsError(DeltaError):
    error_class = "DELTA_CLONE_AMBIGUOUS_TARGET"


class ConvertTargetError(DeltaError):
    error_class = "DELTA_CONVERSION_UNSUPPORTED_SOURCE"


class VacuumRetentionError(DeltaError):
    """Retention below the safety floor without the override flag."""

    error_class = "DELTA_UNSAFE_VACUUM_RETENTION"


class VacuumLiteError(DeltaError):
    """VACUUM LITE cannot prove completeness: log cleanup removed
    commits that were never scanned by a previous vacuum."""

    error_class = "DELTA_CANNOT_VACUUM_LITE"


class OptimizeArgumentError(DeltaError):
    error_class = "DELTA_OPTIMIZE_INVALID_ARGUMENT"


class ClusteringColumnError(DeltaError):
    error_class = "DELTA_CLUSTERING_COLUMNS_MISMATCH"


class StreamingSourceError(DeltaError):
    error_class = "DELTA_STREAMING_SOURCE_ERROR"



class StreamingSchemaChangeError(StreamingSourceError):
    """Non-additive schema change mid-stream (reference
    `DELTA_STREAMING_METADATA_EVOLUTION` family)."""

    error_class = "DELTA_STREAMING_INCOMPATIBLE_SCHEMA_CHANGE"


class CdcNotEnabledError(DeltaError):
    error_class = "DELTA_CHANGE_TABLE_FEED_DISABLED"


class IcebergCompatViolationError(DeltaError):
    error_class = "DELTA_ICEBERG_COMPAT_VIOLATION"


class UniFormConversionError(DeltaError):
    error_class = "DELTA_UNIVERSAL_FORMAT_VIOLATION"


class SharingError(DeltaError):
    error_class = "DELTA_SHARING_ERROR"


class CheckpointError(DeltaError):
    error_class = "DELTA_CHECKPOINT_NON_EXIST_TABLE"


class LogCorruptedError(DeltaError):
    error_class = "DELTA_LOG_FILE_MALFORMED"


class TornCommitError(LogCorruptedError):
    """The *trailing* commit file ends in a torn (partially written)
    JSON line — the signature of an interrupted non-atomic write, as
    opposed to mid-log corruption. Callers can drop the torn tip and
    serve the previous version; `LogCorruptedError` proper means the
    log is damaged somewhere history depends on."""

    error_class = "DELTA_TORN_COMMIT"


class CircuitOpenError(DeltaError):
    """An endpoint's circuit breaker is open: recent calls failed
    repeatedly, so this call fails fast instead of burning a retry
    budget (see delta_tpu/resilience/breaker.py)."""

    error_class = "DELTA_CIRCUIT_BREAKER_OPEN"


class DeadlineExceededError(DeltaError):
    """The request's wall-clock deadline passed before the work
    finished: the client has stopped caring, so the remaining work is
    abandoned rather than completed into the void (see
    delta_tpu/resilience/deadline.py). Deliberately permanent in the
    transient/permanent classification — retrying an expired budget
    cannot help."""

    error_class = "DELTA_DEADLINE_EXCEEDED"


class ServiceOverloadedError(DeltaError):
    """The serve-layer admission controller rejected the request before
    doing any work: the queue is at capacity, the tenant is over its
    rate/concurrency budget, or the server is draining. Classified
    *transient* (delta_tpu/resilience/classify.py): backing off and
    retrying is exactly what the caller should do, and
    ``retry_after_ms`` hints when."""

    error_class = "DELTA_SERVICE_OVERLOADED"

    def __init__(self, message: str, retry_after_ms: int = None,
                 reason: str = None):
        super().__init__(message, retry_after_ms=retry_after_ms,
                         reason=reason)
        self.retry_after_ms = retry_after_ms
        self.reason = reason


class DomainMetadataError(DeltaError):
    error_class = "DELTA_DOMAIN_METADATA_NOT_SUPPORTED"


class RowTrackingError(DeltaError):
    error_class = "DELTA_ROW_TRACKING_ILLEGAL_OPERATION"


class DeletionVectorError(DeltaError):
    error_class = "DELTA_DELETION_VECTOR_INVALID"


class TimeTravelArgumentError(DeltaError):
    error_class = "DELTA_INVALID_TIME_TRAVEL_SPEC"


class SchemaEvolutionError(DeltaError):
    error_class = "DELTA_UNSUPPORTED_SCHEMA_EVOLUTION"


class CatalogTableError(DeltaError):
    error_class = "DELTA_CATALOG_TABLE_ERROR"


class ImportError_(DeltaError):
    error_class = "DELTA_IMPORT_FAILED"


class ConnectProtocolError(DeltaError):
    error_class = "DELTA_CONNECT_PROTOCOL_ERROR"


# ------------------------------------------------------------- catalog

import functools


@functools.lru_cache(maxsize=1)
def error_catalog() -> dict:
    """The stable error-class catalog (reference:
    `spark/src/main/resources/error/delta-error-classes.json` +
    `DeltaThrowableHelper.scala`): maps every ``error_class`` to its
    message template and SQLSTATE."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "resources",
                        "error_classes.json")
    with open(path) as f:
        return json.load(f)


def error_info(err: "DeltaError") -> dict:
    """Structured view of an error: class, SQLSTATE, template, message,
    and the bound context parameters — what the reference surfaces
    through `DeltaThrowableHelper`."""
    catalog = error_catalog()
    entry = catalog.get(err.error_class) or catalog["DELTA_ERROR"]
    return {
        "errorClass": err.error_class,
        "sqlState": entry["sqlState"],
        "messageTemplate": " ".join(entry["message"]),
        "message": str(err),
        "parameters": dict(getattr(err, "context", {}) or {}),
    }

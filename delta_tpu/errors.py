"""Error hierarchy for delta-tpu.

Mirrors the reference's error taxonomy: the concurrent-modification family
raised by conflict checking (spark `DeltaErrors.scala` /
`ConflictChecker.scala:175`), commit failures discriminated as
retryable-vs-conflict (`CommitFailedException`, OptimisticTransaction
retry loop), and the kernel's Table/Snapshot resolution errors.

Each error carries a stable ``error_class`` string (the reference keeps a
JSON catalog of these in ``delta-error-classes.json``) so callers can match
on class rather than message text.
"""

from __future__ import annotations


class DeltaError(Exception):
    """Base class for all delta-tpu errors."""

    error_class: str = "DELTA_ERROR"

    def __init__(self, message: str = "", **context):
        super().__init__(message)
        self.context = context


class TableNotFoundError(DeltaError):
    error_class = "DELTA_TABLE_NOT_FOUND"


class VersionNotFoundError(DeltaError):
    """Requested version is outside the reconstructable range."""

    error_class = "DELTA_VERSION_NOT_FOUND"

    def __init__(self, version=None, earliest=None, latest=None):
        super().__init__(
            f"Cannot time travel Delta table to version {version}. "
            f"Available versions: [{earliest}, {latest}].",
            version=version,
            earliest=earliest,
            latest=latest,
        )


class TimestampEarlierThanCommitRetentionError(DeltaError):
    error_class = "DELTA_TIMESTAMP_EARLIER_THAN_COMMIT_RETENTION"


class TimestampLaterThanLatestCommitError(DeltaError):
    error_class = "DELTA_TIMESTAMP_LATER_THAN_LATEST_COMMIT"


class CommitFailedError(DeltaError):
    """A commit attempt failed.

    ``retryable`` discriminates transient failures (retry at same version)
    from losses of the put-if-absent race (rebase + retry at version+1);
    ``conflict`` marks the latter. Mirrors the semantics of
    storage `CommitFailedException` consumed by
    `OptimisticTransaction.scala:2229-2254`.
    """

    error_class = "DELTA_COMMIT_FAILED"

    def __init__(self, message: str, retryable: bool = False, conflict: bool = False):
        super().__init__(message)
        self.retryable = retryable
        self.conflict = conflict


class ConcurrentModificationError(DeltaError):
    """Base for logical conflicts detected against winning commits."""

    error_class = "DELTA_CONCURRENT_MODIFICATION"


class ProtocolChangedError(ConcurrentModificationError):
    error_class = "DELTA_PROTOCOL_CHANGED"


class MetadataChangedError(ConcurrentModificationError):
    error_class = "DELTA_METADATA_CHANGED"


class ConcurrentAppendError(ConcurrentModificationError):
    """A winning commit added files that this transaction's read predicate
    might have matched."""

    error_class = "DELTA_CONCURRENT_APPEND"


class ConcurrentDeleteReadError(ConcurrentModificationError):
    """A winning commit removed a file this transaction read."""

    error_class = "DELTA_CONCURRENT_DELETE_READ"


class ConcurrentDeleteDeleteError(ConcurrentModificationError):
    """A winning commit removed a file this transaction also removes."""

    error_class = "DELTA_CONCURRENT_DELETE_DELETE"


class ConcurrentTransactionError(ConcurrentModificationError):
    """A winning commit advanced an idempotent-txn appId this transaction read."""

    error_class = "DELTA_CONCURRENT_TRANSACTION"


class ConcurrentWriteError(ConcurrentModificationError):
    error_class = "DELTA_CONCURRENT_WRITE"


class MaxCommitRetriesExceededError(DeltaError):
    error_class = "DELTA_MAX_COMMIT_RETRIES_EXCEEDED"


class InvariantViolationError(DeltaError):
    """NOT NULL / CHECK constraint violated by written data."""

    error_class = "DELTA_VIOLATE_CONSTRAINT"


class UnsupportedTableFeatureError(DeltaError):
    """Protocol requires a reader/writer feature this client does not implement."""

    error_class = "DELTA_UNSUPPORTED_FEATURES_FOR_READ"

    def __init__(self, features, read: bool = True):
        kind = "read" if read else "write"
        super().__init__(
            f"Unsupported Delta table features for {kind}: {sorted(features)}",
            features=sorted(features),
        )
        self.features = frozenset(features)


class InvalidProtocolVersionError(DeltaError):
    error_class = "DELTA_INVALID_PROTOCOL_VERSION"


class ChecksumMismatchError(DeltaError):
    """Post-replay state disagrees with the `.crc` version checksum."""

    error_class = "DELTA_CHECKSUM_MISMATCH"


class CorruptStatsError(DeltaError):
    """Stats content failed to decode (invalid JSON escapes)."""

    error_class = "DELTA_CORRUPT_STATS"


class SchemaMismatchError(DeltaError):
    error_class = "DELTA_SCHEMA_MISMATCH"


class PartitionColumnMismatchError(DeltaError):
    error_class = "DELTA_PARTITION_COLUMN_MISMATCH"


# ------------------------------------------------------------- catalog

import functools


@functools.lru_cache(maxsize=1)
def error_catalog() -> dict:
    """The stable error-class catalog (reference:
    `spark/src/main/resources/error/delta-error-classes.json` +
    `DeltaThrowableHelper.scala`): maps every ``error_class`` to its
    message template and SQLSTATE."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "resources",
                        "error_classes.json")
    with open(path) as f:
        return json.load(f)


def error_info(err: "DeltaError") -> dict:
    """Structured view of an error: class, SQLSTATE, template, message,
    and the bound context parameters — what the reference surfaces
    through `DeltaThrowableHelper`."""
    catalog = error_catalog()
    entry = catalog.get(err.error_class) or catalog["DELTA_ERROR"]
    return {
        "errorClass": err.error_class,
        "sqlState": entry["sqlState"],
        "messageTemplate": " ".join(entry["message"]),
        "message": str(err),
        "parameters": dict(getattr(err, "context", {}) or {}),
    }

"""Table-features registry and protocol negotiation.

PROTOCOL.md:844-876 / reference `TableFeature.scala` +
`TableFeatureSupport.scala`: capability flags with reader/writer version
gating. `readerFeatures` may only exist at (3,7); `writerFeatures` at
writer 7. A *supported* feature is listed in the protocol; it is *active*
only when its metadata requirement is also met (e.g. deletionVectors
supported vs `delta.enableDeletionVectors=true`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional

from delta_tpu.errors import InvalidProtocolVersionError, InvalidTablePropertyError, UnsupportedTableFeatureError
from delta_tpu.models.actions import Metadata, Protocol


@dataclass(frozen=True)
class TableFeature:
    name: str
    min_reader_version: int   # 1 if writer-only
    min_writer_version: int
    is_reader_writer: bool
    # metadata predicate that makes a supported feature *active*
    activated_by: Optional[Callable[[Metadata], bool]] = None
    # legacy features are implicitly supported by older proto versions
    legacy: bool = False


FEATURES: Dict[str, TableFeature] = {}


def _feature(name, min_reader, min_writer, reader_writer, activated_by=None, legacy=False):
    f = TableFeature(name, min_reader, min_writer, reader_writer, activated_by, legacy)
    FEATURES[name] = f
    return f


def _conf_true(key):
    from delta_tpu.config import _parse_bool

    return lambda m: _parse_bool(m.configuration.get(key, ""))


APPEND_ONLY = _feature("appendOnly", 1, 2, False, _conf_true("delta.appendOnly"), legacy=True)
INVARIANTS = _feature("invariants", 1, 2, False, legacy=True)
CHECK_CONSTRAINTS = _feature(
    "checkConstraints", 1, 3, False,
    # a table CREATEd with delta.constraints.* properties needs
    # writer v3 from its first commit (ALTER ADD CONSTRAINT upgrades
    # separately through the txn)
    lambda meta: any(k.startswith("delta.constraints.")
                     for k in meta.configuration),
    legacy=True)
CHANGE_DATA_FEED = _feature(
    "changeDataFeed", 1, 4, False, _conf_true("delta.enableChangeDataFeed"), legacy=True
)
def _schema_has_metadata_key(predicate):
    """Activation by field-metadata key on any (nested) schema field —
    exact, not a substring probe of the serialized JSON."""

    def walk(fields):
        for f in fields:
            md = f.get("metadata") or {}
            if any(predicate(k) for k in md):
                return True
            t = f.get("type")
            if isinstance(t, dict) and t.get("type") == "struct":
                if walk(t.get("fields", [])):
                    return True
        return False

    def check(m):
        import json as _json

        if not m.schemaString:
            return False
        try:
            schema = _json.loads(m.schemaString)
        except ValueError:
            return False
        return walk(schema.get("fields", []))

    return check


GENERATED_COLUMNS = _feature(
    "generatedColumns", 1, 4, False,
    _schema_has_metadata_key(lambda k: k == "delta.generationExpression"),
    legacy=True)
COLUMN_MAPPING = _feature(
    "columnMapping", 2, 5, True,
    lambda m: m.configuration.get("delta.columnMapping.mode", "none") != "none",
    legacy=True,
)
IDENTITY_COLUMNS = _feature(
    "identityColumns", 1, 6, False,
    _schema_has_metadata_key(lambda k: k.startswith("delta.identity.")),
    legacy=True)
DELETION_VECTORS = _feature(
    "deletionVectors", 3, 7, True, _conf_true("delta.enableDeletionVectors")
)
ROW_TRACKING = _feature("rowTracking", 1, 7, False, _conf_true("delta.enableRowTracking"))
TIMESTAMP_NTZ = _feature("timestampNtz", 3, 7, True)
TYPE_WIDENING = _feature("typeWidening", 3, 7, True, _conf_true("delta.enableTypeWidening"))
DOMAIN_METADATA = _feature("domainMetadata", 1, 7, False)
V2_CHECKPOINT = _feature(
    "v2Checkpoint", 3, 7, True,
    lambda m: m.configuration.get("delta.checkpointPolicy", "classic") == "v2",
)
ICEBERG_COMPAT_V1 = _feature("icebergCompatV1", 1, 7, False,
                              _conf_true("delta.enableIcebergCompatV1"))
ICEBERG_COMPAT_V2 = _feature("icebergCompatV2", 1, 7, False,
                             _conf_true("delta.enableIcebergCompatV2"))
IN_COMMIT_TIMESTAMP = _feature(
    "inCommitTimestamp", 1, 7, False, _conf_true("delta.enableInCommitTimestamps")
)
VACUUM_PROTOCOL_CHECK = _feature("vacuumProtocolCheck", 3, 7, True)
CLUSTERING = _feature("clustering", 1, 7, False)
VARIANT_TYPE = _feature("variantType", 3, 7, True)
ALLOW_COLUMN_DEFAULTS = _feature(
    "allowColumnDefaults", 1, 7, False,
    _schema_has_metadata_key(lambda k: k == "CURRENT_DEFAULT"))


SUPPORTED_WRITER_FEATURES = frozenset(FEATURES)
MAX_WRITER_VERSION = 7


def protocol_for_new_table(
    configuration: Dict[str, str], schema_string: Optional[str] = None
) -> Protocol:
    """Minimal protocol satisfying the features activated by the given
    table properties / schema (reference `Protocol.forNewTable`)."""
    meta = Metadata(id="", schemaString=schema_string or "",
                    configuration=dict(configuration))
    needed = [f for f in FEATURES.values() if f.activated_by and f.activated_by(meta)]
    # delta.minReaderVersion/minWriterVersion raise the protocol floor
    # at creation; delta.ignoreProtocolDefaults drops the (1,2) base to
    # the protocol minimum (DeltaConfig.scala minReaderVersion/
    # minWriterVersion/ignoreProtocolDefaults)
    from delta_tpu import config as cfg

    try:
        if cfg.get_table_config(configuration,
                                cfg.IGNORE_PROTOCOL_DEFAULTS):
            min_reader, min_writer = 1, 1
        else:
            min_reader, min_writer = 1, 2
        raw_r = configuration.get(cfg.MIN_READER_VERSION.key)
        raw_w = configuration.get(cfg.MIN_WRITER_VERSION.key)
        forced_r = int(raw_r) if raw_r is not None else None
        forced_w = int(raw_w) if raw_w is not None else None
    except ValueError as e:
        raise InvalidTablePropertyError(
            f"invalid protocol version property: {e}",
            error_class="DELTA_PROTOCOL_PROPERTY_NOT_INT") from None
    # range/consistency validation BEFORE committing: an out-of-range
    # protocol would brick the table for every reader (incl. us)
    if forced_r is not None and not 1 <= forced_r <= 3:
        raise InvalidProtocolVersionError(
            f"requested readerVersion {forced_r} is outside 1..3")
    if forced_w is not None and not 1 <= forced_w <= MAX_WRITER_VERSION:
        raise InvalidProtocolVersionError(
            f"requested writerVersion {forced_w} is outside "
            f"1..{MAX_WRITER_VERSION}")
    if forced_r == 3 and (forced_w or 7) != 7:
        raise InvalidProtocolVersionError(
            "readerVersion 3 requires writerVersion 7 "
            "(feature-vector protocols)",
            error_class="DELTA_READ_FEATURE_PROTOCOL_REQUIRES_WRITE")
    if forced_r is not None:
        min_reader = max(min_reader, forced_r)
        if forced_r == 3:
            min_writer = 7
    if forced_w is not None:
        min_writer = max(min_writer, forced_w)
    for f in needed:
        min_reader = max(min_reader, f.min_reader_version)
        min_writer = max(min_writer, f.min_writer_version)
    non_legacy = [f for f in needed if not f.legacy]
    if non_legacy or min_writer == 7:
        # feature vectors required (writer v7 always carries explicit
        # writerFeatures, even if only legacy features are active; a
        # forced reader 3 likewise requires readerFeatures, possibly
        # empty)
        need_reader_vec = (min_reader >= 3
                           or any(f.min_reader_version >= 3
                                  for f in needed))
        reader_features = sorted(
            f.name for f in needed if f.is_reader_writer
        ) if need_reader_vec else None
        if need_reader_vec:
            min_reader = 3
        writer_features = sorted(f.name for f in needed)
        return Protocol(min_reader, 7,
                        readerFeatures=reader_features,
                        writerFeatures=writer_features)
    return Protocol(min_reader, min_writer)


def upgraded_protocol(current: Protocol, feature: TableFeature) -> Protocol:
    """Protocol after enabling `feature` (moves to (3,7)/writer-7 feature
    vectors when the feature is non-legacy)."""
    reader = set(current.readerFeatures or [])
    writer = set(current.writerFeatures or [])
    min_reader = current.minReaderVersion
    min_writer = current.minWriterVersion
    # on a legacy protocol (no feature vectors) version coverage implies
    # support; at writer 7 a feature counts only when listed
    if (feature.legacy and current.writerFeatures is None
            and min_writer < 7
            and feature.min_writer_version <= min_writer
            and (not feature.is_reader_writer
                 or feature.min_reader_version <= min_reader)):
        return current
    if feature.legacy and min_writer < 7 and current.writerFeatures is None:
        # legacy protocols bump versions instead of listing features
        # (reference: CHECK constraint on a (1,2) table → (1,3))
        return Protocol(
            max(min_reader,
                feature.min_reader_version if feature.is_reader_writer else 1),
            max(min_writer, feature.min_writer_version),
        )
    if current.writerFeatures is None:
        # converting a legacy protocol to feature vectors: every feature
        # the old (reader, writer) versions implied must be listed or it
        # silently loses support (reference Protocol.upgradeToFeatures /
        # implicitlySupportedFeatures)
        for f in FEATURES.values():
            if (f.legacy and f.min_writer_version <= min_writer
                    and (not f.is_reader_writer
                         or f.min_reader_version <= min_reader)):
                writer.add(f.name)
                if f.is_reader_writer:
                    reader.add(f.name)
    min_writer = 7
    writer.add(feature.name)
    if feature.is_reader_writer and feature.min_reader_version >= 3:
        reader.add(feature.name)
    if reader:
        min_reader = 3
    return Protocol(
        min_reader,
        min_writer,
        readerFeatures=sorted(reader) if min_reader >= 3 else None,
        writerFeatures=sorted(writer),
    )


def validate_writable(protocol: Optional[Protocol], metadata: Metadata) -> None:
    """Refuse to write tables whose protocol demands writer features we
    don't implement (`TableFeatureSupport` write-gate)."""
    if protocol is None:
        raise InvalidProtocolVersionError("missing protocol")
    if protocol.minWriterVersion > MAX_WRITER_VERSION:
        raise UnsupportedTableFeatureError(
            {f"writerVersion={protocol.minWriterVersion}"}, read=False
        )
    unsupported = protocol.writer_feature_set() - SUPPORTED_WRITER_FEATURES
    if unsupported:
        raise UnsupportedTableFeatureError(unsupported, read=False)


def is_feature_supported(protocol: Protocol, feature: TableFeature) -> bool:
    if feature.name in protocol.writer_feature_set() or (
        feature.is_reader_writer and feature.name in protocol.reader_feature_set()
    ):
        return True
    if feature.legacy:
        ok_writer = protocol.minWriterVersion >= feature.min_writer_version
        ok_reader = (
            not feature.is_reader_writer
            or protocol.minReaderVersion >= feature.min_reader_version
        )
        return ok_writer and ok_reader and protocol.minWriterVersion < 7
    return False


def is_feature_active(protocol: Protocol, metadata: Metadata, feature: TableFeature) -> bool:
    if not is_feature_supported(protocol, feature):
        return False
    if feature.activated_by is None:
        return True
    return feature.activated_by(metadata)

"""Native (C++) runtime components, bound via ctypes.

The reference's host-side hot paths are JVM-native libraries (Jackson
JSON in `DefaultJsonHandler`, parquet-mr, RoaringBitmap); here the same
roles are C++: `action_scan.cpp` is the specialized multithreaded
NDJSON scanner for `_delta_log` commit files that feeds state
reconstruction.

Build model: compiled on demand with g++ into a content-hashed cache
directory (no pip, no pybind11 — plain C ABI + ctypes). Everything
degrades gracefully: if the toolchain or compiled library is
unavailable, `load()` returns None and callers use the generic
Arrow-based parser.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_SOURCES = (
    os.path.join(_SRC_DIR, "action_scan.cpp"),
    os.path.join(_SRC_DIR, "fa_encode.cpp"),
)
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _cache_dir() -> str:
    base = os.environ.get("DELTA_TPU_NATIVE_CACHE")
    if base:
        return base
    xdg = os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache"))
    return os.path.join(xdg, "delta_tpu_native")


def _build(allow_compile: bool = True) -> Optional[str]:
    h = hashlib.sha256()
    for src_path in _SOURCES:
        with open(src_path, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    out_dir = _cache_dir()
    lib_path = os.path.join(out_dir, f"libdeltatpu-{tag}.so")
    if os.path.exists(lib_path):
        return lib_path
    if not allow_compile:
        return None
    os.makedirs(out_dir, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
    os.close(fd)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        *_SOURCES, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.replace(tmp, lib_path)  # atomic: racing builders both succeed
        return lib_path
    except (subprocess.SubprocessError, OSError):
        # g++ missing/failed/timed out: pure-python paths take over
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def load(allow_compile: bool = True) -> Optional[ctypes.CDLL]:
    """Compile (once, cached) and load the native library; None if the
    toolchain is unavailable. Safe to call from any thread. With
    allow_compile=False only a pre-built cached library is loaded —
    callers on a latency-sensitive path use this so a cold cache never
    blocks on a g++ subprocess."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        if os.environ.get("DELTA_TPU_DISABLE_NATIVE"):
            _TRIED = True
            return None
        # delta-lint: disable=lock-io (audited: the double-checked once-
        # only compile MUST hold the lock across g++ so concurrent first
        # callers don't race duplicate builds; all later calls hit the
        # _LIB/_TRIED fast path above without the lock)
        path = _build(allow_compile)
        if path is None:
            # only a definitive failure (compile attempted) is final
            _TRIED = allow_compile
            return None
        _TRIED = True
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.das_scan.restype = ctypes.c_void_p
        lib.das_scan2.restype = ctypes.c_void_p
        lib.das_scan2.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_int32, ctypes.c_int32]
        lib.das_stats_materialize.restype = ctypes.c_int32
        lib.das_stats_materialize.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p,
                                              ctypes.c_int64]
        lib.das_scan.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                 ctypes.c_int32]
        lib.das_free.argtypes = [ctypes.c_void_p]
        lib.das_error.restype = ctypes.c_int32
        lib.das_error.argtypes = [ctypes.c_void_p]
        lib.das_n.restype = ctypes.c_int64
        lib.das_n.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.das_ptr.restype = ctypes.c_void_p
        lib.das_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.fae_encode.restype = ctypes.c_void_p
        lib.fae_encode.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_int64, ctypes.c_int64,
                                   ctypes.c_int32]
        lib.fae_free.argtypes = [ctypes.c_void_p]
        lib.fae_error.restype = ctypes.c_int32
        lib.fae_error.argtypes = [ctypes.c_void_p]
        lib.fae_n.restype = ctypes.c_int64
        lib.fae_n.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.fae_ptr.restype = ctypes.c_void_p
        lib.fae_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.dar_read.restype = ctypes.c_void_p
        lib.dar_read.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                 ctypes.c_int32]
        lib.dar_free.argtypes = [ctypes.c_void_p]
        lib.dar_error.restype = ctypes.c_int32
        lib.dar_error.argtypes = [ctypes.c_void_p]
        lib.dar_len.restype = ctypes.c_int64
        lib.dar_len.argtypes = [ctypes.c_void_p]
        lib.dar_buf.restype = ctypes.c_void_p
        lib.dar_buf.argtypes = [ctypes.c_void_p]
        lib.dar_starts.restype = ctypes.c_void_p
        lib.dar_starts.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def available(allow_compile: bool = True) -> bool:
    return load(allow_compile) is not None


# buffers below this size parse in negligible time either way — not
# worth triggering a first-time g++ compile on the read path
MIN_BYTES_FOR_COLD_BUILD = 4 << 20


def _np(lib, h, which: int, n: int, dtype, ptr_fn=None) -> np.ndarray:
    """Copy column `which` out of a native result handle as a numpy
    array. `ptr_fn` selects the accessor (das_ptr for scans, fae_ptr for
    encoder results)."""
    if n == 0:
        return np.empty(0, dtype)
    ptr = (ptr_fn or lib.das_ptr)(h, which)
    itemsize = np.dtype(dtype).itemsize
    buf = ctypes.cast(ptr, ctypes.POINTER(ctypes.c_char * (n * itemsize)))
    return np.frombuffer(buf.contents, dtype=dtype).copy()


class _NativeScanHandle:
    """Owns one das_scan result; freed when the last referencing Arrow
    buffer (or the ScanResult) is collected. Foreign buffers reference
    THIS object, not the ScanResult, so no reference cycle forms."""

    __slots__ = ("_lib", "_h")

    def __init__(self, lib, h):
        self._lib = lib
        self._h = h

    def __del__(self):
        try:
            self._lib.das_free(self._h)
        # delta-lint: disable=except-swallow (audited: __del__ runs at
        # arbitrary points incl. interpreter shutdown where the ctypes
        # lib may be half-torn-down; raising or logging here is unsafe)
        except Exception:
            pass


class _NativeReadHandle:
    """Owns one dar_read buffer (lazy-stats spans point into it)."""

    __slots__ = ("_lib", "_h")

    def __init__(self, lib, h):
        self._lib = lib
        self._h = h

    def __del__(self):
        try:
            self._lib.dar_free(self._h)
        # delta-lint: disable=except-swallow (audited: same __del__
        # shutdown-safety contract as _NativeScanHandle)
        except Exception:
            pass


class ScanResult:
    """Columnar output of one native scan.

    Replay-side arrays (codes, flags, refs, line maps) are numpy copies;
    the heavyweight arenas and numeric value buffers that Arrow consumes
    stay in native memory as zero-copy `pa.foreign_buffer`s whose `base`
    keeps the scan handle alive — at the 10M-row scale this avoids
    copying ~2GB through a slow memory system."""

    def __init__(self, lib, h):
        import pyarrow as pa

        owner = self._owner = _NativeScanHandle(lib, h)
        n = self.n_rows = int(lib.das_n(h, 0))
        self.n_lines = int(lib.das_n(h, 1))
        n_oth = self.n_others = int(lib.das_n(h, 2))
        n_pv = self.n_pv_entries = int(lib.das_n(h, 3))

        def col(which, count, dtype):
            return _np(lib, h, which, count, dtype)

        def fbuf(which, nbytes):
            if nbytes == 0:
                return pa.py_buffer(b"")
            return pa.foreign_buffer(lib.das_ptr(h, which), nbytes,
                                     base=owner)

        def strcol(off_which, arena_n_idx, valid_which, count):
            offsets = fbuf(off_which, (count + 1) * 4)
            arena = fbuf(off_which + 1, int(lib.das_n(h, arena_n_idx)))
            if valid_which is None:  # keys are never null
                valid = np.ones(count, dtype=bool)
            else:
                valid = col(valid_which, count, np.uint8).astype(bool)
            return offsets, arena, valid

        def numcol(val_which, valid_which, count, width):
            return (fbuf(val_which, count * width),
                    col(valid_which, count, np.uint8).astype(bool))

        n_uniq = self.n_uniq = int(lib.das_n(h, 4))
        n_refs = self.n_refs = int(lib.das_n(h, 5))
        self.line_no = col(0, n, np.int64)
        self.is_add = col(1, n, np.uint8).astype(bool)
        # dictionary-encoded paths: per-row first-appearance codes plus
        # the unique-path arena in code order; `path_new`/`refs` are the
        # ready-made first-appearance delta encoding (ops/replay.py)
        self.path_code = col(2, n, np.uint32)
        self.path_new = col(3, n, np.uint8).astype(bool)
        self.refs = col(4, n_refs, np.uint32)
        self.uniq_offs = col(5, n_uniq + 1, np.uint32)
        self.uniq_arena = fbuf(6, int(lib.das_n(h, 6)))
        self.pv_offsets = fbuf(7, (n + 1) * 4)
        self.pv_valid = col(8, n, np.uint8).astype(bool)
        self.pv_key = strcol(9, 7, None, n_pv)
        self.pv_val = strcol(11, 8, 13, n_pv)
        self.size = numcol(14, 15, n, 8)
        self.mod_time = numcol(16, 17, n, 8)
        self.data_change = (col(18, n, np.uint8).astype(bool),
                            col(19, n, np.uint8).astype(bool))
        # lazy-stats mode: the stats column is still raw escaped spans in
        # the input buffer; materialize_stats() decodes it on demand
        self.stats_lazy = bool(lib.das_n(h, 14))
        if self.stats_lazy:
            self.stats = None
            self._stats_valid = col(57, n, np.uint8).astype(bool)
        else:
            self.stats = strcol(20, 9, 22, n)
        self.tags = strcol(23, 10, 25, n)
        self.dv_valid = col(26, n, np.uint8).astype(bool)
        self.dv_storage = strcol(27, 11, 29, n)
        self.dv_pathinline = strcol(30, 12, 32, n)
        self.dv_offset = numcol(33, 34, n, 4)
        self.dv_size = numcol(35, 36, n, 4)
        self.dv_card = numcol(37, 38, n, 8)
        self.dv_maxrow = numcol(39, 40, n, 8)
        self.base_row_id = numcol(41, 42, n, 8)
        self.drcv = numcol(43, 44, n, 8)
        self.clustering = strcol(45, 13, 47, n)
        self.del_ts = numcol(48, 49, n, 8)
        self.ext_meta = (col(50, n, np.uint8).astype(bool),
                         col(51, n, np.uint8).astype(bool))
        self.other_line_no = col(52, n_oth, np.int64)
        self.other_start = col(53, n_oth, np.int64)
        self.other_end = col(54, n_oth, np.int64)
        self.line_starts = col(55, self.n_lines, np.int64)

    def attach_read_buffer(self, rh, buf_ptr, total: int) -> None:
        """Adopt the dar_read handle whose buffer the lazy stats spans
        reference. Trade-off made explicit: until stats materialize (or
        never, for pure metadata snapshots) the WHOLE raw commit buffer
        stays resident — ~1.6x the bytes the eager path's decoded stats
        arena would hold — in exchange for skipping the decode entirely.
        The buffer is released as soon as materialization runs."""
        self._rh = _NativeReadHandle(self._owner._lib, rh)
        self._rh_buf = buf_ptr
        self._rh_len = total

    def attach_py_buffer(self, owner, addr: int, total: int) -> None:
        """Python-owned-buffer counterpart of `attach_read_buffer` (the
        `scan_actions(lazy_stats=True)` path): `owner` is whatever object
        keeps the scanned bytes alive and pinned at `addr`. Released on
        materialization, same as the native read handle."""
        self._rh = owner
        self._rh_buf = addr
        self._rh_len = total

    def materialize_stats(self) -> None:
        """Decode the deferred stats spans into the standard column
        buffers (idempotent, thread-safe — ctypes drops the GIL during
        the native call, so an unguarded double call would race on the
        native result)."""
        if not self.stats_lazy:
            return
        import threading

        lock = self.__dict__.setdefault("_stats_lock", threading.Lock())
        with lock:
            if not self.stats_lazy:
                return
            self._materialize_stats_locked()

    def _materialize_stats_locked(self) -> None:
        import pyarrow as pa

        lib = self._owner._lib
        h = self._owner._h
        rc = lib.das_stats_materialize(
            h, ctypes.cast(self._rh_buf, ctypes.c_char_p), self._rh_len)
        if rc != 0:
            from delta_tpu.errors import CorruptStatsError

            raise CorruptStatsError(
                "stats string contains invalid JSON escapes (surfaced at "
                "deferred decode; the eager path reports this at load "
                "time via the generic-parser fallback)")
        n = self.n_rows

        def fbuf(which, nbytes):
            if nbytes == 0:
                return pa.py_buffer(b"")
            return pa.foreign_buffer(lib.das_ptr(h, which), nbytes,
                                     base=self._owner)

        offsets = fbuf(20, (n + 1) * 4)
        arena = fbuf(21, int(lib.das_n(h, 9)))
        self.stats = (offsets, arena, self._stats_valid)
        self.stats_lazy = False
        # spans no longer needed; the read buffer may now be released
        rh = getattr(self, "_rh", None)
        if rh is not None:
            self._rh = None

    def uniq_strings(self):
        """Unique paths (code order) as an Arrow string array."""
        import pyarrow as pa

        return pa.StringArray.from_buffers(
            self.n_uniq, pa.py_buffer(self.uniq_offs.view(np.int32)),
            self.uniq_arena)

    def path_list(self) -> list:
        """Per-row path strings (tests/small results; the hot path keeps
        codes + the unique arena)."""
        uniq = self.uniq_strings().to_pylist()
        return [uniq[c] for c in self.path_code]


def scan_actions(buf, n_threads: int = 0,
                 lazy_stats: bool = False) -> Optional[ScanResult]:
    """Scan a buffer of newline-delimited Delta action JSON. Returns
    None when the native library is unavailable or the buffer doesn't
    parse as well-formed action lines (caller falls back).

    `lazy_stats` defers the stats-string decode (the bulk of commit
    bytes): the result keeps `buf` alive and pinned until
    `materialize_stats()` runs — same contract as the
    `scan_commit_files` lazy path, with a Python-owned buffer."""
    lib = load()
    if lib is None or len(buf) == 0:
        # a zero-byte buffer allocates none of the column buffers the
        # result would wrap; let the caller's generic path handle it
        return None
    if n_threads <= 0:
        from delta_tpu.utils.threads import default_scan_threads

        n_threads = default_scan_threads()
    if isinstance(buf, (bytes, bytearray, memoryview)):
        n_bytes = len(buf)
        if isinstance(buf, bytes):
            data = buf
        else:  # zero-copy view of a writable buffer
            data = (ctypes.c_char * n_bytes).from_buffer(
                buf if isinstance(buf, bytearray) else bytearray(buf))
    else:
        data = bytes(buf)
        n_bytes = len(data)
    if lazy_stats:
        # the deferred decode re-reads the SAME address later, so take a
        # stable pointer now and keep `data` (which pins the bytes) on
        # the result
        if isinstance(data, bytes):
            addr = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
        else:
            addr = ctypes.addressof(data)
        h = lib.das_scan2(ctypes.cast(addr, ctypes.c_char_p), n_bytes,
                          n_threads, 1)
    else:
        h = lib.das_scan(data, n_bytes, n_threads)
    if lib.das_error(h):
        lib.das_free(h)
        return None
    try:
        res = ScanResult(lib, h)  # handle ownership moves to the result
    except BaseException:
        lib.das_free(h)
        raise
    if res.stats_lazy:
        res.attach_py_buffer(data, addr, n_bytes)
    return res


def scan_commit_files(paths, lazy_stats: bool = False) -> Optional[tuple]:
    """Read a list of LOCAL commit files and scan them in one native
    round-trip (no per-file Python overhead, no buffer copy into the
    interpreter). Returns (ScanResult, others_bytes, file_starts,
    total_bytes) where others_bytes is the raw line bytes of each
    non-file action (index-aligned with ScanResult.other_line_no), or
    None when the library is unavailable or either step fails."""
    lib = load()
    if lib is None or not paths:
        return None
    blob = "".join(paths).encode("utf-8")
    offs = np.zeros(len(paths) + 1, dtype=np.int64)
    np.cumsum([len(p.encode("utf-8")) for p in paths], out=offs[1:])
    rh = lib.dar_read(blob, offs.ctypes.data_as(ctypes.c_void_p), len(paths))
    try:
        if lib.dar_error(rh):
            return None
        total = int(lib.dar_len(rh))
        buf_ptr = lib.dar_buf(rh)
        starts = _np(lib, rh, 0, len(paths) + 1, np.int64,
                     ptr_fn=lambda h, w: lib.dar_starts(h))
        from delta_tpu.utils.threads import default_scan_threads

        sh = lib.das_scan2(ctypes.cast(buf_ptr, ctypes.c_char_p), total,
                           default_scan_threads(),
                           1 if lazy_stats else 0)
        if lib.das_error(sh):
            lib.das_free(sh)
            return None
        try:
            scan = ScanResult(lib, sh)  # ownership moves to the result
        except BaseException:
            lib.das_free(sh)
            raise
        # slice the non-file-action lines out while the buffer is alive
        raw = (ctypes.c_char * total).from_address(buf_ptr) if total else b""
        others = [bytes(raw[int(s):int(e)])
                  for s, e in zip(scan.other_start, scan.other_end)]
        if scan.stats_lazy:
            # the spans reference the read buffer: the result adopts it
            scan.attach_read_buffer(rh, buf_ptr, total)
            rh = None
        return scan, others, starts, total
    finally:
        if rh is not None:
            lib.dar_free(rh)


class FaEncoded:
    """Output of the native first-appearance delta encoder — same fields
    the numpy `_try_fa_encode` produces (see ops/replay.py)."""

    __slots__ = ("flag_words", "ref_planes", "sub_idx", "sub_val",
                 "sub_radix", "nbytes", "primary_max")

    def __init__(self, lib, h):
        n_words = int(lib.fae_n(h, 0))
        r_pad = int(lib.fae_n(h, 2))
        ref_width = int(lib.fae_n(h, 3))
        d_pad = int(lib.fae_n(h, 5))
        self.sub_radix = int(lib.fae_n(h, 6))
        self.primary_max = int(lib.fae_n(h, 7))
        self.flag_words = _np(lib, h, 0, n_words, np.uint32,
                              ptr_fn=lib.fae_ptr)
        planes_flat = _np(lib, h, 1, ref_width * r_pad, np.uint8,
                          ptr_fn=lib.fae_ptr)
        self.ref_planes = tuple(
            np.ascontiguousarray(planes_flat[j * r_pad:(j + 1) * r_pad])
            for j in range(ref_width))
        if self.sub_radix > 1:
            self.sub_idx = _np(lib, h, 2, d_pad, np.uint32,
                               ptr_fn=lib.fae_ptr)
            self.sub_val = _np(lib, h, 3, d_pad, np.uint32,
                               ptr_fn=lib.fae_ptr)
        else:
            self.sub_idx = np.empty(0, np.uint32)
            self.sub_val = np.empty(0, np.uint32)
        self.nbytes = (self.flag_words.nbytes
                       + sum(p.nbytes for p in self.ref_planes)
                       + self.sub_idx.nbytes + self.sub_val.nbytes)


NOT_FA = object()  # definitive "stream is not first-appearance coded"


def fa_encode(primary: np.ndarray, sub: Optional[np.ndarray], n: int,
              m: int, n_threads: int = 0, allow_compile: bool = False):
    """Native first-appearance delta encoding of a combined key stream.
    `primary` is the uint32 primary code lane (length n), `sub` the
    optional pre-combined uint32 sub lane. Returns a FaEncoded, None when
    the library is unavailable (caller falls back to numpy), or the
    NOT_FA sentinel when the stream is definitively not
    first-appearance coded (caller skips straight to byte planes). Pass
    allow_compile=True on large inputs where a one-off g++ build is
    worth the wait."""
    lib = load(allow_compile=allow_compile)
    if lib is None:
        return None
    if n_threads <= 0:
        from delta_tpu.utils.threads import default_scan_threads

        n_threads = default_scan_threads()
    primary = np.ascontiguousarray(primary, dtype=np.uint32)
    pk_ptr = primary.ctypes.data_as(ctypes.c_void_p)
    if sub is not None:
        sub = np.ascontiguousarray(sub, dtype=np.uint32)
        dk_ptr = sub.ctypes.data_as(ctypes.c_void_p)
    else:
        dk_ptr = None
    h = lib.fae_encode(pk_ptr, dk_ptr, n, m, n_threads)
    try:
        if lib.fae_error(h):
            return NOT_FA
        return FaEncoded(lib, h)
    finally:
        lib.fae_free(h)

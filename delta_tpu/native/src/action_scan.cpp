// action_scan: specialized multithreaded NDJSON scanner for Delta log
// commit files.
//
// The reference leans on Jackson for this (DefaultJsonHandler,
// kernel-defaults/.../DefaultJsonHandler.java; spark pays it as a JSON
// scan at Snapshot.scala:524). A generic JSON reader must infer a
// unified schema and materialize every field; this scanner knows the
// action schema (PROTOCOL.md:418-822) and emits exactly the columnar
// buffers the canonical file-actions table needs: add/remove rows fully
// decoded into arenas + offsets + validity, everything else (protocol,
// metaData, txn, domainMetadata, commitInfo — O(commits), not O(files))
// returned as byte spans for the host to json.loads.
//
// Contract with the Python side (delta_tpu/native/__init__.py):
// - das_scan(buf, len, n_threads) -> opaque handle (never NULL)
// - das_error(h): 0 ok; 1 = structural parse failure, caller must fall
//   back to the generic parser (no partial results are exposed)
// - das_n(h, i) / das_ptr(h, i): counts and column pointers by the
//   DasField enum below — indices are mirrored in the Python binding.
// - all string columns are (int32 end-offsets per row, one byte arena,
//   uint8 validity); map columns add per-entry offsets. Offsets are
//   Arrow-style: offsets[0] == 0 stored implicitly; the exposed array
//   holds n+1 entries including the leading 0.
//
// Unescaping: full JSON string unescape including \uXXXX surrogate
// pairs -> UTF-8. Raw-capture fields (tags) keep the original JSON
// text, which is itself valid JSON.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- builders

struct StrCol {
  std::string arena;
  std::vector<int32_t> ends;   // running end offset per row (local)
  std::vector<uint8_t> valid;
  void add_null() { ends.push_back((int32_t)arena.size()); valid.push_back(0); }
  void add(const char* s, size_t n) {
    arena.append(s, n);
    ends.push_back((int32_t)arena.size());
    valid.push_back(1);
  }
  void add(const std::string& s) { add(s.data(), s.size()); }
};

template <typename T>
struct NumCol {
  std::vector<T> vals;
  std::vector<uint8_t> valid;
  void add_null() { vals.push_back(0); valid.push_back(0); }
  void add(T v) { vals.push_back(v); valid.push_back(1); }
};

struct Builder {
  std::vector<int64_t> line_no;      // global row number of each file action
  std::vector<uint8_t> is_add;
  StrCol path;
  // partitionValues: per-row entry count; per-entry key/value
  std::vector<int32_t> pv_nentries;
  std::vector<uint8_t> pv_valid;     // row-level presence of the object
  StrCol pv_key;                     // validity unused (keys non-null)
  StrCol pv_val;
  NumCol<int64_t> size;
  NumCol<int64_t> mod_time;
  NumCol<uint8_t> data_change;
  StrCol stats;
  StrCol tags;                       // raw JSON text of the tags object
  std::vector<uint8_t> dv_valid;
  StrCol dv_storage;
  StrCol dv_pathinline;
  NumCol<int32_t> dv_offset;
  NumCol<int32_t> dv_size;
  NumCol<int64_t> dv_card;
  NumCol<int64_t> dv_maxrow;
  NumCol<int64_t> base_row_id;
  NumCol<int64_t> drcv;
  StrCol clustering;
  NumCol<int64_t> del_ts;
  NumCol<uint8_t> ext_meta;
  // non-file-action lines: (global row number, byte start, byte end)
  std::vector<int64_t> other_line_no;
  std::vector<int64_t> other_start;
  std::vector<int64_t> other_end;
  // byte start of every non-blank line, in order (global row numbering)
  std::vector<int64_t> line_starts;
  bool failed = false;
};

// ---------------------------------------------------------------- lexing

struct Cursor {
  const char* p;
  const char* end;
  bool ok = true;
  void ws() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p; }
  bool lit(char c) { ws(); if (p < end && *p == c) { ++p; return true; } return false; }
  char peek() { ws(); return p < end ? *p : '\0'; }
};

void append_utf8(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out.push_back((char)cp);
  } else if (cp < 0x800) {
    out.push_back((char)(0xC0 | (cp >> 6)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back((char)(0xE0 | (cp >> 12)));
    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  } else {
    out.push_back((char)(0xF0 | (cp >> 18)));
    out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  }
}

int hex4(const char* p) {
  int v = 0;
  for (int i = 0; i < 4; i++) {
    char c = p[i];
    v <<= 4;
    if (c >= '0' && c <= '9') v |= c - '0';
    else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
    else return -1;
  }
  return v;
}

// Parse a JSON string (cursor at opening quote). out receives the
// unescaped bytes. Returns false on malformed input.
bool parse_string(Cursor& c, std::string& out) {
  out.clear();
  if (!c.lit('"')) return false;
  const char* p = c.p;
  const char* end = c.end;
  // fast path: no escapes
  const char* q = p;
  while (q < end && *q != '"' && *q != '\\') ++q;
  if (q < end && *q == '"') {
    out.assign(p, q - p);
    c.p = q + 1;
    return true;
  }
  out.assign(p, q - p);
  p = q;
  while (p < end) {
    char ch = *p;
    if (ch == '"') { c.p = p + 1; return true; }
    if (ch != '\\') { out.push_back(ch); ++p; continue; }
    if (p + 1 >= end) return false;
    char e = p[1];
    p += 2;
    switch (e) {
      case '"': out.push_back('"'); break;
      case '\\': out.push_back('\\'); break;
      case '/': out.push_back('/'); break;
      case 'b': out.push_back('\b'); break;
      case 'f': out.push_back('\f'); break;
      case 'n': out.push_back('\n'); break;
      case 'r': out.push_back('\r'); break;
      case 't': out.push_back('\t'); break;
      case 'u': {
        if (p + 4 > end) return false;
        int v = hex4(p);
        if (v < 0) return false;
        p += 4;
        uint32_t cp = (uint32_t)v;
        if (cp >= 0xD800 && cp <= 0xDBFF && p + 6 <= end && p[0] == '\\' &&
            p[1] == 'u') {
          int lo = hex4(p + 2);
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + ((uint32_t)lo - 0xDC00);
            p += 6;
          }
        }
        append_utf8(out, cp);
        break;
      }
      default: return false;
    }
  }
  return false;
}

bool skip_string(Cursor& c) {
  if (!c.lit('"')) return false;
  const char* p = c.p;
  while (p < c.end) {
    if (*p == '\\') { p += 2; continue; }
    if (*p == '"') { c.p = p + 1; return true; }
    ++p;
  }
  return false;
}

// Skip any JSON value (cursor at its first char). String-aware.
bool skip_value(Cursor& c) {
  char ch = c.peek();
  if (ch == '"') return skip_string(c);
  if (ch == '{' || ch == '[') {
    char open = ch, close = (ch == '{') ? '}' : ']';
    c.lit(open);
    int depth = 1;
    const char* p = c.p;
    while (p < c.end && depth) {
      char d = *p;
      if (d == '"') {
        ++p;
        while (p < c.end) {
          if (*p == '\\') { p += 2; continue; }
          if (*p == '"') { ++p; break; }
          ++p;
        }
        continue;
      }
      if (d == open) ++depth;
      else if (d == close) --depth;
      ++p;
    }
    c.p = p;
    return depth == 0;
  }
  // literal / number: consume until a delimiter
  const char* p = c.p;
  while (p < c.end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
         *p != '\t' && *p != '\r' && *p != '\n')
    ++p;
  bool any = p != c.p;
  c.p = p;
  return any;
}

// Capture the raw text of the next value (objects only in practice).
bool capture_raw(Cursor& c, const char** start, const char** stop) {
  c.ws();
  *start = c.p;
  if (!skip_value(c)) return false;
  *stop = c.p;
  return true;
}

enum NumKind { NUM_NULL, NUM_INT, NUM_BOOL_TRUE, NUM_BOOL_FALSE, NUM_BAD };

// Integers (JSON numbers without fraction/exponent are the norm for the
// action schema; fractional/exponent forms are truncated via strtod).
NumKind parse_num_or_lit(Cursor& c, int64_t* out) {
  char ch = c.peek();
  if (ch == 'n') { c.p += 4 <= c.end - c.p ? 4 : 0; return NUM_NULL; }
  if (ch == 't') { c.p += 4 <= c.end - c.p ? 4 : 0; return NUM_BOOL_TRUE; }
  if (ch == 'f') { c.p += 5 <= c.end - c.p ? 5 : 0; return NUM_BOOL_FALSE; }
  const char* p = c.p;
  bool neg = false;
  if (p < c.end && (*p == '-' || *p == '+')) { neg = *p == '-'; ++p; }
  int64_t v = 0;
  const char* digits = p;
  while (p < c.end && *p >= '0' && *p <= '9') { v = v * 10 + (*p - '0'); ++p; }
  if (p == digits) return NUM_BAD;
  if (p < c.end && (*p == '.' || *p == 'e' || *p == 'E')) {
    char* endp = nullptr;
    double d = strtod(c.p, &endp);
    if (endp == c.p) return NUM_BAD;
    c.p = endp;
    *out = (int64_t)d;
    return NUM_INT;
  }
  c.p = p;
  *out = neg ? -v : v;
  return NUM_INT;
}

bool key_is(const std::string& k, const char* name) { return k == name; }

// ------------------------------------------------------------- action parse

// deletionVector object
bool parse_dv(Cursor& c, Builder& b) {
  if (!c.lit('{')) return false;
  b.dv_valid.push_back(1);
  bool s_storage = false, s_path = false, s_off = false, s_size = false,
       s_card = false, s_max = false;
  std::string key, sval;
  if (c.peek() == '}') { c.lit('}'); }
  else {
    while (true) {
      if (!parse_string(c, key)) return false;
      if (!c.lit(':')) return false;
      int64_t num;
      // duplicate keys (legal JSON) would misalign the column builders:
      // fail the scan so the caller uses the generic parser
      if (key_is(key, "storageType")) {
        if (s_storage) return false;
        if (c.peek() == '"') { if (!parse_string(c, sval)) return false; b.dv_storage.add(sval); s_storage = true; }
        else if (!skip_value(c)) return false;
      } else if (key_is(key, "pathOrInlineDv")) {
        if (s_path) return false;
        if (c.peek() == '"') { if (!parse_string(c, sval)) return false; b.dv_pathinline.add(sval); s_path = true; }
        else if (!skip_value(c)) return false;
      } else if (key_is(key, "offset")) {
        if (s_off) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.dv_offset.add((int32_t)num); s_off = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "sizeInBytes")) {
        if (s_size) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.dv_size.add((int32_t)num); s_size = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "cardinality")) {
        if (s_card) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.dv_card.add(num); s_card = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "maxRowIndex")) {
        if (s_max) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.dv_maxrow.add(num); s_max = true; }
        else if (k != NUM_NULL) return false;
      } else {
        if (!skip_value(c)) return false;
      }
      if (c.lit(',')) continue;
      if (c.lit('}')) break;
      return false;
    }
  }
  if (!s_storage) b.dv_storage.add_null();
  if (!s_path) b.dv_pathinline.add_null();
  if (!s_off) b.dv_offset.add_null();
  if (!s_size) b.dv_size.add_null();
  if (!s_card) b.dv_card.add_null();
  if (!s_max) b.dv_maxrow.add_null();
  return true;
}

// partitionValues object -> per-entry key/value
bool parse_pv(Cursor& c, Builder& b) {
  if (!c.lit('{')) return false;
  b.pv_valid.push_back(1);
  int32_t n = 0;
  std::string key, sval;
  if (c.peek() == '}') { c.lit('}'); b.pv_nentries.push_back(0); return true; }
  while (true) {
    if (!parse_string(c, key)) return false;
    if (!c.lit(':')) return false;
    b.pv_key.add(key);
    char ch = c.peek();
    if (ch == '"') {
      if (!parse_string(c, sval)) return false;
      b.pv_val.add(sval);
    } else if (ch == 'n') {
      c.p += 4;
      b.pv_val.add_null();
    } else {
      // non-conforming scalar (number/bool): keep raw text as the value
      const char* s; const char* e;
      if (!capture_raw(c, &s, &e)) return false;
      b.pv_val.add(s, e - s);
    }
    ++n;
    if (c.lit(',')) continue;
    if (c.lit('}')) break;
    return false;
  }
  b.pv_nentries.push_back(n);
  return true;
}

// The add/remove object body (cursor after '{' of the action value).
bool parse_file_action(Cursor& c, Builder& b, bool is_add, int64_t row_no) {
  if (!c.lit('{')) return false;
  bool s_path = false, s_pv = false, s_size = false, s_mt = false,
       s_dc = false, s_stats = false, s_tags = false, s_dv = false,
       s_brid = false, s_drcv = false, s_clust = false, s_dts = false,
       s_ext = false;
  std::string key, sval;
  if (c.peek() == '}') c.lit('}');
  else {
    while (true) {
      if (!parse_string(c, key)) return false;
      if (!c.lit(':')) return false;
      int64_t num;
      if (key_is(key, "path")) {
        if (s_path) return false;
        if (c.peek() == '"') { if (!parse_string(c, sval)) return false; b.path.add(sval); s_path = true; }
        else if (!skip_value(c)) return false;
      } else if (key_is(key, "partitionValues")) {
        if (s_pv) return false;
        if (c.peek() == '{') { if (!parse_pv(c, b)) return false; s_pv = true; }
        else if (!skip_value(c)) return false;
      } else if (key_is(key, "size")) {
        if (s_size) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.size.add(num); s_size = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "modificationTime")) {
        if (s_mt) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.mod_time.add(num); s_mt = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "dataChange")) {
        if (s_dc) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_BOOL_TRUE) { b.data_change.add(1); s_dc = true; }
        else if (k == NUM_BOOL_FALSE) { b.data_change.add(0); s_dc = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "stats")) {
        if (s_stats) return false;
        if (c.peek() == '"') { if (!parse_string(c, sval)) return false; b.stats.add(sval); s_stats = true; }
        else if (!skip_value(c)) return false;
      } else if (key_is(key, "tags")) {
        if (s_tags) return false;
        if (c.peek() == '{') {
          const char* s; const char* e;
          if (!capture_raw(c, &s, &e)) return false;
          b.tags.add(s, e - s);
          s_tags = true;
        } else if (!skip_value(c)) return false;
      } else if (key_is(key, "deletionVector")) {
        if (s_dv) return false;
        if (c.peek() == '{') { if (!parse_dv(c, b)) return false; s_dv = true; }
        else if (!skip_value(c)) return false;
      } else if (key_is(key, "baseRowId")) {
        if (s_brid) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.base_row_id.add(num); s_brid = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "defaultRowCommitVersion")) {
        if (s_drcv) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.drcv.add(num); s_drcv = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "clusteringProvider")) {
        if (s_clust) return false;
        if (c.peek() == '"') { if (!parse_string(c, sval)) return false; b.clustering.add(sval); s_clust = true; }
        else if (!skip_value(c)) return false;
      } else if (key_is(key, "deletionTimestamp")) {
        if (s_dts) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_INT) { b.del_ts.add(num); s_dts = true; }
        else if (k != NUM_NULL) return false;
      } else if (key_is(key, "extendedFileMetadata")) {
        if (s_ext) return false;
        NumKind k = parse_num_or_lit(c, &num);
        if (k == NUM_BOOL_TRUE) { b.ext_meta.add(1); s_ext = true; }
        else if (k == NUM_BOOL_FALSE) { b.ext_meta.add(0); s_ext = true; }
        else if (k != NUM_NULL) return false;
      } else {
        if (!skip_value(c)) return false;
      }
      if (c.lit(',')) continue;
      if (c.lit('}')) break;
      return false;
    }
  }
  b.line_no.push_back(row_no);
  b.is_add.push_back(is_add ? 1 : 0);
  if (!s_path) b.path.add_null();
  if (!s_pv) { b.pv_valid.push_back(0); b.pv_nentries.push_back(0); }
  if (!s_size) b.size.add_null();
  if (!s_mt) b.mod_time.add_null();
  if (!s_dc) b.data_change.add_null();
  if (!s_stats) b.stats.add_null();
  if (!s_tags) b.tags.add_null();
  if (!s_dv) {
    b.dv_valid.push_back(0);
    b.dv_storage.add_null(); b.dv_pathinline.add_null();
    b.dv_offset.add_null(); b.dv_size.add_null();
    b.dv_card.add_null(); b.dv_maxrow.add_null();
  }
  if (!s_brid) b.base_row_id.add_null();
  if (!s_drcv) b.drcv.add_null();
  if (!s_clust) b.clustering.add_null();
  if (!s_dts) b.del_ts.add_null();
  if (!s_ext) b.ext_meta.add_null();
  return true;
}

// One line (one action object). row_no is the line's global row number.
bool parse_line(const char* start, const char* stop, int64_t row_no,
                int64_t base_off, Builder& b) {
  Cursor c{start, stop};
  if (!c.lit('{')) return false;
  std::string key;
  if (!parse_string(c, key)) return false;
  if (!c.lit(':')) return false;
  bool is_add = key_is(key, "add");
  bool is_rm = key_is(key, "remove");
  if ((is_add || is_rm) && c.peek() == '{') {
    if (!parse_file_action(c, b, is_add, row_no)) return false;
    // single-key objects are the norm; tolerate (skip) extra keys
    while (c.lit(',')) {
      if (!parse_string(c, key)) return false;
      if (!c.lit(':')) return false;
      if (!skip_value(c)) return false;
    }
    return c.lit('}');
  }
  // everything else: hand the whole line to the host
  b.other_line_no.push_back(row_no);
  b.other_start.push_back(base_off + (start - start));
  b.other_end.push_back(base_off + (stop - start));
  return true;
}

// ------------------------------------------------------------- result/ABI

struct FinalStr {
  std::string arena;
  std::vector<int32_t> offsets;  // n+1, leading 0
  std::vector<uint8_t> valid;
};

template <typename T>
struct FinalNum {
  std::vector<T> vals;
  std::vector<uint8_t> valid;
};

struct Result {
  int32_t error = 0;
  int64_t n_rows = 0, n_lines = 0, n_others = 0, n_pv_entries = 0;
  std::vector<int64_t> line_no;
  std::vector<uint8_t> is_add;
  FinalStr path, pv_key, pv_val, stats, tags, dv_storage, dv_pathinline,
      clustering;
  std::vector<int32_t> pv_offsets;  // n+1 entry offsets per row
  std::vector<uint8_t> pv_valid;
  FinalNum<int64_t> size, mod_time, dv_card, dv_maxrow, base_row_id, drcv,
      del_ts;
  FinalNum<int32_t> dv_offset, dv_size;
  FinalNum<uint8_t> data_change, ext_meta;
  std::vector<uint8_t> dv_valid;
  std::vector<int64_t> other_line_no, other_start, other_end;
  std::vector<int64_t> line_starts;
};

// false when the merged arena would overflow int32 offsets (the caller
// flags the scan as failed and the host falls back to the generic parser)
bool merge_str(FinalStr& out, std::vector<Builder>& bs, StrCol Builder::* m) {
  size_t rows = 0, bytes = 0;
  for (auto& b : bs) { rows += (b.*m).ends.size(); bytes += (b.*m).arena.size(); }
  if (bytes > (size_t)INT32_MAX) return false;
  out.arena.reserve(bytes);
  out.offsets.reserve(rows + 1);
  out.valid.reserve(rows);
  out.offsets.push_back(0);
  for (auto& b : bs) {
    StrCol& c = b.*m;
    int32_t base = (int32_t)out.arena.size();
    out.arena += c.arena;
    for (int32_t e : c.ends) out.offsets.push_back(base + e);
    out.valid.insert(out.valid.end(), c.valid.begin(), c.valid.end());
  }
  return true;
}

template <typename T, typename M>
void merge_num(FinalNum<T>& out, std::vector<Builder>& bs, M m) {
  for (auto& b : bs) {
    auto& c = b.*m;
    out.vals.insert(out.vals.end(), c.vals.begin(), c.vals.end());
    out.valid.insert(out.valid.end(), c.valid.begin(), c.valid.end());
  }
}

}  // namespace

extern "C" {

void* das_scan(const char* buf, int64_t len, int32_t n_threads) {
  Result* r = new Result();
  if (len <= 0) return r;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 32) n_threads = 32;
  // split at line boundaries
  std::vector<int64_t> cut(n_threads + 1, 0);
  cut[n_threads] = len;
  for (int t = 1; t < n_threads; t++) {
    int64_t target = len * t / n_threads;
    if (target < cut[t - 1]) target = cut[t - 1];
    const char* nl = (const char*)memchr(buf + target, '\n', len - target);
    cut[t] = nl ? (nl - buf) + 1 : len;
  }
  std::vector<Builder> builders(n_threads);
  auto work = [&](int t) {
    Builder& b = builders[t];
    const char* p = buf + cut[t];
    const char* end = buf + cut[t + 1];
    while (p < end) {
      const char* nl = (const char*)memchr(p, '\n', end - p);
      const char* stop = nl ? nl : end;
      // skip blank lines (the inter-file padding byte and trailing \n)
      const char* q = p;
      while (q < stop && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
      if (q != stop) {
        b.line_starts.push_back(p - buf);
        // row number assigned after join; stash local index via size
        if (!parse_line(p, stop, (int64_t)b.line_starts.size() - 1,
                        p - buf, b)) {
          b.failed = true;
          break;
        }
      }
      if (!nl) break;
      p = nl + 1;
    }
  };
  if (n_threads == 1) {
    work(0);  // single-core hosts: no thread spawn at all
  } else {
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; t++) threads.emplace_back(work, t);
    for (auto& th : threads) th.join();
  }
  for (auto& b : builders)
    if (b.failed) { r->error = 1; return r; }

  // rebase per-thread local row numbers to global ones
  int64_t row_base = 0;
  for (auto& b : builders) {
    for (auto& v : b.line_no) v += row_base;
    for (auto& v : b.other_line_no) v += row_base;
    row_base += (int64_t)b.line_starts.size();
  }
  r->n_lines = row_base;

  for (auto& b : builders) {
    r->line_no.insert(r->line_no.end(), b.line_no.begin(), b.line_no.end());
    r->is_add.insert(r->is_add.end(), b.is_add.begin(), b.is_add.end());
    r->pv_valid.insert(r->pv_valid.end(), b.pv_valid.begin(), b.pv_valid.end());
    r->dv_valid.insert(r->dv_valid.end(), b.dv_valid.begin(), b.dv_valid.end());
    r->other_line_no.insert(r->other_line_no.end(), b.other_line_no.begin(),
                            b.other_line_no.end());
    r->other_start.insert(r->other_start.end(), b.other_start.begin(),
                          b.other_start.end());
    r->other_end.insert(r->other_end.end(), b.other_end.begin(),
                        b.other_end.end());
    r->line_starts.insert(r->line_starts.end(), b.line_starts.begin(),
                          b.line_starts.end());
  }
  // line_starts were thread-local offsets from buf already (absolute)
  r->n_rows = (int64_t)r->line_no.size();
  r->n_others = (int64_t)r->other_line_no.size();

  r->pv_offsets.reserve(r->n_rows + 1);
  r->pv_offsets.push_back(0);
  int32_t acc = 0;
  for (auto& b : builders)
    for (int32_t nent : b.pv_nentries) {
      acc += nent;
      r->pv_offsets.push_back(acc);
    }
  r->n_pv_entries = acc;

  bool str_ok = merge_str(r->path, builders, &Builder::path) &&
                merge_str(r->pv_key, builders, &Builder::pv_key) &&
                merge_str(r->pv_val, builders, &Builder::pv_val) &&
                merge_str(r->stats, builders, &Builder::stats) &&
                merge_str(r->tags, builders, &Builder::tags) &&
                merge_str(r->dv_storage, builders, &Builder::dv_storage) &&
                merge_str(r->dv_pathinline, builders, &Builder::dv_pathinline) &&
                merge_str(r->clustering, builders, &Builder::clustering);
  if (!str_ok) { r->error = 1; return r; }
  merge_num(r->size, builders, &Builder::size);
  merge_num(r->mod_time, builders, &Builder::mod_time);
  merge_num(r->data_change, builders, &Builder::data_change);
  merge_num(r->dv_offset, builders, &Builder::dv_offset);
  merge_num(r->dv_size, builders, &Builder::dv_size);
  merge_num(r->dv_card, builders, &Builder::dv_card);
  merge_num(r->dv_maxrow, builders, &Builder::dv_maxrow);
  merge_num(r->base_row_id, builders, &Builder::base_row_id);
  merge_num(r->drcv, builders, &Builder::drcv);
  merge_num(r->del_ts, builders, &Builder::del_ts);
  merge_num(r->ext_meta, builders, &Builder::ext_meta);
  return r;
}

void das_free(void* h) { delete (Result*)h; }
int32_t das_error(void* h) { return ((Result*)h)->error; }

// counts: 0 rows, 1 lines, 2 others, 3 pv entries, and arena byte sizes
int64_t das_n(void* h, int32_t what) {
  Result* r = (Result*)h;
  switch (what) {
    case 0: return r->n_rows;
    case 1: return r->n_lines;
    case 2: return r->n_others;
    case 3: return r->n_pv_entries;
    case 4: return (int64_t)r->path.arena.size();
    case 5: return (int64_t)r->pv_key.arena.size();
    case 6: return (int64_t)r->pv_val.arena.size();
    case 7: return (int64_t)r->stats.arena.size();
    case 8: return (int64_t)r->tags.arena.size();
    case 9: return (int64_t)r->dv_storage.arena.size();
    case 10: return (int64_t)r->dv_pathinline.arena.size();
    case 11: return (int64_t)r->clustering.arena.size();
    default: return -1;
  }
}

const void* das_ptr(void* h, int32_t which) {
  Result* r = (Result*)h;
  switch (which) {
    case 0: return r->line_no.data();
    case 1: return r->is_add.data();
    case 2: return r->path.offsets.data();
    case 3: return r->path.arena.data();
    case 4: return r->path.valid.data();
    case 5: return r->pv_offsets.data();
    case 6: return r->pv_valid.data();
    case 7: return r->pv_key.offsets.data();
    case 8: return r->pv_key.arena.data();
    case 9: return r->pv_val.offsets.data();
    case 10: return r->pv_val.arena.data();
    case 11: return r->pv_val.valid.data();
    case 12: return r->size.vals.data();
    case 13: return r->size.valid.data();
    case 14: return r->mod_time.vals.data();
    case 15: return r->mod_time.valid.data();
    case 16: return r->data_change.vals.data();
    case 17: return r->data_change.valid.data();
    case 18: return r->stats.offsets.data();
    case 19: return r->stats.arena.data();
    case 20: return r->stats.valid.data();
    case 21: return r->tags.offsets.data();
    case 22: return r->tags.arena.data();
    case 23: return r->tags.valid.data();
    case 24: return r->dv_valid.data();
    case 25: return r->dv_storage.offsets.data();
    case 26: return r->dv_storage.arena.data();
    case 27: return r->dv_storage.valid.data();
    case 28: return r->dv_pathinline.offsets.data();
    case 29: return r->dv_pathinline.arena.data();
    case 30: return r->dv_pathinline.valid.data();
    case 31: return r->dv_offset.vals.data();
    case 32: return r->dv_offset.valid.data();
    case 33: return r->dv_size.vals.data();
    case 34: return r->dv_size.valid.data();
    case 35: return r->dv_card.vals.data();
    case 36: return r->dv_card.valid.data();
    case 37: return r->dv_maxrow.vals.data();
    case 38: return r->dv_maxrow.valid.data();
    case 39: return r->base_row_id.vals.data();
    case 40: return r->base_row_id.valid.data();
    case 41: return r->drcv.vals.data();
    case 42: return r->drcv.valid.data();
    case 43: return r->clustering.offsets.data();
    case 44: return r->clustering.arena.data();
    case 45: return r->clustering.valid.data();
    case 46: return r->del_ts.vals.data();
    case 47: return r->del_ts.valid.data();
    case 48: return r->ext_meta.vals.data();
    case 49: return r->ext_meta.valid.data();
    case 50: return r->other_line_no.data();
    case 51: return r->other_start.data();
    case 52: return r->other_end.data();
    case 53: return r->line_starts.data();
    default: return nullptr;
  }
}

}  // extern "C"

// action_scan: specialized NDJSON scanner for Delta log commit files.
//
// The reference leans on Jackson for this (DefaultJsonHandler,
// kernel-defaults/.../DefaultJsonHandler.java; spark pays it as a JSON
// scan at Snapshot.scala:524). A generic JSON reader must infer a
// unified schema and materialize every field; this scanner knows the
// action schema (PROTOCOL.md:418-822) and emits exactly the columnar
// buffers the canonical file-actions table needs.
//
// v2 design notes (why this beats both a generic parser and v1):
// - memchr-driven scanning: glibc memchr is SIMD; the scanner rides it
//   for line splits, string ends, and escape detection instead of
//   per-character loops.
// - zero per-row allocation: values are unescaped straight into the
//   output arenas; one reusable scratch string per thread.
// - paths are dictionary-encoded DURING the scan: an open-addressing
//   hash table assigns dense codes in first-appearance order, so the
//   host never runs a factorize pass, and the first-appearance delta
//   encoding the replay kernel wants (flags + explicit refs — see
//   ops/replay.py) falls out for free: a row's path is either brand new
//   (code == count-so-far) or an explicit back-reference.
// - multi-file read (`dar_read`): reads a whole list of commit files
//   into one buffer without a Python round-trip per file (100k-commit
//   logs pay ~40us/file of interpreter overhead otherwise).
//
// Contract with the Python side (delta_tpu/native/__init__.py):
// - das_scan(buf, len, n_threads) -> opaque handle (never NULL)
// - das_error(h): 0 ok; 1 = structural parse failure, caller must fall
//   back to the generic parser (no partial results are exposed)
// - das_n(h, i) / das_ptr(h, i): counts and column pointers by the
//   index maps below — mirrored in the Python binding.
// - all string columns are (int32 end-offsets per row, one byte arena,
//   uint8 validity); offsets are Arrow-style with the leading 0.
// - paths: per-row uint32 codes + a unique-path arena in code order
//   (code i's bytes are uniq_offs[i]..uniq_offs[i+1]) + per-row
//   is_new flags + refs (codes of the non-new rows, in row order).
//
// Unescaping: full JSON string unescape including \uXXXX surrogate
// pairs -> UTF-8. Raw-capture fields (tags) keep the original JSON
// text, which is itself valid JSON.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#if defined(__SSE2__) || defined(_M_X64) || defined(__x86_64__)
#include <emmintrin.h>
#define DAS_SSE2 1
#endif

namespace {

// ---------------------------------------------------------------- builders

struct StrCol {
  // Arrow layout from the start (offsets with the leading 0) so the
  // single-thread finish is a pure move, not a rebase copy.
  // Row-positional columns are LAZY: absent rows push nothing — a
  // present value at row r first bulk-pads the null gap (`add_at`), and
  // a final `pad_to(n_rows)` densifies the tail. The old
  // one-null-push-per-absent-column-per-row pattern was ~40% of scan
  // time once the template fast path removed the tokenizing cost.
  // Entry-wise columns (pv_key/pv_val) use plain add/add_null.
  std::string arena;
  std::vector<int32_t> offsets{0};
  std::vector<uint8_t> valid;
  void add_null() { offsets.push_back((int32_t)arena.size()); valid.push_back(0); }
  void add(const char* s, size_t n) {
    arena.append(s, n);
    offsets.push_back((int32_t)arena.size());
    valid.push_back(1);
  }
  void pad_to(size_t rows) {
    if (valid.size() < rows) {
      offsets.resize(rows + 1, (int32_t)arena.size());
      valid.resize(rows, 0);
    }
  }
  void add_at(size_t row, const char* s, size_t n) {
    pad_to(row);
    add(s, n);
  }
};

template <typename T>
struct NumCol {
  std::vector<T> vals;
  std::vector<uint8_t> valid;
  void add_null() { vals.push_back(0); valid.push_back(0); }
  void add(T v) { vals.push_back(v); valid.push_back(1); }
  void pad_to(size_t rows) {
    if (valid.size() < rows) {
      vals.resize(rows, 0);
      valid.resize(rows, 0);
    }
  }
  void add_at(size_t row, T v) {
    pad_to(row);
    add(v);
  }
};

// Open-addressing path dictionary: dense codes in first-appearance
// order. One 8-byte slot per entry (32-bit hash tag + code) so a probe
// costs a single cache line — the table spills L2 at millions of
// uniques and every saved miss is ~100ns on this class of host. Exact
// byte compare on tag match keeps 32-bit tag collisions harmless.
struct PathDict {
  struct Slot { uint32_t tag; uint32_t code; };  // tag 0 == empty
  std::vector<Slot> slots;
  size_t mask = 0;
  std::string arena;
  std::vector<uint32_t> offs{0};

  void reserve_slots(size_t want) {
    size_t cap = 1024;
    while (cap < want * 2) cap <<= 1;
    slots.assign(cap, Slot{0, 0});
    mask = cap - 1;
  }
  size_t count() const { return offs.size() - 1; }

  static uint64_t hash_bytes(const char* s, size_t n) {
    // 8-byte-block mix (xxhash-flavored); quality only needs to keep
    // probe chains short — equality is always verified by memcmp.
    uint64_t h = 0x9E3779B97F4A7C15ull ^ (n * 0xC2B2AE3D27D4EB4Full);
    while (n >= 8) {
      uint64_t k;
      memcpy(&k, s, 8);
      k *= 0xC2B2AE3D27D4EB4Full;
      k = (k << 31) | (k >> 33);
      h ^= k * 0x9E3779B97F4A7C15ull;
      h = ((h << 27) | (h >> 37)) * 5 + 0x52DCE729;
      s += 8;
      n -= 8;
    }
    uint64_t tail = 0;
    if (n) memcpy(&tail, s, n);
    h ^= tail * 0x9E3779B97F4A7C15ull;
    h ^= h >> 29;
    h *= 0xBF58476D1CE4E5B9ull;
    h ^= h >> 32;
    return h;
  }

  void grow() {
    std::vector<Slot> old;
    old.swap(slots);
    slots.assign(old.size() * 2, Slot{0, 0});
    mask = slots.size() - 1;
    for (const Slot& sl : old) {
      if (!sl.tag) continue;
      // re-derive the probe start from the stored bytes' hash
      uint64_t h = hash_bytes(arena.data() + offs[sl.code],
                              offs[sl.code + 1] - offs[sl.code]);
      size_t j = h & mask;
      while (slots[j].tag) j = (j + 1) & mask;
      slots[j] = sl;
    }
  }

  uint32_t intern(const char* s, size_t n, bool* was_new) {
    return intern_hashed(s, n, hash_bytes(s, n), was_new);
  }

  // Precomputed-hash variant: callers hash (and prefetch the slot) as
  // soon as the key bytes are known, then intern after other work has
  // hidden the table's cache miss.
  uint32_t intern_hashed(const char* s, size_t n, uint64_t h,
                         bool* was_new) {
    if (count() * 2 >= slots.size()) grow();
    uint32_t tag = (uint32_t)(h >> 32);
    if (!tag) tag = 1;
    size_t j = h & mask;
    while (slots[j].tag) {
      if (slots[j].tag == tag) {
        uint32_t c = slots[j].code;
        size_t len = offs[c + 1] - offs[c];
        if (len == n && memcmp(arena.data() + offs[c], s, n) == 0) {
          *was_new = false;
          return c;
        }
      }
      j = (j + 1) & mask;
    }
    uint32_t c = (uint32_t)count();
    slots[j].tag = tag;
    slots[j].code = c;
    arena.append(s, n);
    offs.push_back((uint32_t)arena.size());
    *was_new = true;
    return c;
  }
};

// ---------------------------------------------------- template fast path
//
// Commit files are overwhelmingly written by one writer emitting file
// actions with an identical field layout, so consecutive lines differ
// only in their values. The scanner learns that layout once — from a
// line the generic parser accepted — as a "template": the line's literal
// byte skeleton plus typed value slots. Later lines are matched with a
// few SIMD memcmps over the skeleton and per-slot value scans: no
// tokenizing, no per-key dispatch (measured ~4-10x over the generic
// walk). Any byte of structural mismatch falls back to the generic
// parser (which learns the new layout), so the fast path is
// correctness-neutral by construction: values are extracted by the same
// string/number scanners at positions the skeleton pins down.

enum SlotType : uint8_t { SL_STR, SL_INT, SL_BOOL, SL_PV, SL_RAW };

struct TmplSlot {
  uint8_t type;   // SlotType
  uint8_t field;  // FieldId (declared below; stored as raw byte here)
};

struct Tmpl {
  std::string line;  // skeleton source bytes (the learned line)
  struct Seg {
    uint32_t off, len;  // literal bytes [off, off+len) of `line`
    TmplSlot slot;      // the value slot that follows the literal
  };
  std::vector<Seg> segs;
  uint32_t tail_off = 0, tail_len = 0;  // closing literal
  bool is_add = false;
};

struct SlotVal {
  const char* vs;  // decoded value span (string content, unescaped)
  const char* ve;
  int64_t num;       // SL_INT / SL_BOOL value; F_PATH: precomputed hash
  int64_t a_start;   // in_arena: column-arena span of the decoded bytes
  int64_t a_end;
  bool esc;          // SL_STR: decoded into scratch (span not in input)
  bool in_arena;     // SL_STR: decoded straight into the column arena
  bool lazy_span;    // SL_STR stats in lazy mode: a_start/a_end are raw
                     // escaped offsets into the input buffer
};

// Inlined equality for the short runtime-length literals (10-40 bytes):
// a library memcmp call per segment costs more than the compare itself.
static inline bool bytes_eq(const char* a, const char* b, size_t n) {
  while (n >= 8) {
    uint64_t x, y;
    memcpy(&x, a, 8);
    memcpy(&y, b, 8);
    if (x != y) return false;
    a += 8;
    b += 8;
    n -= 8;
  }
  if (n >= 4) {
    uint32_t x, y;
    memcpy(&x, a, 4);
    memcpy(&y, b, 4);
    if (x != y) return false;
    a += 4;
    b += 4;
    n -= 4;
  }
  while (n--)
    if (*a++ != *b++) return false;
  return true;
}

constexpr int kMaxTmplSlots = 24;
constexpr size_t kMaxTmplLine = 1 << 16;
constexpr size_t kMaxTmpls = 4;  // MRU-ordered per builder

struct Builder {
  std::vector<int64_t> line_no;      // global row number of each file action
  std::vector<uint8_t> is_add;
  std::vector<uint32_t> path_code;   // local dictionary codes
  std::vector<uint8_t> path_new;     // local first-appearance flag
  PathDict dict;
  // partitionValues: cumulative entry offsets (leading 0); per-entry k/v
  std::vector<int32_t> pv_offsets{0};
  std::vector<uint8_t> pv_valid;     // row-level presence of the object
  StrCol pv_key;                     // validity unused (keys non-null)
  StrCol pv_val;
  NumCol<int64_t> size;
  NumCol<int64_t> mod_time;
  NumCol<uint8_t> data_change;
  StrCol stats;
  StrCol tags;                       // raw JSON text of the tags object
  std::vector<uint8_t> dv_valid;
  StrCol dv_storage;
  StrCol dv_pathinline;
  NumCol<int32_t> dv_offset;
  NumCol<int32_t> dv_size;
  NumCol<int64_t> dv_card;
  NumCol<int64_t> dv_maxrow;
  NumCol<int64_t> base_row_id;
  NumCol<int64_t> drcv;
  StrCol clustering;
  NumCol<int64_t> del_ts;
  NumCol<uint8_t> ext_meta;
  // non-file-action lines: (global row number, byte start, byte end)
  std::vector<int64_t> other_line_no;
  std::vector<int64_t> other_start;
  std::vector<int64_t> other_end;
  // byte start of every non-blank line, in order (global row numbering)
  std::vector<int64_t> line_starts;
  std::string tmp;       // reusable unescape scratch
  std::string path_tmp;  // separate scratch: path bytes stay live while
                         // later fields reuse `tmp`
  // lazy-stats mode: stats VALUES are recorded as raw escaped byte
  // spans into the input buffer (opening quote .. after closing quote)
  // instead of being unescaped into the arena during the scan; a later
  // das_stats_materialize() call decodes them in one pass. Stats are
  // ~60% of commit bytes and many loads never read them.
  bool lazy_stats = false;
  const char* buf_base = nullptr;
  NumCol<int64_t> stats_s;
  NumCol<int64_t> stats_e;
  std::vector<Tmpl> tmpls;  // learned line templates, MRU first
  std::string slot_tmp[kMaxTmplSlots];  // per-slot unescape scratch
  uint32_t tmpl_hits = 0, tmpl_learns = 0;
  bool tmpl_enabled = true;  // cleared when learning never pays off
  size_t cur_row = 0;  // builder-local row index of the action in flight
  struct PendIntern { const char* s; uint32_t n; uint64_t h; };
  std::vector<PendIntern> pend;  // batched interns (see flush_interns)
  bool failed = false;

  void pad_pv_to(size_t rows) {
    if (pv_valid.size() < rows) {
      pv_offsets.resize(rows + 1, pv_offsets.back());
      pv_valid.resize(rows, 0);
    }
  }

  // densify every lazily-padded positional column to `rows`
  void pad_all_to(size_t rows) {
    if (lazy_stats) {
      stats_s.pad_to(rows);
      stats_e.pad_to(rows);
    } else {
      stats.pad_to(rows);
    }
    for (auto* s : {&tags, &clustering, &dv_storage, &dv_pathinline})
      s->pad_to(rows);
    size.pad_to(rows);
    mod_time.pad_to(rows);
    data_change.pad_to(rows);
    dv_offset.pad_to(rows);
    dv_size.pad_to(rows);
    dv_card.pad_to(rows);
    dv_maxrow.pad_to(rows);
    base_row_id.pad_to(rows);
    drcv.pad_to(rows);
    del_ts.pad_to(rows);
    ext_meta.pad_to(rows);
    pad_pv_to(rows);
    if (dv_valid.size() < rows) dv_valid.resize(rows, 0);
  }
};

// ---------------------------------------------------------------- lexing

inline const char* ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

void append_utf8(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out.push_back((char)cp);
  } else if (cp < 0x800) {
    out.push_back((char)(0xC0 | (cp >> 6)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back((char)(0xE0 | (cp >> 12)));
    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  } else {
    out.push_back((char)(0xF0 | (cp >> 18)));
    out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  }
}

int hex4(const char* p) {
  int v = 0;
  for (int i = 0; i < 4; i++) {
    char c = p[i];
    v <<= 4;
    if (c >= '0' && c <= '9') v |= c - '0';
    else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
    else return -1;
  }
  return v;
}

// First position of '"' or '\\' in [p, end) — the simdjson-style
// 16-byte compare+movemask sweep (SSE2 is baseline on x86_64); scalar
// tail/fallback elsewhere. This is THE inner loop of the scanner: every
// string byte passes through it exactly once.
inline const char* scan_to_special(const char* p, const char* end) {
#ifdef DAS_SSE2
  const __m128i quote = _mm_set1_epi8('"');
  const __m128i bslash = _mm_set1_epi8('\\');
  while (p + 16 <= end) {
    __m128i v = _mm_loadu_si128((const __m128i*)p);
    int mask = _mm_movemask_epi8(
        _mm_or_si128(_mm_cmpeq_epi8(v, quote), _mm_cmpeq_epi8(v, bslash)));
    if (mask) return p + __builtin_ctz((unsigned)mask);
    p += 16;
  }
#endif
  while (p < end && *p != '"' && *p != '\\') ++p;
  return p;
}

// Scan a JSON string whose opening quote is at *p. On success returns
// the position after the closing quote and sets (*s, *e) to the decoded
// bytes — a zero-copy span into the input when there are no escapes,
// else a span into `tmp` (overwritten per call). nullptr on malformed.
const char* scan_jstring(const char* p, const char* end, std::string& tmp,
                         const char** s, const char** e) {
  ++p;  // opening quote
  const char* q = scan_to_special(p, end);
  if (q >= end) return nullptr;
  if (*q == '"') {  // fast path: no escapes
    *s = p;
    *e = q;
    return q + 1;
  }
  // slow path: bulk-copy runs between escapes into tmp
  tmp.clear();
  tmp.append(p, q - p);
  p = q;
  while (p < end) {
    char ch = *p;
    if (ch == '"') {
      *s = tmp.data();
      *e = tmp.data() + tmp.size();
      return p + 1;
    }
    if (ch != '\\') {
      const char* stop = scan_to_special(p, end);
      if (stop >= end) return nullptr;
      tmp.append(p, stop - p);
      p = stop;
      continue;
    }
    if (p + 1 >= end) return nullptr;
    char esc = p[1];
    p += 2;
    switch (esc) {
      case '"': tmp.push_back('"'); break;
      case '\\': tmp.push_back('\\'); break;
      case '/': tmp.push_back('/'); break;
      case 'b': tmp.push_back('\b'); break;
      case 'f': tmp.push_back('\f'); break;
      case 'n': tmp.push_back('\n'); break;
      case 'r': tmp.push_back('\r'); break;
      case 't': tmp.push_back('\t'); break;
      case 'u': {
        if (p + 4 > end) return nullptr;
        int v = hex4(p);
        if (v < 0) return nullptr;
        p += 4;
        uint32_t cp = (uint32_t)v;
        if (cp >= 0xD800 && cp <= 0xDBFF && p + 6 <= end && p[0] == '\\' &&
            p[1] == 'u') {
          int lo = hex4(p + 2);
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + ((uint32_t)lo - 0xDC00);
            p += 6;
          }
        }
        append_utf8(tmp, cp);
        break;
      }
      default: return nullptr;
    }
  }
  return nullptr;
}

// Unescape a JSON string (opening quote at *p) by APPENDING the decoded
// bytes to `out` (no clear — used for direct-into-column-arena decoding).
// Returns the position after the closing quote, or nullptr.
const char* scan_jstring_append(const char* p, const char* end,
                                std::string& out) {
  ++p;
  while (p < end) {
    char ch = *p;
    if (ch == '"') return p + 1;
    if (ch != '\\') {
      const char* stop = scan_to_special(p, end);
      if (stop >= end) return nullptr;
      out.append(p, stop - p);
      p = stop;
      continue;
    }
    if (p + 1 >= end) return nullptr;
    char esc = p[1];
    p += 2;
    switch (esc) {
      case '"': out.push_back('"'); break;
      case '\\': out.push_back('\\'); break;
      case '/': out.push_back('/'); break;
      case 'b': out.push_back('\b'); break;
      case 'f': out.push_back('\f'); break;
      case 'n': out.push_back('\n'); break;
      case 'r': out.push_back('\r'); break;
      case 't': out.push_back('\t'); break;
      case 'u': {
        if (p + 4 > end) return nullptr;
        int v = hex4(p);
        if (v < 0) return nullptr;
        p += 4;
        uint32_t cp = (uint32_t)v;
        if (cp >= 0xD800 && cp <= 0xDBFF && p + 6 <= end && p[0] == '\\' &&
            p[1] == 'u') {
          int lo = hex4(p + 2);
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + ((uint32_t)lo - 0xDC00);
            p += 6;
          }
        }
        append_utf8(out, cp);
        break;
      }
      default: return nullptr;
    }
  }
  return nullptr;
}

// Skip a JSON string (opening quote at *p); returns position after the
// closing quote, or nullptr.
const char* skip_jstring(const char* p, const char* end) {
  ++p;
  while (p < end) {
    const char* q = scan_to_special(p, end);
    if (q >= end) return nullptr;
    if (*q == '"') return q + 1;
    p = q + 2;  // skip the escape pair (\" \\ \u... all start with 2 bytes)
  }
  return nullptr;
}

// Skip any JSON value (cursor at its first non-ws char). String-aware.
const char* skip_value(const char* p, const char* end) {
  p = ws(p, end);
  if (p >= end) return nullptr;
  char ch = *p;
  if (ch == '"') return skip_jstring(p, end);
  if (ch == '{' || ch == '[') {
    char open = ch, close = (ch == '{') ? '}' : ']';
    ++p;
    int depth = 1;
    while (p < end && depth) {
      char d = *p;
      if (d == '"') {
        p = skip_jstring(p, end);
        if (!p) return nullptr;
        continue;
      }
      if (d == open) ++depth;
      else if (d == close) --depth;
      ++p;
    }
    return depth == 0 ? p : nullptr;
  }
  const char* q = p;
  while (q < end && *q != ',' && *q != '}' && *q != ']' && *q != ' ' &&
         *q != '\t' && *q != '\r' && *q != '\n')
    ++q;
  return q != p ? q : nullptr;
}

enum NumKind { NUM_NULL, NUM_INT, NUM_BOOL_TRUE, NUM_BOOL_FALSE, NUM_BAD };

// Integers (JSON numbers without fraction/exponent are the norm for the
// action schema; fractional/exponent forms are truncated via strtod).
NumKind parse_num_or_lit(const char** pp, const char* end, int64_t* out) {
  const char* p = ws(*pp, end);
  if (p >= end) return NUM_BAD;
  char ch = *p;
  if (ch == 'n') { *pp = p + 4 <= end ? p + 4 : end; return NUM_NULL; }
  if (ch == 't') { *pp = p + 4 <= end ? p + 4 : end; return NUM_BOOL_TRUE; }
  if (ch == 'f') { *pp = p + 5 <= end ? p + 5 : end; return NUM_BOOL_FALSE; }
  bool neg = false;
  const char* start = p;
  if (p < end && (*p == '-' || *p == '+')) { neg = *p == '-'; ++p; }
  int64_t v = 0;
  const char* digits = p;
  while (p < end && *p >= '0' && *p <= '9') { v = v * 10 + (*p - '0'); ++p; }
  if (p == digits) return NUM_BAD;
  if (p < end && (*p == '.' || *p == 'e' || *p == 'E')) {
    char* endp = nullptr;
    double d = strtod(start, &endp);
    if (endp == start) return NUM_BAD;
    *pp = endp;
    *out = (int64_t)d;
    return NUM_INT;
  }
  *pp = p;
  *out = neg ? -v : v;
  return NUM_INT;
}

// ------------------------------------------------------------- action parse

// Field-key dispatch tokens. Keys are matched by (length, bytes); JSON
// escapes never appear in schema keys, so the raw span is compared.
enum FieldId {
  F_UNKNOWN, F_PATH, F_PARTITION_VALUES, F_SIZE, F_MODIFICATION_TIME,
  F_DATA_CHANGE, F_STATS, F_TAGS, F_DELETION_VECTOR, F_BASE_ROW_ID,
  F_DRCV, F_CLUSTERING, F_DELETION_TIMESTAMP, F_EXT_META,
};

inline FieldId field_id(const char* k, size_t n) {
  switch (n) {
    case 4:
      if (memcmp(k, "path", 4) == 0) return F_PATH;
      if (memcmp(k, "size", 4) == 0) return F_SIZE;
      if (memcmp(k, "tags", 4) == 0) return F_TAGS;
      return F_UNKNOWN;
    case 5:
      return memcmp(k, "stats", 5) == 0 ? F_STATS : F_UNKNOWN;
    case 9:
      return memcmp(k, "baseRowId", 9) == 0 ? F_BASE_ROW_ID : F_UNKNOWN;
    case 10:
      return memcmp(k, "dataChange", 10) == 0 ? F_DATA_CHANGE : F_UNKNOWN;
    case 14:
      return memcmp(k, "deletionVector", 14) == 0 ? F_DELETION_VECTOR
                                                  : F_UNKNOWN;
    case 15:
      return memcmp(k, "partitionValues", 15) == 0 ? F_PARTITION_VALUES
                                                   : F_UNKNOWN;
    case 16:
      return memcmp(k, "modificationTime", 16) == 0 ? F_MODIFICATION_TIME
                                                    : F_UNKNOWN;
    case 17:
      return memcmp(k, "deletionTimestamp", 17) == 0 ? F_DELETION_TIMESTAMP
                                                     : F_UNKNOWN;
    case 18:
      return memcmp(k, "clusteringProvider", 18) == 0 ? F_CLUSTERING
                                                      : F_UNKNOWN;
    case 20:
      return memcmp(k, "extendedFileMetadata", 20) == 0 ? F_EXT_META
                                                        : F_UNKNOWN;
    case 23:
      return memcmp(k, "defaultRowCommitVersion", 23) == 0 ? F_DRCV
                                                           : F_UNKNOWN;
    default:
      return F_UNKNOWN;
  }
}

// deletionVector object (cursor at '{')
const char* parse_dv(const char* p, const char* end, Builder& b) {
  ++p;
  if (b.dv_valid.size() < b.cur_row) b.dv_valid.resize(b.cur_row, 0);
  b.dv_valid.push_back(1);
  bool s_storage = false, s_path = false, s_off = false, s_size = false,
       s_card = false, s_max = false;
  p = ws(p, end);
  if (p < end && *p == '}') {
    ++p;
  } else {
    while (true) {
      p = ws(p, end);
      if (p >= end || *p != '"') return nullptr;
      const char *ks, *ke;
      p = scan_jstring(p, end, b.tmp, &ks, &ke);
      if (!p) return nullptr;
      size_t kn = ke - ks;
      p = ws(p, end);
      if (p >= end || *p != ':') return nullptr;
      ++p;
      p = ws(p, end);
      int64_t num;
      if (kn == 11 && memcmp(ks, "storageType", 11) == 0) {
        if (s_storage) return nullptr;
        if (p < end && *p == '"') {
          const char *vs, *ve;
          p = scan_jstring(p, end, b.tmp, &vs, &ve);
          if (!p) return nullptr;
          b.dv_storage.add_at(b.cur_row, vs, ve - vs);
          s_storage = true;
        } else if (!(p = skip_value(p, end))) return nullptr;
      } else if (kn == 14 && memcmp(ks, "pathOrInlineDv", 14) == 0) {
        if (s_path) return nullptr;
        if (p < end && *p == '"') {
          const char *vs, *ve;
          p = scan_jstring(p, end, b.tmp, &vs, &ve);
          if (!p) return nullptr;
          b.dv_pathinline.add_at(b.cur_row, vs, ve - vs);
          s_path = true;
        } else if (!(p = skip_value(p, end))) return nullptr;
      } else if (kn == 6 && memcmp(ks, "offset", 6) == 0) {
        if (s_off) return nullptr;
        NumKind k = parse_num_or_lit(&p, end, &num);
        if (k == NUM_INT) { b.dv_offset.add_at(b.cur_row, (int32_t)num); s_off = true; }
        else if (k != NUM_NULL) return nullptr;
      } else if (kn == 11 && memcmp(ks, "sizeInBytes", 11) == 0) {
        if (s_size) return nullptr;
        NumKind k = parse_num_or_lit(&p, end, &num);
        if (k == NUM_INT) { b.dv_size.add_at(b.cur_row, (int32_t)num); s_size = true; }
        else if (k != NUM_NULL) return nullptr;
      } else if (kn == 11 && memcmp(ks, "cardinality", 11) == 0) {
        if (s_card) return nullptr;
        NumKind k = parse_num_or_lit(&p, end, &num);
        if (k == NUM_INT) { b.dv_card.add_at(b.cur_row, num); s_card = true; }
        else if (k != NUM_NULL) return nullptr;
      } else if (kn == 11 && memcmp(ks, "maxRowIndex", 11) == 0) {
        if (s_max) return nullptr;
        NumKind k = parse_num_or_lit(&p, end, &num);
        if (k == NUM_INT) { b.dv_maxrow.add_at(b.cur_row, num); s_max = true; }
        else if (k != NUM_NULL) return nullptr;
      } else {
        if (!(p = skip_value(p, end))) return nullptr;
      }
      p = ws(p, end);
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; break; }
      return nullptr;
    }
  }
  // absent dv subfields stay lazy (densified by pad_all_to)
  (void)s_storage; (void)s_path; (void)s_off; (void)s_size; (void)s_card;
  (void)s_max;
  return p;
}

// partitionValues object -> per-entry key/value (cursor at '{')
const char* parse_pv(const char* p, const char* end, Builder& b) {
  ++p;
  b.pad_pv_to(b.cur_row);
  b.pv_valid.push_back(1);
  p = ws(p, end);
  if (p < end && *p == '}') {
    b.pv_offsets.push_back((int32_t)(b.pv_key.offsets.size() - 1));
    return p + 1;
  }
  while (true) {
    p = ws(p, end);
    if (p >= end || *p != '"') return nullptr;
    const char *ks, *ke;
    p = scan_jstring(p, end, b.tmp, &ks, &ke);
    if (!p) return nullptr;
    b.pv_key.add(ks, ke - ks);
    p = ws(p, end);
    if (p >= end || *p != ':') return nullptr;
    ++p;
    p = ws(p, end);
    if (p < end && *p == '"') {
      const char *vs, *ve;
      p = scan_jstring(p, end, b.tmp, &vs, &ve);
      if (!p) return nullptr;
      b.pv_val.add(vs, ve - vs);
    } else if (p < end && *p == 'n') {
      p += 4;
      b.pv_val.add_null();
    } else {
      // non-conforming scalar (number/bool): keep raw text as the value
      const char* vstart = p;
      if (!(p = skip_value(p, end))) return nullptr;
      b.pv_val.add(vstart, p - vstart);
    }
    p = ws(p, end);
    if (p < end && *p == ',') { ++p; continue; }
    if (p < end && *p == '}') { ++p; break; }
    return nullptr;
  }
  b.pv_offsets.push_back((int32_t)(b.pv_key.offsets.size() - 1));
  return p;
}

// Per-row scratch shared by the generic parser and the template fast
// path so both commit rows through the identical tail (finish_file_action).
struct RowScratch {
  bool s_path = false, s_pv = false, s_size = false, s_mt = false,
       s_dc = false, s_stats = false, s_tags = false, s_dv = false,
       s_brid = false, s_drcv = false, s_clust = false, s_dts = false,
       s_ext = false;
  bool path_in_scratch = false;  // span lives in a reused tmp buffer
  const char* path_s = nullptr;
  size_t path_n = 0;
  uint64_t path_h = 0;
};

// Drain the pending intern queue: prefetch every row's dictionary slot
// first (32 independent DRAM misses in flight), then intern in order.
// The serial intern-per-row pattern stalled a full cache miss per row —
// the dictionary spills L2 at hundreds of thousands of unique paths.
void flush_interns(Builder& b) {
  for (const auto& e : b.pend) {
#ifdef DAS_SSE2
    _mm_prefetch((const char*)&b.dict.slots[e.h & b.dict.mask],
                 _MM_HINT_T0);
#else
    (void)e;
#endif
  }
  for (const auto& e : b.pend) {
    bool was_new;
    b.path_code.push_back(b.dict.intern_hashed(e.s, e.n, e.h, &was_new));
    b.path_new.push_back(was_new ? 1 : 0);
  }
  b.pend.clear();
}

constexpr size_t kInternBatch = 32;

// The shared row-commit tail: queue the path intern, push the per-row
// lanes. False when the row has no path (protocol violation — caller
// rejects the scan). Paths decoded into a reused scratch buffer can't
// sit in the queue (the next row clobbers the bytes) — they flush the
// queue and intern immediately; the zero-copy common case batches.
bool finish_file_action(Builder& b, RowScratch& r, bool is_add,
                        int64_t row_no) {
  if (!r.s_path) return false;
  if (r.path_in_scratch) {
    flush_interns(b);
    bool was_new;
    b.path_code.push_back(
        b.dict.intern_hashed(r.path_s, r.path_n, r.path_h, &was_new));
    b.path_new.push_back(was_new ? 1 : 0);
  } else {
    b.pend.push_back({r.path_s, (uint32_t)r.path_n, r.path_h});
    if (b.pend.size() >= kInternBatch) flush_interns(b);
  }
  b.line_no.push_back(row_no);
  b.is_add.push_back(is_add ? 1 : 0);
  // absent columns stay lazy: densified in bulk by pad_all_to
  return true;
}

// The add/remove object body (cursor at '{' of the action value).
const char* parse_file_action(const char* p, const char* end, Builder& b,
                              bool is_add, int64_t row_no) {
  ++p;
  RowScratch rs;
  bool& s_path = rs.s_path;
  bool& s_pv = rs.s_pv;
  bool& s_size = rs.s_size;
  bool& s_mt = rs.s_mt;
  bool& s_dc = rs.s_dc;
  bool& s_stats = rs.s_stats;
  bool& s_tags = rs.s_tags;
  bool& s_dv = rs.s_dv;
  bool& s_brid = rs.s_brid;
  bool& s_drcv = rs.s_drcv;
  bool& s_clust = rs.s_clust;
  bool& s_dts = rs.s_dts;
  bool& s_ext = rs.s_ext;
  const char*& path_s = rs.path_s;
  size_t& path_n = rs.path_n;
  uint64_t& path_h = rs.path_h;
  b.cur_row = b.line_no.size();
  p = ws(p, end);
  if (p < end && *p == '}') {
    ++p;
  } else {
    while (true) {
      p = ws(p, end);
      if (p >= end || *p != '"') return nullptr;
      const char *ks, *ke;
      p = scan_jstring(p, end, b.tmp, &ks, &ke);
      if (!p) return nullptr;
      FieldId f = field_id(ks, ke - ks);
      p = ws(p, end);
      if (p >= end || *p != ':') return nullptr;
      ++p;
      p = ws(p, end);
      int64_t num;
      switch (f) {
        case F_PATH:
          if (s_path) return nullptr;
          if (p < end && *p == '"') {
            const char *vs, *ve;
            p = scan_jstring(p, end, b.path_tmp, &vs, &ve);
            if (!p) return nullptr;
            path_s = vs;
            path_n = (size_t)(ve - vs);
            rs.path_in_scratch = !b.path_tmp.empty() &&
                                 vs == b.path_tmp.data();
            path_h = PathDict::hash_bytes(path_s, path_n);
#ifdef DAS_SSE2
            // start the dictionary slot's cache line on its way while
            // the remaining fields parse (the probe is DRAM-bound)
            _mm_prefetch((const char*)&b.dict.slots[path_h & b.dict.mask],
                         _MM_HINT_T0);
#endif
            s_path = true;
          } else if (!(p = skip_value(p, end))) return nullptr;
          break;
        case F_PARTITION_VALUES:
          if (s_pv) return nullptr;
          if (p < end && *p == '{') {
            if (!(p = parse_pv(p, end, b))) return nullptr;
            s_pv = true;
          } else if (!(p = skip_value(p, end))) return nullptr;
          break;
        case F_SIZE: {
          if (s_size) return nullptr;
          NumKind k = parse_num_or_lit(&p, end, &num);
          if (k == NUM_INT) { b.size.add_at(b.cur_row, num); s_size = true; }
          else if (k != NUM_NULL) return nullptr;
          break;
        }
        case F_MODIFICATION_TIME: {
          if (s_mt) return nullptr;
          NumKind k = parse_num_or_lit(&p, end, &num);
          if (k == NUM_INT) { b.mod_time.add_at(b.cur_row, num); s_mt = true; }
          else if (k != NUM_NULL) return nullptr;
          break;
        }
        case F_DATA_CHANGE: {
          if (s_dc) return nullptr;
          NumKind k = parse_num_or_lit(&p, end, &num);
          if (k == NUM_BOOL_TRUE) { b.data_change.add_at(b.cur_row, 1); s_dc = true; }
          else if (k == NUM_BOOL_FALSE) { b.data_change.add_at(b.cur_row, 0); s_dc = true; }
          else if (k != NUM_NULL) return nullptr;
          break;
        }
        case F_STATS:
          if (s_stats) return nullptr;
          if (p < end && *p == '"') {
            if (b.lazy_stats) {
              const char* lq = skip_jstring(p, end);
              if (!lq) return nullptr;
              b.stats_s.add_at(b.cur_row, p - b.buf_base);
              b.stats_e.add_at(b.cur_row, lq - b.buf_base);
              p = lq;
            } else {
              const char *vs, *ve;
              p = scan_jstring(p, end, b.tmp, &vs, &ve);
              if (!p) return nullptr;
              b.stats.add_at(b.cur_row, vs, ve - vs);
            }
            s_stats = true;
          } else if (!(p = skip_value(p, end))) return nullptr;
          break;
        case F_TAGS:
          if (s_tags) return nullptr;
          if (p < end && *p == '{') {
            const char* vstart = p;
            if (!(p = skip_value(p, end))) return nullptr;
            b.tags.add_at(b.cur_row, vstart, p - vstart);
            s_tags = true;
          } else if (!(p = skip_value(p, end))) return nullptr;
          break;
        case F_DELETION_VECTOR:
          if (s_dv) return nullptr;
          if (p < end && *p == '{') {
            if (!(p = parse_dv(p, end, b))) return nullptr;
            s_dv = true;
          } else if (!(p = skip_value(p, end))) return nullptr;
          break;
        case F_BASE_ROW_ID: {
          if (s_brid) return nullptr;
          NumKind k = parse_num_or_lit(&p, end, &num);
          if (k == NUM_INT) { b.base_row_id.add_at(b.cur_row, num); s_brid = true; }
          else if (k != NUM_NULL) return nullptr;
          break;
        }
        case F_DRCV: {
          if (s_drcv) return nullptr;
          NumKind k = parse_num_or_lit(&p, end, &num);
          if (k == NUM_INT) { b.drcv.add_at(b.cur_row, num); s_drcv = true; }
          else if (k != NUM_NULL) return nullptr;
          break;
        }
        case F_CLUSTERING:
          if (s_clust) return nullptr;
          if (p < end && *p == '"') {
            const char *vs, *ve;
            p = scan_jstring(p, end, b.tmp, &vs, &ve);
            if (!p) return nullptr;
            b.clustering.add_at(b.cur_row, vs, ve - vs);
            s_clust = true;
          } else if (!(p = skip_value(p, end))) return nullptr;
          break;
        case F_DELETION_TIMESTAMP: {
          if (s_dts) return nullptr;
          NumKind k = parse_num_or_lit(&p, end, &num);
          if (k == NUM_INT) { b.del_ts.add_at(b.cur_row, num); s_dts = true; }
          else if (k != NUM_NULL) return nullptr;
          break;
        }
        case F_EXT_META: {
          if (s_ext) return nullptr;
          NumKind k = parse_num_or_lit(&p, end, &num);
          if (k == NUM_BOOL_TRUE) { b.ext_meta.add_at(b.cur_row, 1); s_ext = true; }
          else if (k == NUM_BOOL_FALSE) { b.ext_meta.add_at(b.cur_row, 0); s_ext = true; }
          else if (k != NUM_NULL) return nullptr;
          break;
        }
        case F_UNKNOWN:
          if (!(p = skip_value(p, end))) return nullptr;
          break;
      }
      p = ws(p, end);
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; break; }
      return nullptr;
    }
  }
  // a file action without a path cannot be keyed — reject the scan and
  // let the generic parser surface the protocol violation
  if (!finish_file_action(b, rs, is_add, row_no)) return nullptr;
  return p;
}

// Learn a template from a line the generic parser just accepted. Only
// the plain single-key `{"add":{...}}` / `{"remove":{...}}` shape with
// string/int/bool/partitionValues/tags values is templatable; anything
// else (deletionVector, nulls, arrays, fractional numbers, escaped keys,
// extra top-level keys) aborts and the line keeps using the generic path.
bool learn_template(const char* start, const char* stop, Tmpl& t) {
  if ((size_t)(stop - start) > kMaxTmplLine) return false;
  const char* p = start;
  const char* lit_start = start;
  std::string scratch;
  auto in_line = [&](const char* s) { return s >= start && s < stop; };
  p = ws(p, stop);
  if (p >= stop || *p != '{') return false;
  ++p;
  p = ws(p, stop);
  if (p >= stop || *p != '"') return false;
  const char *ks, *ke;
  p = scan_jstring(p, stop, scratch, &ks, &ke);
  if (!p || !in_line(ks)) return false;  // escaped key: not templatable
  if (ke - ks == 3 && memcmp(ks, "add", 3) == 0) t.is_add = true;
  else if (ke - ks == 6 && memcmp(ks, "remove", 6) == 0) t.is_add = false;
  else return false;
  p = ws(p, stop);
  if (p >= stop || *p != ':') return false;
  ++p;
  p = ws(p, stop);
  if (p >= stop || *p != '{') return false;
  ++p;
  p = ws(p, stop);
  if (p < stop && *p == '}') return false;  // empty action: generic is fine
  t.segs.clear();
  while (true) {
    p = ws(p, stop);
    if (p >= stop || *p != '"') return false;
    p = scan_jstring(p, stop, scratch, &ks, &ke);
    if (!p || !in_line(ks)) return false;
    FieldId f = field_id(ks, ke - ks);
    p = ws(p, stop);
    if (p >= stop || *p != ':') return false;
    ++p;
    p = ws(p, stop);
    if (p >= stop || (int)t.segs.size() >= kMaxTmplSlots) return false;
    Tmpl::Seg sg;
    sg.slot.field = (uint8_t)f;
    char c = *p;
    // commit_template dispatches on FIELD: a slot whose value kind
    // doesn't match what the field's commit case reads (string span vs
    // number) must demote to F_UNKNOWN — the generic parser likewise
    // skips wrong-typed known fields without storing them
    if (c == '-' || (c >= '0' && c <= '9') || c == 't' || c == 'f') {
      switch (f) {
        case F_SIZE: case F_MODIFICATION_TIME: case F_DATA_CHANGE:
        case F_BASE_ROW_ID: case F_DRCV: case F_DELETION_TIMESTAMP:
        case F_EXT_META:
          break;
        default:
          sg.slot.field = (uint8_t)F_UNKNOWN;
      }
    } else if (c == '"') {
      switch (f) {
        case F_PATH: case F_STATS: case F_CLUSTERING: case F_UNKNOWN:
          break;
        default:
          sg.slot.field = (uint8_t)F_UNKNOWN;
      }
    }
    if (c == '"') {
      sg.slot.type = SL_STR;
      // literal includes the opening quote; value ends AT the closing
      // quote (which starts the next literal)
      sg.off = (uint32_t)(lit_start - start);
      sg.len = (uint32_t)(p + 1 - lit_start);
      const char* q = skip_jstring(p, stop);
      if (!q) return false;
      lit_start = q - 1;  // the closing quote
      p = q;
    } else if (c == '-' || (c >= '0' && c <= '9')) {
      sg.slot.type = SL_INT;
      sg.off = (uint32_t)(lit_start - start);
      sg.len = (uint32_t)(p - lit_start);
      const char* q = p;
      if (*q == '-') ++q;
      const char* d = q;
      while (q < stop && *q >= '0' && *q <= '9') ++q;
      if (q == d) return false;
      // fractional/exponent forms would parse differently here than in
      // the generic strtod path — not templatable
      if (q < stop && (*q == '.' || *q == 'e' || *q == 'E')) return false;
      lit_start = q;
      p = q;
    } else if (c == 't' || c == 'f') {
      if (f != F_DATA_CHANGE && f != F_EXT_META) return false;
      sg.slot.type = SL_BOOL;
      sg.off = (uint32_t)(lit_start - start);
      sg.len = (uint32_t)(p - lit_start);
      if (stop - p >= 4 && memcmp(p, "true", 4) == 0) p += 4;
      else if (stop - p >= 5 && memcmp(p, "false", 5) == 0) p += 5;
      else return false;
      lit_start = p;
    } else if (c == '{' &&
               (f == F_PARTITION_VALUES || f == F_TAGS)) {
      sg.slot.type = (f == F_PARTITION_VALUES) ? SL_PV : SL_RAW;
      sg.off = (uint32_t)(lit_start - start);
      sg.len = (uint32_t)(p - lit_start);
      const char* q = skip_value(p, stop);
      if (!q) return false;
      lit_start = q;
      p = q;
    } else {
      return false;  // null / arrays / deletionVector / unknown objects
    }
    t.segs.push_back(sg);
    p = ws(p, stop);
    if (p < stop && *p == ',') { ++p; continue; }
    if (p < stop && *p == '}') { ++p; break; }
    return false;
  }
  p = ws(p, stop);
  if (p >= stop || *p != '}') return false;  // extra top-level keys
  ++p;
  if (ws(p, stop) != stop) return false;
  t.tail_off = (uint32_t)(lit_start - start);
  t.tail_len = (uint32_t)(stop - lit_start);
  t.line.assign(start, stop - start);
  return !t.segs.empty();
}

// Phase 1: match a line against a template, recording value spans. The
// only builder writes are speculative arena appends for escaped
// stats/clustering values — match_template (below) rolls those back on
// a mismatch, so failure is still a clean fallback.
inline bool match_template_impl(Builder& b, const Tmpl& t, const char* p,
                                const char* stop, SlotVal* out) {
  const char* base = t.line.data();
  const size_t nseg = t.segs.size();
  for (size_t i = 0; i < nseg; i++) {
    const Tmpl::Seg& sg = t.segs[i];
    if ((size_t)(stop - p) < sg.len || !bytes_eq(p, base + sg.off, sg.len))
      return false;
    p += sg.len;
    SlotVal& v = out[i];
    // slots are stack scratch reused across template attempts: flags
    // must never leak from a previous (failed) match
    v.esc = false;
    v.in_arena = false;
    v.lazy_span = false;
    switch (sg.slot.type) {
      case SL_STR: {
        if (b.lazy_stats && sg.slot.field == (uint8_t)F_STATS) {
          // raw span only: find the closing quote, decode never
          const char* lq = skip_jstring(p - 1, stop);
          if (!lq) return false;
          v.lazy_span = true;
          v.in_arena = false;
          v.esc = false;
          v.a_start = (p - 1) - b.buf_base;
          v.a_end = lq - b.buf_base;
          p = lq - 1;  // the closing quote starts the next literal
          break;
        }
        const char* q = scan_to_special(p, stop);
        if (q >= stop) return false;
        v.esc = false;
        v.in_arena = false;
        v.lazy_span = false;
        if (*q == '"') {  // no escapes: zero-copy span into the input
          v.vs = p;
          v.ve = q;
          p = q;  // closing quote starts the next literal
        } else {
          v.esc = true;
          // escapes: unescape ONCE here. Plain output columns (stats,
          // clustering) decode STRAIGHT into their arena — stats are
          // ~60% of commit bytes and the scratch-then-copy pattern was
          // a second full pass over them. A later mismatch rolls the
          // arena back (match_template wrapper).
          StrCol* direct = nullptr;
          if (sg.slot.field == (uint8_t)F_STATS) direct = &b.stats;
          else if (sg.slot.field == (uint8_t)F_CLUSTERING)
            direct = &b.clustering;
          if (direct != nullptr) {
            v.in_arena = true;
            v.a_start = (int64_t)direct->arena.size();
            const char* after = scan_jstring_append(p - 1, stop,
                                                    direct->arena);
            if (!after ||
                direct->arena.size() > (size_t)INT32_MAX) return false;
            v.a_end = (int64_t)direct->arena.size();
            p = after - 1;
          } else {
            const char *s2, *e2;
            const char* after =
                scan_jstring(p - 1, stop, b.slot_tmp[i], &s2, &e2);
            if (!after) return false;
            v.vs = s2;
            v.ve = e2;
            p = after - 1;  // scan_jstring consumed the closing quote
          }
        }
        if (sg.slot.field == (uint8_t)F_PATH) {
          // hash + prefetch NOW: the dictionary probe is DRAM-bound and
          // the rest of the match/commit hides its latency (committing
          // without this stalls a full miss per row)
          uint64_t h = PathDict::hash_bytes(v.vs, (size_t)(v.ve - v.vs));
          v.num = (int64_t)h;
#ifdef DAS_SSE2
          _mm_prefetch((const char*)&b.dict.slots[h & b.dict.mask],
                       _MM_HINT_T0);
#endif
        }
        break;
      }
      case SL_INT: {
        const char* q = p;
        bool neg = q < stop && *q == '-';
        if (neg) ++q;
        int64_t val = 0;
        const char* d = q;
        while (q < stop && *q >= '0' && *q <= '9') {
          val = val * 10 + (*q - '0');
          ++q;
        }
        if (q == d) return false;
        v.num = neg ? -val : val;
        p = q;
        break;
      }
      case SL_BOOL: {
        if ((size_t)(stop - p) >= 4 && memcmp(p, "true", 4) == 0) {
          v.num = 1;
          p += 4;
        } else if ((size_t)(stop - p) >= 5 && memcmp(p, "false", 5) == 0) {
          v.num = 0;
          p += 5;
        } else {
          return false;
        }
        break;
      }
      case SL_PV:
      case SL_RAW: {
        if (p >= stop || *p != '{') return false;
        const char* q = skip_value(p, stop);
        if (!q) return false;
        v.vs = p;
        v.ve = q;
        p = q;
        break;
      }
    }
  }
  return (size_t)(stop - p) == t.tail_len &&
         bytes_eq(p, base + t.tail_off, t.tail_len);
}

inline bool match_template(Builder& b, const Tmpl& t, const char* p,
                           const char* stop, SlotVal* out) {
  const size_t stats0 = b.stats.arena.size();
  const size_t clust0 = b.clustering.arena.size();
  if (match_template_impl(b, t, p, stop, out)) return true;
  // roll back speculative decodes from the failed attempt
  if (b.stats.arena.size() != stats0) b.stats.arena.resize(stats0);
  if (b.clustering.arena.size() != clust0) b.clustering.arena.resize(clust0);
  return false;
}



// Phase 2: commit the matched values through the same column adds and
// row tail as the generic parser.
bool commit_template(Builder& b, const Tmpl& t, const SlotVal* vals,
                     int64_t row_no) {
  RowScratch rs;
  b.cur_row = b.line_no.size();
  const size_t nseg = t.segs.size();
  for (size_t i = 0; i < nseg; i++) {
    const TmplSlot& sl = t.segs[i].slot;
    const SlotVal& v = vals[i];
    switch ((FieldId)sl.field) {
      case F_PATH:
        rs.path_s = v.vs;
        rs.path_n = (size_t)(v.ve - v.vs);
        rs.path_h = (uint64_t)v.num;  // hashed (and prefetched) at match
        rs.path_in_scratch = v.esc;   // scratch bytes don't survive a row
        rs.s_path = true;
        break;
      case F_PARTITION_VALUES:
        if (!parse_pv(v.vs, v.ve, b)) return false;
        rs.s_pv = true;
        break;
      case F_SIZE: b.size.add_at(b.cur_row, v.num); rs.s_size = true; break;
      case F_MODIFICATION_TIME: b.mod_time.add_at(b.cur_row, v.num); rs.s_mt = true; break;
      case F_DATA_CHANGE:
        b.data_change.add_at(b.cur_row, (uint8_t)v.num);
        rs.s_dc = true;
        break;
      case F_STATS:
        if (v.lazy_span) {
          b.stats_s.add_at(b.cur_row, v.a_start);
          b.stats_e.add_at(b.cur_row, v.a_end);
        } else if (v.in_arena) {
          if (b.stats.valid.size() < b.cur_row) {
            // null gap BEFORE this row: pad with the pre-append offset
            b.stats.offsets.resize(b.cur_row + 1, (int32_t)v.a_start);
            b.stats.valid.resize(b.cur_row, 0);
          }
          b.stats.offsets.push_back((int32_t)v.a_end);
          b.stats.valid.push_back(1);
        } else {
          b.stats.add_at(b.cur_row, v.vs, v.ve - v.vs);
        }
        rs.s_stats = true;
        break;
      case F_TAGS: b.tags.add_at(b.cur_row, v.vs, v.ve - v.vs); rs.s_tags = true; break;
      case F_BASE_ROW_ID: b.base_row_id.add_at(b.cur_row, v.num); rs.s_brid = true; break;
      case F_DRCV: b.drcv.add_at(b.cur_row, v.num); rs.s_drcv = true; break;
      case F_CLUSTERING:
        if (v.in_arena) {
          if (b.clustering.valid.size() < b.cur_row) {
            b.clustering.offsets.resize(b.cur_row + 1, (int32_t)v.a_start);
            b.clustering.valid.resize(b.cur_row, 0);
          }
          b.clustering.offsets.push_back((int32_t)v.a_end);
          b.clustering.valid.push_back(1);
        } else {
          b.clustering.add_at(b.cur_row, v.vs, v.ve - v.vs);
        }
        rs.s_clust = true;
        break;
      case F_DELETION_TIMESTAMP: b.del_ts.add_at(b.cur_row, v.num); rs.s_dts = true; break;
      case F_EXT_META: b.ext_meta.add_at(b.cur_row, (uint8_t)v.num); rs.s_ext = true; break;
      case F_DELETION_VECTOR:  // never templated
      case F_UNKNOWN:
        break;
    }
  }
  return finish_file_action(b, rs, t.is_add, row_no);
}

bool parse_line_generic(const char* start, const char* stop, int64_t row_no,
                        int64_t base_off, Builder& b);

// One line (one action object). row_no is the line's global row number.
bool parse_line(const char* start, const char* stop, int64_t row_no,
                int64_t base_off, Builder& b) {
  // template fast path: match against the learned skeletons (MRU first)
  SlotVal vals[kMaxTmplSlots];
  for (size_t ti = 0; ti < b.tmpls.size(); ti++) {
    if (match_template(b, b.tmpls[ti], start, stop, vals)) {
      if (ti) std::swap(b.tmpls[0], b.tmpls[ti]);
      ++b.tmpl_hits;
      return commit_template(b, b.tmpls[0], vals, row_no);
    }
  }
  return parse_line_generic(start, stop, row_no, base_off, b);
}

bool parse_line_generic(const char* start, const char* stop, int64_t row_no,
                        int64_t base_off, Builder& b) {
  const char* p = ws(start, stop);
  if (p >= stop || *p != '{') return false;
  ++p;
  p = ws(p, stop);
  if (p >= stop || *p != '"') return false;
  const char *ks, *ke;
  p = scan_jstring(p, stop, b.tmp, &ks, &ke);
  if (!p) return false;
  size_t kn = ke - ks;
  bool is_add = (kn == 3 && memcmp(ks, "add", 3) == 0);
  bool is_rm = (kn == 6 && memcmp(ks, "remove", 6) == 0);
  p = ws(p, stop);
  if (p >= stop || *p != ':') return false;
  ++p;
  p = ws(p, stop);
  if ((is_add || is_rm) && p < stop && *p == '{') {
    if (!(p = parse_file_action(p, stop, b, is_add, row_no))) return false;
    // single-key objects are the norm; tolerate (skip) extra keys
    p = ws(p, stop);
    while (p < stop && *p == ',') {
      ++p;
      p = ws(p, stop);
      if (p >= stop || *p != '"') return false;
      p = scan_jstring(p, stop, b.tmp, &ks, &ke);
      if (!p) return false;
      p = ws(p, stop);
      if (p >= stop || *p != ':') return false;
      ++p;
      if (!(p = skip_value(p, stop))) return false;
      p = ws(p, stop);
    }
    if (p < stop && *p == '}') {
      // learn this line's layout so the next same-shaped line takes the
      // template fast path; stop bothering if layouts never repeat
      if (b.tmpl_enabled) {
        Tmpl t;
        if (learn_template(start, stop, t)) {
          b.tmpls.insert(b.tmpls.begin(), std::move(t));
          if (b.tmpls.size() > kMaxTmpls) b.tmpls.pop_back();
          ++b.tmpl_learns;
          if (b.tmpl_learns > 64 && b.tmpl_hits < b.tmpl_learns)
            b.tmpl_enabled = false;
        }
      }
      return true;
    }
    return false;
  }
  // everything else: hand the whole line to the host
  b.other_line_no.push_back(row_no);
  b.other_start.push_back(base_off);
  b.other_end.push_back(base_off + (stop - start));
  return true;
}

// ------------------------------------------------------------- result/ABI

struct FinalStr {
  std::string arena;
  std::vector<int32_t> offsets;  // n+1, leading 0
  std::vector<uint8_t> valid;
};

template <typename T>
struct FinalNum {
  std::vector<T> vals;
  std::vector<uint8_t> valid;
};

struct Result {
  int32_t error = 0;
  int64_t n_rows = 0, n_lines = 0, n_others = 0, n_pv_entries = 0;
  std::vector<int64_t> line_no;
  std::vector<uint8_t> is_add;
  // dictionary-encoded paths
  std::vector<uint32_t> path_code;   // global codes, per row
  std::vector<uint8_t> path_new;     // global first-appearance flag, per row
  std::vector<uint32_t> refs;        // codes of non-new rows, in row order
  std::string uniq_arena;            // unique path bytes, code order
  std::vector<uint32_t> uniq_offs;   // n_uniq+1, leading 0
  FinalStr pv_key, pv_val, stats, tags, dv_storage, dv_pathinline, clustering;
  std::vector<int32_t> pv_offsets;  // n+1 entry offsets per row
  std::vector<uint8_t> pv_valid;
  FinalNum<int64_t> size, mod_time, dv_card, dv_maxrow, base_row_id, drcv,
      del_ts;
  FinalNum<int32_t> dv_offset, dv_size;
  FinalNum<uint8_t> data_change, ext_meta;
  std::vector<uint8_t> dv_valid;
  int32_t lazy_stats = 0;          // 1: stats live as raw spans below
  FinalNum<int64_t> stats_s, stats_e;
  std::vector<int64_t> other_line_no, other_start, other_end;
  std::vector<int64_t> line_starts;
};

// false when the merged arena would overflow int32 offsets (the caller
// flags the scan as failed and the host falls back to the generic
// parser). The single-builder case (1 thread — the common container
// shape) is a pure move: no copy of arenas or offset rebasing.
bool merge_str(FinalStr& out, std::vector<Builder>& bs, StrCol Builder::* m) {
  size_t bytes = 0, rows = 0;
  for (auto& b : bs) {
    bytes += (b.*m).arena.size();
    rows += (b.*m).valid.size();
  }
  if (bytes > (size_t)INT32_MAX) return false;
  if (bs.size() == 1) {
    StrCol& c = bs[0].*m;
    out.arena = std::move(c.arena);
    out.offsets = std::move(c.offsets);
    out.valid = std::move(c.valid);
    return true;
  }
  out.arena.reserve(bytes);
  out.offsets.reserve(rows + 1);
  out.valid.reserve(rows);
  out.offsets.push_back(0);
  for (auto& b : bs) {
    StrCol& c = b.*m;
    int32_t base = (int32_t)out.arena.size();
    out.arena += c.arena;
    for (size_t i = 1; i < c.offsets.size(); i++)
      out.offsets.push_back(base + c.offsets[i]);
    out.valid.insert(out.valid.end(), c.valid.begin(), c.valid.end());
  }
  return true;
}

template <typename T, typename M>
void merge_num(FinalNum<T>& out, std::vector<Builder>& bs, M m) {
  if (bs.size() == 1) {
    out.vals = std::move((bs[0].*m).vals);
    out.valid = std::move((bs[0].*m).valid);
    return;
  }
  for (auto& b : bs) {
    auto& c = b.*m;
    out.vals.insert(out.vals.end(), c.vals.begin(), c.vals.end());
    out.valid.insert(out.valid.end(), c.valid.begin(), c.valid.end());
  }
}

template <typename T>
void merge_vec(std::vector<T>& out, std::vector<Builder>& bs,
               std::vector<T> Builder::* m) {
  if (bs.size() == 1) {
    out = std::move(bs[0].*m);
    return;
  }
  for (auto& b : bs)
    out.insert(out.end(), (b.*m).begin(), (b.*m).end());
}

}  // namespace

extern "C" {

void* das_scan2(const char* buf, int64_t len, int32_t n_threads,
                int32_t flags) {
  const bool lazy_stats = (flags & 1) != 0;
  Result* r = new Result();
  r->lazy_stats = lazy_stats ? 1 : 0;
  if (len <= 0) return r;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 32) n_threads = 32;
  // split at line boundaries
  std::vector<int64_t> cut(n_threads + 1, 0);
  cut[n_threads] = len;
  for (int t = 1; t < n_threads; t++) {
    int64_t target = len * t / n_threads;
    if (target < cut[t - 1]) target = cut[t - 1];
    const char* nl = (const char*)memchr(buf + target, '\n', len - target);
    cut[t] = nl ? (nl - buf) + 1 : len;
  }
  std::vector<Builder> builders(n_threads);
  auto work = [&](int t) {
    Builder& b = builders[t];
    b.lazy_stats = lazy_stats;
    b.buf_base = buf;
    size_t span = (size_t)(cut[t + 1] - cut[t]);
    // ~230B/line typical: presize the per-row vectors to dodge most
    // geometric regrowth copies — at the GB scale each missed reserve
    // is a multi-hundred-MB realloc memcpy plus a fresh round of page
    // faults (reserve only maps, first touch pays the fault once)
    size_t est_rows = span / 128 + 16;
    b.line_no.reserve(est_rows);
    b.is_add.reserve(est_rows);
    b.path_code.reserve(est_rows);
    b.path_new.reserve(est_rows);
    b.dict.reserve_slots(est_rows);
    b.dict.arena.reserve(span / 6);
    b.dict.offs.reserve(est_rows);
    b.line_starts.reserve(est_rows);
    // stats dominate commit bytes (~60%); the rest are small per-row
    b.stats.arena.reserve(span * 2 / 3);
    b.stats.offsets.reserve(est_rows);
    b.stats.valid.reserve(est_rows);
    b.pv_offsets.reserve(est_rows);
    b.pv_valid.reserve(est_rows);
    b.dv_valid.reserve(est_rows);
    for (auto* c : {&b.size, &b.mod_time, &b.dv_card, &b.dv_maxrow,
                    &b.base_row_id, &b.drcv, &b.del_ts}) {
      c->vals.reserve(est_rows);
      c->valid.reserve(est_rows);
    }
    for (auto* c8 : {&b.data_change, &b.ext_meta}) {
      c8->vals.reserve(est_rows);
      c8->valid.reserve(est_rows);
    }
    for (auto* s : {&b.tags, &b.clustering, &b.dv_storage,
                    &b.dv_pathinline}) {
      s->offsets.reserve(est_rows);
      s->valid.reserve(est_rows);
    }
    b.dv_offset.vals.reserve(est_rows);
    b.dv_offset.valid.reserve(est_rows);
    b.dv_size.vals.reserve(est_rows);
    b.dv_size.valid.reserve(est_rows);
    const char* p = buf + cut[t];
    const char* end = buf + cut[t + 1];
    while (p < end) {
      const char* nl = (const char*)memchr(p, '\n', end - p);
      const char* stop = nl ? nl : end;
      // skip blank lines (the inter-file padding byte and trailing \n)
      const char* q = ws(p, stop);
      if (q != stop) {
        b.line_starts.push_back(p - buf);
        if (!parse_line(p, stop, (int64_t)b.line_starts.size() - 1,
                        p - buf, b)) {
          b.failed = true;
          break;
        }
      }
      if (!nl) break;
      p = nl + 1;
    }
    if (!b.failed) {
      flush_interns(b);
      b.pad_all_to(b.line_no.size());
    }
  };
  if (n_threads == 1) {
    work(0);  // single-core hosts: no thread spawn at all
  } else {
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; t++) threads.emplace_back(work, t);
    for (auto& th : threads) th.join();
  }
  for (auto& b : builders)
    if (b.failed) { r->error = 1; return r; }

  // rebase per-thread local row numbers to global ones
  int64_t row_base = 0;
  for (auto& b : builders) {
    for (auto& v : b.line_no) v += row_base;
    for (auto& v : b.other_line_no) v += row_base;
    row_base += (int64_t)b.line_starts.size();
  }
  r->n_lines = row_base;

  // ---- merge path dictionaries into global first-appearance codes.
  // Thread ranges are in stream order, so walking threads in order and
  // interning each thread's local uniques (themselves in local
  // first-appearance order) reproduces the exact global
  // first-appearance coding a single sequential pass would produce.
  {
    size_t total_uniq_bound = 0, total_bytes = 0, total_rows = 0;
    for (auto& b : builders) {
      total_uniq_bound += b.dict.count();
      total_bytes += b.dict.arena.size();
      total_rows += b.path_code.size();
    }
    if (total_uniq_bound >= 0xFFFFFFFFull) { r->error = 1; return r; }
    r->path_code.reserve(total_rows);
    r->path_new.reserve(total_rows);
    if (n_threads == 1) {
      Builder& b = builders[0];
      r->path_code = std::move(b.path_code);
      r->path_new = std::move(b.path_new);
      r->uniq_arena = std::move(b.dict.arena);
      r->uniq_offs = std::move(b.dict.offs);
    } else {
      PathDict global;
      global.reserve_slots(total_uniq_bound);
      global.arena.reserve(total_bytes);
      global.offs.reserve(total_uniq_bound + 1);
      for (auto& b : builders) {
        size_t nu = b.dict.count();
        std::vector<uint32_t> remap(nu);
        std::vector<uint8_t> remap_new(nu);
        for (size_t c = 0; c < nu; c++) {
          bool was_new;
          remap[c] = global.intern(
              b.dict.arena.data() + b.dict.offs[c],
              b.dict.offs[c + 1] - b.dict.offs[c], &was_new);
          remap_new[c] = was_new ? 1 : 0;
        }
        for (size_t i = 0; i < b.path_code.size(); i++) {
          uint32_t lc = b.path_code[i];
          r->path_code.push_back(remap[lc]);
          r->path_new.push_back(b.path_new[i] & remap_new[lc]);
        }
      }
      r->uniq_arena = std::move(global.arena);
      r->uniq_offs = std::move(global.offs);
    }
    // the Python side views uniq_offs as int32 Arrow offsets
    if (r->uniq_arena.size() > (size_t)INT32_MAX) { r->error = 1; return r; }
    // explicit back-references for the first-appearance delta encoding
    size_t n_refs = 0;
    for (uint8_t f : r->path_new) n_refs += !f;
    r->refs.reserve(n_refs);
    for (size_t i = 0; i < r->path_code.size(); i++)
      if (!r->path_new[i]) r->refs.push_back(r->path_code[i]);
  }

  merge_vec(r->line_no, builders, &Builder::line_no);
  merge_vec(r->is_add, builders, &Builder::is_add);
  merge_vec(r->pv_valid, builders, &Builder::pv_valid);
  merge_vec(r->dv_valid, builders, &Builder::dv_valid);
  merge_vec(r->other_line_no, builders, &Builder::other_line_no);
  merge_vec(r->other_start, builders, &Builder::other_start);
  merge_vec(r->other_end, builders, &Builder::other_end);
  merge_vec(r->line_starts, builders, &Builder::line_starts);
  r->n_rows = (int64_t)r->line_no.size();
  r->n_others = (int64_t)r->other_line_no.size();

  if (builders.size() == 1) {
    r->pv_offsets = std::move(builders[0].pv_offsets);
  } else {
    r->pv_offsets.reserve(r->n_rows + 1);
    r->pv_offsets.push_back(0);
    int32_t base = 0;
    for (auto& b : builders) {
      for (size_t i = 1; i < b.pv_offsets.size(); i++)
        r->pv_offsets.push_back(base + b.pv_offsets[i]);
      base += b.pv_offsets.empty() ? 0 : b.pv_offsets.back();
    }
  }
  r->n_pv_entries = r->pv_offsets.empty() ? 0 : r->pv_offsets.back();

  bool str_ok = merge_str(r->pv_key, builders, &Builder::pv_key) &&
                merge_str(r->pv_val, builders, &Builder::pv_val) &&
                merge_str(r->stats, builders, &Builder::stats) &&
                merge_str(r->tags, builders, &Builder::tags) &&
                merge_str(r->dv_storage, builders, &Builder::dv_storage) &&
                merge_str(r->dv_pathinline, builders, &Builder::dv_pathinline) &&
                merge_str(r->clustering, builders, &Builder::clustering);
  if (!str_ok) { r->error = 1; return r; }
  merge_num(r->size, builders, &Builder::size);
  merge_num(r->mod_time, builders, &Builder::mod_time);
  merge_num(r->data_change, builders, &Builder::data_change);
  merge_num(r->dv_offset, builders, &Builder::dv_offset);
  merge_num(r->dv_size, builders, &Builder::dv_size);
  merge_num(r->dv_card, builders, &Builder::dv_card);
  merge_num(r->dv_maxrow, builders, &Builder::dv_maxrow);
  merge_num(r->base_row_id, builders, &Builder::base_row_id);
  merge_num(r->drcv, builders, &Builder::drcv);
  merge_num(r->del_ts, builders, &Builder::del_ts);
  merge_num(r->ext_meta, builders, &Builder::ext_meta);
  if (lazy_stats) {
    merge_num(r->stats_s, builders, &Builder::stats_s);
    merge_num(r->stats_e, builders, &Builder::stats_e);
  }
  return r;
}

void* das_scan(const char* buf, int64_t len, int32_t n_threads) {
  return das_scan2(buf, len, n_threads, 0);
}

// Decode the deferred stats spans into the standard stats column. One
// bulk pass; idempotent. Returns 0 ok, 1 on malformed escape content
// (the scan only validated escape-pair STRUCTURE in lazy mode).
int32_t das_stats_materialize(void* h, const char* buf, int64_t len) {
  Result* r = (Result*)h;
  if (!r->lazy_stats) return 0;
  const char* end = buf + len;
  size_t total = 0;
  for (size_t i = 0; i < r->stats_s.vals.size(); i++)
    if (r->stats_s.valid[i])
      total += (size_t)(r->stats_e.vals[i] - r->stats_s.vals[i]);
  FinalStr out;
  out.arena.reserve(total);
  out.offsets.reserve(r->stats_s.vals.size() + 1);
  out.valid.reserve(r->stats_s.vals.size());
  out.offsets.push_back(0);
  for (size_t i = 0; i < r->stats_s.vals.size(); i++) {
    if (!r->stats_s.valid[i]) {
      out.offsets.push_back((int32_t)out.arena.size());
      out.valid.push_back(0);
      continue;
    }
    const char* p = buf + r->stats_s.vals[i];
    const char* stop = buf + r->stats_e.vals[i];
    if (stop > end || p >= stop) return 1;
    const char* after = scan_jstring_append(p, stop, out.arena);
    if (after != stop) return 1;
    if (out.arena.size() > (size_t)INT32_MAX) return 1;
    out.offsets.push_back((int32_t)out.arena.size());
    out.valid.push_back(1);
  }
  r->stats = std::move(out);
  // release the span vectors (~18 bytes/row) — the Result outlives the
  // snapshot via Arrow foreign buffers, so dead lanes must not linger
  r->stats_s.vals.clear();
  r->stats_s.vals.shrink_to_fit();
  r->stats_s.valid.clear();
  r->stats_s.valid.shrink_to_fit();
  r->stats_e.vals.clear();
  r->stats_e.vals.shrink_to_fit();
  r->stats_e.valid.clear();
  r->stats_e.valid.shrink_to_fit();
  r->lazy_stats = 0;
  return 0;
}

void das_free(void* h) { delete (Result*)h; }
int32_t das_error(void* h) { return ((Result*)h)->error; }

// counts by index — mirrored in delta_tpu/native/__init__.py:
// 0 rows, 1 lines, 2 others, 3 pv entries, 4 unique paths, 5 refs,
// 6 uniq arena bytes, 7 pv_key arena, 8 pv_val arena, 9 stats arena,
// 10 tags arena, 11 dv_storage arena, 12 dv_pathinline arena,
// 13 clustering arena
int64_t das_n(void* h, int32_t what) {
  Result* r = (Result*)h;
  switch (what) {
    case 0: return r->n_rows;
    case 1: return r->n_lines;
    case 2: return r->n_others;
    case 3: return r->n_pv_entries;
    case 4: return (int64_t)r->uniq_offs.size() - 1;
    case 5: return (int64_t)r->refs.size();
    case 6: return (int64_t)r->uniq_arena.size();
    case 7: return (int64_t)r->pv_key.arena.size();
    case 8: return (int64_t)r->pv_val.arena.size();
    case 9: return (int64_t)r->stats.arena.size();
    case 10: return (int64_t)r->tags.arena.size();
    case 11: return (int64_t)r->dv_storage.arena.size();
    case 12: return (int64_t)r->dv_pathinline.arena.size();
    case 13: return (int64_t)r->clustering.arena.size();
    case 14: return (int64_t)r->lazy_stats;
    default: return -1;
  }
}

const void* das_ptr(void* h, int32_t which) {
  Result* r = (Result*)h;
  switch (which) {
    case 0: return r->line_no.data();
    case 1: return r->is_add.data();
    case 2: return r->path_code.data();
    case 3: return r->path_new.data();
    case 4: return r->refs.data();
    case 5: return r->uniq_offs.data();
    case 6: return r->uniq_arena.data();
    case 7: return r->pv_offsets.data();
    case 8: return r->pv_valid.data();
    case 9: return r->pv_key.offsets.data();
    case 10: return r->pv_key.arena.data();
    case 11: return r->pv_val.offsets.data();
    case 12: return r->pv_val.arena.data();
    case 13: return r->pv_val.valid.data();
    case 14: return r->size.vals.data();
    case 15: return r->size.valid.data();
    case 16: return r->mod_time.vals.data();
    case 17: return r->mod_time.valid.data();
    case 18: return r->data_change.vals.data();
    case 19: return r->data_change.valid.data();
    case 20: return r->stats.offsets.data();
    case 21: return r->stats.arena.data();
    case 22: return r->stats.valid.data();
    case 23: return r->tags.offsets.data();
    case 24: return r->tags.arena.data();
    case 25: return r->tags.valid.data();
    case 26: return r->dv_valid.data();
    case 27: return r->dv_storage.offsets.data();
    case 28: return r->dv_storage.arena.data();
    case 29: return r->dv_storage.valid.data();
    case 30: return r->dv_pathinline.offsets.data();
    case 31: return r->dv_pathinline.arena.data();
    case 32: return r->dv_pathinline.valid.data();
    case 33: return r->dv_offset.vals.data();
    case 34: return r->dv_offset.valid.data();
    case 35: return r->dv_size.vals.data();
    case 36: return r->dv_size.valid.data();
    case 37: return r->dv_card.vals.data();
    case 38: return r->dv_card.valid.data();
    case 39: return r->dv_maxrow.vals.data();
    case 40: return r->dv_maxrow.valid.data();
    case 41: return r->base_row_id.vals.data();
    case 42: return r->base_row_id.valid.data();
    case 43: return r->drcv.vals.data();
    case 44: return r->drcv.valid.data();
    case 45: return r->clustering.offsets.data();
    case 46: return r->clustering.arena.data();
    case 47: return r->clustering.valid.data();
    case 48: return r->del_ts.vals.data();
    case 49: return r->del_ts.valid.data();
    case 50: return r->ext_meta.vals.data();
    case 51: return r->ext_meta.valid.data();
    case 52: return r->other_line_no.data();
    case 53: return r->other_start.data();
    case 54: return r->other_end.data();
    case 55: return r->line_starts.data();
    case 56: return r->stats_s.vals.data();
    case 57: return r->stats_s.valid.data();
    case 58: return r->stats_e.vals.data();
    default: return nullptr;
  }
}

// ----------------------------------------------------------- file reading
//
// dar_read: read a list of local files into one contiguous buffer with
// a forced '\n' after each file (blank separators are skipped by the
// scanner). Listing 100k commit files costs ~40us/file of interpreter
// overhead when read from Python; here it is two syscalls per file.

// GB-scale anonymous buffer mapped with transparent-huge-page advice:
// on hypervisor-backed VMs a minor fault costs tens of microseconds, so
// first-touching a 3GB std::string at 4KiB granularity (~800k faults)
// dominates a cold snapshot load. 2MiB THP cuts the fault count 512x,
// and MADV_POPULATE_WRITE (Linux 5.14+) prefaults in-kernel in one
// syscall instead of per-page user traps.
struct HugeBuf {
  char* p = nullptr;
  size_t n = 0;
  bool alloc(size_t want) {
    if (want == 0) want = 1;
    void* m = mmap(nullptr, want, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE, -1, 0);
    if (m == MAP_FAILED) return false;
    p = (char*)m;
    n = want;
#ifdef MADV_HUGEPAGE
    madvise(p, n, MADV_HUGEPAGE);
#endif
#ifdef MADV_POPULATE_WRITE
    madvise(p, n, MADV_POPULATE_WRITE);
#endif
    return true;
  }
  ~HugeBuf() {
    if (p) munmap(p, n);
  }
};

struct ReadResult {
  int32_t error = 0;           // 0 ok, 1 open/stat/read failure
  HugeBuf buf;
  std::vector<int64_t> starts;  // n+1: byte start of each file region
};

void* dar_read(const char* paths_blob, const int64_t* path_offs,
               int32_t n_files) {
  ReadResult* r = new ReadResult();
  // pass 1: stat for sizes (one syscall per file).
  std::vector<int64_t> sizes(n_files);
  int64_t total = 0;
  for (int32_t i = 0; i < n_files; i++) {
    std::string path(paths_blob + path_offs[i],
                     (size_t)(path_offs[i + 1] - path_offs[i]));
    struct stat st;
    if (stat(path.c_str(), &st) != 0) { r->error = 1; return r; }
    sizes[i] = st.st_size;
    total += st.st_size + 1;
  }
  if (!r->buf.alloc((size_t)total)) { r->error = 1; return r; }
  r->starts.resize(n_files + 1);
  char* out = r->buf.p;
  int64_t off = 0;
  for (int32_t i = 0; i < n_files; i++) { r->starts[i] = off; off += sizes[i] + 1; }
  r->starts[n_files] = off;
  // pass 2a: hand the kernel the FULL read plan up front —
  // POSIX_FADV_WILLNEED binds readahead to the inode and survives the
  // close, so a cold virtio disk streams upcoming files while pass 2b
  // copies earlier ones (measured 11.5s -> ~1.0s for a 687MB
  // 30k-commit log; a 512-file sliding window only reached 3.8s).
  // A copy thread pool was measured and REJECTED on this 1-vCPU box:
  // two copiers on one core regress the warm path.
#ifdef POSIX_FADV_WILLNEED
  // the pre-pass costs ~3 syscalls/file — skip it when a page-cache
  // residency sample says the data is already warm (mincore over ~16
  // evenly-spaced files)
  bool mostly_resident = false;
  {
    int32_t samples = n_files < 16 ? n_files : 16;
    int64_t resident = 0, probed = 0;
    for (int32_t s = 0; s < samples; s++) {
      int32_t i = (int32_t)((int64_t)s * n_files / samples);
      std::string path(paths_blob + path_offs[i],
                       (size_t)(path_offs[i + 1] - path_offs[i]));
      int fd = open(path.c_str(), O_RDONLY);
      if (fd < 0) continue;
      size_t len = (size_t)sizes[i];
      if (len > 0) {
        void* m = mmap(nullptr, len, PROT_READ, MAP_PRIVATE, fd, 0);
        if (m != MAP_FAILED) {
          size_t pages = (len + 4095) / 4096;
          std::vector<unsigned char> vec(pages);
          if (mincore(m, len, vec.data()) == 0) {
            for (unsigned char b : vec) resident += (b & 1);
            probed += (int64_t)pages;
          }
          munmap(m, len);
        }
      }
      close(fd);
    }
    mostly_resident = probed > 0 && resident * 10 >= probed * 9;
  }
  if (!mostly_resident) {
    for (int32_t i = 0; i < n_files; i++) {
      std::string path(paths_blob + path_offs[i],
                       (size_t)(path_offs[i + 1] - path_offs[i]));
      int fd = open(path.c_str(), O_RDONLY);
      if (fd >= 0) {
        posix_fadvise(fd, 0, 0, POSIX_FADV_WILLNEED);
        close(fd);
      }
    }
  }
#endif
  // pass 2b: sequential single-threaded copy.
  for (int32_t i = 0; i < n_files; i++) {
    std::string path(paths_blob + path_offs[i],
                     (size_t)(path_offs[i + 1] - path_offs[i]));
    int fd = open(path.c_str(), O_RDONLY);
    if (fd < 0) { r->error = 1; return r; }
    int64_t base = r->starts[i];
    int64_t got = 0;
    while (got < sizes[i]) {
      ssize_t k = pread(fd, out + base + got, (size_t)(sizes[i] - got), got);
      if (k <= 0) break;
      got += k;
    }
    close(fd);
    if (got != sizes[i]) { r->error = 1; return r; }
    out[base + sizes[i]] = '\n';
  }
  return r;
}

void dar_free(void* h) { delete (ReadResult*)h; }
int32_t dar_error(void* h) { return ((ReadResult*)h)->error; }
int64_t dar_len(void* h) { return (int64_t)((ReadResult*)h)->buf.n; }
const void* dar_buf(void* h) { return ((ReadResult*)h)->buf.p; }
const void* dar_starts(void* h) { return ((ReadResult*)h)->starts.data(); }

}  // extern "C"

// First-appearance delta encoder for the replay transfer path.
//
// Mirrors delta_tpu/ops/replay.py::_try_fa_encode exactly (the numpy
// implementation remains as the toolchain-less fallback and the parity
// oracle): given the primary dictionary-code lane `pk` (first-appearance
// coded by the columnarizer) and the optional small-range sub lane `dk`
// (deletion-vector id codes), produce
//   - is_new flag bits, packed little-endian into u32 words, padded to
//     `m` rows with zeros;
//   - the explicit codes of non-new rows (`refs`), emitted directly as
//     little-endian byte planes (planar, padded with 0);
//   - the sparse (row, value) pairs of the non-zero sub-lane entries.
//
// The stream is "first-appearance coded" iff the j-th row that
// introduces a previously-unseen code carries exactly code j.  Rows are
// classified with a running max (a row is new iff pk[i] == prev_max+1),
// then verified against the global new-row count.  Everything runs in
// three parallel passes over the input (classify+count, prefix-combine,
// emit+verify), so the encoder is memory-bound and scales with threads.
//
// Plain C ABI (no pybind11): an opaque handle exposes result buffers by
// index, exactly like action_scan.cpp.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct FaResult {
  int32_t error = 0;  // 0 ok; 1 = not first-appearance coded / fallback
  std::vector<uint32_t> flag_words;  // m/32
  std::vector<uint8_t> ref_planes;   // ref_width contiguous planes of r_pad
  int64_t n_refs = 0;
  int64_t r_pad = 0;
  int32_t ref_width = 0;
  std::vector<uint32_t> sub_idx;  // d_pad (pad = 0xFFFFFFFF)
  std::vector<uint32_t> sub_val;  // d_pad (pad = 0)
  int64_t n_sub = 0;
  int64_t d_pad = 0;
  int64_t sub_radix = 1;
  int64_t primary_max = -1;  // max primary code seen (-1 when n == 0)
};

int64_t pad_bucket(int64_t n, int64_t min_bucket) {
  // must match ops/replay.py::pad_bucket: pow2 up to 1M, then the next
  // multiple of 512k
  if (n <= min_bucket) return min_bucket;
  if (n <= (1ll << 20)) {
    int64_t b = min_bucket;
    while (b < n) b <<= 1;
    return b;
  }
  const int64_t step = 1ll << 19;
  return ((n + step - 1) / step) * step;
}

int32_t byte_width(uint64_t max_value) {
  // matches replay.py::key_byte_width — the all-ones sentinel of the
  // chosen width must stay free
  for (int32_t w = 1; w <= 3; ++w)
    if (max_value < ((1ull << (8 * w)) - 1)) return w;
  return 4;
}

struct ChunkStat {
  int64_t n_new = 0;
  int64_t n_ref = 0;
  int64_t n_sub = 0;
  uint64_t max_pk = 0;   // max over chunk (0 when empty)
  bool has_pk = false;
  uint64_t max_ref = 0;
  uint64_t max_sub = 0;
};

}  // namespace

extern "C" {

void* fae_encode(const uint32_t* pk, const uint32_t* dk, int64_t n,
                 int64_t m, int32_t n_threads) {
  auto* res = new FaResult();
  if (n == 0) {
    res->flag_words.assign(m / 32, 0);
    res->r_pad = pad_bucket(0, 128);
    res->ref_width = 1;
    res->ref_planes.assign(res->r_pad, 0);
    return res;
  }
  if (n_threads <= 0) n_threads = 1;
  int64_t t_count = std::min<int64_t>(n_threads, (n + 65535) / 65536);
  if (t_count < 1) t_count = 1;
  // chunk bounds on 64-row boundaries so flag-word packing never races
  int64_t chunk = ((n + t_count - 1) / t_count + 63) & ~int64_t(63);
  std::vector<ChunkStat> stats(t_count);

  // ---- pass 1: classify per chunk with a local running max ------------
  // A row is new iff pk[i] == prev_max + 1 where prev_max is the running
  // max over ALL prior rows.  The cross-chunk prefix max isn't known in
  // pass 1, so classify with the LOCAL running max seeded by a sentinel,
  // and re-classify in pass 2 only the prefix of each chunk that the
  // incoming prefix max can affect (rows before the chunk's local max
  // first exceeds the incoming max are the only ones whose prev_max
  // differs).  Simpler and still fast: pass 1 only computes chunk maxima,
  // pass 2 does classify+count with exact prefix maxima, pass 3 emits.
  {
    std::vector<std::thread> ts;
    for (int64_t t = 0; t < t_count; ++t) {
      ts.emplace_back([&, t]() {
        int64_t s = t * chunk, e = std::min(n, s + chunk);
        uint64_t mx = 0;
        bool has = false;
        for (int64_t i = s; i < e; ++i) {
          if (!has || pk[i] > mx) mx = pk[i];
          has = true;
        }
        stats[t].max_pk = mx;
        stats[t].has_pk = has;
      });
    }
    for (auto& th : ts) th.join();
  }
  std::vector<int64_t> prefix_max(t_count);  // exclusive; -1 = none
  {
    int64_t run = -1;
    for (int64_t t = 0; t < t_count; ++t) {
      prefix_max[t] = run;
      if (stats[t].has_pk)
        run = std::max(run, (int64_t)stats[t].max_pk);
    }
    res->primary_max = run;
  }

  // ---- pass 2: exact classify + count ---------------------------------
  res->flag_words.assign(m / 32, 0);
  {
    std::vector<std::thread> ts;
    for (int64_t t = 0; t < t_count; ++t) {
      ts.emplace_back([&, t]() {
        int64_t s = t * chunk, e = std::min(n, s + chunk);
        int64_t prev_max = prefix_max[t];
        ChunkStat& st = stats[t];
        uint32_t* words = res->flag_words.data();
        for (int64_t i = s; i < e; ++i) {
          int64_t v = (int64_t)pk[i];
          if (v == prev_max + 1) {
            words[i >> 5] |= (1u << (i & 31));
            st.n_new++;
          } else {
            st.n_ref++;
            if ((uint64_t)v > st.max_ref) st.max_ref = (uint64_t)v;
          }
          if (v > prev_max) prev_max = v;
          if (dk) {
            uint32_t d = dk[i];
            if (d) {
              st.n_sub++;
              if (d > st.max_sub) st.max_sub = d;
            }
          }
        }
      });
    }
    for (auto& th : ts) th.join();
  }

  std::vector<int64_t> new_base(t_count), ref_base(t_count), sub_base(t_count);
  uint64_t max_ref = 0, max_sub = 0;
  {
    int64_t nn = 0, nr = 0, ns = 0;
    for (int64_t t = 0; t < t_count; ++t) {
      new_base[t] = nn;
      ref_base[t] = nr;
      sub_base[t] = ns;
      nn += stats[t].n_new;
      nr += stats[t].n_ref;
      ns += stats[t].n_sub;
      max_ref = std::max(max_ref, stats[t].max_ref);
      max_sub = std::max(max_sub, stats[t].max_sub);
    }
    res->n_refs = nr;
    res->n_sub = ns;
    res->sub_radix = dk ? (int64_t)max_sub + 1 : 1;
  }
  // range check: combined key must stay below the u32 pad sentinel
  if ((res->primary_max + 1) * res->sub_radix >= 0xFFFFFFFFll) {
    res->error = 1;
    return res;
  }

  // ---- pass 3: emit refs/sub + verify dense first-appearance ----------
  res->r_pad = pad_bucket(res->n_refs, 128);
  res->ref_width = byte_width(max_ref);
  res->ref_planes.assign((int64_t)res->ref_width * res->r_pad, 0);
  if (res->sub_radix > 1) {
    res->d_pad = pad_bucket(res->n_sub, 128);
    res->sub_idx.assign(res->d_pad, 0xFFFFFFFFu);
    res->sub_val.assign(res->d_pad, 0);
  }
  std::atomic<bool> not_fa{false};
  {
    std::vector<std::thread> ts;
    for (int64_t t = 0; t < t_count; ++t) {
      ts.emplace_back([&, t]() {
        int64_t s = t * chunk, e = std::min(n, s + chunk);
        int64_t new_rank = new_base[t], ref_at = ref_base[t];
        int64_t sub_at = sub_base[t];
        const uint32_t* words = res->flag_words.data();
        uint8_t* planes = res->ref_planes.data();
        int32_t w = res->ref_width;
        int64_t rp = res->r_pad;
        for (int64_t i = s; i < e; ++i) {
          if ((words[i >> 5] >> (i & 31)) & 1u) {
            // dense check: the j-th new row must carry code j
            if ((int64_t)pk[i] != new_rank) {
              not_fa.store(true, std::memory_order_relaxed);
              return;
            }
            new_rank++;
          } else {
            uint32_t v = pk[i];
            for (int32_t j = 0; j < w; ++j)
              planes[(int64_t)j * rp + ref_at] = (uint8_t)(v >> (8 * j));
            ref_at++;
          }
          if (dk && res->sub_radix > 1 && dk[i]) {
            res->sub_idx[sub_at] = (uint32_t)i;
            res->sub_val[sub_at] = dk[i];
            sub_at++;
          }
        }
      });
    }
    for (auto& th : ts) th.join();
  }
  if (not_fa.load()) res->error = 1;
  return res;
}

void fae_free(void* h) { delete static_cast<FaResult*>(h); }

int32_t fae_error(void* h) { return static_cast<FaResult*>(h)->error; }

int64_t fae_n(void* h, int32_t which) {
  auto* r = static_cast<FaResult*>(h);
  switch (which) {
    case 0: return (int64_t)r->flag_words.size();
    case 1: return r->n_refs;
    case 2: return r->r_pad;
    case 3: return r->ref_width;
    case 4: return r->n_sub;
    case 5: return r->d_pad;
    case 6: return r->sub_radix;
    case 7: return r->primary_max;
    default: return -1;
  }
}

const void* fae_ptr(void* h, int32_t which) {
  auto* r = static_cast<FaResult*>(h);
  switch (which) {
    case 0: return r->flag_words.data();
    case 1: return r->ref_planes.data();  // ref_width planes of r_pad bytes
    case 2: return r->sub_idx.data();
    case 3: return r->sub_val.data();
    default: return nullptr;
  }
}

}  // extern "C"

"""Streaming schema-tracking log.

Reference `DeltaSourceMetadataTrackingLog.scala` +
`DeltaSourceMetadataEvolutionSupport.scala`: a stream that must survive
schema evolution persists each observed table-metadata change into its
own little log next to the streaming checkpoint
(`<checkpoint>/_schema_log_<tableId>/%020d.json`, put-if-absent writes).
When the source hits a commit whose metaData changes the read schema, it
appends the new entry and stops the stream; the restarted stream reads
the latest entry and uses it as the authoritative read schema for
batches that follow.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

from delta_tpu.errors import DeltaError


class SchemaEvolutionRequiresRestart(DeltaError):
    """The source persisted a new schema; restart the stream to adopt it."""

    error_class = "DELTA_STREAMING_METADATA_EVOLUTION"


@dataclass
class PersistedMetadata:
    """One schema-log entry: the table schema as of a commit version."""

    delta_commit_version: int
    schema_string: str
    partition_columns: list
    configuration: dict
    seq_num: int = 0
    table_id: str = None

    def to_json(self) -> str:
        return json.dumps(
            {
                "deltaCommitVersion": self.delta_commit_version,
                "schemaString": self.schema_string,
                "partitionColumns": self.partition_columns,
                "configuration": self.configuration,
                "tableId": self.table_id,
            },
            sort_keys=True,
        )

    @staticmethod
    def from_json(s: str, seq_num: int) -> "PersistedMetadata":
        try:
            d = json.loads(s)
            return PersistedMetadata(
                delta_commit_version=d["deltaCommitVersion"],
                schema_string=d["schemaString"],
                partition_columns=d.get("partitionColumns", []),
                configuration=d.get("configuration", {}),
                seq_num=seq_num,
                table_id=d.get("tableId"),
            )
        except (ValueError, TypeError, KeyError) as e:
            from delta_tpu.errors import StreamingSourceError

            # `DeltaErrors.failToDeserializeSchemaLog`
            raise StreamingSourceError(
                f"incomplete/corrupt schema log entry {seq_num} ({e}); "
                "pick a new schemaTrackingLocation to restart",
                error_class="DELTA_STREAMING_SCHEMA_LOG_DESERIALIZE_FAILED")


class SchemaTrackingLog:
    """Sequential JSON entries under
    `<location>/_schema_log_<table_id>/`, written with the LogStore
    put-if-absent primitive (concurrent streams race safely)."""

    def __init__(self, engine, location: str, table_id: str):
        self._engine = engine
        self._table_id = table_id
        self._dir = f"{location.rstrip('/')}/_schema_log_{table_id}"

    def _entry_path(self, seq: int) -> str:
        return f"{self._dir}/{seq:020d}.json"

    def entries(self) -> list:
        fs = self._engine.fs
        out = []
        try:
            # listFrom contract: list the parent dir from a child path
            listing = sorted(fs.list_from(self._entry_path(0)),
                             key=lambda f: f.path)
        except FileNotFoundError:
            return out
        for st in listing:
            name = st.path.rsplit("/", 1)[-1]
            if not name.endswith(".json"):
                continue
            try:
                seq = int(name[:-5])
            except ValueError:
                continue
            entry = PersistedMetadata.from_json(
                fs.read_file(st.path).decode("utf-8"), seq)
            if entry.table_id is not None and \
                    entry.table_id != self._table_id:
                from delta_tpu.errors import StreamingSourceError

                # `DeltaErrors.incompatibleSchemaLogDeltaTable`: a
                # schema log reused across tables would replay the
                # wrong schema history
                raise StreamingSourceError(
                    f"schema log entry {seq} was persisted for table "
                    f"id {entry.table_id!r}, expected "
                    f"{self._table_id!r}",
                    error_class=(
                        "DELTA_STREAMING_SCHEMA_LOG_INCOMPATIBLE_DELTA_TABLE_ID"))
            out.append(entry)
        return out

    def latest(self) -> Optional[PersistedMetadata]:
        entries = self.entries()
        return entries[-1] if entries else None

    def append(self, entry: PersistedMetadata) -> PersistedMetadata:
        """Write the next sequential entry (put-if-absent; loser of a
        race re-reads and returns the winner when identical)."""
        from delta_tpu.storage.logstore import logstore_for_path

        cur = self.latest()
        seq = 0 if cur is None else cur.seq_num + 1
        entry.seq_num = seq
        if entry.table_id is None:
            entry.table_id = self._table_id
        if cur is not None and \
                list(cur.partition_columns) != list(entry.partition_columns):
            from delta_tpu.errors import StreamingSourceError

            # `DeltaErrors.incompatibleSchemaLogPartitionSchema`:
            # a partitioning change invalidates every outstanding
            # offset's file-index interpretation
            raise StreamingSourceError(
                f"incompatible partition schema change in stream: "
                f"{cur.partition_columns} -> {entry.partition_columns}",
                error_class=(
                    "DELTA_STREAMING_SCHEMA_LOG_INCOMPATIBLE_PARTITION_SCHEMA"))
        path = self._entry_path(seq)
        store = logstore_for_path(path)
        store.mkdirs(self._dir)
        try:
            store.write(path, entry.to_json().encode("utf-8"), overwrite=False)
        except FileExistsError:
            winner = PersistedMetadata.from_json(
                self._engine.fs.read_file(path).decode("utf-8"), seq)
            if winner.schema_string != entry.schema_string:
                raise
            return winner
        return entry

"""Structured-streaming sink: idempotent micro-batch appends.

Reference `sources/DeltaSink.scala:48`: each micro-batch commits with
`SetTransaction(appId=query_id, version=batch_id)`; a replayed batch whose
id is <= the recorded watermark is skipped — exactly-once without
coordination.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import pyarrow as pa

from delta_tpu.errors import DeltaError, StreamingSourceError
from delta_tpu.models.schema import from_arrow_schema
from delta_tpu.table import Table
from delta_tpu.txn.transaction import Operation
from delta_tpu.write.writer import write_data_files


class DeltaSink:
    def __init__(
        self,
        table_path: str,
        query_id: str,
        engine=None,
        partition_by: Optional[Sequence[str]] = None,
        output_mode: str = "append",
    ):
        self.table = Table.for_path(table_path, engine)
        self.query_id = query_id
        self.partition_by = list(partition_by or [])
        if output_mode not in ("append", "complete"):
            raise StreamingSourceError(f"unsupported output mode {output_mode}",
                                       error_class="DELTA_MODE_NOT_SUPPORTED")
        self.output_mode = output_mode

    def add_batch(self, batch_id: int, data: pa.Table) -> Optional[int]:
        """Commit one micro-batch; returns the commit version, or None if
        this batch id was already committed (replay after restart).

        A `ConcurrentTransactionError` means the idempotency watermark
        for this query advanced underneath us — typically because the
        snapshot the dedup check ran against was stale (an eventually-
        consistent listing lagging our own previous commit). The safe
        response is the same as a query restart: re-read fresh state
        and re-run the watermark check, which either skips the batch
        (already committed) or commits it against current state.
        """
        from delta_tpu.errors import ConcurrentTransactionError

        stale_checks = 0
        while True:
            try:
                return self._commit_batch(batch_id, data)
            except ConcurrentTransactionError:
                stale_checks += 1
                if stale_checks > 3:
                    raise

    def _commit_batch(self, batch_id: int, data: pa.Table) -> Optional[int]:
        exists = self.table.exists()
        builder = self.table.create_transaction_builder(Operation.STREAMING_UPDATE)
        if not exists:
            builder = builder.with_schema(from_arrow_schema(data.schema))
            if self.partition_by:
                builder = builder.with_partition_columns(self.partition_by)
        txn = builder.build()

        last = txn.txn_version(self.query_id)
        if last is not None and batch_id <= last:
            return None  # already applied — exactly-once replay protection
        txn.set_transaction_id(self.query_id, batch_id,
                               last_updated=int(time.time() * 1000))

        meta = txn.metadata()
        if self.output_mode == "complete":
            for f in txn.scan_files():
                txn.remove_file(f.remove(deletion_timestamp=int(time.time() * 1000)))
        adds = write_data_files(
            engine=self.table.engine,
            table_path=self.table.path,
            data=data,
            schema=meta.schema,
            partition_columns=meta.partitionColumns,
            configuration=meta.configuration,
        )
        txn.add_files(adds)
        txn.set_operation_parameters(
            {"outputMode": self.output_mode, "queryId": self.query_id,
             "epochId": batch_id}
        )
        return txn.commit().version

from delta_tpu.streaming.source import DeltaSource, DeltaSourceOffset, ReadLimits
from delta_tpu.streaming.sink import DeltaSink

__all__ = ["DeltaSource", "DeltaSourceOffset", "ReadLimits", "DeltaSink"]

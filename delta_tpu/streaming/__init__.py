from delta_tpu.streaming.source import (
    DeltaCDCSource,
    DeltaSource,
    DeltaSourceOffset,
    ReadLimits,
)
from delta_tpu.streaming.sink import DeltaSink

__all__ = ["DeltaCDCSource", "DeltaSource", "DeltaSourceOffset",
           "ReadLimits", "DeltaSink"]

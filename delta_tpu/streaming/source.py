"""Structured-streaming source: incremental micro-batch reads of a table.

Reference `sources/DeltaSource.scala:721` + `DeltaSourceOffset.scala:55`:
an offset is `(reservoir_version, index, is_initial_snapshot)` — the
initial snapshot is served as an indexed enumeration of the start
snapshot's files, after which the source tails commit files version by
version, admitting files up to the rate limits (`AdmissionLimits:1309`,
maxFilesPerTrigger / maxBytesPerTrigger).

Data-changing removes in tailed commits are an error unless
`ignore_changes` (re-emit rewritten files) or `ignore_deletes` is set —
same contract as the reference.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import ClassVar, Iterator, List, Optional

import pyarrow as pa

from delta_tpu import obs
from delta_tpu.errors import DeltaError, StreamingSchemaChangeError, StreamingSourceError
from delta_tpu.models.actions import (
    AddFile,
    CommitInfo,
    Metadata,
    Protocol,
    RemoveFile,
    actions_from_commit_bytes,
)
from delta_tpu.utils import filenames

_log = logging.getLogger(__name__)

BASE_INDEX = -1  # offset index meaning "before any file of this version"
END_INDEX = -2   # (reference END_INDEX analog: version fully consumed)


@dataclass(frozen=True, order=True)
class DeltaSourceOffset:
    reservoir_version: int
    index: int
    is_initial_snapshot: bool = False
    # provenance fields (`DeltaSourceOffset.scala:43-59`): the offset
    # format version and the table id the offset was produced against.
    # Excluded from equality so positional offsets still compare.
    source_version: int = field(default=1, compare=False)
    reservoir_id: Optional[str] = field(default=None, compare=False)

    VERSION: ClassVar[int] = 1

    def to_json(self) -> str:
        return json.dumps(
            {
                "sourceVersion": self.source_version,
                "reservoirId": self.reservoir_id,
                "reservoirVersion": self.reservoir_version,
                "index": self.index,
                "isStartingVersion": self.is_initial_snapshot,
            }
        )

    @staticmethod
    def from_json(s: str) -> "DeltaSourceOffset":
        from delta_tpu.errors import StreamingSourceError

        try:
            d = json.loads(s)
            version = int(d["reservoirVersion"])
            index = int(d["index"])
            sv = int(d.get("sourceVersion", DeltaSourceOffset.VERSION))
        except (ValueError, TypeError, KeyError) as e:
            # `DeltaErrors.invalidSourceOffsetFormat`
            raise StreamingSourceError(
                f"invalid Delta source offset: {s!r} ({e})",
                error_class="DELTA_INVALID_SOURCE_OFFSET_FORMAT")
        if not 1 <= sv <= DeltaSourceOffset.VERSION:
            # `DeltaSourceOffset.validateSourceVersion` ->
            # `DeltaErrors.invalidSourceVersion`
            raise StreamingSourceError(
                f"sourceVersion({sv}) is invalid",
                error_class="DELTA_INVALID_SOURCE_VERSION")
        return DeltaSourceOffset(
            version, index, bool(d.get("isStartingVersion", False)),
            source_version=sv, reservoir_id=d.get("reservoirId"),
        )


@dataclass
class ReadLimits:
    max_files: Optional[int] = 1000
    max_bytes: Optional[int] = None


@dataclass
class IndexedFile:
    version: int
    index: int
    add: AddFile
    is_initial: bool


class _SchemaChanged(Exception):
    """Internal: a Metadata action with a different schema was seen while
    scanning commit `version` for admission."""

    def __init__(self, version: int):
        super().__init__(version)
        self.version = version


class _ExpiryGuard:
    """Shared by DeltaSource and DeltaCDCSource: when an admission walk
    makes no progress because commit `v`'s file is missing, distinguish
    'not committed yet' (caught up — fine) from 'expired by log cleanup'
    (fatal — stalling silently would report caught-up forever while
    newer versions hold undelivered data).

    The expensive LIST verdict is cached per version, so steady-state
    idle polls cost one failed read plus one `_last_checkpoint` probe
    (cleanup requires a checkpoint at >= v, so a hint behind v proves a
    cached 'pending' verdict still holds); a commit that lands between
    the probe and the LIST is re-probed rather than misreported."""

    def __init__(self, table, what: str):
        self.table = table
        self._what = what
        self._verified_pending: Optional[int] = None

    def _exists(self, v: int) -> bool:
        """Side-effect-free existence probe — no action parsing, so a
        schema change or ignorable-delete in the commit can't raise from
        inside an expiry check (those surface through the admission walk
        on the next poll)."""
        from delta_tpu.utils import filenames as fn

        try:
            self.table.engine.fs.file_status(
                fn.delta_file(self.table.log_path, v))
            return True
        except OSError:
            return False  # missing/unreadable: treat as expired

    def check(self, v: int) -> None:
        from delta_tpu.log.last_checkpoint import read_last_checkpoint

        if self._verified_pending == v:
            try:
                hint = read_last_checkpoint(self.table.engine.fs,
                                            self.table.log_path)
            except OSError:
                hint = None  # parse errors return None inside already
            if hint is None or hint.version < v:
                return
            self._verified_pending = None  # re-verify below
        # duck-typed: incremental poll when the table supports it
        poll = getattr(self.table, "update", None) or self.table.latest_snapshot
        try:
            segment = poll().log_segment
        except Exception as e:
            # can't list — treat as caught up, retry next poll
            _log.debug("expiry-guard poll failed (%s); retrying next "
                       "trigger", e)
            return
        if segment.version < v:
            self._verified_pending = v
            return
        # the snapshot knows version v. Re-probe before declaring it
        # expired: a writer may have committed v after our first read.
        if self._exists(v):
            return  # it exists now; the next poll admits it
        # still unreadable: unbackfilled coordinated commits appear in
        # the segment under _delta_log/_commits/ — wait for backfill
        # rather than erroring. Only _commits/ paths count: a backfilled
        # name in a stale cached listing proves nothing about the file
        # still existing.
        from delta_tpu.utils import filenames as fn

        delta_versions = set()
        for fstat in segment.deltas:
            try:
                dv = fn.delta_version(fstat.path)
            except ValueError:
                continue
            if dv == v and f"/{fn.COMMIT_SUBDIR}/" in fstat.path:
                return  # unbackfilled coordinated commit: wait
            delta_versions.add(dv)
        ckpt_v = getattr(segment, "checkpoint_version", None)
        hole_certain = True
        try:
            # a cached snapshot may predate the covering checkpoint:
            # the _last_checkpoint hint is the authoritative floor
            hint = read_last_checkpoint(self.table.engine.fs,
                                        self.table.log_path)
            if hint is not None:
                ckpt_v = max(ckpt_v if ckpt_v is not None else -1,
                             hint.version)
        except OSError:
            # can't read the hint: a covering checkpoint may exist, so
            # do not escalate to the non-retryable corruption verdict
            hole_certain = False
        if hole_certain and (ckpt_v is None or v > ckpt_v) \
                and delta_versions \
                and min(delta_versions) < v < max(delta_versions):
            # a MID-RANGE hole past any checkpoint (commits exist on
            # both sides of v and no checkpoint covers it) is not
            # expiry — the log itself is broken
            # (`DeltaErrors.deltaVersionsNotContiguousException`)
            raise StreamingSourceError(
                error_class="DELTA_VERSIONS_NOT_CONTIGUOUS",
                message=f"versions ({sorted(delta_versions)[:5]}...) "
                f"are not contiguous: commit {v} is missing between "
                "existing commits")
        raise StreamingSourceError(
            error_class="DELTA_LOG_FILE_NOT_FOUND_FOR_STREAMING_SOURCE",
            message=f"commit {v} required by this {self._what} no longer exists "
            "(expired by log cleanup); restart the stream from a fresh "
            "snapshot")


def _drain_micro_batches(
    source, limits: Optional[ReadLimits], start: Optional[DeltaSourceOffset]
) -> Iterator[tuple[DeltaSourceOffset, pa.Table]]:
    """Shared drain loop: yield (offset, batch) until the source reports
    no progress."""
    cur = start
    while True:
        nxt = source.latest_offset(cur, limits)
        if nxt == cur or nxt is None:
            return
        yield nxt, source.get_batch(cur, nxt)
        cur = nxt


class DeltaSource:
    def __init__(
        self,
        table,
        starting_version: Optional[int] = None,
        ignore_deletes: bool = False,
        ignore_changes: bool = False,
        schema_tracking_log=None,
        starting_timestamp: Optional[int] = None,
    ):
        self.table = table
        self.ignore_deletes = ignore_deletes
        self.ignore_changes = ignore_changes
        if starting_version is not None and starting_timestamp is not None:
            from delta_tpu.errors import InvalidArgumentError

            # `DeltaErrors.startingVersionAndTimestampBothSetException`
            raise InvalidArgumentError(
                "please either provide 'startingVersion' or "
                "'startingTimestamp'",
                error_class="DELTA_STARTING_VERSION_AND_TIMESTAMP_BOTH_SET")
        if starting_timestamp is not None:
            starting_version = self._version_from_timestamp(
                table, starting_timestamp)
        if starting_version is not None and starting_version < 0:
            from delta_tpu.errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"invalid starting version {starting_version}: must be >= 0",
                error_class="DELTA_TIME_TRAVEL_INVALID_BEGIN_VALUE")
        self._starting_version = starting_version
        self._initial_files: Optional[List[AddFile]] = None
        self._initial_version: Optional[int] = None
        self._expiry_guard = _ExpiryGuard(table, "stream")
        # schema evolution across the stream's lifetime
        # (DeltaSourceMetadataTrackingLog semantics): None = fail on any
        # read-incompatible metadata change mid-stream
        self.schema_log = schema_tracking_log
        self._tracked_schema: Optional[str] = None
        if schema_tracking_log is not None:
            latest = schema_tracking_log.latest()
            if latest is not None:
                self._tracked_schema = latest.schema_string

    @staticmethod
    def _version_from_timestamp(table, ts_ms: int) -> int:
        """startingTimestamp -> version: the earliest commit at/after
        the timestamp (`DeltaSource.getStartingVersion`); a timestamp
        after the latest commit is an error."""
        from delta_tpu.history import version_at_or_after_timestamp

        return version_at_or_after_timestamp(table, ts_ms)

    @classmethod
    def from_options(cls, table, options: dict):
        """Build a source + ReadLimits from string options — the
        reference's `DeltaOptions` parsing surface with its validation
        classes. Returns (source, limits)."""
        from delta_tpu.errors import InvalidArgumentError

        opts = {k.lower(): v for k, v in options.items()}

        def boolean(name, default=False):
            v = opts.get(name.lower())
            if v is None:
                return default
            if str(v).lower() in ("true", "false"):
                return str(v).lower() == "true"
            # `DeltaErrors.illegalDeltaOptionException`
            raise InvalidArgumentError(
                f"Invalid value '{v}' for option '{name}', must be "
                "'true' or 'false'", error_class="DELTA_ILLEGAL_OPTION")

        def limit(name):
            v = opts.get(name.lower())
            if v is None:
                return None
            try:
                n = int(v)
                if n <= 0:
                    raise ValueError
            except ValueError:
                # `DeltaErrors.unknownReadLimit`
                raise InvalidArgumentError(
                    f"Invalid value '{v}' for option '{name}': "
                    "expected a positive integer",
                    error_class="DELTA_UNKNOWN_READ_LIMIT")
            return n

        sv = opts.get("startingversion")
        if sv is not None:
            if str(sv).lower() == "latest":
                sv = table.update().version + 1
            else:
                try:
                    sv = int(sv)
                except ValueError:
                    # `DeltaErrors.invalidSourceVersion` option form
                    raise InvalidArgumentError(
                        f"Invalid value '{sv}' for option "
                        "'startingVersion': expected an integer or "
                        "'latest'",
                        error_class="DELTA_INVALID_SOURCE_VERSION")
        ts = opts.get("startingtimestamp")
        if ts is not None:
            from delta_tpu.sql import _timestamp_ms

            ts = _timestamp_ms(str(ts) if str(ts).isdigit()
                               else f"'{ts}'")
        src = cls(
            table,
            starting_version=sv,
            starting_timestamp=ts,
            ignore_deletes=boolean("ignoreDeletes"),
            ignore_changes=boolean("ignoreChanges"),
        )
        limits = ReadLimits()
        mf = limit("maxFilesPerTrigger")
        if mf is not None:
            limits.max_files = mf
        mb = limit("maxBytesPerTrigger")
        if mb is not None:
            limits.max_bytes = mb
        return src, limits

    # -- initial snapshot ---------------------------------------------------

    def _ensure_initial(self) -> None:
        if self._initial_version is not None:
            return
        snap = self.table.update()
        if self._tracked_schema is None:
            # the schema this stream was started against — the baseline
            # for mid-stream metadata-change detection. With a
            # starting_version the baseline is the schema AS OF that
            # version (replayed metaData actions before the change must
            # not trip the detector).
            baseline = snap
            if self._starting_version is not None:
                try:
                    baseline = self.table.snapshot_at(self._starting_version)
                except Exception as e:
                    # version expired: best effort
                    _log.debug("baseline snapshot_at(%d) failed (%s); "
                               "using start snapshot schema",
                               self._starting_version, e)
                    baseline = snap
            self._tracked_schema = baseline.metadata.schemaString
        if self._starting_version is not None:
            # start tailing from a version: no initial snapshot
            self._initial_version = self._starting_version - 1
            self._initial_files = []
            return
        files = snap.state.add_files()
        files.sort(key=lambda f: (f.modificationTime, f.path))
        self._initial_files = files
        self._initial_version = snap.version

    # -- change enumeration -------------------------------------------------

    def _files_from_version(self, version: int) -> Optional[List[AddFile]]:
        """File adds of one commit; None when the commit doesn't exist yet."""
        path = filenames.delta_file(self.table.log_path, version)
        try:
            data = self.table.engine.fs.read_file(path)
        except FileNotFoundError:
            return None
        adds = []
        for a in actions_from_commit_bytes(data):
            if isinstance(a, AddFile) and a.dataChange:
                adds.append(a)
            elif isinstance(a, RemoveFile) and a.dataChange:
                if not (self.ignore_deletes or self.ignore_changes):
                    raise StreamingSourceError(
                        error_class="DELTA_SOURCE_IGNORE_DELETE",
                        message=f"streaming source found a data-changing remove in "
                        f"version {version}; set ignore_deletes/ignore_changes "
                        "or use the CDC reader"
                    )
            elif isinstance(a, Metadata):
                self._on_metadata_action(a, version)
        return adds

    def _on_metadata_action(self, meta: Metadata, version: int) -> None:
        """Mid-stream metaData action: adopt silently if it matches the
        tracked schema; persist + stop otherwise (reference
        `DeltaSourceMetadataEvolutionSupport`)."""
        baseline = self._tracked_schema
        if baseline is None or meta.schemaString == baseline:
            return
        if self.schema_log is None:
            from delta_tpu.errors import DeltaError, StreamingSchemaChangeError, StreamingSourceError

            raise StreamingSchemaChangeError(
                error_class="DELTA_SCHEMA_CHANGED_WITH_VERSION",
                message=f"table schema changed at version {version}; restart the "
                "stream (attach a SchemaTrackingLog to evolve automatically)"
            )
        from delta_tpu.streaming.schema_log import (
            PersistedMetadata,
            SchemaEvolutionRequiresRestart,
        )

        self.schema_log.append(
            PersistedMetadata(
                delta_commit_version=version,
                schema_string=meta.schemaString,
                partition_columns=list(meta.partitionColumns or []),
                configuration=dict(meta.configuration or {}),
            )
        )
        raise SchemaEvolutionRequiresRestart(
            error_class="DELTA_STREAMING_METADATA_EVOLUTION",
            message=f"schema change at version {version} persisted to the schema "
            "log; restart the stream to continue with the new schema"
        )

    def read_schema(self):
        """The schema batches are read with: the tracked schema when a
        schema log has entries, else the table's current schema."""
        from delta_tpu.models.schema import schema_from_json

        if self._tracked_schema is not None:
            return schema_from_json(self._tracked_schema)
        return self.table.update().metadata.schema

    def _indexed_after(
        self, start: Optional[DeltaSourceOffset], limits: ReadLimits
    ) -> List[IndexedFile]:
        """Files strictly after `start`, up to the limits."""
        self._ensure_initial()
        out: List[IndexedFile] = []
        budget_files = limits.max_files if limits.max_files is not None else float("inf")
        budget_bytes = limits.max_bytes if limits.max_bytes is not None else float("inf")

        def admit(f: IndexedFile) -> bool:
            nonlocal budget_files, budget_bytes
            if budget_files < 1:
                return False
            if out and budget_bytes < f.add.size:
                return False
            budget_files -= 1
            budget_bytes -= f.add.size
            out.append(f)
            return True

        if start is None or start.is_initial_snapshot:
            begin_idx = -1 if start is None else start.index
            if self._starting_version is None:
                for i, add in enumerate(self._initial_files):
                    if i <= begin_idx:
                        continue
                    if not admit(
                        IndexedFile(self._initial_version, i, add, True)
                    ):
                        return out
            v = self._initial_version + 1
        else:
            v = start.reservoir_version
        # tail commits
        start_idx = (
            start.index
            if start is not None and not start.is_initial_snapshot
            else -1
        )
        while True:
            adds = self._files_from_version(v)
            if adds is None:
                # distinguish "not committed yet" from "expired by log
                # cleanup" — a silent stall would report caught-up
                # forever (the CDC source shares this guard). Only when
                # the walk made NO progress: admitted files already
                # prove the stream isn't stalled, and the check costs a
                # LIST.
                if not out:
                    self._expiry_guard.check(v)
                break
            for i, add in enumerate(adds):
                if v == (start.reservoir_version if start and not start.is_initial_snapshot else -1) and i <= start_idx:
                    continue
                if not admit(IndexedFile(v, i, add, False)):
                    return out
            v += 1
        return out

    # -- public micro-batch API --------------------------------------------

    def _table_id(self) -> str:
        """The table's immutable id, fetched once (offset stamping and
        validation sit on the per-poll hot path — no extra snapshot
        builds there)."""
        if getattr(self, "_cached_table_id", None) is None:
            self._cached_table_id = \
                self.table.update().metadata.id
        return self._cached_table_id

    def _check_offset_table(self, *offsets) -> None:
        """An offset produced against a different table id must not be
        applied here (`DeltaSource.scala` checkReadIncompatibleSchema
        path -> `DeltaErrors.differentDeltaTableReadByStreamingSource`):
        a checkpoint dir reused for another table would silently replay
        the wrong history."""
        for o in offsets:
            if o is not None and o.reservoir_id is not None \
                    and o.reservoir_id != self._table_id():
                raise StreamingSourceError(
                    f"the streaming query was reading from an "
                    f"unexpected Delta table (id = {o.reservoir_id!r}, "
                    f"expected {self._table_id()!r})",
                    error_class=(
                        "DIFFERENT_DELTA_TABLE_READ_BY_STREAMING_SOURCE"))

    def latest_offset(
        self, start: Optional[DeltaSourceOffset] = None,
        limits: Optional[ReadLimits] = None,
    ) -> Optional[DeltaSourceOffset]:
        with obs.span("stream.latest_offset", table=self.table.path) as sp:
            self._check_offset_table(start)
            files = self._indexed_after(start, limits or ReadLimits())
            sp.set_attr("new_files", len(files))
            if not files:
                return start
            last = files[-1]
            sp.set_attrs(to_version=last.version, to_index=last.index)
            return DeltaSourceOffset(
                last.version, last.index, last.is_initial,
                reservoir_id=self._table_id())

    def get_batch(
        self,
        start: Optional[DeltaSourceOffset],
        end: DeltaSourceOffset,
    ) -> pa.Table:
        """All rows in files after `start` up to and including `end`."""
        with obs.span("stream.get_batch", table=self.table.path,
                      end_version=end.reservoir_version,
                      end_index=end.index) as sp:
            self._check_offset_table(start, end)
            files = self._indexed_after(
                start, ReadLimits(max_files=None, max_bytes=None))
            # Initial-snapshot files share the start snapshot's version and
            # the tail begins at version+1, so (version, index) totally
            # orders the stream.
            end_key = (end.reservoir_version, end.index)
            selected = [
                f.add for f in files if (f.version, f.index) <= end_key]
            batch = self._read_adds(selected)
            sp.set_attrs(files_read=len(selected), rows=batch.num_rows)
            return batch

    def _read_adds(self, adds: List[AddFile]) -> pa.Table:
        from delta_tpu.read.reader import _absolute_path
        from delta_tpu.models.schema import PrimitiveType, to_arrow_type
        from delta_tpu.stats.partition import deserialize_partition_value

        snap = self.table.update()
        schema = snap.schema
        part_cols = snap.partition_columns
        batches = []
        for add in adds:
            tbl = next(
                iter(
                    self.table.engine.parquet.read_parquet_files(
                        [_absolute_path(self.table.path, add.path)]
                    )
                )
            )
            for c in part_cols:
                dtype = PrimitiveType("string")
                if schema is not None and c in schema:
                    fld = schema[c]
                    if isinstance(fld.dataType, PrimitiveType):
                        dtype = fld.dataType
                value = deserialize_partition_value(
                    (add.partitionValues or {}).get(c), dtype
                )
                tbl = tbl.append_column(
                    c, pa.array([value] * tbl.num_rows, to_arrow_type(dtype))
                )
            batches.append(tbl)
        if not batches:
            names = [f.name for f in schema.fields] if schema else []
            from delta_tpu.models.schema import to_arrow_schema

            return to_arrow_schema(schema).empty_table() if schema else pa.table({})
        return pa.concat_tables(batches, promote_options="permissive")

    def micro_batches(
        self, limits: Optional[ReadLimits] = None,
        start: Optional[DeltaSourceOffset] = None,
    ) -> Iterator[tuple[DeltaSourceOffset, pa.Table]]:
        """Drain available data as (offset, batch) pairs until caught up."""
        return _drain_micro_batches(self, limits, start)


class DeltaCDCSource:
    """Streaming read of the change data feed (reference
    `sources/DeltaSourceCDCSupport.scala`): micro-batches carry
    `_change_type` / `_commit_version` / `_commit_timestamp` columns.

    Offsets reuse `DeltaSourceOffset`; a version is the admission unit
    (a commit's changes are never split across batches — its file count
    draws down the budget, and at least one version is always admitted
    so progress never stalls). With no `starting_version`, the current
    snapshot is served first as `insert` rows at the snapshot's version
    — the reference's initial-snapshot-as-inserts contract."""

    def __init__(self, table, starting_version: Optional[int] = None):
        from delta_tpu.config import ENABLE_CDF, cdf_enabled, get_table_config

        self.table = table
        snap = table.update()
        if not cdf_enabled(snap.metadata.configuration):
            from delta_tpu.errors import CdcNotEnabledError

            # same class as the batch CDC reader: callers match on
            # DELTA_CHANGE_TABLE_FEED_DISABLED for both surfaces
            raise CdcNotEnabledError(
                "change data feed is not enabled on this table "
                "(set delta.enableChangeDataFeed=true)"
            )
        self._starting_version = starting_version
        self._initial_version: Optional[int] = None
        self._expiry_guard = _ExpiryGuard(table, "CDC stream")
        # the schema this stream serves; a mid-stream change is an error
        # (same contract as DeltaSource._on_metadata_action)
        if starting_version is not None:
            try:
                base = table.snapshot_at(starting_version)
            except Exception as e:
                # expired version: best effort
                _log.debug("CDC baseline snapshot_at(%d) failed (%s); "
                           "using latest schema", starting_version, e)
                base = snap
        else:
            base = snap
        self._baseline_schema = base.metadata.schemaString

    def _ensure_initial(self) -> None:
        if self._initial_version is not None:
            return
        if self._starting_version is not None:
            self._initial_version = self._starting_version - 1
        else:
            self._initial_version = self.table.update().version

    def _version_file_stats(self, version: int) -> Optional[tuple]:
        """(file_count, byte_count) of the files a CDC read of this
        commit will actually touch — the AddCDCFiles when present, else
        the dataChange add/remove files (mirroring
        `read/cdc.py::table_changes`). None when the commit doesn't
        exist yet. Raises on a mid-stream schema change."""
        path = filenames.delta_file(self.table.log_path, version)
        try:
            data = self.table.engine.fs.read_file(path)
        except FileNotFoundError:
            return None
        from delta_tpu.models.actions import AddCDCFile

        n_cdc = cdc_bytes = n_data = data_bytes = 0
        for a in actions_from_commit_bytes(data):
            if isinstance(a, AddCDCFile):
                n_cdc += 1
                cdc_bytes += a.size or 0
            elif isinstance(a, (AddFile, RemoveFile)) and a.dataChange:
                n_data += 1
                data_bytes += getattr(a, "size", 0) or 0
            elif (isinstance(a, Metadata)
                  and a.schemaString != self._baseline_schema):
                raise _SchemaChanged(version)
        if n_cdc:
            return n_cdc, cdc_bytes
        return n_data, data_bytes

    def latest_offset(
        self, start: Optional[DeltaSourceOffset] = None,
        limits: Optional[ReadLimits] = None,
    ) -> Optional[DeltaSourceOffset]:
        with obs.span("stream.cdc_latest_offset",
                      table=self.table.path) as sp:
            out = self._latest_offset(start, limits)
            if out is not None:
                sp.set_attrs(to_version=out.reservoir_version,
                             initial=out.is_initial_snapshot)
            return out

    def _latest_offset(
        self, start: Optional[DeltaSourceOffset],
        limits: Optional[ReadLimits],
    ) -> Optional[DeltaSourceOffset]:
        self._ensure_initial()
        limits = limits or ReadLimits()
        budget_files = (limits.max_files if limits.max_files is not None
                        else float("inf"))
        budget_bytes = (limits.max_bytes if limits.max_bytes is not None
                        else float("inf"))
        if start is None and self._starting_version is None:
            # the initial snapshot is one indivisible batch
            return DeltaSourceOffset(self._initial_version, END_INDEX,
                                     is_initial_snapshot=True)
        v = (self._initial_version if start is None
             else start.reservoir_version) + 1
        last = None
        while True:
            try:
                stats = self._version_file_stats(v)
            except _SchemaChanged as sc:
                if last is not None:
                    # deliver commits admitted before the schema change;
                    # the next poll starts AT the change and raises
                    return last
                raise StreamingSchemaChangeError(
                    f"table schema changed at version {sc.version}; "
                    "restart the CDC stream to continue with the new "
                    "schema") from None
            if stats is None:
                break
            n, nbytes = stats
            if last is not None and (n > budget_files
                                     or nbytes > budget_bytes):
                break
            budget_files -= n
            budget_bytes -= nbytes
            last = DeltaSourceOffset(v, END_INDEX)
            v += 1
        if last is None:
            self._expiry_guard.check(v)
        return last or start

    def get_batch(
        self, start: Optional[DeltaSourceOffset], end: DeltaSourceOffset
    ) -> pa.Table:
        from delta_tpu.read.cdc import table_changes

        with obs.span("stream.cdc_get_batch", table=self.table.path,
                      end_version=end.reservoir_version) as sp:
            self._ensure_initial()
            parts = []
            if start is None and self._starting_version is None:
                parts.append(self._initial_snapshot_as_inserts())
            begin = ((self._initial_version + 1) if start is None
                     else start.reservoir_version + 1)
            if not end.is_initial_snapshot and begin <= end.reservoir_version:
                parts.append(table_changes(self.table, begin,
                                           end.reservoir_version))
            parts = [p for p in parts if p.num_rows]
            if not parts:
                return self._empty_batch()
            batch = pa.concat_tables(parts, promote_options="permissive")
            sp.set_attr("rows", batch.num_rows)
            return batch

    def _commit_timestamp(self, version: int) -> int:
        try:
            data = self.table.engine.fs.read_file(
                filenames.delta_file(self.table.log_path, version))
        except FileNotFoundError:
            return 0
        for a in actions_from_commit_bytes(data):
            if isinstance(a, CommitInfo):
                return a.inCommitTimestamp or a.timestamp or 0
        return 0

    def _cdc_arrow_schema(self) -> pa.Schema:
        from delta_tpu.models.schema import schema_from_json, to_arrow_schema
        from delta_tpu.read.cdc import (
            CDC_TYPE_COL,
            COMMIT_TIMESTAMP_COL,
            COMMIT_VERSION_COL,
        )

        # the stream's baseline schema, NOT update() — batches
        # for offsets before a schema change must not adopt the new one
        sch = to_arrow_schema(schema_from_json(self._baseline_schema))
        return (sch.append(pa.field(CDC_TYPE_COL, pa.string()))
                .append(pa.field(COMMIT_VERSION_COL, pa.int64()))
                .append(pa.field(COMMIT_TIMESTAMP_COL, pa.int64())))

    def _empty_batch(self) -> pa.Table:
        """Zero rows with the full CDC schema — a metadata-only or
        dataChange=false commit must not yield a schema-less batch."""
        return self._cdc_arrow_schema().empty_table()

    def _initial_snapshot_as_inserts(self) -> pa.Table:
        from delta_tpu.read.cdc import (
            CDC_TYPE_COL,
            COMMIT_TIMESTAMP_COL,
            COMMIT_VERSION_COL,
        )

        snap = self.table.snapshot_at(self._initial_version)
        rows = snap.scan().to_arrow()
        n = rows.num_rows
        ts = self._commit_timestamp(self._initial_version)
        rows = rows.append_column(CDC_TYPE_COL,
                                  pa.array(["insert"] * n, pa.string()))
        rows = rows.append_column(COMMIT_VERSION_COL,
                                  pa.array([self._initial_version] * n,
                                           pa.int64()))
        rows = rows.append_column(COMMIT_TIMESTAMP_COL,
                                  pa.array([ts] * n, pa.int64()))
        return rows

    def micro_batches(
        self, limits: Optional[ReadLimits] = None,
        start: Optional[DeltaSourceOffset] = None,
    ) -> Iterator[tuple[DeltaSourceOffset, pa.Table]]:
        return _drain_micro_batches(self, limits, start)

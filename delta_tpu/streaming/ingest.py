"""Multi-writer exactly-once ingest: N parallel writers, one global
committer.

The reference's Flink connector pattern
(`connectors/flink/.../sink/DeltaSink.java:82` + the single-parallelism
`DeltaGlobalCommitter.java`): many parallel subtasks write Parquet data
files and emit *committables* (the file metadata); a single global
committer collects each checkpoint's committables and performs ONE Delta
transaction for them, carrying a `SetTransaction(appId, checkpointId)`
so a replayed checkpoint (failure/restart re-delivery) is detected and
skipped — exactly-once end to end without any writer-side coordination.

TPU-native notes: writers are host-side I/O workers (a thread pool here;
processes/hosts in a real deployment — the committable is a plain dict
so it serializes anywhere). Per-file stats are collected at write time
so downstream loads keep full data-skipping power.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import pyarrow as pa

from delta_tpu.errors import DeltaError, StreamingSourceError
from delta_tpu.models.actions import AddFile
from delta_tpu.txn.transaction import Operation
from delta_tpu.write.writer import write_data_files


@dataclass
class Committable:
    """One writer subtask's output for one checkpoint."""
    checkpoint_id: int
    subtask: int
    adds: List[AddFile] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "checkpoint_id": self.checkpoint_id,
            "subtask": self.subtask,
            "adds": [a.to_dict() for a in self.adds],
        }

    @staticmethod
    def from_dict(d: dict) -> "Committable":
        return Committable(
            checkpoint_id=d["checkpoint_id"],
            subtask=d["subtask"],
            adds=[AddFile.from_dict(a) for a in d["adds"]],
        )


class IngestWriter:
    """A parallel writer subtask (the Flink `DeltaWriter` role): writes
    Parquet files for its share of a checkpoint's rows and emits a
    Committable. No log access, no coordination — safe at any
    parallelism."""

    def __init__(self, table, subtask: int):
        self._table = table
        self.subtask = subtask

    def write(self, checkpoint_id: int, data: pa.Table) -> Committable:
        snapshot = self._table.latest_snapshot()
        meta = snapshot.metadata
        adds = write_data_files(
            engine=self._table.engine,
            table_path=self._table.path,
            data=data,
            schema=snapshot.schema,
            partition_columns=snapshot.partition_columns,
            configuration=meta.configuration,
        )
        return Committable(checkpoint_id, self.subtask, list(adds))


class GlobalCommitter:
    """The single-parallelism committer (`DeltaGlobalCommitter.java`):
    one Delta transaction per checkpoint, idempotent under re-delivery
    via SetTransaction(appId, checkpointId)."""

    def __init__(self, table, app_id: str):
        self._table = table
        self.app_id = app_id
        self._lock = threading.Lock()

    def last_committed_checkpoint(self) -> Optional[int]:
        snap = self._table.latest_snapshot()
        txn = snap.state.set_transactions.get(self.app_id)
        return txn.version if txn is not None else None

    def commit(self, checkpoint_id: int,
               committables: List[Committable]) -> Optional[int]:
        """Commit one checkpoint's committables; returns the Delta
        version, or None when this checkpoint was already committed
        (restart re-delivery — the files written by the replayed attempt
        are simply never referenced, the same orphan-file contract as the
        reference)."""
        for c in committables:
            if c.checkpoint_id != checkpoint_id:
                raise StreamingSourceError(
                    error_class="DELTA_INGEST_COMMITTABLE_MISMATCH",
                    message=f"committable for checkpoint {c.checkpoint_id} handed "
                    f"to commit of checkpoint {checkpoint_id}")
        with self._lock:
            last = self.last_committed_checkpoint()
            if last is not None and checkpoint_id <= last:
                return None  # duplicate delivery: exactly-once skip
            txn = self._table.create_transaction_builder(
                Operation.STREAMING_UPDATE).build()
            txn.set_transaction_id(self.app_id, checkpoint_id)
            for c in committables:
                txn.add_files(c.adds)
            result = txn.commit()
            return result.version


class IngestJob:
    """Convenience harness wiring N writers + the committer (what a
    stream processor's runtime does): `run_checkpoint` splits a batch
    across the writers (parallel threads), gathers committables, and
    globally commits them as one transaction."""

    def __init__(self, table, app_id: str, parallelism: int = 4):
        self.table = table
        self.committer = GlobalCommitter(table, app_id)
        self.writers = [IngestWriter(table, i) for i in range(parallelism)]

    def run_checkpoint(self, checkpoint_id: int,
                       data: pa.Table) -> Optional[int]:
        n = len(self.writers)
        shares = [data.slice(i * data.num_rows // n,
                             (i + 1) * data.num_rows // n
                             - i * data.num_rows // n)
                  for i in range(n)]
        committables: Dict[int, Committable] = {}
        errors: List[BaseException] = []

        def work(i):
            try:
                if shares[i].num_rows:
                    committables[i] = self.writers[i].write(
                        checkpoint_id, shares[i])
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=work, args=(i,), daemon=True)
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return self.committer.commit(
            checkpoint_id, [committables[i] for i in sorted(committables)])

"""Isolation levels (reference `isolationLevels.scala`).

- SERIALIZABLE: full serializability — concurrent appends that our read
  predicate might have seen conflict.
- WRITE_SERIALIZABLE: writes serialize, reads may see a snapshot that a
  concurrent blind append later "time-travels" behind; blind appends by
  winners don't conflict with our reads.
- SNAPSHOT_ISOLATION: only write-write conflicts (deletes of the same
  files, metadata/protocol changes) matter.

Data-changing commits default to WRITE_SERIALIZABLE; file-rearranging
commits (OPTIMIZE: dataChange=false) can run at SNAPSHOT_ISOLATION
(`OptimisticTransaction.getIsolationLevelToUse`:2076).
"""

from __future__ import annotations

from enum import Enum


class IsolationLevel(Enum):
    SERIALIZABLE = "Serializable"
    WRITE_SERIALIZABLE = "WriteSerializable"
    SNAPSHOT_ISOLATION = "SnapshotIsolation"


def default_isolation_level(data_changed: bool) -> IsolationLevel:
    return (
        IsolationLevel.WRITE_SERIALIZABLE if data_changed
        else IsolationLevel.SNAPSHOT_ISOLATION
    )

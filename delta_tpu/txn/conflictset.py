"""Reusable conflict-set engine: one winner set, many losers.

Extracted from ``Transaction._resolve_conflict`` (ROADMAP item 2) so
the same machinery serves two callers:

- the **solo retry loop**: one transaction checks itself against the
  commits that beat it, folds their in-commit timestamps and row-ID
  watermark, and rebases;
- the **group committer** (``txn/groupcommit.py``): a batch of
  transactions is checked against ONE shared snapshot of winners, and
  each accepted member's own prepared actions are appended to the set
  (via :meth:`ConflictSetEngine.extend`) so later members in the same
  batch are checked against earlier ones exactly as if those had
  already landed.

The engine is deliberately stateless about any particular transaction:
callers pass the ``TransactionReadState`` and their read version, and
get back a :class:`ConflictResolution` (or a typed
``ConcurrentModificationError`` subclass from the checker). All
policy — what to do with a loser — stays with the caller.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from delta_tpu.config import IN_COMMIT_TIMESTAMPS, get_table_config
from delta_tpu.errors import LogCorruptedError
from delta_tpu.models.actions import CommitInfo, Metadata
from delta_tpu.txn.conflict import (
    TransactionReadState,
    WinningCommit,
    check_conflicts,
)


@dataclass
class ConflictResolution:
    """The successful outcome of one member's conflict check."""

    #: max inCommitTimestamp across the winners (None when no winner
    #: carried one) — the floor for the member's own ICT
    winners_ict: Optional[int]
    #: row-ID high watermark claimed by winners, or None
    row_id_high_watermark: Optional[int]
    #: raw rebase dict from ``check_conflicts`` (forward-compatible)
    rebase: dict


class ConflictSetEngine:
    """A growing, ordered set of winning commits plus the fold logic
    every loser needs: logical conflict check, in-commit-timestamp
    monotonicity, row-ID watermark."""

    def __init__(self, winners: Optional[List[WinningCommit]] = None):
        self._winners: List[WinningCommit] = list(winners or [])

    @property
    def winners(self) -> List[WinningCommit]:
        return list(self._winners)

    def winners_after(self, read_version: int) -> List[WinningCommit]:
        """Winners a transaction that read ``read_version`` must check
        against (strictly newer than what it read)."""
        return [w for w in self._winners if w.version > read_version]

    def extend(self, winner: WinningCommit) -> None:
        """Append a newly accepted commit (batch member or fresh
        winner) so subsequent resolves see it."""
        if self._winners and winner.version <= self._winners[-1].version:
            raise ValueError(
                f"winner versions must be ascending: {winner.version} "
                f"after {self._winners[-1].version}")
        self._winners.append(winner)

    def resolve(self, state: TransactionReadState, read_version: int,
                ict_on: bool,
                winners_ict: Optional[int] = None) -> ConflictResolution:
        """Check ``state`` against every winner newer than
        ``read_version``; raises the checker's typed
        ``ConcurrentModificationError`` subclass when the member loses.
        ``ict_on`` is whether in-commit timestamps were enabled at the
        member's read snapshot; winners that change Metadata may toggle
        it mid-fold."""
        winners = self.winners_after(read_version)
        rebase = check_conflicts(state, winners)
        row_hw = rebase.get("row_id_high_watermark")
        for w in winners:
            # a winner may toggle ICT itself: its Metadata governs
            # whether IT and later winners must carry an
            # inCommitTimestamp
            wmeta = next(
                (a for a in w.actions if isinstance(a, Metadata)), None)
            if wmeta is not None:
                ict_on = get_table_config(
                    wmeta.configuration, IN_COMMIT_TIMESTAMPS)
            ci = next(
                (a for a in w.actions if isinstance(a, CommitInfo)), None)
            if ci is not None and ci.inCommitTimestamp is not None:
                winners_ict = max(winners_ict or 0, ci.inCommitTimestamp)
            elif ict_on:
                # `CommitInfo.getRequiredInCommitTimestamp`: on an ICT
                # table every commit must carry its timestamp — a
                # winner without one corrupts the monotonic clock this
                # rebase maintains
                if ci is None:
                    raise LogCorruptedError(
                        f"commit {w.version} has no commitInfo "
                        "but in-commit timestamps are enabled",
                        error_class="DELTA_MISSING_COMMIT_INFO")
                raise LogCorruptedError(
                    f"commitInfo of commit {w.version} has no "
                    "inCommitTimestamp but in-commit "
                    "timestamps are enabled",
                    error_class="DELTA_MISSING_COMMIT_TIMESTAMP")
        return ConflictResolution(
            winners_ict=winners_ict,
            row_id_high_watermark=row_hw,
            rebase=rebase,
        )

"""Optimistic transactions: read-tracked, conflict-checked commits.

The rebuild of `OptimisticTransaction.scala` (commit:1236 →
doCommitRetryIteratively:2198) and kernel `TransactionImpl.java:144`:

    txn = table.start_transaction("WRITE")
    files = txn.scan_files(filter=...)      # reads are tracked
    txn.add_file(add)
    txn.remove_file(remove)
    result = txn.commit()

Commit loop: serialize actions → LogStore.write(N.json, overwrite=False)
(atomic put-if-absent) → on FileAlreadyExistsError, run the conflict
checker against the winning commits and retry at the next version, up to
`settings.max_commit_retries`. Post-commit hooks (checkpointing every
`delta.checkpointInterval` commits, checksum) run best-effort.
"""
# delta-lint: file-disable=shared-state-race — audited:
# A Transaction is thread-confined by contract — one thread builds
# and commits it (same as the reference's OptimisticTransaction,
# which is also unsynchronized); concurrency happens BETWEEN
# transactions and is handled by the commit conflict checker.

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from delta_tpu import obs
from delta_tpu.config import (
    CHECKPOINT_INTERVAL,
    IN_COMMIT_TIMESTAMPS,
    get_table_config,
    settings,
)
from delta_tpu.errors import (
    CommitFailedError,
    ConcurrentTransactionError,
    DeltaError,
    InvalidArgumentError,
    MaxCommitRetriesExceededError,
    MetadataChangedError,
    ProtocolChangedError,
    TableNotFoundError,
)
from delta_tpu.expressions.tree import Expression
from delta_tpu.models.actions import (
    Action,
    AddCDCFile,
    AddFile,
    CommitInfo,
    DomainMetadata,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
    actions_to_commit_bytes,
)
from delta_tpu.txn.conflict import TransactionReadState
from delta_tpu.txn.isolation import IsolationLevel, default_isolation_level
from delta_tpu.utils import filenames

_log = logging.getLogger(__name__)

# put-if-absent retries that conflicted with their OWN landed commit
# (write applied, success response lost) and were recovered in place
_SELF_COMMITS = obs.counter("txn.self_commit_recovered")


class Operation:
    WRITE = "WRITE"
    STREAMING_UPDATE = "STREAMING UPDATE"
    DELETE = "DELETE"
    UPDATE = "UPDATE"
    MERGE = "MERGE"
    OPTIMIZE = "OPTIMIZE"
    CREATE_TABLE = "CREATE TABLE"
    REPLACE_TABLE = "REPLACE TABLE"
    SET_TBLPROPERTIES = "SET TBLPROPERTIES"
    ADD_COLUMNS = "ADD COLUMNS"
    CHANGE_COLUMN = "CHANGE COLUMN"
    RENAME_COLUMN = "RENAME COLUMN"
    DROP_COLUMNS = "DROP COLUMNS"
    ADD_CONSTRAINT = "ADD CONSTRAINT"
    DROP_CONSTRAINT = "DROP CONSTRAINT"
    UPGRADE_PROTOCOL = "UPGRADE PROTOCOL"
    RESTORE = "RESTORE"
    CLONE = "CLONE"
    VACUUM_START = "VACUUM START"
    VACUUM_END = "VACUUM END"
    TRUNCATE = "TRUNCATE"
    CONVERT = "CONVERT"
    CLUSTER_BY = "CLUSTER BY"
    MANUAL_UPDATE = "Manual Update"


@dataclass
class CommitResult:
    version: int
    committed: bool
    snapshot_fn: Optional[object] = None
    attempts: int = 1

    @property
    def post_commit_snapshot(self):
        return self.snapshot_fn() if self.snapshot_fn else None


class TransactionBuilder:
    """Builds a Transaction against the current table state (or a new
    table). Mirrors kernel `TransactionBuilderImpl`."""

    def __init__(self, table, operation: str = Operation.WRITE, engine_info: Optional[str] = None):
        self._table = table
        self._operation = operation
        self._engine_info = engine_info or f"delta-tpu/{_version()}"
        self._schema = None
        self._partition_columns: Optional[List[str]] = None
        self._txn_app_id: Optional[str] = None
        self._txn_version: Optional[int] = None
        self._table_properties: Optional[Dict[str, str]] = None
        self._isolation: Optional[IsolationLevel] = None
        self._max_retries: Optional[int] = None

    def with_schema(self, schema) -> "TransactionBuilder":
        self._schema = schema
        return self

    def with_partition_columns(self, cols: Sequence[str]) -> "TransactionBuilder":
        self._partition_columns = list(cols)
        return self

    def with_transaction_id(self, app_id: str, version: int) -> "TransactionBuilder":
        self._txn_app_id, self._txn_version = app_id, version
        return self

    def with_table_properties(self, props: Dict[str, str]) -> "TransactionBuilder":
        self._table_properties = dict(props)
        return self

    def with_isolation_level(self, level: IsolationLevel) -> "TransactionBuilder":
        self._isolation = level
        return self

    def with_max_retries(self, n: int) -> "TransactionBuilder":
        self._max_retries = n
        return self

    def build(self) -> "Transaction":
        try:
            snapshot = self._table.latest_snapshot()
        except TableNotFoundError:
            snapshot = None

        if snapshot is None and self._schema is None:
            raise InvalidArgumentError(
                f"table {self._table.path} does not exist; provide a schema "
                "to create it", error_class="DELTA_METADATA_ABSENT"
            )

        txn = Transaction(
            table=self._table,
            snapshot=snapshot,
            operation=self._operation,
            engine_info=self._engine_info,
            isolation=self._isolation,
            max_retries=self._max_retries,
        )
        if snapshot is None:
            from delta_tpu.models.schema import StructType, schema_from_json, schema_to_json
            from delta_tpu.features import protocol_for_new_table

            props = dict(self._table_properties or {})
            schema_obj = (
                self._schema
                if isinstance(self._schema, StructType)
                else schema_from_json(self._schema)
            )
            if props.get("delta.columnMapping.mode", "none") != "none":
                from delta_tpu.columnmapping import assign_column_mapping

                schema_obj, props = assign_column_mapping(schema_obj, props)

            # creation-only protocol properties are consumed here, not
            # persisted in Metadata.configuration (reference strips
            # them the same way)
            persisted = {k: v for k, v in props.items()
                         if k not in ("delta.minReaderVersion",
                                      "delta.minWriterVersion",
                                      "delta.ignoreProtocolDefaults")}
            metadata = Metadata(
                id=str(uuid.uuid4()),
                schemaString=schema_to_json(schema_obj),
                partitionColumns=list(self._partition_columns or []),
                configuration=persisted,
                createdTime=int(time.time() * 1000),
            )
            txn.update_metadata(metadata)
            txn.update_protocol(
                protocol_for_new_table(props, metadata.schemaString))
        elif self._table_properties:
            meta = snapshot.metadata
            new_conf = dict(meta.configuration)
            new_conf.update(self._table_properties)
            if new_conf != meta.configuration:
                import dataclasses

                txn.update_metadata(dataclasses.replace(meta, configuration=new_conf))

        if self._txn_app_id is not None:
            txn.set_transaction_id(self._txn_app_id, self._txn_version)
        return txn


def _version() -> str:
    from delta_tpu.version import __version__

    return __version__


class Transaction:
    def __init__(
        self,
        table,
        snapshot,
        operation: str,
        engine_info: str,
        isolation: Optional[IsolationLevel] = None,
        max_retries: Optional[int] = None,
    ):
        self._table = table
        self.read_snapshot = snapshot
        self.operation = operation
        self.engine_info = engine_info
        self.txn_id = str(uuid.uuid4())
        self._isolation = isolation
        self._max_retries = (
            max_retries if max_retries is not None else settings.max_commit_retries
        )

        self._adds: List[AddFile] = []
        self._removes: List[RemoveFile] = []
        self._cdcs: List[AddCDCFile] = []
        self._set_txns: Dict[str, SetTransaction] = {}
        self._domain_metadata: Dict[str, DomainMetadata] = {}
        self._new_metadata: Optional[Metadata] = None
        self._new_protocol: Optional[Protocol] = None
        self._op_parameters: Dict[str, object] = {}
        self._op_metrics: Dict[str, object] = {}

        self._read_predicates: List[Expression] = []
        self._winners_row_watermark: Optional[int] = None
        self._read_whole_table = False
        self._read_files: set = set()
        self._read_app_ids: set = set()
        self._committed = False
        # observer hook for deterministic concurrency tests (the
        # TransactionExecutionObserver analogue)
        self.observer = None

    # -- read tracking ------------------------------------------------------

    @property
    def read_version(self) -> int:
        return self.read_snapshot.version if self.read_snapshot else -1

    def metadata(self) -> Optional[Metadata]:
        if self._new_metadata is not None:
            return self._new_metadata
        return self.read_snapshot.metadata if self.read_snapshot else None

    def protocol(self) -> Optional[Protocol]:
        if self._new_protocol is not None:
            return self._new_protocol
        return self.read_snapshot.protocol if self.read_snapshot else None

    def scan_files(self, filter: Optional[Expression] = None):
        """Scan the read snapshot, recording the predicate (or whole-table
        read) and the returned file keys for conflict checking."""
        if self.read_snapshot is None:
            return []
        scan = self.read_snapshot.scan(filter=filter)
        files = scan.files()
        if filter is None:
            self._read_whole_table = True
        else:
            self._read_predicates.append(filter)
        for f in files:
            self._read_files.add((f.path, f.dv_unique_id))
        return files

    def mark_read_whole_table(self) -> None:
        self._read_whole_table = True

    def txn_version(self, app_id: str) -> Optional[int]:
        """Read an idempotent-txn watermark; the read is tracked."""
        self._read_app_ids.add(app_id)
        if self.read_snapshot is None:
            return None
        return self.read_snapshot.set_transaction_version(app_id)

    # -- staging ------------------------------------------------------------

    def add_file(self, add: AddFile) -> None:
        self._adds.append(add)

    def add_files(self, adds: Sequence[AddFile]) -> None:
        self._adds.extend(adds)

    def remove_file(self, remove: RemoveFile) -> None:
        self._removes.append(remove)

    def remove_files(self, removes: Sequence[RemoveFile]) -> None:
        self._removes.extend(removes)

    def add_cdc_file(self, cdc: AddCDCFile) -> None:
        self._cdcs.append(cdc)

    def set_transaction_id(self, app_id: str, version: int, last_updated: Optional[int] = None):
        existing = self.txn_version(app_id)
        if existing is not None and version <= existing:
            raise ConcurrentTransactionError(
                f"transaction {app_id} already advanced to {existing} >= {version}"
            )
        if last_updated is None:
            # always stamped (reference commit path does the same):
            # delta.setTransactionRetentionDuration drops un-timestamped
            # entries at the first checkpoint, which would break
            # idempotent replay protection for fresh watermarks
            last_updated = int(time.time() * 1000)
        self._set_txns[app_id] = SetTransaction(app_id, version, last_updated)

    def update_metadata(self, metadata: Metadata) -> None:
        _check_column_name_characters(metadata)
        # partition columns must name schema fields and be unique
        # (`DeltaErrors.partitionColumnNotFoundException` semantics)
        if metadata.schema is not None and not metadata.schema.fields:
            # `DeltaErrors.emptyDataException`
            raise InvalidArgumentError(
                "Data used in creating the Delta table doesn't have "
                "any columns.", error_class="DELTA_EMPTY_DATA")
        if metadata.schema is not None:
            from delta_tpu.colgen import validate_generated_schema

            validate_generated_schema(metadata.schema,
                                      metadata.partitionColumns)
        pcols = list(metadata.partitionColumns or [])
        if pcols:
            schema = metadata.schema
            known = {f.name for f in schema.fields} if schema else set()
            missing = [c for c in pcols if c not in known]
            if missing:
                raise InvalidArgumentError(
                    f"partition column(s) {missing} not found in schema "
                    f"{sorted(known)}",
                    error_class="DELTA_INVALID_PARTITION_COLUMN"
                )
            if len(set(pcols)) != len(pcols):
                raise InvalidArgumentError(f"duplicate partition columns: {pcols}")
            if schema is not None and len(pcols) == len(schema.fields):
                # `DeltaErrors.cannotUseAllColumnsForPartitionColumns`:
                # every row group would be a partition directory with
                # empty data files
                raise InvalidArgumentError(
                    "cannot use all columns for partition columns",
                    error_class="DELTA_CANNOT_USE_ALL_COLUMNS_FOR_PARTITION")
            if schema is not None:
                from delta_tpu.models.schema import (
                    ArrayType,
                    MapType,
                    StructType,
                )

                by_name = {f.name: f for f in schema.fields}
                for c in pcols:
                    if isinstance(by_name[c].dataType,
                                  (ArrayType, MapType, StructType)):
                        # `DeltaErrors.invalidPartitionColumnType`
                        raise InvalidArgumentError(
                            f"using column {c} of type "
                            f"{by_name[c].dataType.to_json_value()} as "
                            "a partition column is not supported",
                            error_class="DELTA_INVALID_PARTITION_COLUMN_TYPE")
        self._new_metadata = metadata

    def update_protocol(self, protocol: Protocol) -> None:
        self._new_protocol = protocol

    def set_domain_metadata(self, domain: str, configuration: str) -> None:
        self._check_domain_metadata_supported()
        self._domain_metadata[domain] = DomainMetadata(domain, configuration, removed=False)

    def _check_domain_metadata_supported(self) -> None:
        """DomainMetadata actions require the domainMetadata writer
        feature (PROTOCOL.md domain metadata section; reference raises
        DELTA_DOMAIN_METADATA_NOT_SUPPORTED)."""
        # a staged upgrade (e.g. CLUSTER BY adds domainMetadata just
        # before setting its domain) takes precedence over the snapshot
        snap = self.read_snapshot
        proto = self._new_protocol if self._new_protocol is not None \
            else (snap.protocol if snap is not None else None)
        if proto is None:
            return
        from delta_tpu.features import is_feature_supported, DOMAIN_METADATA
        from delta_tpu.errors import DomainMetadataError

        if not is_feature_supported(proto, DOMAIN_METADATA):
            raise DomainMetadataError(
                "setting domain metadata requires the domainMetadata "
                "writer table feature (protocol "
                f"({proto.minReaderVersion}, {proto.minWriterVersion}))")

    def remove_domain_metadata(self, domain: str) -> None:
        self._check_domain_metadata_supported()
        self._domain_metadata[domain] = DomainMetadata(domain, "", removed=True)

    def set_operation_parameters(self, params: Dict[str, object]) -> None:
        self._op_parameters.update(params)

    def set_operation_metrics(self, metrics: Dict[str, object]) -> None:
        self._op_metrics.update(metrics)

    # -- commit -------------------------------------------------------------

    @property
    def data_changed(self) -> bool:
        return any(a.dataChange for a in self._adds) or any(
            r.dataChange for r in self._removes
        )

    def _prepare_actions(self, attempt_version: int, winners_ict: Optional[int]) -> List[Action]:
        """prepareCommit (`OptimisticTransaction.scala:1910`): validate and
        order actions; first line is commitInfo (required when ICT on)."""
        meta = self.metadata()
        if meta is None:
            raise InvalidArgumentError(
                "cannot commit a transaction with no metadata",
                error_class="DELTA_METADATA_ABSENT")
        if self.read_snapshot is None and self._new_protocol is None:
            raise InvalidArgumentError(
                "new table commit must include a protocol",
                error_class="DELTA_PROTOCOL_ABSENT")
        from delta_tpu.features import validate_writable

        validate_writable(self.protocol(), meta)

        from delta_tpu.interop.icebergcompat import validate_iceberg_compat

        validate_iceberg_compat(meta, self.protocol(), adds=self._adds)

        from delta_tpu.config import APPEND_ONLY

        if get_table_config(meta.configuration, APPEND_ONLY) and any(
            r.dataChange for r in self._removes
        ):
            # commit-level backstop (`DeltaLog.assertRemovable`): DML
            # commands check earlier, but a raw transaction must not
            # bypass the table contract. dataChange=false removes
            # (OPTIMIZE rewrites) stay allowed.
            raise InvalidArgumentError(
                error_class="DELTA_APPEND_ONLY_REMOVES",
                message="This table is configured to only allow appends "
                "(delta.appendOnly=true); data-changing removes are not "
                "permitted")

        now = int(time.time() * 1000)
        ict = None
        if get_table_config(meta.configuration, IN_COMMIT_TIMESTAMPS):
            prev = 0
            if self.read_snapshot is not None:
                prev = self.read_snapshot.timestamp_ms
            if winners_ict is not None:
                prev = max(prev, winners_ict)
            ict = max(now, prev + 1)
            # enablement provenance (PROTOCOL.md in-commit timestamps):
            # when ICT turns on mid-table, record the enabling version +
            # timestamp so timestamp search knows where the ICT range starts
            prov_key = "delta.inCommitTimestampEnablementVersion"
            was_enabled = self.read_snapshot is not None and get_table_config(
                self.read_snapshot.metadata.configuration, IN_COMMIT_TIMESTAMPS
            )
            if (
                not was_enabled
                and self.read_snapshot is not None
                and prov_key not in meta.configuration
            ):
                import dataclasses as _dc

                conf = dict(meta.configuration)
                conf[prov_key] = str(attempt_version)
                conf["delta.inCommitTimestampEnablementTimestamp"] = str(ict)
                meta = _dc.replace(meta, configuration=conf)
                self._new_metadata = meta

        # row tracking: assign fresh baseRowIds + the watermark domain
        adds = self._adds
        row_tracking_dm = None
        from delta_tpu.rowtracking import (
            ROW_TRACKING_DOMAIN,
            assign_fresh_row_ids,
            current_high_watermark,
            is_row_tracking_supported,
        )

        if is_row_tracking_supported(self.protocol()) and self._adds:
            hw = max(
                current_high_watermark(self.read_snapshot),
                self._winners_row_watermark
                if self._winners_row_watermark is not None
                else -1,
            )
            adds, row_tracking_dm = assign_fresh_row_ids(
                self._adds, hw, attempt_version
            )

        self._committed_ict = ict  # consumed by the incremental .crc writer
        commit_info = CommitInfo(
            timestamp=now,
            inCommitTimestamp=ict,
            operation=self.operation,
            operationParameters=self._op_parameters or {},
            operationMetrics=self._compute_metrics(),
            readVersion=self.read_version if self.read_version >= 0 else None,
            isolationLevel=self._isolation_level().value,
            isBlindAppend=(not self._removes and not self._read_files
                           and not self._read_predicates and not self._read_whole_table),
            engineInfo=self.engine_info,
            txnId=self.txn_id,
        )
        actions: List[Action] = [commit_info]
        if self._new_protocol is not None:
            actions.append(self._new_protocol)
        if self._new_metadata is not None:
            actions.append(self._new_metadata)
        actions.extend(self._set_txns.values())
        domains = dict(self._domain_metadata)
        if row_tracking_dm is not None and ROW_TRACKING_DOMAIN not in domains:
            domains[ROW_TRACKING_DOMAIN] = row_tracking_dm
        actions.extend(domains.values())
        actions.extend(self._removes)
        actions.extend(adds)
        actions.extend(self._cdcs)
        return actions

    def _compute_metrics(self) -> Dict[str, str]:
        m = {
            "numOutputFiles": str(len(self._adds)),
            "numOutputBytes": str(sum(a.size for a in self._adds)),
        }
        if self._removes:
            m["numRemovedFiles"] = str(len(self._removes))
        m.update({k: _metric_str(v) for k, v in self._op_metrics.items()
                  if v is not None})
        return m

    def _isolation_level(self) -> IsolationLevel:
        if self._isolation is not None:
            return self._isolation
        # delta.isolationLevel table property overrides the
        # data-changed default (DeltaConfig.scala isolationLevel)
        meta = self.metadata()
        if meta is not None:
            from delta_tpu.config import ISOLATION_LEVEL, get_table_config

            raw = meta.configuration.get(ISOLATION_LEVEL.key)
            if raw is not None:
                return IsolationLevel(ISOLATION_LEVEL.parse(raw))
        return default_isolation_level(self.data_changed)

    def _read_state(self) -> TransactionReadState:
        meta = self.metadata()
        return TransactionReadState(
            read_predicates=list(self._read_predicates),
            read_whole_table=self._read_whole_table,
            read_files=set(self._read_files),
            read_app_ids=set(self._read_app_ids) | set(self._set_txns),
            removed_keys={(r.path, r.dv_unique_id) for r in self._removes},
            written_domains=set(self._domain_metadata),
            metadata_changed=self._new_metadata is not None,
            protocol_changed=self._new_protocol is not None,
            partition_columns=list(meta.partitionColumns) if meta else [],
            isolation=self._isolation_level(),
            metadata=meta,
        )

    def _coordinator(self):
        meta = self.metadata()
        if meta is None:
            return None
        from delta_tpu.coordinatedcommits import coordinator_for_table

        return coordinator_for_table(meta.configuration)

    def _write_commit(self, engine, log_path: str, version: int, data: bytes) -> None:
        """One commit attempt: put-if-absent file write, or coordinator RPC
        for coordinated-commit tables. Raises FileExistsError on loss."""
        coordinator = self._coordinator()
        if coordinator is None:
            path = filenames.delta_file(log_path, version)
            engine.json.write_json_file_atomically(path, data, overwrite=False)
            return
        import time as _time

        from delta_tpu.coordinatedcommits import CommitFailedException
        from delta_tpu.resilience import breaker_for, default_policy

        ts = int(_time.time() * 1000)
        try:
            # Retryable coordinator failures (network, coordinator
            # restarts) are absorbed here; conflicts and non-retryable
            # failures pass through to the txn machinery untouched.
            default_policy().call(
                lambda: coordinator.commit(log_path, version, data, ts),
                breaker=breaker_for("commit-coordinator"))
        except CommitFailedException as e:
            if e.conflict:
                raise FileExistsError(str(e)) from e
            raise CommitFailedError(str(e), retryable=e.retryable) from e
        if self.observer:
            # the coordinator accepted the commit (and ran any batch
            # backfill) — the reference's backfillPhase boundary
            hook = getattr(self.observer, "after_backfill", None)
            if hook is not None:
                hook(self, version)

    def _read_commit_range(self, engine, log_path: str, lo: int, hi: int):
        """Winning commits [lo, hi] — backfilled files or coordinator
        unbackfilled entries."""
        coordinator = self._coordinator()
        unbackfilled = {}
        if coordinator is not None:
            from delta_tpu.resilience import breaker_for, default_policy

            resp = default_policy().call(
                lambda: coordinator.get_commits(log_path, lo, hi),
                breaker=breaker_for("commit-coordinator"))
            for c in resp.commits:
                unbackfilled[c.version] = c.file_status.path
        from delta_tpu.models.actions import actions_from_commit_bytes
        from delta_tpu.txn.conflict import WinningCommit

        out = []
        for v in range(lo, hi + 1):
            path = unbackfilled.get(v, filenames.delta_file(log_path, v))
            try:
                data = engine.fs.read_file(path)
            except FileNotFoundError:
                data = engine.fs.read_file(filenames.delta_file(log_path, v))
            out.append(WinningCommit(v, actions_from_commit_bytes(data)))
        return out

    def commit(self) -> CommitResult:
        """doCommitRetryIteratively (`OptimisticTransaction.scala:2198`)."""
        if self._committed:
            raise InvalidArgumentError("transaction already committed",
                                       error_class="DELTA_TRANSACTION_ALREADY_COMMITTED")
        with obs.span("txn.commit", table=self._table.path,
                      operation=self.operation,
                      read_version=self.read_version,
                      txn_id=self.txn_id) as csp:
            result = self._commit_loop()
            csp.set_attrs(committed_version=result.version,
                          attempts=result.attempts)
            return result

    def _commit_loop(self) -> CommitResult:
        engine = self._table.engine
        log_path = self._table.log_path
        attempt_version = self.read_version + 1
        winners_ict: Optional[int] = None
        attempts = 0
        t_start = time.perf_counter()

        def _report(committed_version, success):
            self._report_metrics(committed_version, success, attempts,
                                 t_start)

        gc = self._group_committer()
        if gc is not None:
            outcome = gc.submit(self)
            if outcome.version is not None:
                # committed (possibly rebased) through the batch — one
                # arbiter round trip shared with the other members
                return self._finish_commit(outcome.version, outcome.data,
                                           1, t_start)
            # conflict-rejected or degraded: fall through to the solo
            # retry path, which re-resolves against the commits that
            # actually landed (a batch-mate we "lost" to may not have)

        while attempts <= self._max_retries:
            attempts += 1
            with obs.span("txn.attempt", attempt=attempts,
                          version=attempt_version) as asp:
                if self.observer:
                    self.observer.before_commit_attempt(self, attempt_version)
                actions = self._prepare_actions(attempt_version, winners_ict)
                data = actions_to_commit_bytes(actions)
                if self.observer:
                    # prepare/commit phase boundary: actions are validated +
                    # serialized; the commit file is not yet visible
                    hook = getattr(self.observer, "after_prepare", None)
                    if hook is not None:
                        hook(self, attempt_version)
                try:
                    self._write_commit(engine, log_path, attempt_version, data)
                except FileExistsError:
                    asp.set_attr("conflict", True)
                    if self.observer:
                        self.observer.on_commit_conflict(self, attempt_version)
                    # Apparently lost the race: find the current latest and
                    # read the winners — first checking whether the "winner"
                    # at our version is actually us (ambiguous write
                    # outcome), else conflict-check, rebase, retry.
                    latest = self._latest_version(engine, log_path,
                                                  attempt_version)
                    winners = self._read_commit_range(
                        engine, log_path, attempt_version, latest
                    )
                    if self._is_own_commit(winners[0]):
                        # Not a loss at all: an ambiguous write outcome
                        # (the PUT landed but its response was lost) made
                        # the retried put-if-absent observe our OWN commit
                        # as FileExistsError. Rebasing would re-commit the
                        # same actions at N+1 — duplicate data. The txnId
                        # we serialize into commitInfo makes the case
                        # detectable; fall through to the success path at
                        # this attempt version.
                        _SELF_COMMITS.inc()
                        asp.set_attrs(conflict=False, self_commit=True)
                        obs.add_event("txn.self_commit_recovered",
                                      version=attempt_version)
                    else:
                        winners_ict = self._resolve_conflict(
                            winners, attempt_version, latest, winners_ict,
                            _report, asp)
                        attempt_version = latest + 1
                        continue
                    # (self-commit) fall through to the success path
            return self._finish_commit(attempt_version, data, attempts,
                                       t_start)
        raise MaxCommitRetriesExceededError(
            f"commit failed after {attempts} attempts (last tried version "
            f"{attempt_version})"
        )

    def _finish_commit(self, version: int, data: bytes, attempts: int,
                       t_start: float) -> CommitResult:
        """The shared success tail of both commit paths (solo loop and
        group-commit batch): mark committed, feed the snapshot cache,
        fire observers/metrics/hooks, build the result."""
        self._committed = True
        # hand the bytes we just wrote to the snapshot cache BEFORE
        # the hooks run, so they (and the next update() poll) advance
        # incrementally without re-reading our own commit
        notify = getattr(self._table, "notify_commit", None)
        if notify is not None and self._coordinator() is None:
            notify(version, data)
        if self.observer:
            self.observer.after_commit(self, version)
        self._report_metrics(version, True, attempts, t_start)
        self._run_post_commit_hooks(version)
        table = self._table
        return CommitResult(
            version=version,
            committed=True,
            snapshot_fn=lambda: table.update(),
            attempts=attempts,
        )

    def _report_metrics(self, committed_version: Optional[int],
                        success: bool, attempts: int,
                        t_start: float) -> None:
        engine = self._table.engine
        if getattr(engine, "metrics_reporters", None):
            from delta_tpu.metrics import transaction_report

            engine.report_metrics(
                transaction_report(
                    self._table.path,
                    self.operation,
                    self.read_version,
                    committed_version,
                    attempts,
                    (time.perf_counter() - t_start) * 1000,
                    len(self._adds),
                    len(self._removes),
                    success,
                )
            )

    def _group_committer(self):
        """The table's group committer, or None when this transaction
        must take the solo path: disabled by env, a brand-new table
        (read_version < 0 — there is no snapshot to batch against), or
        an observer-driven test that phase-locks the solo protocol."""
        if self.observer is not None or self.read_version < 0:
            return None
        from delta_tpu.txn.groupcommit import group_committer_for

        return group_committer_for(self._table)

    def _ict_enabled_at_read(self) -> bool:
        """Whether in-commit timestamps were enabled at this
        transaction's read snapshot (the starting state for the
        conflict-set ICT fold)."""
        return self.read_snapshot is not None and get_table_config(
            self.read_snapshot.metadata.configuration, IN_COMMIT_TIMESTAMPS)

    def _is_own_commit(self, winner) -> bool:
        """True when the 'winning' commit at our attempt version is the
        one THIS transaction wrote, identified by the ``txnId`` we
        serialize into every commitInfo."""
        ci = next(
            (a for a in winner.actions if isinstance(a, CommitInfo)), None)
        return ci is not None and ci.txnId == self.txn_id

    def _resolve_conflict(self, winners, attempt_version: int, latest: int,
                          winners_ict: Optional[int], report, asp
                          ) -> Optional[int]:
        """Genuine lost race: check logical conflicts against every
        winner and fold their in-commit timestamps into the rebase
        (delegated to the shared `ConflictSetEngine` — the group
        committer runs the same machinery per batch member). Returns
        the updated ``winners_ict``; raises when the loss is not
        retryable."""
        from delta_tpu.txn.conflictset import ConflictSetEngine

        with obs.span("txn.conflict_check",
                      lost_version=attempt_version,
                      winners=latest - attempt_version + 1):
            try:
                res = ConflictSetEngine(winners).resolve(
                    self._read_state(), attempt_version - 1,
                    self._ict_enabled_at_read(), winners_ict)
            except Exception:
                report(None, False)
                raise
        if res.row_id_high_watermark is not None:
            self._winners_row_watermark = max(
                self._winners_row_watermark or -1,
                res.row_id_high_watermark,
            )
        # no backoff sleep today: rebase work itself spaces the
        # retries; the attr keeps trace shape stable if one lands
        asp.set_attrs(rebased_to=latest + 1, backoff_ms=0)
        return res.winners_ict

    def _latest_version(self, engine, log_path: str, at_least: int) -> int:
        latest = at_least
        prefix = filenames.listing_prefix(log_path, at_least)
        for fstat in engine.fs.list_from(prefix):
            if filenames.is_delta_file(fstat.path):
                latest = max(latest, filenames.delta_version(fstat.path))
        coordinator = self._coordinator()
        if coordinator is not None:
            latest = max(
                latest, coordinator.get_commits(log_path).latest_table_version
            )
        return latest

    def _run_post_commit_hooks(self, version: int) -> None:
        meta = self.metadata()
        from delta_tpu.hooks import PostCommitHookError, run_post_commit_hooks

        try:
            run_post_commit_hooks(self._table, self, version, meta)
        except PostCommitHookError:
            # the commit has landed; a critical hook (e.g. symlink
            # manifest) failing is a caller-visible error
            raise
        except Exception:
            # Other post-commit hooks are best-effort (reference: hook
            # failures do not fail the commit) — but their failures must
            # be observable, or checkpoint/checksum drift is silent.
            _log.warning("post-commit hook failed after commit %d "
                         "(commit is durable)", version, exc_info=True)


def _metric_str(v) -> str:
    """operationMetrics values are string-valued in the reference's
    commitInfo serialization (`SQLMetric.value.toString` — booleans as
    'true'/'false', integral floats without the trailing '.0'). Callers
    hand `set_operation_metrics` arbitrary objects; this is the one
    normalization point."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


_INVALID_NAME_CHARS = " ,;{}()\n\t="


def _check_column_name_characters(metadata: Metadata) -> None:
    """Column names containing ' ,;{}()\\n\\t=' require column mapping
    (PROTOCOL column-mapping section; the reference rejects them at
    every schema change via `SchemaUtils`). Checked at the
    update_metadata choke point so CREATE, ALTER ADD COLUMNS, and
    schema evolution all pass through it; nested struct/array/map
    fields included."""
    if metadata.configuration.get("delta.columnMapping.mode",
                                  "none") != "none":
        return
    schema = metadata.schema
    if schema is None:
        return
    from delta_tpu.models.schema import ArrayType, MapType, StructType

    bad: List[str] = []

    def walk(dt, prefix: str) -> None:
        if isinstance(dt, StructType):
            for f in dt.fields:
                name = f"{prefix}.{f.name}" if prefix else f.name
                if any(c in f.name for c in _INVALID_NAME_CHARS):
                    bad.append(name)
                walk(f.dataType, name)
        elif isinstance(dt, ArrayType):
            walk(dt.elementType, prefix + "[]")
        elif isinstance(dt, MapType):
            walk(dt.keyType, prefix + "{key}")
            walk(dt.valueType, prefix + "{value}")

    walk(schema, "")
    if bad:
        raise InvalidArgumentError(
            f"column name(s) {bad} contain invalid characters "
            "(' ,;{}()\\n\\t='); enable column mapping "
            "(delta.columnMapping.mode = 'name') to use them",
            error_class="DELTA_INVALID_CHARACTERS_IN_COLUMN_NAME")

from delta_tpu.txn.transaction import Transaction, TransactionBuilder, Operation
from delta_tpu.txn.isolation import IsolationLevel

__all__ = ["Transaction", "TransactionBuilder", "Operation", "IsolationLevel"]

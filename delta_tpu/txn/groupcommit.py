"""Group commit: batch compatible concurrent transactions into one
arbiter round trip.

ROADMAP item 2: at heavy multi-writer traffic the commit path
serializes on the arbiter — every transaction pays one storage round
trip and one conflict check, and losers pay them again per rebase. The
group committer amortizes both. Writers that reach ``commit()`` within
a bounded window (``DELTA_TPU_GROUP_COMMIT_WINDOW_MS``, default 2) are
queued per table; a leader drains up to
``DELTA_TPU_GROUP_COMMIT_MAX_BATCH`` members, conflict-checks the
whole batch against ONE snapshot of landed winners (the shared
``ConflictSetEngine``), assigns the accepted members consecutive
versions — each accepted member's prepared actions are appended to
the conflict set so later members are checked against earlier ones
exactly as if those had landed — and emits them as one batched write.

Per-member typed outcomes keep failure member-scoped:

- ``committed`` / ``rebased``: this member's commit is durable at
  ``outcome.version`` (rebased when that is above its read version).
- ``rejected``: the member logically conflicts (typed
  ``ConcurrentModificationError`` from the checker) with a landed
  winner or an earlier batch member. It degrades to the solo retry
  path — never fails the batch — because the batch-mate it lost to
  might itself fail to land; the solo path re-resolves against what is
  actually on disk and raises the genuine typed error if the conflict
  is real.
- ``solo``: the emit outcome for this member is unknown or negative
  (lost race, transport error, ambiguous ack). The member re-enters
  the solo loop where PR 5b self-commit recovery (CommitInfo.txnId
  compare) resolves ambiguity without duplicating data.

Ambiguity ladder on emit failure: per-member read-back of the assigned
version compares ``txnId`` (the per-member analogue of solo
self-commit recovery — this is what `ChaosStore.ack_loss_rate` on the
batched path exercises); members proven landed are committed, everyone
else degrades to solo. Read-back errors also degrade to solo — safe,
because the solo path's own self-commit detection is the backstop.

Breaker/deadline scopes apply at batch granularity: the leader's one
emit runs under the ``commit-coordinator`` breaker (coordinated
tables) or the storage `io_call` breaker (logstore tables), and under
the LEADER's ambient deadline. A waiter's own deadline is honoured at
member granularity: while still un-sealed in the queue it retracts and
raises ``DeadlineExceededError``; once its batch is sealed it waits
for the (bounded) emit to finish.

Disabled by default (``DELTA_TPU_GROUP_COMMIT=1`` to enable): solo
commits must not pay the window latency unless a deployment opts in.
"""
# delta-lint: file-disable=shared-state-race — audited:
# GroupCommitter is the one intentionally shared object on the commit
# path. Every access to the queue/leader flag is under self._lock;
# member outcome/lead_now/sealed hand-offs are published under the
# same lock or before the member's Event is set (the Event is the
# happens-before edge). Member transactions themselves stay
# thread-confined: the leader only touches a member txn between seal
# and outcome-set, while its owning thread is parked in submit().

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from delta_tpu import obs
from delta_tpu.errors import ConcurrentModificationError, DeltaError
from delta_tpu.models.actions import actions_to_commit_bytes
from delta_tpu.resilience.deadline import check_deadline, expired
from delta_tpu.txn.conflict import WinningCommit
from delta_tpu.txn.conflictset import ConflictSetEngine
from delta_tpu.utils import filenames

_log = logging.getLogger(__name__)

_GROUP_BATCHES = obs.counter("txn.group_commit.batches")
_GROUP_MEMBERS = obs.counter("txn.group_commit.members")
_GROUP_REJECTED = obs.counter("txn.group_commit.rejected")
_GROUP_SOLO = obs.counter("txn.group_commit.solo_degraded")
_GROUP_READBACK = obs.counter("txn.group_commit.readback_recovered")
_GROUP_SIZE = obs.histogram("txn.group_commit.batch_size")
_GROUP_WAIT = obs.histogram("txn.group_commit.wait_ms")

# outcome kinds
COMMITTED = "committed"
REBASED = "rebased"
REJECTED = "rejected"
SOLO = "solo"

_TRUTHY = ("1", "on", "true", "yes")


def group_commit_enabled() -> bool:
    return os.environ.get("DELTA_TPU_GROUP_COMMIT",
                          "").strip().lower() in _TRUTHY


def group_commit_window_s() -> float:
    return float(os.environ.get("DELTA_TPU_GROUP_COMMIT_WINDOW_MS",
                                "2")) / 1000.0


def group_commit_max_batch() -> int:
    return max(1, int(os.environ.get("DELTA_TPU_GROUP_COMMIT_MAX_BATCH",
                                     "16")))


@dataclass
class MemberOutcome:
    """What the batch decided for one member transaction."""

    kind: str  # COMMITTED | REBASED | REJECTED | SOLO
    version: Optional[int] = None
    data: Optional[bytes] = None
    error: Optional[BaseException] = None


class _Member:
    __slots__ = ("txn", "event", "outcome", "sealed", "lead_now")

    def __init__(self, txn):
        self.txn = txn
        self.event = threading.Event()
        self.outcome: Optional[MemberOutcome] = None
        self.sealed = False      # drained into a batch; must wait
        self.lead_now = False    # baton: this member leads the next batch


class GroupCommitter:
    """Per-table batching point for concurrent committers."""

    def __init__(self, table, window_s: Optional[float] = None,
                 max_batch: Optional[int] = None):
        self._table = table
        self._window_s = (window_s if window_s is not None
                          else group_commit_window_s())
        self._max_batch = (max_batch if max_batch is not None
                           else group_commit_max_batch())
        self._lock = threading.Lock()
        self._queue: List[_Member] = []
        self._leader_active = False

    # ------------------------------------------------------------ entry
    def submit(self, txn) -> MemberOutcome:
        """Queue ``txn`` for the next batch and block until its
        outcome is decided. The first member to arrive while no leader
        is active becomes the leader; the baton passes to a queued
        member whenever the queue is non-empty after an emit."""
        m = _Member(txn)
        t0 = time.perf_counter()
        with self._lock:
            self._queue.append(m)
            if not self._leader_active:
                self._leader_active = True
                m.lead_now = True
        while m.outcome is None:
            if m.lead_now:
                m.lead_now = False
                self._lead()
                continue
            m.event.wait(timeout=0.05)
            m.event.clear()
            if m.outcome is not None or m.lead_now:
                continue
            if expired():
                # member-granularity deadline: retract while still
                # un-sealed; once sealed the emit is already paying for
                # us, so wait it out (it is bounded by the leader's own
                # deadline/breaker)
                with self._lock:
                    retract = not m.sealed and m in self._queue
                    if retract:
                        self._queue.remove(m)
                if retract:
                    check_deadline("group-commit wait")
        _GROUP_WAIT.observe((time.perf_counter() - t0) * 1000.0)
        return m.outcome

    # ----------------------------------------------------------- leader
    def _lead(self) -> None:
        time.sleep(self._window_s)  # accumulation window
        with self._lock:
            batch = self._queue[: self._max_batch]
            del self._queue[: len(batch)]
            for m in batch:
                m.sealed = True
        try:
            if batch:
                self._emit(batch)
        finally:
            with self._lock:
                if self._queue:
                    nxt = self._queue[0]
                    nxt.lead_now = True
                    nxt.event.set()
                else:
                    self._leader_active = False

    def _emit(self, batch: List[_Member]) -> None:
        try:
            with obs.span("txn.group_commit", table=self._table.path,
                          members=len(batch)) as sp:
                self._emit_inner(batch, sp)
        except Exception:
            # Safety net, not a handler: per-member outcomes (including
            # every ConcurrentModificationError) were assigned inside
            # _emit_inner. Anything reaching here is an engine bug or
            # environmental failure — log it and degrade the still
            # undecided members to the solo path, which re-resolves
            # from durable state.
            _log.warning("group-commit emit failed; undecided members "
                         "degrade to solo", exc_info=True)
        finally:
            for m in batch:
                if m.outcome is None:
                    m.outcome = MemberOutcome(SOLO)
                    _GROUP_SOLO.inc()
                m.event.set()

    def _emit_inner(self, batch: List[_Member], sp) -> None:
        engine = self._table.engine
        log_path = self._table.log_path
        lead = batch[0].txn
        min_read = min(m.txn.read_version for m in batch)
        latest = lead._latest_version(engine, log_path, min_read)
        winners = []
        if latest > min_read:
            winners = lead._read_commit_range(engine, log_path,
                                              min_read + 1, latest)
        cs = ConflictSetEngine(winners)
        accepted = []  # (member, assigned version, serialized bytes)
        next_version = latest + 1
        for m in batch:
            txn = m.txn
            try:
                res = cs.resolve(txn._read_state(), txn.read_version,
                                 txn._ict_enabled_at_read())
            except ConcurrentModificationError as e:
                # reject ONLY the loser; it degrades to the solo retry
                # path (never the batch) — the batch-mate it lost to
                # may itself fail to land, so the solo re-check against
                # durable state is what makes the rejection final
                m.outcome = MemberOutcome(REJECTED, error=e)
                _GROUP_REJECTED.inc()
                continue
            if res.row_id_high_watermark is not None:
                txn._winners_row_watermark = max(
                    txn._winners_row_watermark or -1,
                    res.row_id_high_watermark)
            assigned = next_version
            try:
                acts = txn._prepare_actions(assigned, res.winners_ict)
            except DeltaError as e:
                # deterministic validation failure (not a race): let
                # the solo path surface the identical error to the
                # member's own thread
                m.outcome = MemberOutcome(SOLO, error=e)
                _GROUP_SOLO.inc()
                continue
            data = actions_to_commit_bytes(acts)
            cs.extend(WinningCommit(assigned, acts))
            accepted.append((m, assigned, data))
            next_version += 1
        sp.set_attrs(accepted=len(accepted),
                     rejected=len(batch) - len(accepted),
                     base_version=latest)
        if not accepted:
            return
        try:
            self._emit_writes(engine, log_path, accepted)
        except Exception as e:
            sp.set_attr("emit_error", type(e).__name__)
            self._resolve_by_readback(engine, log_path, accepted, e)
        else:
            for m, v, data in accepted:
                kind = COMMITTED if v == m.txn.read_version + 1 else REBASED
                m.outcome = MemberOutcome(kind, version=v, data=data)
        _GROUP_BATCHES.inc()
        _GROUP_MEMBERS.inc(len(accepted))
        _GROUP_SIZE.observe(len(accepted))

    def _emit_writes(self, engine, log_path: str, accepted) -> None:
        """One batched write for the accepted run. Coordinated tables
        go through `commit_batch` under the commit-coordinator breaker;
        logstore tables through the engine's batched atomic-put (which
        `ExternalArbiterLogStore` turns into one claim round trip).
        Raises on any non-success — per-member fates are then resolved
        by read-back, never assumed."""
        coordinator = accepted[0][0].txn._coordinator()
        if coordinator is not None:
            from delta_tpu.coordinatedcommits import CommitFailedException
            from delta_tpu.resilience import breaker_for, default_policy

            ts = int(time.time() * 1000)
            commits = [(v, data) for _, v, data in accepted]
            try:
                default_policy().call(
                    lambda: coordinator.commit_batch(log_path, commits, ts),
                    breaker=breaker_for("commit-coordinator"))
            except CommitFailedException as e:
                raise FileExistsError(str(e)) from e
            return
        items = [(filenames.delta_file(log_path, v), data)
                 for _, v, data in accepted]
        writer = getattr(engine.json, "write_json_files_atomically", None)
        if writer is not None:
            writer(items, overwrite=False)
        else:
            for path, data in items:
                engine.json.write_json_file_atomically(path, data,
                                                       overwrite=False)

    def _resolve_by_readback(self, engine, log_path: str, accepted,
                             cause: BaseException) -> None:
        """The emit failed or was ambiguous (lost race / transport
        error / lost ack): decide each member's fate by reading back
        its assigned version and comparing ``txnId`` — the per-member
        self-commit recovery. Proven-landed members are committed;
        everyone else (including read-back failures) degrades to solo,
        where the solo loop's own self-commit detection is the final
        backstop against duplicate data."""
        for m, v, data in accepted:
            landed = False
            try:
                w = m.txn._read_commit_range(engine, log_path, v, v)[0]
                landed = m.txn._is_own_commit(w)
            except FileNotFoundError:
                landed = False
            except Exception:
                _log.warning(
                    "group-commit read-back of version %d failed after "
                    "emit error (%s); degrading member to solo",
                    v, cause, exc_info=True)
                m.outcome = MemberOutcome(SOLO, error=cause)
                _GROUP_SOLO.inc()
                continue
            if landed:
                kind = COMMITTED if v == m.txn.read_version + 1 else REBASED
                m.outcome = MemberOutcome(kind, version=v, data=data)
                _GROUP_READBACK.inc()
                obs.add_event("txn.group_commit.readback_recovered",
                              version=v)
            else:
                m.outcome = MemberOutcome(SOLO, error=cause)
                _GROUP_SOLO.inc()


def group_committer_for(table) -> Optional[GroupCommitter]:
    """The table's lazily-attached group committer, or None when group
    commit is disabled. One committer per Table object: batching scope
    is the in-process contention domain (cross-process contention is
    what the arbiter itself serializes)."""
    if not group_commit_enabled():
        return None
    with table._lock:
        gc = getattr(table, "_group_committer", None)
        if gc is None:
            gc = GroupCommitter(table)
            table._group_committer = gc
    return gc

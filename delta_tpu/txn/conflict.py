"""Conflict detection against winning commits (optimistic-concurrency
rebase).

Semantics follow `ConflictChecker.scala:175` / kernel
`internal/replay/ConflictChecker.java:98`: after losing the put-if-absent
race at version v, read the winning commit files [v, latest] and check, in
order:

1. protocol change by winner        → ProtocolChangedError
2. metadata change by winner        → MetadataChangedError
3. winner's added files visible to our read predicates
   (per isolation level)            → ConcurrentAppendError
4. winner removed a file we read    → ConcurrentDeleteReadError
5. winner removed a file we remove  → ConcurrentDeleteDeleteError
6. winner advanced an idempotent-txn appId we read
                                    → ConcurrentTransactionError
7. winner touched a metadata domain we also write
                                    → ConcurrentWriteError (domain)

If nothing conflicts, the transaction is *rebased*: it may retry at
latest+1 (and must fold the winners' SetTransactions into its own
read-state for the next round).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Set

from delta_tpu.errors import (
    ConcurrentAppendError,
    ConcurrentDeleteDeleteError,
    ConcurrentDeleteReadError,
    ConcurrentTransactionError,
    ConcurrentWriteError,
    MetadataChangedError,
    ProtocolChangedError,
)
from delta_tpu.expressions.tree import Expression, split_conjuncts
from delta_tpu.models.actions import (
    Action,
    AddFile,
    DomainMetadata,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
    actions_from_commit_bytes,
)
from delta_tpu.txn.isolation import IsolationLevel
from delta_tpu.utils import filenames

_log = logging.getLogger(__name__)


@dataclass
class WinningCommit:
    version: int
    actions: List[Action]

    @property
    def is_blind_append(self) -> bool:
        from delta_tpu.models.actions import CommitInfo

        for a in self.actions:
            if isinstance(a, CommitInfo) and a.isBlindAppend is not None:
                return bool(a.isBlindAppend)
        # conservatively not blind if it contains removes or reads
        return not any(isinstance(a, RemoveFile) for a in self.actions)


@dataclass
class TransactionReadState:
    """What the losing transaction read + intends to write."""

    read_predicates: List[Expression] = field(default_factory=list)
    read_whole_table: bool = False
    read_files: Set[tuple] = field(default_factory=set)       # (path, dv_id)
    read_app_ids: Set[str] = field(default_factory=set)
    removed_keys: Set[tuple] = field(default_factory=set)     # (path, dv_id)
    written_domains: Set[str] = field(default_factory=set)
    metadata_changed: bool = False
    protocol_changed: bool = False
    partition_columns: List[str] = field(default_factory=list)
    isolation: IsolationLevel = IsolationLevel.WRITE_SERIALIZABLE
    metadata: Optional[Metadata] = None  # for column-mapping-aware stats


def read_winning_commits(fs, log_path: str, from_version: int, to_version: int) -> List[WinningCommit]:
    out = []
    for v in range(from_version, to_version + 1):
        data = fs.read_file(filenames.delta_file(log_path, v))
        out.append(WinningCommit(v, actions_from_commit_bytes(data)))
    return out


def _matching_adds(adds: Sequence[AddFile],
                   state: TransactionReadState):
    """Boolean may-match mask over the winner's AddFiles, evaluated
    VECTORIZED per conjunct (one `skipping_mask` / partition-batch
    call over all files — `ConflictChecker.scala:584` consults the
    same skipping index on the winner's files DataFrame).

    Per predicate (a conjunction): any conjunct DISPROVED — exactly,
    against partitionValues, for partition-only conjuncts; via
    min/max/nullCount stats for data conjuncts — disproves the whole
    predicate for that file. A file may-match only if no conjunct of
    some read predicate is disproved for it. Unevaluable conjuncts
    widen to true (`ConflictCheckerPredicateElimination.scala:30`
    semantics: dropping a conjunct only over-approximates the match
    set — the safe direction). Missing stats keep the file
    (conservative)."""
    import numpy as np

    n = len(adds)
    if state.read_whole_table:
        return np.ones(n, bool)
    if not state.read_predicates:
        return np.zeros(n, bool)
    import pyarrow as pa

    from delta_tpu.expressions.eval import evaluate_predicate_host
    from delta_tpu.stats.partition import partition_values_to_batch
    from delta_tpu.stats.skipping import skipping_mask

    pcols = set(state.partition_columns)
    pbatch = None
    stats_files = pa.table({
        "stats": pa.array([a.stats for a in adds], pa.string())})

    may = np.zeros(n, bool)
    for pred in state.read_predicates:
        alive = np.ones(n, bool)
        for conj in split_conjuncts(pred):
            refs = conj.references()
            if refs and all(r[0] in pcols for r in refs):
                if pbatch is None:
                    pbatch = partition_values_to_batch(
                        [a.partitionValues for a in adds],
                        state.partition_columns)
                try:
                    res = np.asarray(
                        evaluate_predicate_host(conj, pbatch),
                        dtype=bool)
                    alive &= res
                except Exception as e:
                    # can't evaluate exactly -> widen to true (sound:
                    # over-approximating visibility only adds conflicts)
                    _log.debug("partition predicate unevaluable for "
                               "conflict check, widening: %s", e)
            else:
                try:
                    alive &= skipping_mask(stats_files, [conj],
                                           state.metadata)
                except Exception as e:
                    # unevaluable -> widen to true (same soundness)
                    _log.debug("stats predicate unevaluable for "
                               "conflict check, widening: %s", e)
        may |= alive
        if may.all():
            break
    return may


def check_conflicts(
    state: TransactionReadState,
    winners: Sequence[WinningCommit],
) -> dict:
    """Raises a ConcurrentModificationError subclass on logical conflict;
    otherwise returns the rebase info {'txn_versions': {appId: version}}.
    """
    rebase_txns = {}
    rebase_row_watermark: List[int] = []
    for w in winners:
        blind = w.is_blind_append
        # check order per the module docstring: protocol, metadata,
        # then appends (batched), then the per-action checks
        for a in w.actions:
            if isinstance(a, Protocol):
                raise ProtocolChangedError(
                    f"protocol changed by concurrent commit {w.version}"
                )
            if isinstance(a, Metadata):
                raise MetadataChangedError(
                    f"metadata changed by concurrent commit {w.version}"
                )
        check_appends = (
            state.isolation == IsolationLevel.SERIALIZABLE
            or (state.isolation == IsolationLevel.WRITE_SERIALIZABLE
                and not blind)
        )
        adds = [a for a in w.actions if isinstance(a, AddFile)] \
            if check_appends else []
        if adds:
            mask = _matching_adds(adds, state)
            if mask.any():
                first = adds[int(mask.argmax())]
                raise ConcurrentAppendError(
                    f"files added by concurrent commit {w.version} may "
                    f"match this transaction's read predicate: "
                    f"{first.path}"
                )
        for a in w.actions:
            if isinstance(a, RemoveFile):
                key = (a.path, a.dv_unique_id)
                if key in state.read_files:
                    raise ConcurrentDeleteReadError(
                        f"file read by this transaction was removed by "
                        f"concurrent commit {w.version}: {a.path}"
                    )
                if key in state.removed_keys:
                    raise ConcurrentDeleteDeleteError(
                        f"file removed by both this transaction and "
                        f"concurrent commit {w.version}: {a.path}"
                    )
            if isinstance(a, SetTransaction):
                if a.appId in state.read_app_ids:
                    raise ConcurrentTransactionError(
                        f"idempotent-transaction appId {a.appId} advanced by "
                        f"concurrent commit {w.version}"
                    )
                rebase_txns[a.appId] = a.version
            if isinstance(a, DomainMetadata):
                from delta_tpu.rowtracking import (
                    ROW_TRACKING_DOMAIN,
                    watermark_from_domain,
                )

                if a.domain == ROW_TRACKING_DOMAIN:
                    # system domain: auto-resolved by folding the winner's
                    # watermark and reassigning ids on rebase
                    rebase_row_watermark.append(watermark_from_domain(a))
                    continue
                if a.domain in state.written_domains:
                    raise ConcurrentWriteError(
                        f"metadata domain {a.domain!r} modified by concurrent "
                        f"commit {w.version}"
                    )
    return {
        "txn_versions": rebase_txns,
        "row_id_high_watermark": (
            max(rebase_row_watermark) if rebase_row_watermark else None
        ),
    }

"""Conflict detection against winning commits (optimistic-concurrency
rebase).

Semantics follow `ConflictChecker.scala:175` / kernel
`internal/replay/ConflictChecker.java:98`: after losing the put-if-absent
race at version v, read the winning commit files [v, latest] and check, in
order:

1. protocol change by winner        → ProtocolChangedError
2. metadata change by winner        → MetadataChangedError
3. winner's added files visible to our read predicates
   (per isolation level)            → ConcurrentAppendError
4. winner removed a file we read    → ConcurrentDeleteReadError
5. winner removed a file we remove  → ConcurrentDeleteDeleteError
6. winner advanced an idempotent-txn appId we read
                                    → ConcurrentTransactionError
7. winner touched a metadata domain we also write
                                    → ConcurrentWriteError (domain)

If nothing conflicts, the transaction is *rebased*: it may retry at
latest+1 (and must fold the winners' SetTransactions into its own
read-state for the next round).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Set

from delta_tpu.errors import (
    ConcurrentAppendError,
    ConcurrentDeleteDeleteError,
    ConcurrentDeleteReadError,
    ConcurrentTransactionError,
    ConcurrentWriteError,
    MetadataChangedError,
    ProtocolChangedError,
)
from delta_tpu.expressions.tree import Expression, split_conjuncts
from delta_tpu.models.actions import (
    Action,
    AddFile,
    DomainMetadata,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
    actions_from_commit_bytes,
)
from delta_tpu.txn.isolation import IsolationLevel
from delta_tpu.utils import filenames


@dataclass
class WinningCommit:
    version: int
    actions: List[Action]

    @property
    def is_blind_append(self) -> bool:
        from delta_tpu.models.actions import CommitInfo

        for a in self.actions:
            if isinstance(a, CommitInfo) and a.isBlindAppend is not None:
                return bool(a.isBlindAppend)
        # conservatively not blind if it contains removes or reads
        return not any(isinstance(a, RemoveFile) for a in self.actions)


@dataclass
class TransactionReadState:
    """What the losing transaction read + intends to write."""

    read_predicates: List[Expression] = field(default_factory=list)
    read_whole_table: bool = False
    read_files: Set[tuple] = field(default_factory=set)       # (path, dv_id)
    read_app_ids: Set[str] = field(default_factory=set)
    removed_keys: Set[tuple] = field(default_factory=set)     # (path, dv_id)
    written_domains: Set[str] = field(default_factory=set)
    metadata_changed: bool = False
    protocol_changed: bool = False
    partition_columns: List[str] = field(default_factory=list)
    isolation: IsolationLevel = IsolationLevel.WRITE_SERIALIZABLE


def read_winning_commits(fs, log_path: str, from_version: int, to_version: int) -> List[WinningCommit]:
    out = []
    for v in range(from_version, to_version + 1):
        data = fs.read_file(filenames.delta_file(log_path, v))
        out.append(WinningCommit(v, actions_from_commit_bytes(data)))
    return out


def _add_matches_predicates(add: AddFile, state: TransactionReadState) -> bool:
    """Could this added file have matched any of our read predicates?
    Partition-only conjuncts are evaluated exactly against the file's
    partitionValues; anything else conservatively matches (the reference
    evaluates against stats when available, else conservatively)."""
    if state.read_whole_table:
        return True
    if not state.read_predicates:
        return False
    import pyarrow as pa

    from delta_tpu.expressions.eval import evaluate_predicate_host
    from delta_tpu.stats.partition import partition_values_to_batch

    pcols = set(state.partition_columns)
    for pred in state.read_predicates:
        for conj in split_conjuncts(pred):
            refs = conj.references()
            if refs and all(r[0] in pcols for r in refs):
                batch = partition_values_to_batch(
                    [add.partitionValues], state.partition_columns
                )
                try:
                    if bool(evaluate_predicate_host(conj, batch)[0]):
                        return True
                except Exception:
                    return True  # can't evaluate exactly -> conservative
            else:
                return True  # non-partition predicate: can't disprove overlap
    return False


def check_conflicts(
    state: TransactionReadState,
    winners: Sequence[WinningCommit],
) -> dict:
    """Raises a ConcurrentModificationError subclass on logical conflict;
    otherwise returns the rebase info {'txn_versions': {appId: version}}.
    """
    rebase_txns = {}
    rebase_row_watermark: List[int] = []
    for w in winners:
        blind = w.is_blind_append
        for a in w.actions:
            if isinstance(a, Protocol):
                raise ProtocolChangedError(
                    f"protocol changed by concurrent commit {w.version}"
                )
            if isinstance(a, Metadata):
                raise MetadataChangedError(
                    f"metadata changed by concurrent commit {w.version}"
                )
            if isinstance(a, AddFile):
                check_appends = (
                    state.isolation == IsolationLevel.SERIALIZABLE
                    or (state.isolation == IsolationLevel.WRITE_SERIALIZABLE and not blind)
                )
                if check_appends and _add_matches_predicates(a, state):
                    raise ConcurrentAppendError(
                        f"files added by concurrent commit {w.version} may "
                        f"match this transaction's read predicate: {a.path}"
                    )
            if isinstance(a, RemoveFile):
                key = (a.path, a.dv_unique_id)
                if key in state.read_files:
                    raise ConcurrentDeleteReadError(
                        f"file read by this transaction was removed by "
                        f"concurrent commit {w.version}: {a.path}"
                    )
                if key in state.removed_keys:
                    raise ConcurrentDeleteDeleteError(
                        f"file removed by both this transaction and "
                        f"concurrent commit {w.version}: {a.path}"
                    )
            if isinstance(a, SetTransaction):
                if a.appId in state.read_app_ids:
                    raise ConcurrentTransactionError(
                        f"idempotent-transaction appId {a.appId} advanced by "
                        f"concurrent commit {w.version}"
                    )
                rebase_txns[a.appId] = a.version
            if isinstance(a, DomainMetadata):
                from delta_tpu.rowtracking import (
                    ROW_TRACKING_DOMAIN,
                    watermark_from_domain,
                )

                if a.domain == ROW_TRACKING_DOMAIN:
                    # system domain: auto-resolved by folding the winner's
                    # watermark and reassigning ids on rebase
                    rebase_row_watermark.append(watermark_from_domain(a))
                    continue
                if a.domain in state.written_domains:
                    raise ConcurrentWriteError(
                        f"metadata domain {a.domain!r} modified by concurrent "
                        f"commit {w.version}"
                    )
    return {
        "txn_versions": rebase_txns,
        "row_id_high_watermark": (
            max(rebase_row_watermark) if rebase_row_watermark else None
        ),
    }

"""MERGE INTO: upserts with matched / not-matched / not-matched-by-source
clauses.

Reference `commands/MergeIntoCommand.scala` + `commands/merge/
ClassicMergeExecutor.scala`: find touched files via a join of the source
against the target on the merge condition, rewrite those files applying
clause actions row-wise (first matching clause wins), append inserts,
enforce the at-most-one-source-match cardinality rule, emit CDC rows.

API (mirrors `DeltaMergeBuilder`):

    (MergeBuilder(table, source, on=(col("target.id") == col("source.id")))
        .when_matched_update(set={"v": col("source.v")})
        .when_matched_delete(condition=col("source.op") == lit("del"))
        .when_not_matched_insert(values={"id": col("source.id"), ...})
        .when_not_matched_by_source_delete()
        .execute())

Conditions and values are expressions over a namespaced batch: columns of
the target are `target.<name>`, of the source `source.<name>`.
"""
# delta-lint: file-disable=shared-state-race — audited:
# MergeBuilder is a per-operation fluent builder: it is created,
# mutated, and executed by the single thread running the MERGE —
# sharing one across threads is outside its contract (matching the
# reference's DeltaMergeBuilder).

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import pandas as pd
import pyarrow as pa

from delta_tpu.config import ENABLE_CDF, cdf_enabled, get_table_config
from delta_tpu.errors import DeltaError, InvalidArgumentError, MissingTransactionLogError
from delta_tpu.expressions.tree import (
    And,
    Column,
    Comparison,
    Expression,
    split_conjuncts,
)
from delta_tpu.txn.transaction import Operation
from delta_tpu.write.writer import write_data_files


class MergeCardinalityError(DeltaError):
    error_class = "DELTA_MULTIPLE_SOURCE_ROW_MATCHING_TARGET_ROW_IN_MERGE"


@dataclass
class MergeClause:
    kind: str  # update | delete | insert
    condition: Optional[Expression] = None
    assignments: Optional[Dict[str, object]] = None  # update/insert values


# pure equi-joins at/above this many combined rows route through
# the device sort/segment join (ops/join.py); tests lower it to 0
DEVICE_JOIN_MIN_ROWS = 65_536


@dataclass
class MergeMetrics:
    num_target_rows_updated: int = 0
    num_target_rows_deleted: int = 0
    num_target_rows_inserted: int = 0
    num_target_rows_copied: int = 0
    num_target_files_rewritten: int = 0
    num_target_files_scanned: int = 0
    num_source_rows: int = 0
    version: Optional[int] = None


class MergeBuilder:
    def __init__(self, table, source: pa.Table, on: Expression):
        self._table = table
        self._source = source
        self._on = on
        self._matched: List[MergeClause] = []
        self._not_matched: List[MergeClause] = []
        self._not_matched_by_source: List[MergeClause] = []
        self._schema_evolution = False

    def with_schema_evolution(self):
        """Evolve the target schema with source-only columns (the
        reference's `withSchemaEvolution()`); without it, extra source
        columns in *All clauses are an error."""
        self._schema_evolution = True
        return self

    def when_matched_update(self, set: Dict[str, object], condition=None):
        self._matched.append(MergeClause("update", condition, dict(set)))
        return self

    def when_matched_update_all(self, condition=None):
        self._matched.append(MergeClause("update", condition, None))
        return self

    def when_matched_delete(self, condition=None):
        self._matched.append(MergeClause("delete", condition))
        return self

    def when_not_matched_insert(self, values: Dict[str, object], condition=None):
        self._not_matched.append(MergeClause("insert", condition, dict(values)))
        return self

    def when_not_matched_insert_all(self, condition=None):
        self._not_matched.append(MergeClause("insert", condition, None))
        return self

    def when_not_matched_by_source_update(self, set: Dict[str, object], condition=None):
        self._not_matched_by_source.append(MergeClause("update", condition, dict(set)))
        return self

    def when_not_matched_by_source_delete(self, condition=None):
        self._not_matched_by_source.append(MergeClause("delete", condition))
        return self

    def execute(self) -> MergeMetrics:
        self._validate_clauses()
        return _execute_merge(
            self._table, self._source, self._on,
            self._matched, self._not_matched, self._not_matched_by_source,
            schema_evolution=self._schema_evolution,
        )

    def _validate_clauses(self) -> None:
        """Reference analysis rules: a MERGE needs at least one WHEN
        clause (`DELTA_MERGE_MISSING_WHEN`), and within each clause
        family only the LAST clause may omit its condition — an
        unconditional non-last clause would shadow everything after it
        (`DELTA_NON_LAST_MATCHED_CLAUSE_OMIT_CONDITION` family)."""
        if not (self._matched or self._not_matched
                or self._not_matched_by_source):
            raise InvalidArgumentError(
                "MERGE requires at least one WHEN clause",
                error_class="DELTA_MERGE_MISSING_WHEN")
        for clauses, ec in (
                (self._matched,
                 "DELTA_NON_LAST_MATCHED_CLAUSE_OMIT_CONDITION"),
                (self._not_matched,
                 "DELTA_NON_LAST_NOT_MATCHED_CLAUSE_OMIT_CONDITION"),
                (self._not_matched_by_source,
                 "DELTA_NON_LAST_NOT_MATCHED_BY_SOURCE_CLAUSE_OMIT_CONDITION")):
            for c in clauses[:-1]:
                if c.condition is None:
                    raise InvalidArgumentError(
                        "only the last clause of its kind may omit a "
                        "condition; an unconditional earlier clause "
                        "would shadow the rest", error_class=ec)


def merge(table, source: pa.Table, on: Expression) -> MergeBuilder:
    return MergeBuilder(table, source, on)


def _source_key_bounds(t_keys: List[str], s_keys: List[str],
                       source: pa.Table) -> Optional[Expression]:
    """AND of per-key [min, max] range predicates over the target equi-key
    columns, computed from the source — a safe superset of the matchable
    rows (NULL keys never equi-match, so dropping them keeps the bound
    valid). None when no key yields a usable bound."""
    import pyarrow.compute as pc

    from delta_tpu.expressions.tree import Literal
    from delta_tpu.stats.collection import _supports_minmax

    conjuncts: List[Expression] = []
    for t_key, s_key in zip(t_keys, s_keys):
        if "." in t_key or s_key not in source.column_names:
            continue  # nested targets: skip (no bound, still correct)
        col_arr = source.column(s_key)
        if not _supports_minmax(col_arr.type):
            continue
        if col_arr.null_count == len(col_arr):
            continue
        if pa.types.is_floating(col_arr.type):
            flat = (col_arr.combine_chunks()
                    if isinstance(col_arr, pa.ChunkedArray) else col_arr)
            if pc.any(pc.is_nan(pc.drop_null(flat))).as_py():
                # NaN source keys CAN match NaN target rows (Spark
                # NaN = NaN is true), but min_max skips NaNs — a range
                # bound would wrongly prune all-NaN target files
                continue
        mm = pc.min_max(col_arr)
        mn, mx = mm["min"].as_py(), mm["max"].as_py()
        if mn is None or mx is None:
            continue
        target_col = Column((t_key,))
        conjuncts.append(Comparison(">=", target_col, Literal(mn)))
        conjuncts.append(Comparison("<=", target_col, Literal(mx)))
    if not conjuncts:
        return None
    pred = conjuncts[0]
    for c in conjuncts[1:]:
        pred = pred & c
    return pred


def _equi_keys(on: Expression) -> tuple[List[str], List[str], List[Expression]]:
    """Split the ON condition into target/source equi-key pairs + residual
    conjuncts (the join fast path; residual evaluated per candidate pair)."""
    t_keys, s_keys, residual = [], [], []
    for conj in split_conjuncts(on):
        if isinstance(conj, Comparison) and conj.op == "=":
            sides = [conj.left, conj.right]
            if all(isinstance(s, Column) for s in sides):
                roots = {s.name_path[0] for s in sides}
                if roots == {"target", "source"}:
                    t = next(s for s in sides if s.name_path[0] == "target")
                    s = next(s for s in sides if s.name_path[0] == "source")
                    t_keys.append(".".join(t.name_path[1:]))
                    s_keys.append(".".join(s.name_path[1:]))
                    continue
        residual.append(conj)
    return t_keys, s_keys, residual


def _namespaced_batch(target: pa.Table, source: pa.Table) -> pa.Table:
    """Rows side by side as struct columns `target` / `source`."""
    cols = {}
    for name, tbl in (("target", target), ("source", source)):
        arrays = [tbl.column(c).combine_chunks() for c in tbl.column_names]
        cols[name] = pa.StructArray.from_arrays(arrays, names=tbl.column_names)
    return pa.table(cols)


def _eval_values(
    assignments: Optional[Dict[str, object]],
    batch: pa.Table,
    target_schema: pa.Schema,
    source_prefix_ok: bool,
) -> pa.Table:
    """Materialize clause output rows (full target schema)."""
    from delta_tpu.expressions.eval import evaluate_host
    import pyarrow.compute as pc

    n = batch.num_rows
    out = {}
    if assignments is None:
        # hoisted: one combine + name map for the whole clause batch
        s_struct_all = batch.column("source").combine_chunks()
        by_lower_all = {sn.lower(): sn for sn in s_struct_all.type.names}
    else:
        # case-collision duplicates are rejected at analysis time in
        # _execute_merge, so lower-casing here cannot silently collapse
        amap = {k.lower(): v for k, v in assignments.items()}
    for f in target_schema:
        if assignments is not None and f.name.lower() in amap:
            v = amap[f.name.lower()]
            if isinstance(v, Expression):
                arr = evaluate_host(v, batch)
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.combine_chunks()
                arr = arr.cast(f.type, safe=False)
            else:
                arr = pa.array([v] * n, f.type)
        elif assignments is None:
            # UPDATE * / INSERT *: take the source column of the same
            # name — resolved case-insensitively, like the reference
            # analyzer (a source 'ID' feeds a target 'id')
            actual = by_lower_all.get(f.name.lower())
            if actual is None:
                arr = pa.nulls(n, f.type)
            else:
                arr = pc.struct_field(s_struct_all, actual).cast(
                    f.type, safe=False)
        else:
            # unassigned target column keeps its current value (update) or
            # null (insert — no target side present)
            tcol = batch.column("target").combine_chunks()
            if f.name in tcol.type.names:
                arr = pc.struct_field(tcol, f.name).cast(f.type, safe=False)
            else:
                arr = pa.nulls(n, f.type)
        out[f.name] = arr
    return pa.table(out)


def _execute_merge(
    table, source, on, matched, not_matched, not_matched_by_source,
    schema_evolution: bool = False,
) -> MergeMetrics:
    import pyarrow.compute as pc

    from delta_tpu.commands.dml import _read_file_with_partitions, _write_cdc
    from delta_tpu.expressions.eval import evaluate_predicate_host
    from delta_tpu.models.schema import to_arrow_schema

    txn = table.create_transaction_builder(Operation.MERGE).build()
    snapshot = txn.read_snapshot
    if snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    meta = snapshot.metadata
    use_cdc = cdf_enabled(meta.configuration)
    schema = snapshot.schema

    # new-column detection (case-insensitive, like the reference
    # analyzer): source-only columns consumed by *All clauses, plus
    # explicit assignments targeting unknown columns. Without
    # with_schema_evolution() both are errors (never silent drops).
    target_by_lower = {f.name.lower() for f in schema.fields}
    from delta_tpu.colgen import IDENTITY_START_KEY, IDENTITY_STEP_KEY

    identity_lower = {
        f.name.lower() for f in schema.fields
        if IDENTITY_START_KEY in f.metadata
        or IDENTITY_STEP_KEY in f.metadata}
    # duplicate assignments (incl. case-only collisions) are an analysis
    # error regardless of whether any row reaches the clause
    for c in (matched + not_matched + not_matched_by_source):
        if not c.assignments:
            continue
        seen: set = set()
        for k in c.assignments:
            if k.lower() in seen:
                raise InvalidArgumentError(
                    f"duplicate assignment for column '{k}' in MERGE clause",
                    error_class="DELTA_DUPLICATE_COLUMNS_ON_UPDATE_TABLE"
                )
            seen.add(k.lower())
            # UPDATE clauses must not touch identity columns (same
            # rule as dml.update — values are system-allocated);
            # INSERT clauses may, when allowExplicitInsert is set
            # (enforced downstream by apply_column_generation)
            if c.kind == "update" and k.lower() in identity_lower:
                from delta_tpu.errors import IdentityColumnError

                raise IdentityColumnError(
                    f"UPDATE on IDENTITY column {k} is not supported "
                    "in MERGE",
                    error_class="DELTA_IDENTITY_COLUMNS_UPDATE_NOT_SUPPORTED")
    # UPDATE SET * expands to an assignment per same-named source
    # column, so it would overwrite system-allocated identity values
    # just like an explicit assignment — guard it too, not only the
    # explicit-assignments loop above
    if identity_lower and any(
            c.kind == "update" and c.assignments is None
            for c in matched):
        star_hit = sorted(c for c in source.column_names
                          if c.lower() in identity_lower)
        if star_hit:
            from delta_tpu.errors import IdentityColumnError

            raise IdentityColumnError(
                f"UPDATE on IDENTITY column {star_hit[0]} is not "
                "supported in MERGE (UPDATE SET * assigns it from the "
                "source)",
                error_class="DELTA_IDENTITY_COLUMNS_UPDATE_NOT_SUPPORTED")
    extra_cols = [c for c in source.column_names
                  if c.lower() not in target_by_lower]
    has_star = any(c.assignments is None and c.kind != "delete"
                   for c in (matched + not_matched))
    unknown_assigned = sorted({
        k for c in (matched + not_matched + not_matched_by_source)
        if c.assignments
        for k in c.assignments if k.lower() not in target_by_lower})
    schema_evolved = False
    if unknown_assigned:
        source_by_lower = {c.lower() for c in source.column_names}
        missing = [k for k in unknown_assigned
                   if k.lower() not in source_by_lower]
        if missing:
            raise InvalidArgumentError(
                f"assignment target column(s) {missing} exist in neither "
                "the target schema nor the source",
                error_class="DELTA_COLUMN_NOT_FOUND_IN_MERGE")
        if not schema_evolution:
            raise InvalidArgumentError(
                f"assignment target column(s) {unknown_assigned} not in "
                "the target schema; call with_schema_evolution() to "
                "evolve the table")
    if (extra_cols and has_star and not schema_evolution):
        raise InvalidArgumentError(
            error_class="DELTA_MERGE_UNRESOLVED_EXPRESSION",
            message=f"source column(s) {extra_cols} not in the target schema; "
            "call with_schema_evolution() to evolve the table")
    if (extra_cols and has_star) or unknown_assigned:
        import dataclasses

        from delta_tpu.columnmapping import assign_column_mapping, mapping_mode
        from delta_tpu.models.schema import from_arrow_schema, schema_to_json
        from delta_tpu.schema_evolution import merge_schemas

        # evolve only the consumed source columns: all of them under a
        # *All clause, else just the explicitly assigned ones
        cols_to_add = set(extra_cols) if (extra_cols and has_star) else set()
        for k in unknown_assigned:
            cols_to_add.add(next(c for c in source.column_names
                                 if c.lower() == k.lower()))
        evolved = merge_schemas(
            schema, from_arrow_schema(source.select(sorted(cols_to_add)).schema))
        conf = dict(meta.configuration)
        if mapping_mode(conf) != "none":
            # new fields need column-mapping ids/physical names (exactly
            # as ALTER TABLE ADD COLUMNS assigns them)
            evolved, conf = assign_column_mapping(evolved, conf)
        txn.update_metadata(dataclasses.replace(
            meta, schemaString=schema_to_json(evolved),
            configuration=conf))
        meta = txn.metadata()
        schema = evolved
        schema_evolved = True
    target_arrow_schema = to_arrow_schema(schema)
    now_ms = int(time.time() * 1000)
    metrics = MergeMetrics(num_source_rows=source.num_rows)

    t_keys, s_keys, residual = _equi_keys(on)
    # source-derived file pruning (the reference's dynamic pruning via
    # MergeIntoMaterializeSource): equi-join keys bound the target rows
    # that can match, so files outside [min(source key), max(source key)]
    # are skipped entirely. Only safe when no clause touches UNmatched
    # target rows.
    scan_pred = None
    if not not_matched_by_source:
        scan_pred = _source_key_bounds(t_keys, s_keys, source)
    candidates = txn.scan_files(filter=scan_pred)
    metrics.num_target_files_scanned = len(candidates)

    # ---- load target rows with provenance ----
    from delta_tpu.commands.dml import _existing_dv_mask

    file_tables = []
    for fi, add in enumerate(candidates):
        t = _read_file_with_partitions(table, snapshot, add)
        dv_mask = _existing_dv_mask(table, add, t.num_rows)
        if dv_mask is not None:
            # rows already soft-deleted by a deletion vector are not part
            # of the table: they must neither match nor be copied into
            # rewritten files (resurrection)
            t = t.filter(pa.array(~dv_mask))
        t = t.append_column("__file", pa.array(np.full(t.num_rows, fi, np.int64)))
        t = t.append_column("__row", pa.array(np.arange(t.num_rows, dtype=np.int64)))
        file_tables.append(t)
    target_all = (
        pa.concat_tables(file_tables, promote_options="permissive")
        if file_tables
        else None
    )

    # ---- join ----
    # Pure equi-joins (no residual conjuncts) go through the device
    # sort/segment join (ops/join.py — the TPU-native shuffle-join
    # analogue of ClassicMergeExecutor); residual-predicate joins keep
    # the host pair join so the residual can disambiguate multi-matches
    # before the cardinality rule fires.
    device_matched_s = None
    if target_all is not None and target_all.num_rows and source.num_rows:
        if t_keys:
            import pyarrow.compute as _pc

            # SQL equi-join semantics: NULL keys never match — but real
            # float NaN keys DO (Spark treats NaN = NaN as true). Drop
            # only genuinely-NULL rows, using Arrow validity (after
            # to_pandas, NULL and NaN are indistinguishable).
            t_key_arrs = {k: target_all.column(k).combine_chunks()
                          for k in t_keys}
            s_key_arrs = {k: source.column(k).combine_chunks()
                          for k in s_keys}
            t_null = np.zeros(target_all.num_rows, dtype=bool)
            for k in t_keys:
                t_null |= np.asarray(_pc.is_null(t_key_arrs[k]))
            s_null = np.zeros(source.num_rows, dtype=bool)
            for k in s_keys:
                s_null |= np.asarray(_pc.is_null(s_key_arrs[k]))
            t_valid = np.nonzero(~t_null)[0]
            s_valid = np.nonzero(~s_null)[0]
            use_device = (not residual
                          and len(t_valid) + len(s_valid)
                          >= DEVICE_JOIN_MIN_ROWS)
            if use_device:
                from delta_tpu.ops.join import equi_join_device

                t_cols = [t_key_arrs[k].take(pa.array(t_valid))
                          .to_pandas().to_numpy() for k in t_keys]
                s_cols = [s_key_arrs[k].take(pa.array(s_valid))
                          .to_pandas().to_numpy() for k in s_keys]
                match_src, n_multi, _src_matched = equi_join_device(
                    t_cols, s_cols)
                if matched and n_multi:
                    raise MergeCardinalityError(
                        f"{n_multi} target row(s) matched "
                        "by multiple source rows; MERGE with update/delete "
                        "requires at most one match")
                hit = match_src >= 0
                tpos = t_valid[np.nonzero(hit)[0]]
                spos = s_valid[match_src[hit]]
                # the kernel's per-source matched flags cover duplicate-
                # key sources that never appear in a (target, source)
                # pair (legal in insert-only merges) — used below for
                # insert detection instead of unique(spos)
                device_matched_s = s_valid[np.nonzero(_src_matched)[0]]
            else:
                tdf = pd.DataFrame(
                    {k: target_all.column(k).to_pandas() for k in t_keys})
                sdf = pd.DataFrame(
                    {k: source.column(k).to_pandas() for k in s_keys})
                tdf["__tpos"] = np.arange(len(tdf))
                sdf["__spos"] = np.arange(len(sdf))
                tdf = tdf[~t_null]
                sdf = sdf[~s_null]
                joined = tdf.merge(
                    sdf, left_on=t_keys, right_on=s_keys, how="inner",
                    suffixes=("", "_s"))
                tpos = joined["__tpos"].to_numpy()
                spos = joined["__spos"].to_numpy()
        else:
            tpos, spos = np.meshgrid(
                np.arange(target_all.num_rows), np.arange(source.num_rows),
                indexing="ij",
            )
            tpos, spos = tpos.ravel(), spos.ravel()
        if residual and len(tpos):
            pair_batch = _namespaced_batch(
                target_all.take(pa.array(tpos, pa.int64())),
                source.take(pa.array(spos, pa.int64())),
            )
            keep = np.ones(len(tpos), dtype=bool)
            for conj in residual:
                keep &= evaluate_predicate_host(conj, pair_batch)
            tpos, spos = tpos[keep], spos[keep]
    else:
        tpos = np.empty(0, np.int64)
        spos = np.empty(0, np.int64)

    # ---- cardinality rule ----
    if (matched) and len(tpos):
        uniq, counts = np.unique(tpos, return_counts=True)
        if (counts > 1).any():
            raise MergeCardinalityError(
                f"{int((counts > 1).sum())} target row(s) matched by multiple "
                "source rows; MERGE with update/delete requires at most one match"
            )

    matched_t = np.unique(tpos)
    matched_s = (np.unique(spos) if device_matched_s is None
                 else device_matched_s)

    # ---- matched clause resolution (per pair; first clause wins) ----
    pair_action = np.full(len(tpos), -1, dtype=np.int64)  # index into `matched`
    if matched and len(tpos):
        pair_batch = _namespaced_batch(
            target_all.take(pa.array(tpos, pa.int64())),
            source.take(pa.array(spos, pa.int64())),
        )
        undecided = np.ones(len(tpos), dtype=bool)
        for ci, clause in enumerate(matched):
            if not undecided.any():
                break
            ok = (
                evaluate_predicate_host(clause.condition, pair_batch)
                if clause.condition is not None
                else np.ones(len(tpos), dtype=bool)
            )
            sel = undecided & ok
            pair_action[sel] = ci
            undecided &= ~sel

    # ---- build per-target-row plan (vectorized — no per-pair loop) ----
    if matched and len(tpos):
        is_del_clause = np.array([c.kind == "delete" for c in matched],
                                 dtype=bool)
        acted = pair_action >= 0
        act_clamped = np.clip(pair_action, 0, None)
        del_pair = acted & is_del_clause[act_clamped]
        upd_pair = acted & ~is_del_clause[act_clamped]
        delete_t = tpos[del_pair].astype(np.int64)
        update_t = tpos[upd_pair].astype(np.int64)   # target rows updated
        update_pi = np.nonzero(upd_pair)[0]          # their pair indices
    else:
        delete_t = np.empty(0, np.int64)
        update_t = np.empty(0, np.int64)
        update_pi = np.empty(0, np.int64)

    # ---- not-matched (insert) ----
    insert_tables = []
    if not_matched and source.num_rows:
        unmatched_mask = np.ones(source.num_rows, dtype=bool)
        unmatched_mask[matched_s] = False
        un_idx = np.nonzero(unmatched_mask)[0]
        if len(un_idx):
            sub = source.take(pa.array(un_idx, pa.int64()))
            empty_target = target_arrow_schema.empty_table()
            batch = _namespaced_batch(
                _null_target_rows(target_arrow_schema, sub.num_rows), sub
            )
            undecided = np.ones(sub.num_rows, dtype=bool)
            for clause in not_matched:
                if not undecided.any():
                    break
                ok = (
                    evaluate_predicate_host(clause.condition, batch)
                    if clause.condition is not None
                    else np.ones(sub.num_rows, dtype=bool)
                )
                sel = undecided & ok
                if sel.any():
                    rows = _eval_values(
                        clause.assignments,
                        batch.filter(pa.array(sel)),
                        target_arrow_schema,
                        True,
                    )
                    insert_tables.append(rows)
                undecided &= ~sel

    # ---- not-matched-by-source (per-clause batch eval, no row loop) ----
    nmbs_delete_t = np.empty(0, np.int64)
    nmbs_upd_t = np.empty(0, np.int64)       # target rows, aligned with
    nmbs_upd_rows: Optional[pa.Table] = None  # ...rows of this table
    if not_matched_by_source and target_all is not None and target_all.num_rows:
        by_source_mask = np.zeros(target_all.num_rows, dtype=bool)
        by_source_mask[matched_t] = True
        un_idx = np.nonzero(~by_source_mask)[0]
        if len(un_idx):
            sub = target_all.take(pa.array(un_idx, pa.int64()))
            batch = _namespaced_batch(sub, _null_source_rows(source.schema, sub.num_rows))
            undecided = np.ones(sub.num_rows, dtype=bool)
            del_parts, upd_idx_parts, upd_row_parts = [], [], []
            for clause in not_matched_by_source:
                if not undecided.any():
                    break
                ok = (
                    evaluate_predicate_host(clause.condition, batch)
                    if clause.condition is not None
                    else np.ones(sub.num_rows, dtype=bool)
                )
                sel = undecided & ok
                if sel.any():
                    if clause.kind == "delete":
                        del_parts.append(un_idx[sel])
                    else:
                        upd_idx_parts.append(un_idx[sel])
                        upd_row_parts.append(_eval_values(
                            clause.assignments,
                            batch.filter(pa.array(sel)),
                            target_arrow_schema,
                            False,
                        ))
                undecided &= ~sel
            if del_parts:
                nmbs_delete_t = np.concatenate(del_parts)
            if upd_idx_parts:
                nmbs_upd_t = np.concatenate(upd_idx_parts)
                nmbs_upd_rows = pa.concat_tables(
                    upd_row_parts, promote_options="permissive")

    # ---- rewrite touched files (vectorized grouping) ----
    part_cols = snapshot.partition_columns
    cdc_del, cdc_pre, cdc_post = [], [], []
    file_of = (
        np.asarray(target_all.column("__file"), dtype=np.int64)
        if target_all is not None and target_all.num_rows
        else np.empty(0, np.int64)
    )
    n_target = len(file_of)
    del_mask = np.zeros(n_target, dtype=bool)
    del_mask[delete_t] = True
    del_mask[nmbs_delete_t] = True
    upd_mask = np.zeros(n_target, dtype=bool)
    upd_mask[update_t] = True
    nmbs_mask = np.zeros(n_target, dtype=bool)
    nmbs_mask[nmbs_upd_t] = True

    touched = del_mask | upd_mask | nmbs_mask
    touched_files = np.unique(file_of[touched]) if n_target else []

    upd_file = file_of[update_t] if len(update_t) else np.empty(0, np.int64)
    upd_clause = (pair_action[update_pi] if len(update_pi)
                  else np.empty(0, np.int64))
    nmbs_file = (file_of[nmbs_upd_t] if len(nmbs_upd_t)
                 else np.empty(0, np.int64))

    for fi in touched_files:
        fi = int(fi)
        add = candidates[fi]
        here = file_of == fi
        kept = here & ~del_mask & ~upd_mask & ~nmbs_mask
        out_parts = []
        n_kept = int(kept.sum())
        if n_kept:
            out_parts.append(_align_to_schema(
                _strip_provenance(target_all.filter(pa.array(kept))),
                target_arrow_schema))
            metrics.num_target_rows_copied += n_kept
        # matched updates in this file, grouped by clause, batch eval
        in_file = upd_file == fi
        for ci in np.unique(upd_clause[in_file]) if in_file.any() else []:
            sel = in_file & (upd_clause == ci)
            pis = update_pi[sel]
            pair_batch_f = _namespaced_batch(
                target_all.take(pa.array(tpos[pis], pa.int64())),
                source.take(pa.array(spos[pis], pa.int64())),
            )
            new_rows = _eval_values(
                matched[int(ci)].assignments, pair_batch_f,
                target_arrow_schema, True
            )
            out_parts.append(new_rows)
            metrics.num_target_rows_updated += new_rows.num_rows
            if use_cdc:
                cdc_pre.append(
                    _strip_provenance(
                        target_all.take(pa.array(tpos[pis], pa.int64()))
                    )
                )
                cdc_post.append(new_rows)
        nmbs_sel = nmbs_file == fi
        if nmbs_sel.any():
            rows = nmbs_upd_rows.take(
                pa.array(np.nonzero(nmbs_sel)[0], pa.int64()))
            out_parts.append(rows)
            metrics.num_target_rows_updated += rows.num_rows
        n_del_here = int((here & del_mask).sum())
        metrics.num_target_rows_deleted += n_del_here
        if use_cdc and n_del_here:
            cdc_del.append(
                _strip_provenance(target_all.filter(pa.array(here & del_mask)))
            )
        txn.remove_file(add.remove(deletion_timestamp=now_ms))
        metrics.num_target_files_rewritten += 1
        if out_parts:
            new_data = pa.concat_tables(out_parts, promote_options="permissive")
            adds = write_data_files(
                engine=table.engine, table_path=table.path, data=new_data,
                schema=schema, partition_columns=part_cols,
                configuration=meta.configuration,
            )
            txn.add_files(adds)

    # ---- inserts ----
    if insert_tables:
        ins = pa.concat_tables(insert_tables, promote_options="permissive")
        metrics.num_target_rows_inserted = ins.num_rows
        adds = write_data_files(
            engine=table.engine, table_path=table.path, data=ins,
            schema=schema, partition_columns=part_cols,
            configuration=meta.configuration,
        )
        txn.add_files(adds)
        if use_cdc:
            _write_cdc(table, snapshot, txn, ins, "insert")

    if use_cdc:
        for rows, kind in (
            (cdc_del, "delete"), (cdc_pre, "update_preimage"), (cdc_post, "update_postimage"),
        ):
            if rows:
                _write_cdc(
                    table, snapshot, txn,
                    pa.concat_tables(rows, promote_options="permissive"), kind,
                )

    if not txn._adds and not txn._removes and not schema_evolved:
        return metrics  # nothing touched (an evolved schema still commits)
    txn.set_operation_parameters({"predicate": repr(on)})
    txn.set_operation_metrics(
        {
            "numTargetRowsUpdated": metrics.num_target_rows_updated,
            "numTargetRowsDeleted": metrics.num_target_rows_deleted,
            "numTargetRowsInserted": metrics.num_target_rows_inserted,
            "numTargetRowsCopied": metrics.num_target_rows_copied,
            "numSourceRows": metrics.num_source_rows,
        }
    )
    result = txn.commit()
    metrics.version = result.version
    return metrics


def _align_to_schema(t: pa.Table, schema: pa.Schema) -> pa.Table:
    """Null-fill columns `t` lacks (pre-evolution rows), order + cast to
    `schema`."""
    cols = []
    for f in schema:
        if f.name in t.column_names:
            cols.append(t.column(f.name))
        else:
            cols.append(pa.nulls(t.num_rows, f.type))
    return pa.table(dict(zip(schema.names, cols))).cast(schema)


def _strip_provenance(t: pa.Table) -> pa.Table:
    return t.drop_columns([c for c in ("__file", "__row") if c in t.column_names])


def _null_target_rows(schema: pa.Schema, n: int) -> pa.Table:
    return pa.table({f.name: pa.nulls(n, f.type) for f in schema})


def _null_source_rows(schema: pa.Schema, n: int) -> pa.Table:
    return pa.table({f.name: pa.nulls(n, f.type) for f in schema})

"""ALTER TABLE ... DROP FEATURE: protocol feature removal with
pre-downgrade cleanup.

Reference `AlterTableDropFeatureDeltaCommand` +
`PreDowngradeTableFeatureCommand.scala`: each removable feature defines a
pre-downgrade step that erases the feature's traces from the *current*
version (disable the table property, purge deletion vectors, strip
schema metadata, drop domain metadata, ...). Reader-writer features
additionally require the *history* to be clean, since old commits and
checkpoints may still carry the feature — the reference gates this on a
24h wait + `TRUNCATE HISTORY`; we implement TRUNCATE HISTORY as an
immediate checkpoint + log cleanup so the downgrade is one call.

After pre-downgrade, the protocol is rewritten without the feature and
collapsed back to legacy (reader, writer) versions when no non-legacy
feature remains (reference `Protocol.downgraded`).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from delta_tpu.errors import FeatureDropHistoricalVersionsExistError, DeltaError, FeatureDropError, MissingTransactionLogError
from delta_tpu.features import FEATURES, TableFeature, is_feature_supported
from delta_tpu.models.actions import Metadata, Protocol
from delta_tpu.models.schema import (
    StructField,
    StructType,
    schema_from_json,
    schema_to_json,
)

DROP_FEATURE_OP = "DROP FEATURE"

# features whose traces we know how to erase; everything else refuses
# (reference `RemovableFeature`)
_REMOVABLE = {
    "deletionVectors",
    "inCommitTimestamp",
    "v2Checkpoint",
    "typeWidening",
    "rowTracking",
    "clustering",
    "vacuumProtocolCheck",
    "checkConstraints",
    "changeDataFeed",
    "columnMapping",
    "domainMetadata",
    "allowColumnDefaults",
}

# configuration keys each feature's pre-downgrade must remove
_CONF_KEYS: Dict[str, List[str]] = {
    "deletionVectors": ["delta.enableDeletionVectors"],
    "inCommitTimestamp": [
        "delta.enableInCommitTimestamps",
        "delta.inCommitTimestampEnablementVersion",
        "delta.inCommitTimestampEnablementTimestamp",
    ],
    "v2Checkpoint": ["delta.checkpointPolicy"],
    "typeWidening": ["delta.enableTypeWidening"],
    "rowTracking": ["delta.enableRowTracking"],
    "changeDataFeed": ["delta.enableChangeDataFeed"],
    "columnMapping": ["delta.columnMapping.mode", "delta.columnMapping.maxColumnId"],
}


def drop_feature(table, feature_name: str, truncate_history: bool = False) -> int:
    """Run the pre-downgrade step for `feature_name`, verify no traces
    remain, and commit the downgraded protocol. Returns the version of
    the protocol-downgrade commit."""
    feature = FEATURES.get(feature_name)
    if feature is None:
        raise FeatureDropError(
            f"unknown table feature {feature_name!r}; known features: "
            f"{sorted(FEATURES)}")
    if feature_name not in _REMOVABLE:
        raise FeatureDropError(
            f"feature {feature_name!r} cannot be dropped (not removable)",
            error_class="DELTA_FEATURE_DROP_NONREMOVABLE_FEATURE")

    snapshot = table.latest_snapshot()
    if snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    proto = snapshot.protocol
    if feature_name not in proto.writer_feature_set() and (
        feature_name not in proto.reader_feature_set()
    ):
        if is_feature_supported(proto, feature):
            raise FeatureDropError(
                error_class="DELTA_FEATURE_DROP_IMPLICITLY_SUPPORTED",
                message=f"feature {feature_name!r} is implicitly supported by "
                f"protocol ({proto.minReaderVersion}, {proto.minWriterVersion}) "
                "legacy versions; dropping legacy features requires them to "
                "be listed explicitly (writer version 7)")
        raise FeatureDropError(
            f"feature {feature_name!r} is not present on this table",
            error_class="DELTA_FEATURE_DROP_FEATURE_NOT_PRESENT")

    _pre_downgrade(table, feature_name)

    # reader-writer features leave traces in historical commits and
    # checkpoints; those stay readable until history is truncated
    if feature.is_reader_writer and feature_name != "vacuumProtocolCheck":
        if not truncate_history:
            raise FeatureDropHistoricalVersionsExistError(
                f"dropping reader+writer feature {feature_name!r} requires "
                "history truncation: historical versions may still carry the "
                "feature. Re-run with TRUNCATE HISTORY "
                "(drop_feature(..., truncate_history=True))")
        _truncate_history(table)

    return _commit_downgrade(table, feature)


def _pre_downgrade(table, name: str) -> None:
    from delta_tpu.commands.alter import unset_properties

    snapshot = table.latest_snapshot()
    meta = snapshot.metadata
    conf = meta.configuration

    if name == "deletionVectors":
        from delta_tpu.commands.reorg import reorg_purge

        if conf.get("delta.enableDeletionVectors", "").lower() == "true":
            unset_properties(table, _CONF_KEYS[name])
        reorg_purge(table)
        still = [f for f in table.latest_snapshot().scan().files()
                 if f.deletionVector is not None]
        if still:
            raise FeatureDropError(
                f"{len(still)} file(s) still carry deletion vectors after purge",
                error_class="DELTA_FEATURE_DROP_STILL_ACTIVE")
        return

    if name == "checkConstraints":
        from delta_tpu.constraints import table_constraints

        existing = table_constraints(conf)
        if existing:
            raise FeatureDropError(
                error_class="DELTA_CANNOT_DROP_CHECK_CONSTRAINT_FEATURE",
                message=f"cannot drop checkConstraints: constraint(s) "
                f"{sorted(existing)} still exist — DROP CONSTRAINT them first")
        return

    if name == "rowTracking":
        from delta_tpu.rowtracking import ROW_TRACKING_DOMAIN

        _strip_metadata_and_domains(
            table, conf_keys=_CONF_KEYS[name], domains=[ROW_TRACKING_DOMAIN])
        return

    if name == "clustering":
        from delta_tpu.clustering import CLUSTERING_DOMAIN

        _strip_metadata_and_domains(table, conf_keys=[], domains=[CLUSTERING_DOMAIN])
        return

    if name == "columnMapping":
        schema = schema_from_json(meta.schemaString)
        renamed = [f.name for f in schema.fields if f.physical_name != f.name]
        if renamed:
            raise FeatureDropError(
                "cannot drop columnMapping: column(s) "
                f"{renamed} have physical names differing from their logical "
                "names (a rename or drop happened); rewrite the table first")

        def strip(f: StructField) -> StructField:
            md = {k: v for k, v in f.metadata.items()
                  if not k.startswith("delta.columnMapping.")}
            return dataclasses.replace(f, metadata=md)

        new_schema = StructType([strip(f) for f in schema.fields])
        _strip_metadata_and_domains(
            table, conf_keys=_CONF_KEYS[name], domains=[], new_schema=new_schema)
        return

    if name == "typeWidening":
        # files written before a widening already read correctly only via
        # the feature; materialize the wide type everywhere first
        from delta_tpu.commands.reorg import reorg_rewrite_all

        if conf.get("delta.enableTypeWidening", "").lower() == "true":
            unset_properties(table, _CONF_KEYS[name])
        reorg_rewrite_all(table)
        return

    if name == "v2Checkpoint":
        keys = [k for k in _CONF_KEYS[name] if k in conf]
        if conf.get("delta.checkpointPolicy", "classic") != "classic":
            _strip_metadata_and_domains(table, conf_keys=keys, domains=[])
        # replace any V2 checkpoint with a classic one at the head version
        table.checkpoint()
        return

    if name == "allowColumnDefaults":
        schema = schema_from_json(meta.schemaString)

        def strip(f: StructField) -> StructField:
            md = {k: v for k, v in f.metadata.items()
                  if k not in ("CURRENT_DEFAULT", "EXISTS_DEFAULT")}
            return dataclasses.replace(f, metadata=md)

        new_schema = StructType([strip(f) for f in schema.fields])
        if new_schema != schema:
            _strip_metadata_and_domains(
                table, conf_keys=[], domains=[], new_schema=new_schema)
        return

    if name == "domainMetadata":
        live = {d: dm for d, dm in
                table.latest_snapshot().state.domain_metadata.items()
                if not dm.removed}
        if live:
            raise FeatureDropError(
                f"cannot drop domainMetadata: live domain(s) {sorted(live)} "
                "still exist")
        return

    keys = [k for k in _CONF_KEYS.get(name, ()) if k in conf]
    if keys:
        unset_properties(table, keys)


def _strip_metadata_and_domains(table, conf_keys: List[str],
                                domains: List[str],
                                new_schema: Optional[StructType] = None) -> None:
    txn = table.create_transaction_builder(DROP_FEATURE_OP).build()
    meta = txn.metadata()
    conf = {k: v for k, v in meta.configuration.items() if k not in set(conf_keys)}
    replacement = dataclasses.replace(
        meta, configuration=conf,
        schemaString=(schema_to_json(new_schema) if new_schema is not None
                      else meta.schemaString))
    if replacement != meta:
        txn.update_metadata(replacement)
    for d in domains:
        if d in txn.read_snapshot.state.domain_metadata:
            txn.remove_domain_metadata(d)
    txn.set_operation_parameters({"preDowngrade": True})
    txn.commit()


def _truncate_history(table) -> None:
    """Checkpoint the head version and delete every shadowed log file,
    regardless of age (the TRUNCATE HISTORY arm of the reference command,
    with the 24h wait collapsed to 'now')."""
    import time

    from delta_tpu.log.cleanup import cleanup_expired_logs

    table.checkpoint()
    cleanup_expired_logs(table, retention_ms=0,
                         now_ms=int(time.time() * 1000) + 60_000)


def _commit_downgrade(table, feature: TableFeature) -> int:
    txn = table.create_transaction_builder(DROP_FEATURE_OP).build()
    proto = txn.protocol()
    meta = txn.metadata()
    if feature.activated_by is not None and feature.activated_by(meta):
        raise FeatureDropError(
            f"feature {feature.name!r} is still active after pre-downgrade",
            error_class="DELTA_FEATURE_DROP_STILL_ACTIVE")
    txn.update_protocol(_downgraded_protocol(proto, feature.name))
    txn.set_operation_parameters({"featureName": feature.name})
    return txn.commit().version


def _downgraded_protocol(proto: Protocol, name: str) -> Protocol:
    writer = proto.writer_feature_set() - {name}
    reader = proto.reader_feature_set() - {name}
    remaining = [FEATURES[n] for n in writer | reader if n in FEATURES]
    unknown = (writer | reader) - set(FEATURES)
    if not unknown and all(f.legacy for f in remaining):
        # collapse to legacy versions (reference Protocol.downgraded)
        min_writer = max([f.min_writer_version for f in remaining], default=2)
        min_reader = max(
            [f.min_reader_version for f in remaining if f.is_reader_writer],
            default=1)
        return Protocol(min_reader, min_writer)
    min_reader = 3 if reader else 1
    return Protocol(
        min_reader, 7,
        readerFeatures=sorted(reader) if min_reader >= 3 else None,
        writerFeatures=sorted(writer))

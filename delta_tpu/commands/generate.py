"""GENERATE symlink_format_manifest: Hive/Presto/Athena-readable
manifests of the table's live data files.

Reference `commands/DeltaGenerateCommand.scala` +
`hooks/GenerateSymlinkManifest.scala`: writes one text file per
partition under `_symlink_format_manifest/`, each line an absolute data
file URI. With the `delta.compatibility.symlinkFormatManifest.enabled`
table property, a post-commit hook regenerates only the partitions a
commit touched and deletes manifests of emptied partitions.

Deletion vectors cannot be expressed in a symlink manifest (external
engines would read soft-deleted rows), so generation refuses when any
live file carries a DV — same gate as the reference.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from delta_tpu.errors import DeltaError, InvalidArgumentError, MissingTransactionLogError
from delta_tpu.stats.partition import partition_path

MANIFEST_DIR = "_symlink_format_manifest"
MANIFEST_NAME = "manifest"


def _manifest_location(table_path: str, pv: Dict[str, Optional[str]],
                       partition_columns: List[str]) -> str:
    rel = partition_path(pv, partition_columns).rstrip("/")
    base = f"{table_path}/{MANIFEST_DIR}"
    return f"{base}/{rel}/{MANIFEST_NAME}" if rel else f"{base}/{MANIFEST_NAME}"


def _absolute(table_path: str, p: str) -> str:
    if "://" in p or p.startswith("/"):
        return p
    return os.path.join(table_path, p)


def generate_symlink_manifest(table) -> Dict[str, int]:
    """Full regeneration: one manifest per live partition; stale
    partition manifests are removed. Returns {manifest_path: num_files}."""
    snapshot = table.latest_snapshot()
    if snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    _check_compatible(snapshot)
    files = snapshot.scan().files()
    _check_no_dvs(files)
    part_cols = snapshot.partition_columns

    groups: Dict[Tuple, List[str]] = {}
    for f in files:
        pv = f.partitionValues or {}
        key = tuple(pv.get(c) for c in part_cols)
        groups.setdefault(key, []).append(_absolute(table.path, f.path))

    written = _write_manifests(table, part_cols, groups)
    _delete_stale_manifests(table, keep=set(written))
    return written


def incremental_symlink_manifest_hook(table, txn, version: int, metadata) -> None:
    """Post-commit: regenerate manifests only for the partitions the
    commit added or removed files in (reference
    `GenerateSymlinkManifest.incrementally`)."""
    if metadata.configuration.get(
            "delta.compatibility.symlinkFormatManifest.enabled", ""
    ).lower() != "true":
        return
    touched_pvs = [a.partitionValues or {} for a in txn._adds]
    touched_pvs += [r.partitionValues or {} for r in txn._removes]
    if not touched_pvs:
        return
    snapshot = table.snapshot_at(version)
    _check_compatible(snapshot)
    part_cols = snapshot.partition_columns
    touched: Set[Tuple] = {
        tuple(pv.get(c) for c in part_cols) for pv in touched_pvs
    }

    files = snapshot.scan().files()
    _check_no_dvs(files)
    groups: Dict[Tuple, List[str]] = {k: [] for k in touched}
    for f in files:
        pv = f.partitionValues or {}
        key = tuple(pv.get(c) for c in part_cols)
        if key in touched:
            groups[key].append(_absolute(table.path, f.path))

    live = {k: v for k, v in groups.items() if v}
    _write_manifests(table, part_cols, live)
    # partitions that lost their last file lose their manifest
    for key in touched - set(live):
        pv = dict(zip(part_cols, key))
        loc = _manifest_location(table.path, pv, part_cols)
        try:
            table.engine.fs.delete(loc)
        except FileNotFoundError:
            pass


def _check_compatible(snapshot) -> None:
    """Column mapping renames physical columns/partition dirs in ways a
    symlink manifest cannot describe to external engines (same gate as
    the reference's GenerateSymlinkManifest protocol check)."""
    from delta_tpu.columnmapping import mapping_mode

    if mapping_mode(snapshot.metadata.configuration) != "none":
        raise InvalidArgumentError(
            "symlink manifests are not supported on column-mapped tables",
            error_class="DELTA_GENERATE_WITH_COLUMN_MAPPING")


def _check_no_dvs(files: Iterable) -> None:
    n = sum(1 for f in files if f.deletionVector is not None)
    if n:
        raise InvalidArgumentError(
            error_class="DELTA_GENERATE_WITH_DELETION_VECTORS",
            message=f"cannot generate symlink manifests: {n} live file(s) carry "
            "deletion vectors (external engines would see deleted rows); "
            "run REORG TABLE ... APPLY (PURGE) first")


def _write_manifests(table, part_cols: List[str],
                     groups: Dict[Tuple, List[str]]) -> Dict[str, int]:
    written: Dict[str, int] = {}
    for key, paths in sorted(groups.items(), key=lambda kv: str(kv[0])):
        pv = dict(zip(part_cols, key))
        loc = _manifest_location(table.path, pv, part_cols)
        body = ("\n".join(sorted(paths)) + "\n").encode()
        table.engine.fs.mkdirs(os.path.dirname(loc))
        table.engine.fs.write_file(loc, body)
        written[loc] = len(paths)
    return written


def _delete_stale_manifests(table, keep: Set[str]) -> None:
    root = f"{table.path}/{MANIFEST_DIR}"
    try:
        listing = list(table.engine.fs.walk(root))
    except FileNotFoundError:
        return
    for f in listing:
        if os.path.basename(f.path) == MANIFEST_NAME and f.path not in keep:
            try:
                table.engine.fs.delete(f.path)
            except FileNotFoundError:
                pass

"""REORG TABLE ... APPLY (PURGE): rewrite files carrying soft-deleted
rows or stale physical layouts into clean files.

Reference `commands/DeltaReorgTableCommand.scala` — REORG is OPTIMIZE
with a file-selection predicate instead of a size threshold: PURGE picks
files with deletion vectors (materializing the deletes), and the
upgrade-uniform variant picks files that predate a physical-schema
change (we expose that as `reorg_rewrite_all`). The rewrite itself is a
dataChange=false OPTIMIZE-style commit, so streaming sources ignore it.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from delta_tpu.errors import DeltaError, InvalidArgumentError, MissingTransactionLogError
from delta_tpu.models.actions import AddFile
from delta_tpu.txn.isolation import IsolationLevel
from delta_tpu.txn.transaction import Operation
from delta_tpu.write.writer import write_data_files

from delta_tpu.commands.optimize import DEFAULT_MAX_FILE_SIZE, OptimizeMetrics


def reorg_purge(table, max_file_size: int = DEFAULT_MAX_FILE_SIZE) -> OptimizeMetrics:
    """Rewrite every file that has a deletion vector, dropping the
    deleted rows for good (REORG ... APPLY (PURGE))."""
    return _reorg(table, lambda f: f.deletionVector is not None,
                  "REORG (PURGE)", max_file_size)


def reorg_rewrite_all(table, max_file_size: int = DEFAULT_MAX_FILE_SIZE) -> OptimizeMetrics:
    """Rewrite every live file (REORG upgrade-compat variant — used to
    materialize a physical-layout change across all files)."""
    return _reorg(table, lambda f: True, "REORG (REWRITE)", max_file_size)


def reorg_upgrade_uniform(table, iceberg_compat_version: int = 2,
                          max_file_size: int = DEFAULT_MAX_FILE_SIZE) -> OptimizeMetrics:
    """REORG TABLE ... APPLY (UPGRADE UNIFORM (ICEBERG_COMPAT_VERSION=N)):
    make an existing table IcebergCompat-ready — materialize any
    deletion vectors, drop the DV feature, then enable column mapping +
    the compat flag + UniForm iceberg in one property commit (reference
    `DeltaReorgTableCommand.scala` upgrade-uniform mode)."""
    from delta_tpu.commands.alter import set_properties
    from delta_tpu.table import Table as _Table

    if iceberg_compat_version not in (1, 2):
        raise InvalidArgumentError(
            f"unsupported ICEBERG_COMPAT_VERSION {iceberg_compat_version}")
    metrics = reorg_purge(table, max_file_size)

    fresh = _Table.for_path(table.path, table.engine)
    other = 1 if iceberg_compat_version == 2 else 2
    props = {
        f"delta.enableIcebergCompatV{iceberg_compat_version}": "true",
        # upgrading between versions must not trip the mutual-exclusion
        # check after the purge already ran
        f"delta.enableIcebergCompatV{other}": "false",
        "delta.enableDeletionVectors": "false",
        "delta.universalFormat.enabledFormats": "iceberg",
    }
    conf = fresh.latest_snapshot().metadata.configuration
    if conf.get("delta.columnMapping.mode", "none") == "none":
        props["delta.columnMapping.mode"] = "name"
    set_properties(fresh, props)
    return metrics


def _reorg(table, selector: Callable[[AddFile], bool], op_name: str,
           max_file_size: int) -> OptimizeMetrics:
    from delta_tpu.read.reader import read_add_file_logical

    import pyarrow as pa

    txn = table.create_transaction_builder(Operation.OPTIMIZE).build()
    txn._isolation = IsolationLevel.SNAPSHOT_ISOLATION
    snapshot = txn.read_snapshot
    if snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    meta = snapshot.metadata

    targets = [f for f in txn.scan_files() if selector(f)]
    metrics = OptimizeMetrics()
    if not targets:
        return metrics

    now_ms = int(time.time() * 1000)
    new_adds: List[AddFile] = []
    # rewrite per source file: keeps partition membership trivially stable
    # and bounds memory to one file's rows
    for f in targets:
        data = read_add_file_logical(table.engine, table.path, snapshot, f)
        if data.num_rows:
            adds = write_data_files(
                engine=table.engine,
                table_path=table.path,
                data=data,
                schema=meta.schema,
                partition_columns=meta.partitionColumns,
                configuration=meta.configuration,
                data_change=False,
            )
            new_adds.extend(adds)
        txn.remove_file(f.remove(deletion_timestamp=now_ms, data_change=False))
        metrics.num_files_removed += 1
        metrics.bytes_removed += f.size

    txn.add_files(new_adds)
    metrics.num_files_added = len(new_adds)
    metrics.bytes_added = sum(a.size for a in new_adds)
    txn.set_operation_parameters({"applyPurge": op_name == "REORG (PURGE)"})
    txn.set_operation_metrics({
        "numAddedFiles": metrics.num_files_added,
        "numRemovedFiles": metrics.num_files_removed,
        "numAddedBytes": metrics.bytes_added,
        "numRemovedBytes": metrics.bytes_removed,
    })
    metrics.version = txn.commit().version
    return metrics

"""ALTER TABLE operations: columns, properties, protocol, column mapping.

Mirrors the reference's `AlterDeltaTableCommand` family
(`commands/alterDeltaTableCommands.scala`): each operation is a
metadata/protocol-only transaction.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from delta_tpu.columnmapping import (
    MODE_KEY,
    assign_column_mapping,
    drop_column as _drop_from_schema,
    mapping_mode,
    rename_column as _rename_in_schema,
    validate_mode_change,
)
from delta_tpu.errors import DeltaError, InvalidArgumentError, InvalidProtocolVersionError, MissingTransactionLogError, SchemaEvolutionError, SchemaMismatchError
from delta_tpu.features import FEATURES, upgraded_protocol
from delta_tpu.models.schema import (
    DataType,
    StructField,
    StructType,
    schema_from_json,
    schema_to_json,
)
from delta_tpu.schema_evolution import can_widen
from delta_tpu.txn.transaction import Operation


def _check_dependent_columns(schema, configuration, column: str,
                             what: str) -> None:
    """A column referenced by a generated column's expression or a
    CHECK constraint cannot be dropped/renamed
    (`DeltaErrors.generatedColumnsDependentColumnChange` /
    `.constraintDependentColumnChange`)."""
    from delta_tpu.colgen import _ref_overlaps, generated_dependents
    from delta_tpu.constraints import CONSTRAINT_PREFIX
    from delta_tpu.expressions.parser import ParseError, parse_expression

    deps = generated_dependents(schema, column)
    if deps:
        raise SchemaEvolutionError(
            f"cannot {what} column {column}: generated column(s) "
            f"{deps} depend on it",
            error_class="DELTA_GENERATED_COLUMNS_DEPENDENT_COLUMN_CHANGE")
    for key, expr in (configuration or {}).items():
        if not key.startswith(CONSTRAINT_PREFIX):
            continue
        try:
            refs = {".".join(r)
                    for r in parse_expression(expr).references()}
        except ParseError:
            continue
        if any(_ref_overlaps(r, column) for r in refs):
            raise SchemaEvolutionError(
                f"cannot {what} column {column}: CHECK constraint "
                f"{key[len(CONSTRAINT_PREFIX):]!r} depends on it",
                error_class="DELTA_CONSTRAINT_DEPENDENT_COLUMN_CHANGE")


def _metadata_txn(table, operation: str):
    txn = table.create_transaction_builder(operation).build()
    if txn.read_snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    return txn


def _commit_schema(txn, new_schema: StructType, operation_params: Dict,
                   new_conf: Optional[Dict[str, str]] = None) -> int:
    meta = txn.metadata()
    replacement = dataclasses.replace(
        meta,
        schemaString=schema_to_json(new_schema),
        configuration=dict(new_conf if new_conf is not None else meta.configuration),
    )
    txn.update_metadata(replacement)
    # schema metadata can activate features (CURRENT_DEFAULT →
    # allowColumnDefaults, generation expressions, identity columns);
    # the protocol must list them before the commit lands
    proto = txn.protocol()
    for feat in FEATURES.values():
        if feat.activated_by is not None and feat.activated_by(replacement):
            upgraded = upgraded_protocol(proto, feat)
            if upgraded != proto:
                proto = upgraded
                txn.update_protocol(proto)
    txn.set_operation_parameters(operation_params)
    return txn.commit().version


def _add_nested_field(schema: StructType, parent: list,
                      leaf: StructField) -> StructType:
    """Rebuild `schema` with `leaf` appended inside the struct at
    `parent` path. Missing parent -> DELTA_ADD_COLUMN_STRUCT_NOT_FOUND;
    non-struct parent -> DELTA_ADD_COLUMN_PARENT_NOT_STRUCT (reference
    `SchemaUtils.addColumn` error conditions)."""
    head = parent[0]
    if head not in schema:
        raise SchemaEvolutionError(
            f"Struct not found at position {head}",
            error_class="DELTA_ADD_COLUMN_STRUCT_NOT_FOUND")
    out = []
    for f in schema.fields:
        if f.name != head:
            out.append(f)
            continue
        if not isinstance(f.dataType, StructType):
            raise SchemaEvolutionError(
                f"cannot add {leaf.name} because its parent {head} is "
                f"not a StructType ({f.dataType.to_json_value()})",
                error_class="DELTA_ADD_COLUMN_PARENT_NOT_STRUCT")
        inner = (
            _add_nested_field(f.dataType, parent[1:], leaf)
            if len(parent) > 1
            else StructType(list(f.dataType.fields) + [leaf]))
        if len(parent) == 1 and leaf.name in f.dataType:
            raise SchemaMismatchError(
                f"column {head}.{leaf.name} already exists")
        out.append(StructField(f.name, inner, f.nullable,
                               dict(f.metadata)))
    return StructType(out)


def add_columns(table, columns: Sequence[StructField]) -> int:
    """ADD COLUMNS (always nullable; appended at the end). Dotted
    names (`a.b.c`) add a nested field inside the struct at `a.b`."""
    txn = _metadata_txn(table, Operation.ADD_COLUMNS)
    meta = txn.metadata()
    schema = schema_from_json(meta.schemaString)
    conf = dict(meta.configuration)
    for f in columns:
        if not f.nullable:
            raise SchemaEvolutionError("added columns must be nullable",
                                       error_class="DELTA_ADD_COLUMN_NOT_NULLABLE")
        if "." in f.name:
            parts = f.name.split(".")
            leaf = StructField(parts[-1], f.dataType, f.nullable,
                               dict(f.metadata))
            schema = _add_nested_field(schema, parts[:-1], leaf)
            continue
        if f.name in schema:
            raise SchemaMismatchError(f"column {f.name} already exists")
        schema = StructType(schema.fields + [f])
    new_schema = schema
    if mapping_mode(conf) != "none":
        new_schema, conf = assign_column_mapping(new_schema, conf)
    return _commit_schema(
        txn, new_schema, {"columns": [f.name for f in columns]}, conf
    )


def rename_column(table, old: str, new: str) -> int:
    """RENAME COLUMN — metadata-only; requires column mapping."""
    txn = _metadata_txn(table, Operation.RENAME_COLUMN)
    meta = txn.metadata()
    if mapping_mode(meta.configuration) == "none":
        raise SchemaEvolutionError(
            "RENAME COLUMN requires column mapping "
            "(set delta.columnMapping.mode = 'name')",
            error_class="DELTA_UNSUPPORTED_RENAME_COLUMN"
        )
    schema = schema_from_json(meta.schemaString)
    _check_dependent_columns(schema, meta.configuration, old, "rename")
    new_schema = _rename_in_schema(schema, old, new)
    partition_cols = [
        new if c == old else c for c in meta.partitionColumns
    ]
    replacement = dataclasses.replace(
        meta,
        schemaString=schema_to_json(new_schema),
        partitionColumns=partition_cols,
    )
    txn.update_metadata(replacement)
    txn.set_operation_parameters({"oldName": old, "newName": new})
    return txn.commit().version


def drop_column(table, name: str) -> int:
    """DROP COLUMN — metadata-only; requires column mapping."""
    txn = _metadata_txn(table, Operation.DROP_COLUMNS)
    meta = txn.metadata()
    if mapping_mode(meta.configuration) == "none":
        raise SchemaEvolutionError(
            "DROP COLUMN requires column mapping "
            "(set delta.columnMapping.mode = 'name')",
            error_class="DELTA_UNSUPPORTED_DROP_COLUMN"
        )
    if name in meta.partitionColumns:
        raise SchemaEvolutionError(f"cannot drop partition column {name}",
                                   error_class="DELTA_UNSUPPORTED_DROP_PARTITION_COLUMN")
    schema = schema_from_json(meta.schemaString)
    _check_dependent_columns(schema, meta.configuration, name, "drop")
    if "." in name:
        new_schema = _drop_nested_field(schema, name.split("."))
    else:
        new_schema = _drop_from_schema(schema, name)
    return _commit_schema(txn, new_schema, {"column": name})


def _drop_nested_field(schema: StructType, parts: list) -> StructType:
    """Drop a nested field; an intermediate that is not a struct is
    the reference's
    `DeltaErrors.dropNestedColumnsFromNonStructTypeException`."""
    from delta_tpu.errors import NonExistentColumnError

    head = parts[0]
    if head not in schema:
        raise NonExistentColumnError(f"column {head} not found")
    out = []
    for f in schema.fields:
        if f.name != head:
            out.append(f)
            continue
        if not isinstance(f.dataType, StructType):
            raise SchemaEvolutionError(
                f"cannot drop nested column from a non-struct type: "
                f"{f.dataType.to_json_value()}",
                error_class=(
                    "DELTA_UNSUPPORTED_DROP_NESTED_COLUMN_FROM_NON_STRUCT_TYPE"))
        if len(parts) == 2:
            inner = _drop_from_schema(f.dataType, parts[1])
        else:
            inner = _drop_nested_field(f.dataType, parts[1:])
        out.append(StructField(f.name, inner, f.nullable,
                               dict(f.metadata)))
    return StructType(out)


def change_column_type(table, name: str, new_type: DataType) -> int:
    """CHANGE COLUMN TYPE — only widening changes, gated on the
    typeWidening feature."""
    txn = _metadata_txn(table, Operation.CHANGE_COLUMN)
    meta = txn.metadata()
    schema = schema_from_json(meta.schemaString)
    if name not in schema:
        raise SchemaMismatchError(f"column {name} not found",
                                  error_class="DELTA_COLUMN_NOT_FOUND_IN_SCHEMA")
    f = schema[name]
    if not can_widen(f.dataType, new_type):
        raise SchemaEvolutionError(
            f"unsupported type change {f.dataType.to_json_value()} -> "
            f"{new_type.to_json_value()} (only widening changes allowed)",
            error_class="DELTA_CANNOT_CHANGE_DATA_TYPE"
        )
    if meta.configuration.get("delta.enableTypeWidening", "").lower() != "true":
        raise SchemaEvolutionError("set delta.enableTypeWidening = true first",
                                   error_class="DELTA_TYPE_WIDENING_DISABLED")
    new_fields = [
        StructField(x.name, new_type, x.nullable, dict(x.metadata))
        if x.name == name
        else x
        for x in schema.fields
    ]
    # upgrade protocol for the typeWidening feature
    proto = upgraded_protocol(txn.protocol(), FEATURES["typeWidening"])
    if proto != txn.protocol():
        txn.update_protocol(proto)
    return _commit_schema(
        txn, StructType(new_fields),
        {"column": name, "newType": new_type.to_json_value()},
    )


def set_properties(table, properties: Dict[str, str]) -> int:
    txn = _metadata_txn(table, Operation.SET_TBLPROPERTIES)
    meta = txn.metadata()
    from delta_tpu.config import validate_table_properties
    from delta_tpu.coordinatedcommits.client import validate_cc_alter_set

    validate_cc_alter_set(meta.configuration, properties)
    validate_table_properties(properties)
    conf = dict(meta.configuration)
    old_mode = mapping_mode(conf)
    conf.update(properties)

    from delta_tpu.interop.icebergcompat import validate_enablement

    validate_enablement(txn.read_snapshot, conf)
    new_mode = mapping_mode(conf)
    schema = schema_from_json(meta.schemaString)
    if old_mode != new_mode:
        validate_mode_change(old_mode, new_mode)
        schema, conf = assign_column_mapping(schema, conf)
        proto = upgraded_protocol(txn.protocol(), FEATURES["columnMapping"])
        if proto != txn.protocol():
            txn.update_protocol(proto)
    # feature-activating properties may demand protocol upgrades
    for feat in FEATURES.values():
        if feat.activated_by is not None:
            probe = dataclasses.replace(meta, configuration=conf)
            if feat.activated_by(probe):
                proto = upgraded_protocol(txn.protocol(), feat)
                if proto != txn.protocol():
                    txn.update_protocol(proto)
    return _commit_schema(txn, schema, {"properties": dict(properties)}, conf)


def unset_properties(table, keys: Sequence[str],
                     if_exists: bool = False) -> int:
    txn = _metadata_txn(table, Operation.SET_TBLPROPERTIES)
    meta = txn.metadata()
    from delta_tpu.coordinatedcommits.client import validate_cc_alter_unset

    validate_cc_alter_unset(meta.configuration, keys)
    missing = [k for k in keys if k not in meta.configuration]
    if missing and not if_exists:
        raise InvalidArgumentError(
            f"cannot unset non-existent propert{'ies' if len(missing) > 1 else 'y'} "
            f"{missing}; use UNSET TBLPROPERTIES IF EXISTS",
            error_class="DELTA_UNSET_NON_EXISTENT_PROPERTY")
    conf = {k: v for k, v in meta.configuration.items() if k not in set(keys)}
    replacement = dataclasses.replace(meta, configuration=conf)
    txn.update_metadata(replacement)
    txn.set_operation_parameters({"unset": list(keys)})
    return txn.commit().version


def upgrade_protocol(table, min_reader: Optional[int] = None,
                     min_writer: Optional[int] = None,
                     feature: Optional[str] = None) -> int:
    txn = _metadata_txn(table, Operation.UPGRADE_PROTOCOL)
    proto = txn.protocol()
    if feature is not None:
        if feature not in FEATURES:
            raise InvalidArgumentError(f"unknown table feature {feature}",
                                       error_class="DELTA_UNSUPPORTED_FEATURES_IN_CONFIG")
        new_proto = upgraded_protocol(proto, FEATURES[feature])
    else:
        new_proto = dataclasses.replace(
            proto,
            minReaderVersion=max(proto.minReaderVersion, min_reader or 0),
            minWriterVersion=max(proto.minWriterVersion, min_writer or 0),
        )
    if new_proto == proto:
        return txn.read_version
    if (new_proto.minReaderVersion < proto.minReaderVersion
            or new_proto.minWriterVersion < proto.minWriterVersion):
        raise InvalidProtocolVersionError("protocol downgrade is not allowed",
                                          error_class="DELTA_INVALID_PROTOCOL_DOWNGRADE")
    txn.update_protocol(new_proto)
    txn.set_operation_parameters(
        {"newProtocol": new_proto.to_dict()}
    )
    return txn.commit().version
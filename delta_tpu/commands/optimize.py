"""OPTIMIZE: bin-packing compaction and Z-order / Hilbert clustering.

Reference `commands/OptimizeTableCommand.scala:251-427` (OptimizeExecutor:
candidate selection → `groupFilesIntoBins` → per-bin rewrite →
SnapshotIsolation commit with dataChange=false) and
`skipping/MultiDimClustering.scala:41-69` (curve-key range clustering).

TPU mapping: the clustering permutation (rank → curve key → sort) runs
entirely on device (`ops/zorder.py`); bin packing is a host heuristic
(`BinPackingUtils.binPackBySize` semantics).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from delta_tpu import obs
from delta_tpu.errors import DeltaError, MissingTransactionLogError, OptimizeArgumentError
from delta_tpu.expressions.tree import Expression
from delta_tpu.models.actions import AddFile
from delta_tpu.txn.isolation import IsolationLevel
from delta_tpu.txn.transaction import Operation
from delta_tpu.write.writer import write_data_files

DEFAULT_MIN_FILE_SIZE = 256 * 1024 * 1024   # files below this are compacted
DEFAULT_MAX_FILE_SIZE = 256 * 1024 * 1024   # bin capacity


@dataclass
class OptimizeMetrics:
    num_files_added: int = 0
    num_files_removed: int = 0
    bytes_added: int = 0
    bytes_removed: int = 0
    num_bins: int = 0
    num_batches: int = 1
    partitions_optimized: int = 0
    version: Optional[int] = None

    def to_dict(self) -> Dict:
        return dict(self.__dict__)


def bin_pack_by_size(
    files: Sequence[AddFile], max_bin_size: int
) -> List[List[AddFile]]:
    """First-fit-decreasing-ish packing (reference
    `BinPackingUtils.binPackBySize:317`: sort ascending, accumulate until
    the bin would overflow)."""
    bins: List[List[AddFile]] = []
    cur: List[AddFile] = []
    cur_size = 0
    for f in sorted(files, key=lambda f: f.size):
        if cur and cur_size + f.size > max_bin_size:
            bins.append(cur)
            cur, cur_size = [], 0
        cur.append(f)
        cur_size += f.size
    if cur:
        bins.append(cur)
    return bins


class OptimizeBuilder:
    """`table.optimize().where(...).execute_compaction()` /
    `.execute_zorder_by("c1", "c2")` (mirrors `DeltaOptimizeBuilder`)."""

    def __init__(self, table):
        self._table = table
        self._filter: Optional[Expression] = None

    def where(self, predicate: Expression) -> "OptimizeBuilder":
        self._filter = predicate
        return self

    def execute_compaction(
        self,
        min_file_size: int = DEFAULT_MIN_FILE_SIZE,
        max_file_size: int = DEFAULT_MAX_FILE_SIZE,
    ) -> OptimizeMetrics:
        return _run_optimize(
            self._table, self._filter, zorder_by=None,
            min_file_size=min_file_size, max_file_size=max_file_size,
        )

    def execute_zorder_by(
        self, *columns: str, curve: str = "zorder",
        max_file_size: int = DEFAULT_MAX_FILE_SIZE,
    ) -> OptimizeMetrics:
        if not columns:
            raise OptimizeArgumentError("ZORDER BY requires at least one column",
                                        error_class="DELTA_ZORDER_REQUIRES_COLUMN")
        return _run_optimize(
            self._table, self._filter, zorder_by=list(columns), curve=curve,
            min_file_size=None, max_file_size=max_file_size,
        )

    def execute_full(
        self, max_file_size: int = DEFAULT_MAX_FILE_SIZE,
    ) -> OptimizeMetrics:
        """OPTIMIZE ... FULL: re-cluster EVERY file of a clustered
        table, including files already in stable ZCubes
        (`OptimizeTableCommand.scala` isFull; only valid on clustered
        tables — `DeltaErrors.optimizeFullNotSupportedException`)."""
        from delta_tpu.clustering import clustering_columns

        snap = self._table.latest_snapshot()
        if not clustering_columns(snap):
            raise OptimizeArgumentError(
                "OPTIMIZE FULL is only supported for clustered tables "
                "with non-empty clustering columns",
                error_class="DELTA_OPTIMIZE_FULL_NOT_SUPPORTED")
        return _run_optimize(
            self._table, self._filter, zorder_by=None,
            min_file_size=None, max_file_size=max_file_size, full=True,
        )


def _run_optimize(
    table,
    filter: Optional[Expression],
    zorder_by: Optional[List[str]],
    max_file_size: int,
    min_file_size: Optional[int],
    curve: str = "zorder",
    full: bool = False,
) -> OptimizeMetrics:
    with obs.span("command.optimize", table=table.path,
                  zorder=bool(zorder_by)) as sp:
        metrics = _run_optimize_inner(
            table, filter, zorder_by, max_file_size, min_file_size, curve,
            full)
        sp.set_attrs(files_removed=metrics.num_files_removed,
                     files_added=metrics.num_files_added)
        return metrics


def _run_optimize_inner(
    table,
    filter: Optional[Expression],
    zorder_by: Optional[List[str]],
    max_file_size: int,
    min_file_size: Optional[int],
    curve: str = "zorder",
    full: bool = False,
) -> OptimizeMetrics:
    from delta_tpu.clustering import (
        clustering_columns,
        file_in_stable_zcube,
        new_zcube_tags,
    )

    txn = table.create_transaction_builder(Operation.OPTIMIZE).build()
    txn._isolation = IsolationLevel.SNAPSHOT_ISOLATION
    snapshot = txn.read_snapshot
    if snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    meta = snapshot.metadata
    schema = meta.schema

    # clustered table: compaction becomes clustering by the domain's
    # columns (`OptimizeExecutor` isClusteredTable semantics)
    cluster_cols = clustering_columns(snapshot)
    zcube_tags = None
    if zorder_by is None and cluster_cols:
        zorder_by = cluster_cols
        min_file_size = None
        zcube_tags = new_zcube_tags(cluster_cols, curve)
        if filter is not None:
            # `DeltaErrors.clusteringWithPartitionPredicatesException`:
            # clustered tables cluster the whole table, never a slice
            raise OptimizeArgumentError(
                "predicates are not supported when optimizing a "
                "clustered table",
                error_class="DELTA_CLUSTERING_WITH_PARTITION_PREDICATE")
    elif zorder_by and cluster_cols:
        raise OptimizeArgumentError(
            "clustered tables use OPTIMIZE (no ZORDER BY); clustering "
            f"columns are {cluster_cols}",
            error_class="DELTA_CLUSTERING_WITH_ZORDER_BY")

    if zorder_by:
        from delta_tpu.stats.collection import stats_columns

        indexed = {".".join(p) for p in stats_columns(
            schema, meta.configuration, meta.partitionColumns)} \
            if schema is not None else None
        for c in zorder_by:
            if c in meta.partitionColumns:
                raise OptimizeArgumentError(f"cannot Z-order by partition column {c}",
                                        error_class="DELTA_ZORDERING_ON_PARTITION_COLUMN")
            if schema is not None and c not in schema:
                raise OptimizeArgumentError(f"Z-order column {c} not in schema",
                                        error_class="DELTA_ZORDERING_COLUMN_DOES_NOT_EXIST")
            if indexed is not None and c not in indexed:
                # `DeltaErrors.zOrderingOnColumnWithNoStatsException`:
                # clustering by an unindexed column cannot help skipping
                raise OptimizeArgumentError(
                    f"Z-ordering on {c} will be ineffective: no "
                    "file statistics are collected for it (see "
                    "delta.dataSkippingStatsColumns / "
                    "delta.dataSkippingNumIndexedCols)",
                    error_class="DELTA_ZORDERING_ON_COLUMN_WITHOUT_STATS")

    candidates = txn.scan_files(filter=filter)
    if full:
        zcube_tags = zcube_tags or (
            new_zcube_tags(cluster_cols, curve) if cluster_cols else None)
        # OPTIMIZE FULL ignores ZCube stability: everything re-clusters
    elif zcube_tags is not None:
        # skip files already in a stable cube over the same columns
        cube_sizes: Dict[str, int] = {}
        from delta_tpu.clustering import ZCUBE_ID_TAG

        for f in candidates:
            cid = (f.tags or {}).get(ZCUBE_ID_TAG)
            if cid:
                cube_sizes[cid] = cube_sizes.get(cid, 0) + f.size
        candidates = [
            f for f in candidates
            if not file_in_stable_zcube(f, zorder_by, cube_sizes)
        ]
    metrics = OptimizeMetrics()

    # Explicit Z-order stamps ZCube tags on its output too, so scan
    # planning (and the bench's skip-rate assert) can see which files
    # were curve-clustered. Kept separate from `zcube_tags`: explicit
    # zorder must not inherit the clustered path's stable-cube
    # candidate filtering, clusteringProvider, or operationParameters
    # clusterBy semantics.
    explicit_tags = (new_zcube_tags(zorder_by, curve)
                     if zorder_by and zcube_tags is None else None)

    # group per partition (bins never span partitions)
    by_partition: Dict[tuple, List[AddFile]] = {}
    for f in candidates:
        key = tuple(sorted((f.partitionValues or {}).items()))
        by_partition.setdefault(key, []).append(f)

    now_ms = int(time.time() * 1000)
    new_adds: List[AddFile] = []
    removed: List[AddFile] = []
    for pkey, files in sorted(by_partition.items()):
        if zorder_by is None:
            small = [f for f in files if f.size < min_file_size]
            bins = [
                b for b in bin_pack_by_size(small, max_file_size) if len(b) > 1
            ]
        else:
            # multi-dim clustering rewrites every candidate file
            bins = [files] if files else []
        for bin_files in bins:
            adds = _rewrite_bin(
                table, snapshot, bin_files, zorder_by, curve, max_file_size
            )
            if zcube_tags is not None:
                import dataclasses

                adds = [
                    dataclasses.replace(
                        a, tags={**(a.tags or {}), **zcube_tags},
                        clusteringProvider="liquid",
                    )
                    for a in adds
                ]
            elif explicit_tags is not None:
                import dataclasses

                adds = [
                    dataclasses.replace(
                        a, tags={**(a.tags or {}), **explicit_tags})
                    for a in adds
                ]
            new_adds.extend(adds)
            removed.extend(bin_files)
            metrics.num_bins += 1
        if bins:
            metrics.partitions_optimized += 1

    if not removed:
        return metrics  # nothing to do; no commit

    for f in removed:
        txn.remove_file(f.remove(deletion_timestamp=now_ms, data_change=False))
    txn.add_files(new_adds)
    txn.set_operation_parameters(
        {
            "predicate": repr(filter) if filter is not None else "[]",
            "zOrderBy": list(zorder_by) if zorder_by and zcube_tags is None else [],
            "clusterBy": list(zorder_by) if zcube_tags is not None else [],
            "auto": False,
        }
    )
    metrics.num_files_added = len(new_adds)
    metrics.num_files_removed = len(removed)
    metrics.bytes_added = sum(a.size for a in new_adds)
    metrics.bytes_removed = sum(r.size for r in removed)
    txn.set_operation_metrics(
        {
            "numAddedFiles": metrics.num_files_added,
            "numRemovedFiles": metrics.num_files_removed,
            "numAddedBytes": metrics.bytes_added,
            "numRemovedBytes": metrics.bytes_removed,
        }
    )
    result = txn.commit()
    metrics.version = result.version
    return metrics


def _rewrite_bin(
    table, snapshot, bin_files: List[AddFile],
    zorder_by: Optional[List[str]], curve: str, max_file_size: int,
) -> List[AddFile]:
    """Read the bin's rows (deletion vectors applied, physical→logical
    names mapped), optionally reorder along the curve, and write back as
    (approximately) bin-size files. Rewritten files drop their DVs —
    OPTIMIZE purges soft-deleted rows like the reference's
    `OptimizeExecutor`."""
    from delta_tpu.read.reader import read_add_file_logical

    engine = table.engine
    meta = snapshot.metadata
    schema = meta.schema
    data = pa.concat_tables(
        [read_add_file_logical(engine, table.path, snapshot, f)
         for f in bin_files],
        promote_options="permissive",
    )

    if zorder_by:
        import pyarrow.compute as pc

        cols = []
        for c in zorder_by:
            arr = data.column(c).combine_chunks()
            if arr.null_count:
                fill = "" if pa.types.is_string(arr.type) else 0
                arr = pc.fill_null(arr, fill)
            a = np.asarray(arr)
            if a.dtype == object:
                a = a.astype(str)
            cols.append(a)
        from delta_tpu.ops.zorder import zorder_sort_indices

        perm = zorder_sort_indices(cols, curve=curve)
        data = data.take(pa.array(perm, pa.int64()))

    total_bytes = sum(f.size for f in bin_files)
    n_out = max(1, -(-total_bytes // max_file_size))
    rows_per_file = max(1, -(-data.num_rows // n_out))

    part_cols = meta.partitionColumns
    return write_data_files(
        engine=engine,
        table_path=table.path,
        data=data,
        schema=schema,
        partition_columns=part_cols,
        configuration=meta.configuration,
        data_change=False,
        target_rows_per_file=rows_per_file if n_out > 1 else None,
    )

"""DML: DELETE and UPDATE (copy-on-write or deletion-vector mode) + CDC.

Reference `commands/DeleteCommand.scala` / `UpdateCommand.scala` /
`DMLWithDeletionVectorsHelper.scala`:

1. Scan candidate files with the predicate (partition pruning + stats
   skipping narrow the rewrite set).
2. Per candidate, evaluate the predicate on actual rows:
   - no rows match       → file untouched,
   - DELETE all rows     → remove the file outright,
   - otherwise copy-on-write (rewrite surviving/updated rows) or, for
     DELETE with `delta.enableDeletionVectors`, write a DV marking the
     deleted row indexes (file stays, logical file key changes).
3. Stage removes+adds; CDC mode additionally writes `_change_data/` files
   (`_change_type` = delete / update_preimage / update_postimage).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from delta_tpu.config import DELETION_VECTORS_ENABLED, ENABLE_CDF, cdf_enabled, get_table_config
from delta_tpu.errors import AppendOnlyTableError, DeltaError, InvalidArgumentError, MissingTransactionLogError
from delta_tpu.expressions.tree import Expression
from delta_tpu.models.actions import AddCDCFile, AddFile
from delta_tpu.txn.transaction import Operation
from delta_tpu.write.writer import write_data_files

CDC_TYPE_COL = "_change_type"


@dataclass
class DMLMetrics:
    num_files_scanned: int = 0
    num_files_rewritten: int = 0
    num_files_removed_fully: int = 0
    num_dvs_written: int = 0
    num_rows_deleted: int = 0
    num_rows_updated: int = 0
    num_rows_copied: int = 0
    version: Optional[int] = None


def _read_file_with_partitions(table, snapshot, add: AddFile) -> pa.Table:
    """Full physical row set (DV NOT applied — DML computes row indices
    positionally against the Parquet order), logical names, partition
    columns appended."""
    from delta_tpu.read.reader import read_add_file_logical

    return read_add_file_logical(
        table.engine, table.path, snapshot, add, apply_dv=False)


def _existing_dv_mask(table, add: AddFile, num_rows: int) -> Optional[np.ndarray]:
    if add.deletionVector is None:
        return None
    from delta_tpu.dv.descriptor import load_deletion_vector_mask

    return load_deletion_vector_mask(
        table.engine, table.path, add.deletionVector.to_dict(), num_rows
    )


def _write_cdc(table, snapshot, txn, rows: pa.Table, change_type: str) -> None:
    if rows.num_rows == 0:
        return
    import uuid as _uuid

    engine = table.engine
    rel = f"{filename_prefix()}cdc-{_uuid.uuid4()}.parquet"
    path = f"{table.path}/{rel}"
    data = rows.append_column(
        CDC_TYPE_COL, pa.array([change_type] * rows.num_rows, pa.string())
    )
    # CDC rows drop partition columns like data files? No: CDC files carry
    # the full row; we keep everything except re-derived partition dirs.
    status = engine.parquet.write_parquet_file(path, data)
    txn.add_cdc_file(
        AddCDCFile(path=rel, partitionValues={}, size=status.size, dataChange=False)
    )


def filename_prefix() -> str:
    from delta_tpu.utils.filenames import CHANGE_DATA_DIR

    return f"{CHANGE_DATA_DIR}/"


def delete(table, predicate: Optional[Expression] = None) -> DMLMetrics:
    """DELETE FROM table WHERE predicate (None = delete everything)."""
    txn = table.create_transaction_builder(Operation.DELETE).build()
    snapshot = txn.read_snapshot
    if snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    meta = snapshot.metadata
    if meta.configuration.get("delta.appendOnly", "").lower() == "true":
        raise AppendOnlyTableError("cannot DELETE from an append-only table")
    use_dv = get_table_config(meta.configuration, DELETION_VECTORS_ENABLED)
    use_cdc = cdf_enabled(meta.configuration)
    now_ms = int(time.time() * 1000)
    metrics = DMLMetrics()

    candidates = txn.scan_files(filter=predicate)
    metrics.num_files_scanned = len(candidates)

    if predicate is None:
        for f in candidates:
            txn.remove_file(f.remove(deletion_timestamp=now_ms))
            metrics.num_files_removed_fully += 1
            if f.stats:
                nr = f.num_records()
                metrics.num_rows_deleted += nr or 0
        txn.set_operation_parameters({"predicate": "true"})
        result = txn.commit()
        metrics.version = result.version
        return metrics

    delete_matching_rows(txn, table, snapshot, predicate, metrics,
                         now_ms=now_ms, use_dv=use_dv, use_cdc=use_cdc,
                         candidates=candidates)

    if not txn._adds and not txn._removes:
        return metrics  # nothing matched; no commit
    txn.set_operation_parameters({"predicate": repr(predicate)})
    txn.set_operation_metrics(
        {
            "numDeletedRows": metrics.num_rows_deleted,
            "numRemovedFiles": metrics.num_files_removed_fully + metrics.num_files_rewritten + metrics.num_dvs_written,
            "numCopiedRows": metrics.num_rows_copied,
            "numDeletionVectorsAdded": metrics.num_dvs_written,
        }
    )
    result = txn.commit()
    metrics.version = result.version
    return metrics


def delete_matching_rows(
    txn,
    table,
    snapshot,
    predicate: Expression,
    metrics: DMLMetrics,
    now_ms: Optional[int] = None,
    use_dv: Optional[bool] = None,
    use_cdc: Optional[bool] = None,
    candidates=None,
) -> None:
    """Stage the removal of all rows matching `predicate` into an open
    transaction: full-file removes, deletion-vector writes, or
    copy-on-write rewrites (+ CDC files), exactly as DELETE — shared by
    DELETE and by overwrite-with-replaceWhere."""
    meta = snapshot.metadata
    if now_ms is None:
        now_ms = int(time.time() * 1000)
    if use_dv is None:
        use_dv = get_table_config(meta.configuration, DELETION_VECTORS_ENABLED)
    if use_cdc is None:
        use_cdc = cdf_enabled(meta.configuration)
    if candidates is None:
        candidates = txn.scan_files(filter=predicate)

    from delta_tpu.expressions.eval import evaluate_predicate_host

    dv_writes: List[tuple] = []
    for add in candidates:
        data = _read_file_with_partitions(table, snapshot, add)
        existing_mask = _existing_dv_mask(table, add, data.num_rows)
        visible = (
            ~existing_mask if existing_mask is not None
            else np.ones(data.num_rows, dtype=bool)
        )
        matches = evaluate_predicate_host(predicate, data) & visible
        n_match = int(matches.sum())
        if n_match == 0:
            continue
        metrics.num_rows_deleted += n_match
        n_visible = int(visible.sum())
        if n_match == n_visible:
            txn.remove_file(add.remove(deletion_timestamp=now_ms))
            metrics.num_files_removed_fully += 1
        elif use_dv:
            all_deleted = matches | (existing_mask if existing_mask is not None else False)
            dv_writes.append((add, np.nonzero(all_deleted)[0].astype(np.uint64)))
        else:
            survivors = data.filter(pa.array(visible & ~matches))
            metrics.num_rows_copied += survivors.num_rows
            adds = write_data_files(
                engine=table.engine,
                table_path=table.path,
                data=survivors,
                schema=snapshot.schema,
                partition_columns=snapshot.partition_columns,
                configuration=meta.configuration,
            )
            txn.add_files(adds)
            txn.remove_file(add.remove(deletion_timestamp=now_ms))
            metrics.num_files_rewritten += 1
        if use_cdc:
            _write_cdc(table, snapshot, txn, data.filter(pa.array(matches)), "delete")

    if dv_writes:
        from delta_tpu.dv.descriptor import write_deletion_vector_file
        from delta_tpu.dv.roaring import RoaringBitmapArray

        descriptors = write_deletion_vector_file(
            table.engine, table.path,
            [RoaringBitmapArray(idx) for _, idx in dv_writes],
        )
        import dataclasses

        for (add, idx), desc in zip(dv_writes, descriptors):
            txn.remove_file(add.remove(deletion_timestamp=now_ms))
            new_add = dataclasses.replace(
                add, deletionVector=desc, dataChange=True,
            )
            new_add.extra = dict(add.extra)
            txn.add_file(new_add)
            metrics.num_dvs_written += 1


def update(
    table,
    assignments: Dict[str, object],
    predicate: Optional[Expression] = None,
) -> DMLMetrics:
    """UPDATE table SET col=value|fn(batch)->array WHERE predicate.

    `assignments` values: a constant, an Expression, or a callable
    (pa.Table) -> pa.Array evaluated over the matched rows.
    """
    txn = table.create_transaction_builder(Operation.UPDATE).build()
    snapshot = txn.read_snapshot
    if snapshot is None:
        raise MissingTransactionLogError(f"no table at {table.path}")
    meta = snapshot.metadata
    if meta.configuration.get("delta.appendOnly", "").lower() == "true":
        raise AppendOnlyTableError("cannot UPDATE an append-only table")
    if meta.schema is not None:
        from delta_tpu.colgen import IDENTITY_START_KEY, IDENTITY_STEP_KEY
        from delta_tpu.errors import IdentityColumnError

        identity_cols = {
            f.name for f in meta.schema.fields
            if IDENTITY_START_KEY in f.metadata
            or IDENTITY_STEP_KEY in f.metadata}
        hit = sorted(identity_cols & set(assignments))
        if hit:
            # `DeltaErrors.identityColumnUpdateNotSupported`: values
            # are system-allocated; an UPDATE would break uniqueness
            raise IdentityColumnError(
                f"UPDATE on IDENTITY column(s) {hit} is not supported",
                error_class="DELTA_IDENTITY_COLUMNS_UPDATE_NOT_SUPPORTED")
    use_cdc = cdf_enabled(meta.configuration)
    now_ms = int(time.time() * 1000)
    metrics = DMLMetrics()

    from delta_tpu.expressions.eval import evaluate_host, evaluate_predicate_host

    candidates = txn.scan_files(filter=predicate)
    metrics.num_files_scanned = len(candidates)

    for add in candidates:
        data = _read_file_with_partitions(table, snapshot, add)
        existing_mask = _existing_dv_mask(table, add, data.num_rows)
        if existing_mask is not None:
            data = data.filter(pa.array(~existing_mask))
        matches = (
            evaluate_predicate_host(predicate, data)
            if predicate is not None
            else np.ones(data.num_rows, dtype=bool)
        )
        n_match = int(matches.sum())
        if n_match == 0:
            continue
        matched = data.filter(pa.array(matches))
        updated = _apply_assignments(matched, assignments, evaluate_host)
        untouched = data.filter(pa.array(~matches))
        new_data = pa.concat_tables([untouched, updated], promote_options="permissive")
        metrics.num_rows_updated += n_match
        metrics.num_rows_copied += untouched.num_rows
        adds = write_data_files(
            engine=table.engine,
            table_path=table.path,
            data=new_data,
            schema=snapshot.schema,
            partition_columns=snapshot.partition_columns,
            configuration=meta.configuration,
        )
        txn.add_files(adds)
        txn.remove_file(add.remove(deletion_timestamp=now_ms))
        metrics.num_files_rewritten += 1
        if use_cdc:
            _write_cdc(table, snapshot, txn, matched, "update_preimage")
            _write_cdc(table, snapshot, txn, updated, "update_postimage")

    if not txn._adds and not txn._removes:
        return metrics
    txn.set_operation_parameters(
        {"predicate": repr(predicate) if predicate is not None else "true"}
    )
    txn.set_operation_metrics(
        {
            "numUpdatedRows": metrics.num_rows_updated,
            "numCopiedRows": metrics.num_rows_copied,
            "numRemovedFiles": metrics.num_files_rewritten,
        }
    )
    result = txn.commit()
    metrics.version = result.version
    return metrics


def _apply_assignments(matched: pa.Table, assignments, evaluate_host) -> pa.Table:
    out = matched
    for col_name, value in assignments.items():
        if col_name not in out.column_names:
            raise InvalidArgumentError(f"unknown column in SET: {col_name}",
                                       error_class="DELTA_MISSING_SET_COLUMN")
        idx = out.column_names.index(col_name)
        if isinstance(value, Expression):
            arr = evaluate_host(value, out)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
        elif callable(value):
            arr = value(out)
        else:
            arr = pa.array([value] * out.num_rows, out.schema.field(idx).type)
        arr = arr.cast(out.schema.field(idx).type, safe=False) if hasattr(arr, "cast") else arr
        out = out.set_column(idx, out.schema.field(idx), arr)
    return out

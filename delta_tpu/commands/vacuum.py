"""VACUUM: delete unreferenced data files after the retention window.

Reference `commands/VacuumCommand.scala:59,224`: the protected set is the
latest snapshot's live files, the DV files they reference, and tombstoned
files whose deletionTimestamp is inside the retention window. Everything
else under the table directory (excluding `_delta_log`) whose
modification time predates the cutoff is deleted. Hidden files/dirs
(`_`/`.` prefixed, except `_change_data`) are skipped.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional

from delta_tpu.config import TOMBSTONE_RETENTION, get_table_config
from delta_tpu.errors import InvalidArgumentError, VacuumRetentionError
from delta_tpu.utils import filenames


@dataclass
class VacuumResult:
    files_deleted: List[str] = field(default_factory=list)
    dirs_scanned: int = 0
    dry_run: bool = False

    @property
    def num_deleted(self) -> int:
        return len(self.files_deleted)


def _is_hidden(name: str) -> bool:
    return (name.startswith("_") or name.startswith(".")) and not name.startswith(
        filenames.CHANGE_DATA_DIR
    )


def _walk_table_files(table_path: str):
    """Yield (abs_path, rel_path, mtime_ms) for data-area files."""
    for root, dirs, files in os.walk(table_path):
        rel_root = os.path.relpath(root, table_path)
        parts = [] if rel_root == "." else rel_root.split(os.sep)
        dirs[:] = [
            d for d in dirs
            if not (_is_hidden(d) and not parts)  # top-level hidden dirs skipped
            or d == filenames.CHANGE_DATA_DIR
        ]
        if parts and _is_hidden(parts[0]) and parts[0] != filenames.CHANGE_DATA_DIR:
            continue
        for f in files:
            if _is_hidden(f):
                continue
            abs_path = os.path.join(root, f)
            rel = os.path.relpath(abs_path, table_path)
            try:
                mtime = int(os.stat(abs_path).st_mtime * 1000)
            except FileNotFoundError:
                continue
            yield abs_path, rel.replace(os.sep, "/"), mtime


INVENTORY_COLUMNS = ("path", "length", "isDir", "modificationTime")


def _inventory_files(table_path: str, inventory):
    """Yield (abs_path, rel_path, mtime_ms) from a pre-computed
    inventory instead of listing (`VacuumCommand.scala:59` USING
    INVENTORY; required schema `VacuumCommand.scala:69`: path, length,
    isDir, modificationTime). Accepts a pyarrow Table or pandas
    DataFrame; paths may be absolute or table-relative, and rows
    outside the table root or under hidden dirs are ignored exactly
    like the listing path would."""
    import pyarrow as pa

    if isinstance(inventory, pa.Table):
        cols = set(inventory.column_names)
    else:
        cols = set(getattr(inventory, "columns", ()))
    missing = [c for c in INVENTORY_COLUMNS if c not in cols]
    if missing:
        raise InvalidArgumentError(
            f"invalid inventory schema: missing column(s) {missing}; "
            f"required: {list(INVENTORY_COLUMNS)}",
            error_class="DELTA_INVALID_INVENTORY_SCHEMA")
    if isinstance(inventory, pa.Table):
        rows = zip(inventory.column("path").to_pylist(),
                   inventory.column("isDir").to_pylist(),
                   inventory.column("modificationTime").to_pylist())
    else:
        rows = zip(inventory["path"].tolist(),
                   inventory["isDir"].tolist(),
                   inventory["modificationTime"].tolist())
    import math
    import posixpath

    base = table_path.rstrip("/")
    for path, is_dir, mtime in rows:
        if is_dir or path is None:
            continue
        if path.startswith(base + "/"):
            rel = path[len(base) + 1:]
        elif "://" in path or path.startswith("/"):
            continue  # outside the table root
        else:
            rel = path
        # canonicalize: '..' segments could escape the table root
        # (unlinking arbitrary files) or alias a live file past the
        # string-keyed protected-set check — the listing path can
        # never produce them, so reject rather than resolve upward
        rel = posixpath.normpath(rel.replace(os.sep, "/"))
        if rel.startswith("..") or rel.startswith("/") or rel == ".":
            continue
        top = rel.split("/", 1)[0]
        if _is_hidden(top) and top != filenames.CHANGE_DATA_DIR:
            continue
        if _is_hidden(rel.rsplit("/", 1)[-1]):
            continue
        if mtime is None or (isinstance(mtime, float)
                             and math.isnan(mtime)):
            # unknown age: skip, like the in-flight-txn stance —
            # an epoch-0 default would make it an unconditional
            # deletion candidate
            continue
        yield os.path.join(base, rel), rel, int(mtime)


def vacuum(
    table,
    retention_hours: Optional[float] = None,
    dry_run: bool = False,
    enforce_retention_check: bool = True,
    inventory=None,
) -> VacuumResult:
    snapshot = table.latest_snapshot()
    state = snapshot.state
    conf = state.metadata.configuration
    default_ms = get_table_config(conf, TOMBSTONE_RETENTION)
    retention_ms = (
        int(retention_hours * 3_600_000) if retention_hours is not None else default_ms
    )
    if enforce_retention_check and retention_ms < 0:
        raise VacuumRetentionError("retention must be >= 0")
    now_ms = int(time.time() * 1000)
    cutoff = now_ms - retention_ms

    protected: set = set()
    from urllib.parse import unquote

    fa = state.file_actions
    live_paths = fa.column("path").to_pylist()
    masks = state.live_mask | state.tombstone_mask
    del_ts = fa.column("deletion_timestamp").to_pylist()
    dvs = fa.column("deletion_vector").to_pylist()
    live = state.live_mask
    for i, p in enumerate(live_paths):
        if not masks[i]:
            continue
        keep = live[i] or (del_ts[i] or 0) >= cutoff
        if not keep:
            continue
        if "://" not in p and not p.startswith("/"):
            protected.add(unquote(p))
        dv = dvs[i]
        if dv and dv.get("storageType") == "u":
            from delta_tpu.dv.descriptor import absolute_dv_path

            abs_dv = absolute_dv_path(table.path, dv)
            protected.add(os.path.relpath(abs_dv, table.path).replace(os.sep, "/"))

    result = VacuumResult(dry_run=dry_run)
    doomed: List[str] = []
    candidates = (_inventory_files(table.path, inventory)
                  if inventory is not None
                  else _walk_table_files(table.path))
    for abs_path, rel, mtime in candidates:
        if rel in protected:
            continue
        if mtime >= cutoff:
            continue  # too young — may belong to an in-flight txn
        result.files_deleted.append(rel)
        doomed.append(abs_path)
    if not dry_run and doomed:
        # parallel delete, as the reference's distributed delete
        # (`VacuumCommand.scala:224`) — object-store unlink latency
        # dominates, not CPU
        from delta_tpu.utils.threads import parallel_map

        def _unlink(p: str) -> None:
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass

        parallel_map(_unlink, doomed)
    return result

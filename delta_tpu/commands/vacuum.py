"""VACUUM: delete unreferenced data files after the retention window.

Reference `commands/VacuumCommand.scala:59,224`: the protected set is the
latest snapshot's live files, the DV files they reference, and tombstoned
files whose deletionTimestamp is inside the retention window. Everything
else under the table directory (excluding `_delta_log`) whose
modification time predates the cutoff is deleted. Hidden files/dirs
(`_`/`.` prefixed, except `_change_data`) are skipped.

Three candidate sources, mirroring the reference's dispatch
(`VacuumCommand.scala:281-333`):
- FULL (default): recursive listing of the table directory;
- USING INVENTORY: a caller-supplied frame of (path, length, isDir,
  modificationTime) rows;
- LITE (`vacuum_type="LITE"`): candidates come from the delta log
  itself — RemoveFile tombstones (and their DV files) plus CDC files
  recorded in the commit range since the last vacuum's watermark
  (`VacuumCommand.scala:506-636`). Never lists the data directory, so
  untracked files survive; a `_last_vacuum_info` watermark file makes
  successive LITE runs incremental.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import List, Optional

from delta_tpu import obs
from delta_tpu.config import TOMBSTONE_RETENTION, get_table_config
from delta_tpu.errors import (
    InvalidArgumentError,
    TimestampEarlierThanCommitRetentionError,
    VacuumLiteError,
    VacuumRetentionError,
)
from delta_tpu.utils import filenames


@dataclass
class VacuumResult:
    files_deleted: List[str] = field(default_factory=list)
    dirs_scanned: int = 0
    dry_run: bool = False
    type_of_vacuum: str = "FULL"
    eligible_start_commit_version: Optional[int] = None
    eligible_end_commit_version: Optional[int] = None

    @property
    def num_deleted(self) -> int:
        return len(self.files_deleted)


def _is_hidden(name: str) -> bool:
    return (name.startswith("_") or name.startswith(".")) and not name.startswith(
        filenames.CHANGE_DATA_DIR
    )


def _walk_table_files(table_path: str):
    """Yield (abs_path, rel_path, mtime_ms) for data-area files."""
    for root, dirs, files in os.walk(table_path):
        rel_root = os.path.relpath(root, table_path)
        parts = [] if rel_root == "." else rel_root.split(os.sep)
        dirs[:] = [
            d for d in dirs
            if not (_is_hidden(d) and not parts)  # top-level hidden dirs skipped
            or d == filenames.CHANGE_DATA_DIR
        ]
        if parts and _is_hidden(parts[0]) and parts[0] != filenames.CHANGE_DATA_DIR:
            continue
        for f in files:
            if _is_hidden(f):
                continue
            abs_path = os.path.join(root, f)
            rel = os.path.relpath(abs_path, table_path)
            try:
                mtime = int(os.stat(abs_path).st_mtime * 1000)
            except FileNotFoundError:
                continue
            yield abs_path, rel.replace(os.sep, "/"), mtime


INVENTORY_COLUMNS = ("path", "length", "isDir", "modificationTime")


def _inventory_files(table_path: str, inventory):
    """Yield (abs_path, rel_path, mtime_ms) from a pre-computed
    inventory instead of listing (`VacuumCommand.scala:59` USING
    INVENTORY; required schema `VacuumCommand.scala:69`: path, length,
    isDir, modificationTime). Accepts a pyarrow Table or pandas
    DataFrame; paths may be absolute or table-relative, and rows
    outside the table root or under hidden dirs are ignored exactly
    like the listing path would."""
    import pyarrow as pa

    if isinstance(inventory, pa.Table):
        cols = set(inventory.column_names)
    else:
        cols = set(getattr(inventory, "columns", ()))
    missing = [c for c in INVENTORY_COLUMNS if c not in cols]
    if missing:
        raise InvalidArgumentError(
            f"invalid inventory schema: missing column(s) {missing}; "
            f"required: {list(INVENTORY_COLUMNS)}",
            error_class="DELTA_INVALID_INVENTORY_SCHEMA")
    if isinstance(inventory, pa.Table):
        rows = zip(inventory.column("path").to_pylist(),
                   inventory.column("isDir").to_pylist(),
                   inventory.column("modificationTime").to_pylist())
    else:
        rows = zip(inventory["path"].tolist(),
                   inventory["isDir"].tolist(),
                   inventory["modificationTime"].tolist())
    import math
    import posixpath

    base = table_path.rstrip("/")
    for path, is_dir, mtime in rows:
        if is_dir or path is None:
            continue
        if path.startswith(base + "/"):
            rel = path[len(base) + 1:]
        elif "://" in path or path.startswith("/"):
            continue  # outside the table root
        else:
            rel = path
        # canonicalize: '..' segments could escape the table root
        # (unlinking arbitrary files) or alias a live file past the
        # string-keyed protected-set check — the listing path can
        # never produce them, so reject rather than resolve upward
        rel = posixpath.normpath(rel.replace(os.sep, "/"))
        if rel.startswith("..") or rel.startswith("/") or rel == ".":
            continue
        top = rel.split("/", 1)[0]
        if _is_hidden(top) and top != filenames.CHANGE_DATA_DIR:
            continue
        if _is_hidden(rel.rsplit("/", 1)[-1]):
            continue
        if mtime is None or (isinstance(mtime, float)
                             and math.isnan(mtime)):
            # unknown age: skip, like the in-flight-txn stance —
            # an epoch-0 default would make it an unconditional
            # deletion candidate
            continue
        yield os.path.join(base, rel), rel, int(mtime)


LAST_VACUUM_INFO = "_last_vacuum_info"


def _last_vacuum_watermark(table) -> Optional[int]:
    """The previous vacuum's latestCommitVersionOutsideOfRetentionWindow
    from `_delta_log/_last_vacuum_info` (`VacuumCommand.scala:948`);
    None when absent or unreadable (corrupt info only widens the next
    LITE scan, never breaks it)."""
    path = f"{table.log_path}/{LAST_VACUUM_INFO}"
    try:
        data = table.engine.fs.read_file(path)
        return json.loads(data.decode())[
            "latestCommitVersionOutsideOfRetentionWindow"]
    except (FileNotFoundError, KeyError, ValueError):
        return None


def _persist_last_vacuum_info(table, watermark: Optional[int]) -> None:
    """Best-effort watermark persistence (`VacuumCommand.scala:967`).
    Both FULL and LITE vacuums advance the watermark (advance-only,
    never reset to null — see the caller's rationale), except a FULL
    run that left mtime-skewed survivors behind, which skips the
    advance so the next LITE still rescans the commits that removed
    them."""
    path = f"{table.log_path}/{LAST_VACUUM_INFO}"
    body = json.dumps(
        {"latestCommitVersionOutsideOfRetentionWindow": watermark}
    ).encode()
    try:
        table.engine.fs.write_file(path, body)
    except OSError:
        pass


def _read_commit_actions(table, version: int):
    from delta_tpu.models.actions import actions_from_commit_bytes

    fs = table.engine.fs
    try:
        data = fs.read_file(filenames.delta_file(table.log_path, version))
    except FileNotFoundError:
        # unbackfilled coordinated commit: look in _delta_log/_commits
        commit_dir = f"{table.log_path}/_commits"
        for st in fs.list_from(f"{commit_dir}/"):
            name = st.path.rsplit("/", 1)[-1]
            if name.startswith(f"{version:020d}.") and \
                    name.endswith(".json"):
                data = fs.read_file(st.path)
                break
        else:
            raise
    return actions_from_commit_bytes(data)


def _commit_outside_retention(table, cutoff_ms: int) -> Optional[int]:
    """Version of the newest commit at/before the cutoff, or None when
    every commit is inside the retention window
    (`VacuumCommand.scala:285-296`)."""
    from delta_tpu.history import version_at_timestamp

    try:
        return version_at_timestamp(table, cutoff_ms,
                                    can_return_last_commit=True)
    except TimestampEarlierThanCommitRetentionError:
        return None


def _lite_candidates(table, snapshot, cutoff_ms: int,
                     last_mark: Optional[int]):
    """(candidates, start_version, end_version) for VACUUM LITE: the
    deletion candidates are the RemoveFile tombstones (+ their on-disk
    DV files) and AddCDCFile entries recorded in commits
    [start, end], where end is the newest commit outside the retention
    window and start resumes after the last vacuum's watermark
    (`VacuumCommand.scala:506-556`). Candidate mtime is the remove's
    deletionTimestamp, so the caller's shared cutoff filter applies
    unchanged; CDC files get mtime 0 (always eligible once their
    commit leaves the window, matching `VacuumCommand.scala:622`)."""
    from delta_tpu.models.actions import AddCDCFile, RemoveFile

    end = _commit_outside_retention(table, cutoff_ms)
    if end is None:
        return [], None, None  # nothing old enough to vacuum

    fs = table.engine.fs
    versions = sorted(
        filenames.delta_version(st.path)
        for st in fs.list_from(f"{table.log_path}/")
        if filenames.is_delta_file(st.path))
    if not versions:
        return [], None, None
    earliest = versions[0]
    # Log cleanup removed commits we never scanned: tombstones may
    # have expired out of the log unobserved — only a FULL listing can
    # find those files now. No gap when last_mark + 1 == earliest
    # (every expired commit was already scanned; the reference's
    # `VacuumCommand.scala:533` check is conservative by one here).
    if earliest != 0 and (last_mark is None
                          or last_mark + 1 < earliest):
        raise VacuumLiteError(
            "VACUUM LITE cannot delete all eligible files as some "
            "files are not referenced by the Delta log. Please run "
            "VACUUM FULL.")
    # strictly after the watermark: re-scanning the watermark commit
    # itself would re-report (and re-"delete") files a previous run
    # already removed. A corrupt watermark beyond `end` just yields an
    # empty range.
    start = last_mark + 1 if last_mark is not None else earliest
    if start > end:
        return [], None, end

    import posixpath
    from urllib.parse import unquote

    base = table.path.rstrip("/")
    by_path = {}

    def _offer(raw: str, mtime: int) -> None:
        # decode BEFORE the root checks: '%2Fetc%2Fx' must be treated
        # as the absolute path it decodes to, not a relative name
        rel = unquote(raw)
        if rel.startswith(base + "/"):
            rel = rel[len(base) + 1:]
        elif "://" in rel or rel.startswith("/"):
            return  # outside the table root (e.g. shallow clone source)
        # same traversal guard as _inventory_files: a '..' segment in a
        # logged path could escape the table root on unlink
        rel = posixpath.normpath(rel.replace(os.sep, "/"))
        if rel.startswith("..") or rel.startswith("/") or rel == ".":
            return
        prev = by_path.get(rel)
        if prev is None or mtime > prev:
            by_path[rel] = mtime

    for v in range(start, end + 1):
        for a in _read_commit_actions(table, v):
            if isinstance(a, RemoveFile):
                mtime = a.deletionTimestamp or 0
                _offer(a.path, mtime)
                dv = a.deletionVector
                if dv is not None and dv.storageType == "u":
                    from delta_tpu.dv.descriptor import absolute_dv_path

                    abs_dv = absolute_dv_path(base, {
                        "storageType": dv.storageType,
                        "pathOrInlineDv": dv.pathOrInlineDv})
                    _offer(abs_dv, mtime)
            elif isinstance(a, AddCDCFile):
                _offer(a.path, 0)

    out = [(os.path.join(base, rel), rel, mtime)
           for rel, mtime in by_path.items()]
    return out, start, end


def vacuum(
    table,
    retention_hours: Optional[float] = None,
    dry_run: bool = False,
    enforce_retention_check: bool = True,
    inventory=None,
    vacuum_type: str = "FULL",
) -> VacuumResult:
    with obs.span("command.vacuum", table=table.path, dry_run=dry_run,
                  vacuum_type=vacuum_type.upper()) as sp:
        result = _vacuum(table, retention_hours, dry_run,
                         enforce_retention_check, inventory, vacuum_type)
        sp.set_attrs(files_deleted=result.num_deleted,
                     dirs_scanned=result.dirs_scanned)
        return result


def _vacuum(
    table,
    retention_hours: Optional[float],
    dry_run: bool,
    enforce_retention_check: bool,
    inventory,
    vacuum_type: str,
) -> VacuumResult:
    vacuum_type = vacuum_type.upper()
    if vacuum_type not in ("FULL", "LITE"):
        raise InvalidArgumentError(
            f"invalid vacuum type {vacuum_type!r}: expected FULL or "
            "LITE", error_class="DELTA_ILLEGAL_ARGUMENT")
    if inventory is not None and vacuum_type == "LITE":
        raise InvalidArgumentError(
            "VACUUM LITE does not accept an inventory",
            error_class="DELTA_ILLEGAL_ARGUMENT")
    snapshot = table.latest_snapshot()
    state = snapshot.state
    conf = state.metadata.configuration
    default_ms = get_table_config(conf, TOMBSTONE_RETENTION)
    retention_ms = (
        int(retention_hours * 3_600_000) if retention_hours is not None else default_ms
    )
    if enforce_retention_check and retention_ms < 0:
        raise VacuumRetentionError("retention must be >= 0")
    now_ms = int(time.time() * 1000)
    cutoff = now_ms - retention_ms

    protected: set = set()
    from urllib.parse import unquote

    fa = state.file_actions
    live_paths = fa.column("path").to_pylist()
    masks = state.live_mask | state.tombstone_mask
    del_ts = fa.column("deletion_timestamp").to_pylist()
    dvs = fa.column("deletion_vector").to_pylist()
    live = state.live_mask
    # tombstones whose deletionTimestamp already expired: deletable per
    # the log, so if one SURVIVES the mtime guard below the watermark
    # must not advance past the commit that removed it
    expired: set = set()
    for i, p in enumerate(live_paths):
        if not masks[i]:
            continue
        keep = live[i] or (del_ts[i] or 0) >= cutoff
        if not keep:
            if "://" not in p and not p.startswith("/"):
                expired.add(unquote(p))
            continue
        if "://" not in p and not p.startswith("/"):
            protected.add(unquote(p))
        dv = dvs[i]
        if dv and dv.get("storageType") == "u":
            from delta_tpu.dv.descriptor import absolute_dv_path

            abs_dv = absolute_dv_path(table.path, dv)
            protected.add(os.path.relpath(abs_dv, table.path).replace(os.sep, "/"))

    result = VacuumResult(dry_run=dry_run, type_of_vacuum=vacuum_type)
    doomed: List[str] = []
    last_mark = _last_vacuum_watermark(table)
    lite_end = None
    if inventory is not None:
        candidates = _inventory_files(table.path, inventory)
    elif vacuum_type == "LITE":
        candidates, lite_start, lite_end = _lite_candidates(
            table, snapshot, cutoff, last_mark)
        result.eligible_start_commit_version = lite_start
        result.eligible_end_commit_version = lite_end
    else:
        candidates = _walk_table_files(table.path)
    skewed_survivor = False
    for abs_path, rel, mtime in candidates:
        if rel in protected:
            continue
        if mtime >= cutoff:
            # too young — may belong to an in-flight txn. A file whose
            # REMOVE already expired per the log but whose on-disk
            # mtime is skewed forward survives this run; remember that
            # so the FULL watermark below doesn't seal it in forever.
            if rel in expired:
                skewed_survivor = True
            continue
        result.files_deleted.append(rel)
        doomed.append(abs_path)
    if not dry_run and doomed:
        # parallel delete, as the reference's distributed delete
        # (`VacuumCommand.scala:224`) — object-store unlink latency
        # dominates, not CPU
        from delta_tpu.utils.threads import parallel_map

        def _unlink(p: str) -> None:
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass

        parallel_map(_unlink, doomed)
    if not dry_run and inventory is None:
        # Advance-only watermark: an empty run (cutoff before the
        # earliest commit, or no new commits since the last watermark)
        # must not reset or regress it — that would force the next run
        # to rescan, or spuriously trip the log-cleanup gap check. A
        # true FULL vacuum walks every file, so it advances the
        # watermark too — unlike the reference, which resets it to
        # null after FULL (`VacuumCommand.scala:484`) and thereby
        # wedges LITE forever on any table whose log head has been
        # cleaned up. An INVENTORY vacuum observes only the rows the
        # caller supplied, which proves nothing about unlisted
        # tombstones — it never touches the watermark.
        # ... except when a FULL walk left mtime-skewed survivors: their
        # remove actions live in commits the watermark would skip, so a
        # later LITE could never reconsider them once their mtime ages
        # out. Hold the watermark until a run observes no such survivor.
        new_mark = lite_end if vacuum_type == "LITE" else \
            (None if skewed_survivor
             else _commit_outside_retention(table, cutoff))
        if new_mark is not None and (last_mark is None
                                     or new_mark > last_mark):
            _persist_last_vacuum_info(table, new_mark)
    return result

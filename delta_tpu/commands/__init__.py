"""Command layer: OPTIMIZE, VACUUM, DML (DELETE/UPDATE/MERGE), RESTORE,
CONVERT — the spark `commands/` analogue over the transaction core."""

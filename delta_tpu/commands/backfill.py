"""Row-tracking backfill: retrofit baseRowId onto pre-existing files.

Reference `commands/backfill/RowTrackingBackfillCommand.scala` +
`BackfillExecutor.scala`: enabling row tracking on an existing table is
a three-step flow — (1) upgrade the protocol with the `rowTracking`
writer feature, (2) commit batches that re-add every live file lacking a
`baseRowId` (dataChange=false; the normal commit path assigns fresh ids
from the watermark domain), (3) flip `delta.enableRowTracking=true` so
readers may rely on the ids.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from delta_tpu.errors import DeltaError, InvalidArgumentError
from delta_tpu.features import ROW_TRACKING, upgraded_protocol
from delta_tpu.rowtracking import is_row_tracking_supported
from delta_tpu.txn.transaction import Operation

DEFAULT_BATCH_SIZE = 100_000


@dataclass
class BackfillMetrics:
    num_files_backfilled: int = 0
    num_batches: int = 0
    final_version: Optional[int] = None


def backfill_row_tracking(
    table, batch_size: int = DEFAULT_BATCH_SIZE
) -> BackfillMetrics:
    """Enable row tracking on an existing table and backfill ids."""
    if batch_size <= 0:
        raise InvalidArgumentError("batch_size must be positive")
    metrics = BackfillMetrics()

    snap = table.latest_snapshot()
    if not is_row_tracking_supported(snap.protocol):
        txn = table.create_transaction_builder(Operation.UPGRADE_PROTOCOL).build()
        txn.update_protocol(upgraded_protocol(snap.protocol, ROW_TRACKING))
        txn.commit()
        snap = table.latest_snapshot()

    while True:
        missing = [
            a for a in snap.state.add_files() if a.baseRowId is None
        ][:batch_size]
        if not missing:
            break
        txn = table.create_transaction_builder(Operation.MANUAL_UPDATE).build()
        import dataclasses

        # re-add with dataChange=false; commit() assigns fresh baseRowIds
        # + advances the watermark domain (rowtracking.assign_fresh_row_ids)
        for a in missing:
            txn.add_file(dataclasses.replace(a, dataChange=False))
        txn.set_operation_parameters(
            {"operation": "ROW TRACKING BACKFILL", "batchSize": len(missing)}
        )
        result = txn.commit()
        metrics.num_files_backfilled += len(missing)
        metrics.num_batches += 1
        metrics.final_version = result.version
        snap = table.latest_snapshot()

    # readers may now depend on the ids
    txn = table.create_transaction_builder(Operation.SET_TBLPROPERTIES).build()
    import dataclasses

    meta = txn.metadata()
    conf = dict(meta.configuration)
    if conf.get("delta.enableRowTracking", "").lower() != "true":
        conf["delta.enableRowTracking"] = "true"
        txn.update_metadata(dataclasses.replace(meta, configuration=conf))
        result = txn.commit()
        metrics.final_version = result.version
    return metrics

"""RESTORE TABLE ... TO VERSION AS OF / CLONE / CONVERT TO DELTA.

- restore: diff the target snapshot against the current one; re-add files
  the restore brings back, remove files added since, restore metadata
  (`commands/RestoreTableCommand.scala` semantics; fails if data files of
  the target version were already vacuumed unless force).
- clone (shallow): new table whose AddFiles point at the source table's
  files via absolute paths (`commands/CloneTableCommand.scala`).
- convert: import a plain Parquet directory as version 0
  (`commands/ConvertToDeltaCommand.scala`).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import pyarrow as pa

from delta_tpu.errors import CloneTargetExistsError, ConvertTargetError, DeltaError, RestoreTargetError
from delta_tpu.models.actions import AddFile, Metadata
from delta_tpu.table import Table
from delta_tpu.txn.transaction import Operation


@dataclass
class RestoreMetrics:
    num_restored_files: int = 0
    num_removed_files: int = 0
    version: Optional[int] = None


def restore(table, version: Optional[int] = None, timestamp_ms: Optional[int] = None,
            force: bool = False) -> RestoreMetrics:
    if (version is None) == (timestamp_ms is None):
        raise RestoreTargetError(
            "restore requires exactly one of version / timestamp",
            error_class="DELTA_ONEOF_IN_TIMETRAVEL")
    if version is not None:
        target = table.snapshot_at(version)
    else:
        from delta_tpu.errors import (
            TimestampEarlierThanCommitRetentionError,
            TimestampLaterThanLatestCommitError,
        )

        # `RestoreTableCommand` maps time-travel range misses to its
        # own classes (`DeltaErrors.restoreTimestampBefore/GreaterThan
        # LatestCommit`)
        try:
            target = table.snapshot_as_of_timestamp(timestamp_ms)
        except TimestampEarlierThanCommitRetentionError as e:
            raise RestoreTargetError(
                f"cannot restore table to timestamp {timestamp_ms}: "
                f"it is before the earliest available version ({e})",
                error_class="DELTA_CANNOT_RESTORE_TIMESTAMP_EARLIER")
        except TimestampLaterThanLatestCommitError as e:
            raise RestoreTargetError(
                f"cannot restore table to timestamp {timestamp_ms}: "
                f"it is after the latest available version ({e})",
                error_class="DELTA_CANNOT_RESTORE_TIMESTAMP_GREATER")
    current = table.latest_snapshot()
    now_ms = int(time.time() * 1000)

    cur_files = {
        (f["path"], f["dv_id"]): f
        for f in current.state.add_files_table.select(["path", "dv_id"]).to_pylist()
    }
    target_adds = target.state.add_files()
    target_keys = {(a.path, a.dv_unique_id) for a in target_adds}

    to_add = [a for a in target_adds if (a.path, a.dv_unique_id) not in cur_files]
    cur_adds = current.state.add_files()
    to_remove = [a for a in cur_adds if (a.path, a.dv_unique_id) not in target_keys]

    if not force:
        # fail when restored files no longer exist (vacuumed)
        for a in to_add:
            p = a.path
            abs_path = p if ("://" in p or p.startswith("/")) else f"{table.path}/{p}"
            if not table.engine.fs.exists(abs_path):
                raise RestoreTargetError(
                    error_class="DELTA_RESTORE_MISSING_DATA_FILE",
                    message=f"cannot restore: data file {a.path} was removed "
                    "(probably by VACUUM); use force=True to restore anyway"
                )

    txn = table.create_transaction_builder(Operation.RESTORE).build()
    import dataclasses

    for a in to_add:
        txn.add_file(dataclasses.replace(a, dataChange=True))
    for a in to_remove:
        txn.remove_file(a.remove(deletion_timestamp=now_ms))
    if target.metadata.to_dict() != current.metadata.to_dict():
        txn.update_metadata(target.metadata)
    txn.set_operation_parameters(
        {"version": version, "timestamp": timestamp_ms}
    )
    txn.set_operation_metrics(
        {
            "numRestoredFiles": len(to_add),
            "numRemovedFiles": len(to_remove),
        }
    )
    result = txn.commit()
    return RestoreMetrics(len(to_add), len(to_remove), result.version)


def clone(source_table, dest_path: str, shallow: bool = True,
          properties: Optional[Dict[str, str]] = None) -> int:
    """CLONE. Shallow: dest commits AddFiles with absolute paths into the
    source table's data. Deep: data files are copied into the destination
    and re-added under their relative paths (`CloneTableBase.scala`
    shallow/deep modes). Returns the dest commit version."""
    snap = source_table.latest_snapshot()
    dest = Table.for_path(dest_path, source_table.engine)
    if dest.exists():
        raise CloneTargetExistsError(f"clone destination {dest_path} already exists")
    if os.path.isdir(dest_path) and os.listdir(dest_path):
        # a non-table directory with content: cloning over it would
        # mix foreign files into the table data
        # (`DeltaErrors.cloneOnNonEmptyTarget` semantics)
        raise CloneTargetExistsError(
            f"clone destination {dest_path} is a non-empty directory; "
            "CLONE requires an empty or nonexistent target",
            error_class="DELTA_UNSUPPORTED_NON_EMPTY_CLONE")
    meta = snap.metadata

    new_conf = dict(meta.configuration)
    new_conf.update(properties or {})
    builder = (
        dest.create_transaction_builder(Operation.CLONE)
        .with_schema(meta.schemaString)
        .with_partition_columns(meta.partitionColumns)
        .with_table_properties(new_conf)
    )
    txn = builder.build()
    import dataclasses

    src_root = source_table.path
    fs = source_table.engine.fs
    used_rel: set = set()
    copied_dvs: set = set()
    for i, a in enumerate(snap.state.add_files()):
        p = a.path
        abs_path = p if ("://" in p or p.startswith("/")) else f"{src_root}/{p}"
        if shallow:
            txn.add_file(dataclasses.replace(a, path=abs_path, dataChange=True))
            continue
        # deep: materialize the bytes under the destination root,
        # preserving the relative layout (partition dirs). Absolute
        # source paths get fresh unique names — basenames from different
        # directories may collide.
        if "://" not in p and not p.startswith("/") and p not in used_rel:
            rel = p
        else:
            base = p.rsplit("/", 1)[-1]
            rel = f"part-{i:05d}-{base}"
        used_rel.add(rel)
        target = f"{dest.path}/{rel}"
        parent = target.rsplit("/", 1)[0]
        fs.mkdirs(parent)
        fs.write_file(target, fs.read_file(abs_path))
        dv = a.deletionVector
        if dv is not None and dv.storageType == "u":
            # the DV bitmap file is table-root-relative: copy it so the
            # clone stays self-contained (CloneTableBase deep semantics)
            from delta_tpu.dv.descriptor import absolute_dv_path

            row = {"storageType": dv.storageType,
                   "pathOrInlineDv": dv.pathOrInlineDv}
            src_dv = absolute_dv_path(src_root, row)
            dst_dv = absolute_dv_path(dest.path, row)
            if src_dv not in copied_dvs:
                copied_dvs.add(src_dv)
                fs.mkdirs(dst_dv.rsplit("/", 1)[0])
                fs.write_file(dst_dv, fs.read_file(src_dv))
        txn.add_file(dataclasses.replace(a, path=rel, dataChange=True))
    txn.set_operation_parameters(
        {"source": src_root, "sourceVersion": snap.version,
         "isShallow": shallow}
    )
    return txn.commit().version


def convert_to_delta(
    path: str,
    partition_schema: Optional[Dict[str, str]] = None,
    engine=None,
    collect_stats: bool = True,
) -> int:
    """Convert a directory of Parquet files (optionally Hive-partitioned)
    into a Delta table in place. Footer reads (schema + per-file stats)
    run on the shared I/O pool, the reference's parallel file-manifest
    read (`commands/convert/ConvertUtils.scala`); `collect_stats` fills
    each AddFile's stats from row-group footers so the converted table
    data-skips immediately without scanning data."""
    import pyarrow.parquet as pq

    from delta_tpu.models.schema import PrimitiveType, from_arrow_schema
    from delta_tpu.utils.threads import parallel_map

    table = Table.for_path(path, engine)
    if table.exists():
        raise ConvertTargetError(f"{path} is already a Delta table",
                                 error_class="DELTA_CONVERT_TARGET_ALREADY_DELTA")
    part_schema = partition_schema or {}
    part_cols = list(part_schema)

    manifest: List[tuple] = []  # (abs_path, rel_path, partition_values)
    root = table.path
    for dirpath, dirs, files in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        if rel_dir.startswith("_delta_log"):
            continue
        dirs[:] = [d for d in dirs if not d.startswith((".", "_"))]
        pv: Dict[str, Optional[str]] = {}
        if rel_dir != ".":
            for part in rel_dir.split(os.sep):
                if "=" in part:
                    k, _, v = part.partition("=")
                    from urllib.parse import unquote

                    pv[k] = None if v == "__HIVE_DEFAULT_PARTITION__" else unquote(v)
        for fname in files:
            if not fname.endswith(".parquet") or fname.startswith((".", "_")):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            missing = [k for k in part_cols if k not in pv]
            if missing:
                raise ConvertTargetError(
                    f"file {rel} lacks partition values for {missing}",
                    error_class="DELTA_CONVERSION_NO_PARTITION_FOUND"
                )
            manifest.append((full, rel, {k: pv.get(k) for k in part_cols}))
    if not manifest:
        raise ConvertTargetError(f"no parquet files found under {path}")

    arrow_schema = pq.read_schema(manifest[0][0])
    schema = from_arrow_schema(arrow_schema)
    for col_name, type_name in part_schema.items():
        if col_name not in schema:
            schema = schema.add(col_name, PrimitiveType(type_name))

    from delta_tpu.stats.footer import footer_stats

    def _to_add(entry: tuple) -> AddFile:
        full, rel, pvals = entry
        st = os.stat(full)
        stats = (footer_stats(full, schema, {}, part_cols)
                 if collect_stats else None)
        return AddFile(
            path=rel,
            partitionValues=pvals,
            size=st.st_size,
            modificationTime=int(st.st_mtime * 1000),
            dataChange=True,
            stats=stats,
        )

    adds: List[AddFile] = parallel_map(_to_add, manifest)

    from delta_tpu.models.schema import schema_to_json

    txn = (
        table.create_transaction_builder(Operation.CONVERT)
        .with_schema(schema)
        .with_partition_columns(part_cols)
        .build()
    )
    txn.add_files(adds)
    txn.set_operation_parameters({"numFiles": len(adds), "partitionedBy": part_cols})
    return txn.commit().version

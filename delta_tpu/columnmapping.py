"""Column mapping: logical→physical column indirection.

Reference `DeltaColumnMapping.scala:93-120`: modes `none` | `name` | `id`.
Under `name`/`id` every field carries `delta.columnMapping.id` (stable
int) and `delta.columnMapping.physicalName` (`col-<uuid>`) in its
metadata; Parquet files use physical names, so renaming/dropping a
logical column is a metadata-only operation.

This module assigns mapping metadata, rewrites schemas between logical
and physical forms, and provides the rename/drop transformations ALTER
TABLE uses.
"""

from __future__ import annotations

import uuid
from typing import Dict, Optional

from delta_tpu.errors import ColumnMappingModeChangeError, DeltaError, DuplicateColumnError, NonExistentColumnError, SchemaEvolutionError
from delta_tpu.models.actions import Metadata
from delta_tpu.models.schema import (
    COLUMN_MAPPING_ID_KEY,
    COLUMN_MAPPING_PHYSICAL_NAME_KEY,
    ArrayType,
    DataType,
    MapType,
    StructField,
    StructType,
)

MODE_KEY = "delta.columnMapping.mode"
MAX_ID_KEY = "delta.columnMapping.maxColumnId"


def mapping_mode(configuration: Dict[str, str]) -> str:
    return configuration.get(MODE_KEY, "none")


def _assign_in_type(dt: DataType, next_id) -> DataType:
    if isinstance(dt, StructType):
        return StructType([_assign_field(f, next_id) for f in dt.fields])
    if isinstance(dt, ArrayType):
        return ArrayType(_assign_in_type(dt.elementType, next_id), dt.containsNull)
    if isinstance(dt, MapType):
        return MapType(
            _assign_in_type(dt.keyType, next_id),
            _assign_in_type(dt.valueType, next_id),
            dt.valueContainsNull,
        )
    return dt


def _assign_field(f: StructField, next_id) -> StructField:
    md = dict(f.metadata)
    if COLUMN_MAPPING_ID_KEY not in md:
        md[COLUMN_MAPPING_ID_KEY] = next_id()
    if COLUMN_MAPPING_PHYSICAL_NAME_KEY not in md:
        md[COLUMN_MAPPING_PHYSICAL_NAME_KEY] = f"col-{uuid.uuid4()}"
    return StructField(f.name, _assign_in_type(f.dataType, next_id), f.nullable, md)


def assign_column_mapping(schema: StructType, configuration: Dict[str, str]) -> tuple:
    """Assign ids/physical names to all fields lacking them. Returns
    (new schema, new configuration with bumped maxColumnId)."""
    max_id = int(configuration.get(MAX_ID_KEY, "0"))

    def next_id():
        nonlocal max_id
        max_id += 1
        return max_id

    new_schema = StructType([_assign_field(f, next_id) for f in schema.fields])
    new_conf = dict(configuration)
    new_conf[MAX_ID_KEY] = str(max_id)
    return new_schema, new_conf


def physical_schema(schema: StructType) -> StructType:
    """Logical schema → physical (names replaced, metadata kept)."""

    def conv_type(dt: DataType) -> DataType:
        if isinstance(dt, StructType):
            return StructType(
                [
                    StructField(
                        f.physical_name, conv_type(f.dataType), f.nullable, dict(f.metadata)
                    )
                    for f in dt.fields
                ]
            )
        if isinstance(dt, ArrayType):
            return ArrayType(conv_type(dt.elementType), dt.containsNull)
        if isinstance(dt, MapType):
            return MapType(conv_type(dt.keyType), conv_type(dt.valueType), dt.valueContainsNull)
        return dt

    return conv_type(schema)  # type: ignore[return-value]


def logical_to_physical_names(schema: StructType) -> Dict[str, str]:
    return {f.name: f.physical_name for f in schema.fields}


def physical_to_logical_names(schema: StructType) -> Dict[str, str]:
    return {f.physical_name: f.name for f in schema.fields}


def physical_name_path(schema: StructType, name_path: tuple) -> Optional[tuple]:
    """Translate a logical column path to its physical path (None if any
    segment is missing)."""
    out = []
    cur: Optional[DataType] = schema
    for part in name_path:
        if not isinstance(cur, StructType) or part not in cur:
            return None
        f = cur[part]
        out.append(f.physical_name)
        cur = f.dataType
    return tuple(out)


def validate_mode_change(old_mode: str, new_mode: str) -> None:
    """Legal transitions: none->name, none->id (on new tables), same->same.
    name/id cannot be dropped (`DeltaColumnMapping` restrictions)."""
    if old_mode == new_mode:
        return
    if old_mode == "none" and new_mode in ("name", "id"):
        return
    raise ColumnMappingModeChangeError(
        f"unsupported column mapping mode change {old_mode} -> {new_mode}"
    )


def rename_column(schema: StructType, old: str, new: str) -> StructType:
    """Metadata-only rename (requires mapping mode != none)."""
    if new in schema:
        raise DuplicateColumnError(f"column {new} already exists")
    fields = []
    found = False
    for f in schema.fields:
        if f.name == old:
            fields.append(StructField(new, f.dataType, f.nullable, dict(f.metadata)))
            found = True
        else:
            fields.append(f)
    if not found:
        raise NonExistentColumnError(f"column {old} not found")
    return StructType(fields)


def drop_column(schema: StructType, name: str) -> StructType:
    if name not in schema:
        raise NonExistentColumnError(f"column {name} not found")
    if len(schema.fields) == 1:
        raise SchemaEvolutionError("cannot drop the last column",
                                   error_class="DELTA_DROP_COLUMN_ON_SINGLE_FIELD_SCHEMA")
    return StructType([f for f in schema.fields if f.name != name])
